"""risectl-lite: operator CLI against a live data directory.

The `src/ctl/src/cmd_impl/` analog (hummock/meta/table subcommands) for
the single-process runtime: inspect the DDL log, the LSM manifest, state
tables, and metrics, or trigger a full compaction — without writing any
Python.

    python -m risingwave_tpu.ctl <command> --data-dir DIR [...]

Commands:
    jobs                      list catalog objects from the DDL log
    ddl-log                   print the raw DDL log entries
    manifest                  committed epoch + per-table runs/sizes
    dump NAME [--limit N]     rows of an object's state table
    compact                   merge every table's runs into one base
    metrics                   Prometheus exposition after recovery
    backup --dest DIR         self-contained snapshot copy (restore =
                              open the copy as a data directory)
    history                   retained manifest versions (time travel)
    trace [--last N]          per-barrier span summary; flags OPEN
                              (stalled) epochs with the stuck job —
                              works on a LIVE or wedged data dir;
                              --stuck-only drops committed epochs so
                              stalls survive fresh committed traffic
    trace export              merge barrier_trace.jsonl +
                              epoch_profile.jsonl + heartbeat clock
                              samples into Chrome/Perfetto trace-event
                              JSON on one coordinator-clock timeline
                              (--format chrome, -o FILE) — a whole
                              warmup/chaos run opens in ui.perfetto.dev
    profile [JOB]             fused-job epoch timeline from
                              epoch_profile.jsonl: phase totals
                              (host-pack / dispatch / device-sync /
                              commit), compile events, top-N slowest
                              epochs (JSON) — decompose warmup vs
                              steady state without rerunning anything;
                              --follow tails the file live
                              (rotation-aware `tail -f`)
    failpoints [--spec S]     list declared fault-injection points and
                              which the spec (default: $RW_FAILPOINTS)
                              arms; --arm validates a spec and prints
                              the export line to arm a process tree;
                              --ledger [FILE] prints a recorded fire
                              ledger — (ordinal, point, thread, hit) per
                              fire, the exact-replay record a chaos run
                              writes under RW_FAILPOINT_LEDGER (no FILE:
                              the live in-process ledger)
    fused-stats               per-fused-job growth/replay/retrace
                              counters and current per-node capacities
                              (JSON) — diagnose capacity-bound runs
                              without reading bench logs
    tiering [JOB]             hot/cold state-tier report per fused job:
                              per-node resident vs cold row counts,
                              Xor8 negative-cache liveness, and the
                              demotion / promotion / filter-probe
                              counters (the `rw_state_tiering` system
                              table, offline) — answers "is state
                              spilling, and is the filter earning its
                              keep"
    serving                   serving-tier read-cache report: per
                              cached MV the snapshot epoch, row count,
                              and hit / miss / coalesced / fill
                              counters, plus the process-wide device-
                              pull total (the `rw_serving_cache` system
                              table) — answers "are SELECTs actually
                              serving from host memory"
    compile-status [JOB]      per-signature AOT compile state of every
                              fused job (pending / ready / cached /
                              failed, with capacity bucket and compile
                              seconds) plus the job's plan-shape hash —
                              answers "why is this job still warming
                              up" and proves zero-compile warm starts;
                              --wait SECS lets in-flight background
                              compiles land first
    skew [JOB]                key-skew summary per fused job: node
                              skew_ratio, per-shard load under the
                              current routing bounds, top-K hot keys,
                              adopted hot-key replication policy, and a
                              vnode-occupancy sparkline — read from the
                              skew_stats.json mirror, so it works on a
                              DEAD data dir (--json for the raw rows)
    blackbox [ACTION]         flight-recorder postmortems: `list` the
                              dumped bundles of a data dir, `show NAME`
                              one bundle's records, or `dump` a fresh
                              bundle from the on-disk telemetry ring
                              mirror (blackbox_ring.jsonl) — the dump
                              path never opens a Database, so it works
                              on a DEAD or wedged directory: the last
                              ~4 MB of ladder moves, pressure ticks,
                              epochs, checkpoints, sheds, rebalances,
                              recoveries and supervisor events, exactly
                              as the process saw them before it died
    dlq [JOB]                 poison-pill dead-letter queue: list the
                              quarantined input rows (default — reads
                              the durable table directly, works on a
                              DEAD dir), --requeue ID,..|all re-injects
                              them into the live job (opens a Database,
                              replays DDL, ticks delivery), --purge
                              ID,..|all drops them (data loss accepted,
                              audit closed)
"""
from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any, List, Optional


def _store(data_dir: str):
    from ..state import SpillStateStore
    if not os.path.exists(os.path.join(data_dir, "MANIFEST.json")):
        raise SystemExit(f"{data_dir}: no MANIFEST.json — not a data dir")
    return SpillStateStore(data_dir)


def _ddl_entries(store) -> List[Any]:
    """(seq, sql) rows of the DDL log without a Database."""
    from ..sql.database import DDL_LOG_DTYPES, DDL_LOG_PK, DDL_LOG_TABLE_ID
    from ..state import StateTable
    log = StateTable(store, DDL_LOG_TABLE_ID, list(DDL_LOG_DTYPES),
                     list(DDL_LOG_PK))
    return sorted(log.iter_all())


def cmd_ddl_log(args) -> int:
    store = _store(args.data_dir)
    for seq, sql in _ddl_entries(store):
        print(f"{seq:6d}  {sql}")
    return 0


def cmd_jobs(args) -> int:
    """Catalog objects, parsed from the DDL log (no dataflow rebuild)."""
    from ..sql import ast as A
    from ..sql.parser import parse_sql
    store = _store(args.data_dir)
    live = {}
    for _seq, sql in _ddl_entries(store):
        try:
            stmts = parse_sql(sql)
        except ValueError:
            continue
        for stmt in stmts:
            if isinstance(stmt, A.CreateTable):
                kind = "SOURCE" if stmt.is_source else "TABLE"
                live[stmt.name] = (kind, f"{len(stmt.columns)} columns")
            elif isinstance(stmt, A.CreateMaterializedView):
                live[stmt.name] = ("MATERIALIZED VIEW", "")
            elif isinstance(stmt, A.CreateSink):
                live[stmt.name] = ("SINK", stmt.with_options.get(
                    "connector", "collect"))
            elif isinstance(stmt, A.CreateFunction):
                live[stmt.name] = ("FUNCTION", stmt.language)
            elif isinstance(stmt, A.DropObject):
                live.pop(stmt.name, None)
    for name, (kind, extra) in live.items():
        print(f"{kind:18s} {name}" + (f"  ({extra})" if extra else ""))
    return 0


def cmd_manifest(args) -> int:
    store = _store(args.data_dir)
    m = store._manifest
    out = {"committed_epoch": m["committed_epoch"], "tables": {}}
    for tid, runs in sorted(m["tables"].items(), key=lambda kv: int(kv[0])):
        sizes = []
        for name in runs:
            try:
                sizes.append(os.path.getsize(store._run_path(name)))
            except OSError:
                sizes.append(-1)
        out["tables"][tid] = {
            "rows": m["counts"].get(tid, 0),
            "runs": [{"name": n, "bytes": s}
                     for n, s in zip(runs, sizes)],
        }
    print(json.dumps(out, indent=2))
    return 0


def cmd_dump(args) -> int:
    """Rows of an object's state table, decoded through the catalog (the
    `ctl table scan` analog). Opens a full Database (DDL replay) so the
    schema and key layout are exact."""
    from ..sql import Database
    db = Database(data_dir=args.data_dir, device="auto")
    try:
        obj = db.catalog.get(args.name)
    except KeyError:
        raise SystemExit(f"no such object: {args.name}")
    job = (obj.runtime or {}).get("fused_job")
    st = (obj.runtime or {}).get("state_table")
    if job is None and st is None:
        raise SystemExit(f"{args.name}: object has no state table "
                         f"({obj.kind})")
    rows = job.mv_rows_now() if job is not None else list(st.iter_all())
    names = [f.name for f in obj.schema.fields]
    print("\t".join(names))
    for i, r in enumerate(rows):
        if args.limit is not None and i >= args.limit:
            print(f"... ({len(rows) - args.limit} more)")
            break
        print("\t".join("NULL" if v is None else str(v) for v in r))
    print(f"-- {len(rows)} rows")
    return 0


def cmd_compact(args) -> int:
    store = _store(args.data_dir)
    merged = store.compact_all()
    if not merged:
        print("nothing to compact")
    for tid, n in sorted(merged.items(), key=lambda kv: int(kv[0])):
        print(f"table {tid}: merged {n} runs -> 1 base")
    return 0


def cmd_metrics(args) -> int:
    """Read-only: recover and expose, WITHOUT ticking a barrier (a
    diagnostic must not advance the committed epoch)."""
    from ..sql import Database
    from ..utils.metrics import REGISTRY
    db = Database(data_dir=args.data_dir, device="auto")
    REGISTRY.gauge("committed_epoch", "last committed epoch"
                   ).set(db.store.committed_epoch)
    REGISTRY.gauge("streaming_jobs", "running dataflows"
                   ).set(len(db._iters) + len(db._fused))
    print(db.metrics())
    return 0


def cmd_trace(args) -> int:
    """Offline barrier-span summary (`monitor_service.rs:82` await-tree
    analog): reads the data dir's trace log without opening the Database,
    so it works against a WEDGED process's directory too.

    `trace export --format chrome [-o FILE]` instead merges the barrier
    trace, the epoch profile and the heartbeat clock samples into ONE
    Chrome/Perfetto trace-event JSON (utils/export.py): a whole warmup
    or chaos run opens in ui.perfetto.dev."""
    from ..utils.trace import TRACE_FILE, diagnose
    if args.action == "export":
        if args.format != "chrome":
            raise SystemExit(f"unknown export format {args.format!r} "
                             "(supported: chrome)")
        from ..utils.export import export_chrome, validate_chrome
        doc = export_chrome(args.data_dir)
        problems = validate_chrome(doc)
        if problems:
            for p in problems:
                print(f"export invariant violated: {p}", file=sys.stderr)
            return 1
        payload = json.dumps(doc)
        if args.out:
            with open(args.out, "w") as f:
                f.write(payload)
            n = len(doc["traceEvents"])
            print(f"wrote {n} events -> {args.out} "
                  "(open in ui.perfetto.dev)")
        else:
            print(payload)
        return 0
    if args.action is not None:
        raise SystemExit(f"unknown trace action {args.action!r} "
                         "(supported: export)")
    path = os.path.join(args.data_dir, TRACE_FILE)
    if not os.path.exists(path):
        print("no barrier trace (directory has no barrier_trace.jsonl)")
        return 1
    print(diagnose(path, last=args.last, stuck_only=args.stuck_only))
    return 0


def cmd_profile(args) -> int:
    """Offline epoch-profile summary (the fused-path flame-graph-lite):
    reads epoch_profile.jsonl without opening the Database — same
    wedged-process contract as `trace`. `--follow` instead TAILS the
    file live (rotation-aware): one line per epoch/compile record as the
    running process flushes them — `tail -f` that understands the
    format and survives `rotate_tail`."""
    from ..utils.profile import (PROFILE_FILE, format_record, summarize_file,
                                 tail_jsonl)
    path = os.path.join(args.data_dir, PROFILE_FILE)
    if args.follow:
        # a missing FILE is fine (the job may not have flushed yet; the
        # tail waits for it) — but a missing DIRECTORY is a typo that
        # would otherwise hang silently forever
        if not os.path.isdir(args.data_dir):
            print(f"{args.data_dir}: not a directory", file=sys.stderr)
            return 1
        if not os.path.exists(path):
            print(f"waiting for {path} ...", file=sys.stderr)
        try:
            for rec in tail_jsonl(path):
                if args.job is not None and rec.get("job") != args.job:
                    continue
                line = format_record(rec)
                if line:
                    print(line, flush=True)
        except KeyboardInterrupt:
            pass
        return 0
    if not os.path.exists(path):
        print("no epoch profile (directory has no epoch_profile.jsonl — "
              "fused jobs write it when DeviceConfig.profile is on)")
        return 1
    out = summarize_file(path, job=args.job, top=args.top)
    if args.job is not None and not out:
        print(f"no profile records for job {args.job!r}")
        return 1
    print(json.dumps(out, indent=2, sort_keys=True))
    return 0


def cmd_backup(args) -> int:
    """Copy the committed snapshot (manifest + referenced runs + device
    marker) into a self-contained directory; restore = open it as a data
    directory (`src/meta/src/backup_restore/` analog)."""
    store = _store(args.data_dir)
    n = store.backup(args.dest)
    print(f"backed up {n} run files + manifest -> {args.dest}")
    print("restore: open it as a data_dir "
          f"(Database(data_dir='{args.dest}'))")
    return 0


def cmd_failpoints(args) -> int:
    """Discover/validate fault-injection points (`utils/failpoint.py`).
    Points are declared at their hook sites, so importing the hook-site
    modules populates the listing; arming is per-process via the
    RW_FAILPOINTS environment variable (spawned workers inherit it)."""
    from ..utils import failpoint as fp
    # imported for their declare() side effects
    import risingwave_tpu.connectors.sink  # noqa: F401
    import risingwave_tpu.runtime.exchange_net  # noqa: F401
    import risingwave_tpu.runtime.remote_fragments  # noqa: F401
    import risingwave_tpu.runtime.worker  # noqa: F401
    import risingwave_tpu.state.hummock  # noqa: F401
    try:
        # fused device-path points (dispatch / device_sync /
        # growth_replay / checkpoint_commit); jax-hosted module, so a
        # jax-less operator box still lists the host-side points
        import risingwave_tpu.device.fused  # noqa: F401
    except ImportError:
        pass
    if args.ledger is not None:
        try:
            entries = fp.load_ledger(args.ledger) if args.ledger \
                else fp.ledger()
        except OSError as e:
            raise SystemExit(f"cannot read ledger {args.ledger!r}: {e}")
        except ValueError as e:
            raise SystemExit(f"bad ledger {args.ledger!r}: {e}")
        if not entries:
            print("ledger is empty (no failpoint fired"
                  + (f" in {args.ledger}" if args.ledger else "") + ")")
            return 0
        print(f"{'ordinal':>7s}  {'point':28s} {'thread':20s} hit")
        for o, point, thread, hit in entries:
            print(f"{o:7d}  {point:28s} {thread:20s} {hit}")
        print(f"-- {len(entries)} fires; re-arm exactly with "
              f"{fp.LEDGER_ENV}=<this file>")
        return 0
    spec = args.arm if args.arm is not None else args.spec
    try:
        points = {p.name: p for p in fp.parse_spec(spec or "")}
    except ValueError as e:
        raise SystemExit(f"bad failpoint spec: {e}")
    unknown = sorted(set(points) - set(fp.KNOWN))
    if args.arm is not None:
        if unknown:
            raise SystemExit(f"unknown failpoint(s): {', '.join(unknown)}")
        print(f"export {fp.ENV_VAR}="
              f"'{','.join(p.spec() for p in points.values())}'")
        return 0
    for name in sorted(fp.KNOWN):
        p = points.get(name)
        state = (f"ARMED prob={p.prob:g} seed={p.seed}"
                 + (f" max_fires={p.max_fires}"
                    if p.max_fires is not None else "")) if p else "off"
        print(f"{name:28s} {state:40s} {fp.KNOWN[name]}")
    for name in unknown:
        print(f"{name:28s} ARMED (unknown point — never fires)")
    return 0


def cmd_fused_stats(args) -> int:
    """Capacity-lifecycle report of every fused device job (the growth
    counters persist in each job's state table, so the numbers are
    cumulative across restarts). Opens a full Database: the DDL replay
    rebuilds the fused programs and recovery presizes them from the
    persisted high-water marks — a recovery that itself performs growth
    replays would show up in the counters."""
    from ..sql import Database
    db = Database(data_dir=args.data_dir, device="auto")
    if not db._fused:
        print("no fused device jobs in this data directory")
        return 0
    out = {name: job.cap_report() for name, job in db._fused.items()}
    print(json.dumps(out, indent=2, sort_keys=True))
    return 0


def cmd_tiering(args) -> int:
    """Hot/cold state-tier report of every fused job (or one JOB): the
    `rw_state_tiering` system-table rows, printed as a table. Opens a
    full Database — recovery rebuilds both tiers (device residents +
    host cold stores) from the journal, so the numbers reflect what a
    restarted job would actually hold."""
    from ..sql import Database
    db = Database(data_dir=args.data_dir, device="auto")
    jobs = {name: job for name, job in db._fused.items()
            if args.job is None or name == args.job}
    if not jobs:
        print("no fused device jobs in this data directory"
              if args.job is None else f"no fused job {args.job!r}")
        return 0 if args.job is None else 1
    cols = ("node", "type", "resident", "cold", "filter", "promotable",
            "demotions", "promotions", "demote_ev", "probes", "hits",
            "fallbacks")
    for name, job in sorted(jobs.items()):
        rows = job.tiering_report()
        if not rows:
            print(f"{name}: state tiering off (or no tierable nodes)")
            continue
        print(name)
        print("  " + "  ".join(f"{c:>9s}" for c in cols))
        for r in rows:
            cells = [str(r[0]), str(r[1]),
                     str(r[2]), str(r[3]),
                     "live" if r[4] else "off",
                     "yes" if r[5] else "no"] + [str(v) for v in r[6:]]
            print("  " + "  ".join(f"{c:>9s}" for c in cells))
    return 0


def cmd_serving(args) -> int:
    """Serving-tier read-cache report (`rw_serving_cache`, offline):
    per cached MV the snapshot epoch / row count and the hit / miss /
    coalesced / fill counters, plus the process-wide device-pull
    total. A healthy read-heavy deployment shows hits >> fills."""
    from ..sql import Database
    from ..device.shard_exec import PULL_STATS
    db = Database(data_dir=args.data_dir, device="auto")
    rows = db.read_cache.report()
    if not rows:
        print("serving cache empty (no fused MV has been read)")
    else:
        cols = ("mv", "epoch", "rows", "hits", "misses", "coalesced",
                "fills")
        print("  ".join(f"{c:>10s}" for c in cols))
        for r in rows:
            print("  ".join(f"{str(v):>10s}" for v in r))
    print(f"device pulls (process total): {PULL_STATS['device_pulls']}")
    reps = PULL_STATS["replica_pulls"]
    if reps:
        # the read-load split over the replica mesh axis — a healthy
        # replicated deployment spreads pulls round-robin, not all on
        # the write path's replica 0
        print("  by replica: " + "  ".join(
            f"r{rep}={n}" for rep, n in sorted(reps.items())))
    return 0


def cmd_blackbox(args) -> int:
    """Flight-recorder postmortems (`utils/blackbox.py`). `dump` reads
    the blackbox_ring.jsonl mirror straight off the directory — no
    Database, no jax, works on the data dir of a DEAD process (torn
    tail lines from the crash are tolerated) — and writes a
    self-describing bundle under <data-dir>/blackbox/. `list`/`show`
    browse the bundles already there (auto-dumped on escalations,
    in-place recoveries, quarantines and wedge reaps, or by `dump`)."""
    from ..utils.blackbox import dump_from_dir, list_bundles, read_bundle
    if args.action == "dump":
        try:
            path = dump_from_dir(args.data_dir, reason=args.reason)
        except (OSError, ValueError) as e:
            print(f"blackbox dump failed: {e}", file=sys.stderr)
            return 1
        if path is None:
            print(f"no telemetry ring in {args.data_dir} (the process "
                  "never attached a recorder, or the ring file was "
                  "removed) — nothing to dump")
            return 1
        print(f"dumped -> {path}")
        return 0
    try:
        bundles = list_bundles(args.data_dir)
    except OSError as e:
        print(f"cannot read {args.data_dir}: {e}", file=sys.stderr)
        return 1
    if args.action == "list" or args.action is None:
        if not bundles:
            print("no blackbox bundles (nothing triggered a dump; "
                  "`blackbox dump` takes one from the live ring mirror)")
            return 0
        print(f"{'bundle':44s} {'reason':24s} {'records':>7s}  kinds")
        for name, m in bundles:
            print(f"{name:44s} {m.get('reason', '?'):24s} "
                  f"{m.get('records', 0):7d}  "
                  f"{','.join(m.get('kinds', []))}")
        return 0
    if args.action == "show":
        if args.bundle is None:
            raise SystemExit("blackbox show needs a bundle name "
                             "(see `blackbox list`)")
        names = [n for n, _m in bundles]
        if args.bundle not in names:
            raise SystemExit(f"no bundle {args.bundle!r} "
                             f"(have: {', '.join(names) or 'none'})")
        try:
            recs = read_bundle(args.data_dir, args.bundle)
        except (OSError, ValueError) as e:
            raise SystemExit(f"cannot read bundle {args.bundle!r}: {e}")
        for rec in recs:
            print(json.dumps(rec, sort_keys=True))
        print(f"-- {len(recs)} records", file=sys.stderr)
        return 0
    raise SystemExit(f"unknown blackbox action {args.action!r} "
                     "(supported: list, show, dump)")


def cmd_skew(args) -> int:
    """Key-skew summary of every fused job (`rw_key_skew`, offline):
    per-node skew_ratio + per-shard load under the current routing
    bounds, the top-K hot keys, the adopted hot-key replication policy,
    and a vnode-occupancy sparkline. Reads the `skew_stats.json` mirror
    each job writes beside epoch_profile.jsonl at every checkpoint —
    works on a DEAD data dir, the `compile-status --offline` contract
    (the file IS the offline surface; there is no live mode to need)."""
    from ..device.fused import SKEW_FILE
    from ..device.skew_stats import SK_BUCKETS, sparkline
    path = os.path.join(args.data_dir, SKEW_FILE)
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        # ValueError: a crash can leave the snapshot truncated — the
        # dead-dir contract degrades gracefully, never tracebacks
        print(f"no skew snapshot ({path} missing or unreadable — the "
              "data dir predates skew mirroring, ran with skew_stats "
              "off, or never reached a checkpoint)")
        return 1
    jobs = doc.get("jobs", {})
    if args.job is not None:
        jobs = {k: v for k, v in jobs.items() if k == args.job}
        if not jobs:
            print(f"no skew snapshot for job {args.job!r}")
            return 1
    if args.json:
        print(json.dumps(jobs, indent=2, sort_keys=True))
        return 0
    for name, rec in sorted(jobs.items()):
        print(f"job {name}  shards={rec.get('mesh_shards', 1)}  "
              f"events={rec.get('committed_events', 0)}  "
              f"rebalances={rec.get('rebalances', 0)}")
        vb = rec.get("vnode_bounds")
        if vb:
            print(f"  vnode bounds: {vb}")
        rows = [tuple(r) for r in rec.get("rows", [])]
        nodes = sorted({(r[0], r[1]) for r in rows})
        for ni, tname in nodes:
            sub = [r for r in rows if r[0] == ni and r[1] == tname]
            occ = [0] * SK_BUCKETS
            for r in sub:
                if r[2] == "vnode_occ":
                    occ[int(r[3])] = int(r[5])
            ratio = next((r[6] for r in sub if r[2] == "skew_ratio"),
                         None)
            shard = next((r[6] for r in sub if r[2] == "shard_skew"),
                         None)
            line = f"  node {ni} {tname}: occ {sparkline(occ)}"
            if ratio is not None:
                line += f"  skew_ratio={ratio:.2f}x"
            if shard is not None:
                line += f"  shard_skew={shard:.2f}x"
            print(line)
            hot = [r for r in sub if r[2] == "hot_key"]
            for r in sorted(hot, key=lambda r: r[3]):
                print(f"    hot key #{r[3]}: key={r[4]} "
                      f"rows/epoch={r[5]}")
            pol = [r for r in sub if r[2] == "hot_policy"]
            if pol:
                keys = [r[4] for r in sorted(pol, key=lambda r: r[3])]
                print(f"    replicating side {pol[0][5]} for hot keys "
                      f"{keys}")
            loads = [r for r in sub if r[2] == "shard_load"]
            if loads:
                print("    shard loads: " + " ".join(
                    f"{int(r[5])}" for r in
                    sorted(loads, key=lambda r: r[3])))
    return 0


def cmd_compile_status(args) -> int:
    """AOT compile-service state per fused job (the warmup-wall
    dashboard). Opens a full Database: DDL replay rebuilds the fused
    programs, recovery presizes them, and CREATE-time pre-warm kicks
    their shapes onto the background pool — so the report shows exactly
    what a restarting operator would see: signatures already in the
    persistent cache load as fast `cached` entries, fresh shapes sit
    `pending` until their background compile lands.

    --offline skips the Database entirely and reads the
    `compile_manifest.json` mirror the service writes into the data dir
    at every save: which plan shapes and signatures were ever compiled
    (and their cost), straight from a DEAD directory — no process, no
    jax import, no recompiles."""
    if args.offline:
        from ..device.compile_service import offline_report, read_manifest
        m = read_manifest(args.data_dir)
        if m is None:
            print("no compile manifest (directory has no "
                  "compile_manifest.json mirror — the data dir predates "
                  "manifest mirroring, or never ran with aot_compile on; "
                  "RW_COMPILE_CACHE_DIR names the cache-dir fallback)")
            return 1
        print(json.dumps(offline_report(m), indent=2, sort_keys=True))
        return 0
    from ..device.compile_service import get_service
    from ..sql import Database
    db = Database(data_dir=args.data_dir, device="auto")
    if not db._fused:
        print("no fused device jobs in this data directory")
        return 0
    if args.job is not None and args.job not in db._fused:
        raise SystemExit(f"no fused job {args.job!r} "
                         f"(have: {', '.join(sorted(db._fused))})")
    svc = get_service()
    if args.wait:
        svc.wait_idle(args.wait)
    jobs = [args.job] if args.job is not None else sorted(db._fused)
    out = {}
    for j in jobs:
        job = db._fused[j]
        rows = svc.status(j)
        out[j] = {
            "plan_hash": job.plan_hash,
            "aot": job.compile_service is not None,
            "signatures": rows,
            "counts": {st: sum(1 for r in rows if r["state"] == st)
                       for st in ("pending", "ready", "cached", "failed")},
        }
    print(json.dumps(out, indent=2, sort_keys=True))
    return 0


def cmd_dlq(args) -> int:
    """Poison-pill dead-letter queue (`rw_dead_letter`): list the
    quarantined input rows of a job (or all jobs), re-inject them into
    the live dataflow once the underlying poison condition is fixed, or
    purge them. Listing reads the durable DLQ table directly — no
    Database, works on a dead directory; requeue/purge open a full
    Database (DDL replay respawns the worker sets) and commit the
    status flip durably."""
    if args.requeue is None and args.purge is None:
        store = _store(args.data_dir)
        from ..runtime.remote_fragments import DeadLetterQueue
        from ..sql.database import DLQ_TABLE_ID
        from ..state import StateTable
        dlq = DeadLetterQueue(StateTable(
            store, DLQ_TABLE_ID, list(DeadLetterQueue.DTYPES),
            list(DeadLetterQueue.PK)))
        ents = dlq.entries(job=args.job)
        if not ents:
            print("dead-letter queue is empty"
                  + (f" for job {args.job!r}" if args.job else ""))
            return 0
        print(f"{'id':>5s}  {'job':12s} {'slot':>4s} {'side':>4s} "
              f"{'epoch':>7s}  {'status':12s} {'sign':>4s}  row")
        for (i, job, slot, side, epoch, _fp, sign, rrepr, _payload,
             status, _ts) in ents:
            print(f"{i:5d}  {job:12s} {slot:4d} {side:4d} {epoch:7d}  "
                  f"{status:12s} {sign:4d}  {rrepr}")
        print(f"-- {len(ents)} rows; requeue with "
              f"`dlq {args.job or '<job>'} --data-dir {args.data_dir} "
              "--requeue all` once the poison condition is fixed")
        return 0
    if args.requeue is not None and args.purge is not None:
        raise SystemExit("dlq: --requeue and --purge are mutually "
                         "exclusive (one destructive action at a time)")
    if args.job is None:
        raise SystemExit("dlq --requeue/--purge needs the JOB argument")
    from ..sql import Database
    db = Database(data_dir=args.data_dir, device="auto")
    ids = None
    spec = args.purge if args.purge is not None else args.requeue
    if spec != "all":
        try:
            ids = [int(x) for x in spec.split(",") if x]
        except ValueError:
            raise SystemExit(f"bad id list {spec!r} (want 'all' or "
                             "comma-separated ids)")
    if args.purge is not None:
        n = db.dlq_purge(args.job, ids)
        print(f"purged {n} dead-letter rows of {args.job!r}")
        return 0
    try:
        n = db.dlq_requeue(args.job, ids)
    except ValueError as e:
        raise SystemExit(str(e))
    for _ in range(max(0, args.ticks)):
        db.tick()
    print(f"requeued {n} rows into {args.job!r} "
          f"(delivered over {args.ticks} barriers)")
    return 0


def cmd_history(args) -> int:
    """Retained manifest versions (time-travel window)."""
    store = _store(args.data_dir)
    for m in store.history_versions():
        n_runs = sum(len(r) for r in m["tables"].values())
        print(f"epoch {m['committed_epoch']}: {len(m['tables'])} tables, "
              f"{n_runs} runs")
    if not store.history_versions():
        print("no retained versions")
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m risingwave_tpu.ctl",
        description="risectl-lite: inspect/operate a data directory")
    sub = p.add_subparsers(dest="cmd", required=True)
    for name, fn in [("jobs", cmd_jobs), ("ddl-log", cmd_ddl_log),
                     ("manifest", cmd_manifest), ("compact", cmd_compact),
                     ("metrics", cmd_metrics),
                     ("fused-stats", cmd_fused_stats)]:
        sp = sub.add_parser(name)
        sp.add_argument("--data-dir", required=True)
        sp.set_defaults(fn=fn)
    sp = sub.add_parser("dump")
    sp.add_argument("name")
    sp.add_argument("--data-dir", required=True)
    sp.add_argument("--limit", type=int, default=None)
    sp.set_defaults(fn=cmd_dump)
    sp = sub.add_parser("trace")
    sp.add_argument("action", nargs="?", default=None,
                    help="'export' merges barrier trace + epoch profile "
                         "+ clock samples into Chrome/Perfetto "
                         "trace-event JSON")
    sp.add_argument("--data-dir", required=True)
    sp.add_argument("--last", type=int, default=5)
    sp.add_argument("--stuck-only", action="store_true",
                    help="print only OPEN (uncommitted) epochs")
    sp.add_argument("--format", default="chrome",
                    help="export format (chrome)")
    sp.add_argument("-o", "--out", default=None,
                    help="export output file (default: stdout)")
    sp.set_defaults(fn=cmd_trace)
    sp = sub.add_parser("profile")
    sp.add_argument("job", nargs="?", default=None)
    sp.add_argument("--data-dir", required=True)
    sp.add_argument("--top", type=int, default=10,
                    help="slowest epochs to list per job")
    sp.add_argument("--follow", action="store_true",
                    help="tail epoch_profile.jsonl live "
                         "(rotation-aware) instead of summarizing")
    sp.set_defaults(fn=cmd_profile)
    sp = sub.add_parser("skew")
    sp.add_argument("job", nargs="?", default=None)
    sp.add_argument("--data-dir", required=True)
    sp.add_argument("--json", action="store_true",
                    help="raw snapshot JSON instead of the summary")
    sp.set_defaults(fn=cmd_skew)
    sp = sub.add_parser("tiering")
    sp.add_argument("job", nargs="?", default=None)
    sp.add_argument("--data-dir", required=True)
    sp.set_defaults(fn=cmd_tiering)
    sp = sub.add_parser("serving")
    sp.add_argument("--data-dir", required=True)
    sp.set_defaults(fn=cmd_serving)
    sp = sub.add_parser("blackbox")
    sp.add_argument("action", nargs="?", default=None,
                    help="list (default) | show BUNDLE | dump")
    sp.add_argument("bundle", nargs="?", default=None,
                    help="bundle name for `show`")
    sp.add_argument("--data-dir", required=True)
    sp.add_argument("--reason", default="manual",
                    help="reason tag stamped on a `dump` bundle")
    sp.set_defaults(fn=cmd_blackbox)
    sp = sub.add_parser("compile-status")
    sp.add_argument("job", nargs="?", default=None)
    sp.add_argument("--data-dir", required=True)
    sp.add_argument("--wait", type=float, default=0.0,
                    help="seconds to let in-flight background compiles "
                         "finish before reporting")
    sp.add_argument("--offline", action="store_true",
                    help="read the data dir's compile_manifest.json "
                         "mirror instead of opening a Database (works "
                         "on a dead directory)")
    sp.set_defaults(fn=cmd_compile_status)
    sp = sub.add_parser("backup")
    sp.add_argument("--data-dir", required=True)
    sp.add_argument("--dest", required=True)
    sp.set_defaults(fn=cmd_backup)
    sp = sub.add_parser("history")
    sp.add_argument("--data-dir", required=True)
    sp.set_defaults(fn=cmd_history)
    sp = sub.add_parser("dlq")
    sp.add_argument("job", nargs="?", default=None)
    sp.add_argument("--data-dir", required=True)
    sp.add_argument("--requeue", default=None, metavar="IDS|all",
                    help="re-inject quarantined rows (comma-separated "
                         "ids or 'all') into the live job")
    sp.add_argument("--purge", default=None, metavar="IDS|all",
                    help="drop quarantined rows outright")
    sp.add_argument("--ticks", type=int, default=4,
                    help="barriers to drive after a requeue so the rows "
                         "reach the MV/sink (default 4)")
    sp.set_defaults(fn=cmd_dlq)
    sp = sub.add_parser("failpoints")
    sp.add_argument("--spec", default=os.environ.get("RW_FAILPOINTS", ""))
    sp.add_argument("--arm", default=None,
                    help="validate a spec and print the export line")
    sp.add_argument("--ledger", nargs="?", const="", default=None,
                    metavar="FILE",
                    help="print a recorded fire ledger (omit FILE for "
                         "the live in-process ledger)")
    sp.set_defaults(fn=cmd_failpoints)
    args = p.parse_args(argv)
    return args.fn(args)
