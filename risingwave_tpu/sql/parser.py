"""SQL lexer + recursive-descent parser.

The `src/sqlparser/` analog (the reference forks sqlparser-rs; this is a
fresh Pratt-style parser over the dialect subset the framework executes).
"""
from __future__ import annotations

import re
from typing import Any, List, Optional, Tuple

from . import ast as A

# ---------------------------------------------------------------------------
# Lexer
# ---------------------------------------------------------------------------

_TOKEN_RE = re.compile(r"""
    (?P<ws>\s+|--[^\n]*|/\*.*?\*/)
  | (?P<num>\d+\.\d*(?:[eE][+-]?\d+)?|\.\d+(?:[eE][+-]?\d+)?|\d+(?:[eE][+-]?\d+)?)
  | (?P<str>'(?:[^']|'')*')
  | (?P<dollar>\$\$.*?\$\$)
  | (?P<param>\$\d+)
  | (?P<qid>"(?:[^"]|"")*")
  | (?P<id>[A-Za-z_][A-Za-z0-9_$]*)
  | (?P<op><>|!=|<=|>=|\|\||::|[-+*/%(),.;=<>\[\]])
""", re.VERBOSE | re.DOTALL)


class Token:
    __slots__ = ("kind", "value", "pos")

    def __init__(self, kind: str, value: str, pos: int = -1):
        self.kind = kind       # 'num' | 'str' | 'id' | 'kw' | 'op' | 'eof'
        self.value = value
        self.pos = pos         # char offset in the source text

    def __repr__(self):
        return f"{self.kind}:{self.value}"


_KEYWORDS = {
    "select", "from", "where", "group", "by", "having", "order", "limit",
    "offset", "as", "and", "or", "not", "is", "null", "true", "false",
    "join", "inner", "left", "right", "full", "outer", "cross", "on",
    "create", "table", "source", "materialized", "view", "sink", "index",
    "drop", "insert", "into", "values", "delete", "update", "set", "flush",
    "show", "tables", "sources", "sinks", "views", "primary", "key", "with",
    "case", "when", "then", "else", "end", "cast", "extract", "interval",
    "between", "in", "like", "distinct", "asc", "desc", "exists", "if",
    "over", "partition", "watermark", "for", "append", "only", "explain",
    "tumble", "hop", "emit", "window", "close", "cascade", "rows", "range",
    "unbounded", "preceding", "following", "current", "row", "union", "all",
    "alter",
}


def tokenize(sql: str) -> List[Token]:
    out: List[Token] = []
    pos = 0
    while pos < len(sql):
        m = _TOKEN_RE.match(sql, pos)
        if not m:
            raise ValueError(f"cannot tokenize at: {sql[pos:pos+30]!r}")
        start = pos
        pos = m.end()
        kind = m.lastgroup
        text = m.group()
        if kind == "ws":
            continue
        if kind == "id":
            low = text.lower()
            out.append(Token("kw" if low in _KEYWORDS else "id", low, start))
        elif kind == "qid":
            out.append(Token("id", text[1:-1].replace('""', '"'), start))
        elif kind == "str":
            out.append(Token("str", text[1:-1].replace("''", "'"), start))
        elif kind == "dollar":
            out.append(Token("str", text[2:-2], start))
        elif kind == "param":
            out.append(Token("param", text[1:], start))
        else:
            out.append(Token(kind, text, start))
    out.append(Token("eof", "", len(sql)))
    return out


# ---------------------------------------------------------------------------
# Parser
# ---------------------------------------------------------------------------

_CMP_OPS = {"=", "<>", "!=", "<", "<=", ">", ">="}
_TYPE_NAMES = {
    "int", "integer", "int4", "bigint", "int8", "smallint", "int2",
    "real", "float4", "double", "float8", "float", "numeric", "decimal",
    "boolean", "bool", "varchar", "text", "string", "character",
    "date", "time", "timestamp", "timestamptz", "interval", "bytea",
    "serial",
}


class Parser:
    def __init__(self, sql: str):
        self.toks = tokenize(sql)
        self.i = 0
        # CTE scope for the query being parsed: name -> Select | SetOp |
        # A.ChangelogTable; referenced names desugar in _table_factor
        self._ctes: dict = {}

    # ---- token helpers --------------------------------------------------
    def peek(self, ahead: int = 0) -> Token:
        return self.toks[min(self.i + ahead, len(self.toks) - 1)]

    def next(self) -> Token:
        t = self.toks[self.i]
        self.i += 1
        return t

    def accept(self, kind: str, value: Optional[str] = None) -> Optional[Token]:
        t = self.peek()
        if t.kind == kind and (value is None or t.value == value):
            return self.next()
        return None

    def expect(self, kind: str, value: Optional[str] = None) -> Token:
        t = self.accept(kind, value)
        if t is None:
            raise ValueError(
                f"expected {value or kind}, got {self.peek()!r} at {self.i}")
        return t

    def accept_kw(self, *kws: str) -> Optional[str]:
        t = self.peek()
        if t.kind == "kw" and t.value in kws:
            self.next()
            return t.value
        return None

    def expect_kw(self, kw: str) -> None:
        if not self.accept_kw(kw):
            raise ValueError(f"expected {kw.upper()}, got {self.peek()!r}")

    def ident(self) -> str:
        t = self.peek()
        if t.kind in ("id", "kw"):
            self.next()
            return t.value
        raise ValueError(f"expected identifier, got {t!r}")

    # ---- entry ----------------------------------------------------------
    def parse_statements(self) -> List[Any]:
        stmts = []
        while self.peek().kind != "eof":
            stmts.append(self.parse_statement())
            while self.accept("op", ";"):
                pass
        return stmts

    def parse_statement(self) -> Any:
        t = self.peek()
        if t.kind == "kw":
            if t.value == "select":
                return self.parse_query()
            if t.value == "create":
                return self.parse_create()
            if t.value == "drop":
                return self.parse_drop()
            if t.value == "insert":
                return self.parse_insert()
            if t.value == "delete":
                return self.parse_delete()
            if t.value == "update":
                return self.parse_update()
            if t.value == "flush":
                self.next()
                return A.Flush()
            if t.value == "show":
                self.next()
                if self.accept_kw("all"):
                    return A.ShowVar(None)
                kind = self.ident()
                if kind == "materialized":
                    self.expect_kw("views")
                    kind = "materialized views"
                if kind in ("tables", "sources", "sinks",
                            "materialized views"):
                    return A.ShowObjects(kind)
                return A.ShowVar(kind)
            if t.value == "set":
                self.next()
                return self._parse_set(system=False)
            if t.value == "explain":
                self.next()
                nxt = self.peek()
                if nxt.kind == "id" and nxt.value == "analyze":
                    # EXPLAIN ANALYZE <mv>: live per-operator stats of a
                    # RUNNING streaming job (no statement re-execution —
                    # the batch path has no runtime worth instrumenting)
                    self.next()
                    return A.ExplainAnalyze(self.ident())
                return A.Explain(self.parse_statement())
            if t.value == "alter":
                return self.parse_alter()
            if t.value == "with":
                return self.parse_query()
        raise ValueError(f"cannot parse statement at {t!r}")

    # ---- DDL ------------------------------------------------------------
    def _parse_set(self, system: bool) -> Any:
        """SET <name> [=|TO] <value>; values are literals or bare idents
        (PG-style, e.g. SET timezone TO utc)."""
        name = self.ident()
        if not self.accept("op", "="):
            if self.peek().kind == "id" and self.peek().value == "to":
                self.next()
        t = self.peek()
        if t.kind == "num":
            self.next()
            v: Any = (float(t.value) if any(c in t.value for c in ".eE")
                      else int(t.value))
        elif t.kind == "str":
            self.next()
            v = t.value
        elif t.kind == "kw" and t.value in ("true", "false"):
            self.next()
            v = t.value == "true"
        else:
            v = self.ident()
        return A.SetVar(name, v, system=system)

    def parse_alter(self) -> Any:
        """ALTER MATERIALIZED VIEW <name> SET PARALLELISM [=|TO] <n> /
        ALTER SYSTEM SET <param> [=|TO] <value>
        (`src/frontend/src/handler/alter_parallelism.rs`,
        `handler/alter_system.rs` analogs)."""
        self.expect_kw("alter")
        if self.peek().kind == "id" and self.peek().value == "system":
            self.next()
            self.expect_kw("set")
            return self._parse_set(system=True)
        self.expect_kw("materialized")
        self.expect_kw("view")
        name = self.ident()
        self.expect_kw("set")
        word = self.ident()
        if word != "parallelism":
            raise ValueError(f"ALTER ... SET {word!r} not supported")
        if not self.accept("op", "="):
            if self.peek().kind == "id" and self.peek().value == "to":
                self.next()
        tok = self.expect("num")
        return A.AlterParallelism(name, int(tok.value))

    def parse_create(self) -> Any:
        self.expect_kw("create")
        if self.accept_kw("table"):
            return self._create_table(is_source=False)
        if self.accept_kw("source"):
            return self._create_table(is_source=True)
        if self.accept_kw("materialized"):
            self.expect_kw("view")
            name = self.ident()
            self.expect_kw("as")
            q = self.parse_query()
            self._accept_emit_clause(q)
            return A.CreateMaterializedView(name, q)
        if (self.peek().kind == "id" and self.peek().value == "function") \
                or (self.peek().kind == "kw" and self.peek().value == "or"
                    and self.peek(1).value == "replace"):
            return self._create_function()
        if self.accept_kw("sink"):
            name = self.ident()
            from_name, query = None, None
            if self.accept_kw("from"):
                from_name = self.ident()
            else:
                self.expect_kw("as")
                query = self.parse_query()
            opts = self._with_options()
            return A.CreateSink(name, from_name, query, opts)
        if self.accept_kw("index"):
            name = self.ident()
            self.expect_kw("on")
            table = self.ident()
            self.expect("op", "(")
            cols = [self.ident()]
            while self.accept("op", ","):
                cols.append(self.ident())
            self.expect("op", ")")
            return A.CreateIndex(name, table, cols)
        raise ValueError(f"CREATE what? {self.peek()!r}")

    def _create_function(self) -> A.CreateFunction:
        """CREATE [OR REPLACE] FUNCTION name(t1, t2) RETURNS t
        LANGUAGE python AS $$ ... $$"""
        or_replace = False
        if self.peek().value == "or":
            self.next()
            if self.ident() != "replace":
                raise ValueError("expected REPLACE after CREATE OR")
            or_replace = True
        if self.ident() != "function":
            raise ValueError("CREATE what?")
        name = self.ident()
        arg_types: List[str] = []
        self.expect("op", "(")
        if not (self.peek().kind == "op" and self.peek().value == ")"):
            arg_types.append(self._func_param())
            while self.accept("op", ","):
                arg_types.append(self._func_param())
        self.expect("op", ")")
        word = self.ident()
        if word == "returns":
            ret = self._type_name()
            word = self.ident()
        else:
            raise ValueError("CREATE FUNCTION requires RETURNS <type>")
        if word != "language":
            raise ValueError("CREATE FUNCTION requires LANGUAGE")
        language = self.ident()
        self.expect_kw("as")
        body = self.expect("str").value
        return A.CreateFunction(name, arg_types, ret, language, body,
                                or_replace)

    def _func_param(self) -> str:
        """[pname] type — the optional parameter name is skipped."""
        if self.peek().kind == "id" and self.peek().value not in _TYPE_NAMES \
                and self.peek(1).kind in ("id", "kw") \
                and self.peek(1).value in _TYPE_NAMES:
            self.next()
        return self._type_name()

    def _accept_emit_clause(self, q: A.Select) -> None:
        if self.accept_kw("emit"):
            self.expect_kw("on")
            self.expect_kw("window")
            self.expect_kw("close")
            q.emit_on_window_close = True  # type: ignore[attr-defined]

    def _create_table(self, is_source: bool) -> A.CreateTable:
        name = self.ident()
        columns: List[A.ColumnDef] = []
        pk: List[str] = []
        watermark = None
        if self.accept("op", "("):
            while True:
                if self.accept_kw("primary"):
                    self.expect_kw("key")
                    self.expect("op", "(")
                    pk.append(self.ident())
                    while self.accept("op", ","):
                        pk.append(self.ident())
                    self.expect("op", ")")
                elif self.accept_kw("watermark"):
                    self.expect_kw("for")
                    col = self.ident()
                    self.expect_kw("as")
                    watermark = (col, self.parse_expr())
                else:
                    cname = self.ident()
                    tname = self._type_name()
                    cd = A.ColumnDef(cname, tname)
                    if self.accept_kw("primary"):
                        self.expect_kw("key")
                        cd.primary_key = True
                        pk.append(cname)
                    columns.append(cd)
                if not self.accept("op", ","):
                    break
            self.expect("op", ")")
        append_only = False
        if self.accept_kw("append"):
            self.expect_kw("only")
            append_only = True
        opts = self._with_options()
        return A.CreateTable(name, columns, pk, opts, append_only, is_source,
                             watermark)

    def _type_name(self) -> str:
        t = self.ident()
        if t == "double":
            self.accept_kw("precision") if False else self.accept("id", "precision")
            return "double"
        if t == "character":
            if self.accept("id", "varying"):
                t = "varchar"
        if t in ("numeric", "decimal", "varchar") and self.accept("op", "("):
            self.next()
            if self.accept("op", ","):
                self.next()
            self.expect("op", ")")
        return t

    def _with_options(self) -> dict:
        opts: dict = {}
        if self.accept_kw("with"):
            self.expect("op", "(")
            while True:
                k = self.ident()
                while self.accept("op", "."):
                    k += "." + self.ident()
                self.expect("op", "=")
                t = self.next()
                opts[k] = t.value
                if not self.accept("op", ","):
                    break
            self.expect("op", ")")
        return opts

    def parse_drop(self) -> A.DropObject:
        self.expect_kw("drop")
        kind = self.ident()
        if kind == "materialized":
            self.expect_kw("view")
            kind = "materialized view"
        if_exists = False
        if self.accept_kw("if"):
            self.expect_kw("exists")
            if_exists = True
        name = self.ident()
        cascade = bool(self.accept_kw("cascade"))
        return A.DropObject(kind, name, if_exists, cascade)

    # ---- DML ------------------------------------------------------------
    def parse_insert(self) -> A.Insert:
        self.expect_kw("insert")
        self.expect_kw("into")
        table = self.ident()
        cols: List[str] = []
        if self.accept("op", "("):
            cols.append(self.ident())
            while self.accept("op", ","):
                cols.append(self.ident())
            self.expect("op", ")")
        if self.accept_kw("values"):
            rows = []
            while True:
                self.expect("op", "(")
                row = [self.parse_expr()]
                while self.accept("op", ","):
                    row.append(self.parse_expr())
                self.expect("op", ")")
                rows.append(row)
                if not self.accept("op", ","):
                    break
            return A.Insert(table, cols, rows)
        q = self.parse_query()
        return A.Insert(table, cols, [], q)

    def parse_delete(self) -> A.Delete:
        self.expect_kw("delete")
        self.expect_kw("from")
        table = self.ident()
        where = self.parse_expr() if self.accept_kw("where") else None
        return A.Delete(table, where)

    def parse_update(self) -> A.Update:
        self.expect_kw("update")
        table = self.ident()
        self.expect_kw("set")
        assigns = []
        while True:
            c = self.ident()
            self.expect("op", "=")
            assigns.append((c, self.parse_expr()))
            if not self.accept("op", ","):
                break
        where = self.parse_expr() if self.accept_kw("where") else None
        return A.Update(table, assigns, where)

    # ---- SELECT ---------------------------------------------------------
    def parse_query(self) -> A.Query:
        """[WITH ctes] select [UNION [ALL] select]... — the `ast/query.rs`
        Query/SetExpr surface. CTEs include the changelog form
        (`WITH name AS changelog FROM obj`)."""
        saved = self._ctes
        if self.accept_kw("with"):
            self._ctes = dict(saved)
            while True:
                name = self.ident()
                self.expect_kw("as")
                if self.peek().kind == "id" \
                        and self.peek().value == "changelog":
                    self.next()
                    self.expect_kw("from")
                    obj = self.ident()
                    while self.accept("op", "."):   # schema-qualified
                        obj = self.ident()
                    self._ctes[name] = A.ChangelogTable(obj, alias=name)
                else:
                    self.expect("op", "(")
                    self._ctes[name] = self.parse_query()
                    self.expect("op", ")")
                if not self.accept("op", ","):
                    break
        try:
            q: A.Query = self.parse_select()
            while self.accept_kw("union"):
                prev = q.right if isinstance(q, A.SetOp) else q
                if prev.order_by or prev.limit is not None:
                    raise ValueError("ORDER BY/LIMIT before UNION must be "
                                     "parenthesized")
                all_ = bool(self.accept_kw("all"))
                if self.accept_kw("distinct"):
                    all_ = False
                q = A.SetOp("union", all_, q, self.parse_select())
            if isinstance(q, A.SetOp):
                # trailing ORDER BY/LIMIT bind to the whole set operation
                last = q.right
                q.order_by = last.order_by
                q.limit, q.offset = last.limit, last.offset
                last.order_by, last.limit, last.offset = [], None, None
        finally:
            self._ctes = saved
        return q

    def parse_select(self) -> A.Select:
        self.expect_kw("select")
        distinct = bool(self.accept_kw("distinct"))
        items = [self._select_item()]
        while self.accept("op", ","):
            items.append(self._select_item())
        from_ = None
        if self.accept_kw("from"):
            from_ = self._table_expr()
        where = self.parse_expr() if self.accept_kw("where") else None
        group_by: List[A.ExprNode] = []
        if self.accept_kw("group"):
            self.expect_kw("by")
            group_by.append(self.parse_expr())
            while self.accept("op", ","):
                group_by.append(self.parse_expr())
        having = self.parse_expr() if self.accept_kw("having") else None
        order_by: List[Tuple[A.ExprNode, bool]] = []
        if self.accept_kw("order"):
            self.expect_kw("by")
            order_by.append(self._order_item())
            while self.accept("op", ","):
                order_by.append(self._order_item())
        limit = offset = None
        if self.accept_kw("limit"):
            limit = int(self.expect("num").value)
        if self.accept_kw("offset"):
            offset = int(self.expect("num").value)
        return A.Select(items, from_, where, group_by, having, order_by,
                        limit, offset, distinct)

    def _select_item(self) -> A.SelectItem:
        if self.accept("op", "*"):
            return A.SelectItem(A.Star())
        # table.* ?
        if (self.peek().kind in ("id",) and self.peek(1).kind == "op"
                and self.peek(1).value == "." and self.peek(2).value == "*"):
            t = self.ident()
            self.next(); self.next()
            return A.SelectItem(A.Star(table=t))
        e = self.parse_expr()
        alias = None
        if self.accept_kw("as"):
            alias = self.ident()
        elif self.peek().kind == "id":
            alias = self.ident()
        return A.SelectItem(e, alias)

    def _order_item(self) -> Tuple[A.ExprNode, bool]:
        e = self.parse_expr()
        desc = False
        if self.accept_kw("desc"):
            desc = True
        else:
            self.accept_kw("asc")
        return (e, desc)

    def _table_expr(self) -> A.TableRef:
        left = self._table_factor()
        while True:
            if self.accept("op", ","):
                right = self._table_factor()
                left = A.Join(left, right, "cross", None)
                continue
            kind = None
            if self.peek().kind == "id" \
                    and self.peek().value.lower() == "asof":
                # ASOF JOIN / ASOF INNER JOIN / ASOF LEFT [OUTER] JOIN
                # (`parser.rs:5012` Keyword::ASOF)
                self.next()
                if self.accept_kw("join"):
                    kind = "asof_inner"
                elif self.accept_kw("inner"):
                    self.expect_kw("join")
                    kind = "asof_inner"
                elif self.accept_kw("left"):
                    self.accept_kw("outer")
                    self.expect_kw("join")
                    kind = "asof_left"
                else:
                    raise ValueError("expected JOIN, INNER JOIN or LEFT "
                                     "JOIN after ASOF")
            elif self.accept_kw("join"):
                kind = "inner"
            elif self.accept_kw("inner"):
                self.expect_kw("join")
                kind = "inner"
            elif self.accept_kw("left"):
                self.accept_kw("outer")
                self.expect_kw("join")
                kind = "left"
            elif self.accept_kw("right"):
                self.accept_kw("outer")
                self.expect_kw("join")
                kind = "right"
            elif self.accept_kw("full"):
                self.accept_kw("outer")
                self.expect_kw("join")
                kind = "full"
            elif self.accept_kw("cross"):
                self.expect_kw("join")
                kind = "cross"
            if kind is None:
                return left
            right = self._table_factor()
            on = None
            if kind != "cross":
                self.expect_kw("on")
                on = self.parse_expr()
            left = A.Join(left, right, kind, on)

    def _table_factor(self) -> A.TableRef:
        if self.accept_kw("tumble") or self.accept_kw("hop"):
            kind = self.toks[self.i - 1].value
            self.expect("op", "(")
            inner = self._table_factor()
            self.expect("op", ",")
            tc = self.ident()
            args = []
            while self.accept("op", ","):
                args.append(self.parse_expr())
            self.expect("op", ")")
            alias = self._alias()
            return A.WindowTable(kind, inner, tc, args, alias)
        if self.accept("op", "("):
            if self.peek().kind == "kw" and self.peek().value in ("select",
                                                                  "with"):
                q = self.parse_query()
                self.expect("op", ")")
                return A.SubqueryTable(q, self._alias())
            t = self._table_expr()
            self.expect("op", ")")
            a = self._alias()
            if a:
                t.alias = a
            return t
        name = self.ident()
        if name.lower() in ("generate_series", "unnest") \
                and self.peek().kind == "op" and self.peek().value == "(":
            self.expect("op", "(")
            args = [self.parse_expr()]
            while self.accept("op", ","):
                args.append(self.parse_expr())
            self.expect("op", ")")
            return A.TableFunctionTable(name.lower(), args, self._alias())
        # t FOR SYSTEM_TIME AS OF PROCTIME() — temporal join version side
        if self.peek().kind == "kw" and self.peek().value == "for" \
                and self.peek(1).kind == "id" \
                and self.peek(1).value.lower() == "system_time":
            self.next()
            self.next()
            self.expect_kw("as")
            if not (self.peek().kind == "id"
                    and self.peek().value.lower() == "of"):
                raise ValueError("expected OF after FOR SYSTEM_TIME AS")
            self.next()
            fn = self.ident()
            if fn.lower() != "proctime":
                raise ValueError("only FOR SYSTEM_TIME AS OF PROCTIME() "
                                 "is supported")
            self.expect("op", "(")
            self.expect("op", ")")
            return A.TemporalTable(A.NamedTable(name, None), self._alias())
        alias = self._alias()
        cte = self._ctes.get(name)
        if cte is not None:
            if isinstance(cte, A.ChangelogTable):
                return A.ChangelogTable(cte.inner, alias or name)
            return A.SubqueryTable(cte, alias or name)
        return A.NamedTable(name, alias)

    def _alias(self) -> Optional[str]:
        if self.accept_kw("as"):
            return self.ident()
        # ASOF introduces a join (t ASOF JOIN u ...), never an implicit
        # alias — `AS asof` still works
        if self.peek().kind == "id" and self.peek().value.lower() != "asof":
            return self.ident()
        return None

    # ---- expressions (precedence climbing) ------------------------------
    def parse_expr(self) -> A.ExprNode:
        return self._or_expr()

    def _or_expr(self) -> A.ExprNode:
        e = self._and_expr()
        while self.accept_kw("or"):
            e = A.BinOp("or", e, self._and_expr())
        return e

    def _and_expr(self) -> A.ExprNode:
        e = self._not_expr()
        while self.accept_kw("and"):
            e = A.BinOp("and", e, self._not_expr())
        return e

    def _not_expr(self) -> A.ExprNode:
        if self.accept_kw("not"):
            return A.UnaryOp("not", self._not_expr())
        return self._cmp_expr()

    def _cmp_expr(self) -> A.ExprNode:
        e = self._add_expr()
        while True:
            t = self.peek()
            if t.kind == "op" and t.value in _CMP_OPS:
                self.next()
                e = A.BinOp(t.value, e, self._add_expr())
                continue
            if t.kind == "kw" and t.value == "is":
                self.next()
                neg = bool(self.accept_kw("not"))
                self.expect_kw("null")
                e = A.IsNullExpr(e, neg)
                continue
            if t.kind == "kw" and t.value in ("between", "in", "like"):
                self.next()
                if t.value == "between":
                    lo = self._add_expr()
                    self.expect_kw("and")
                    hi = self._add_expr()
                    e = A.Between(e, lo, hi, False)
                elif t.value == "in":
                    self.expect("op", "(")
                    if self.peek().kind == "kw" and \
                            self.peek().value == "select":
                        q = self.parse_select()
                        self.expect("op", ")")
                        e = A.InSubquery(e, q, False)
                        continue
                    items = [self.parse_expr()]
                    while self.accept("op", ","):
                        items.append(self.parse_expr())
                    self.expect("op", ")")
                    e = A.InList(e, items, False)
                else:
                    pat = self._add_expr()
                    e = A.FuncCall("like", [e, pat])
                continue
            if t.kind == "kw" and t.value == "not" and \
                    self.peek(1).value in ("between", "in", "like"):
                self.next()
                kw = self.next().value
                if kw == "between":
                    lo = self._add_expr()
                    self.expect_kw("and")
                    hi = self._add_expr()
                    e = A.Between(e, lo, hi, True)
                elif kw == "in":
                    self.expect("op", "(")
                    if self.peek().kind == "kw" and \
                            self.peek().value == "select":
                        q = self.parse_select()
                        self.expect("op", ")")
                        e = A.InSubquery(e, q, True)
                        continue
                    items = [self.parse_expr()]
                    while self.accept("op", ","):
                        items.append(self.parse_expr())
                    self.expect("op", ")")
                    e = A.InList(e, items, True)
                else:
                    pat = self._add_expr()
                    e = A.UnaryOp("not", A.FuncCall("like", [e, pat]))
                continue
            return e

    def _add_expr(self) -> A.ExprNode:
        e = self._mul_expr()
        while True:
            t = self.peek()
            if t.kind == "op" and t.value in ("+", "-", "||"):
                self.next()
                op = "concat" if t.value == "||" else t.value
                r = self._mul_expr()
                e = A.FuncCall("concat_op", [e, r]) if op == "concat" \
                    else A.BinOp(op, e, r)
            else:
                return e

    def _mul_expr(self) -> A.ExprNode:
        e = self._unary_expr()
        while True:
            t = self.peek()
            if t.kind == "op" and t.value in ("*", "/", "%"):
                self.next()
                e = A.BinOp(t.value, e, self._unary_expr())
            else:
                return e

    def _unary_expr(self) -> A.ExprNode:
        if self.accept("op", "-"):
            return A.UnaryOp("-", self._unary_expr())
        if self.accept("op", "+"):
            return self._unary_expr()
        return self._postfix_expr()

    def _postfix_expr(self) -> A.ExprNode:
        e = self._primary()
        while True:
            if self.accept("op", "::"):
                e = A.CastExpr(e, self._type_name())
            elif self.peek().kind == "op" and self.peek().value == "[":
                self.next()
                idx = int(self.expect("num").value)
                self.expect("op", "]")
                e = A.Index(e, idx)
            else:
                return e

    def _primary(self) -> A.ExprNode:
        t = self.peek()
        if t.kind == "num":
            self.next()
            v = float(t.value) if any(c in t.value for c in ".eE") \
                else int(t.value)
            return A.Lit(v)
        if t.kind == "str":
            self.next()
            return A.Lit(t.value)
        if t.kind == "param":
            self.next()
            return A.Param(int(t.value))
        if t.kind == "op" and t.value == "(":
            self.next()
            if self.peek().kind == "kw" and self.peek().value == "select":
                q = self.parse_select()
                self.expect("op", ")")
                return A.SubqueryExpr(q)
            e = self.parse_expr()
            self.expect("op", ")")
            return e
        if t.kind == "kw":
            if t.value == "null":
                self.next()
                return A.Lit(None)
            if t.value in ("true", "false"):
                self.next()
                return A.Lit(t.value == "true")
            if t.value == "interval":
                self.next()
                s = self.expect("str").value
                unit = None
                if self.peek().kind == "id":
                    unit = self.ident()
                return A.Lit(s + (" " + unit if unit else ""), "interval")
            if t.value == "case":
                return self._case()
            if t.value == "cast":
                self.next()
                self.expect("op", "(")
                e = self.parse_expr()
                self.expect_kw("as")
                ty = self._type_name()
                self.expect("op", ")")
                return A.CastExpr(e, ty)
            if t.value == "extract":
                self.next()
                self.expect("op", "(")
                fld = self.ident()
                self.expect_kw("from")
                e = self.parse_expr()
                self.expect("op", ")")
                return A.ExtractExpr(fld, e)
            if t.value == "exists":
                raise ValueError("EXISTS subqueries not supported yet")
            if t.value == "distinct":
                raise ValueError("misplaced DISTINCT")
        if t.kind == "id" \
                and t.value.lower() in ("date", "timestamp", "timestamptz",
                                        "time") \
                and self.peek(1).kind == "str":
            # typed string literal: DATE '2024-01-01' == CAST(.. AS DATE)
            ty = t.value.lower()
            self.next()
            s = self.expect("str").value
            return A.CastExpr(A.Lit(s), ty)
        if t.kind == "id" and t.value.lower() == "array" \
                and self.peek(1).kind == "op" and self.peek(1).value == "[":
            self.next()
            self.next()
            items = []
            if not (self.peek().kind == "op" and self.peek().value == "]"):
                items.append(self.parse_expr())
                while self.accept("op", ","):
                    items.append(self.parse_expr())
            self.expect("op", "]")
            return A.ArrayLit(items)
        # identifier: column, qualified column, or function call
        name = self.ident()
        if self.accept("op", "("):
            distinct = bool(self.accept_kw("distinct"))
            args: List[A.ExprNode] = []
            if self.accept("op", "*"):
                pass  # count(*)
            elif not (self.peek().kind == "op" and self.peek().value == ")"):
                args.append(self.parse_expr())
                while self.accept("op", ","):
                    args.append(self.parse_expr())
            self.expect("op", ")")
            within = None
            if self.peek().kind == "id" and self.peek().value == "within" \
                    and self.peek(1).kind == "kw" \
                    and self.peek(1).value == "group":
                self.next()
                self.next()
                self.expect("op", "(")
                self.expect_kw("order")
                self.expect_kw("by")
                within = self.parse_expr()
                self.expect("op", ")")
            filt = None
            if self.peek().kind == "id" and self.peek().value == "filter" \
                    and self.peek(1).kind == "op" \
                    and self.peek(1).value == "(":
                self.next()
                self.expect("op", "(")
                self.expect_kw("where")
                filt = self.parse_expr()
                self.expect("op", ")")
            over = None
            if self.accept_kw("over"):
                over = self._window_spec()
            return A.FuncCall(name, args, distinct, over, filt,
                              within_group=within)
        if self.accept("op", "."):
            col = self.ident()
            return A.Col(col, table=name)
        return A.Col(name)

    def _window_spec(self) -> A.WindowSpec:
        self.expect("op", "(")
        partition: List[A.ExprNode] = []
        order: List[Tuple[A.ExprNode, bool]] = []
        if self.accept_kw("partition"):
            self.expect_kw("by")
            partition.append(self.parse_expr())
            while self.accept("op", ","):
                partition.append(self.parse_expr())
        if self.accept_kw("order"):
            self.expect_kw("by")
            order.append(self._order_item())
            while self.accept("op", ","):
                order.append(self._order_item())
        frame = None
        if self.accept_kw("rows") or self.accept_kw("range"):
            mode = self.toks[self.i - 1].value
            if self.accept_kw("between"):
                start = self._frame_bound()
                self.expect_kw("and")
                end = self._frame_bound()
            else:
                start = self._frame_bound()
                end = ("current",)
            frame = (mode, start, end)
        self.expect("op", ")")
        return A.WindowSpec(partition, order, frame)

    def _frame_bound(self) -> Tuple:
        if self.accept_kw("unbounded"):
            if self.accept_kw("preceding"):
                return ("unbounded", "preceding")
            if self.accept_kw("following"):
                return ("unbounded", "following")
            raise ValueError("expected PRECEDING or FOLLOWING after "
                             "UNBOUNDED")
        if self.accept_kw("current"):
            self.expect_kw("row")
            return ("current",)
        e = self.parse_expr()
        if self.accept_kw("preceding"):
            return ("preceding", e)
        if self.accept_kw("following"):
            return ("following", e)
        raise ValueError("expected PRECEDING or FOLLOWING in frame bound")

    def _case(self) -> A.CaseExpr:
        self.expect_kw("case")
        operand = None
        if not (self.peek().kind == "kw" and self.peek().value == "when"):
            operand = self.parse_expr()
        branches = []
        while self.accept_kw("when"):
            cond = self.parse_expr()
            self.expect_kw("then")
            branches.append((cond, self.parse_expr()))
        else_expr = self.parse_expr() if self.accept_kw("else") else None
        self.expect_kw("end")
        return A.CaseExpr(operand, branches, else_expr)


def parse_sql(sql: str) -> List[Any]:
    return Parser(sql).parse_statements()


def parse_sql_with_text(sql: str) -> List[tuple]:
    """[(stmt, source_text)] — source slices let DDL be logged verbatim."""
    p = Parser(sql)
    out = []
    while p.peek().kind != "eof":
        start = p.peek().pos
        stmt = p.parse_statement()
        end = p.peek().pos if p.peek().kind != "eof" else len(sql)
        while p.accept("op", ";"):
            end = p.peek().pos if p.peek().kind != "eof" else len(sql)
        out.append((stmt, sql[start:end].rstrip().rstrip(";")))
    return out
