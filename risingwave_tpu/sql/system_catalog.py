"""System catalogs + plan rendering.

Analog of the reference's `rw_catalog` system tables
(`src/frontend/src/catalog/system_catalog/rw_catalog/`) and EXPLAIN
output (`src/frontend/src/optimizer/plan_node/mod.rs` Display impls),
collapsed to the single-process runtime: system tables are virtual
batch-only snapshots built from the live catalog; EXPLAIN renders the
actually-planned executor tree (the physical plan — this runtime lowers
AST straight to executors)."""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Tuple

from ..core import dtypes as T
from ..core.schema import Schema


def _rows_tables(db) -> List[Tuple]:
    return [(o.name, o.table_id, o.append_only)
            for o in db.catalog.objects.values() if o.kind == "table"]


def _rows_mvs(db) -> List[Tuple]:
    return [(o.name, o.table_id,
             o.parallelism if o.parallelism is not None else 0)
            for o in db.catalog.objects.values() if o.kind == "mv"]


def _rows_sources(db) -> List[Tuple]:
    return [(o.name, o.table_id,
             o.with_options.get("connector", "dml"))
            for o in db.catalog.objects.values()
            if o.kind in ("source", "table")]


def _rows_sinks(db) -> List[Tuple]:
    return [(o.name, o.with_options.get("connector", "collect"))
            for o in db.catalog.objects.values() if o.kind == "sink"]


def _rows_params(db) -> List[Tuple]:
    return [(k, str(v)) for k, v in sorted(db.system_params.values.items())]


def _rows_columns(db) -> List[Tuple]:
    out = []
    for o in db.catalog.objects.values():
        if o.kind in ("table", "source", "mv"):
            for i, f in enumerate(o.schema.fields):
                out.append((o.name, f.name, i, str(f.dtype)))
    return out


# name -> (schema, row builder). Names mirror rw_catalog.
SYSTEM_TABLES: Dict[str, Tuple[Schema, Callable[[Any], List[Tuple]]]] = {
    "rw_tables": (Schema.of(("name", T.VARCHAR), ("id", T.INT64),
                            ("append_only", T.BOOLEAN)), _rows_tables),
    "rw_materialized_views": (
        Schema.of(("name", T.VARCHAR), ("id", T.INT64),
                  ("parallelism", T.INT64)), _rows_mvs),
    "rw_sources": (Schema.of(("name", T.VARCHAR), ("id", T.INT64),
                             ("connector", T.VARCHAR)), _rows_sources),
    "rw_sinks": (Schema.of(("name", T.VARCHAR), ("connector", T.VARCHAR)),
                 _rows_sinks),
    "rw_system_parameters": (
        Schema.of(("name", T.VARCHAR), ("value", T.VARCHAR)), _rows_params),
    "rw_columns": (Schema.of(("relation", T.VARCHAR), ("name", T.VARCHAR),
                             ("position", T.INT64), ("type", T.VARCHAR)),
                   _rows_columns),
    # per-barrier span rows (utils/trace.py): job='<barrier>' carries the
    # whole-epoch state/total; phase RUNNING / OPEN marks a stall
    "rw_barrier_trace": (
        Schema.of(("epoch", T.INT64), ("kind", T.VARCHAR),
                  ("job", T.VARCHAR), ("state", T.VARCHAR),
                  ("ms", T.FLOAT64)),
        lambda db: db.tracer.rows()),
    # backfill progress per streaming job (`barrier/progress.rs` /
    # rw_ddl_progress analog): rows emitted / snapshot total per upstream
    "rw_ddl_progress": (
        Schema.of(("job", T.VARCHAR), ("upstream", T.VARCHAR),
                  ("emitted", T.INT64), ("total", T.INT64),
                  ("progress", T.VARCHAR)),
        lambda db: _ddl_progress(db)),
    # epoch-timeline profiler (utils/profile.py): one row per fused-job
    # epoch with its phase split — host pack, async dispatch, blocking
    # device sync, state-table commit (ring-buffered; the full history
    # is in epoch_profile.jsonl / `risectl profile`)
    "rw_epoch_profile": (
        Schema.of(("job", T.VARCHAR), ("seq", T.INT64),
                  ("events", T.INT64), ("shards", T.INT64),
                  ("host_pack_ms", T.FLOAT64),
                  ("dispatch_ms", T.FLOAT64), ("exchange_ms", T.FLOAT64),
                  ("device_sync_ms", T.FLOAT64),
                  ("commit_ms", T.FLOAT64), ("wall_ms", T.FLOAT64)),
        lambda db: _epoch_profile(db)),
    # per-node attribution from the on-device stats vector: row flow,
    # observed entries vs capacity (occupancy), allocated HBM
    "rw_fused_node_stats": (
        Schema.of(("job", T.VARCHAR), ("node", T.INT64),
                  ("type", T.VARCHAR), ("slot", T.VARCHAR),
                  ("rows_in", T.INT64), ("rows_out", T.INT64),
                  ("entries", T.INT64), ("capacity", T.INT64),
                  ("occupancy", T.FLOAT64), ("hbm_mb", T.FLOAT64),
                  ("overflow", T.BOOLEAN)),
        lambda db: _fused_node_stats(db)),
    # metrics-plane worker heartbeats: age of the last M frame per
    # remote worker; `wedged?` = alive process, stale heartbeat
    "rw_worker_liveness": (
        Schema.of(("job", T.VARCHAR), ("worker", T.VARCHAR),
                  ("pid", T.INT64), ("last_epoch", T.INT64),
                  ("heartbeat_age_s", T.FLOAT64), ("state", T.VARCHAR)),
        lambda db: db._worker_liveness_rows()),
}


def _epoch_profile(db) -> List[Tuple]:
    return [row for job in db._fused.values()
            for row in job.profiler.rows()]


def _fused_node_stats(db) -> List[Tuple]:
    return [(name,) + row for name, job in db._fused.items()
            for row in job.node_report()]


def _ddl_progress(db) -> List[Tuple]:
    from .database import _Backfill, _walk_executors
    out = []
    for obj in db.catalog.objects.values():
        rt = obj.runtime if isinstance(obj.runtime, dict) else None
        shared = rt.get("shared") if rt else None
        if shared is None:
            continue
        for e in _walk_executors(shared.upstream):
            if isinstance(e, _Backfill) and e.total:
                out.append((obj.name, e.upstream_name, e.emitted,
                            e.total, f"{e.progress * 100:.1f}%"))
    return out


# ---------------------------------------------------------------------------
# EXPLAIN rendering
# ---------------------------------------------------------------------------

def _label(e) -> str:
    name = e.name or type(e).__name__
    bits: List[str] = []
    gk = getattr(e, "group_key_indices", None)
    if gk is not None:
        bits.append(f"group_key={list(gk)}")
    calls = getattr(e, "calls", None)
    if calls:
        try:
            bits.append("aggs=[" + ", ".join(c.kind for c in calls) + "]")
        except Exception:
            pass
    ki = getattr(e, "key_idx", None)
    if isinstance(ki, dict):
        bits.append(f"on={ki.get('a')}={ki.get('b')}")
    mesh = getattr(e, "mesh", None)
    if mesh is not None:
        bits.append(f"mesh={mesh.devices.size}")
    if getattr(e, "append_only", False):
        bits.append("append_only")
    return name + (" { " + ", ".join(bits) + " }" if bits else "")


def render_plan(e, depth: int = 0) -> str:
    lines = ["  " * depth + ("-> " if depth else "") + _label(e)]
    children = []
    for attr in ("input", "left_exec", "right_exec", "port"):
        c = getattr(e, attr, None)
        if c is not None:
            children.append(c)
    children.extend(getattr(e, "inputs", ()))
    for c in children:
        lines.append(render_plan(c, depth + 1))
    return "\n".join(lines)
