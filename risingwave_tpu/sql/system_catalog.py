"""System catalogs + plan rendering.

Analog of the reference's `rw_catalog` system tables
(`src/frontend/src/catalog/system_catalog/rw_catalog/`) and EXPLAIN
output (`src/frontend/src/optimizer/plan_node/mod.rs` Display impls),
collapsed to the single-process runtime: system tables are virtual
batch-only snapshots built from the live catalog; EXPLAIN renders the
actually-planned executor tree (the physical plan — this runtime lowers
AST straight to executors)."""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Tuple

from ..core import dtypes as T
from ..core.schema import Schema


def _rows_tables(db) -> List[Tuple]:
    return [(o.name, o.table_id, o.append_only)
            for o in db.catalog.objects.values() if o.kind == "table"]


def _rows_mvs(db) -> List[Tuple]:
    return [(o.name, o.table_id,
             o.parallelism if o.parallelism is not None else 0)
            for o in db.catalog.objects.values() if o.kind == "mv"]


def _rows_sources(db) -> List[Tuple]:
    return [(o.name, o.table_id,
             o.with_options.get("connector", "dml"))
            for o in db.catalog.objects.values()
            if o.kind in ("source", "table")]


def _rows_sinks(db) -> List[Tuple]:
    return [(o.name, o.with_options.get("connector", "collect"))
            for o in db.catalog.objects.values() if o.kind == "sink"]


def _rows_params(db) -> List[Tuple]:
    return [(k, str(v)) for k, v in sorted(db.system_params.values.items())]


def _rows_columns(db) -> List[Tuple]:
    out = []
    for o in db.catalog.objects.values():
        if o.kind in ("table", "source", "mv"):
            for i, f in enumerate(o.schema.fields):
                out.append((o.name, f.name, i, str(f.dtype)))
    return out


# name -> (schema, row builder). Names mirror rw_catalog.
SYSTEM_TABLES: Dict[str, Tuple[Schema, Callable[[Any], List[Tuple]]]] = {
    "rw_tables": (Schema.of(("name", T.VARCHAR), ("id", T.INT64),
                            ("append_only", T.BOOLEAN)), _rows_tables),
    "rw_materialized_views": (
        Schema.of(("name", T.VARCHAR), ("id", T.INT64),
                  ("parallelism", T.INT64)), _rows_mvs),
    "rw_sources": (Schema.of(("name", T.VARCHAR), ("id", T.INT64),
                             ("connector", T.VARCHAR)), _rows_sources),
    "rw_sinks": (Schema.of(("name", T.VARCHAR), ("connector", T.VARCHAR)),
                 _rows_sinks),
    "rw_system_parameters": (
        Schema.of(("name", T.VARCHAR), ("value", T.VARCHAR)), _rows_params),
    "rw_columns": (Schema.of(("relation", T.VARCHAR), ("name", T.VARCHAR),
                             ("position", T.INT64), ("type", T.VARCHAR)),
                   _rows_columns),
    # per-barrier span rows (utils/trace.py): job='<barrier>' carries the
    # whole-epoch state/total; phase RUNNING / OPEN marks a stall
    "rw_barrier_trace": (
        Schema.of(("epoch", T.INT64), ("kind", T.VARCHAR),
                  ("job", T.VARCHAR), ("state", T.VARCHAR),
                  ("ms", T.FLOAT64)),
        lambda db: db.tracer.rows()),
    # backfill progress per streaming job (`barrier/progress.rs` /
    # rw_ddl_progress analog): rows emitted / snapshot total per upstream
    "rw_ddl_progress": (
        Schema.of(("job", T.VARCHAR), ("upstream", T.VARCHAR),
                  ("emitted", T.INT64), ("total", T.INT64),
                  ("progress", T.VARCHAR)),
        lambda db: _ddl_progress(db)),
    # epoch-timeline profiler (utils/profile.py): one row per fused-job
    # epoch with its phase split — host pack, H2D transfer enqueue
    # (staged ingest buffers), async dispatch, blocking device sync,
    # state-table commit (ring-buffered; the full history is in
    # epoch_profile.jsonl / `risectl profile`). pack/h2d split the old
    # host_pack column disjointly; promote_h2d/demote_d2h are the state
    # tier's surgery phases (zero with tiering off).
    "rw_epoch_profile": (
        Schema.of(("job", T.VARCHAR), ("seq", T.INT64),
                  ("events", T.INT64), ("shards", T.INT64),
                  ("pack_ms", T.FLOAT64), ("h2d_ms", T.FLOAT64),
                  ("promote_h2d_ms", T.FLOAT64),
                  ("dispatch_ms", T.FLOAT64), ("exchange_ms", T.FLOAT64),
                  ("device_sync_ms", T.FLOAT64),
                  ("demote_d2h_ms", T.FLOAT64),
                  ("commit_ms", T.FLOAT64), ("wall_ms", T.FLOAT64)),
        lambda db: _epoch_profile(db)),
    # per-node attribution from the on-device stats vector: row flow,
    # observed entries vs capacity (occupancy), allocated HBM
    "rw_fused_node_stats": (
        Schema.of(("job", T.VARCHAR), ("node", T.INT64),
                  ("type", T.VARCHAR), ("slot", T.VARCHAR),
                  ("rows_in", T.INT64), ("rows_out", T.INT64),
                  ("entries", T.INT64), ("capacity", T.INT64),
                  ("occupancy", T.FLOAT64), ("hbm_mb", T.FLOAT64),
                  ("overflow", T.BOOLEAN)),
        lambda db: _fused_node_stats(db)),
    # metrics-plane worker heartbeats: age of the last frame per remote
    # worker (ANY frame counts — data proves liveness as well as M
    # frames); `wedged?` = alive process, stale heartbeat, and no
    # undrained output waiting on the coordinator. Ages recompute at
    # SELECT time.
    "rw_worker_liveness": (
        Schema.of(("job", T.VARCHAR), ("worker", T.VARCHAR),
                  ("pid", T.INT64), ("last_epoch", T.INT64),
                  ("heartbeat_age_s", T.FLOAT64), ("state", T.VARCHAR)),
        lambda db: db._worker_liveness_rows()),
    # source->MV end-to-end freshness (utils/freshness.py): last commit's
    # ingest->commit wall, the SELECT-time staleness (now - last
    # committed ingest), and ring quantiles
    "rw_mv_freshness": (
        Schema.of(("mv", T.VARCHAR), ("epoch", T.INT64),
                  ("ingest_ts", T.FLOAT64), ("commit_ts", T.FLOAT64),
                  ("freshness_s", T.FLOAT64), ("staleness_s", T.FLOAT64),
                  ("p50_s", T.FLOAT64), ("p99_s", T.FLOAT64),
                  ("commits", T.INT64)),
        lambda db: db._freshness.rows()),
    # key-skew telemetry (device/skew_stats.py): per keyed fused node,
    # the vnode-occupancy histogram (metric='vnode_occ', one row per
    # bucket, share = fraction of live keys), its max/mean ratio
    # (metric='skew_ratio', share carries the ratio, value the live
    # total) and the top-K heavy-hitter candidates (metric='hot_key',
    # key = 40-bit-truncated hot key, value = its per-epoch row count)
    "rw_key_skew": (
        Schema.of(("job", T.VARCHAR), ("node", T.INT64),
                  ("type", T.VARCHAR), ("metric", T.VARCHAR),
                  ("ordinal", T.INT64), ("key", T.INT64),
                  ("value", T.INT64), ("share", T.FLOAT64)),
        lambda db: _key_skew(db)),
    # tiered-state residency (device/tiering.py): per demotion-eligible
    # fused node, the hot-tier residency high-water vs the cold-tier
    # row count, whether the Xor8 negative cache is live, whether the
    # node can demote at all (promotable=false nodes are recency-stats
    # only), and the job-wide demotion/promotion/filter counters
    "rw_state_tiering": (
        Schema.of(("job", T.VARCHAR), ("node", T.INT64),
                  ("type", T.VARCHAR), ("resident", T.INT64),
                  ("cold", T.INT64), ("filter_live", T.BOOLEAN),
                  ("promotable", T.BOOLEAN), ("demotions", T.INT64),
                  ("promotions", T.INT64), ("demote_events", T.INT64),
                  ("filter_probes", T.INT64), ("filter_hits", T.INT64),
                  ("filter_fallbacks", T.INT64)),
        lambda db: _state_tiering(db)),
    # serving-tier read cache (serving/read_cache.py): one row per
    # cached fused MV — the snapshot's epoch stamp and row count plus
    # the hit/miss/coalesced/fill counters that prove the one-pull-per-
    # (MV, epoch) invariant is holding in production
    "rw_serving_cache": (
        Schema.of(("mv", T.VARCHAR), ("cache_epoch", T.INT64),
                  ("cached_rows", T.INT64), ("hits", T.INT64),
                  ("misses", T.INT64), ("coalesced", T.INT64),
                  ("fills", T.INT64)),
        lambda db: list(db.read_cache.report())),
    # serving-tier device-pull accounting (shard_exec.PULL_STATS): how
    # many host transfers SELECT serving has cost, split by the replica
    # column that served each one — the read-load balance over the
    # replica mesh axis. replica=-1 is the process total.
    "rw_serving_pulls": (
        Schema.of(("replica", T.INT64), ("pulls", T.INT64)),
        lambda db: _serving_pulls(db)),
    # flow telemetry (device/skew_stats.py): the traffic-per-vnode view
    # of rw_key_skew — per flow-armed node, this job-lifetime's ROUTED
    # rows per vnode bucket (metric='vnode_traffic', share = the
    # bucket's fraction of total traffic), the traffic max/mean ratio
    # ('traffic_skew'), the traffic-vs-occupancy divergence
    # ('traffic_div', half the L1 distance of the normalized histograms
    # — the "hot flow over cold state" signal) and the burst-vs-
    # sustained ratio from the per-node EWMA ring ('traffic_burst').
    "rw_vnode_traffic": (
        Schema.of(("job", T.VARCHAR), ("node", T.INT64),
                  ("type", T.VARCHAR), ("metric", T.VARCHAR),
                  ("ordinal", T.INT64), ("value", T.INT64),
                  ("share", T.FLOAT64)),
        lambda db: _vnode_traffic(db)),
    # poison-pill dead-letter queue (fault-tolerance v3): one row per
    # input record the supervisor sidelined after bounded respawns kept
    # dying on the same retained window. The full audit trail of the
    # bounded data loss — `risectl dlq <job>` lists/requeues/purges the
    # same rows. epoch=-1 marks the open (not-yet-barriered) tail of the
    # quarantined window; status walks quarantined -> requeued.
    "rw_dead_letter": (
        Schema.of(("id", T.INT64), ("job", T.VARCHAR), ("slot", T.INT64),
                  ("side", T.INT64), ("epoch", T.INT64),
                  ("fingerprint", T.VARCHAR), ("sign", T.INT64),
                  ("row", T.VARCHAR), ("status", T.VARCHAR),
                  ("ts", T.FLOAT64)),
        lambda db: _dead_letter(db)),
    # overload control plane (utils/overload.py): per job, the current
    # degradation-ladder state (seq=0) plus the transition history
    # (seq>0, newest last) — state walks normal -> throttled -> degraded
    # -> shedding and back with hysteresis; `stretch` is the live epoch-
    # cadence multiplier, `pressure` the [0,1] credit-starvation signal
    # the transition acted on, `dominant_source` the labeled evidence
    # ("stall:<kind>" / "sink:<name>" / "queue:<set>") that drove it —
    # every rung now says WHY it was taken.
    "rw_overload": (
        Schema.of(("job", T.VARCHAR), ("seq", T.INT64),
                  ("state", T.VARCHAR), ("prev_state", T.VARCHAR),
                  ("pressure", T.FLOAT64), ("stretch", T.INT64),
                  ("since_ts", T.FLOAT64), ("ts", T.FLOAT64),
                  ("dominant_source", T.VARCHAR)),
        lambda db: db._overload.rows()),
    # pressure attribution (utils/overload.py): the labeled evidence
    # rows behind the overload_pressure scalar — per-seam stall
    # fractions ('stall'), per-sink spool ratios ('sink'), per-worker-
    # set exchange queue ratios ('queue'), plus one 'combined' row
    # holding the recombined scalar. pressure_of IS
    # combine_contributions(these rows), so SQL can verify the
    # decomposition recombines exactly; `dominant` flags the argmax the
    # ladder transitions were stamped with.
    "rw_pressure_attrib": (
        Schema.of(("family", T.VARCHAR), ("source", T.VARCHAR),
                  ("value", T.FLOAT64), ("dominant", T.BOOLEAN)),
        lambda db: db._overload.attribution_rows()),
    # per-source admission control: token-bucket state + the offered/
    # admitted/deferred poll counters whose difference is the source's
    # admission lag (backpressure debt pushed back to the connector)
    "rw_source_admission": (
        Schema.of(("source", T.VARCHAR), ("state", T.VARCHAR),
                  ("factor", T.FLOAT64), ("offered", T.INT64),
                  ("admitted", T.INT64), ("deferred", T.INT64),
                  ("shed_rows", T.INT64), ("lag", T.INT64)),
        lambda db: db._overload.admission_rows()),
    # durable shed audit (RW_LOAD_SHED only): one row per source window
    # dropped by admission control on the shedding rung — the gap is a
    # recorded decision, never a silent loss (the rw_dead_letter
    # pattern, minus the payload: unadmitted data has no exact bytes to
    # requeue)
    "rw_shed_log": (
        Schema.of(("id", T.INT64), ("source", T.VARCHAR),
                  ("epoch", T.INT64), ("rows", T.INT64),
                  ("reason", T.VARCHAR), ("ts", T.FLOAT64)),
        lambda db: db._shed_log.entries()),
}


def _dead_letter(db) -> List[Tuple]:
    # project the binary payload column out — the system-table view is
    # the human-readable audit surface; exact bytes stay in the store
    return [(r[0], r[1], r[2], r[3], r[4], r[5], r[6], r[7], r[9], r[10])
            for r in db._dlq.entries()]


def _epoch_profile(db) -> List[Tuple]:
    return [row for job in db._fused.values()
            for row in job.profiler.rows()]


def _key_skew(db) -> List[Tuple]:
    return [(name,) + row for name, job in db._fused.items()
            for row in job.skew_report()]


_TRAFFIC_METRICS = ("vnode_traffic", "traffic_skew", "traffic_div",
                    "traffic_burst")


def _vnode_traffic(db) -> List[Tuple]:
    # the traffic slice of skew_report, minus the (always-NULL here)
    # hot-key column
    return [(name, node, tname, metric, ordinal, value, share)
            for name, job in db._fused.items()
            for node, tname, metric, ordinal, _key, value, share
            in job.skew_report()
            if metric in _TRAFFIC_METRICS]


def _serving_pulls(db) -> List[Tuple]:
    from ..device.shard_exec import PULL_STATS
    rows = [(int(rep), int(n))
            for rep, n in sorted(PULL_STATS["replica_pulls"].items())]
    return rows + [(-1, int(PULL_STATS["device_pulls"]))]


def _state_tiering(db) -> List[Tuple]:
    return [(name,) + row for name, job in db._fused.items()
            for row in job.tiering_report()]


def _fused_node_stats(db) -> List[Tuple]:
    return [(name,) + row for name, job in db._fused.items()
            for row in job.node_report()]


def _ddl_progress(db) -> List[Tuple]:
    from .database import _Backfill, _walk_executors
    out = []
    for obj in db.catalog.objects.values():
        rt = obj.runtime if isinstance(obj.runtime, dict) else None
        shared = rt.get("shared") if rt else None
        if shared is None:
            continue
        for e in _walk_executors(shared.upstream):
            if isinstance(e, _Backfill) and e.total:
                out.append((obj.name, e.upstream_name, e.emitted,
                            e.total, f"{e.progress * 100:.1f}%"))
    return out


# ---------------------------------------------------------------------------
# EXPLAIN rendering
# ---------------------------------------------------------------------------

def _label(e) -> str:
    name = e.name or type(e).__name__
    bits: List[str] = []
    gk = getattr(e, "group_key_indices", None)
    if gk is not None:
        bits.append(f"group_key={list(gk)}")
    calls = getattr(e, "calls", None)
    if calls:
        try:
            bits.append("aggs=[" + ", ".join(c.kind for c in calls) + "]")
        except Exception:
            pass
    ki = getattr(e, "key_idx", None)
    if isinstance(ki, dict):
        bits.append(f"on={ki.get('a')}={ki.get('b')}")
    mesh = getattr(e, "mesh", None)
    if mesh is not None:
        bits.append(f"mesh={mesh.devices.size}")
    if getattr(e, "append_only", False):
        bits.append("append_only")
    return name + (" { " + ", ".join(bits) + " }" if bits else "")


def _plan_children(e) -> List[Any]:
    """Child executors of one node — the ONE place that knows the child
    attribute names, shared by EXPLAIN and EXPLAIN ANALYZE so the two
    surfaces can never show different trees."""
    children = []
    for attr in ("input", "left_exec", "right_exec", "port"):
        c = getattr(e, attr, None)
        if c is not None:
            children.append(c)
    children.extend(getattr(e, "inputs", ()))
    return children


def render_plan(e, depth: int = 0) -> str:
    lines = ["  " * depth + ("-> " if depth else "") + _label(e)]
    for c in _plan_children(e):
        lines.append(render_plan(c, depth + 1))
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# streaming EXPLAIN ANALYZE: the live per-operator tree of a RUNNING job
# ---------------------------------------------------------------------------


def explain_analyze_fused(name: str, job) -> str:
    """Per-operator tree of a running fused device job.

    Every number comes from surfaces the job already maintains — the
    stats vector totals behind `rw_fused_node_stats` (rows/occupancy
    agree with that table by construction: both read `node_report`),
    the epoch profiler's phase totals, per-node compile events, and the
    skew telemetry — so rendering costs zero device traffic and the
    numbers are checkpoint-fresh (the same contract as the system
    tables)."""
    import time
    prog = job.program
    prof = job.profiler
    elapsed = max(1e-9, time.monotonic() - job.t_created)
    ph = dict(prof.totals)
    busy = sum(ph.values())
    head = [
        f"Streaming EXPLAIN ANALYZE: {name} "
        f"(fused, shards={job.mesh_shards}, "
        f"events={job.committed}/{job.max_events or '?'}, "
        f"epochs={prof.epochs}, elapsed={elapsed:.1f}s, "
        f"eps={job.committed / elapsed:.0f})",
        "phase share: " + " | ".join(
            f"{k} {v / elapsed * 100:.1f}%" for k, v in ph.items())
        + f" | idle {max(0.0, elapsed - busy) / elapsed * 100:.1f}%",
    ]
    # per-(node, slot) attribution grouped by node — THE rows behind
    # rw_fused_node_stats, so eps/occupancy columns agree with it
    by_node: Dict[int, List[Tuple]] = {}
    for row in job.node_report():
        by_node.setdefault(row[0], []).append(row)
    # per-node compile wall from the profiler's labeled events
    compile_s: Dict[int, float] = {}
    with prof._ev_lock:
        infos = list(prof.compile_info)
    for rec in infos:
        try:
            idx = int(rec["label"].split(":", 1)[0])
        except (ValueError, KeyError):
            continue
        compile_s[idx] = compile_s.get(idx, 0.0) + rec.get("s", 0.0)
    consumed = {j for n in prog.nodes for j in n.inputs}
    roots = [i for i in range(len(prog.nodes)) if i not in consumed]
    lines: List[str] = []

    def node_line(i: int) -> str:
        node = prog.nodes[i]
        tname = type(node).__name__
        label = f"{i}:{tname}"
        if tname == "ChainNode":
            label += "[" + ">".join(type(m).__name__.replace("Node", "")
                                    for m in node.chain) + "]"
        slots = by_node.get(i, [])
        rows_in = slots[0][3] if slots else 0
        rows_out = slots[0][4] if slots else 0
        bits = [f"rows_in={rows_in}", f"rows_out={rows_out}",
                f"eps_in={rows_in / elapsed:.0f}",
                f"eps_out={rows_out / elapsed:.0f}"]
        if rows_in:
            bits.append(f"amp={rows_out / rows_in:.2f}")
        for (_i, _t, slot, _ri, _ro, entries, cap, occ, hbm,
             overflow) in slots:
            if slot == "-":
                continue
            bits.append(f"{slot}={entries}/{cap}"
                        + (f"({occ * 100:.0f}%)" if cap else "")
                        + (" OVERFLOW" if overflow else ""))
        hbm_total = sum(s[8] for s in slots)
        if hbm_total:
            bits.append(f"hbm={hbm_total:.1f}MB")
        ratio = job.node_skew_ratio(i)
        if ratio is not None:
            bits.append(f"skew={ratio:.1f}x")
        if compile_s.get(i):
            bits.append(f"compile_s={compile_s[i]:.2f}")
        return label + " { " + ", ".join(bits) + " }"

    def render(i: int, depth: int) -> None:
        lines.append("  " * depth + ("-> " if depth else "") + node_line(i))
        for j in prog.nodes[i].inputs:
            render(j, depth + 1)

    for r in roots:
        render(r, 0)
    return "\n".join(head + lines)


def _analyze_bits(e) -> List[str]:
    """Live annotations for one host executor: backfill progress,
    remote-worker liveness, and channel queue depths (the
    busy/backpressure signal of the host path — a full result channel
    means the consumer is the bottleneck, a full dispatch channel means
    the worker is)."""
    bits: List[str] = []
    if getattr(e, "total", None) and hasattr(e, "emitted"):
        bits.append(f"backfill={e.emitted}/{e.total}")
    r = getattr(e, "_remote", None)
    if r is not None:
        for (_j, worker, pid, last_epoch, age,
             state) in r.liveness_rows(""):
            bits.append(f"{worker}[pid={pid} {state} epoch={last_epoch} "
                        f"hb_age={age:.1f}s]")
        # result-side backpressure: queued output the coordinator has
        # not consumed, per worker channel
        for i, ch in enumerate(getattr(r, "channels", ())):
            q = len(getattr(ch, "buf", ()))
            if q:
                bits.append(f"out_queue[{i}]={q}/{ch.capacity}")
        # dispatch-side backpressure: input waiting on a slow worker
        for side, chans in enumerate(getattr(r, "in_channels", ())):
            for i, nc in enumerate(chans):
                q = nc._data_len() if hasattr(nc, "_data_len") else 0
                if q:
                    bits.append(f"in_queue[{side}.{i}]={q}/{nc.capacity}")
    return bits


def explain_analyze_host(name: str, obj) -> str:
    """Per-operator tree of a running host/multi-process MV: the
    planned executor tree annotated with live counters — backfill
    progress, per-worker liveness + last result epoch (the metrics
    plane), and exchange queue depths (backpressure)."""
    shared = (obj.runtime or {}).get("shared")
    if shared is None:
        return f"{name}: no live dataflow (fused or dropped?)"
    head = [f"Streaming EXPLAIN ANALYZE: {name} (host placement)"]
    lines: List[str] = []

    def walk(e, depth: int) -> None:
        bits = _analyze_bits(e)
        lines.append("  " * depth + ("-> " if depth else "") + _label(e)
                     + (" { " + ", ".join(bits) + " }" if bits else ""))
        for c in _plan_children(e):
            walk(c, depth + 1)

    walk(shared.upstream, 0)
    return "\n".join(head + lines)
