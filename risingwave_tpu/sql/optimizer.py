"""Logical query optimizer: rewrite rules over the bound-free AST.

Analog of `src/frontend/src/optimizer/` scoped to the rules that matter
for this runtime's direct AST->executor lowering (the reference runs
100+ rules over a logical plan IR; here the AST IS the logical plan —
one shape per query — so rules rewrite `Select` trees before lowering):

* constant folding (`const_eval_rewriter.rs` analog): literal arithmetic
  / comparisons / boolean algebra collapse, `WHERE TRUE` drops,
  `WHERE FALSE` stays (planner emits the empty-filter form);
* predicate pushdown (`predicate_push_down.rs` analog): WHERE conjuncts
  over a subquery-in-FROM move inside the subquery (below its
  aggregation when they only touch group-by columns — filtering before
  the agg shrinks device state); pushdown through joins moves
  side-local conjuncts into the relevant subquery side;
* projection pruning happens structurally at lowering (the planner only
  materializes referenced columns into operator payloads).

Every applied rule is recorded; `EXPLAIN` surfaces the list.
"""
from __future__ import annotations

from dataclasses import replace
from typing import Any, Dict, List, Optional, Tuple

from . import ast as A

_ARITH = {
    "+": lambda a, b: a + b,
    "-": lambda a, b: a - b,
    "*": lambda a, b: a * b,
    "=": lambda a, b: a == b,
    "<>": lambda a, b: a != b,
    "<": lambda a, b: a < b,
    "<=": lambda a, b: a <= b,
    ">": lambda a, b: a > b,
    ">=": lambda a, b: a >= b,
}


def fold_expr(e: Any, log: List[str]) -> Any:
    """Bottom-up constant folding over the generic expression walker
    (`planner._clone_with` — it knows every node's children, including
    CASE branch tuples). Division is left alone (type/zero semantics
    belong to the expression layer)."""
    from .planner import _clone_with
    if not isinstance(e, A.ExprNode):
        return e
    e = _clone_with(e, lambda c: fold_expr(c, log))
    if isinstance(e, A.BinOp):
        left, right = e.left, e.right
        if isinstance(left, A.Lit) and isinstance(right, A.Lit) \
                and e.op in _ARITH and left.value is not None \
                and right.value is not None \
                and not isinstance(left.value, str) \
                and not isinstance(right.value, str) \
                and left.type_hint is None and right.type_hint is None:
            try:
                v = _ARITH[e.op](left.value, right.value)
            except Exception:  # noqa: BLE001 — leave unfoldable alone
                return e
            log.append(f"const_fold({left.value} {e.op} {right.value})")
            return A.Lit(v)
        if e.op in ("and", "or"):
            for a, b in ((left, right), (right, left)):
                if isinstance(a, A.Lit) and isinstance(a.value, bool):
                    log.append(f"bool_short_circuit({e.op})")
                    if e.op == "and":
                        return b if a.value else A.Lit(False)
                    return A.Lit(True) if a.value else b
    elif isinstance(e, A.UnaryOp):
        if e.op == "not" and isinstance(e.operand, A.Lit) \
                and isinstance(e.operand.value, bool):
            log.append("const_fold(not)")
            return A.Lit(not e.operand.value)
    return e


def _conjuncts(e: Optional[Any]) -> List[Any]:
    if e is None:
        return []
    if isinstance(e, A.BinOp) and e.op == "and":
        return _conjuncts(e.left) + _conjuncts(e.right)
    return [e]


def _conjoin(parts: List[Any]) -> Optional[Any]:
    out = None
    for p in parts:
        out = p if out is None else A.BinOp("and", out, p)
    return out


def _col_tables(e: Any, out: set) -> bool:
    """Collect table qualifiers of every Col; False if any Col is
    unqualified (can't attribute it safely) or a subquery lurks."""
    from .planner import _children
    if isinstance(e, A.Col):
        if e.table is None:
            return False
        out.add(e.table)
        return True
    if isinstance(e, A.SubqueryExpr):
        return False
    ok = True
    for c in _children(e):
        ok = _col_tables(c, out) and ok
    return ok


def _subquery_output(q: A.Select) -> Optional[Dict[str, Any]]:
    """alias -> defining expression for the subquery's select items;
    None when the output shape is unknowable (stars)."""
    out: Dict[str, Any] = {}
    for it in q.items:
        if isinstance(it.expr, A.Star):
            return None
        name = it.alias or (it.expr.name if isinstance(it.expr, A.Col)
                            else None)
        if name is None:
            continue
        out[name] = it.expr
    return out


def _substitute(e: Any, mapping: Dict[str, Any]) -> Any:
    from .planner import _clone_with
    if isinstance(e, A.Col):
        return mapping[e.name]
    if not isinstance(e, A.ExprNode):
        return e
    return _clone_with(e, lambda c: _substitute(c, mapping))


def _contains_agg(e: Any) -> bool:
    from .planner import _contains_agg as pca
    return pca(e)


def _contains_window(e: Any) -> bool:
    from .planner import _children
    if isinstance(e, A.FuncCall) and e.over is not None:
        return True
    return any(_contains_window(c) for c in _children(e))


def _push_into_subquery(sub: A.SubqueryTable, pred: Any,
                        log: List[str]) -> bool:
    """Move `pred` (conjunct over sub.alias columns only) inside the
    subquery — below its aggregation when every referenced column is
    group-by-defined, else into HAVING."""
    q = sub.query
    if q.limit is not None or q.offset:
        return False          # filtering below LIMIT changes the result
    outmap = _subquery_output(q)
    if outmap is None:
        return False
    cols: set = set()

    def names(e, acc):
        from .planner import _children
        if isinstance(e, A.Col):
            acc.add(e.name)
        for c in _children(e):
            names(c, acc)
    names(pred, cols)
    if not cols.issubset(outmap):
        return False
    defs = {c: outmap[c] for c in cols}
    if any(_contains_window(d) for d in defs.values()):
        # window-function outputs: filtering before frame evaluation
        # changes the frames (and OVER can't run in WHERE/HAVING)
        return False
    if any(_contains_agg(d) for d in defs.values()):
        if q.group_by or any(_contains_agg(i.expr) for i in q.items):
            # references an aggregate output: becomes a HAVING conjunct
            inner = _substitute(_strip_qualifiers(pred), defs)
            q.having = _conjoin(_conjuncts(q.having) + [inner])
            log.append("push_predicate_to_having")
            return True
        return False
    inner = _substitute(_strip_qualifiers(pred), defs)
    q.where = _conjoin(_conjuncts(q.where) + [inner])
    log.append("push_predicate_below_agg" if q.group_by
               else "push_predicate_into_subquery")
    return True


def _strip_qualifiers(e: Any) -> Any:
    from .planner import _clone_with
    if isinstance(e, A.Col):
        return A.Col(e.name, None)
    if not isinstance(e, A.ExprNode):
        return e
    return _clone_with(e, _strip_qualifiers)


def _aliased_subqueries(t: Optional[A.TableRef],
                        out: Dict[str, A.SubqueryTable],
                        nullable: bool = False) -> None:
    """Collect alias -> subquery for sides a WHERE conjunct may legally
    move into. A nullable outer-join side is excluded: filtering it
    pre-join would turn matched-then-filtered rows into NULL extensions
    instead of removing them."""
    if isinstance(t, A.SubqueryTable) and t.alias and not nullable:
        out[t.alias] = t
    if isinstance(t, A.Join):
        left_nullable = nullable or t.kind in ("right", "full")
        right_nullable = nullable or t.kind in ("left", "full")
        _aliased_subqueries(t.left, out, left_nullable)
        _aliased_subqueries(t.right, out, right_nullable)


# ---------------------------------------------------------------------------
# rule framework (`src/frontend/src/optimizer/` OptimizationStage analog:
# named rules applied to fixpoint in ordered passes, every application
# logged for EXPLAIN; cost input = catalog row counts via RuleContext)
# ---------------------------------------------------------------------------


class RuleContext:
    def __init__(self, log: List[str], stats=None):
        self.log = log
        self._stats = stats

    def rows(self, table: Optional[str]) -> Optional[int]:
        """Current row count of a named relation (None = unknown) — the
        cost model's cardinality source (the reference reads catalog
        statistics the same way)."""
        if self._stats is None or table is None:
            return None
        try:
            return self._stats(table)
        except Exception:  # noqa: BLE001 — stats are advisory
            return None


class Rule:
    """One rewrite: apply() mutates `q` in place and returns True when it
    changed something (the driver iterates to fixpoint)."""
    name = "?"

    def apply(self, q: A.Select, ctx: RuleContext) -> bool:
        raise NotImplementedError


class ConstantFolding(Rule):
    name = "constant_folding"

    def apply(self, q, ctx):
        # change detection via the log: every real fold records a line
        # (fold_expr clones unconditionally, so identity can't be used)
        n0 = len(ctx.log)
        if q.where is not None:
            q.where = fold_expr(q.where, ctx.log)
            if isinstance(q.where, A.Lit) and q.where.value is True:
                q.where = None
                ctx.log.append("drop_where_true")
        if q.having is not None:
            q.having = fold_expr(q.having, ctx.log)
        q.items = [replace(it, expr=fold_expr(it.expr, ctx.log))
                   if isinstance(it.expr, A.ExprNode) else it
                   for it in q.items]
        return len(ctx.log) > n0


class PredicatePushdown(Rule):
    """WHERE conjuncts over one aliased FROM-subquery move inside it
    (below its aggregation when group-key-only) — predicate_push_down.rs
    analog."""
    name = "predicate_pushdown"

    def apply(self, q, ctx):
        subs: Dict[str, A.SubqueryTable] = {}
        _aliased_subqueries(q.from_, subs)
        if not subs or q.where is None:
            return False
        keep: List[Any] = []
        changed = False
        for pred in _conjuncts(q.where):
            tabs: set = set()
            if _col_tables(pred, tabs) and len(tabs) == 1 \
                    and next(iter(tabs)) in subs \
                    and _push_into_subquery(subs[next(iter(tabs))],
                                            pred, ctx.log):
                changed = True
                continue
            keep.append(pred)
        q.where = _conjoin(keep)
        return changed


def _rel_alias(t: Any) -> Optional[str]:
    if isinstance(t, A.NamedTable):
        return t.alias or t.name
    if isinstance(t, A.SubqueryTable):
        return t.alias
    return None


def _rel_name(t: Any) -> Optional[str]:
    return t.name if isinstance(t, A.NamedTable) else None


class JoinReorder(Rule):
    """Greedy cost-based reordering of pure INNER-join chains: flatten
    the tree, then rebuild left-deep starting from the smallest relation
    and repeatedly joining the smallest CONNECTED one (a predicate must
    link it — no cross products introduced). The cost input is current
    catalog row counts; unknown sizes sort last. The reference's
    reorder rule works over its logical join graph the same way
    (`optimizer/rule/`, join ordering)."""
    name = "join_reorder"

    def apply(self, q, ctx):
        t = q.from_
        if not isinstance(t, A.Join) or t.kind != "inner":
            return False
        rels: List[Any] = []
        preds: List[Any] = []

        def flatten(x) -> bool:
            if isinstance(x, A.Join) and x.kind == "inner" \
                    and x.on is not None:
                if not (flatten(x.left) and flatten(x.right)):
                    return False
                preds.extend(_conjuncts(x.on))
                return True
            if isinstance(x, (A.NamedTable, A.SubqueryTable)) \
                    and _rel_alias(x):
                rels.append(x)
                return True
            return False

        if not flatten(t) or len(rels) < 3:
            return False
        # SELECT * follows the join-tree column order — reordering would
        # silently reshape the output schema
        if any(isinstance(it.expr, A.Star) for it in q.items):
            return False
        aliases = [_rel_alias(r) for r in rels]
        if len(set(aliases)) != len(aliases):
            return False
        # predicate -> set of aliases it references
        pinfo = []
        for p in preds:
            tabs: set = set()
            if not _col_tables(p, tabs) or not tabs <= set(aliases):
                return False        # unresolvable column -> keep shape
            equi = (isinstance(p, A.BinOp) and p.op == "="
                    and isinstance(p.left, A.Col)
                    and isinstance(p.right, A.Col) and len(tabs) == 2)
            pinfo.append((p, tabs, equi))
        sizes = {a: ctx.rows(_rel_name(r))
                 for a, r in zip(aliases, rels)}
        if all(v is None for v in sizes.values()):
            return False            # no cost signal: keep the user's order
        big = 1 << 60

        def size(a):
            return sizes[a] if sizes[a] is not None else big

        by_alias = dict(zip(aliases, rels))
        order = [min(aliases, key=size)]
        remaining = [a for a in aliases if a != order[0]]
        placed_preds: List[List[Any]] = []
        used = [False] * len(pinfo)
        while remaining:
            have = set(order)
            # connectivity = an EQUI predicate links the candidate to the
            # placed set; residual conjuncts alone would build a join the
            # planner rejects ("requires at least one equi-condition")
            connected = [a for a in remaining
                         if any(equi and a in tabs and tabs - {a} <= have
                                for p, tabs, equi in pinfo)]
            if not connected:
                return False        # would need a cross product
            nxt = min(connected, key=size)
            order.append(nxt)
            remaining.remove(nxt)
            have.add(nxt)
            batch = []
            for i, (p, tabs, _e) in enumerate(pinfo):
                if not used[i] and tabs <= have:
                    used[i] = True
                    batch.append(p)
            placed_preds.append(batch)
        if order == aliases:
            return False            # already optimal
        tree: Any = by_alias[order[0]]
        for a, batch in zip(order[1:], placed_preds):
            tree = A.Join(tree, by_alias[a], "inner", _conjoin(batch))
        q.from_ = tree
        ctx.log.append(f"join_reorder({'⋈'.join(order)})")
        return True


RULES: List[Rule] = [ConstantFolding(), PredicatePushdown(), JoinReorder()]
_MAX_PASSES = 4


def optimize(q: A.Select, log: Optional[List[str]] = None,
             stats=None) -> A.Select:
    """Run the rule set to fixpoint over `q` (recursively over FROM
    subqueries, inside-out like the reference's stage pipeline)."""
    if log is None:
        log = []
    q.applied_rules = log   # type: ignore[attr-defined]

    def rec_tables(t: Optional[A.TableRef]) -> None:
        if isinstance(t, A.SubqueryTable):
            optimize(t.query, log, stats)
        elif isinstance(t, A.Join):
            rec_tables(t.left)
            rec_tables(t.right)
        elif isinstance(t, A.WindowTable):
            rec_tables(t.inner)
    rec_tables(q.from_)

    ctx = RuleContext(log, stats)
    for _ in range(_MAX_PASSES):
        applied = [r.apply(q, ctx) for r in RULES]   # no short-circuit
        if not any(applied):
            break
    return q
