"""Binder + planner: Select AST -> executor tree.

Collapses the reference's binder -> logical plan -> optimizer -> stream plan
pipeline (`src/frontend/src/{binder,planner,optimizer}/`) into one direct
lowering: each SELECT shape maps onto the executor set the same way the
reference's optimized stream plans do (Project/Filter/HashAgg/HashJoin/
HopWindow/OverWindow/TopN/Materialize). The 100+ rewrite rules exist to
normalize hand-written SQL into those shapes; here the planner emits them
directly and leaves micro-optimization to XLA on the device path.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from ..core import dtypes as T
from ..core.dtypes import DataType, Interval, TypeKind, parse_interval
from ..core.schema import Field, Schema
from ..expr import (AGG_KINDS, AggCall, Case, Coalesce, Expr, InputRef,
                    Literal, build_func, cast)
from ..expr.expression import IsNull
from ..ops import (FilterExecutor, HashAggExecutor, HashJoinExecutor,
                   HopWindowExecutor, JoinType, OverWindowExecutor,
                   ProjectExecutor, SimpleAggExecutor, TopNExecutor,
                   WindowFuncCall)
from ..ops.executor import Executor
from . import ast as A

_TYPE_MAP = {
    "int": T.INT32, "integer": T.INT32, "int4": T.INT32,
    "smallint": T.INT16, "int2": T.INT16,
    "bigint": T.INT64, "int8": T.INT64, "serial": T.INT64,
    "real": T.FLOAT32, "float4": T.FLOAT32,
    "double": T.FLOAT64, "float8": T.FLOAT64, "float": T.FLOAT64,
    "numeric": T.DECIMAL, "decimal": T.DECIMAL,
    "boolean": T.BOOLEAN, "bool": T.BOOLEAN,
    "varchar": T.VARCHAR, "text": T.VARCHAR, "string": T.VARCHAR,
    "date": T.DATE, "time": T.TIME, "timestamp": T.TIMESTAMP,
    "timestamptz": T.TIMESTAMPTZ, "interval": T.INTERVAL, "bytea": T.BYTEA,
}


def type_from_name(name: str) -> DataType:
    dt = _TYPE_MAP.get(name.lower())
    if dt is None:
        raise ValueError(f"unknown type {name!r}")
    return dt


# ---------------------------------------------------------------------------
# Namespace: the column scope a plan node exposes
# ---------------------------------------------------------------------------


@dataclass
class ColumnEntry:
    table: Optional[str]
    name: str
    dtype: DataType


@dataclass
class Namespace:
    cols: List[ColumnEntry]
    # indices forming the stream key: the minimal column set that makes rows
    # unique in the change stream (StreamMaterialize pk derivation analog,
    # `src/frontend/src/optimizer/plan_node/stream_materialize.rs`). The MV
    # pk must cover it or duplicate rows collapse.
    stream_key: List[int] = field(default_factory=list)
    n_visible: Optional[int] = None    # hidden stream-key cols sit past this
    watermark_idx: Optional[int] = None   # column carrying the watermark

    def resolve(self, name: str, table: Optional[str] = None) -> int:
        hits = [i for i, c in enumerate(self.cols)
                if c.name == name and (table is None or c.table == table)]
        if not hits:
            raise ValueError(f"column {table + '.' if table else ''}{name} "
                             f"does not exist")
        if len(hits) > 1:
            raise ValueError(f"column reference {name!r} is ambiguous")
        return hits[0]

    def schema(self) -> Schema:
        return Schema([Field(c.name, c.dtype) for c in self.cols])

    @staticmethod
    def of_schema(schema: Schema, table: Optional[str],
                  stream_key: Optional[Sequence[int]] = None) -> "Namespace":
        return Namespace([ColumnEntry(table, f.name, f.dtype)
                          for f in schema.fields],
                         list(stream_key or []))

    def concat(self, other: "Namespace") -> "Namespace":
        off = len(self.cols)
        return Namespace(self.cols + other.cols,
                         self.stream_key + [i + off
                                            for i in other.stream_key])


# ---------------------------------------------------------------------------
# Expression binding
# ---------------------------------------------------------------------------

_BINOP_FUNC = {
    "+": "add", "-": "subtract", "*": "multiply", "/": "divide",
    "%": "modulus", "=": "equal", "<>": "not_equal", "!=": "not_equal",
    "<": "less_than", "<=": "less_than_or_equal", ">": "greater_than",
    ">=": "greater_than_or_equal", "and": "and", "or": "or",
}


def _lit(value: Any, hint: Optional[str]) -> Literal:
    if hint == "interval":
        return Literal(parse_interval(value), T.INTERVAL)
    if value is None:
        return Literal(None, T.VARCHAR)
    if isinstance(value, bool):
        return Literal(value, T.BOOLEAN)
    if isinstance(value, int):
        return Literal(value, T.INT32 if -2**31 <= value < 2**31 else T.INT64)
    if isinstance(value, float):
        return Literal(value, T.FLOAT64)
    if isinstance(value, str):
        return Literal(value, T.VARCHAR)
    raise ValueError(f"cannot type literal {value!r}")


class Binder:
    def __init__(self, ns: Namespace):
        self.ns = ns

    def bind(self, node: A.ExprNode) -> Expr:
        if isinstance(node, A.Param):
            raise ValueError(f"there is no parameter ${node.index} "
                             "(unbound prepared-statement placeholder)")
        if isinstance(node, A.Lit):
            return _lit(node.value, node.type_hint)
        if isinstance(node, A.Col):
            i = self.ns.resolve(node.name, node.table)
            return InputRef(i, self.ns.cols[i].dtype)
        if isinstance(node, A.BinOp):
            return build_func(_BINOP_FUNC[node.op],
                              [self.bind(node.left), self.bind(node.right)])
        if isinstance(node, A.UnaryOp):
            if node.op == "not":
                return build_func("not", [self.bind(node.operand)])
            return build_func("neg", [self.bind(node.operand)])
        if isinstance(node, A.FuncCall):
            if node.name in ("count", "sum", "min", "max", "avg") \
                    and node.over is None:
                raise ValueError(f"aggregate {node.name} in scalar context")
            if node.filter is not None:
                raise ValueError("FILTER is only supported on aggregate "
                                 "function calls")
            if node.name == "concat_op":
                return build_func("concat_op", [self.bind(a)
                                                for a in node.args])
            return build_func(node.name, [self.bind(a) for a in node.args])
        if isinstance(node, A.CaseExpr):
            branches = []
            for cond, res in node.branches:
                if node.operand is not None:
                    cond = A.BinOp("=", node.operand, cond)
                branches.append((self.bind(cond), self.bind(res)))
            els = self.bind(node.else_expr) if node.else_expr else None
            ret = branches[0][1].return_type
            return Case(branches, els, ret)
        if isinstance(node, A.CastExpr):
            return cast(self.bind(node.operand),
                        type_from_name(node.type_name))
        if isinstance(node, A.ExtractExpr):
            return build_func("extract",
                              [Literal(node.field.upper(), T.VARCHAR),
                               self.bind(node.operand)])
        if isinstance(node, A.IsNullExpr):
            return IsNull(self.bind(node.operand), negated=node.negated)
        if isinstance(node, A.Between):
            lo = A.BinOp(">=", node.operand, node.low)
            hi = A.BinOp("<=", node.operand, node.high)
            e = A.BinOp("and", lo, hi)
            if node.negated:
                e = A.UnaryOp("not", e)
            return self.bind(e)
        if isinstance(node, A.InList):
            e: Optional[A.ExprNode] = None
            for item in node.items:
                eq = A.BinOp("=", node.operand, item)
                e = eq if e is None else A.BinOp("or", e, eq)
            if node.negated:
                e = A.UnaryOp("not", e)
            return self.bind(e)
        if isinstance(node, A.Index):
            inner = node.operand
            if isinstance(inner, A.FuncCall) and inner.name == "regexp_match":
                args = [self.bind(a) for a in inner.args]
                args.append(Literal(node.index, T.INT32))
                return build_func("regexp_match_idx", args)
            raise ValueError("subscript is only supported on "
                             "regexp_match(...)")
        if isinstance(node, A.InSubquery):
            raise ValueError("IN (SELECT ...) is only supported as a "
                             "top-level WHERE condition")
        if isinstance(node, A.SubqueryExpr):
            raise ValueError("scalar subqueries are only supported on one "
                             "side of a WHERE/HAVING comparison")
        raise ValueError(f"cannot bind {node!r}")


# ---------------------------------------------------------------------------
# Aggregate extraction
# ---------------------------------------------------------------------------


def _find_aggs(node: A.ExprNode, out: List[A.FuncCall]) -> None:
    if isinstance(node, A.FuncCall) and node.over is None and \
            node.name in AGG_KINDS:
        out.append(node)
        return
    for child in _children(node):
        _find_aggs(child, out)


def _children(node: A.ExprNode) -> List[A.ExprNode]:
    if isinstance(node, A.BinOp):
        return [node.left, node.right]
    if isinstance(node, A.UnaryOp):
        return [node.operand]
    if isinstance(node, A.FuncCall):
        return list(node.args)
    if isinstance(node, A.CaseExpr):
        out = list(node.branches and
                   [x for b in node.branches for x in b] or [])
        if node.operand:
            out.append(node.operand)
        if node.else_expr:
            out.append(node.else_expr)
        return out
    if isinstance(node, (A.CastExpr, A.ExtractExpr, A.IsNullExpr)):
        return [node.operand]
    if isinstance(node, A.Between):
        return [node.operand, node.low, node.high]
    if isinstance(node, A.InList):
        return [node.operand] + node.items
    if isinstance(node, (A.Index, A.InSubquery)):
        return [node.operand]
    return []


def _contains_agg(node: A.ExprNode) -> bool:
    found: List[A.FuncCall] = []
    _find_aggs(node, found)
    return bool(found)


# ---------------------------------------------------------------------------
# Planner
# ---------------------------------------------------------------------------

_JOIN_KIND = {"inner": JoinType.INNER, "left": JoinType.LEFT_OUTER,
              "right": JoinType.RIGHT_OUTER, "full": JoinType.FULL_OUTER}


class Planner:
    """Plans one Select into an executor tree.

    `subscribe(name) -> (Executor, Schema, pk)` is supplied by the runtime
    (Database): streaming change feed + backfill for MV plans, snapshot
    source for batch queries — the planner is mode-agnostic, exactly the
    to-stream / to-batch split of the reference's plan_node lowering.
    """

    def __init__(self, subscribe: Callable[[str], Tuple[Executor, Schema]],
                 make_state: Optional[Callable[[Sequence[DataType],
                                                Sequence[int]], Any]] = None,
                 device=None, barrier_source=None, watermark_of=None,
                 state_table_of=None):
        self.subscribe = subscribe
        # name -> StateTable | None: the object's arrangement, for
        # lookup/delta joins (ops/lookup_join.py)
        self.state_table_of = state_table_of
        # SET streaming_enable_delta_join (stamped by Database per CREATE)
        self.delta_join = False
        # state-table factory: (dtypes, pk) -> StateTable | None. Called in
        # a DETERMINISTIC order per statement so table ids line up when the
        # DDL log replays on recovery.
        self.make_state = make_state or (lambda dtypes, pk: None)
        # DeviceConfig | None — the SQL->TPU dispatch seam (the reference's
        # from_proto/mod.rs:151-197 analog): eligible HashAgg fragments
        # lower onto DeviceHashAggExecutor. Must be stable across restarts
        # of the same data directory (state-table layouts differ).
        self.device = device
        # () -> Executor yielding only barriers; required to plan NOW()
        # (the `now.rs` barrier-receiver registration)
        self.barrier_source = barrier_source
        # name -> watermark column index | None (EOWC Sort planning)
        self.watermark_of = watermark_of or (lambda name: None)
        # host-path fragment parallelism (SET streaming_parallelism): >1
        # plans HashAgg as Dispatch -> k agg fragments -> Merge
        self.parallelism = 1

    def _make_hash_agg(self, input: Executor, group_indices: List[int],
                       calls: List[AggCall], gdtypes: List[DataType],
                       eowc: bool = False, wc: Optional[int] = None,
                       carry_cols: Optional[List[int]] = None
                       ) -> Executor:
        """Device-vs-host HashAgg dispatch. State-table allocation order is
        deterministic PER DISPATCH POLICY (host: one pickled-state table;
        device: payload table + one table per min/max input column), and the
        policy is recorded in the data directory and validated on reopen
        (Database._check_device_marker) — so DDL-log replay always re-runs
        under the policy that shaped the tables."""
        from ..ops.device_agg import (DeviceHashAggExecutor,
                                      device_agg_eligible,
                                      device_minput_count,
                                      device_payload_dtypes)
        # bottom-up append-only property (generic/agg.rs `input.append_only`):
        # derived from the executor tree, so it is deterministic for a given
        # DDL + dispatch policy and replays identically on recovery
        ao = bool(input.append_only)
        if self.device is not None and not eowc \
                and device_agg_eligible(calls, self.device.minmax, ao):
            st = self.make_state(gdtypes + device_payload_dtypes(calls, ao),
                                 list(range(len(group_indices))))
            # one (group..., encoded value, count) table per retractable
            # min/max call — pk covers group + value
            mts = [self.make_state(gdtypes + [T.INT64, T.INT64],
                                   list(range(len(group_indices) + 1)))
                   for _ in range(device_minput_count(calls, ao))]
            return DeviceHashAggExecutor(input, group_indices, calls,
                                         state_table=st, minput_tables=mts,
                                         mesh=self.device.mesh,
                                         capacity=self.device.capacity,
                                         append_only=ao)
        if self.parallelism > 1 and group_indices and not eowc \
                and getattr(self, "placement", "local") == "process":
            # worker OS processes over the credit-flow exchange — real CPU
            # parallelism (stream_manager.rs:610 actor placement analog).
            # 2-phase: stateless partial agg in workers, stateful final agg
            # here (its state table makes recovery identical to the local
            # path; workers respawn with nothing to restore). Plans the
            # 2-phase rewrite can't express fall through to local topology.
            from ..runtime.remote_fragments import (RemoteFragmentSet,
                                                    serializable_agg)
            if serializable_agg(input, calls):
                # prune to the columns the fragment reads before anything
                # crosses the wire (exchange bytes + encode CPU are the
                # coordinator's budget)
                used = list(dict.fromkeys(
                    list(group_indices)
                    + [c.arg.index for c in calls if c.arg is not None]))
                prune = ProjectExecutor(
                    input, [InputRef(i, input.schema.fields[i].dtype)
                            for i in used],
                    [input.schema.fields[i].name for i in used])
                prune.append_only = input.append_only
                remap = {old: new for new, old in enumerate(used)}
                pruned_calls = [
                    AggCall(c.kind,
                            InputRef(remap[c.arg.index],
                                     c.arg.return_type)
                            if c.arg is not None else None)
                    for c in calls]
                rfs = RemoteFragmentSet(
                    prune, [remap[i] for i in group_indices], pruned_calls,
                    self.parallelism,
                    supervise=getattr(self, "supervise", False))
                merge = rfs.merge_executor()
                ng = len(group_indices)
                st = self.make_state(gdtypes + [T.BYTEA], list(range(ng)))
                return HashAggExecutor(merge, list(range(ng)),
                                       rfs.final_calls(), state_table=st)
            from ..runtime.remote_fragments import (make_remote_agg,
                                                    remotable_calls)
            if carry_cols and remotable_calls(calls):
                # retractable/owned-group placement: workers keep the
                # FULL stateful agg for their hash-owned groups; the
                # coordinator shadows the live input rows and re-seeds
                # respawned workers — agg state is a pure function of
                # the live input multiset. Shadow pk = the carried
                # stream-key columns (the unique row identity).
                dts = input.schema.dtypes
                shadow = self.make_state(dts, list(carry_cols))
                rfs = make_remote_agg(input, group_indices, calls,
                                      self.parallelism, shadow,
                                      supervise=getattr(self, "supervise",
                                                        False))
                return rfs.merge_executor()
        if self.parallelism > 1 and group_indices and not eowc:
            # Dispatch -> k parallel agg fragments -> Merge: the reference's
            # hash-exchange topology (`dispatch.rs:777` HashDataDispatcher,
            # `merge.rs:235` alignment) run inside one process. Group keys
            # hash to disjoint vnode blocks, so each fragment owns its
            # groups and the merged change stream equals the 1-fragment one.
            from ..ops import (Channel, ChannelSource, DispatchExecutor,
                               MergeExecutor)
            from ..ops.exchange import FragmentPump
            k = self.parallelism
            in_ch = [Channel(capacity=4096) for _ in range(k)]
            disp = DispatchExecutor(input, in_ch, kind="hash",
                                    key_indices=list(group_indices))
            out_ch = [Channel(capacity=4096) for _ in range(k)]
            pumps = []
            schema = None
            for i in range(k):
                st = self.make_state(gdtypes + [T.BYTEA],
                                     list(range(len(group_indices))))
                frag = HashAggExecutor(
                    ChannelSource(in_ch[i], input.schema, disp),
                    group_indices, calls, state_table=st)
                schema = frag.schema
                pumps.append(FragmentPump(frag, out_ch[i]))
            return MergeExecutor(out_ch, schema, pumps=pumps)
        st = self.make_state(gdtypes + [T.BYTEA],
                             list(range(len(group_indices))))
        return HashAggExecutor(input, group_indices, calls, state_table=st,
                               emit_on_window_close=eowc,
                               window_col_in_group=wc)

    # ---- FROM -----------------------------------------------------------
    def _plan_table(self, ref: A.TableRef) -> Tuple[Executor, Namespace]:
        if isinstance(ref, A.NamedTable):
            execu, schema, pk = self.subscribe(ref.name)
            ns = Namespace.of_schema(schema, ref.alias or ref.name, pk)
            ns.watermark_idx = self.watermark_of(ref.name)
            return execu, ns
        if isinstance(ref, A.SubqueryTable):
            execu, ns = self.plan_query(ref.query)
            alias = ref.alias
            return execu, Namespace(
                [ColumnEntry(alias, c.name, c.dtype) for c in ns.cols],
                list(ns.stream_key))
        if isinstance(ref, A.ChangelogTable):
            return self._plan_changelog(ref)
        if isinstance(ref, A.WindowTable):
            execu, ns = self._plan_table(ref.inner)
            ti = ns.resolve(ref.time_col)
            b = Binder(ns)
            ivals = [b.bind(a) for a in ref.args]
            assert all(isinstance(e, Literal) for e in ivals), \
                "window sizes must be INTERVAL literals"
            if ref.kind == "tumble":
                size = ivals[0].value
                hop = size
            else:
                hop, size = ivals[0].value, ivals[1].value
            execu = HopWindowExecutor(execu, ti, hop, size)
            alias = ref.alias
            cols = [ColumnEntry(alias or c.table, c.name, c.dtype)
                    for c in ns.cols]
            cols += [ColumnEntry(alias, "window_start", T.TIMESTAMP),
                     ColumnEntry(alias, "window_end", T.TIMESTAMP)]
            # each input row appears once per window: key = input key + win
            sk = list(ns.stream_key) + [len(cols) - 2]
            out = Namespace(cols, sk)
            out.watermark_idx = ns.watermark_idx
            return execu, out
        if isinstance(ref, A.TableFunctionTable):
            return self._plan_table_function(ref)
        if isinstance(ref, A.TemporalTable):
            raise ValueError("FOR SYSTEM_TIME AS OF PROCTIME() is only "
                             "valid as the right side of a join")
        if isinstance(ref, A.Join):
            return self._plan_join(ref)
        raise ValueError(f"cannot plan table ref {ref!r}")

    def _plan_table_function(self, ref: A.TableFunctionTable
                             ) -> Tuple[Executor, Namespace]:
        """FROM generate_series(...) / UNNEST(ARRAY[...]) — a bounded scan
        (`table_function/mod.rs:174`; batch `generate_series.rs`)."""
        from ..ops import TableFunctionScanExecutor
        if self.barrier_source is None:
            raise ValueError("table functions need a streaming context")
        tf = self._bind_table_function(ref.name, ref.args,
                                       Binder(Namespace([], [])))
        # PG: the alias of a single-column SRF names the COLUMN too
        # (SELECT g FROM generate_series(1,3) AS g)
        col = ref.alias or ref.name
        execu = TableFunctionScanExecutor(tf, col, self.barrier_source())
        cols = [ColumnEntry(col, col, tf.return_type),
                ColumnEntry(col, "_row_id", T.INT64)]
        return execu, Namespace(cols, [1], 1)

    def _bind_table_function(self, name: str, args: List[A.ExprNode],
                             b: "Binder"):
        from ..ops import BoundTableFunction
        from ..ops.project_set import series_return_type
        if name == "unnest":
            if len(args) != 1 or not isinstance(args[0], A.ArrayLit):
                raise ValueError("UNNEST supports ARRAY[...] literals only "
                                 "(array-typed columns are not supported)")
            elems = [b.bind(x) for x in args[0].items]
            if not elems:
                raise ValueError("UNNEST of an empty array")
            return BoundTableFunction("unnest", elems,
                                      elems[0].return_type)
        if not 2 <= len(args) <= 3:
            raise ValueError("generate_series(start, stop[, step])")
        bound = [b.bind(x) for x in args]
        rt = series_return_type([e.return_type for e in bound])
        if rt.kind == TypeKind.TIMESTAMP:
            # DATE bounds are day counts while the series runs in
            # TIMESTAMP microseconds — cast them up front, as the
            # reference does (`generate_series.rs` casts args to the
            # common timestamp type before evaluation). PG requires an
            # interval step for the timestamp form: without one, the
            # default step of 1 would mean one row per MICROSECOND.
            if len(bound) < 3:
                raise ValueError("generate_series over timestamps/dates "
                                 "requires an interval step")
            from ..expr.functions import cast as _cast
            bound = [_cast(e, T.TIMESTAMP)
                     if e.return_type.kind == TypeKind.DATE else e
                     for e in bound]
        return BoundTableFunction("generate_series", bound, rt)

    def _plan_changelog(self, ref: A.ChangelogTable
                        ) -> Tuple[Executor, Namespace]:
        """WITH x AS changelog FROM t (`changelog.rs` + the frontend's
        CteInner::ChangeLog lowering): upstream change stream ->
        append-only rows + `changelog_op` + hidden `_changelog_row_id`."""
        from ..ops import ChangelogExecutor, RowIdGenExecutor
        execu, schema, _pk = self.subscribe(ref.inner)
        chg = ChangelogExecutor(execu, op_name="changelog_op",
                                with_row_id=True)
        rid = len(chg.schema.fields) - 1
        execu = RowIdGenExecutor(chg, row_id_index=rid)
        alias = ref.alias or ref.inner
        cols = [ColumnEntry(alias, f.name, f.dtype)
                for f in chg.schema.fields]
        return execu, Namespace(cols, [rid])

    def _plan_join(self, ref: A.Join) -> Tuple[Executor, Namespace]:
        if isinstance(ref.right, A.TemporalTable):
            return self._plan_temporal_join(ref)
        if isinstance(ref.left, A.TemporalTable):
            raise ValueError("the version table (FOR SYSTEM_TIME) must be "
                             "the right side of a temporal join")
        lexec, lns = self._plan_table(ref.left)
        rexec, rns = self._plan_table(ref.right)
        ns = lns.concat(rns)
        conjuncts = _split_and(ref.on)
        if ref.kind in ("asof_inner", "asof_left"):
            return self._plan_asof_join(ref, lexec, lns, rexec, rns, ns,
                                        conjuncts)
        if ref.kind == "cross":
            # comma-join: steal equi conjuncts from the WHERE clause (the
            # reference's cross-join elimination / predicate-pushdown-into-
            # join rewrite, `optimizer/rule/` translate_apply + push rules);
            # `FROM a, b WHERE a.k = b.k` plans as an inner hash join
            stolen = []
            for c in list(self._pending_where):
                if _equi_pair(c, ns, len(lns.cols)) is not None:
                    stolen.append(c)
                    self._pending_where.remove(c)
            if not stolen:
                raise ValueError("cross join without equi-condition is not "
                                 "supported in streaming plans")
            conjuncts = stolen
            ref = A.Join(ref.left, ref.right, "inner", None)
        # split ON into equi-conjuncts and residual condition
        lkeys: List[int] = []
        rkeys: List[int] = []
        residual: List[A.ExprNode] = []
        nl = len(lns.cols)
        for c in conjuncts:
            pair = _equi_pair(c, ns, nl)
            if pair is not None:
                lkeys.append(pair[0])
                rkeys.append(pair[1] - nl)
            else:
                residual.append(c)
        if not lkeys:
            raise ValueError("join requires at least one equi-condition")
        cond = None
        if residual:
            node = residual[0]
            for r in residual[1:]:
                node = A.BinOp("and", node, r)
            cond = Binder(ns).bind(node)
        if self.delta_join and ref.kind == "inner" \
                and self.state_table_of is not None \
                and isinstance(ref.left, A.NamedTable) \
                and isinstance(ref.right, A.NamedTable):
            lookup = self._try_lookup_join(ref, lexec, rexec, lkeys, rkeys,
                                           cond)
            if lookup is not None:
                return lookup, ns
        ldtypes = [c.dtype for c in lns.cols]
        rdtypes = [c.dtype for c in rns.cols]
        # both dispatch paths share one state-table layout (row + degree,
        # pk = whole row), so the device policy doesn't reshape join state
        left_state = self.make_state(ldtypes + [T.INT64],
                                     list(range(len(ldtypes))))
        right_state = self.make_state(rdtypes + [T.INT64],
                                      list(range(len(rdtypes))))
        if self.device is not None and ref.kind == "inner":
            from ..ops.device_join import DeviceHashJoinExecutor
            execu: Executor = DeviceHashJoinExecutor(
                lexec, rexec, lkeys, rkeys, condition=cond,
                left_state=left_state, right_state=right_state,
                mesh=self.device.mesh, capacity=self.device.capacity)
        elif self.parallelism > 1 \
                and getattr(self, "placement", "local") == "process" \
                and cond is None \
                and ref.kind in ("inner", "left", "right", "full"):
            # hash-partitioned join across worker OS processes: workers
            # own their key space and keep the full join state; the
            # coordinator shadows both sides and re-seeds respawned
            # workers (runtime/remote_fragments.py RemoteStatefulSet)
            from ..runtime.remote_fragments import make_remote_join
            rfs = make_remote_join(lexec, rexec, lkeys, rkeys,
                                   _JOIN_KIND[ref.kind],
                                   self.parallelism,
                                   left_state, right_state,
                                   supervise=getattr(self, "supervise",
                                                     False))
            return rfs.merge_executor(), ns
        else:
            execu = HashJoinExecutor(
                lexec, rexec, lkeys, rkeys, _JOIN_KIND[ref.kind],
                condition=cond,
                left_state=left_state, right_state=right_state)
        return execu, ns

    def _try_lookup_join(self, ref: A.Join, lexec, rexec, lkeys, rkeys,
                         cond) -> Optional[Executor]:
        """Arrangement-sharing lookup/delta join when both sides' join
        keys are pk prefixes of their state tables (the reference's
        delta-join rule requires exactly this index property,
        `stream_delta_join.rs`); None -> fall back to hash join."""
        from ..ops.lookup_join import LookupJoinExecutor
        lt = self.state_table_of(ref.left.name, lkeys)
        rt = self.state_table_of(ref.right.name, rkeys)
        if lt is None or rt is None:
            return None                 # keys not indexed -> hash join
        return LookupJoinExecutor(lexec, rexec, lkeys, rkeys, lt, rt,
                                  condition=cond)

    def _plan_asof_join(self, ref: A.Join, lexec, lns, rexec, rns, ns,
                        conjuncts) -> Tuple[Executor, Namespace]:
        """ASOF [LEFT] JOIN: equi keys + exactly ONE inequality conjunct
        (`stream_asof_join.rs` / `asof_join.rs` AsOfDesc)."""
        from ..ops.asof_join import AsOfJoinExecutor
        nl = len(lns.cols)
        lkeys: List[int] = []
        rkeys: List[int] = []
        ineq: Optional[Tuple[int, int, str]] = None   # (l, r, op as l-op-r)
        flip = {"<": ">", "<=": ">=", ">": "<", ">=": "<="}
        for c in conjuncts:
            pair = _equi_pair(c, ns, nl)
            if pair is not None:
                lkeys.append(pair[0])
                rkeys.append(pair[1] - nl)
                continue
            if isinstance(c, A.BinOp) and c.op in flip \
                    and isinstance(c.left, A.Col) \
                    and isinstance(c.right, A.Col):
                li = ns.resolve(c.left.name, c.left.table)
                ri = ns.resolve(c.right.name, c.right.table)
                op = c.op
                if ri < nl <= li:
                    li, ri, op = ri, li, flip[op]
                if li < nl <= ri:
                    if ineq is not None:
                        raise ValueError("ASOF JOIN requires exactly one "
                                         "inequality condition")
                    ineq = (li, ri - nl, op)
                    continue
            raise ValueError("unsupported ASOF JOIN condition (equi "
                             "conjuncts + one column inequality only)")
        if not lkeys:
            raise ValueError("ASOF JOIN requires at least one "
                             "equi-condition")
        if ineq is None:
            raise ValueError("ASOF JOIN requires an inequality condition")
        ldtypes = [c.dtype for c in lns.cols]
        rdtypes = [c.dtype for c in rns.cols]
        left_state = self.make_state(ldtypes, list(range(len(ldtypes))))
        right_state = self.make_state(rdtypes, list(range(len(rdtypes))))
        execu = AsOfJoinExecutor(
            lexec, rexec, lkeys, rkeys, ineq[0], ineq[1], ineq[2],
            left_outer=ref.kind == "asof_left",
            left_pk=lns.stream_key, right_pk=rns.stream_key,
            left_state=left_state, right_state=right_state)
        # exactly (left: =1 | inner: <=1) output row per left row: the
        # LEFT stream key alone identifies output rows
        out_ns = Namespace(ns.cols, list(lns.stream_key), None)
        return execu, out_ns

    def _plan_temporal_join(self, ref: A.Join) -> Tuple[Executor, Namespace]:
        """stream JOIN t FOR SYSTEM_TIME AS OF PROCTIME() ON ...
        (`temporal_join.rs:44`): right side is a version index that is
        looked up, not joined — output is append-only."""
        from ..ops import TemporalJoinExecutor
        if ref.kind not in ("inner", "left"):
            raise ValueError("temporal joins support INNER and LEFT only")
        tref: A.TemporalTable = ref.right
        lexec, lns = self._plan_table(ref.left)
        rexec, rschema, rpk = self.subscribe(tref.inner.name)
        alias = tref.alias or tref.inner.name
        rns = Namespace.of_schema(rschema, alias, rpk)
        ns = lns.concat(rns)
        lkeys: List[int] = []
        rkeys: List[int] = []
        residual: List[A.ExprNode] = []
        nl = len(lns.cols)
        for c in _split_and(ref.on):
            pair = _equi_pair(c, ns, nl)
            if pair is not None:
                lkeys.append(pair[0])
                rkeys.append(pair[1] - nl)
            else:
                residual.append(c)
        if not lkeys:
            raise ValueError("temporal join requires an equi-condition on "
                             "the version table")
        cond = None
        if residual:
            node = residual[0]
            for r in residual[1:]:
                node = A.BinOp("and", node, r)
            cond = Binder(ns).bind(node)
        rdtypes = [f.dtype for f in rschema.fields]
        right_state = self.make_state(rdtypes, list(rpk or
                                                    range(len(rdtypes))))
        execu = TemporalJoinExecutor(
            lexec, rexec, lkeys, rkeys, outer=ref.kind == "left",
            condition=cond, right_pk=rpk, right_state=right_state)
        # output identity comes from the left stream alone: right-side
        # changes never retract emitted rows, so left stream key + right pk
        # make output rows unique
        out = Namespace(ns.cols, list(lns.stream_key)
                        + [nl + i for i in (rpk or [])])
        out.watermark_idx = lns.watermark_idx
        return execu, out

    # ---- SELECT ---------------------------------------------------------
    def plan_query(self, q: A.Query) -> Tuple[Executor, Namespace]:
        if isinstance(q, A.SetOp):
            return self._plan_setop(q)
        return self.plan_select(q)

    def _plan_setop(self, q: A.SetOp) -> Tuple[Executor, Namespace]:
        """UNION [ALL] -> UnionExecutor (`union.rs`). Branch rows stay
        distinguishable via a hidden `_branch` discriminator appended to
        the stream key (the reference StreamUnion's hidden source column);
        UNION distinct dedups with a group-only HashAgg over the visible
        columns, like the reference's UNION -> Union + Agg rewrite."""
        from ..ops import UnionExecutor
        if getattr(q, "emit_on_window_close", False):
            raise ValueError("EMIT ON WINDOW CLOSE is not supported on "
                             "UNION queries")
        branches: List[Tuple[Executor, Namespace]] = []
        for part in (q.left, q.right):
            if isinstance(part, A.Select) and part.from_ is None:
                branches.append(self._plan_values(part))
            else:
                branches.append(self.plan_query(part))
        l_ns = branches[0][1]
        lv = l_ns.n_visible if l_ns.n_visible is not None else len(l_ns.cols)
        for _, ns in branches[1:]:
            v = ns.n_visible if ns.n_visible is not None else len(ns.cols)
            if v != lv:
                raise ValueError("each UNION query must have the same "
                                 "number of columns")
            for i in range(lv):
                if ns.cols[i].dtype != l_ns.cols[i].dtype:
                    raise ValueError(
                        f"UNION types {l_ns.cols[i].dtype} and "
                        f"{ns.cols[i].dtype} cannot be matched (column "
                        f"{l_ns.cols[i].name!r})")
        if not q.all:
            # visible columns only; the dedup agg restores set semantics
            parts = []
            for execu, ns in branches:
                exprs = [InputRef(i, ns.cols[i].dtype) for i in range(lv)]
                parts.append(ProjectExecutor(
                    execu, exprs, [c.name for c in ns.cols[:lv]]))
            union: Executor = UnionExecutor(parts)
            dts = [c.dtype for c in l_ns.cols[:lv]]
            union = self._make_hash_agg(union, list(range(lv)), [], dts)
            out = Namespace([ColumnEntry(None, c.name, c.dtype)
                             for c in l_ns.cols[:lv]], list(range(lv)), lv)
            return self._setop_limit(q, union, out)
        # UNION ALL: carry each branch's stream key + a branch literal; the
        # key layouts must agree or output rows lose identity. Append-only
        # branches whose key layout differs get a minted row-id identity
        # (retraction-free, so fresh ids are safe).
        sk_dtypes = [[ns.cols[i].dtype for i in ns.stream_key]
                     for _, ns in branches]
        if any(d != sk_dtypes[0] for d in sk_dtypes[1:]):
            from ..ops import RowIdGenExecutor
            target = next((d for (e, _), d in zip(branches, sk_dtypes)
                           if not e.append_only), [T.INT64])
            for bi, ((execu, ns), skd) in enumerate(zip(list(branches),
                                                        sk_dtypes)):
                if skd == target or not execu.append_only:
                    continue
                if len(target) != 1 or target[0] not in (T.INT64, T.SERIAL):
                    break
                idx = len(ns.cols)
                execu = RowIdGenExecutor(execu, row_id_index=idx)
                ns = Namespace(ns.cols + [ColumnEntry(None, "_uid",
                                                      target[0])],
                               [idx], ns.n_visible)
                branches[bi] = (execu, ns)
                sk_dtypes[bi] = target
        if any(d != sk_dtypes[0] for d in sk_dtypes[1:]):
            raise ValueError("UNION ALL branches derive incompatible "
                             "stream keys; add DISTINCT or align the "
                             "branch row identities")
        parts = []
        for bi, (execu, ns) in enumerate(branches):
            exprs = [InputRef(i, ns.cols[i].dtype) for i in range(lv)]
            names = [c.name for c in ns.cols[:lv]]
            for ki, si in enumerate(ns.stream_key):
                exprs.append(InputRef(si, ns.cols[si].dtype))
                names.append(f"_sk{ki}")
            exprs.append(Literal(bi, T.INT32))
            names.append("_branch")
            parts.append(ProjectExecutor(execu, exprs, names))
        union = UnionExecutor(parts)
        cols = [ColumnEntry(None, c.name, c.dtype) for c in l_ns.cols[:lv]]
        nsk = len(sk_dtypes[0])
        cols += [ColumnEntry(None, f"_sk{k}", d)
                 for k, d in enumerate(sk_dtypes[0])]
        cols.append(ColumnEntry(None, "_branch", T.INT32))
        out = Namespace(cols, list(range(lv, lv + nsk + 1)), lv)
        return self._setop_limit(q, union, out)

    def _setop_limit(self, q: A.SetOp, execu: Executor, ns: Namespace
                     ) -> Tuple[Executor, Namespace]:
        if getattr(q, "limit", None) is None:
            return execu, ns
        order = [(ns.resolve(_order_name(e, ns)), d)
                 for e, d in q.order_by] if q.order_by else []
        st = self.make_state([c.dtype for c in ns.cols],
                             list(range(len(ns.cols))))
        return TopNExecutor(execu, order, q.limit, q.offset or 0,
                            state_table=st), ns

    def _plan_values(self, q: A.Select) -> Tuple[Executor, Namespace]:
        """Constant SELECT (no FROM) inside a set operation -> a one-shot
        Values source (`values.rs`)."""
        if self.barrier_source is None:
            raise ValueError("SELECT without FROM is a batch-only statement")
        from ..core.schema import Field, Schema
        from ..ops import ValuesExecutor
        row, fields = [], []
        for it in q.items:
            dt = const_expr_type(it.expr)
            row.append(eval_const(it.expr, dt))
            fields.append(Field(it.alias or _default_name(it.expr), dt))
        schema = Schema(fields)
        execu = ValuesExecutor(schema, [tuple(row)], self.barrier_source())
        ns = Namespace([ColumnEntry(None, f.name, f.dtype) for f in fields],
                       [], len(fields))
        return execu, ns

    def plan_select(self, q: A.Select) -> Tuple[Executor, Namespace]:
        # logical rewrites (sql/optimizer.py) run once per tree; subquery
        # recursion below sees already-optimized nodes
        if not hasattr(q, "applied_rules"):
            from .optimizer import optimize
            stats = None
            if self.state_table_of is not None:
                def stats(name, _sto=self.state_table_of):
                    st = _sto(name)
                    return len(st) if st is not None else None
            optimize(q, stats=stats)
        if q.from_ is None:
            raise ValueError("SELECT without FROM is a batch-only statement")
        # WHERE conjuncts are visible to FROM planning so comma-joins can
        # steal their equi conditions (cross-join elimination)
        outer_pw = getattr(self, "_pending_where", [])
        self._pending_where = _split_and(q.where)
        execu, ns = self._plan_table(q.from_)
        conjs = self._pending_where
        self._pending_where = outer_pw

        if conjs:
            plain: List[A.ExprNode] = []
            for conj in conjs:
                if _contains_now(conj):
                    execu = self._plan_now_filter(execu, ns, conj)
                elif isinstance(conj, A.InSubquery):
                    execu = self._plan_in_subquery(execu, ns, conj)
                elif _subquery_cmp(conj) is not None:
                    execu = self._plan_subquery_filter(execu, ns, conj)
                else:
                    plain.append(conj)
            if plain:
                node = plain[0]
                for c in plain[1:]:
                    node = A.BinOp("and", node, c)
                execu = FilterExecutor(execu, Binder(ns).bind(node))

        # expand stars (hidden system/stream-key columns stay hidden,
        # like PG's ctid)
        items: List[A.SelectItem] = []
        for it in q.items:
            if isinstance(it.expr, A.Star):
                for i, c in enumerate(ns.cols):
                    if c.name.startswith("_"):
                        continue
                    if it.expr.table is None or c.table == it.expr.table:
                        items.append(A.SelectItem(A.Col(c.name, c.table),
                                                  c.name))
            else:
                items.append(it)

        has_aggs = bool(q.group_by) or any(_contains_agg(i.expr)
                                           for i in items) or \
            (q.having is not None and _contains_agg(q.having))

        if has_aggs:
            execu, ns, items = self._plan_agg(execu, ns, q, items)
        if q.having is not None and not has_aggs:
            execu = FilterExecutor(execu, Binder(ns).bind(q.having))

        # over-window functions
        if any(isinstance(i.expr, A.FuncCall) and i.expr.over is not None
               for i in items):
            execu, ns, items = self._plan_over_window(execu, ns, items)

        # set-returning functions in the SELECT list -> ProjectSet
        # (`project_set.rs`); it subsumes the final projection
        from ..ops.project_set import TABLE_FUNCTIONS
        if any(isinstance(i.expr, A.FuncCall)
               and i.expr.name.lower() in TABLE_FUNCTIONS
               and i.expr.over is None for i in items):
            if getattr(q, "emit_on_window_close", False):
                raise ValueError("EMIT ON WINDOW CLOSE with set-returning "
                                 "functions is not supported")
            execu, ns = self._plan_project_set(execu, ns, items)
            if q.distinct:
                raise ValueError("SELECT DISTINCT with set-returning "
                                 "functions is not supported")
            if q.limit is not None:
                order = [(ns.resolve(_order_name(e, ns)), d)
                         for e, d in q.order_by] if q.order_by else []
                st = self.make_state([c.dtype for c in ns.cols],
                                     list(range(len(ns.cols))))
                execu = TopNExecutor(execu, order, q.limit, q.offset or 0,
                                     state_table=st)
            return execu, ns

        # final projection; upstream stream-key columns ride along hidden
        # unless already selected, so the MV pk can preserve multiplicity
        # (StreamMaterialize pk derivation analog)
        b = Binder(ns)
        exprs = [b.bind(i.expr) for i in items]
        names = [i.alias or _default_name(i.expr) for i in items]
        n_visible = len(items)
        ns_watermark_idx = ns.watermark_idx
        out_sk: List[int] = []
        if q.distinct:
            out_sk = list(range(n_visible))   # output is set-like
        else:
            for ki, sk_idx in enumerate(ns.stream_key):
                pos = next((j for j, e in enumerate(exprs)
                            if isinstance(e, InputRef) and e.index == sk_idx),
                           None)
                if pos is None:
                    pos = len(exprs)
                    exprs.append(InputRef(sk_idx, ns.cols[sk_idx].dtype))
                    names.append(f"_sk{ki}")
                out_sk.append(pos)
        execu = ProjectExecutor(execu, exprs, names)
        ns = Namespace([ColumnEntry(None, n, e.return_type)
                        for n, e in zip(names, exprs)],
                       out_sk, n_visible)

        if q.distinct:
            if execu.append_only:
                # insert-only input: dedup needs no counts, only a seen-set
                # (`dedup/append_only_dedup.rs`)
                from ..ops import AppendOnlyDedupExecutor
                dts = [c.dtype for c in ns.cols]
                st = self.make_state(dts, list(range(len(dts))))
                execu = AppendOnlyDedupExecutor(
                    execu, list(range(len(ns.cols))), state_table=st)
            else:
                execu = self._make_hash_agg(execu,
                                            list(range(len(ns.cols))), [],
                                            [c.dtype for c in ns.cols])
            # schema unchanged: group keys only

        if getattr(q, "emit_on_window_close", False) and not has_aggs:
            # EOWC without aggregation: emit rows in event-time order once
            # the watermark passes (`sort.rs`); requires the watermark
            # column in the output
            tc = next((j for j, e in enumerate(exprs)
                       if isinstance(e, InputRef)
                       and e.index == ns_watermark_idx), None) \
                if ns_watermark_idx is not None else None
            if tc is None:
                raise ValueError(
                    "EMIT ON WINDOW CLOSE requires a watermarked time "
                    "column in the select list")
            from ..ops import SortExecutor
            st = self.make_state([c.dtype for c in ns.cols],
                                 list(ns.stream_key))
            execu = SortExecutor(execu, tc, state_table=st)

        if q.limit is not None:
            order = [(ns.resolve(_order_name(e, ns)), d)
                     for e, d in q.order_by] if q.order_by else []
            st = self.make_state([c.dtype for c in ns.cols],
                                 list(range(len(ns.cols))))
            execu = TopNExecutor(execu, order, q.limit, q.offset or 0,
                                 state_table=st)
        return execu, ns

    def _plan_project_set(self, execu: Executor, ns: Namespace,
                          items: List[A.SelectItem]
                          ) -> Tuple[Executor, Namespace]:
        """Lower the select list to ProjectSet items: scalar expressions
        plus bound table functions, with the upstream stream key carried
        hidden and `projected_row_id` completing the output identity."""
        from ..ops import ProjectSetExecutor
        from ..ops.project_set import TABLE_FUNCTIONS
        b = Binder(ns)
        ps_items: List[Tuple[str, Any]] = []
        names: List[str] = []
        for it in items:
            e = it.expr
            if isinstance(e, A.FuncCall) \
                    and e.name.lower() in TABLE_FUNCTIONS and e.over is None:
                tf = self._bind_table_function(e.name.lower(), e.args, b)
                ps_items.append(("tf", tf))
                names.append(it.alias or e.name.lower())
            else:
                be = b.bind(e)
                ps_items.append(("s", be))
                names.append(it.alias or _default_name(e))
        n_visible = len(ps_items)
        carry = list(ns.stream_key)
        execu = ProjectSetExecutor(execu, ps_items, names, carry=carry)
        cols = [ColumnEntry(None, f.name, f.dtype)
                for f in execu.schema.fields]
        sk = list(range(n_visible, len(cols)))
        # the upstream watermark column survives either as a selected
        # scalar InputRef or via the hidden carry columns — map it through
        # so downstream EOWC/watermark operators keep advancing
        wm_out = None
        if ns.watermark_idx is not None:
            wm_out = next((j for j, (k, it) in enumerate(ps_items)
                           if k == "s" and isinstance(it, InputRef)
                           and it.index == ns.watermark_idx), None)
            if wm_out is None and ns.watermark_idx in carry:
                wm_out = n_visible + carry.index(ns.watermark_idx)
        return execu, Namespace(cols, sk, n_visible, watermark_idx=wm_out)

    def _plan_now_filter(self, execu: Executor, ns: Namespace,
                         conj: A.ExprNode) -> Executor:
        """`col <cmp> f(now())` -> Now + DynamicFilter (`now.rs`,
        `dynamic_filter.rs`): the bound is a one-row stream advancing with
        the barrier clock; rows enter/leave the output as it moves."""
        from ..ops import DynamicFilterExecutor, NowExecutor
        if self.barrier_source is None:
            raise ValueError("NOW() requires a streaming context")
        if not (isinstance(conj, A.BinOp) and conj.op in (">", ">=", "<",
                                                          "<=")):
            raise ValueError("NOW() is only supported in temporal filter "
                             "comparisons (col > NOW() - interval)")
        flip = {">": "<", ">=": "<=", "<": ">", "<=": ">="}
        lhs, rhs, cmp = conj.left, conj.right, conj.op
        if _contains_now(lhs):
            lhs, rhs, cmp = rhs, lhs, flip[cmp]
        if not isinstance(lhs, A.Col) or _contains_now(lhs):
            raise ValueError("the non-NOW() side of a temporal filter must "
                             "be a plain column")
        key_col = ns.resolve(lhs.name, lhs.table)
        now_st = self.make_state([T.TIMESTAMP], [0])
        now_src = NowExecutor(self.barrier_source(), state_table=now_st)
        now_ns = Namespace([ColumnEntry(None, "now", T.TIMESTAMP)], [0])
        bound = Binder(now_ns).bind(_rewrite_now(rhs))
        rhs_exec = ProjectExecutor(now_src, [bound], ["bound"])
        dts = [c.dtype for c in ns.cols]
        df_st = self.make_state(dts + [T.INT64], list(range(len(dts))))
        return DynamicFilterExecutor(execu, rhs_exec, key_col, cmp,
                                     state_table=df_st)

    def _plan_in_subquery(self, execu: Executor, ns: Namespace,
                          conj: A.InSubquery) -> Executor:
        """col [NOT] IN (SELECT ...) -> left semi/anti hash join (the
        reference's subquery unnesting into StreamHashJoin, `hash_join.rs`
        LeftSemi/LeftAnti arms). NOTE: NULLs in the subquery follow join
        semantics, not PG's three-valued NOT IN (no NULL-producing
        subqueries in the supported workloads)."""
        if not isinstance(conj.operand, A.Col):
            raise ValueError("IN (SELECT ...) requires a plain column on "
                             "the left")
        li = ns.resolve(conj.operand.name, conj.operand.table)
        sub_exec, sub_ns = self.plan_query(conj.query)
        nvis = sub_ns.n_visible if sub_ns.n_visible is not None \
            else len(sub_ns.cols)
        if nvis != 1:
            raise ValueError("IN subquery must select exactly one column")
        ldtypes = [c.dtype for c in ns.cols]
        rdtypes = [c.dtype for c in sub_ns.cols]
        left_state = self.make_state(ldtypes + [T.INT64],
                                     list(range(len(ldtypes))))
        right_state = self.make_state(rdtypes + [T.INT64],
                                      list(range(len(rdtypes))))
        jt = JoinType.LEFT_ANTI if conj.negated else JoinType.LEFT_SEMI
        return HashJoinExecutor(execu, sub_exec, [li], [0], jt,
                                left_state=left_state,
                                right_state=right_state)

    def _plan_subquery_filter(self, execu: Executor, ns: Namespace,
                              conj: A.ExprNode) -> Executor:
        """col CMP (SELECT scalar) -> DynamicFilter with the one-row
        subquery stream as the moving bound (`dynamic_filter.rs`; the
        reference unnests uncorrelated scalar subqueries the same way)."""
        lhs, rhs, cmp = _subquery_cmp(conj)
        if not isinstance(lhs, A.Col):
            raise ValueError("the non-subquery side of the comparison must "
                             "be a plain column")
        key_col = ns.resolve(lhs.name, lhs.table)
        sub_exec, sub_ns = self.plan_query(rhs.query)
        nvis = sub_ns.n_visible if sub_ns.n_visible is not None \
            else len(sub_ns.cols)
        if nvis != 1:
            raise ValueError("scalar subquery must select exactly one "
                             "column")
        sub_exec = ProjectExecutor(
            sub_exec, [InputRef(0, sub_ns.cols[0].dtype)], ["bound"])
        dts = [c.dtype for c in ns.cols]
        df_st = self.make_state(dts + [T.INT64], list(range(len(dts))))
        from ..ops import DynamicFilterExecutor
        return DynamicFilterExecutor(execu, sub_exec, key_col, cmp,
                                     state_table=df_st)

    def _plan_agg(self, execu: Executor, ns: Namespace, q: A.Select,
                  items: List[A.SelectItem]
                  ) -> Tuple[Executor, Namespace, List[A.SelectItem]]:
        b = Binder(ns)
        group_exprs = [b.bind(g) for g in q.group_by]

        aggs: List[A.FuncCall] = []
        for it in items:
            _find_aggs(it.expr, aggs)
        if q.having is not None:
            _find_aggs(q.having, aggs)

        # pre-projection: group keys then agg args
        pre_exprs: List[Expr] = list(group_exprs)
        pre_names = [f"g{i}" for i in range(len(group_exprs))]
        calls: List[AggCall] = []
        for i, a in enumerate(aggs):
            direct: Tuple = ()
            if a.name == "approx_percentile":
                # ordered-set: approx_percentile(q[, rel_err]) WITHIN
                # GROUP (ORDER BY v) — direct args must be literals
                # (`binder/expr/function/aggregate.rs:183`)
                if a.within_group is None or not 1 <= len(a.args) <= 2:
                    raise ValueError(
                        "approx_percentile(quantile[, relative_error]) "
                        "WITHIN GROUP (ORDER BY col)")
                dvals = []
                for x in a.args:
                    lit = b.bind(x)
                    if not isinstance(lit, Literal) or lit.value is None:
                        raise ValueError("approx_percentile direct "
                                         "arguments must be constants")
                    dvals.append(float(lit.value))
                direct = tuple(dvals)
                a = A.FuncCall(a.name, [a.within_group], a.distinct,
                               a.over, a.filter)
            if a.args:
                arg = b.bind(a.args[0])
                idx = len(pre_exprs)
                pre_exprs.append(arg)
                pre_names.append(f"a{i}")
                call_arg = InputRef(idx, arg.return_type)
            else:
                call_arg = None
            filt_ref = None
            if a.filter is not None:
                fe = b.bind(a.filter)
                fi = len(pre_exprs)
                pre_exprs.append(fe)
                pre_names.append(f"f{i}")
                filt_ref = InputRef(fi, T.BOOLEAN)
            calls.append(AggCall(a.name, call_arg, distinct=a.distinct,
                                 filter=filt_ref, direct_args=direct))
        if not pre_exprs:
            # count(*)-only: chunks must keep their cardinality, and a
            # zero-column chunk cannot (`DataChunk` derives capacity from
            # its columns) — project a constant
            pre_exprs = [Literal(1, T.INT32)]
            pre_names = ["_one"]
        # under process placement, carry the upstream stream key through
        # the pre-agg projection: remote stateful agg fragments need a
        # unique row identity for the coordinator's input shadow
        carry_cols: Optional[List[int]] = None
        if getattr(self, "placement", "local") == "process" \
                and self.parallelism > 1 and group_exprs \
                and not getattr(q, "emit_on_window_close", False) \
                and ns.stream_key:
            carry_cols = []
            for sk in ns.stream_key:
                carry_cols.append(len(pre_exprs))
                pre_exprs.append(InputRef(sk, ns.cols[sk].dtype))
                pre_names.append(f"_rk{sk}")
        proj = ProjectExecutor(execu, pre_exprs, pre_names)
        eowc = getattr(q, "emit_on_window_close", False)
        wc = None
        if eowc:
            wc = _find_window_col(q.group_by)
        if group_exprs:
            gdtypes = [e.return_type for e in group_exprs]
            agg: Executor = self._make_hash_agg(
                proj, list(range(len(group_exprs))), calls, gdtypes,
                eowc=eowc, wc=wc, carry_cols=carry_cols)
        else:
            st = self.make_state([T.INT64, T.BYTEA], [0])
            agg = SimpleAggExecutor(proj, calls, state_table=st)

        # post-agg namespace: group cols (resolvable by original AST) + aggs
        post_cols = []
        for i, g in enumerate(q.group_by):
            name = _default_name(g)
            post_cols.append(ColumnEntry(_table_of(g), name,
                                         group_exprs[i].return_type))
        for i, (a, c) in enumerate(zip(aggs, calls)):
            post_cols.append(ColumnEntry(None, f"agg#{i}", c.return_type))
        # the group key IS the stream key after aggregation (empty for the
        # single-row SimpleAgg output)
        post_ns = Namespace(post_cols, list(range(len(group_exprs))))

        # rewrite items/having: replace agg calls with agg#i refs, group
        # exprs with their post-agg columns
        def rewrite(node: A.ExprNode) -> A.ExprNode:
            for i, g in enumerate(q.group_by):
                if node == g:
                    c = post_cols[i]
                    return A.Col(c.name, c.table)
            if isinstance(node, A.FuncCall) and node.over is None and \
                    node.name in AGG_KINDS:
                idx = next(i for i, a in enumerate(aggs) if a is node)
                return A.Col(f"agg#{idx}")
            clone = _clone_with(node, rewrite)
            return clone

        new_items = [A.SelectItem(rewrite(i.expr), i.alias) for i in items]
        out: Executor = agg
        if q.having is not None:
            plain: List[A.ExprNode] = []
            for conj in _split_and(q.having):
                conj = rewrite(conj)
                if _subquery_cmp(conj) is not None:
                    out = self._plan_subquery_filter(out, post_ns, conj)
                else:
                    plain.append(conj)
            if plain:
                node = plain[0]
                for c in plain[1:]:
                    node = A.BinOp("and", node, c)
                out = FilterExecutor(out, Binder(post_ns).bind(node))
        return out, post_ns, new_items

    def _frame_offset(self, bound: Tuple, b: "Binder", is_start: bool,
                      order_kind=None) -> Optional[int]:
        """Frame bound -> signed offset (None = unbounded, 0 = current).
        PRECEDING is negative, FOLLOWING positive. Interval offsets scale
        to the ORDER BY column's unit: microseconds for TIMESTAMP, days
        for DATE (whose runtime values are day counts)."""
        if bound[0] == "unbounded":
            # PG: frame start cannot be UNBOUNDED FOLLOWING, frame end
            # cannot be UNBOUNDED PRECEDING
            if is_start and bound[1] == "following":
                raise ValueError("frame start cannot be UNBOUNDED "
                                 "FOLLOWING")
            if not is_start and bound[1] == "preceding":
                raise ValueError("frame end cannot be UNBOUNDED PRECEDING")
            return None
        if bound[0] == "current":
            return 0
        e = b.bind(bound[1])
        if not isinstance(e, Literal) or e.value is None:
            raise ValueError("frame offsets must be constants")
        v = e.value
        if isinstance(v, Interval):
            if v.months:
                raise ValueError("month intervals are not valid frame "
                                 "offsets")
            if order_kind == TypeKind.DATE:
                if v.usecs:
                    raise ValueError("sub-day interval frame offsets are "
                                     "not valid over a DATE order column")
                v = v.days
            else:
                v = v.days * 86_400_000_000 + v.usecs
        if order_kind is None or isinstance(v, int):
            # ROWS offsets are row counts — integers only (PG errors on
            # fractional ROWS offsets rather than truncating)
            if float(v) != int(v):
                raise ValueError("ROWS frame offsets must be integers")
            v = int(v)
        else:
            v = float(v) if not isinstance(v, (int, float)) else v
        return -v if bound[0] == "preceding" else v

    def _plan_over_window(self, execu: Executor, ns: Namespace,
                          items: List[A.SelectItem]):
        specs = [i for i in items
                 if isinstance(i.expr, A.FuncCall) and i.expr.over is not None]
        first = specs[0].expr.over
        for s in specs[1:]:
            o = s.expr.over
            # frames are per-CALL (the executor computes each call's
            # frame independently); only partition/order must agree
            if o.partition_by != first.partition_by \
                    or o.order_by != first.order_by:
                raise ValueError("multiple distinct OVER() "
                                 "partition/order specs unsupported")
        b = Binder(ns)
        partition = [_as_input_ref(b.bind(p)) for p in first.partition_by]
        order = [(_as_input_ref(b.bind(e)), d) for e, d in first.order_by]
        def bind_frame(spec):
            """Per-CALL frame: each OVER() clause carries its own."""
            frame, mode = (None, 0), "rows"
            if spec.frame is not None:
                mode = spec.frame[0]
                ok = None
                if mode == "range" and order:
                    ok = ns.cols[order[0][0]].dtype.kind
                    has_offset = any(
                        bd[0] in ("preceding", "following")
                        for bd in (spec.frame[1], spec.frame[2]))
                    if has_offset and ok not in (
                            TypeKind.INT16, TypeKind.INT32, TypeKind.INT64,
                            TypeKind.FLOAT32, TypeKind.FLOAT64,
                            TypeKind.DECIMAL, TypeKind.TIMESTAMP,
                            TypeKind.TIMESTAMPTZ, TypeKind.DATE,
                            TypeKind.TIME):
                        # PG rejects offset RANGE frames over non-
                        # orderable-by-offset columns at plan time
                        raise ValueError(
                            "RANGE with offset requires a numeric or "
                            "datetime ORDER BY column")
                frame = (self._frame_offset(spec.frame[1], b, True, ok),
                         self._frame_offset(spec.frame[2], b, False, ok))
                if frame[0] is not None and frame[1] is not None \
                        and frame[0] > frame[1]:
                    raise ValueError("frame start cannot be past frame "
                                     "end")
            return frame, mode
        calls = []
        for s in specs:
            f: A.FuncCall = s.expr
            if f.filter is not None:
                raise ValueError("FILTER on window functions is not "
                                 "supported")
            arg = b.bind(f.args[0]) if f.args else None
            if f.name in ("sum", "count", "min", "max", "avg",
                          "first_value", "last_value"):
                frame, mode = bind_frame(f.over)
                calls.append(WindowFuncCall(f.name, arg, frame=frame,
                                            frame_mode=mode))
            else:
                # rank family / lag / lead ignore the frame clause (PG)
                offset = 1
                if f.name in ("lag", "lead") and len(f.args) > 2:
                    raise ValueError(
                        f"{f.name} default-value argument (3-arg form) "
                        "is not supported")
                if f.name in ("lag", "lead") and len(f.args) > 1:
                    # the offset argument must be a plan-time constant
                    # (PG allows expressions; this runtime's incremental
                    # affected-range computation needs a fixed offset)
                    try:
                        off = eval_const(f.args[1], T.INT64)
                    except Exception:
                        raise ValueError(
                            f"{f.name} offset must be a constant "
                            "integer") from None
                    if off is None or int(off) < 0:
                        raise ValueError(
                            f"{f.name} offset must be a non-negative "
                            f"constant, got {off!r}")
                    offset = int(off)
                calls.append(WindowFuncCall(f.name, arg, offset=offset))
        st = self.make_state([c.dtype for c in ns.cols],
                             list(range(len(ns.cols))))
        execu = OverWindowExecutor(execu, partition, order, calls,
                                   state_table=st)
        cols = list(ns.cols)
        new_items = []
        wi = 0
        for it in items:
            if isinstance(it.expr, A.FuncCall) and it.expr.over is not None:
                name = f"w#{wi}"
                cols.append(ColumnEntry(None, name, calls[wi].return_type))
                new_items.append(A.SelectItem(A.Col(name), it.alias))
                wi += 1
            else:
                new_items.append(it)
        return execu, Namespace(cols, list(ns.stream_key)), new_items


# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------


def eval_const(e: A.ExprNode, dtype: Optional[DataType] = None):
    """Evaluate a constant expression (no column refs) to a Python value."""
    from ..core.chunk import Op, StreamChunk
    b = Binder(Namespace([]))
    expr = b.bind(e)
    chunk = StreamChunk.from_rows([T.INT64], [(Op.INSERT, (0,))])
    col = expr.eval(chunk)
    v = col.get(0)
    if dtype is not None and v is not None:
        from ..expr import cast as _cast
        lit = Literal(v, expr.return_type)
        v = _cast(lit, dtype).eval(chunk).get(0)
    return v


def const_expr_type(e: A.ExprNode) -> DataType:
    return Binder(Namespace([])).bind(e).return_type


def _subquery_cmp(node: A.ExprNode):
    """(lhs, SubqueryExpr, cmp) when `node` is a comparison with a scalar
    subquery on exactly one side (cmp flipped if it's the left)."""
    if not (isinstance(node, A.BinOp)
            and node.op in (">", ">=", "<", "<=", "=")):
        return None
    flip = {">": "<", ">=": "<=", "<": ">", "<=": ">=", "=": "="}
    if isinstance(node.right, A.SubqueryExpr) \
            and not isinstance(node.left, A.SubqueryExpr):
        return (node.left, node.right, node.op)
    if isinstance(node.left, A.SubqueryExpr) \
            and not isinstance(node.right, A.SubqueryExpr):
        return (node.right, node.left, flip[node.op])
    return None


def _contains_now(node: A.ExprNode) -> bool:
    if isinstance(node, A.FuncCall) and node.name == "now" and not node.args:
        return True
    return any(_contains_now(c) for c in _children(node))


def _rewrite_now(node: A.ExprNode) -> A.ExprNode:
    """now() -> the Now stream's single column."""
    if isinstance(node, A.FuncCall) and node.name == "now" and not node.args:
        return A.Col("now")
    return _clone_with(node, _rewrite_now)


def _split_and(node: Optional[A.ExprNode]) -> List[A.ExprNode]:
    if node is None:
        return []
    if isinstance(node, A.BinOp) and node.op == "and":
        return _split_and(node.left) + _split_and(node.right)
    return [node]


def _equi_pair(node: A.ExprNode, ns: Namespace, nl: int
               ) -> Optional[Tuple[int, int]]:
    if not (isinstance(node, A.BinOp) and node.op == "="):
        return None
    if not (isinstance(node.left, A.Col) and isinstance(node.right, A.Col)):
        return None
    try:
        li = ns.resolve(node.left.name, node.left.table)
        ri = ns.resolve(node.right.name, node.right.table)
    except ValueError:
        return None
    if li < nl <= ri:
        return (li, ri)
    if ri < nl <= li:
        return (ri, li)
    return None


def _as_input_ref(e: Expr) -> int:
    if not isinstance(e, InputRef):
        raise ValueError("PARTITION BY / ORDER BY must be plain columns")
    return e.index


def _order_name(e: A.ExprNode, ns: Namespace) -> str:
    if isinstance(e, A.Col):
        return e.name
    raise ValueError("ORDER BY in MV must reference output columns")


def _default_name(e: A.ExprNode) -> str:
    if isinstance(e, A.Col):
        return e.name
    if isinstance(e, A.FuncCall):
        return e.name
    if isinstance(e, A.ExtractExpr):
        return "extract"
    if isinstance(e, A.CaseExpr):
        return "case"
    if isinstance(e, A.CastExpr):
        return _default_name(e.operand)
    return "?column?"


def _table_of(e: A.ExprNode) -> Optional[str]:
    return e.table if isinstance(e, A.Col) else None


def _find_window_col(group_by: List[A.ExprNode]) -> Optional[int]:
    for i, g in enumerate(group_by):
        if isinstance(g, A.Col) and g.name in ("window_start", "window_end"):
            return i
    raise ValueError("EMIT ON WINDOW CLOSE requires window_start/window_end "
                     "in GROUP BY")


def _clone_with(node: A.ExprNode, f) -> A.ExprNode:
    if isinstance(node, A.BinOp):
        return A.BinOp(node.op, f(node.left), f(node.right))
    if isinstance(node, A.UnaryOp):
        return A.UnaryOp(node.op, f(node.operand))
    if isinstance(node, A.FuncCall):
        return A.FuncCall(node.name, [f(a) for a in node.args],
                          node.distinct, node.over, node.filter,
                          within_group=node.within_group)
    if isinstance(node, A.CaseExpr):
        return A.CaseExpr(f(node.operand) if node.operand else None,
                          [(f(c), f(r)) for c, r in node.branches],
                          f(node.else_expr) if node.else_expr else None)
    if isinstance(node, A.CastExpr):
        return A.CastExpr(f(node.operand), node.type_name)
    if isinstance(node, A.ExtractExpr):
        return A.ExtractExpr(node.field, f(node.operand))
    if isinstance(node, A.IsNullExpr):
        return A.IsNullExpr(f(node.operand), node.negated)
    if isinstance(node, A.Between):
        return A.Between(f(node.operand), f(node.low), f(node.high),
                         node.negated)
    if isinstance(node, A.InList):
        return A.InList(f(node.operand), [f(i) for i in node.items],
                        node.negated)
    if isinstance(node, A.Index):
        return A.Index(f(node.operand), node.index)
    if isinstance(node, A.InSubquery):
        return A.InSubquery(f(node.operand), node.query, node.negated)
    return node
