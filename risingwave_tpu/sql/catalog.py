"""Catalog: named tables/sources/MVs/sinks -> schemas + state table ids.

Analog of the reference's meta catalog + frontend catalog cache
(`src/meta/src/controller/catalog/`, `src/frontend/src/catalog/`), collapsed
to the single-process control plane.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from ..core.schema import Schema


@dataclass
class CatalogObject:
    name: str
    kind: str                      # 'table' | 'source' | 'mv' | 'sink' | 'index'
    schema: Schema
    pk: List[int]                  # pk column indices into schema
    table_id: int                  # MV/table state table id
    append_only: bool = False
    with_options: Dict[str, str] = field(default_factory=dict)
    watermark_col: Optional[int] = None
    watermark_delay_usecs: int = 0
    n_visible: Optional[int] = None   # hidden stream-key cols sit past this
    parallelism: Optional[int] = None  # ALTER ... SET PARALLELISM override
    index_on: Optional[str] = None     # indexes: the indexed table's name
    # runtime attachments (set by Database)
    runtime: Any = None


class Catalog:
    def __init__(self):
        self.objects: Dict[str, CatalogObject] = {}
        self._next_table_id = 1

    def alloc_table_id(self) -> int:
        tid = self._next_table_id
        self._next_table_id += 1
        return tid

    def create(self, obj: CatalogObject) -> None:
        if obj.name in self.objects:
            raise ValueError(f"object {obj.name!r} already exists")
        self.objects[obj.name] = obj

    def drop(self, name: str, kind: Optional[str] = None) -> CatalogObject:
        obj = self.objects.get(name)
        if obj is None:
            raise KeyError(f"object {name!r} does not exist")
        if kind is not None and obj.kind != kind and \
                not (kind == "table" and obj.kind in ("table", "source")):
            raise ValueError(f"{name!r} is a {obj.kind}, not a {kind}")
        del self.objects[name]
        return obj

    def get(self, name: str) -> CatalogObject:
        obj = self.objects.get(name)
        if obj is None:
            raise KeyError(f"relation {name!r} does not exist")
        return obj

    def list(self, kind: str) -> List[str]:
        return sorted(n for n, o in self.objects.items() if o.kind == kind)
