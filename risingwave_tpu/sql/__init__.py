"""SQL frontend: lexer/parser -> AST -> binder -> stream/batch plans.

Re-design of the reference's L9 frontend (`src/frontend/`, `src/sqlparser/`)
scoped to the streaming-SQL core: DDL (tables, sources, MVs, sinks), DML
(insert/delete), and SELECT with joins, aggregation, windows (TUMBLE/HOP),
over-window functions, ORDER BY/LIMIT — the shapes the Nexmark suite uses.
The optimizer is deliberately minimal (the reference's 100+ rule framework
exists to canonicalize what this planner emits directly); plans lower
straight onto the executor layer (`risingwave_tpu/ops/`).
"""
from .catalog import Catalog, CatalogObject
from .database import Database
from .parser import parse_sql
