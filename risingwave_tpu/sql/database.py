"""Database: the single-process control plane + session surface.

Plays the combined role of the reference's frontend session
(`src/frontend/src/session.rs`), meta DDL controller
(`src/meta/src/rpc/ddl_controller.rs:295`) and barrier worker
(`src/meta/src/barrier/worker.rs:380`): executes statements, owns the
catalog, spawns streaming jobs, ticks barriers through ALL jobs, and
commits epochs to the state store.

Dataflow topology: every table/source/MV materializes into a state table
and exposes its change stream through a `SharedStream`; downstream MVs tap
a port and prepend a backfill snapshot (the `backfill/` executor analog —
consistent because DDL happens between barriers, so a new port sees exactly
the changes after the snapshot).
"""
from __future__ import annotations

from typing import Any, Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from ..connectors import ListReader
from ..connectors.nexmark import NexmarkReader
from ..connectors.datagen import DatagenReader
from ..core import dtypes as T
from ..core.chunk import Column, Op, StreamChunk
from ..core.dtypes import DataType
from ..core.schema import Field, Schema
from ..ops import (Barrier, BarrierInjector, BatchScan, ConflictBehavior,
                   MaterializeExecutor, RowIdGenExecutor, SourceExecutor,
                   WatermarkFilterExecutor)
from ..ops.executor import Executor, SharedStream
from ..ops.message import Message, Watermark
from ..state import MemoryStateStore, SpillStateStore, StateStore, StateTable
from . import ast as A
from .catalog import Catalog, CatalogObject
from .parser import parse_sql
from .planner import Binder, Namespace, Planner, type_from_name

ROWID = "_row_id"
# DDL log layout (shared with risingwave_tpu.ctl): table id 0 holds
# (seq, sql) rows keyed by seq
import threading

# Set (active=True) by pgwire handler threads: statements arriving over the
# network carry this marker so security-sensitive DDL (embedded UDFs) can be
# gated per-connection without touching the embedding process's local API.
WIRE_SESSION = threading.local()

DDL_LOG_TABLE_ID = 0
DDL_LOG_DTYPES = (T.INT64, T.VARCHAR)
DDL_LOG_PK = (0,)
# durable poison-pill dead-letter queue (fault-tolerance v3): a reserved
# table id far above anything the catalog allocates, shared by every job
# in the directory (rows carry the job name) and readable standalone by
# `risectl dlq` without a Database
DLQ_TABLE_ID = 0x7EAD
# durable shed-window audit log (overload control plane): same reserved-
# id pattern as the dead-letter queue — one row per source window shed
# under RW_LOAD_SHED, readable standalone (rw_shed_log)
SHED_TABLE_ID = 0x5EED


class _Backfill(Executor):
    """Yield the upstream snapshot in bounded chunks, then the live
    change stream (`arrangement_backfill.rs` analog — snapshot is
    consistent because DDL runs between barriers). Progress (rows
    emitted / total) is tracked per executor and surfaced through
    `rw_ddl_progress` (the meta `barrier/progress.rs` reporting)."""

    CHUNK = 1024

    def __init__(self, snapshot: Optional[StreamChunk], port: Executor,
                 upstream_name: str = ""):
        super().__init__(port.schema, "Backfill")
        self.append_only = port.append_only
        self.snapshot = snapshot
        self.port = port
        self.upstream_name = upstream_name
        self.total = snapshot.capacity if snapshot is not None else 0
        self.emitted = 0
        self.done = self.total == 0

    @property
    def progress(self) -> float:
        return 1.0 if self.done else self.emitted / max(1, self.total)

    def execute(self) -> Iterator[Message]:
        if self.snapshot is not None and self.snapshot.capacity:
            cols = self.snapshot.columns
            n = self.snapshot.capacity
            for lo in range(0, n, self.CHUNK):
                hi = min(n, lo + self.CHUNK)
                idx = np.arange(lo, hi)
                yield StreamChunk(self.snapshot.ops[lo:hi],
                                  [c.take(idx) for c in cols])
                self.emitted = hi
        self.done = True
        yield from self.port.execute()


def _walk_executors(root) -> Iterator[Any]:
    """Walk an executor tree through the common child attributes.
    `pumps` descends through a Merge's upstream dispatchers into NESTED
    remote fragment sets (an agg set fed by a join set) — without it the
    liveness sweep, EXPLAIN ANALYZE and the dead-letter wiring only saw
    the topmost set of a multi-set topology."""
    stack = [root]
    seen = set()
    while stack:
        e = stack.pop()
        if e is None or id(e) in seen:
            continue
        seen.add(id(e))
        yield e
        for attr in ("input", "left_exec", "right_exec", "port",
                     "inputs", "pumps"):
            v = getattr(e, attr, None)
            if isinstance(v, list):
                stack.extend(v)
            elif v is not None:
                stack.append(v)


class Database:
    def __init__(self, store: Optional[StateStore] = None,
                 data_dir: Optional[str] = None,
                 checkpoint_frequency: Optional[int] = None,
                 device=None, config=None):
        # node config tier: explicit ctor args override the config file
        from ..config import NodeConfig, SystemParams, default_session_vars
        if isinstance(config, str):
            config = NodeConfig.from_toml(config)
        self.config = config or NodeConfig()
        if data_dir is None:
            data_dir = self.config.storage.data_dir
        if device is None:
            device = self.config.device
        if checkpoint_frequency is None:
            checkpoint_frequency = self.config.streaming.checkpoint_frequency
        if store is None:
            store = (SpillStateStore(data_dir) if data_dir
                     else MemoryStateStore())
        self.store = store
        # system-param + session-var tiers
        self.system_params = SystemParams()
        self.system_params.values["checkpoint_frequency"] = \
            checkpoint_frequency
        self.session_vars = default_session_vars()
        # SQL->TPU dispatch policy (config.resolve_device): None = host-only.
        # Must match the value used when this data directory was created —
        # device-path state tables persist raw payload columns, host-path
        # tables persist pickled AggGroups — so the policy is recorded next
        # to the durable store and validated on reopen (fail fast instead of
        # corrupting recovered state).
        from ..config import resolve_device
        # device="auto": adopt whatever policy the data directory was
        # created with (inspection tools — risectl — must be able to open
        # any directory without knowing its policy, and must not stamp a
        # marker onto one that has none)
        self._marker_readonly = device == "auto"
        if device == "auto":
            device = self._device_from_marker(data_dir)
        self.device = resolve_device(device)
        self._check_device_marker()
        self.catalog = Catalog()
        # per-barrier span tree (inject -> per-job collect -> commit),
        # ring-buffered for rw_barrier_trace and file-logged in the data
        # dir for offline hang diagnosis (risectl trace)
        from ..utils.trace import BarrierTracer
        self.tracer = BarrierTracer(data_dir)
        # flight recorder (utils/blackbox.py): point the process-wide
        # telemetry ring's on-disk mirror at this data dir so a crash or
        # wedge leaves its last seconds readable by `risectl blackbox`
        from ..utils.blackbox import RECORDER
        RECORDER.attach(data_dir)
        RECORDER.record("boot", {"device": repr(device),
                                 "data_dir": data_dir})
        # source->MV freshness (utils/freshness.py): every MV commit
        # records ingest->commit wall; surfaced as rw_mv_freshness + the
        # mv_freshness_seconds histogram
        from ..utils.freshness import FreshnessTracker
        self._freshness = FreshnessTracker()
        # oldest ingest stamp of the barriers in the CURRENT checkpoint
        # window (host MVs commit whole windows at once; freshness must
        # anchor on the window's oldest event, not the sealing barrier's)
        self._window_ingest: Optional[float] = None
        # fused jobs mirror epoch-profile records here (risectl profile)
        self._data_dir = data_dir
        self.injector = BarrierInjector(checkpoint_frequency)
        self.sinks: List[Tuple[str, Iterator[Message]]] = []   # job pumps
        self._iters: Dict[str, Iterator[Message]] = {}
        # fused device jobs (whole-fragment epoch programs, device/fused.py)
        self._fused: Dict[str, Any] = {}
        # capacity high-water of DROPPED fused jobs, keyed by PLAN-SHAPE
        # HASH -> {node shape key -> caps}: a re-created MV with the same
        # plan shape — under any name — presizes from its predecessor
        # instead of re-climbing the growth ladder (try_fuse
        # cap_registry). Structural keys survive planner refactors; they
        # are the same keys the AOT compile manifest uses.
        self._fused_cap_hw: Dict[str, Dict[str, Dict[str, int]]] = {}
        self.sink_results: Dict[str, List[Tuple]] = {}
        self.epoch_committed = 0
        self._nexmark_gen = None
        # upstream (SharedStream, port) pairs captured while planning the
        # statement currently being executed; moved onto the created object
        self._pending_subs: List[Tuple[SharedStream, Any]] = []
        # DDL log (catalog persistence): table id 0 holds (seq, sql) rows;
        # replayed on open so a restarted process rebuilds its dataflows
        # (the meta catalog + recovery analog, `worker.rs:664`)
        self._functions: set = set()      # this session's UDF names
        self._ddl_log = StateTable(self.store, DDL_LOG_TABLE_ID,
                                   list(DDL_LOG_DTYPES), list(DDL_LOG_PK))
        self._ddl_seq = 0
        # poison-pill dead-letter queue (rw_dead_letter / risectl dlq):
        # durable through the same store as everything else, created
        # BEFORE catalog recovery so replayed jobs wire into it
        from ..runtime.remote_fragments import DeadLetterQueue
        self._dlq = DeadLetterQueue(StateTable(
            self.store, DLQ_TABLE_ID, list(DeadLetterQueue.DTYPES),
            list(DeadLetterQueue.PK)))
        # overload control plane (utils/overload.py): the per-job
        # degradation ladder + per-source admission buckets close the
        # loop from credit-starvation evidence to action once per tick;
        # the shed log audits every window dropped under RW_LOAD_SHED;
        # the select gate bounds concurrent pgwire SELECTs. Created
        # BEFORE catalog recovery so replayed sources wire their buckets.
        from ..utils.overload import OverloadManager, SelectGate, ShedLog
        self._shed_log = ShedLog(StateTable(
            self.store, SHED_TABLE_ID, list(ShedLog.DTYPES),
            list(ShedLog.PK)))
        self._overload = OverloadManager()
        self.select_gate = SelectGate()
        # serving tier (serving/read_cache.py): host-side epoch-versioned
        # MV snapshots — pgwire SELECTs over fused MVs serve from here,
        # one device pull per (MV, epoch) no matter how many readers.
        # Starts cold (restart/recovery included): the first read after
        # any commit repopulates.
        from ..serving import MVReadCache
        self.read_cache = MVReadCache()
        self._replaying = False
        self._recover_catalog()

    def _device_mode_str(self) -> str:
        if self.device is None:
            return "off"
        mode = ("mesh:%d" % self.device.mesh.devices.size
                if self.device.mesh is not None else "single")
        ms = getattr(self.device, "mesh_shards", 1) or 1
        if self.device.mesh is None and ms > 1:
            # mesh-sharded FUSED programs: state layouts are per-shard,
            # so a reopen must shard identically. Replicas MIRROR state
            # (layouts unchanged) but the marker still records them —
            # reopen policy checks must be exact, not merely compatible.
            mode += ":fshard%d" % ms
            reps = getattr(self.device, "replicas", 1) or 1
            if reps > 1:
                mode += ":rep%d" % reps
        return mode + (":minmax" if self.device.minmax else "")

    @staticmethod
    def _device_from_marker(data_dir: Optional[str]):
        """Reconstruct the device argument a data directory was created
        with (its device_mode.json marker); "off" when unmarked."""
        import json
        import os
        if not data_dir:
            return "off"
        path = os.path.join(data_dir, "device_mode.json")
        if not os.path.exists(path):
            return "off"
        with open(path) as f:
            mode = json.load(f)["mode"]
        if mode == "off":
            return "off"
        from ..config import DeviceConfig
        parts = mode.split(":")
        minmax = parts[-1] == "minmax"
        if minmax:
            parts = parts[:-1]
        if parts[0] == "single":
            ms = 1
            reps = 1
            if len(parts) > 1 and parts[1].startswith("fshard"):
                ms = int(parts[1][len("fshard"):])
            if len(parts) > 2 and parts[2].startswith("rep"):
                reps = int(parts[2][len("rep"):])
            return DeviceConfig(minmax=minmax, mesh_shards=ms,
                                replicas=reps)
        from ..parallel import make_mesh
        return DeviceConfig(mesh=make_mesh(int(parts[1])), minmax=minmax)

    def _check_device_marker(self) -> None:
        """Durable stores record the dispatch policy that shaped their state
        tables; a reopen under a different policy fails fast."""
        import json
        import os
        d = getattr(self.store, "dir", None)
        if d is None:
            return
        path = os.path.join(d, "device_mode.json")
        mode = self._device_mode_str()
        if os.path.exists(path):
            with open(path) as f:
                saved = json.load(f)["mode"]
            if saved != mode:
                raise ValueError(
                    f"data directory was created with device={saved!r} but "
                    f"reopened with device={mode!r}; state-table layouts "
                    "differ between dispatch policies")
        elif not self._marker_readonly:
            with open(path, "w") as f:
                json.dump({"mode": mode}, f)

    def _recover_catalog(self) -> None:
        entries = sorted(self._ddl_log.iter_all())
        if not entries:
            return
        self._replaying = True
        saved_vars = dict(self.session_vars)
        try:
            for seq, sql in entries:
                self._ddl_seq = max(self._ddl_seq, seq + 1)
                for stmt in parse_sql(sql):
                    self._execute(stmt)
        finally:
            self._replaying = False
            # replayed SET pins (plan-shape determinism) must not leak into
            # the fresh session
            self.session_vars = saved_vars

    def _log_ddl(self, sql: str) -> None:
        if self._replaying:
            return
        self._ddl_log.insert((self._ddl_seq, sql))
        self._ddl_seq += 1
        self._ddl_log.commit(self.injector.epoch.curr)
        self.store.commit_epoch(self.injector.epoch.curr)

    # ------------------------------------------------------------------
    # statement surface
    # ------------------------------------------------------------------
    def run(self, sql: str) -> List[Any]:
        from .parser import parse_sql_with_text
        out = []
        for stmt, text in parse_sql_with_text(sql):
            result = self._execute(stmt)
            if isinstance(stmt, (A.CreateTable, A.CreateMaterializedView,
                                 A.CreateSink, A.DropObject, A.CreateIndex,
                                 A.AlterParallelism, A.CreateFunction)) \
                    or (isinstance(stmt, A.SetVar) and stmt.system):
                if isinstance(stmt, A.CreateMaterializedView):
                    # plan shape depends on these session vars; pin them in
                    # the log so replay replans the same fragment topology
                    k = int(self.session_vars.get("streaming_parallelism")
                            or 0)
                    self._log_ddl(f"SET streaming_parallelism TO {k}")
                    pl = self.session_vars.get("streaming_placement")
                    if pl and pl != "local":
                        self._log_ddl(f"SET streaming_placement TO {pl}")
                    sv = bool(self.session_vars.get(
                        "streaming_supervision"))
                    self._log_ddl("SET streaming_supervision TO "
                                  + ("true" if sv else "false"))
                    dj = bool(self.session_vars.get(
                        "streaming_enable_delta_join"))
                    self._log_ddl("SET streaming_enable_delta_join TO "
                                  + ("true" if dj else "false"))
                self._log_ddl(text)
            out.append(result)
        return out

    def query(self, sql: str) -> List[Tuple]:
        """Run a single SELECT and return rows."""
        stmts = parse_sql(sql)
        assert len(stmts) == 1 and isinstance(stmts[0], (A.Select, A.SetOp))
        return self._run_batch_select(stmts[0])

    def _execute(self, stmt: Any) -> Any:
        if isinstance(stmt, A.CreateTable):
            return self._create_table(stmt)
        if isinstance(stmt, A.CreateMaterializedView):
            return self._create_mv(stmt)
        if isinstance(stmt, A.CreateFunction):
            return self._create_function(stmt)
        if isinstance(stmt, A.CreateSink):
            return self._create_sink(stmt)
        if isinstance(stmt, A.CreateIndex):
            return self._create_index(stmt)
        if isinstance(stmt, A.DropObject):
            return self._drop(stmt)
        if isinstance(stmt, A.Insert):
            return self._insert(stmt)
        if isinstance(stmt, A.Delete):
            return self._delete(stmt)
        if isinstance(stmt, A.Update):
            return self._update(stmt)
        if isinstance(stmt, A.Flush):
            return self.flush()
        if isinstance(stmt, (A.Select, A.SetOp)):
            return self._run_batch_select(stmt)
        if isinstance(stmt, A.ShowObjects):
            kind = {"tables": "table", "sources": "source",
                    "materialized views": "mv", "sinks": "sink"}[stmt.kind]
            return self.catalog.list(kind)
        if isinstance(stmt, A.Explain):
            return self._explain(stmt.stmt)
        if isinstance(stmt, A.ExplainAnalyze):
            return self._explain_analyze(stmt.target)
        if isinstance(stmt, A.AlterParallelism):
            return self._alter_parallelism(stmt)
        if isinstance(stmt, A.SetVar):
            return self._set_var(stmt)
        if isinstance(stmt, A.ShowVar):
            return self._show_var(stmt)
        raise ValueError(f"unsupported statement {stmt!r}")

    # ------------------------------------------------------------------
    # DDL
    # ------------------------------------------------------------------
    def _create_table(self, stmt: A.CreateTable) -> str:
        fields = [Field(c.name, type_from_name(c.type_name))
                  for c in stmt.columns]
        has_pk = bool(stmt.primary_key)
        if not has_pk:
            fields.append(Field(ROWID, T.INT64))
        schema = Schema(fields)
        pk = [schema.index_of(n) for n in stmt.primary_key] if has_pk \
            else [len(fields) - 1]
        tid = self.catalog.alloc_table_id()
        obj = CatalogObject(stmt.name, "source" if stmt.is_source else "table",
                            schema, pk, tid, stmt.append_only,
                            stmt.with_options)
        connector = stmt.with_options.get("connector", "dml")
        reader = self._make_reader(connector, stmt, schema)
        # split offsets persist for real connectors only: a DML buffer is
        # transient, and restoring its offset would skip freshly pushed rows
        split_st = None if connector == "dml" else StateTable(
            self.store, self.catalog.alloc_table_id(),
            [T.VARCHAR, T.VARCHAR], [0])
        src: Executor = SourceExecutor(schema, reader, self.injector,
                                       split_state_table=split_st,
                                       name=f"Source({stmt.name})",
                                       append_only=(connector != "dml"
                                                    or stmt.append_only))
        if connector != "dml":
            # source admission control: a per-epoch token bucket rated by
            # the downstream overload ladder; sheds (RW_LOAD_SHED only)
            # audit into the durable rw_shed_log. DML buffers stay
            # ungated — their pushes are synchronous client calls.
            bucket = self._overload.bucket(stmt.name)
            bucket.shed_sink = self._shed_record
            src.admission = bucket
        if not has_pk:
            src = RowIdGenExecutor(src, row_id_index=len(fields) - 1,
                                   shard=tid & 0x3FF)
        if stmt.watermark is not None:
            col, delay_expr = stmt.watermark
            ns = Namespace.of_schema(schema, stmt.name)
            ti = ns.resolve(col)
            bound = Binder(ns).bind(delay_expr)
            delay = _extract_delay(bound, ti)
            wm_st = StateTable(self.store, self.catalog.alloc_table_id(),
                               [T.INT64, schema.fields[ti].dtype], [0])
            src = WatermarkFilterExecutor(src, ti, delay, wm_st)
            obj.watermark_col = ti
        if stmt.is_source and connector != "dml":
            # SOURCES are passive pipes, not tables (`source_executor.rs`:
            # the reference never persists a source's stream; an MV on a
            # source starts from its creation point). Skipping the
            # per-row materialization is also the host path's single
            # biggest per-event cost.
            mv_table = None
            shared = SharedStream(src)
        else:
            mv_table = StateTable(self.store, tid, schema.dtypes, pk)
            # minted rowids never collide, so the conflict scan is pure
            # overhead there — and NO_CHECK is what lets Materialize keep
            # the append-only property for the device agg specialization
            mat = MaterializeExecutor(src, mv_table,
                                      ConflictBehavior.NO_CHECK if not has_pk
                                      else ConflictBehavior.OVERWRITE)
            shared = SharedStream(mat)
        obj.runtime = {"reader": reader if connector == "dml" else None,
                       "state_table": mv_table, "shared": shared,
                       "port": shared.subscribe()}
        # Virtual source (fused device path): a nexmark source under a
        # single-chip device policy does NOT start a host datagen job —
        # fused MVs regenerate events on device. The host chain is built
        # (for planning and as the fallback) but activates lazily, only if
        # a non-fusable consumer appears (_activate_source). Matches the
        # reference, where a SOURCE runs no dataflow until consumed
        # (`create_source.rs` — sources are passive until subscribed).
        obj.runtime["virtual"] = (stmt.is_source and connector == "nexmark"
                                  and self.device is not None
                                  and self.device.fuse
                                  and self.device.mesh is None)
        self.catalog.create(obj)
        if not obj.runtime["virtual"]:
            self._iters[stmt.name] = obj.runtime["port"].execute()
        return f"CREATE_{'SOURCE' if stmt.is_source else 'TABLE'}"

    def _activate_source(self, name: str) -> None:
        obj = self.catalog.get(name)
        rt = obj.runtime or {}
        if rt.get("virtual"):
            rt["virtual"] = False
            self._iters[name] = rt["port"].execute()

    def _make_reader(self, connector: str, stmt: A.CreateTable,
                     schema: Schema):
        if connector == "dml":
            return ListReader([])
        if connector == "nexmark":
            from ..connectors.nexmark import NexmarkConfig, NexmarkGenerator
            table = stmt.with_options.get("nexmark.table", "bid").lower()
            maxe = stmt.with_options.get("nexmark.max.events")
            per = int(stmt.with_options.get("nexmark.chunk.size", "8192"))
            kd = stmt.with_options.get("nexmark.key.dist", "")
            if self._nexmark_gen is None:
                # key_dist (e.g. 'zipf:1.5') reshapes the bid
                # auction/bidder picks into a power-law — reproducible
                # skewed workloads for tests and bench. The generator is
                # shared across this database's nexmark sources (one
                # event clock), so the FIRST nexmark source pins it.
                self._nexmark_gen = NexmarkGenerator(
                    NexmarkConfig(key_dist=kd) if kd else None)
            elif kd and self._nexmark_gen.cfg.key_dist != kd:
                raise ValueError(
                    "nexmark sources share one generator; key.dist "
                    f"{kd!r} conflicts with "
                    f"{self._nexmark_gen.cfg.key_dist!r}")
            cols = [c.name for c in stmt.columns]
            reader = NexmarkReader(table, self._nexmark_gen,
                                   events_per_poll=per,
                                   max_events=int(maxe) if maxe else None,
                                   columns=cols)
            # per-source host-ingest opt-in (fused jobs feed this source
            # through the staging pipeline instead of device datagen)
            ing = stmt.with_options.get("nexmark.ingest", "").lower()
            if ing and ing not in ("host", "device"):
                raise ValueError(
                    f"nexmark.ingest={ing!r} (supported: host, device)")
            reader.ingest_mode = "" if ing == "device" else ing
            return reader
        if connector == "datagen":
            from ..connectors.datagen import FieldGen
            per = int(float(stmt.with_options.get("rows.per.poll", "1024")))
            maxr = stmt.with_options.get("datagen.max.rows")
            # fields.<col>.kind = 'sequence' | 'random' | 'zipf:<s>'
            # (+ fields.<col>.start/end/seed) — the reference's datagen
            # field options; zipf makes skewed keys reproducible
            fields: Dict[str, FieldGen] = {}
            for k, v in stmt.with_options.items():
                if not k.startswith("fields.") or not k.endswith(".kind"):
                    continue
                col = k[len("fields."):-len(".kind")]
                opts = stmt.with_options
                kind, s = str(v), 1.5
                if kind.startswith("zipf"):
                    kind, _, sv = kind.partition(":")
                    s = float(sv) if sv else 1.5
                    kind = "zipf"
                fields[col] = FieldGen(
                    kind=kind,
                    start=int(opts.get(f"fields.{col}.start", "0")),
                    end=int(opts.get(f"fields.{col}.end", str(2**31))),
                    seed=int(opts.get(f"fields.{col}.seed", "0")),
                    s=s)
            return DatagenReader(schema, fields=fields or None,
                                 rows_per_chunk=per,
                                 max_rows=int(maxr) if maxr else None)
        if connector in ("fs", "filesystem", "posix_fs"):
            from ..connectors.base import SplitSourceReader, make_parser
            from ..connectors.filesystem import DirEnumerator, LineFileReader
            opts = stmt.with_options
            path = opts.get("fs.path")
            if not path:
                raise ValueError("fs connector requires fs.path")
            fmt = opts.get("format", opts.get("fs.format", "json"))
            return SplitSourceReader(
                DirEnumerator(path, opts.get("fs.pattern", "*")),
                LineFileReader(),
                make_parser(fmt, schema, opts),
                records_per_poll=int(opts.get("fs.records.per.poll",
                                              "4096")))
        raise ValueError(f"unknown connector {connector!r}")

    def _subscribe(self, name: str) -> Tuple[Executor, Schema]:
        obj = self.catalog.get(name)
        rt = obj.runtime
        snap = None
        if not self._replaying and rt["state_table"] is not None:
            # DDL-log replay: downstream recovered state already includes
            # the snapshot — re-backfilling would double-count. Sources
            # have no table (passive pipes): MVs start from now.
            snapshot_rows = list(rt["state_table"].iter_all())
            if snapshot_rows:
                snap = StreamChunk.from_rows(
                    obj.schema.dtypes,
                    [(Op.INSERT, r) for r in snapshot_rows])
        port = rt["shared"].subscribe()
        self._pending_subs.append((rt["shared"], port))
        return _Backfill(snap, port, name), obj.schema, obj.pk

    def _make_state(self, dtypes, pk):
        return StateTable(self.store, self.catalog.alloc_table_id(),
                          list(dtypes), list(pk))

    def _watermark_of(self, name: str) -> Optional[int]:
        obj = self.catalog.objects.get(name)
        return getattr(obj, "watermark_col", None) if obj else None

    def _barrier_source(self):
        from ..ops import BarrierSource
        return BarrierSource(self.injector)

    def _make_planner(self, subscribe, inj: Optional[BarrierInjector] = None,
                      **kw) -> Planner:
        """Planner wired to this Database's NOW()/watermark context; `inj`
        scopes barrier feeds to a one-shot batch injector."""
        from ..ops import BarrierSource
        bs = (lambda: BarrierSource(inj)) if inj is not None \
            else self._barrier_source
        return Planner(subscribe, barrier_source=bs,
                       watermark_of=self._watermark_of,
                       state_table_of=self._state_table_of, **kw)

    def _state_table_of(self, name: str, keycols=None):
        """The object's arrangement whose pk prefix covers `keycols` —
        its own state table, or any index on it (create_index.rs)."""
        obj = self.catalog.objects.get(name)
        if obj is None or not isinstance(obj.runtime, dict):
            return None
        if keycols is None:
            return obj.runtime.get("state_table")
        cands = [obj] + [o for o in self.catalog.objects.values()
                         if getattr(o, "index_on", None) == name]
        k = len(keycols)
        for o in cands:
            st = (o.runtime or {}).get("state_table") \
                if isinstance(o.runtime, dict) else None
            if st is not None \
                    and sorted(st.pk_indices[:k]) == sorted(keycols):
                return st
        return None

    def _create_index(self, stmt: A.CreateIndex) -> str:
        """CREATE INDEX i ON t (cols): an auto-maintained arrangement of
        the table with pk = (index cols, table pk) — exactly how the
        reference models indexes (an index IS a materialized view with a
        reordered pk, `frontend/src/handler/create_index.rs`); lookup/
        delta joins probe it when the join key matches its pk prefix."""
        src = self.catalog.get(stmt.table)
        if src.kind not in ("table", "mv"):
            raise ValueError("CREATE INDEX requires a table or "
                             "materialized view")
        name_to_pos = {f.name: i for i, f in enumerate(src.schema.fields)}
        try:
            idx_cols = [name_to_pos[c] for c in stmt.columns]
        except KeyError as e:
            raise ValueError(f"index column {e.args[0]!r} does not exist")
        pk = idx_cols + [i for i in src.pk if i not in idx_cols]
        self._pending_subs = []
        execu, schema, _ = self._subscribe(stmt.table)
        tid = self.catalog.alloc_table_id()
        # distribute by the INDEX columns: all rows of one key land in one
        # vnode, so a prefix probe reads a single vnode range (the
        # reference distributes arrangements by their join/index key)
        table = StateTable(self.store, tid, schema.dtypes, pk,
                           dist_key_indices=idx_cols)
        mat = MaterializeExecutor(execu, table, ConflictBehavior.NO_CHECK)
        shared = SharedStream(mat)
        obj = CatalogObject(stmt.name, "index", schema, pk, tid)
        obj.runtime = {"state_table": table, "shared": shared,
                       "port": shared.subscribe(), "reader": None,
                       "upstream_subs": self._pending_subs}
        obj.index_on = stmt.table
        self._pending_subs = []
        self.catalog.create(obj)
        self._iters[stmt.name] = obj.runtime["port"].execute()
        return "CREATE_INDEX"

    def _create_mv(self, stmt: A.CreateMaterializedView) -> str:
        planner = self._make_planner(self._subscribe,
                                     make_state=self._make_state,
                                     device=self.device)
        # SET streaming_parallelism > 1 plans host HashAgg through the
        # Dispatch/Merge exchange (0 = default single fragment); persisted
        # per CREATE in the DDL log so recovery replans identically
        planner.parallelism = max(
            1, int(self.session_vars.get("streaming_parallelism") or 0))
        # 'process' places parallel fragments in worker OS processes
        # (runtime/remote_fragments.py) — real host concurrency; Python
        # threads cannot provide it (GIL)
        planner.placement = self.session_vars.get("streaming_placement",
                                                  "local")
        # supervised placement: a FragmentSupervisor respawns single dead
        # workers in place instead of tearing the job down
        planner.supervise = bool(self.session_vars.get(
            "streaming_supervision"))
        planner.delta_join = bool(self.session_vars.get(
            "streaming_enable_delta_join"))
        self._pending_subs = []
        execu, ns = planner.plan_query(stmt.query)
        schema = ns.schema()
        # MV pk = the derived stream key (hidden columns appended by the
        # planner when the select list drops them) — preserves duplicate-row
        # multiplicity exactly like the reference's StreamMaterialize pk
        pk = list(ns.stream_key)
        tid = self.catalog.alloc_table_id()
        mv_table = StateTable(self.store, tid, schema.dtypes, pk)
        # whole-fragment fusion (device/fuse_planner.py): an eligible plan
        # over replayable sources becomes ONE jitted epoch program with
        # device-resident state; the per-operator host DAG is dropped
        if self.device is not None and self.device.fuse:
            from ..device.fuse_planner import try_fuse
            job = try_fuse(execu, ns, self.device, stmt.name,
                           mv_state_table=mv_table,
                           make_state=self._make_state,
                           cap_registry=self._fused_cap_hw)
            if job is not None:
                for shared, port in self._pending_subs:
                    shared.unsubscribe(port)
                self._pending_subs = []
                obj = CatalogObject(stmt.name, "mv", schema, pk, tid)
                obj.n_visible = ns.n_visible
                obj.runtime = {"state_table": mv_table, "shared": None,
                               "port": None, "reader": None,
                               "upstream_subs": [], "fused_job": job}
                self.catalog.create(obj)
                self._fused[stmt.name] = job
                if getattr(job, "ingest", None) is not None:
                    # host-ingest jobs keep PR 14's per-source admission
                    # semantics: each multiplexed source gets the same
                    # overload-manager bucket a host SourceExecutor
                    # would (rw_source_admission rows, ladder-rated
                    # factor, deferral lag) — unadmitted windows stay at
                    # the connector, never in RAM
                    for sname in job.ingest.source_names():
                        b = self._overload.bucket(sname)
                        b.shed_sink = self._shed_record
                        job.ingest.buckets[sname] = b
                job.profiler.attach(self._data_dir)
                # skew snapshots (risectl skew, offline-capable) mirror
                # beside epoch_profile.jsonl at every checkpoint
                job.data_dir = self._data_dir
                job.freshness = self._freshness
                if job.compile_service is not None and self._data_dir:
                    # mirror the compile manifest into the data dir so
                    # `risectl compile-status --offline` reads it from a
                    # dead directory (no live process, no cache dir)
                    job.compile_service.attach_dir(self._data_dir)
                job.recover()      # no-op unless the store has a committed
                # CREATE-time AOT kickoff: the plan's shapes (post-
                # presize) compile in the background while the
                # interpreted path serves the first epochs; identically-
                # shaped jobs and DROP+re-CREATE find every signature
                # already compiled (zero-compile warm start)
                job.prewarm()
                return "CREATE_MATERIALIZED_VIEW"     # event counter
            # fallback: the plan stayed on the host/per-operator path, so
            # any virtual (never-started) sources it reads must activate
            for sname in _source_names(stmt.query):
                o = self.catalog.objects.get(sname)
                if o is not None and (o.runtime or {}).get("virtual"):
                    self._activate_source(sname)
        # operator change streams are exact (retractions carry full rows,
        # updates arrive as U-/U+ pairs on the stream key), so the MV needs
        # no conflict scan — NoCheck, like the reference's StreamMaterialize
        # for non-DML inputs (materialize.rs handle_conflict gating)
        mat = MaterializeExecutor(execu, mv_table, ConflictBehavior.NO_CHECK)
        shared = SharedStream(mat)
        obj = CatalogObject(stmt.name, "mv", schema, pk, tid)
        obj.n_visible = ns.n_visible
        obj.runtime = {"state_table": mv_table, "shared": shared,
                       "port": shared.subscribe(), "reader": None,
                       "upstream_subs": self._pending_subs}
        self._pending_subs = []
        self.catalog.create(obj)
        self._iters[stmt.name] = obj.runtime["port"].execute()
        # stamp every remote worker set in the plan with its owning job
        # name + this process's dead-letter queue: the poison-pill
        # quarantine's audit identity (rw_dead_letter rows, the
        # supervisor_quarantined_total{job} label, risectl dlq routing)
        for e in _walk_executors(shared.upstream):
            r = getattr(e, "_remote", None)
            if r is not None:
                r.job_name = stmt.name
                r.dead_letter = self._dlq
        return "CREATE_MATERIALIZED_VIEW"

    def _explain(self, inner: Any) -> str:
        """EXPLAIN renders the physical plan this runtime would execute —
        the executor tree the planner lowers to (the AST lowers straight
        to executors; there is one plan shape). No state tables are
        allocated and no subscriptions are taken."""
        from .system_catalog import render_plan
        if isinstance(inner, A.CreateMaterializedView):
            q = inner.query
        elif isinstance(inner, (A.Select, A.SetOp)):
            q = inner
        else:
            return repr(inner)
        execu, _ns = self._make_planner(
            self._peek_subscribe(), inj=BarrierInjector(),
            device=self.device).plan_query(q)
        out = render_plan(execu)
        rules = getattr(q, "applied_rules", None)
        if rules:
            out += "\n-- rewrites: " + ", ".join(rules)
        return out

    def _explain_analyze(self, name: str) -> str:
        """EXPLAIN ANALYZE <mv>: live per-operator tree of a RUNNING
        streaming job — eps in/out, row amplification, occupancy vs
        capacity, HBM, per-phase time share, skew ratios (fused), or
        worker liveness + exchange backpressure (host/process
        placement). Numbers come from the same checkpoint-fresh
        surfaces as the rw_* system tables; rendering performs no
        device sync and no statement re-execution."""
        from .system_catalog import (explain_analyze_fused,
                                     explain_analyze_host)
        obj = self.catalog.get(name)
        if obj.kind not in ("mv", "sink", "index", "table"):
            raise ValueError(
                f"EXPLAIN ANALYZE needs a running streaming job; "
                f"{name!r} is a {obj.kind}")
        job = (obj.runtime or {}).get("fused_job") \
            if isinstance(obj.runtime, dict) else None
        if job is not None:
            return explain_analyze_fused(name, job)
        return explain_analyze_host(name, obj)

    def _peek_subscribe(self):
        """Schema-only subscribe: plans without taking subscriptions or
        allocating state (EXPLAIN / pgwire Describe)."""
        inj = BarrierInjector()

        def peek(name: str):
            from .system_catalog import SYSTEM_TABLES
            if name in SYSTEM_TABLES and name not in self.catalog.objects:
                schema, _builder = SYSTEM_TABLES[name]
                src = SourceExecutor(schema, ListReader([]), inj,
                                     name=f"SysScan({name})")
                return src, schema, list(range(len(schema)))
            obj = self.catalog.get(name)
            src = SourceExecutor(obj.schema, ListReader([]), inj,
                                 name=f"Scan({name})")
            rt = obj.runtime or {}
            shared = rt.get("shared")
            if shared is not None:
                src.append_only = shared.upstream.append_only
            return src, obj.schema, obj.pk

        return peek

    def describe_select(self, q):
        """Row description of a SELECT without executing it (the pgwire
        Describe answer)."""
        if isinstance(q, A.Select) and q.from_ is None:
            row = tuple(_eval_const(i.expr, None) for i in q.items)
            return [(it.alias or "?column?", _const_dtype(v))
                    for it, v in zip(q.items, row)]
        _execu, ns = self._make_planner(
            self._peek_subscribe(), inj=BarrierInjector()).plan_query(q)
        n_vis = ns.n_visible or len(ns.cols)
        return [(c.name, c.dtype) for c in ns.cols[:n_vis]]

    def _set_var(self, stmt: A.SetVar) -> str:
        """SET (session tier) / ALTER SYSTEM SET (cluster tier,
        DDL-logged so restarts replay it). System params take effect
        immediately where the runtime consumes them."""
        if stmt.system:
            v = self.system_params.set(stmt.name, stmt.value)
            if stmt.name == "checkpoint_frequency":
                self.injector.checkpoint_frequency = max(1, int(v))
            return f"ALTER_SYSTEM_{stmt.name}"
        from ..config import SESSION_VAR_DEFAULTS
        if stmt.name not in SESSION_VAR_DEFAULTS:
            raise ValueError(
                f"unrecognized configuration parameter {stmt.name!r}")
        want = type(SESSION_VAR_DEFAULTS[stmt.name])
        v = stmt.value
        if want is bool and isinstance(v, str):
            v = v.strip().lower() in ("t", "true", "1", "on")
        elif not isinstance(v, want):
            v = want(v)
        self.session_vars[stmt.name] = v
        return f"SET_{stmt.name}"

    def _show_var(self, stmt: A.ShowVar):
        if stmt.name is None:                      # SHOW ALL
            return sorted(self.session_vars.items())
        if stmt.name == "parameters":              # SHOW PARAMETERS
            return sorted(self.system_params.values.items())
        if stmt.name in self.session_vars:
            return self.session_vars[stmt.name]
        return self.system_params.get(stmt.name)

    def _alter_parallelism(self, stmt: A.AlterParallelism) -> str:
        """Elastic scale-out/in of one job's device-sharded operators
        (`src/meta/src/stream/scale.rs:2329` reschedule analog).

        Runs at a barrier boundary: `flush()` completes the in-flight
        barrier on every job first (all epoch buffers empty, state
        committed), then each device engine re-shards its vnode-mapped
        state onto an n-device mesh (`parallel/rescale.py`). Logged to the
        DDL log, so recovery replays the same topology — engines that
        recover AFTER the replayed rescale load their rows straight onto
        the new mesh."""
        obj = self.catalog.get(stmt.name)
        if obj.kind != "mv":
            raise ValueError(f"{stmt.name!r} is not a materialized view")
        if (obj.runtime or {}).get("fused_job") is not None:
            raise ValueError(
                f"{stmt.name!r} runs as a fused single-chip device job; "
                "create the database with a device mesh to shard it")
        n = stmt.parallelism
        if n < 1:
            raise ValueError("PARALLELISM must be >= 1")
        if not self._replaying:
            # barrier boundary; during DDL-log replay the dataflow is
            # half-rebuilt and ticking it would feed sources into only the
            # already-replayed jobs (buffers are empty anyway on replay)
            self.flush()
        from ..parallel import make_mesh
        mesh = make_mesh(n) if n > 1 else None
        rescaled = 0
        stack = [obj.runtime["shared"].upstream]
        seen = set()
        while stack:
            e = stack.pop()
            if id(e) in seen:
                continue
            seen.add(id(e))
            if hasattr(e, "rescale_mesh"):
                e.rescale_mesh(mesh)
                rescaled += 1
            for attr in ("input", "port", "left_exec", "right_exec",
                         "barrier_source"):
                c = getattr(e, attr, None)
                if c is not None:
                    stack.append(c)
            stack.extend(getattr(e, "inputs", ()))   # Union/Merge children
        obj.parallelism = n
        return f"ALTER_PARALLELISM_{rescaled}"

    def _create_function(self, stmt: A.CreateFunction) -> str:
        """CREATE FUNCTION ... LANGUAGE python (`udf/python.rs` analog):
        the body executes in-process and registers a scalar function.
        DDL-logged, so recovery re-registers it before dependent MVs
        replay."""
        if stmt.language.lower() != "python":
            raise ValueError(f"LANGUAGE {stmt.language} not supported "
                             "(python only)")
        # embedded UDFs exec() arbitrary code in the server process; pgwire
        # sessions (detected via the WIRE_SESSION thread-local their handler
        # threads set) are refused unless the operator opted in (the
        # reference gates embedded UDFs the same way). The embedding
        # process's own local API is never gated, and DDL replay is exempt:
        # the statement was authorized when it was first accepted.
        via_wire = getattr(WIRE_SESSION, "active", False)
        if via_wire and not getattr(WIRE_SESSION, "udf_allowed", False) \
                and not self._replaying:
            raise ValueError(
                "embedded Python UDFs are disabled for network clients "
                "(start the server with enable_embedded_udf=True)")
        if stmt.name.lower() in self._functions and not stmt.or_replace \
                and not self._replaying:
            raise ValueError(f"function {stmt.name!r} already exists")
        from ..expr.functions import register_python_udf
        # the registry is process-global (build_func has no session scope);
        # duplicate detection is per-Database, last registration wins
        register_python_udf(
            stmt.name, stmt.body,
            [type_from_name(t) for t in stmt.arg_types],
            type_from_name(stmt.return_type), replace=True)
        self._functions.add(stmt.name.lower())
        return "CREATE_FUNCTION"

    def _create_sink(self, stmt: A.CreateSink) -> str:
        self._pending_subs = []
        sink_pk = None
        if stmt.from_name is not None:
            execu, schema, sink_pk = self._subscribe(stmt.from_name)
        else:
            execu, ns = self._make_planner(
                self._subscribe, make_state=self._make_state,
                device=self.device).plan_query(stmt.query)
            schema = ns.schema()
        obj = CatalogObject(stmt.name, "sink", schema, [], 0,
                            with_options=stmt.with_options)
        connector = stmt.with_options.get("connector", "collect")
        if connector in ("fs", "filesystem", "posix_fs"):
            from ..connectors.sink import FileSink, SinkExecutor
            path = stmt.with_options.get("fs.path")
            if not path:
                raise ValueError("fs sink requires fs.path")
            sink = FileSink(path, schema,
                            fmt=stmt.with_options.get("format", "jsonl"),
                            append_only=execu.append_only)
            # durable delivery log (the log-store analog): commits in the
            # same store epoch as the source offsets, closing the crash
            # window between external delivery and checkpoint
            log_table = StateTable(
                self.store, self.catalog.alloc_table_id(),
                [T.INT64, T.INT64, T.INT64, T.BYTEA], [0, 1])
            # upstream pk (when sinking FROM a materialized object)
            # arms the sink-boundary dedupe: post-respawn refreshes may
            # re-state rows the changelog already carries, and the MV's
            # by-pk reconciliation doesn't reach external files
            # durable per-pk mirror journal (fault-tolerance v3): the
            # delivered mirror persists through this table with epoch-
            # fenced commits, so a coordinator restart rebuilds it and a
            # refresh racing the crash cannot duplicate into the file
            mirror_table = StateTable(
                self.store, self.catalog.alloc_table_id(),
                [T.BYTEA, T.INT64, T.BYTEA], [0]) if sink_pk else None
            sink_exec = SinkExecutor(execu, sink, log_table=log_table,
                                     pk_indices=sink_pk,
                                     mirror_table=mirror_table)
            obj.runtime = {"sink": sink, "sink_exec": sink_exec,
                           "collect": None,
                           "state_table": None, "shared": None,
                           "reader": None,
                           "upstream_subs": self._pending_subs}
            self._pending_subs = []
            self.catalog.create(obj)
            self._iters[stmt.name] = sink_exec.execute()
            return "CREATE_SINK"
        rows: List[Tuple] = []
        self.sink_results[stmt.name] = rows
        obj.runtime = {"collect": rows, "state_table": None, "shared": None,
                       "reader": None, "upstream_subs": self._pending_subs}
        self._pending_subs = []
        self.catalog.create(obj)
        self._iters[stmt.name] = self._sink_pump(execu, rows)
        return "CREATE_SINK"

    @staticmethod
    def _sink_pump(execu: Executor, rows: List[Tuple]) -> Iterator[Message]:
        for msg in execu.execute():
            if isinstance(msg, StreamChunk):
                for op, r in msg.compact().op_rows():
                    rows.append((op, r))
            yield msg

    def _drop(self, stmt: A.DropObject) -> str:
        if stmt.name in self.catalog.objects:
            dep = self._dependent_of(stmt.name)
            if dep is not None:
                # the reference refuses to drop relations with dependent
                # streaming jobs (catalog ensure_*_not_referenced)
                raise ValueError(
                    f"cannot drop {stmt.name!r}: streaming job {dep!r} "
                    "depends on it (drop that first)")
        try:
            obj = self.catalog.drop(stmt.name)
        except KeyError:
            if stmt.if_exists:
                return "DROP_SKIPPED"
            raise
        self._iters.pop(stmt.name, None)
        self._freshness.forget(stmt.name)
        self._overload.forget(stmt.name)
        self.read_cache.invalidate(stmt.name)
        dropped_job = self._fused.pop(stmt.name, None)
        if dropped_job is not None:
            if getattr(dropped_job, "ingest", None) is not None:
                dropped_job.ingest.close()    # join the staging thread
            if getattr(dropped_job, "tiering", None) is not None:
                # a re-created MV under the same name starts with no
                # demotion history — a stale journal would replay
                # evictions against state that never saw them
                dropped_job.tiering.clear_journal()
            # remember where its capacities topped out, keyed by plan
            # shape — a re-created MV with the same plan (any name)
            # starts there (zero growth replays); structurally identical
            # entries merge by max
            reg = self._fused_cap_hw.setdefault(dropped_job.plan_hash, {})
            for k, caps in dropped_job.shape_hints().items():
                prev = reg.setdefault(k, {})
                for s, c in caps.items():
                    prev[s] = max(prev.get(s, 0), c)
        # release upstream taps, or their buffers grow forever
        for shared, port in (obj.runtime or {}).get("upstream_subs", []):
            shared.unsubscribe(port)
        return "DROP"

    def _dependent_of(self, name: str) -> Optional[str]:
        """A streaming job that reads `name`'s arrangement, if any: an
        index ON it, or an MV whose lookup join probes its state table."""
        target = self.catalog.objects[name]
        st = (target.runtime or {}).get("state_table") \
            if isinstance(target.runtime, dict) else None
        tables = {id(st)} if st is not None else set()
        # an index's own table is probed under the indexed table's NAME
        for o in self.catalog.objects.values():
            if getattr(o, "index_on", None) == name \
                    and isinstance(o.runtime, dict):
                ist = o.runtime.get("state_table")
                if ist is not None:
                    tables.add(id(ist))
                return o.name        # index depends on its base directly
        from ..ops.lookup_join import LookupJoinExecutor
        for o in self.catalog.objects.values():
            if o.name == name or not isinstance(o.runtime, dict):
                continue
            shared = o.runtime.get("shared")
            if shared is None:
                continue
            for e in _walk_executors(shared.upstream):
                if isinstance(e, LookupJoinExecutor) \
                        and (id(e.larr.table) in tables
                             or id(e.rarr.table) in tables):
                    return o.name
        return None

    # ------------------------------------------------------------------
    # DML
    # ------------------------------------------------------------------
    def _insert(self, stmt: A.Insert) -> str:
        obj = self.catalog.get(stmt.table)
        reader: ListReader = obj.runtime["reader"]
        assert reader is not None, f"{stmt.table} is not DML-writable"
        schema = obj.schema
        data_cols = [f.name for f in schema.fields if f.name != ROWID]
        target = stmt.columns or data_cols
        rows = []
        for r in stmt.rows:
            vals = {c: _eval_const(e, _dtype(schema, c))
                    for c, e in zip(target, r)}
            # full schema row; _row_id stays NULL for RowIdGen to mint
            rows.append(tuple(vals.get(f.name) for f in schema.fields))
        reader.push(StreamChunk.from_rows(
            schema.dtypes, [(Op.INSERT, r) for r in rows]))
        self.flush()
        return f"INSERT_{len(rows)}"

    # ------------------------------------------------------------------
    # COPY FROM STDIN (pgwire firehose entry point)
    # ------------------------------------------------------------------
    def copy_describe(self, table: str) -> int:
        """Validate a COPY target and return its data-column count (the
        CopyInResponse column count — hidden _row_id excluded)."""
        obj = self.catalog.get(table)
        rt = obj.runtime if isinstance(obj.runtime, dict) else None
        if rt is None or rt.get("reader") is None:
            raise ValueError(f"{table} is not COPY-writable (DML tables "
                             "only — sources pull from their connector)")
        return sum(1 for f in obj.schema.fields if f.name != ROWID)

    def _copy_bucket(self, table: str):
        """The COPY firehose rides the same per-source admission buckets
        as connector sources (PR 14): re-rated by the overload ladder,
        refilled once per epoch. COPY refills its own bucket on epoch
        change — a DML table has no SourceExecutor to do it."""
        b = self._overload.bucket(table)
        if b.shed_sink is None:
            b.shed_sink = self._shed_record   # audited drops -> rw_shed_log
        cur = self.injector.epoch.curr
        if getattr(b, "_copy_epoch", None) != cur:
            b._copy_epoch = cur
            b.epoch_refill(max(1, b.stretch))
        return b

    def copy_chunk(self, table: str, text: str, fmt: str = "text",
                   delim: str = "\t",
                   force: bool = False) -> Tuple[str, int]:
        """One admission-gated COPY batch: parse `text` (newline-framed
        rows in the given format) and push through the table's DML
        reader. Returns (verdict, rows): `defer` pushed nothing — the
        caller holds the wire (TCP backpressure to the producer) and
        retries; `shed` dropped the batch with a durable rw_shed_log
        audit row (shedding rung + RW_LOAD_SHED only); `admit` pushed.
        `force` bypasses a defer after the caller's bounded wait so a
        COPY can never deadlock on a quiescent barrier clock."""
        obj = self.catalog.get(table)
        reader = (obj.runtime or {}).get("reader")
        assert reader is not None, f"{table} is not COPY-writable"
        b = self._copy_bucket(table)
        verdict = b.admit()
        if verdict == "defer" and not force:
            return "defer", 0
        rows = self._parse_copy(obj.schema, text, fmt, delim)
        if not rows:
            return "admit", 0
        if verdict == "shed":
            b.note_shed(self.injector.epoch.curr, len(rows))
            return "shed", len(rows)
        b.note_admitted(len(rows))
        reader.push(StreamChunk.from_rows(
            obj.schema.dtypes, [(Op.INSERT, r) for r in rows]))
        return "admit", len(rows)

    def copy_rows(self, table: str, text: str, fmt: str = "text",
                  delim: str = "\t") -> int:
        """Admission-gated COPY with a bounded defer wait (the embedded
        API / pgwire convenience wrapper around copy_chunk)."""
        import time as _time
        deadline = _time.monotonic() + 1.0
        while True:
            verdict, n = self.copy_chunk(
                table, text, fmt, delim,
                force=_time.monotonic() >= deadline)
            if verdict != "defer":
                return n if verdict == "admit" else 0
            _time.sleep(0.01)

    @staticmethod
    def _parse_copy(schema: Schema, text: str, fmt: str,
                    delim: str) -> List[Tuple]:
        """COPY text/csv lines -> full-schema host rows (the minimal PG
        subset: text format with \\N NULLs and backslash escapes, csv
        with RFC-4180 quoting — embedded delimiters/newlines/doubled
        quotes inside quoted fields — where an empty UNQUOTED field is
        NULL and a quoted empty field is the empty string)."""
        from ..connectors.base import _coerce
        fields = [f for f in schema.fields if f.name != ROWID]
        has_rowid = len(fields) != len(schema.fields)
        rows: List[Tuple] = []

        def build(vals: List[Optional[str]]) -> None:
            if len(vals) != len(fields):
                raise ValueError(
                    f"COPY row has {len(vals)} columns, table expects "
                    f"{len(fields)}")
            r = [None if v is None else _coerce(v, f.dtype)
                 for v, f in zip(vals, fields)]
            rows.append(tuple(r) + ((None,) if has_rowid else ()))

        if fmt == "csv":
            for parts in _csv_rows(text, delim):
                if parts == ["\\."]:     # end-of-data marker (PG
                    continue             # recognizes it in csv too)
                build(parts)
        else:
            import re
            # single-pass unescape: sequential str.replace would let an
            # escaped backslash's second byte re-match as '\\t' etc.
            unesc = {"t": "\t", "n": "\n", "r": "\r", "\\": "\\"}
            pat = re.compile(r"\\(.)")
            for ln in text.split("\n"):
                ln = ln.rstrip("\r")
                if not ln or ln == "\\.":
                    continue
                vals: List[Optional[str]] = []
                for p in ln.split(delim):
                    if p == "\\N":
                        vals.append(None)
                    else:
                        vals.append(pat.sub(
                            lambda m: unesc.get(m.group(1), m.group(1)),
                            p))
                build(vals)
        return rows

    def _delete(self, stmt: A.Delete) -> str:
        obj = self.catalog.get(stmt.table)
        if obj.append_only:
            raise ValueError(
                f"table {stmt.table!r} is APPEND ONLY: DELETE is not "
                "allowed (the plan property is load-bearing downstream)")
        reader: ListReader = obj.runtime["reader"]
        assert reader is not None
        # bind predicate against the table, evaluate over the current MV
        rows = list(obj.runtime["state_table"].iter_all())
        if not rows:
            return "DELETE_0"
        chunk = StreamChunk.from_rows(obj.schema.dtypes,
                                      [(Op.DELETE, r) for r in rows])
        if stmt.where is not None:
            ns = Namespace.of_schema(obj.schema, stmt.table)
            pred = Binder(ns).bind(stmt.where)
            col = pred.eval(chunk)
            keep = np.asarray(col.values, dtype=object)
            mask = np.array([bool(v) and bool(ok)
                             for v, ok in zip(keep, col.validity)])
            chunk = chunk.with_visibility(chunk.vis_mask() & mask)
        chunk = chunk.compact()
        if chunk.capacity == 0:
            return "DELETE_0"
        # deletes flow through the source so downstream MVs retract; rows
        # already carry their _row_id (RowIdGen preserves non-NULL ids)
        reader.push(chunk)
        n = chunk.capacity
        self.flush()
        return f"DELETE_{n}"

    def _update(self, stmt: A.Update) -> str:
        """UPDATE = U-/U+ pairs through the source (row ids preserved, so
        downstream retraction works like the reference's DML update path)."""
        obj = self.catalog.get(stmt.table)
        if obj.append_only:
            raise ValueError(
                f"table {stmt.table!r} is APPEND ONLY: UPDATE is not "
                "allowed (the plan property is load-bearing downstream)")
        reader: ListReader = obj.runtime["reader"]
        assert reader is not None, f"{stmt.table} is not DML-writable"
        rows = list(obj.runtime["state_table"].iter_all())
        if not rows:
            return "UPDATE_0"
        ns = Namespace.of_schema(obj.schema, stmt.table)
        b = Binder(ns)
        scan = StreamChunk.from_rows(obj.schema.dtypes,
                                     [(Op.INSERT, r) for r in rows])
        if stmt.where is not None:
            col = b.bind(stmt.where).eval(scan)
            keep = [bool(v) and bool(ok)
                    for v, ok in zip(col.values, col.validity)]
        else:
            keep = [True] * len(rows)
        assigns = [(obj.schema.index_of(c), b.bind(e))
                   for c, e in stmt.assignments]
        new_cols = {i: e.eval(scan) for i, e in assigns}
        pairs = []
        n = 0
        for ri, row in enumerate(rows):
            if not keep[ri]:
                continue
            new_row = list(row)
            for i, _ in assigns:
                c = new_cols[i]
                new_row[i] = c.get(ri)
            if tuple(new_row) == row:
                continue
            pairs += [(Op.UPDATE_DELETE, row),
                      (Op.UPDATE_INSERT, tuple(new_row))]
            n += 1
        if not pairs:
            return "UPDATE_0"
        reader.push(StreamChunk.from_rows(obj.schema.dtypes, pairs))
        self.flush()
        return f"UPDATE_{n}"

    # ------------------------------------------------------------------
    # barrier loop (GlobalBarrierWorker tick)
    # ------------------------------------------------------------------
    def tick(self) -> None:
        """Inject one barrier and drive every job until it passes."""
        import time as _time
        from ..utils.metrics import REGISTRY
        t0 = _time.perf_counter()
        self._heartbeat_workers()
        # overload control plane: fold this instant's credit-starvation
        # evidence (stall fractions, queue depths, sink stalls) into the
        # per-job degradation ladders and re-rate source admission —
        # BEFORE the barrier goes out, so this tick's dispatch already
        # runs under the decided state (cadence stretch, throttling)
        self._overload.tick(self)
        b = self.injector.inject()
        span = self.tracer.inject(b.epoch.curr, b.kind.value)
        # fused device jobs first: their epoch dispatch is ASYNC (no device
        # sync), so host executors below overlap with device compute
        for jname, job in self._fused.items():
            span.job_start(jname)
            job.on_barrier(b)
            span.job_end(jname)
        for name, it in list(self._iters.items()):
            span.job_start(name)
            for msg in it:
                if isinstance(msg, Barrier) and msg.epoch.curr == b.epoch.curr:
                    break
            span.job_end(name)
        # fold this barrier's ingest stamp (sources noted it while the
        # jobs drove) into the checkpoint window's oldest
        b_ing = b.best_ingest_ts()
        if b_ing is not None:
            self._window_ingest = b_ing if self._window_ingest is None \
                else min(self._window_ingest, b_ing)
        if b.is_checkpoint:
            self.store.commit_epoch(b.epoch.curr)
            self.epoch_committed = b.epoch.curr
            # post-checkpoint sink-committer step: the epoch's log entries
            # are durable now, so external delivery can go out
            for obj in self.catalog.objects.values():
                se = (obj.runtime or {}).get("sink_exec") \
                    if isinstance(obj.runtime, dict) else None
                if se is not None:
                    se.deliver_durable()
            # source->MV freshness: this commit durably reflects every
            # barrier since the LAST checkpoint; anchor = the oldest
            # source-stamped chunk wall across the whole window (the
            # per-barrier stamps folded below — with checkpoint_frequency
            # > 1 the sealing barrier's own stamp would under-report
            # staleness by up to a window). Fused jobs record their own
            # commits (their ingest is the device dispatch, not a host
            # chunk).
            ingest = self._window_ingest
            self._window_ingest = None
            if ingest is not None:
                commit_wall = _time.time()
                for obj in self.catalog.objects.values():
                    rt = obj.runtime if isinstance(obj.runtime, dict) \
                        else None
                    if obj.kind == "mv" and rt \
                            and rt.get("fused_job") is None:
                        self._freshness.commit(obj.name, b.epoch.curr,
                                               ingest, commit_wall)
        # per-worker barrier decomposition + clock-offset samples from
        # the remote result drains, folded into the tracer before the
        # commit event so the jsonl stays ordered within the epoch
        for _name, r in self._remote_sets():
            for epoch, worker, ts in r.drain_align_log():
                self.tracer.worker_align(epoch, worker, ts)
            for worker, sent, recv in r.drain_hb_log():
                self.tracer.hb_sample(worker, sent, recv)
        span.commit()   # barrier fully collected (checkpoint or not)
        # barrier latency + epoch progress (streaming_stats.rs analog)
        REGISTRY.histogram("barrier_latency_seconds",
                           "inject-to-collect barrier latency"
                           ).observe(_time.perf_counter() - t0)
        REGISTRY.counter("barrier_count", "barriers completed").inc()
        REGISTRY.gauge("committed_epoch", "last committed epoch"
                       ).set(self.epoch_committed)
        REGISTRY.gauge("streaming_jobs", "running dataflows"
                       ).set(len(self._iters))

    def _remote_sets(self) -> Iterator[Tuple[str, Any]]:
        """(job name, remote worker set) pairs across all live jobs — the
        shared walk behind the liveness sweep, the worker_liveness gauge
        and the rw_worker_liveness system table."""
        for obj in self.catalog.objects.values():
            rt = obj.runtime if isinstance(obj.runtime, dict) else None
            shared = rt.get("shared") if rt else None
            if shared is None:
                continue
            for e in _walk_executors(shared.upstream):
                r = getattr(e, "_remote", None)
                if r is not None:
                    yield obj.name, r

    def _worker_liveness_rows(self) -> List[Tuple]:
        """rw_worker_liveness rows: per-worker heartbeat age + state (ok /
        wedged? / dead) from the metrics-plane heartbeat frames, plus one
        row per file sink (worker='sink') whose state flips to `stalled`
        while external delivery is deferred — slow-sink isolation's
        liveness surface."""
        import os as _os
        import time as _time
        rows = [row for name, r in self._remote_sets()
                for row in r.liveness_rows(name)]
        now = _time.time()
        for obj in self.catalog.objects.values():
            rt = obj.runtime if isinstance(obj.runtime, dict) else None
            se = rt.get("sink_exec") if rt else None
            if se is not None:
                rows.append((obj.name, "sink", _os.getpid(),
                             se.sink.committed_epoch,
                             now - se.last_delivery_ts,
                             "stalled" if se.stalled else "ok"))
        return rows

    def _shed_record(self, source: str, epoch: int, rows: int) -> None:
        """AdmissionBucket shed sink: audit one shed source window into
        the durable rw_shed_log (committed at the current epoch, durable
        at the next checkpoint — the rw_dead_letter pattern)."""
        self._shed_log.record(source, epoch, rows, "admission",
                              self.injector.epoch.curr)
        from ..utils.blackbox import RECORDER
        RECORDER.record("shed", {"source": source, "epoch": int(epoch),
                                 "rows": int(rows)})

    def _heartbeat_workers(self) -> None:
        """Proactive worker liveness sweep, once per barrier tick (the
        meta heartbeat/expire analog, `src/meta/src/manager/cluster.rs`):
        a worker that dies while its job is QUIESCENT surfaces at the
        next tick instead of whenever traffic next touches its stream,
        and a WEDGED worker (alive, heartbeat frames gone stale) shows in
        the worker_liveness gauge before any spawn/drain deadline."""
        from ..runtime.remote_fragments import RemoteWorkerDied
        from ..utils.metrics import REGISTRY
        liveness = REGISTRY.gauge(
            "worker_liveness",
            "seconds since a worker's last metrics-plane heartbeat",
            labels=("job", "worker"))
        for name, r in self._remote_sets():
            for job, wname, _pid, _ep, age, _state in r.liveness_rows(name):
                liveness.labels(job, wname).set(age)
            if getattr(r, "supervisor", None) is not None:
                # supervised sets self-heal (or escalate) in place —
                # the sweep is just an extra detection path for
                # deaths while the job is quiescent
                r.check_alive()
                continue
            r._check_wedged()
            for w in r.workers:
                if w.proc.poll() is not None:
                    REGISTRY.counter(
                        "worker_heartbeat_failures",
                        "dead workers caught by the heartbeat sweep"
                        ).inc()
                    raise RemoteWorkerDied(
                        f"worker pid={w.proc.pid} of job "
                        f"{name!r} exited rc="
                        f"{w.proc.returncode} (heartbeat sweep; "
                        "restart the job — DDL replay rebuilds it)")

    # ------------------------------------------------------------------
    # dead-letter queue (poison-pill quarantine surface)
    # ------------------------------------------------------------------
    def dlq_requeue(self, job: str, ids: Optional[Sequence[int]] = None
                    ) -> int:
        """Re-inject quarantined input rows of `job` back into its live
        remote worker sets (risectl `dlq --requeue`): decode each
        payload, re-apply it to the shadow, route it to its key-owning
        worker, and flip the entry to status='requeued'. Returns the row
        count. Call between ticks; the next barrier states the rows
        downstream exactly once."""
        from ..core.encoding import decode_row
        rset = None
        for name, r in self._remote_sets():
            if name == job:
                rset = r
                break
        if rset is None:
            # resolve the worker set BEFORE filtering entries: a requeue
            # against a job that cannot consume one must fail with the
            # reason, not report "requeued 0 rows"
            obj = self.catalog.objects.get(job)
            if obj is not None and isinstance(obj.runtime, dict) \
                    and obj.runtime.get("fused_job") is not None:
                raise ValueError(
                    f"cannot requeue into {job!r}: it is a FUSED device "
                    "job — its input regenerates deterministically on "
                    "device and there is no remote worker set to consume "
                    "a requeue. Quarantined rows of a fused job can only "
                    "be listed or purged (`risectl dlq " + job +
                    " --purge ...`); see README 'Dead-letter queue'.")
            if obj is None:
                raise ValueError(f"cannot requeue into {job!r}: no such "
                                 "job in the catalog")
            raise ValueError(
                f"cannot requeue into {job!r}: the job has no live "
                "remote worker set (local placement). Only process-"
                "placement jobs (SET streaming_placement TO process) "
                "have dead-letter consumers.")
        ents = self._dlq.entries(job=job, status="quarantined")
        if ids is not None:
            idset = {int(x) for x in ids}
            ents = [e for e in ents if int(e[0]) in idset]
        if not ents:
            return 0
        n = 0
        by_side: Dict[int, List[Tuple[int, Tuple]]] = {}
        for e in ents:
            side = int(e[3])
            row = decode_row(e[8], list(rset.in_dtypes[side]))
            by_side.setdefault(side, []).append((int(e[6]), tuple(row)))
        for side, pairs in by_side.items():
            n += rset.requeue_rows(side, pairs)
        self._dlq.mark([e[0] for e in ents], "requeued",
                       self.injector.epoch.curr)
        return n

    def dlq_purge(self, job: str, ids: Optional[Sequence[int]] = None
                  ) -> int:
        """Drop dead-letter entries of `job` outright (audit closed,
        data loss accepted)."""
        ents = self._dlq.entries(job=job)
        if ids is not None:
            idset = {int(x) for x in ids}
            ents = [e for e in ents if int(e[0]) in idset]
        return self._dlq.mark([e[0] for e in ents], None,
                              self.injector.epoch.curr)

    def metrics(self) -> str:
        """Prometheus text exposition (MonitorService analog)."""
        from ..utils.metrics import REGISTRY
        return REGISTRY.expose()

    def flush(self, ticks: int = 2) -> str:
        for _ in range(ticks):
            self.tick()
        return "FLUSH"

    # ------------------------------------------------------------------
    # batch SELECT
    # ------------------------------------------------------------------
    def _batch_subscribe(self, inj: BarrierInjector):
        def subscribe(name: str):
            from .system_catalog import SYSTEM_TABLES
            if name in SYSTEM_TABLES and name not in self.catalog.objects:
                schema, builder = SYSTEM_TABLES[name]
                rows = builder(self)
                chunks = ([StreamChunk.from_rows(
                    schema.dtypes, [(Op.INSERT, r) for r in rows])]
                    if rows else [])
                src = SourceExecutor(schema, ListReader(chunks), inj,
                                     name=f"SysScan({name})")
                return src, schema, list(range(len(schema)))
            obj = self.catalog.get(name)
            job = (obj.runtime or {}).get("fused_job")
            if job is not None:
                # sync + pull the CURRENT device MV, through the serving
                # cache (a fresh snapshot is a host-memory hit; misses
                # coalesce onto one device pull)
                rows = self._serve_mv_rows(name, job)
            elif obj.runtime.get("state_table") is None:
                raise ValueError(
                    f"source {name!r} is not directly queryable (sources "
                    "are unmaterialized streams — create a MATERIALIZED "
                    "VIEW over it)")
            else:
                rows = list(obj.runtime["state_table"].iter_all())
            chunks = []
            if rows:
                chunks.append(StreamChunk.from_rows(
                    obj.schema.dtypes, [(Op.INSERT, r) for r in rows]))
            src = SourceExecutor(obj.schema, ListReader(chunks), inj,
                                 name=f"Scan({name})")
            return src, obj.schema, obj.pk

        return subscribe

    def _run_batch_setop(self, q: A.SetOp) -> List[Tuple]:
        """One-shot UNION [ALL] over snapshots (stream-replay path)."""
        self.flush(1)
        inj = BarrierInjector()
        # plan without the trailing order/limit; applied host-side below
        plan_q = A.SetOp(q.op, q.all, q.left, q.right)
        execu, ns = self._make_planner(self._batch_subscribe(inj),
                                       inj=inj).plan_query(plan_q)
        n_vis = ns.n_visible or len(ns.cols)
        self.last_description = [(c.name, c.dtype)
                                 for c in ns.cols[:n_vis]]
        state: Dict[Tuple, int] = {}
        it = execu.execute()
        inj.inject()
        inj.inject_stop()
        for msg in it:
            if isinstance(msg, StreamChunk):
                for op, r in msg.compact().op_rows():
                    state[r] = state.get(r, 0) + (1 if op.is_insert else -1)
        out = [r for r, n in state.items() for _ in range(n)]
        if q.order_by:
            name_of = {c.name: i for i, c in
                       reversed(list(enumerate(ns.cols[:n_vis])))}
            for e, desc in reversed(q.order_by):
                if not isinstance(e, A.Col) or e.name not in name_of:
                    raise ValueError("ORDER BY after UNION must reference "
                                     "output columns")
                i = name_of[e.name]
                out.sort(key=lambda r: _sort_key(r[i]), reverse=desc)
        if q.offset:
            out = out[q.offset:]
        if q.limit is not None:
            out = out[: q.limit]
        return [r[:n_vis] for r in out]

    def _serve_mv_rows(self, name: str, job) -> List[Tuple]:
        """Fused-MV rows through the serving cache: a snapshot stamped
        at the job's current epoch counter is a host-memory hit;
        misses fill through `mv_rows_versioned` (torn-pull-safe) with
        concurrent readers coalesced onto the single device pull."""
        from ..config import ROBUSTNESS
        if not ROBUSTNESS.serving_cache:
            return job.mv_rows_now()
        # the version stamp (`job.counter`) is an EVENT count; the knob
        # is in fused epochs — convert so `rw_serving_staleness_epochs=2`
        # tolerates two dispatched epochs, whatever their event budget
        staleness = max(0, int(ROBUSTNESS.serving_staleness_epochs)) \
            * max(1, int(getattr(job.program, "epoch_events", 1) or 1))
        served_epoch, rows = self.read_cache.get(
            name, int(job.counter), staleness, job.mv_rows_versioned)
        # SERVED staleness: when the cache answered from an older epoch
        # (within the staleness bound), rw_mv_freshness must report the
        # lag the reader actually experienced, not the store's head
        self._freshness.note_served(name, int(served_epoch),
                                    int(job.counter),
                                    self.read_cache.fill_time(name))
        return rows

    def _serving_mvs(self, ref) -> Optional[List[str]]:
        """Names of the fused MVs a FROM tree reads, or None when any
        base relation is NOT a fused MV (host tables, sources, system
        tables, table functions: all ineligible for cache serving)."""
        if isinstance(ref, A.NamedTable):
            obj = self.catalog.objects.get(ref.name)
            rt = obj.runtime if obj is not None else None
            job = rt.get("fused_job") if isinstance(rt, dict) else None
            return [ref.name] if job is not None else None
        if isinstance(ref, A.Join):
            left = self._serving_mvs(ref.left)
            right = self._serving_mvs(ref.right)
            return left + right \
                if left is not None and right is not None else None
        if isinstance(ref, (A.WindowTable, A.TemporalTable)):
            return self._serving_mvs(ref.inner)
        if isinstance(ref, A.SubqueryTable):
            return self._serving_mvs(ref.query.from_) \
                if ref.query.from_ is not None else None
        return None

    def _serving_skip_flush(self, q, serving: bool) -> bool:
        """Whether a pgwire SELECT may skip the per-statement flush and
        serve from the read cache. Only the serving front door opts in
        (`serving=True`); embedded `Database.query` keeps the flush so
        its SELECT-advances-the-stream semantics are untouched. The
        SELECT must read only fused MVs, and at least one checkpoint
        must have committed (a cold engine still flushes once)."""
        from ..config import ROBUSTNESS
        if not serving or not ROBUSTNESS.serving_cache:
            return False
        if getattr(q, "from_", None) is None:
            return False
        return self.epoch_committed > 0 \
            and self._serving_mvs(q.from_) is not None

    def _run_batch_select(self, q, serving: bool = False) -> List[Tuple]:
        # SELECT without FROM: evaluate constant expressions
        if isinstance(q, A.SetOp):
            return self._run_batch_setop(q)
        if q.from_ is None:
            row = tuple(_eval_const(i.expr, None) for i in q.items)
            self.last_description = [
                (it.alias or "?column?", _const_dtype(v))
                for it, v in zip(q.items, row)]
            return [row]
        if not self._serving_skip_flush(q, serving):
            self.flush(1)
        inj = BarrierInjector()
        subscribe = self._batch_subscribe(inj)
        # plan without limit/order; ORDER BY columns ride along as hidden
        # trailing items (PG allows ordering by non-output expressions)
        items = list(q.items) + [A.SelectItem(e, f"__ord{i}")
                                 for i, (e, _) in enumerate(q.order_by)]
        plan_q = A.Select(items, q.from_, q.where, q.group_by, q.having,
                         [], None, None, q.distinct)
        execu, ns = self._make_planner(subscribe,
                                       inj=inj).plan_select(plan_q)
        # visible = user items (stars expanded) — minus hidden ORDER BY
        # helpers and planner-appended stream-key columns
        n_vis = (ns.n_visible or len(ns.cols)) - len(q.order_by)
        # row description for wire-protocol frontends (pgwire RowDescription)
        self.last_description = [(c.name, c.dtype)
                                 for c in ns.cols[:n_vis]]
        # preferred path: convert to batch executors (vectorized one-shot
        # pipeline, src/batch analog). Plans with no batch form yet replay
        # as a bounded stream (the pre-batch-engine behavior).
        from ..batch import SeqScan, translate_stream_plan

        def scan_of(src):
            return SeqScan(src.schema, [c.data_chunk()
                                        for c in src.reader.chunks],
                           name=src.name)

        batch = translate_stream_plan(execu, scan_of)
        if batch is not None:
            out = batch.rows()
        else:
            state: Dict[Tuple, int] = {}
            it = execu.execute()
            inj.inject()
            inj.inject_stop()
            for msg in it:
                if isinstance(msg, StreamChunk):
                    for op, r in msg.compact().op_rows():
                        if op.is_insert:
                            state[r] = state.get(r, 0) + 1
                        else:
                            state[r] = state.get(r, 0) - 1
            out = [r for r, n in state.items() for _ in range(n)]
        for i in range(len(q.order_by) - 1, -1, -1):
            desc = q.order_by[i][1]
            out.sort(key=lambda r: _sort_key(r[n_vis + i]), reverse=desc)
        if q.offset:
            out = out[q.offset:]
        if q.limit is not None:
            out = out[: q.limit]
        return [r[:n_vis] for r in out]


def _csv_rows(text: str, delim: str) -> List[List[Optional[str]]]:
    """RFC-4180 row splitter for COPY csv: quoted fields may hold the
    delimiter, newlines, and doubled quotes; an UNQUOTED empty field is
    NULL (None) while a quoted empty field is ''. A hand state machine
    because csv.reader both discards quoted-ness (collapsing '\"\"' and
    '' to the same value) and needs pre-split lines (tearing embedded
    newlines)."""
    rows: List[List[Optional[str]]] = []
    field: List[str] = []
    row: List[Optional[str]] = []
    quoted = False      # current field was opened with a quote
    in_q = False        # currently inside the quotes
    i, n = 0, len(text)

    def end_field():
        nonlocal quoted
        v = "".join(field)
        row.append(v if quoted or v != "" else None)
        field.clear()
        quoted = False

    while i < n:
        c = text[i]
        if in_q:
            if c == '"':
                if i + 1 < n and text[i + 1] == '"':
                    field.append('"')
                    i += 1
                else:
                    in_q = False
            else:
                field.append(c)
        elif c == '"' and not field:
            quoted = True
            in_q = True
        elif c == delim:
            end_field()
        elif c == "\n" or c == "\r":
            if c == "\r" and i + 1 < n and text[i + 1] == "\n":
                i += 1
            if field or quoted or row:
                end_field()
                rows.append(list(row))
                row.clear()
        else:
            field.append(c)
        i += 1
    if field or quoted or row:
        end_field()
        rows.append(list(row))
    return rows


def _source_names(q: A.Select) -> List[str]:
    """Every NamedTable under a Select's FROM tree (subqueries included)."""
    out: List[str] = []

    def walk_ref(r):
        if isinstance(r, A.NamedTable):
            out.append(r.name)
        elif isinstance(r, A.SubqueryTable):
            walk(r.query)
        elif isinstance(r, A.ChangelogTable):
            out.append(r.inner)
        elif isinstance(r, A.WindowTable):
            walk_ref(r.inner)
        elif isinstance(r, A.Join):
            walk_ref(r.left)
            walk_ref(r.right)

    def walk(s):
        if isinstance(s, A.SetOp):
            walk(s.left)
            walk(s.right)
        elif s.from_ is not None:
            walk_ref(s.from_)

    walk(q)
    return out


def _const_dtype(v) -> DataType:
    """Best-effort output type of a constant expression (pgwire needs a
    RowDescription even for SELECT-without-FROM)."""
    if isinstance(v, bool):
        return T.BOOLEAN
    if isinstance(v, int):
        return T.INT64
    if isinstance(v, float):
        return T.FLOAT64
    return T.VARCHAR


def _sort_key(v):
    return (v is None, v)


def _dtype(schema: Schema, col: str) -> DataType:
    return schema.fields[schema.index_of(col)].dtype


def _coerce(v, dtype: DataType):
    if v is None:
        return None
    return dtype.coerce(v) if hasattr(dtype, "coerce") else v


def _eval_const(e: A.ExprNode, dtype: Optional[DataType]):
    from .planner import eval_const
    return eval_const(e, dtype)


def _extract_delay(bound, time_idx: int) -> int:
    """WATERMARK FOR c AS c - INTERVAL '...' -> delay usecs."""
    from ..expr.expression import FunctionCall, InputRef, Literal
    if isinstance(bound, FunctionCall) and "subtract" in bound.name:
        a, b = bound.args
        if isinstance(b, Literal):
            iv = b.value
            return iv.total_usecs_approx() if hasattr(
                iv, "total_usecs_approx") else int(iv)
    if isinstance(bound, InputRef):
        return 0
    raise ValueError("WATERMARK expression must be `col - INTERVAL '...'`")
