"""SQL AST nodes (the `src/sqlparser/src/ast/` analog, minimal)."""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, List, Optional, Sequence, Tuple

# ---------------------------------------------------------------------------
# Expressions
# ---------------------------------------------------------------------------


class ExprNode:
    pass


@dataclass
class Lit(ExprNode):
    value: Any                 # python value; None = NULL
    type_hint: Optional[str] = None   # 'interval', etc.


@dataclass
class Col(ExprNode):
    name: str
    table: Optional[str] = None


@dataclass
class Star(ExprNode):
    table: Optional[str] = None


@dataclass
class BinOp(ExprNode):
    op: str                    # '+', '-', '=', 'and', ...
    left: ExprNode
    right: ExprNode


@dataclass
class UnaryOp(ExprNode):
    op: str                    # 'not', '-'
    operand: ExprNode


@dataclass
class FuncCall(ExprNode):
    name: str
    args: List[ExprNode]
    distinct: bool = False
    over: Optional["WindowSpec"] = None
    filter: Optional[ExprNode] = None   # FILTER (WHERE ...) on aggregates
    # WITHIN GROUP (ORDER BY e) — ordered-set aggregates
    # (approx_percentile); the direct args stay in `args`
    within_group: Optional[ExprNode] = None


@dataclass
class Param(ExprNode):
    """$n placeholder in a prepared statement (1-based). Replaced with a
    Lit at Bind time (`pg_extended.rs` bound-statement analog); binding
    one directly is an error."""
    index: int


def max_param(node: Any) -> int:
    """Highest $n index anywhere in a statement tree (0 = none)."""
    import dataclasses
    best = 0
    stack = [node]
    while stack:
        x = stack.pop()
        if isinstance(x, Param):
            best = max(best, x.index)
        elif dataclasses.is_dataclass(x) and not isinstance(x, type):
            stack.extend(getattr(x, f.name)
                         for f in dataclasses.fields(x))
        elif isinstance(x, (list, tuple)):
            stack.extend(x)
    return best


def bind_params(node: Any, lits: "List[Lit]") -> Any:
    """Deep-substitute every Param with its bound literal — the
    plan-once half of Parse/Bind: the statement tree parsed at Parse is
    reused for every Bind/Execute, no re-lex/re-parse."""
    import dataclasses

    def sub(x):
        if isinstance(x, Param):
            if x.index - 1 >= len(lits):
                raise ValueError(f"no value for placeholder ${x.index}")
            return lits[x.index - 1]
        if dataclasses.is_dataclass(x) and not isinstance(x, type):
            kw = {}
            for f in dataclasses.fields(x):
                v = getattr(x, f.name)
                nv = sub(v)
                if nv is not v:
                    kw[f.name] = nv
            return dataclasses.replace(x, **kw) if kw else x
        if isinstance(x, list):
            out = [sub(v) for v in x]
            return out if any(a is not b for a, b in zip(out, x)) else x
        if isinstance(x, tuple):
            out = tuple(sub(v) for v in x)
            return out if any(a is not b for a, b in zip(out, x)) else x
        return x

    return sub(node)


@dataclass
class ArrayLit(ExprNode):
    """ARRAY[e1, e2, ...] — consumed by UNNEST (no array columns yet)."""
    items: List[ExprNode]


@dataclass
class WindowSpec:
    partition_by: List[ExprNode]
    order_by: List[Tuple[ExprNode, bool]]   # (expr, desc)
    # (mode, start, end): mode 'rows'|'range'; bounds are
    # ('unbounded',) | ('current',) | ('preceding', expr) |
    # ('following', expr); None = no explicit frame (default)
    frame: Optional[Tuple] = None


@dataclass
class CaseExpr(ExprNode):
    operand: Optional[ExprNode]
    branches: List[Tuple[ExprNode, ExprNode]]
    else_expr: Optional[ExprNode]


@dataclass
class CastExpr(ExprNode):
    operand: ExprNode
    type_name: str


@dataclass
class ExtractExpr(ExprNode):
    field: str
    operand: ExprNode


@dataclass
class IsNullExpr(ExprNode):
    operand: ExprNode
    negated: bool


@dataclass
class InList(ExprNode):
    operand: ExprNode
    items: List[ExprNode]
    negated: bool


@dataclass
class Between(ExprNode):
    operand: ExprNode
    low: ExprNode
    high: ExprNode
    negated: bool


@dataclass
class SubqueryExpr(ExprNode):
    query: "Select"


@dataclass
class InSubquery(ExprNode):
    """operand [NOT] IN (SELECT ...) — plans as a semi/anti join."""
    operand: ExprNode
    query: "Select"
    negated: bool


@dataclass
class Index(ExprNode):
    """expr[i] — array subscript (regexp_match group access)."""
    operand: ExprNode
    index: int


# ---------------------------------------------------------------------------
# FROM clause
# ---------------------------------------------------------------------------


class TableRef:
    alias: Optional[str]


@dataclass
class NamedTable(TableRef):
    name: str
    alias: Optional[str] = None


@dataclass
class WindowTable(TableRef):
    """TUMBLE(t, time_col, size) / HOP(t, time_col, hop, size)."""
    kind: str                  # 'tumble' | 'hop'
    inner: TableRef
    time_col: str
    args: List[ExprNode]       # intervals
    alias: Optional[str] = None


@dataclass
class SubqueryTable(TableRef):
    query: "Select"
    alias: Optional[str] = None


@dataclass
class Join(TableRef):
    left: TableRef
    right: TableRef
    kind: str                  # 'inner' | 'left' | 'right' | 'full' | 'cross'
    on: Optional[ExprNode]
    alias: Optional[str] = None


@dataclass
class ChangelogTable(TableRef):
    """WITH name AS changelog FROM obj (`ast/query.rs` CteInner::ChangeLog):
    the upstream's retractable change stream as an append-only relation with
    a `changelog_op` column."""
    inner: str
    alias: Optional[str] = None


@dataclass
class TableFunctionTable(TableRef):
    """FROM-clause table function: generate_series(...) / unnest(ARRAY[...])
    (`src/expr/core/src/table_function/mod.rs:174`)."""
    name: str                  # 'generate_series' | 'unnest'
    args: List[ExprNode]
    alias: Optional[str] = None


@dataclass
class TemporalTable(TableRef):
    """t FOR SYSTEM_TIME AS OF PROCTIME() — the version side of a temporal
    join (`src/stream/src/executor/temporal_join.rs:44`)."""
    inner: "NamedTable"
    alias: Optional[str] = None


# ---------------------------------------------------------------------------
# Statements
# ---------------------------------------------------------------------------


@dataclass
class SelectItem:
    expr: ExprNode
    alias: Optional[str] = None


@dataclass
class Select:
    items: List[SelectItem]
    from_: Optional[TableRef]
    where: Optional[ExprNode] = None
    group_by: List[ExprNode] = field(default_factory=list)
    having: Optional[ExprNode] = None
    order_by: List[Tuple[ExprNode, bool]] = field(default_factory=list)
    limit: Optional[int] = None
    offset: Optional[int] = None
    distinct: bool = False


@dataclass
class SetOp:
    """UNION [ALL] (`ast/query.rs` SetExpr::SetOperation). `left`/`right`
    are Select or nested SetOp. ORDER BY/LIMIT written after the last
    branch belong to the whole set operation (hoisted by the parser)."""
    op: str                    # 'union'
    all: bool
    left: Any
    right: Any
    order_by: List[Tuple[ExprNode, bool]] = field(default_factory=list)
    limit: Optional[int] = None
    offset: Optional[int] = None


Query = Any                    # Select | SetOp


@dataclass
class ColumnDef:
    name: str
    type_name: str
    primary_key: bool = False
    watermark_delay: Optional[ExprNode] = None   # WATERMARK FOR c AS c - d


@dataclass
class CreateTable:
    name: str
    columns: List[ColumnDef]
    primary_key: List[str]
    with_options: dict
    append_only: bool = False
    is_source: bool = False
    watermark: Optional[Tuple[str, ExprNode]] = None


@dataclass
class CreateMaterializedView:
    name: str
    query: Select


@dataclass
class CreateFunction:
    """CREATE FUNCTION name(argtypes) RETURNS t LANGUAGE python AS $$..$$
    (the reference's embedded-Python UDF, `src/expr/impl/src/udf/python.rs`)."""
    name: str
    arg_types: List[str]
    return_type: str
    language: str
    body: str
    or_replace: bool = False


@dataclass
class CreateSink:
    name: str
    from_name: Optional[str]
    query: Optional[Select]
    with_options: dict


@dataclass
class CreateIndex:
    name: str
    table: str
    columns: List[str]


@dataclass
class DropObject:
    kind: str                  # 'table' | 'source' | 'materialized view' ...
    name: str
    if_exists: bool = False
    cascade: bool = False


@dataclass
class Insert:
    table: str
    columns: List[str]
    rows: List[List[ExprNode]]
    query: Optional[Select] = None


@dataclass
class Delete:
    table: str
    where: Optional[ExprNode]


@dataclass
class Update:
    table: str
    assignments: List[Tuple[str, ExprNode]]
    where: Optional[ExprNode]


@dataclass
class Flush:
    pass


@dataclass
class ShowObjects:
    kind: str


@dataclass
class Explain:
    stmt: Any


@dataclass
class ExplainAnalyze:
    """EXPLAIN ANALYZE <mv>: the live per-operator tree of a RUNNING
    streaming job (eps, amplification, occupancy, phase shares, skew) —
    unlike EXPLAIN, which renders the plan a statement WOULD run."""
    target: str


@dataclass
class AlterParallelism:
    """ALTER MATERIALIZED VIEW <name> SET PARALLELISM <n>."""
    name: str
    parallelism: int


@dataclass
class SetVar:
    """SET <name> = <value> (session) / ALTER SYSTEM SET (cluster)."""
    name: str
    value: Any
    system: bool = False


@dataclass
class ShowVar:
    """SHOW <name> | SHOW ALL | SHOW PARAMETERS."""
    name: Optional[str]   # None = ALL
