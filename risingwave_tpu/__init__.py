"""risingwave_tpu — a TPU-native streaming SQL engine.

A ground-up re-design of RisingWave's capabilities (reference at
/root/reference, see /root/repo/SURVEY.md) for JAX/XLA on TPU: Postgres-dialect
SQL in, incrementally-maintained materialized views out, with Chandy-Lamport
barrier checkpointing, vnode-hash data parallelism over a device mesh, and
epoch-versioned durable operator state.

Layer map (mirrors SURVEY.md §1, re-hosted):
  core/        L0 columnar kernel: chunks, types, vnode hash, epochs, encodings
  expr/        L4 vectorized expression & aggregate function layer
  ops/         L5 stream executors (generator protocol over Message streams)
  state/       L2/L3 state tables + storage backends + checkpoints
  device/      Pallas/XLA per-epoch kernels and HBM-resident operator state
  parallel/    vnode→mesh sharding, shard_map steps, exchange collectives
  runtime/     actors, barrier manager, dataflow assembly, recovery
  sql/         L9 parser/binder/planner (Postgres dialect subset)
  connectors/  L6 sources (nexmark, datagen) and sinks
  meta/        L8 control plane: catalog, DDL, checkpoint coordination
"""

__version__ = "0.1.0"


def _configure_jax() -> None:
    """SQL BIGINT/TIMESTAMP require 64-bit device integers; enable x64 before
    any array is created. Hot kernels still downcast to int32/bf16 where the
    value range allows (see risingwave_tpu/device/)."""
    try:
        import jax
        jax.config.update("jax_enable_x64", True)
    except ImportError:  # pragma: no cover - jax is a hard dep in practice
        pass


_configure_jax()
