"""Stream-plan -> batch-plan translation.

The SQL frontend plans once (binder + stream lowering in `sql/planner.py`
— the reference's logical plan); a batch query then converts that tree
to batch executors (`to_batch`, the reference's
`optimizer/plan_node/logical_*.rs` batch lowering). Stateless operators
(project/filter/hop-window/expand/row-id) are engine-agnostic and run
as-is over the batch stream; stateful ones (agg, join, top-n, dedup) map
to their batch twins. Returns None when a node has no batch form yet —
the caller falls back to replaying the plan as a bounded stream.
"""
from __future__ import annotations

from typing import Any, Callable, Optional

from .executor import (BatchExecutor, BatchHashAgg, BatchHashJoin,
                       BatchSimpleAgg, BatchUnion, SeqScan, StatelessWrap)


def translate_stream_plan(e: Any,
                          scan_of: Callable[[Any], Optional[BatchExecutor]]
                          ) -> Optional[BatchExecutor]:
    """Map a planned stream executor tree to batch executors.

    `scan_of(source_executor)` supplies the snapshot SeqScan for a leaf
    (the caller knows where the pinned chunks live)."""
    from ..ops.agg import (HashAggExecutor, SimpleAggExecutor,
                           StatelessSimpleAggExecutor)
    from ..ops.device_agg import DeviceHashAggExecutor
    from ..ops.device_join import DeviceHashJoinExecutor
    from ..ops.join import HashJoinExecutor, JoinType
    from ..ops.simple import (ExpandExecutor, FilterExecutor,
                              ProjectExecutor, RowIdGenExecutor,
                              UnionExecutor, ValuesExecutor)
    from ..ops.source import SourceExecutor
    from ..ops.topn import AppendOnlyDedupExecutor, TopNExecutor
    from ..ops.window import HopWindowExecutor

    def rec(node: Any) -> Optional[BatchExecutor]:
        if isinstance(node, SourceExecutor):
            return scan_of(node)
        if isinstance(node, RowIdGenExecutor):
            # snapshot rows already carry their ids; the generator only
            # matters for live DML — but batch scans feed fresh chunks
            # through it so NULL ids (none in snapshots) would stay NULL
            inner = rec(node.input)
            return None if inner is None else StatelessWrap(inner, node)
        if isinstance(node, (ProjectExecutor, FilterExecutor,
                             HopWindowExecutor, ExpandExecutor,
                             AppendOnlyDedupExecutor)):
            # Dedup is stateful across barriers but a freshly planned
            # instance over a finite batch behaves identically
            inner = rec(node.input)
            return None if inner is None else StatelessWrap(inner, node)
        if isinstance(node, (HashAggExecutor, DeviceHashAggExecutor)):
            inner = rec(node.input)
            if inner is None:
                return None
            return BatchHashAgg(inner, node.group_key_indices, node.calls)
        if isinstance(node, SimpleAggExecutor):
            inner = rec(node.input)
            return None if inner is None else BatchSimpleAgg(inner,
                                                             node.calls)
        if isinstance(node, StatelessSimpleAggExecutor):
            inner = rec(node.input)
            return None if inner is None else BatchSimpleAgg(inner,
                                                             node.calls)
        if isinstance(node, HashJoinExecutor):
            left = rec(node.left_exec)
            right = rec(node.right_exec)
            if left is None or right is None:
                return None
            return BatchHashJoin(left, right,
                                 node.sides["l"].key_indices,
                                 node.sides["r"].key_indices,
                                 node.join_type, node.condition)
        if isinstance(node, DeviceHashJoinExecutor):
            left = rec(node.left_exec)
            right = rec(node.right_exec)
            if left is None or right is None:
                return None
            return BatchHashJoin(left, right, node.key_idx["a"],
                                 node.key_idx["b"], JoinType.INNER,
                                 node.condition)
        if isinstance(node, UnionExecutor):
            subs = [rec(i) for i in node.inputs]
            if any(s is None for s in subs):
                return None
            return BatchUnion(subs)
        # TopN / group-TopN / dedup / over-window / EOWC and anything
        # unknown: no batch form here yet
        return None

    return rec(e)
