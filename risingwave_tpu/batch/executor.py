"""Batch executors: pull-based chunk pipelines over a pinned snapshot.

Re-design of the reference's batch executor framework
(`src/batch/src/executor/mod.rs:47` `Executor` trait — schema + a chunk
stream). Where the stream engine maintains state across barriers, a batch
executor runs a finite chunk stream to completion; operators are
vectorized over `DataChunk`s (expressions evaluate columnar via
`expr/expression.py`) and aggregation reuses the exact `AggState`
machinery so batch and stream results agree bit-for-bit.

Snapshot pinning: the scan's chunks are materialized from the committed
state at plan time (the runtime flushes the in-flight barrier first), the
`batch_table/mod.rs:892` snapshot-read analog for a single-process store.
"""
from __future__ import annotations

from collections import defaultdict
from typing import Any, Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from ..core.chunk import Column, DataChunk
from ..core.schema import Field, Schema
from ..expr.agg import AggCall, create_agg_state


class BatchExecutor:
    """Base: `execute()` yields DataChunks; finite."""

    def __init__(self, schema: Schema, name: str = ""):
        self.schema = schema
        self.name = name or type(self).__name__

    def execute(self) -> Iterator[DataChunk]:
        raise NotImplementedError

    def rows(self) -> List[Tuple]:
        out: List[Tuple] = []
        for ch in self.execute():
            out.extend(ch.rows())
        return out


class SeqScan(BatchExecutor):
    """Scan a materialized snapshot (`row_seq_scan.rs` analog)."""

    def __init__(self, schema: Schema, chunks: Sequence[DataChunk],
                 name: str = "SeqScan"):
        super().__init__(schema, name)
        self.chunks = list(chunks)

    def execute(self) -> Iterator[DataChunk]:
        yield from self.chunks


class StatelessWrap(BatchExecutor):
    """Run a STATELESS stream operator's vectorized `on_chunk` over the
    batch stream (project/filter/hop-window/expand are identical in both
    engines — the reference shares `expr/` the same way)."""

    def __init__(self, input: BatchExecutor, op: Any):
        super().__init__(op.schema, f"Batch({op.name})")
        self.input = input
        self.op = op

    def execute(self) -> Iterator[DataChunk]:
        from ..core.chunk import StreamChunk
        for ch in self.input.execute():
            ch = ch.compact()
            sc = StreamChunk(np.zeros(ch.capacity, dtype=np.int8),
                             ch.columns)
            for out in self.op.on_chunk(sc):
                if isinstance(out, StreamChunk):
                    yield out.data_chunk()


class BatchHashAgg(BatchExecutor):
    """Vectorized grouping + exact AggState accumulation
    (`hash_agg.rs` analog)."""

    def __init__(self, input: BatchExecutor,
                 group_key_indices: Sequence[int],
                 calls: Sequence[AggCall]):
        fields = [input.schema.fields[i] for i in group_key_indices]
        fields += [Field(f"agg#{i}", c.return_type)
                   for i, c in enumerate(calls)]
        super().__init__(Schema(fields), "BatchHashAgg")
        self.input = input
        self.group_key_indices = list(group_key_indices)
        self.calls = list(calls)

    def execute(self) -> Iterator[DataChunk]:
        from ..expr.agg import DistinctDedup
        groups: Dict[Tuple, Tuple[List[Any], List[Any]]] = {}
        for ch in self.input.execute():
            ch = ch.compact()
            if ch.capacity == 0:
                continue
            keys = list(zip(*(ch.columns[i].to_list()
                              for i in self.group_key_indices))) \
                if self.group_key_indices else [()] * ch.capacity
            # evaluate each call's argument column once per chunk
            arg_cols = [c.arg.eval(ch) if c.arg is not None else None
                        for c in self.calls]
            filt_cols = [c.filter.eval(ch) if c.filter is not None else None
                         for c in self.calls]
            for i, k in enumerate(keys):
                g = groups.get(k)
                if g is None:
                    g = groups[k] = (
                        [create_agg_state(c) for c in self.calls],
                        [DistinctDedup() if c.distinct else None
                         for c in self.calls])
                st, dedups = g
                for ci, (call, ac) in enumerate(zip(self.calls, arg_cols)):
                    fc = filt_cols[ci]
                    if fc is not None and not (fc.validity[i]
                                               and fc.values[i]):
                        continue
                    if ac is None:                 # count(*)
                        st[ci].apply(1, 1)
                        continue
                    v = ac.get(i)
                    if v is None:                  # NULLs don't aggregate
                        continue
                    d = dedups[ci]
                    if d is not None and d.apply(1, v) == 0:
                        continue                   # duplicate DISTINCT value
                    st[ci].apply(1, v)
        rows = [k + tuple(st.output() for st in sts)
                for k, (sts, _d) in groups.items()]
        if rows:
            yield DataChunk.from_rows(self.schema.dtypes, rows)


class BatchSimpleAgg(BatchHashAgg):
    """Global aggregation: exactly one output row, even on empty input
    (`sort_agg.rs`/simple agg semantics)."""

    def __init__(self, input: BatchExecutor, calls: Sequence[AggCall]):
        super().__init__(input, [], calls)
        self.name = "BatchSimpleAgg"

    def execute(self) -> Iterator[DataChunk]:
        got = list(super().execute())
        if got:
            yield from got
        else:
            sts = [create_agg_state(c) for c in self.calls]
            yield DataChunk.from_rows(
                self.schema.dtypes, [tuple(s.output() for s in sts)])


class BatchHashJoin(BatchExecutor):
    """Build-probe equi join with optional residual condition
    (`hash_join.rs` analog; build = right side)."""

    def __init__(self, left: BatchExecutor, right: BatchExecutor,
                 left_keys: Sequence[int], right_keys: Sequence[int],
                 join_type: str = "inner", condition: Any = None,
                 chunk_size: int = 4096):
        from ..ops.join import JoinType
        jt = join_type.value if isinstance(join_type, JoinType) else join_type
        if jt in ("left_semi", "left_anti"):
            schema = left.schema
        else:
            schema = left.schema.concat(right.schema)
        super().__init__(schema, f"BatchHashJoin[{jt}]")
        self.left, self.right = left, right
        self.lk, self.rk = list(left_keys), list(right_keys)
        self.join_type = jt
        self.condition = condition
        self.chunk_size = chunk_size

    def _passes(self, rows: List[Tuple]) -> List[bool]:
        if self.condition is None or not rows:
            return [True] * len(rows)
        probe_schema = self.left.schema.concat(self.right.schema)
        ch = DataChunk.from_rows(probe_schema.dtypes, rows)
        c = self.condition.eval(ch)
        return [bool(ok) and bool(v)
                for v, ok in zip(c.values, c.validity)]

    def execute(self) -> Iterator[DataChunk]:
        build: Dict[Tuple, List[Tuple]] = defaultdict(list)
        for ch in self.right.execute():
            for row in ch.rows():
                k = tuple(row[i] for i in self.rk)
                if any(v is None for v in k):
                    continue
                build[k].append(row)
        matched_right: set = set()
        out: List[Tuple] = []
        jt = self.join_type

        def flush():
            nonlocal out
            if out:
                yield DataChunk.from_rows(self.schema.dtypes, out)
                out = []

        nr = len(self.right.schema)
        for ch in self.left.execute():
            for lrow in ch.rows():
                k = tuple(lrow[i] for i in self.lk)
                cands = build.get(k, []) if not any(v is None for v in k) \
                    else []
                pairs = [lrow + r for r in cands]
                ok = self._passes(pairs)
                hits = [r for r, o in zip(cands, ok) if o]
                if jt == "left_semi":
                    if hits:
                        out.append(lrow)
                elif jt == "left_anti":
                    if not hits:
                        out.append(lrow)
                else:
                    for r in hits:
                        out.append(lrow + r)
                        if jt in ("right_outer", "full_outer"):
                            matched_right.add(id(r))
                    if not hits and jt in ("left_outer", "full_outer"):
                        out.append(lrow + (None,) * nr)
                if len(out) >= self.chunk_size:
                    yield from flush()
        if jt in ("right_outer", "full_outer"):
            nl = len(self.left.schema)
            for rows_ in build.values():
                for r in rows_:
                    if id(r) not in matched_right:
                        out.append((None,) * nl + r)
        yield from flush()


class BatchUnion(BatchExecutor):
    def __init__(self, inputs: Sequence[BatchExecutor]):
        super().__init__(inputs[0].schema, "BatchUnion")
        self.inputs = list(inputs)

    def execute(self) -> Iterator[DataChunk]:
        for i in self.inputs:
            yield from i.execute()
