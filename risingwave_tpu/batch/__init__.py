"""Batch query engine (reference: `src/batch/`)."""
from .executor import (BatchExecutor, BatchHashAgg, BatchHashJoin,
                       BatchSimpleAgg, BatchUnion, SeqScan, StatelessWrap)
from .from_stream import translate_stream_plan

__all__ = [
    "BatchExecutor", "BatchHashAgg", "BatchHashJoin", "BatchSimpleAgg",
    "BatchUnion", "SeqScan", "StatelessWrap", "translate_stream_plan",
]
