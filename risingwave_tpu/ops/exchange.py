"""Exchange layer: dispatch, merge, permit channels.

Reference: `src/stream/src/executor/dispatch.rs` (HashDataDispatcher `:777`,
vis-bitmap building + U-pair fixing `:843-930`; Broadcast/Simple/RoundRobin
`:509,690,969`), `merge.rs:235` (barrier-aligned merge), and
`exchange/permit.rs:35` (credit-based backpressure channel).

In the TPU runtime the device-side exchange is one all-to-all inside the
jitted epoch step (`parallel/sharded_agg.py`); these HOST executors exist
for multi-fragment host pipelines (different operators at different
parallelism) and for the multi-host DCN path, where chunks move between
processes — the same two-tier split the reference has between in-process
channels and gRPC streams.
"""
from __future__ import annotations

from collections import deque
from typing import Deque, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from ..core.chunk import Op, StreamChunk
from ..core.schema import Schema
from ..core.vnode import VNODE_COUNT, compute_vnodes
from .executor import Executor
from .message import Barrier, Message, Watermark


class Channel:
    """Bounded in-process channel with permit accounting
    (`exchange/permit.rs:35`): data messages consume permits, barriers are
    exempt (they must never be blocked by backpressure)."""

    def __init__(self, capacity: int = 64):
        self.capacity = capacity
        self.buf: Deque[Message] = deque()
        self.closed = False          # producer done: recv drains then ends

    def try_send(self, msg: Message) -> bool:
        if isinstance(msg, StreamChunk) and self._data_len() >= self.capacity:
            return False
        self.buf.append(msg)
        return True

    def send(self, msg: Message) -> None:
        # single-threaded runtime: the consumer drains between sends, so a
        # full channel here means a missing consumer — surface it
        if not self.try_send(msg):
            raise RuntimeError("channel full: downstream not consuming "
                               "(permit backpressure would block here)")

    def close(self) -> None:
        self.closed = True

    def _data_len(self) -> int:
        return sum(1 for m in self.buf if isinstance(m, StreamChunk))

    def recv(self) -> Optional[Message]:
        return self.buf.popleft() if self.buf else None

    def __len__(self) -> int:
        return len(self.buf)


class ThreadedChannel(Channel):
    """Channel with real blocking semantics for producer/consumer threads
    or background socket drains: send blocks on capacity, recv stays
    non-blocking (MergeExecutor polls), and a shared condition lets a
    consumer sleep until ANY of its inputs has data (`wait`)."""

    def __init__(self, capacity: int = 64, cond=None):
        import threading
        super().__init__(capacity)
        self.cv = cond or threading.Condition()

    def try_send(self, msg: Message) -> bool:
        with self.cv:
            if not super().try_send(msg):
                return False
            self.cv.notify_all()
            return True

    def send(self, msg: Message) -> None:
        import time
        with self.cv:
            t0 = None
            while isinstance(msg, StreamChunk) \
                    and self._data_len() >= self.capacity and not self.closed:
                if t0 is None:
                    t0 = time.monotonic()
                self.cv.wait(1.0)
            if t0 is not None:
                # a result drain stalled on a full merge channel — the
                # coordinator is the slow party; feed the overload ladder
                from ..utils.overload import PRESSURE
                PRESSURE.note("result_channel", time.monotonic() - t0)
            if self.closed and isinstance(msg, StreamChunk):
                return               # consumer gone; chunks are droppable
            self.buf.append(msg)
            self.cv.notify_all()

    def close(self) -> None:
        with self.cv:
            self.closed = True
            self.cv.notify_all()

    def send_batch(self, msgs: Sequence[Message]) -> None:
        """Append a pre-assembled batch atomically, WITHOUT capacity
        waits: the producer already holds the whole batch in memory, so
        blocking it here gains nothing and can deadlock a producer the
        consumer thread must later join (the supervised drain's
        epoch-atomic release)."""
        with self.cv:
            self.buf.extend(msgs)
            self.cv.notify_all()

    def recv(self) -> Optional[Message]:
        with self.cv:
            msg = self.buf.popleft() if self.buf else None
            if msg is not None:
                self.cv.notify_all()    # wake a send() blocked on capacity
            return msg

    def wait(self, timeout: float = 0.05) -> None:
        with self.cv:
            if not self.buf and not self.closed:
                self.cv.wait(timeout)


class DispatchExecutor:
    """Output side of an exchange: consumes one upstream, feeds N channels.

    Not an `Executor` (it terminates a fragment); `pump_until_barrier`
    drives it. Dispatch kinds: hash (vnode), broadcast, simple, round-robin
    (`DispatcherImpl`, dispatch.rs:509).
    """

    def __init__(self, input: Executor, outputs: Sequence[Channel],
                 kind: str = "hash", key_indices: Sequence[int] = (),
                 vnode_count: int = VNODE_COUNT):
        assert kind in ("hash", "broadcast", "simple", "round_robin")
        if kind == "simple":
            assert len(outputs) == 1
        self.input = input
        self.outputs = list(outputs)
        self.kind = kind
        self.key_indices = list(key_indices)
        self.vnode_count = vnode_count
        n = len(outputs)
        # contiguous vnode blocks — THE map (parallel/mesh.py), not an
        # inlined copy: host exchange and device shard planes must agree
        # on block boundaries even when n doesn't divide vnode_count
        from ..parallel.mesh import shard_of_vnode
        self.vnode_to_out = shard_of_vnode(
            np.arange(vnode_count, dtype=np.int64), n,
            vnode_count).astype(np.int32)
        self._rr = 0
        self._iter: Optional[Iterator[Message]] = None
        # last barrier fanned out + an optional observer: the
        # FragmentSupervisor logs dispatched barriers so a respawned
        # worker can be fed every barrier its predecessor never delivered
        self.last_barrier: Optional[Barrier] = None
        self.on_barrier = None

    def _dispatch_chunk(self, chunk: StreamChunk) -> None:
        if self.kind == "broadcast":
            for ch in self.outputs:
                ch.send(chunk)
            return
        if self.kind == "simple":
            self.outputs[0].send(chunk)
            return
        if self.kind == "round_robin":
            self.outputs[self._rr].send(chunk)
            self._rr = (self._rr + 1) % len(self.outputs)
            return
        # hash: vnode per row -> per-output visibility bitmaps
        # (dispatch.rs:843-930)
        chunk = chunk.compact()
        n = chunk.capacity
        if n == 0:
            return
        vnodes = compute_vnodes([chunk.columns[i] for i in self.key_indices],
                                vnode_count=self.vnode_count)
        out_of_row = self.vnode_to_out[vnodes]
        ops = chunk.ops
        # U-pair fixing: when the two halves of an update pair land on
        # different outputs, degrade them to Delete + Insert so each side
        # sees a self-consistent chunk (dispatch.rs:891-909). Vectorized:
        # hits are (U-, U+) adjacencies split across outputs — they cannot
        # overlap (a row can't be both U- and U+), so a bulk write is safe.
        # Append-only streams skip this entirely.
        if (ops >= Op.UPDATE_DELETE).any():
            ops = ops.copy()
            split = np.flatnonzero(
                (ops[:-1] == Op.UPDATE_DELETE)
                & (ops[1:] == Op.UPDATE_INSERT)
                & (out_of_row[:-1] != out_of_row[1:]))
            ops[split] = Op.DELETE
            ops[split + 1] = Op.INSERT
        for oi, ch in enumerate(self.outputs):
            vis = out_of_row == oi
            if not vis.any():
                continue
            ch.send(StreamChunk(ops, chunk.columns, vis))

    def pump_until_barrier(self) -> Optional[Barrier]:
        """Forward messages until a barrier; the barrier goes to EVERY
        output (Chandy-Lamport marker fan-out). Exhaustion closes the
        outputs so consumers (local fragments or remote workers) see EOS."""
        if self._iter is None:
            self._iter = self.input.execute()
        for msg in self._iter:
            if isinstance(msg, Barrier):
                self.last_barrier = msg
                if self.on_barrier is not None:
                    self.on_barrier(msg)
                for ch in self.outputs:
                    ch.send(msg)
                return msg
            if isinstance(msg, StreamChunk):
                if msg.cardinality:
                    self._dispatch_chunk(msg)
            elif isinstance(msg, Watermark):
                for ch in self.outputs:
                    ch.send(msg)
        for ch in self.outputs:
            close = getattr(ch, "close", None)
            if close:
                close()
        return None


class ChannelSource(Executor):
    """Fragment input boundary: reads one exchange channel; when empty,
    drives the upstream dispatcher (`exchange/input.rs` LocalInput — the
    pull side of a permit channel)."""

    def __init__(self, chan: Channel, schema: Schema,
                 pump: "DispatchExecutor"):
        super().__init__(schema, "ChannelSource")
        self.chan = chan
        self.pump = pump
        self.append_only = pump.input.append_only

    def execute(self) -> Iterator[Message]:
        while True:
            msg = self.chan.recv()
            if msg is None:
                if self.pump.pump_until_barrier() is None:
                    return
                continue
            yield msg
            if isinstance(msg, Barrier) and msg.is_stop():
                return


class FragmentPump:
    """Drives one executor chain into an exchange channel until its next
    barrier — the per-fragment actor loop (`actor.rs:157`) flattened into
    the cooperative single-thread runtime. Duck-typed like
    DispatchExecutor for MergeExecutor's pump list."""

    def __init__(self, execu: Executor, out: Channel):
        self.execu = execu
        self.out = out
        self._iter: Optional[Iterator[Message]] = None

    def pump_until_barrier(self) -> Optional[Barrier]:
        if self._iter is None:
            self._iter = self.execu.execute()
        for msg in self._iter:
            self.out.send(msg)
            if isinstance(msg, Barrier):
                return msg
        self.out.close()
        return None


class MergeExecutor(Executor):
    """Input side: merge N upstream channels with barrier alignment
    (`merge.rs:235,403-480`): chunks flow through freely; when one upstream
    yields a barrier, that input is blocked (its messages buffered) until
    every other input yields the same barrier, then ONE barrier is emitted.

    Watermarks: per-upstream watermark tracked, min across inputs emitted
    (`executor/watermark/`-style min alignment)."""

    def __init__(self, inputs: Sequence[Channel], schema: Schema,
                 pumps: Sequence[DispatchExecutor] = ()):
        super().__init__(schema, "Merge")
        self.inputs = list(inputs)
        self.pumps = list(pumps)   # upstream dispatchers to drive on demand
        self._wm: List[Optional[int]] = [None] * len(inputs)
        self._wm_emitted: Optional[int] = None
        # hook polled while idle-waiting: remote deployments raise here
        # when a worker died, instead of spinning on a barrier that will
        # never align (the failure-detection seam)
        self.health_check = lambda: None

    def execute(self) -> Iterator[Message]:
        n = len(self.inputs)
        pending_barrier: List[Optional[Barrier]] = [None] * n
        # epoch of a pumped-but-not-yet-aligned barrier: while set, the
        # pumps are NOT driven again, so at most ONE barrier is ever in
        # flight beyond the last alignment. Without this, a self-ticking
        # source injects a barrier per pump while async workers are
        # still responding — unbounded queues on a loaded host, and the
        # supervisor's single-barrier re-injection / two-epoch
        # retransmit retention would miss skipped epochs (barrier skew).
        awaiting: Optional[int] = None
        while True:
            progressed = False
            for i, ch in enumerate(self.inputs):
                if pending_barrier[i] is not None:
                    continue   # blocked until alignment completes
                msg = ch.recv()
                if msg is None:
                    continue
                progressed = True
                if isinstance(msg, Barrier):
                    pending_barrier[i] = msg
                elif isinstance(msg, Watermark):
                    self._wm[i] = msg.value
                    if all(w is not None for w in self._wm):
                        low = min(self._wm)
                        if self._wm_emitted is None or low > self._wm_emitted:
                            self._wm_emitted = low
                            yield Watermark(msg.col_idx, msg.dtype, low)
                else:
                    yield msg
            if all(b is not None for b in pending_barrier):
                b = pending_barrier[0]
                assert all(x.epoch.curr == b.epoch.curr
                           for x in pending_barrier[1:]), \
                    ("barrier skew",
                     [x.epoch.curr for x in pending_barrier])
                awaiting = None
                yield b.with_trace(self.name)
                if b.is_stop():
                    return
                pending_barrier = [None] * n
                continue
            if not progressed:
                self.health_check()
                # An in-flight barrier (`awaiting` pumped, or some input
                # delivered it already): EVERY input received it via the
                # pump fan-out, so stragglers need no further input —
                # wait for one instead of pumping (its send() notifies,
                # so the wait cuts short on arrival). Plain in-process
                # channels can't be waited on; for them pumping IS how
                # stragglers progress, so fall through to the pumps.
                if awaiting is not None \
                        or any(b is not None for b in pending_barrier):
                    straggler = next(
                        (ch for i, ch in enumerate(self.inputs)
                         if pending_barrier[i] is None
                         and hasattr(ch, "wait") and not ch.closed
                         and len(ch) == 0), None)
                    if straggler is not None:
                        straggler.wait(0.005)
                        continue
                # all unblocked channels empty: drive the upstream pumps
                done = True
                for p in self.pumps:
                    b = p.pump_until_barrier()
                    if b is not None:
                        done = False
                        if awaiting is None or b.epoch.curr > awaiting:
                            awaiting = b.epoch.curr
                if not done:
                    continue
                # pumps exhausted. Inputs backed by threads/processes may
                # still be computing: drain until every channel is closed.
                if all(ch.closed and len(ch) == 0 for ch in self.inputs):
                    return
                waiter = next((ch for ch in self.inputs
                               if hasattr(ch, "wait")
                               and not (ch.closed and len(ch) == 0)), None)
                if waiter is None:
                    return     # plain channels: nothing will ever arrive
                waiter.wait(0.05)
