"""Stateless executors: Project, Filter, Union, Values, RowIdGen, Expand.

Reference: `src/stream/src/executor/{project.rs,filter.rs,union.rs,values.rs,
row_id_gen.rs,expand.rs}`. These are the vmap-analog layer: per-chunk
vectorized transforms with no cross-chunk state.
"""
from __future__ import annotations

from typing import Any, Dict, Iterator, List, Optional, Sequence

import numpy as np

from ..core.chunk import Column, Op, StreamChunk
from ..core.schema import Field, Schema
from ..core import dtypes as T
from ..expr.expression import Expr, InputRef
from .executor import Executor, UnaryExecutor
from .message import Barrier, Message, Watermark


class ProjectExecutor(UnaryExecutor):
    """Evaluate expressions over each chunk (`project.rs`)."""

    def __init__(self, input: Executor, exprs: Sequence[Expr],
                 names: Optional[Sequence[str]] = None):
        names = names or [f"expr#{i}" for i in range(len(exprs))]
        schema = Schema([Field(n, e.return_type) for n, e in zip(names, exprs)])
        super().__init__(input, schema)
        self.append_only = input.append_only
        self.exprs = list(exprs)

    def on_chunk(self, chunk: StreamChunk) -> Iterator[Message]:
        chunk = chunk.compact()
        data = chunk.data_chunk()
        cols = [e.eval(data) for e in self.exprs]
        yield StreamChunk(chunk.ops, cols)

    def on_watermark(self, wm: Watermark) -> Iterator[Message]:
        # pass through only if some output expr is a direct ref of the col
        for out_idx, e in enumerate(self.exprs):
            if isinstance(e, InputRef) and e.index == wm.col_idx:
                yield Watermark(out_idx, wm.dtype, wm.value)
                return


class FilterExecutor(UnaryExecutor):
    """Predicate filter with U-/U+ pair fixing (`filter.rs`): when a predicate
    flips across an update pair, the pair degrades to a single DELETE or
    INSERT so downstream state stays consistent."""

    def __init__(self, input: Executor, predicate: Expr):
        super().__init__(input, input.schema)
        self.append_only = input.append_only
        self.predicate = predicate

    def on_chunk(self, chunk: StreamChunk) -> Iterator[Message]:
        chunk = chunk.compact()
        pred = self.predicate.eval(chunk.data_chunk())
        passes = pred.values.astype(np.bool_) & pred.validity
        ops = chunk.ops.copy()
        vis = passes.copy()
        i = 0
        n = chunk.capacity
        while i < n:
            if ops[i] == Op.UPDATE_DELETE and i + 1 < n and ops[i + 1] == Op.UPDATE_INSERT:
                old_p, new_p = passes[i], passes[i + 1]
                if old_p and not new_p:
                    ops[i] = Op.DELETE
                    vis[i], vis[i + 1] = True, False
                elif not old_p and new_p:
                    ops[i + 1] = Op.INSERT
                    vis[i], vis[i + 1] = False, True
                i += 2
            else:
                i += 1
        if vis.any():
            yield StreamChunk(ops, chunk.columns, vis)


class UnionExecutor(Executor):
    """Merge N inputs with barrier alignment (`union.rs` + the alignment that
    `MergeExecutor` (merge.rs:235) performs): chunks interleave freely between
    barriers; a barrier is forwarded only once ALL inputs yielded it."""

    def __init__(self, inputs: Sequence[Executor]):
        super().__init__(inputs[0].schema, "Union")
        self.append_only = all(i.append_only for i in inputs)
        self.inputs = list(inputs)
        # per-column min-tracking across inputs (`union.rs`
        # BufferedWatermarks): the union's watermark for a column is the
        # MIN of every live input's latest watermark; it is emitted only
        # once all live inputs have reported and only when it advances
        self._in_wms: List[Dict[int, Any]] = [{} for _ in inputs]
        self._out_wms: Dict[int, Any] = {}
        self._wm_dtypes: Dict[int, Any] = {}

    def _check_col(self, col: int, dtype,
                   alive: Sequence[bool]) -> Iterator[Message]:
        reporters = [w for a, w in zip(alive, self._in_wms)
                     if a and col in w]
        n_alive = sum(1 for a in alive if a)
        if not reporters or len(reporters) < n_alive:
            return
        lo = min(w[col] for w in reporters)
        if self._out_wms.get(col) is None or lo > self._out_wms[col]:
            self._out_wms[col] = lo
            yield Watermark(col, dtype, lo)

    def _on_watermark(self, idx: int, wm: Watermark,
                      alive: Sequence[bool]) -> Iterator[Message]:
        self._in_wms[idx][wm.col_idx] = wm.value
        self._wm_dtypes[wm.col_idx] = wm.dtype
        yield from self._check_col(wm.col_idx, wm.dtype, alive)

    def _on_input_done(self, alive: Sequence[bool]) -> Iterator[Message]:
        """A finished input stops constraining the min — watermarks held
        waiting for it must be re-evaluated and released (the reference
        re-checks on buffer removal, `union.rs`/BufferedWatermarks)."""
        for col, dtype in self._wm_dtypes.items():
            yield from self._check_col(col, dtype, alive)

    def execute(self) -> Iterator[Message]:
        iters = [inp.execute() for inp in self.inputs]
        alive = [True] * len(iters)
        while any(alive):
            barrier: Optional[Barrier] = None
            # drain each input up to its barrier
            for idx, it in enumerate(iters):
                if not alive[idx]:
                    continue
                while True:
                    try:
                        msg = next(it)
                    except StopIteration:
                        alive[idx] = False
                        yield from self._on_input_done(alive)
                        break
                    if isinstance(msg, Barrier):
                        barrier = msg
                        break
                    if isinstance(msg, Watermark):
                        yield from self._on_watermark(idx, msg, alive)
                        continue
                    yield msg
            if barrier is not None:
                yield barrier.with_trace(self.name)
            else:
                return


class ValuesExecutor(Executor):
    """Emit a fixed set of rows once, then pass barriers (`values.rs`)."""

    def __init__(self, schema: Schema, rows: Sequence[Sequence],
                 barrier_source: "Executor"):
        super().__init__(schema, "Values")
        self.append_only = True
        self.rows = list(rows)
        self.barrier_source = barrier_source

    def execute(self) -> Iterator[Message]:
        emitted = False
        for msg in self.barrier_source.execute():
            if not emitted and isinstance(msg, Barrier):
                yield msg
                if self.rows:
                    from ..core.chunk import StreamChunk as SC
                    yield SC.from_rows(self.schema.dtypes,
                                       [(Op.INSERT, r) for r in self.rows])
                emitted = True
            else:
                yield msg


class RowIdGenExecutor(UnaryExecutor):
    """Fill a serial row-id column (`row_id_gen.rs`): ids embed the vnode so
    generation is conflict-free across parallel shards."""

    # Bit budget (63 bits total, like the reference's row-id layout
    # timestamp|vnode|sequence): 41-bit millis since _ID_EPOCH | 10-bit
    # shard | 12-bit per-ms sequence. Millis are anchored to a custom epoch
    # (like the reference's row-id generator) so the 41-bit field lasts
    # ~69 years from 2024 instead of overflowing into the sign bit in 2039.
    # Restart-disjointness holds because a restarted process re-reads the
    # clock; minting >4096 ids/ms advances the logical millis ahead of wall
    # clock (same caveat as the reference).
    _SEQ_BITS = 12
    _SHARD_BITS = 10
    _ID_EPOCH_MS = 1_704_067_200_000   # 2024-01-01T00:00:00Z

    def __init__(self, input: Executor, row_id_index: int, shard: int = 0):
        super().__init__(input, input.schema)
        self.append_only = input.append_only
        self.row_id_index = row_id_index
        # logical counter = millis * 2^12 + seq; monotonic, clock-anchored
        self._counter = self._now_ms() << self._SEQ_BITS
        if not 0 <= shard < (1 << self._SHARD_BITS):
            raise ValueError(f"shard {shard} exceeds {self._SHARD_BITS} bits")
        self.shard = shard

    @classmethod
    def _now_ms(cls) -> int:
        import time
        return int(time.time() * 1000) - cls._ID_EPOCH_MS

    def on_chunk(self, chunk: StreamChunk) -> Iterator[Message]:
        chunk = chunk.compact()
        n = chunk.capacity
        # re-anchor to the wall clock whenever it has moved past the counter
        self._counter = max(self._counter,
                            self._now_ms() << self._SEQ_BITS)
        counters = np.arange(self._counter, self._counter + n, dtype=np.int64)
        ms, seq = counters >> self._SEQ_BITS, counters & ((1 << self._SEQ_BITS) - 1)
        ids = ((ms << (self._SHARD_BITS + self._SEQ_BITS))
               | (self.shard << self._SEQ_BITS) | seq)
        self._counter += n
        cols = list(chunk.columns)
        if self.row_id_index >= len(cols):
            # connector chunks don't carry the row-id column; append it
            cols.append(Column(T.SERIAL, ids))
        else:
            old = cols[self.row_id_index]
            if old.validity.any():
                # rows that already carry an id (DML deletes/updates resolved
                # against the table) keep it; only NULL ids are minted
                ids = np.where(old.validity,
                               old.values.astype(np.int64, copy=False), ids)
            cols[self.row_id_index] = Column(T.SERIAL, ids)
        yield StreamChunk(chunk.ops, cols)


class ExpandExecutor(UnaryExecutor):
    """Row → multiple subset rows with a flag column (`expand.rs`), used for
    grouping sets / distinct agg rewrites."""

    def __init__(self, input: Executor, subsets: Sequence[Sequence[int]]):
        in_schema = input.schema
        fields = [Field(f.name, f.dtype) for f in in_schema.fields]
        fields.append(Field("flag", T.INT64))
        super().__init__(input, Schema(fields), "Expand")
        self.append_only = input.append_only
        self.subsets = [list(s) for s in subsets]

    def on_chunk(self, chunk: StreamChunk) -> Iterator[Message]:
        chunk = chunk.compact()
        n = chunk.capacity
        for flag, subset in enumerate(self.subsets):
            cols = []
            for i, c in enumerate(chunk.columns):
                if i in subset:
                    cols.append(c)
                else:
                    vals = (np.empty(n, dtype=object)
                            if c.dtype.np_dtype == np.dtype(object)
                            else np.zeros(n, dtype=c.dtype.np_dtype))
                    if c.dtype.np_dtype == np.dtype(object):
                        vals[:] = None
                    cols.append(Column(c.dtype, vals, np.zeros(n, dtype=np.bool_)))
            cols.append(Column(T.INT64, np.full(n, flag, dtype=np.int64)))
            yield StreamChunk(chunk.ops, cols)
