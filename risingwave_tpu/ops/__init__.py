"""Stream executors (reference: `src/stream/src/executor/`)."""
from .executor import Executor, SharedStream, UnaryExecutor
from .materialize import BatchScan, ConflictBehavior, MaterializeExecutor
from .message import Barrier, BarrierKind, Message, Mutation, MutationKind, Watermark
from .simple import (ExpandExecutor, FilterExecutor, ProjectExecutor,
                     RowIdGenExecutor, UnionExecutor, ValuesExecutor)
from .exchange import (Channel, ChannelSource, DispatchExecutor,
                       FragmentPump, MergeExecutor)
from .source import (BarrierInjector, BarrierSource, SourceExecutor,
                     SourceReader)
from .agg import (HashAggExecutor, SimpleAggExecutor,
                  StatelessSimpleAggExecutor)
from .device_agg import DeviceHashAggExecutor, device_agg_eligible
from .join import HashJoinExecutor, JoinType
from .topn import AppendOnlyDedupExecutor, TopNExecutor
from .watermark import WatermarkFilterExecutor
from .window import HopWindowExecutor, OverWindowExecutor, WindowFuncCall
from .misc import (ChangelogExecutor, DynamicFilterExecutor, NowExecutor,
                   SortExecutor)
from .project_set import (BoundTableFunction, ProjectSetExecutor,
                          TableFunctionScanExecutor)
from .asof_join import AsOfJoinExecutor
from .temporal_join import TemporalJoinExecutor

__all__ = [
    "Executor", "SharedStream", "UnaryExecutor", "BatchScan",
    "ConflictBehavior", "MaterializeExecutor", "Barrier", "BarrierKind",
    "Message", "Mutation", "MutationKind", "Watermark", "ExpandExecutor",
    "FilterExecutor", "ProjectExecutor", "RowIdGenExecutor", "UnionExecutor",
    "ValuesExecutor", "BarrierInjector", "BarrierSource",
    "SourceExecutor", "SourceReader",
    "HashAggExecutor", "SimpleAggExecutor", "StatelessSimpleAggExecutor",
    "DeviceHashAggExecutor", "device_agg_eligible",
    "HashJoinExecutor", "JoinType", "AppendOnlyDedupExecutor", "TopNExecutor",
    "HopWindowExecutor", "OverWindowExecutor", "WindowFuncCall",
    "WatermarkFilterExecutor", "Channel", "ChannelSource",
    "DispatchExecutor", "FragmentPump", "MergeExecutor",
    "ChangelogExecutor", "DynamicFilterExecutor", "NowExecutor",
    "SortExecutor", "BoundTableFunction", "ProjectSetExecutor",
    "TableFunctionScanExecutor", "TemporalJoinExecutor",
]
