"""Executor protocol.

Re-design of the reference's `Execute` trait
(`src/stream/src/executor/mod.rs:203`): an executor is a generator over
`Message`s. Composition is by wrapping input generators (the reference pins
boxed streams; Python generators give the same pull-based dataflow). The
invariant every stateful executor obeys (mod.rs docs + `state_table.rs`):
buffer state changes, `commit(epoch)` when a barrier arrives, THEN yield the
barrier downstream.

One executor here serves a whole fragment's data-parallelism: vnode-level
parallelism lives on the device mesh (see risingwave_tpu/parallel/), not in N
OS-level actors — that is the core TPU-first re-design.
"""
from __future__ import annotations

from typing import Any, Callable, Iterator, List, Optional, Sequence

from ..core.chunk import StreamChunk
from ..core.schema import Schema
from .message import Barrier, Message, Watermark


class Executor:
    """Base: `execute()` yields Chunk | Barrier | Watermark."""

    # True when this stream can never emit DELETE / UPDATE rows — the
    # reference's append-only plan property (derived bottom-up over the
    # plan, `generic/agg.rs` `input.append_only()`). Lets the device agg
    # keep min/max as a single extreme column instead of a multiset.
    append_only = False

    def __init__(self, schema: Schema, name: str = ""):
        self.schema = schema
        self.name = name or type(self).__name__

    def execute(self) -> Iterator[Message]:
        raise NotImplementedError

    def __iter__(self) -> Iterator[Message]:
        return self.execute()


class UnaryExecutor(Executor):
    """Single-input executor with chunk/barrier/watermark hooks."""

    def __init__(self, input: Executor, schema: Schema, name: str = ""):
        super().__init__(schema, name)
        self.input = input

    # hooks ---------------------------------------------------------------
    def on_chunk(self, chunk: StreamChunk) -> Iterator[Message]:
        raise NotImplementedError

    def on_barrier(self, barrier: Barrier) -> Iterator[Message]:
        """Emit pre-barrier output (e.g. agg change chunks); commit state.
        The barrier itself is yielded by the driver loop afterwards."""
        return iter(())

    def on_watermark(self, wm: Watermark) -> Iterator[Message]:
        yield wm

    def execute(self) -> Iterator[Message]:
        for msg in self.input.execute():
            if isinstance(msg, StreamChunk):
                if msg.cardinality > 0:
                    yield from self.on_chunk(msg)
            elif isinstance(msg, Barrier):
                yield from self.on_barrier(msg)
                yield msg.with_trace(self.name)
            elif isinstance(msg, Watermark):
                yield from self.on_watermark(msg)
            else:  # pragma: no cover
                raise TypeError(f"unexpected message {msg!r}")


class SharedStream:
    """Fan-out buffer: lets one upstream executor feed multiple downstream
    consumers (the reference does this with per-dispatcher channels in
    `DispatchExecutor`; in-process we tee the generator)."""

    def __init__(self, upstream: Executor):
        self.upstream = upstream
        self._iter = None
        self._buffers: List[List[Message]] = []

    def subscribe(self) -> "SharedStreamPort":
        buf: List[Message] = []
        self._buffers.append(buf)
        return SharedStreamPort(self, buf)

    def unsubscribe(self, port: "SharedStreamPort") -> None:
        """Detach a consumer (DROP of a downstream MV/sink) — its buffer
        must stop accumulating messages. Identity-based removal: buffers
        are usually empty lists, and list.remove's equality match would
        detach some OTHER consumer's empty buffer."""
        self._buffers = [b for b in self._buffers if b is not port.buf]

    def _pump(self) -> bool:
        if self._iter is None:
            self._iter = self.upstream.execute()
        try:
            msg = next(self._iter)
        except StopIteration:
            return False
        for b in self._buffers:
            b.append(msg)
        return True


class SharedStreamPort(Executor):
    def __init__(self, shared: SharedStream, buf: List[Message]):
        super().__init__(shared.upstream.schema, f"tee({shared.upstream.name})")
        self.shared = shared
        self.buf = buf
        self.append_only = shared.upstream.append_only

    def execute(self) -> Iterator[Message]:
        while True:
            while not self.buf:
                if not self.shared._pump():
                    return
            yield self.buf.pop(0)
