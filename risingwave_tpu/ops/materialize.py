"""Materialize executor — writes the MV table.

Reference: `src/stream/src/executor/mview/materialize.rs:59,77,166` with
conflict behaviors Overwrite / IgnoreConflict / NoCheck. Under Overwrite the
executor corrects the change stream against current state (an INSERT hitting
an existing pk becomes an update pair), so downstream MVs stay consistent.
"""
from __future__ import annotations

import enum
from typing import Iterator, List, Optional, Sequence, Tuple

from ..core.chunk import Op, StreamChunk, StreamChunkBuilder
from ..core.schema import Schema
from ..state.state_table import StateTable
from .executor import Executor, UnaryExecutor
from .message import Barrier, Message


class ConflictBehavior(enum.Enum):
    NO_CHECK = "no_check"
    OVERWRITE = "overwrite"
    IGNORE = "ignore"
    DO_UPDATE_IF_NOT_NULL = "do_update_if_not_null"


class MaterializeExecutor(UnaryExecutor):
    def __init__(self, input: Executor, table: StateTable,
                 conflict: ConflictBehavior = ConflictBehavior.NO_CHECK,
                 name: str = "Materialize"):
        super().__init__(input, input.schema, name)
        # conflict rewriting (OVERWRITE / DO_UPDATE_IF_NOT_NULL) can turn an
        # insert into an update pair when a pk collides, so only the
        # NO_CHECK path preserves the append-only property (creators use
        # NO_CHECK when the pk is a minted rowid, which never collides)
        self.append_only = (input.append_only
                            and conflict == ConflictBehavior.NO_CHECK)
        self.table = table
        self.conflict = conflict

    def on_chunk(self, chunk: StreamChunk) -> Iterator[Message]:
        chunk = chunk.compact()
        if self.conflict == ConflictBehavior.NO_CHECK:
            self.table.write_chunk(chunk)
            yield chunk
            return
        # conflict-checked path: rewrite the chunk against current state
        out = StreamChunkBuilder(self.schema.dtypes)
        pk_idx = self.table.pk_indices
        for op, row in chunk.op_rows():
            pk = [row[i] for i in pk_idx]
            existing = self.table.get_by_pk(pk)
            if op.is_insert:
                if existing is None:
                    self.table.insert(row)
                    out.append_row(Op.INSERT, row)
                elif self.conflict == ConflictBehavior.OVERWRITE:
                    if tuple(existing) != tuple(row):
                        self.table.update(existing, row)
                        out.append_update(existing, row)
                elif self.conflict == ConflictBehavior.DO_UPDATE_IF_NOT_NULL:
                    merged = tuple(row[i] if row[i] is not None else existing[i]
                                   for i in range(len(row)))
                    if merged != tuple(existing):
                        self.table.update(existing, merged)
                        out.append_update(existing, merged)
                # IGNORE: keep the first row, drop the new one
            else:
                if existing is not None:
                    self.table.delete(existing)
                    out.append_row(Op.DELETE, existing)
                # deleting a non-existent pk is a no-op under conflict handling
        result = out.take()
        if result is not None:
            yield result

    def on_barrier(self, barrier: Barrier) -> Iterator[Message]:
        self.table.commit(barrier.epoch.curr)
        return iter(())


class BatchScan:
    """Snapshot read of a materialized table at the committed epoch —
    the `StorageTable::batch_iter_with_pk_bounds` analog
    (`src/storage/src/table/batch_table/mod.rs:892`)."""

    def __init__(self, table: StateTable, schema: Schema):
        self.table = table
        self.schema = schema

    def rows(self) -> List[Tuple]:
        return list(self.table.iter_all())

    def sorted_rows(self) -> List[Tuple]:
        """Rows in global pk order (iter_all is vnode-major, so re-sort)."""
        from ..core.encoding import SortKey
        pk_idx = self.table.pk_indices
        return sorted(
            self.rows(),
            key=lambda r: SortKey([r[i] for i in pk_idx],
                                  self.table.pk_dtypes, self.table.order_desc))
