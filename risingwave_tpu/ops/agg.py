"""Aggregation executors: HashAgg, SimpleAgg, StatelessSimpleAgg.

Reference: `src/stream/src/executor/aggregate/{hash_agg.rs,simple_agg.rs,
stateless_simple_agg.rs,agg_group.rs,distinct.rs}`. Chunk application updates
in-memory group states; at each barrier the executor emits a change chunk
(insert / retract / update pairs) for groups whose outputs changed
(`hash_agg.rs:331,411`), then commits state.

The first implicit aggregate is always row_count (`agg_group.rs` does the
same): count(*) decides group liveness — a group whose row count reaches 0
emits a DELETE and drops its state.

The TPU device path for the int-keyed sum/count/min/max subset lives in
`risingwave_tpu/device/agg_step.py` (sharded: `parallel/sharded_agg.py`);
this host implementation is the exact path and the fallback for decimals
and other host-only types.
"""
from __future__ import annotations

import heapq
import pickle
from typing import Any, Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from ..core.chunk import Column, Op, StreamChunk, StreamChunkBuilder
from ..core.schema import Field, Schema
from ..core import dtypes as T
from ..expr.agg import AggCall, AggState, DistinctDedup, create_agg_state
from ..expr.expression import Expr
from ..state.state_table import StateTable
from .executor import Executor, UnaryExecutor
from .message import Barrier, Message, Watermark

_NOT_NULL = object()  # count(*) sentinel value


class AggGroup:
    """Per-group state: row_count + one AggState per call
    (`agg_group.rs` analog)."""

    __slots__ = ("states", "dedups", "prev_output", "row_count")

    def __init__(self, calls: Sequence[AggCall]):
        self.states: List[AggState] = [create_agg_state(c) for c in calls]
        self.dedups: List[Optional[DistinctDedup]] = [
            DistinctDedup() if c.distinct else None for c in calls]
        self.prev_output: Optional[Tuple] = None  # None = never emitted
        self.row_count = 0

    def apply(self, sign: int, values: Sequence[Any]) -> None:
        self.row_count += sign
        for i, st in enumerate(self.states):
            v = values[i]
            if v is _NOT_NULL:
                st.apply(sign, v)
                continue
            if v is None:
                continue  # strict aggregates skip NULL inputs
            d = self.dedups[i]
            if d is not None:
                fs = d.apply(sign, v)
                if fs != 0:
                    st.apply(fs, v)
            else:
                st.apply(sign, v)

    def output(self) -> Tuple:
        return tuple(st.output() for st in self.states)


def _eval_agg_inputs(calls: Sequence[AggCall], chunk: StreamChunk
                     ) -> List[Optional[np.ndarray]]:
    """Evaluate each call's arg expression + filter over the chunk once
    (vectorized); returns per-call value arrays with None for filtered/NULL."""
    data = chunk.data_chunk()
    n = chunk.capacity
    out = []
    for c in calls:
        if c.arg is None:
            vals = np.empty(n, dtype=object)
            vals[:] = _NOT_NULL
        else:
            col = c.arg.eval(data)
            vals = np.empty(n, dtype=object)
            for i in range(n):
                vals[i] = col.get(i)
        if c.filter is not None:
            f = c.filter.eval(data)
            keep = f.values.astype(np.bool_) & f.validity
            for i in range(n):
                if not keep[i]:
                    vals[i] = None
        out.append(vals)
    return out


class HashAggExecutor(UnaryExecutor):
    """Group-by aggregation (`hash_agg.rs`)."""

    def __init__(self, input: Executor, group_key_indices: Sequence[int],
                 calls: Sequence[AggCall],
                 state_table: Optional[StateTable] = None,
                 emit_on_window_close: bool = False,
                 window_col_in_group: Optional[int] = None):
        in_schema = input.schema
        fields = [in_schema.fields[i] for i in group_key_indices]
        fields += [Field(f"agg#{i}", c.return_type) for i, c in enumerate(calls)]
        super().__init__(input, Schema(fields), "HashAgg")
        self.group_key_indices = list(group_key_indices)
        self.calls = list(calls)
        self.groups: Dict[Tuple, AggGroup] = {}
        self.dirty: Dict[Tuple, AggGroup] = {}
        self.state_table = state_table
        self._recovered = state_table is None
        # EOWC: buffer change emission until the watermark passes the window
        # column (`hash_agg.rs:420-429` SortBuffer semantics).
        self.emit_on_window_close = emit_on_window_close
        if emit_on_window_close:
            assert window_col_in_group is not None, \
                "EOWC requires window_col_in_group (the window column's " \
                "position within the group key)"
        self.window_col_in_group = window_col_in_group
        self.window_watermark: Optional[Any] = None
        self._emitted_windows_upto: Optional[Any] = None
        self._wm_dtype: Optional[Any] = None
        # min-heap of (window_value, seq, group_key): closed windows pop in
        # order without scanning all live groups (SortBuffer analog)
        self._window_heap: List[Tuple[Any, int, Tuple]] = []
        self._heap_seq = 0
        # watermark-driven state cleaning (`state_table.rs:1002` analog):
        # a watermark on a group-key column proves groups below it can
        # never change again — their state is dropped at the next barrier
        # (the MV keeps the rows; no retraction is emitted)
        self._clean_wm: Optional[Tuple[int, Any]] = None   # (group_pos, val)

    # ---- state persistence (pickled AggGroup per group key) ----
    def _recover(self) -> None:
        if self._recovered:
            return
        self._recovered = True
        for row in self.state_table.iter_all():
            key = tuple(row[: len(self.group_key_indices)])
            g: AggGroup = pickle.loads(row[-1])
            self.groups[key] = g
            wc = self.window_col_in_group
            if self.emit_on_window_close and key[wc] is not None:
                heapq.heappush(self._window_heap,
                               (key[wc], self._heap_seq, key))
                self._heap_seq += 1

    def on_chunk(self, chunk: StreamChunk) -> Iterator[Message]:
        self._recover()
        chunk = chunk.compact()
        agg_vals = _eval_agg_inputs(self.calls, chunk)
        signs = chunk.signs()
        n = chunk.capacity
        gki = self.group_key_indices
        wc = self.window_col_in_group
        for i in range(n):
            key = tuple(chunk.columns[j].get(i) for j in gki)
            if self.emit_on_window_close:
                # late-data guard: rows for already-emitted windows are
                # dropped — emitted EOWC output is final
                if (self._emitted_windows_upto is not None
                        and key[wc] is not None
                        and key[wc] < self._emitted_windows_upto):
                    continue
            g = self.groups.get(key)
            if g is None:
                g = self.groups[key] = AggGroup(self.calls)
                if self.emit_on_window_close and key[wc] is not None:
                    heapq.heappush(self._window_heap,
                                   (key[wc], self._heap_seq, key))
                    self._heap_seq += 1
            g.apply(int(signs[i]), [v[i] for v in agg_vals])
            self.dirty[key] = g
        return iter(())

    def _emit_group(self, out: StreamChunkBuilder, key: Tuple, g: AggGroup
                    ) -> None:
        new_out = g.output()
        if g.row_count == 0:
            if g.prev_output is not None:
                out.append_row(Op.DELETE, key + g.prev_output)
            del self.groups[key]
            if self.state_table is not None:
                self.state_table.delete(key + (pickle.dumps(g),))
            return
        if g.prev_output is None:
            out.append_row(Op.INSERT, key + new_out)
        elif g.prev_output != new_out:
            out.append_update(key + g.prev_output, key + new_out)
        g.prev_output = new_out
        if self.state_table is not None:
            self.state_table.insert(key + (pickle.dumps(g),))

    def on_barrier(self, barrier: Barrier) -> Iterator[Message]:
        self._recover()
        out = StreamChunkBuilder(self.schema.dtypes)
        wm_out: Optional[Watermark] = None
        if self.emit_on_window_close:
            self._emit_eowc(out)
            # persist still-open windows so recovery does not lose them
            if self.state_table is not None:
                for key, g in self.dirty.items():
                    self.state_table.insert(key + (pickle.dumps(g),))
            self.dirty.clear()
            # the watermark is released only AFTER the rows it closes
            # (`hash_agg.rs` SortBuffer contract: output respects watermarks)
            if (self.window_watermark is not None
                    and self.window_watermark != self._emitted_windows_upto):
                self._emitted_windows_upto = self.window_watermark
                wm_out = Watermark(self.window_col_in_group, self._wm_dtype,
                                   self.window_watermark)
        else:
            for key, g in self.dirty.items():
                self._emit_group(out, key, g)
            self.dirty.clear()
            self._clean_state()
        for chunk in out.drain():
            yield chunk
        if wm_out is not None:
            yield wm_out
        if self.state_table is not None:
            self.state_table.commit(barrier.epoch.curr)

    def _clean_state(self) -> None:
        if self._clean_wm is None:
            return
        gi, wv = self._clean_wm
        self._clean_wm = None
        dead = [k for k in self.groups
                if k[gi] is not None and k[gi] < wv]
        for k in dead:
            g = self.groups.pop(k)
            if self.state_table is not None:
                self.state_table.delete(k + (pickle.dumps(g),))

    def _emit_eowc(self, out: StreamChunkBuilder) -> None:
        """Emit only groups whose window column is closed by the watermark;
        emitted groups are final (append-only output). Closed windows pop
        from the heap in window order — O(closed log n), not O(live)."""
        if self.window_watermark is None:
            return
        wm = self.window_watermark
        # a watermark promises no future rows with value < wm, so exactly
        # the windows strictly below it are closed (watermark_filter.rs
        # keeps `ts >= watermark`)
        while self._window_heap and self._window_heap[0][0] < wm:
            _, _, key = heapq.heappop(self._window_heap)
            g = self.groups.pop(key, None)
            if g is None:
                continue  # already closed (recovery rebuilt the heap)
            self.dirty.pop(key, None)
            if g.row_count > 0 and g.prev_output is None:
                out.append_row(Op.INSERT, key + g.output())
            if self.state_table is not None:
                self.state_table.delete(key + (pickle.dumps(g),))

    def on_watermark(self, wm: Watermark) -> Iterator[Message]:
        if (self.emit_on_window_close and self.window_col_in_group is not None
                and self.group_key_indices[self.window_col_in_group] == wm.col_idx):
            # buffer: released at the barrier after closed windows are emitted
            self.window_watermark = wm.value
            self._wm_dtype = wm.dtype
        elif wm.col_idx in self.group_key_indices:
            gi = self.group_key_indices.index(wm.col_idx)
            self._clean_wm = (gi, wm.value)
            yield Watermark(gi, wm.dtype, wm.value)


class SimpleAggExecutor(UnaryExecutor):
    """Global aggregation — exactly one group, always emits a row (even for
    zero input rows, matching SQL `SELECT count(*) FROM t` = 0)
    (`simple_agg.rs`)."""

    def __init__(self, input: Executor, calls: Sequence[AggCall],
                 state_table: Optional[StateTable] = None):
        fields = [Field(f"agg#{i}", c.return_type) for i, c in enumerate(calls)]
        super().__init__(input, Schema(fields), "SimpleAgg")
        self.calls = list(calls)
        self.group = AggGroup(self.calls)
        self.state_table = state_table
        self._recovered = state_table is None
        self.dirty = True  # first barrier emits the initial row

    def _recover(self) -> None:
        if self._recovered:
            return
        self._recovered = True
        for row in self.state_table.iter_all():
            self.group = pickle.loads(row[-1])

    def on_chunk(self, chunk: StreamChunk) -> Iterator[Message]:
        self._recover()
        chunk = chunk.compact()
        agg_vals = _eval_agg_inputs(self.calls, chunk)
        signs = chunk.signs()
        for i in range(chunk.capacity):
            self.group.apply(int(signs[i]), [v[i] for v in agg_vals])
        self.dirty = True
        return iter(())

    def on_barrier(self, barrier: Barrier) -> Iterator[Message]:
        self._recover()
        if self.dirty:
            new_out = self.group.output()
            # SQL semantics for the empty group: count()=0, sum()=NULL
            if self.group.prev_output is None:
                yield StreamChunk.from_rows(self.schema.dtypes,
                                            [(Op.INSERT, new_out)])
            elif new_out != self.group.prev_output:
                b = StreamChunkBuilder(self.schema.dtypes)
                b.append_update(self.group.prev_output, new_out)
                yield b.take()
            self.group.prev_output = new_out
            self.dirty = False
            if self.state_table is not None:
                self.state_table.insert((0, pickle.dumps(self.group)))
        if self.state_table is not None:
            self.state_table.commit(barrier.epoch.curr)


class StatelessPartialAggExecutor(UnaryExecutor):
    """Grouped per-chunk partial aggregation with NO cross-epoch state —
    the pre-shuffle stage of 2-phase aggregation (`stateless_simple_agg.rs`
    generalized with a group key, as the reference's batch/stream 2-phase
    agg rewrite plans it). Partials accumulate across the EPOCH and flush
    one INSERT row per touched group at the barrier: (group cols...,
    partial outputs...) — epoch granularity is what makes the reduction
    effective (per-chunk partials barely compress keys that cluster over
    time, like nexmark auction ids). Downstream merges with sum0/min/max.
    Statelessness ACROSS barriers is the recovery story for remote
    placement: a killed worker loses only uncommitted-epoch partials,
    which the barrier protocol discards anyway."""

    def __init__(self, input: Executor, group_indices: Sequence[int],
                 calls: Sequence[AggCall]):
        if not input.append_only:
            raise ValueError("stateless partial aggregation requires an "
                             "append-only input")
        gfields = [input.schema.fields[i] for i in group_indices]
        fields = gfields + [Field(f"agg#{i}", c.return_type)
                            for i, c in enumerate(calls)]
        super().__init__(input, Schema(fields), "StatelessPartialAgg")
        self.append_only = True
        self.group_key_indices = list(group_indices)
        self.calls = list(calls)
        self._groups: dict = {}

    def on_chunk(self, chunk: StreamChunk) -> Iterator[Message]:
        chunk = chunk.compact()
        agg_vals = _eval_agg_inputs(self.calls, chunk)
        signs = chunk.signs()
        rows = chunk.data_chunk().rows()
        for i, row in enumerate(rows):
            if signs[i] < 0:
                raise ValueError("retraction reached a stateless partial "
                                 "aggregation (append-only violated)")
            key = tuple(row[j] for j in self.group_key_indices)
            g = self._groups.get(key)
            if g is None:
                g = self._groups[key] = AggGroup(self.calls)
            g.apply(1, [v[i] for v in agg_vals])
        return iter(())

    def on_barrier(self, barrier: Barrier) -> Iterator[Message]:
        if self._groups:
            yield StreamChunk.from_rows(
                self.schema.dtypes,
                [(Op.INSERT, key + g.output())
                 for key, g in self._groups.items()])
            self._groups = {}


class StatelessSimpleAggExecutor(UnaryExecutor):
    """Per-chunk partial aggregation emitted immediately — the pre-shuffle
    local agg (`stateless_simple_agg.rs`). Output rows are partial states
    (e.g. partial sums + counts) to be merged downstream."""

    def __init__(self, input: Executor, calls: Sequence[AggCall]):
        fields = [Field(f"agg#{i}", c.return_type) for i, c in enumerate(calls)]
        super().__init__(input, Schema(fields), "StatelessSimpleAgg")
        self.calls = list(calls)

    def on_chunk(self, chunk: StreamChunk) -> Iterator[Message]:
        chunk = chunk.compact()
        g = AggGroup(self.calls)
        agg_vals = _eval_agg_inputs(self.calls, chunk)
        signs = chunk.signs()
        for i in range(chunk.capacity):
            g.apply(int(signs[i]), [v[i] for v in agg_vals])
        if g.row_count != 0 or any(s.output() is not None for s in g.states):
            yield StreamChunk.from_rows(self.schema.dtypes,
                                        [(Op.INSERT, g.output())])
