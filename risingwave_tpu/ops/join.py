"""Streaming hash join.

Reference: `src/stream/src/executor/hash_join.rs` (3.5k LoC north-star op):
two-input barrier-aligned loop (`:575-686`), per-side `JoinHashMap` over
row + degree state (`join/hash_join.rs:181`), eq-join per chunk with outer
null-row retraction driven by match degrees.

Degree bookkeeping (the part that makes outer joins incremental): every stored
row carries the count of current matches on the other side. A right insert
that takes a left row's degree 0→1 retracts the left row's null-padded output;
a delete that takes it 1→0 re-emits it (`join/hash_join.rs` degree table).

The host dict path is exact for all types; the device probe path for int keys
lives in risingwave_tpu/device/.
"""
from __future__ import annotations

import enum
from typing import Any, Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from ..core.chunk import Op, StreamChunk, StreamChunkBuilder
from ..core.schema import Field, Schema
from ..core import dtypes as T
from ..expr.expression import Expr
from ..state.state_table import StateTable
from .executor import Executor
from .message import Barrier, Message, Watermark


class JoinType(enum.Enum):
    INNER = "inner"
    LEFT_OUTER = "left_outer"
    RIGHT_OUTER = "right_outer"
    FULL_OUTER = "full_outer"
    LEFT_SEMI = "left_semi"
    LEFT_ANTI = "left_anti"
    RIGHT_SEMI = "right_semi"
    RIGHT_ANTI = "right_anti"


class JoinEntry:
    """One stored input row + its current match degree."""
    __slots__ = ("row", "degree")

    def __init__(self, row: Tuple, degree: int = 0):
        self.row = row
        self.degree = degree


class JoinSide:
    """One side's state: key -> {pk: JoinEntry}
    (`JoinHashMap`, `src/stream/src/executor/join/hash_join.rs:181`).

    Contract (same as the reference): input rows are unique per pk (the
    upstream stream key) — the planner guarantees a stream key on every
    stream, inserting RowIdGen when the source has none."""

    def __init__(self, key_indices: Sequence[int], pk_indices: Sequence[int],
                 schema: Schema, state_table: Optional[StateTable] = None):
        self.key_indices = list(key_indices)
        self.pk_indices = list(pk_indices)
        self.schema = schema
        self.table: Dict[Tuple, Dict[Tuple, JoinEntry]] = {}
        self.state_table = state_table
        self._recovered = state_table is None

    def recover(self) -> None:
        if self._recovered:
            return
        self._recovered = True
        n = len(self.schema)
        for srow in self.state_table.iter_all():
            row, degree = srow[:n], srow[n]
            key = tuple(row[i] for i in self.key_indices)
            pk = tuple(row[i] for i in self.pk_indices)
            self.table.setdefault(key, {})[pk] = JoinEntry(tuple(row), degree)

    def key_of(self, row: Sequence[Any]) -> Tuple:
        return tuple(row[i] for i in self.key_indices)

    def pk_of(self, row: Sequence[Any]) -> Tuple:
        return tuple(row[i] for i in self.pk_indices)

    def matches(self, key: Tuple) -> List[JoinEntry]:
        d = self.table.get(key)
        return list(d.values()) if d else []

    def insert(self, row: Tuple, degree: int) -> JoinEntry:
        e = JoinEntry(row, degree)
        self.table.setdefault(self.key_of(row), {})[self.pk_of(row)] = e
        return e

    def remove(self, row: Tuple) -> Optional[JoinEntry]:
        key = self.key_of(row)
        d = self.table.get(key)
        if not d:
            return None
        e = d.pop(self.pk_of(row), None)
        if not d:
            del self.table[key]
        return e

    def persist(self, epoch: int) -> None:
        """Rewrite dirty state at barrier. Incremental write-set tracking:
        entries touched since last barrier are re-upserted."""
        if self.state_table is None:
            return
        # write-through happens in the executor via _mark_dirty
        self.state_table.commit(epoch)

    def upsert_state(self, e: JoinEntry) -> None:
        if self.state_table is not None:
            self.state_table.insert(e.row + (e.degree,))

    def delete_state(self, e: JoinEntry) -> None:
        if self.state_table is not None:
            self.state_table.delete(e.row + (e.degree,))


def _null_row(n: int) -> Tuple:
    return tuple([None] * n)


class HashJoinExecutor(Executor):
    def __init__(self, left: Executor, right: Executor,
                 left_keys: Sequence[int], right_keys: Sequence[int],
                 join_type: JoinType = JoinType.INNER,
                 condition: Optional[Expr] = None,
                 left_pk: Optional[Sequence[int]] = None,
                 right_pk: Optional[Sequence[int]] = None,
                 left_state: Optional[StateTable] = None,
                 right_state: Optional[StateTable] = None,
                 max_chunk_size: int = 1024):
        if join_type in (JoinType.LEFT_SEMI, JoinType.LEFT_ANTI):
            schema = left.schema
        elif join_type in (JoinType.RIGHT_SEMI, JoinType.RIGHT_ANTI):
            schema = right.schema
        else:
            schema = left.schema.concat(right.schema)
        super().__init__(schema, f"HashJoin[{join_type.value}]")
        # inner/semi joins of append-only inputs only ever insert; outer
        # joins retract their NULL-padded rows, anti joins retract on probe
        self.append_only = (left.append_only and right.append_only
                            and join_type in (JoinType.INNER,
                                              JoinType.LEFT_SEMI,
                                              JoinType.RIGHT_SEMI))
        self.left_exec, self.right_exec = left, right
        self.join_type = join_type
        self.condition = condition
        lpk = list(left_pk) if left_pk is not None else list(range(len(left.schema)))
        rpk = list(right_pk) if right_pk is not None else list(range(len(right.schema)))
        self.sides = {
            "l": JoinSide(left_keys, lpk, left.schema, left_state),
            "r": JoinSide(right_keys, rpk, right.schema, right_state),
        }
        self.max_chunk_size = max_chunk_size
        # watermark min-alignment on equi-key pairs (hash_join.rs derives
        # output watermarks ONLY for key columns: state rows below both
        # sides' key watermark can never match again — non-key watermarks
        # don't survive a join because old state rows resurface in output).
        self._wm: Dict[str, Dict[int, Any]] = {"l": {}, "r": {}}
        self._emitted_wm: Dict[int, Any] = {}
        self._clean_wm: Dict[int, Any] = {}   # key_pos -> aligned watermark

    # ---- condition eval, vectorized over all candidates of one input row ----
    def _filter_matches(self, side: str, row: Tuple,
                        cands: List[JoinEntry]) -> List[JoinEntry]:
        if self.condition is None or not cands:
            return cands
        from ..core.chunk import DataChunk
        if side == "l":
            rows = [row + e.row for e in cands]
        else:
            rows = [e.row + row for e in cands]
        ch = DataChunk.from_rows(
            self.left_exec.schema.dtypes + self.right_exec.schema.dtypes, rows)
        c = self.condition.eval(ch)
        return [e for e, ok, valid in zip(cands, c.values, c.validity)
                if valid and ok]

    def _joined(self, side: str, this_row: Tuple, other_row: Tuple) -> Tuple:
        return (this_row + other_row) if side == "l" else (other_row + this_row)

    def _process_row(self, side: str, op: Op, row: Tuple,
                     out: StreamChunkBuilder) -> None:
        """Apply one input row from `side`, appending output rows to `out`.
        Degree algebra per `join/hash_join.rs`: matches' degrees move with
        this row; 0↔1 transitions drive outer null-row and semi/anti flips."""
        jt = self.join_type
        me = self.sides[side]
        other = self.sides["r" if side == "l" else "l"]
        key = me.key_of(row)
        # SQL NULL semantics: a NULL key equals nothing, including another
        # NULL — such rows match nothing and are not stored (the reference
        # null-checks key columns in hash_join.rs before probing)
        has_null_key = any(v is None for v in key)
        matches = [] if has_null_key else \
            self._filter_matches(side, row, other.matches(key))
        null_other = _null_row(len(other.schema))
        null_me = _null_row(len(me.schema))
        is_insert = op.is_insert
        d = 1 if is_insert else -1

        # update state + degrees first
        if has_null_key:
            pass
        elif is_insert:
            me.upsert_state(me.insert(row, len(matches)))
        else:
            e = me.remove(row)
            if e is not None:
                me.delete_state(e)
        for m in matches:
            m.degree += d
            other.upsert_state(m)

        # emission, per join type
        outer_types = (JoinType.INNER, JoinType.LEFT_OUTER,
                       JoinType.RIGHT_OUTER, JoinType.FULL_OUTER)
        if jt in outer_types:
            this_outer = jt == JoinType.FULL_OUTER or \
                (jt == JoinType.LEFT_OUTER and side == "l") or \
                (jt == JoinType.RIGHT_OUTER and side == "r")
            other_outer = jt == JoinType.FULL_OUTER or \
                (jt == JoinType.LEFT_OUTER and side == "r") or \
                (jt == JoinType.RIGHT_OUTER and side == "l")
            if this_outer and not matches:
                out.append_row(Op.INSERT if is_insert else Op.DELETE,
                               self._joined(side, row, null_other))
            for m in matches:
                if other_outer and is_insert and m.degree == 1:
                    # other row gains its first match: null row -> joined row
                    out.append_update(self._joined(side, null_me, m.row),
                                      self._joined(side, row, m.row))
                elif other_outer and not is_insert and m.degree == 0:
                    # other row loses its last match: joined row -> null row
                    out.append_update(self._joined(side, row, m.row),
                                      self._joined(side, null_me, m.row))
                else:
                    out.append_row(Op.INSERT if is_insert else Op.DELETE,
                                   self._joined(side, row, m.row))
            return

        is_anti = jt in (JoinType.LEFT_ANTI, JoinType.RIGHT_ANTI)
        output_side = "l" if jt in (JoinType.LEFT_SEMI, JoinType.LEFT_ANTI) else "r"
        if side == output_side:
            # arrival on the output side: emit iff (has match) != anti
            if (len(matches) > 0) != is_anti:
                out.append_row(Op.INSERT if is_insert else Op.DELETE, row)
        else:
            # arrival on the probe side flips output rows on 0<->1 transitions
            for m in matches:
                if is_insert and m.degree == 1:
                    out.append_row(Op.DELETE if is_anti else Op.INSERT, m.row)
                elif not is_insert and m.degree == 0:
                    out.append_row(Op.INSERT if is_anti else Op.DELETE, m.row)

    def _process_chunk(self, side: str, chunk: StreamChunk
                       ) -> Iterator[StreamChunk]:
        out = StreamChunkBuilder(self.schema.dtypes, self.max_chunk_size)
        for op, row in chunk.compact().op_rows():
            # updates decay to delete+insert; RW preserves pairs when the key
            # is unchanged — semantically equivalent downstream
            self._process_row(side, op, row, out)
        yield from out.drain()

    def execute(self) -> Iterator[Message]:
        for s in self.sides.values():
            s.recover()
        liter = self.left_exec.execute()
        riter = self.right_exec.execute()
        alive = True
        while alive:
            barrier = None
            for side, it in (("l", liter), ("r", riter)):
                while True:
                    try:
                        msg = next(it)
                    except StopIteration:
                        alive = False
                        break
                    if isinstance(msg, Barrier):
                        barrier = msg
                        break
                    if isinstance(msg, StreamChunk):
                        if msg.cardinality:
                            yield from self._process_chunk(side, msg)
                    elif isinstance(msg, Watermark):
                        yield from self._on_watermark(side, msg)
            if barrier is None:
                return
            self._clean_state()
            for s in self.sides.values():
                if s.state_table is not None:
                    s.state_table.commit(barrier.epoch.curr)
            yield barrier.with_trace(self.name)
            if barrier.is_stop():
                return

    def _on_watermark(self, side: str, wm: Watermark) -> Iterator[Message]:
        me = self.sides[side]
        if wm.col_idx not in me.key_indices:
            return
        kp = me.key_indices.index(wm.col_idx)
        self._wm[side][kp] = wm.value
        other = "r" if side == "l" else "l"
        ov = self._wm[other].get(kp)
        if ov is None:
            return
        low = min(wm.value, ov)
        prev = self._emitted_wm.get(kp)
        if prev is not None and low <= prev:
            return
        self._emitted_wm[kp] = low
        self._clean_wm[kp] = low
        nl = len(self.left_exec.schema)
        lcol = self.sides["l"].key_indices[kp]
        rcol = self.sides["r"].key_indices[kp]
        if self.join_type in (JoinType.INNER, JoinType.LEFT_OUTER,
                              JoinType.RIGHT_OUTER, JoinType.FULL_OUTER):
            yield Watermark(lcol, wm.dtype, low)
            yield Watermark(nl + rcol, wm.dtype, low)
        elif self.join_type in (JoinType.LEFT_SEMI, JoinType.LEFT_ANTI):
            yield Watermark(lcol, wm.dtype, low)
        else:
            yield Watermark(rcol, wm.dtype, low)

    def _clean_state(self) -> None:
        """Drop state rows below the aligned key watermark — they can never
        match a future row on either side (`state_table.rs:1002` analog)."""
        if not self._clean_wm:
            return
        for kp, wv in self._clean_wm.items():
            for s in self.sides.values():
                dead = [k for k in s.table
                        if k[kp] is not None and k[kp] < wv]
                for k in dead:
                    for e in s.table.pop(k).values():
                        s.delete_state(e)
        self._clean_wm.clear()
