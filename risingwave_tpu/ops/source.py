"""Source executor + barrier injection.

Reference: `src/stream/src/executor/source/source_executor.rs:53` — a source
actor owns a split reader and a barrier channel; barriers interleave with data
chunks and split offsets are persisted in a split state table at each barrier.

Here `BarrierInjector` plays the role of the meta barrier RPC fan-out
(`ControlStreamManager::inject_barrier`, `src/meta/src/barrier/rpc.rs:598`):
every registered source gets a copy of each barrier; Merge/Join alignment
downstream reconverges them.
"""
from __future__ import annotations

from collections import deque
from typing import Any, Deque, Dict, Iterator, List, Optional, Sequence

from ..core.chunk import StreamChunk
from ..core.epoch import EpochPair, now_epoch
from ..core.schema import Schema
from ..core import dtypes as T
from ..state.state_table import StateTable
from ..utils.failpoint import declare, failpoint
from .executor import Executor
from .message import Barrier, BarrierKind, Message, Mutation, MutationKind, Watermark

declare("overload.burst",
        "ingest-burst chaos: while armed, each source epoch admits 10x "
        "the normal chunk budget — the deterministic offered-load spike "
        "the overload ladder must absorb")


class SourceReader:
    """Connector-side reader protocol (`SplitReader` analog,
    `src/connector/src/source/base.rs:474`). Readers that know when
    their data actually arrived set `last_ingest_ts` (wall clock of the
    last successful poll) — the source->MV freshness measure anchors on
    it; readers without it fall back to the executor's yield wall."""

    last_ingest_ts: Optional[float] = None

    def poll(self) -> Optional[StreamChunk]:
        """Next chunk, or None if no data is currently available."""
        raise NotImplementedError

    def split_states(self) -> Dict[str, Any]:
        """split_id -> offset, persisted at each barrier."""
        return {}

    def seek(self, states: Dict[str, Any]) -> None:
        """Restore split offsets on recovery."""


class BarrierInjector:
    """Creates barriers and fans them out to every registered source."""

    def __init__(self, checkpoint_frequency: int = 1,
                 start_epoch: Optional[int] = None):
        import time as _time
        self.queues: List[Deque[Barrier]] = []
        self.checkpoint_frequency = max(1, checkpoint_frequency)
        self._tick = 0
        curr = start_epoch if start_epoch is not None else now_epoch()
        self.epoch = EpochPair.new_initial(curr)
        self._initial_sent = False
        # freshness seam: the epoch each barrier seals opened when the
        # PREVIOUS barrier went out — no event of the epoch can predate
        # that, so it is the conservative ingest fallback
        self._last_inject_ts = _time.time()

    def register(self) -> Deque[Barrier]:
        q: Deque[Barrier] = deque()
        self.queues.append(q)
        return q

    def inject(self, kind: Optional[BarrierKind] = None,
               mutation: Optional[Mutation] = None) -> Barrier:
        if not self._initial_sent:
            k = BarrierKind.INITIAL
            self._initial_sent = True
        elif kind is not None:
            k = kind
        else:
            self._tick += 1
            k = (BarrierKind.CHECKPOINT
                 if self._tick % self.checkpoint_frequency == 0
                 else BarrierKind.BARRIER)
            self.epoch = self.epoch.next(now_epoch(self.epoch.curr))
        import time as _time
        b = Barrier(self.epoch, k, mutation)
        b.open_ts = self._last_inject_ts
        self._last_inject_ts = _time.time()
        for q in self.queues:
            q.append(b)
        return b

    def inject_stop(self) -> Barrier:
        return self.inject(BarrierKind.CHECKPOINT, Mutation(MutationKind.STOP))

    @property
    def any_pending(self) -> bool:
        return any(q for q in self.queues)


class BarrierSource(Executor):
    """Chunk-less source: yields only the injector's barriers. Feeds
    executors that are driven by barriers alone (Now, Values — the
    reference's barrier-receiver registration,
    `src/stream/src/task/barrier_manager.rs` for `now.rs`)."""

    def __init__(self, injector: "BarrierInjector"):
        super().__init__(Schema([]), "BarrierSource")
        self.append_only = True
        self.injector = injector
        self.queue = injector.register()

    def execute(self) -> Iterator[Message]:
        while True:
            if self.queue:
                b = self.queue.popleft()
                yield b.with_trace(self.name)
                if b.is_stop():
                    return
            else:
                # idle: tick (same deadlock-avoidance as SourceExecutor)
                self.injector.inject()


class SourceExecutor(Executor):
    def __init__(self, schema: Schema, reader: SourceReader,
                 injector: BarrierInjector,
                 split_state_table: Optional[StateTable] = None,
                 name: str = "Source", append_only: bool = False):
        super().__init__(schema, name)
        # connector sources only ever insert; DML tables push retractions
        # through their reader, so the creator decides
        self.append_only = append_only
        self.reader = reader
        self.injector = injector
        self.queue = injector.register()
        self.split_state_table = split_state_table
        self._recovered = False
        # wall of the FIRST chunk of the current epoch (freshness stamp)
        self._first_chunk_ts: Optional[float] = None
        # source admission control (utils/overload.AdmissionBucket, set
        # by the Database for connector sources): a per-epoch token
        # bucket whose rate follows the downstream overload ladder. None
        # = ungated (DML tables, ad-hoc scans) — exactly the old path.
        self.admission = None

    def _persist_splits(self, epoch: int) -> None:
        if self.split_state_table is None:
            return
        for split_id, offset in self.reader.split_states().items():
            self.split_state_table.insert((split_id, repr(offset)))
        self.split_state_table.commit(epoch)

    def _recover_splits(self) -> None:
        if self.split_state_table is None or self._recovered:
            return
        self._recovered = True
        states = {}
        for row in self.split_state_table.iter_all():
            import ast
            states[row[0]] = ast.literal_eval(row[1])
        if states:
            self.reader.seek(states)

    def _poll_gated(self) -> Optional[StreamChunk]:
        """Admission-gated reader poll. `defer` skips the poll entirely
        — the unread data stays AT the connector (file offset, generator
        cursor), which is backpressure propagated to the source itself.
        `shed` (shedding rung + RW_LOAD_SHED only) polls the window and
        drops it, recording the audited gap through the bucket's shed
        sink (`rw_shed_log`)."""
        adm = self.admission
        if adm is None:
            return self.reader.poll()
        verdict = adm.admit()
        if verdict == "defer":
            return None
        # batch throttle rides along with cadence throttle: readers that
        # expose a `throttle` knob shrink their per-poll batch too
        if hasattr(self.reader, "throttle"):
            self.reader.throttle = adm.factor
        chunk = self.reader.poll()
        if chunk is None or chunk.cardinality == 0:
            return chunk
        if verdict == "shed":
            adm.note_shed(self.injector.epoch.curr,
                          int(chunk.cardinality))
            return None
        adm.note_admitted(int(chunk.cardinality))
        return chunk

    def _stamp_ingest(self) -> None:
        """First chunk of the current epoch: remember when its data came
        off the connector (the reader's poll wall when it reports one,
        else now) — folded onto the sealing barrier for the source->MV
        freshness measure."""
        if self._first_chunk_ts is None:
            import time as _time
            self._first_chunk_ts = getattr(self.reader, "last_ingest_ts",
                                           None) or _time.time()

    def execute(self) -> Iterator[Message]:
        paused = False
        self._first_chunk_ts = None
        # Data available when a barrier is pending still belongs to the epoch
        # the barrier seals — drain it first (bounded, so an unbounded reader
        # cannot starve barriers; reference bounds this with channel capacity).
        max_chunks_before_barrier = 64
        drained = 0
        burst = 1
        while True:
            if self.queue:
                # cadence stretch (degraded rung): bigger epochs amortize
                # barrier overhead; burst chaos: 10x the offered budget
                stretch = (self.admission.stretch
                           if self.admission is not None else 1)
                limit = max_chunks_before_barrier * max(1, stretch) * burst
                if (not paused and drained < limit
                        and self.queue[0].kind != BarrierKind.INITIAL):
                    chunk = self._poll_gated()
                    if chunk is not None and chunk.cardinality > 0:
                        drained += 1
                        self._stamp_ingest()
                        yield chunk
                        continue
                drained = 0
                b = self.queue.popleft()
                burst = 10 if failpoint("overload.burst") else 1
                # per-EPOCH admission refill at the sealing barrier: the
                # budget is `capacity * factor` poll tokens, scaled by
                # the same stretch/burst multipliers the drain limit
                # uses (the overload manager re-rates `factor` per tick)
                if self.admission is not None:
                    self.admission.epoch_refill(
                        max(1, self.admission.stretch) * burst)
                if b.kind == BarrierKind.INITIAL:
                    self._recover_splits()
                if b.is_checkpoint:
                    self._persist_splits(b.epoch.curr)
                if b.mutation is not None:
                    if b.mutation.kind == MutationKind.PAUSE:
                        paused = True
                    elif b.mutation.kind == MutationKind.RESUME:
                        paused = False
                if self._first_chunk_ts is not None:
                    # mutate the injector's SHARED instance (the yielded
                    # copy never reaches the coordinator's tick loop)
                    b.note_ingest(self._first_chunk_ts)
                    self._first_chunk_ts = None
                yield b.with_trace(self.name)
                if b.is_stop():
                    return
                continue
            if paused:
                # no data while paused; force the runner to tick barriers
                self.injector.inject()
                continue
            chunk = self._poll_gated()
            if chunk is not None and chunk.cardinality > 0:
                self._stamp_ingest()
                yield chunk
            else:
                # idle: auto-tick a barrier for ALL sources so bounded inputs
                # drain deterministically and alignment never deadlocks
                self.injector.inject()
