"""TopN executors (plain + grouped).

Reference: `src/stream/src/executor/top_n/` (`top_n_plain.rs`, `group_top_n.rs`,
`top_n_cache.rs`): maintain the ordered state per (group), emit window deltas
when rows enter/leave [offset, offset+limit).

Incremental emission: an insert/delete at sorted position p shifts the window
boundary only — at most one row enters and one leaves, found in O(log n) via
bisect on the memcomparable sort key (the same key encoding the state table
uses, so in-memory order == durable order).
"""
from __future__ import annotations

import bisect
from typing import Any, Dict, Iterator, List, Optional, Sequence, Tuple

from ..core.chunk import Op, StreamChunk, StreamChunkBuilder
from ..core.encoding import encode_key, encode_row
from ..core.schema import Schema
from ..state.state_table import StateTable
from .executor import Executor, UnaryExecutor
from .message import Barrier, Message


class _OrderedMultiset:
    """Sorted (sort_key_bytes, row) list with bisect ops."""

    __slots__ = ("items",)

    def __init__(self):
        self.items: List[Tuple[bytes, Tuple]] = []

    def insert(self, key: bytes, row: Tuple) -> int:
        pos = bisect.bisect_left(self.items, (key, row))
        self.items.insert(pos, (key, row))
        return pos

    def remove(self, key: bytes, row: Tuple) -> Optional[int]:
        pos = bisect.bisect_left(self.items, (key, row))
        if pos < len(self.items) and self.items[pos] == (key, row):
            del self.items[pos]
            return pos
        return None

    def __len__(self):
        return len(self.items)

    def at(self, i: int) -> Optional[Tuple[bytes, Tuple]]:
        return self.items[i] if 0 <= i < len(self.items) else None


class TopNExecutor(UnaryExecutor):
    """ORDER BY ... OFFSET o LIMIT l over the whole stream (`top_n_plain.rs`)."""

    def __init__(self, input: Executor, order_by: Sequence[Tuple[int, bool]],
                 limit: int, offset: int = 0,
                 state_table: Optional[StateTable] = None,
                 group_key: Sequence[int] = ()):
        super().__init__(input, input.schema,
                         "GroupTopN" if group_key else "TopN")
        self.order_by = list(order_by)
        self.limit = limit
        self.offset = offset
        self.group_key = list(group_key)
        self.groups: Dict[Tuple, _OrderedMultiset] = {}
        self.state_table = state_table
        self._recovered = state_table is None

    def _sort_key(self, row: Tuple) -> bytes:
        cols = [row[i] for i, _ in self.order_by]
        dts = [self.schema.dtypes[i] for i, _ in self.order_by]
        desc = [d for _, d in self.order_by]
        # full-row value encoding as a stable tiebreak
        return encode_key(cols, dts, desc) + encode_row(row, self.schema.dtypes)

    def _recover(self) -> None:
        if self._recovered:
            return
        self._recovered = True
        for row in self.state_table.iter_all():
            g = tuple(row[i] for i in self.group_key)
            self.groups.setdefault(g, _OrderedMultiset()).insert(
                self._sort_key(row), tuple(row))

    def on_chunk(self, chunk: StreamChunk) -> Iterator[Message]:
        self._recover()
        out = StreamChunkBuilder(self.schema.dtypes)
        lo, hi = self.offset, self.offset + self.limit
        for op, row in chunk.compact().op_rows():
            g = tuple(row[i] for i in self.group_key)
            ms = self.groups.get(g)
            if ms is None:
                ms = self.groups[g] = _OrderedMultiset()
            key = self._sort_key(row)
            if op.is_insert:
                pos = ms.insert(key, row)
                if self.state_table is not None:
                    self.state_table.insert(row)
                if pos < hi:
                    # element shifted to index hi (old hi-1) exits the window
                    exiting = ms.at(hi)
                    if exiting is not None:
                        out.append_row(Op.DELETE, exiting[1])
                    # p < lo: old element at lo-1 shifted into the window
                    # start; lo <= p < hi: the new row itself enters
                    entering = ms.at(lo) if pos < lo else (key, row)
                    if entering is not None:
                        out.append_row(Op.INSERT, entering[1])
            else:
                pos = ms.remove(key, row)
                if pos is None:
                    continue  # unknown row; ignore (consistency wrapper logs)
                if self.state_table is not None:
                    self.state_table.delete(row)
                if pos < hi:
                    if pos < lo:
                        # row above window removed: old [lo] (now at lo-1)
                        # falls out of the window
                        exiting = ms.at(lo - 1)
                        if exiting is not None:
                            out.append_row(Op.DELETE, exiting[1])
                    else:
                        out.append_row(Op.DELETE, row)
                    # old [hi] (now at hi-1) shifts into the window
                    entering = ms.at(hi - 1)
                    if entering is not None and len(ms) >= hi:
                        out.append_row(Op.INSERT, entering[1])
        yield from out.drain()

    def on_barrier(self, barrier: Barrier) -> Iterator[Message]:
        if self.state_table is not None:
            self.state_table.commit(barrier.epoch.curr)
        return iter(())


class AppendOnlyDedupExecutor(UnaryExecutor):
    """Drop duplicate keys in an append-only stream (`dedup/append_only_dedup.rs`)."""

    def __init__(self, input: Executor, key_indices: Sequence[int],
                 state_table: Optional[StateTable] = None):
        super().__init__(input, input.schema, "AppendOnlyDedup")
        self.append_only = input.append_only
        self.key_indices = list(key_indices)
        self.seen: set = set()
        self.state_table = state_table
        self._recovered = state_table is None

    def _recover(self) -> None:
        if self._recovered:
            return
        self._recovered = True
        for row in self.state_table.iter_all():
            self.seen.add(tuple(row[i] for i in self.key_indices))

    def on_chunk(self, chunk: StreamChunk) -> Iterator[Message]:
        self._recover()
        import numpy as np
        chunk = chunk.compact()
        keep = np.zeros(chunk.capacity, dtype=bool)
        for i in range(chunk.capacity):
            k = tuple(chunk.columns[j].get(i) for j in self.key_indices)
            if k not in self.seen:
                self.seen.add(k)
                keep[i] = True
                if self.state_table is not None:
                    self.state_table.insert(chunk.row_at(i))
        if keep.any():
            yield chunk.with_visibility(keep)

    def on_barrier(self, barrier: Barrier) -> Iterator[Message]:
        if self.state_table is not None:
            self.state_table.commit(barrier.epoch.curr)
        return iter(())
