"""Window executors: HopWindow (table function) and OverWindow (window
functions).

Reference: `src/stream/src/executor/hop_window.rs` and
`src/stream/src/executor/over_window/general.rs` (+ `over_partition.rs`,
`frame_finder.rs`). HopWindow expands each row into size/hop overlapping
windows — vectorized here with numpy repeat instead of per-row loops.
OverWindow recomputes affected partitions against ordered state and emits
output diffs; correct (if not maximally incremental) for all frame shapes —
the per-partition delta optimization mirrors what `over_partition.rs` caches
and is a later device-path concern.
"""
from __future__ import annotations

from typing import Any, Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from ..core.chunk import Column, Op, StreamChunk, StreamChunkBuilder
from ..core.dtypes import Interval
from ..core.encoding import SortKey
from ..core.schema import Field, Schema
from ..core import dtypes as T
from ..expr.agg import AggCall, create_agg_state
from ..expr.expression import Expr
from ..state.state_table import StateTable
from .executor import Executor, UnaryExecutor
from .message import Barrier, Message, Watermark


class HopWindowExecutor(UnaryExecutor):
    """TUMBLE is the hop==size special case. Appends window_start/window_end
    columns; each input row appears in size/hop output windows."""

    def __init__(self, input: Executor, time_col: int, hop: Interval,
                 size: Interval):
        in_schema = input.schema
        fields = list(in_schema.fields) + [
            Field("window_start", T.TIMESTAMP), Field("window_end", T.TIMESTAMP)]
        super().__init__(input, Schema(fields), "HopWindow")
        self.append_only = input.append_only
        self.time_col = time_col
        self.hop_usecs = hop.total_usecs_approx()
        self.size_usecs = size.total_usecs_approx()
        assert self.hop_usecs > 0 and self.size_usecs % self.hop_usecs == 0, \
            "window size must be a multiple of hop"
        self.n_windows = self.size_usecs // self.hop_usecs

    def on_chunk(self, chunk: StreamChunk) -> Iterator[Message]:
        chunk = chunk.compact()
        n = chunk.capacity
        ts = chunk.columns[self.time_col].values.astype(np.int64)
        # latest hop-aligned start <= ts
        first_start = (ts // self.hop_usecs) * self.hop_usecs
        reps = self.n_windows
        idx = np.repeat(np.arange(n), reps)
        k = np.tile(np.arange(reps, dtype=np.int64), n)
        starts = first_start[idx] - k * self.hop_usecs
        ends = starts + self.size_usecs
        ops = chunk.ops[idx]
        cols = [c.take(idx) for c in chunk.columns]
        cols.append(Column(T.TIMESTAMP, starts))
        cols.append(Column(T.TIMESTAMP, ends))
        valid = chunk.columns[self.time_col].validity[idx]
        yield StreamChunk(ops, cols, valid)

    def on_watermark(self, wm: Watermark) -> Iterator[Message]:
        if wm.col_idx == self.time_col:
            # a closed input timestamp closes windows starting <= wm - (size-hop)
            ws = ((wm.value // self.hop_usecs) * self.hop_usecs
                  - (self.size_usecs - self.hop_usecs))
            yield Watermark(len(self.schema) - 2, T.TIMESTAMP, ws)
        else:
            yield wm


class WindowFuncCall:
    """One OVER() call: kind in {row_number, rank, dense_rank, lag, lead,
    sum, count, min, max, avg, first_value, last_value}."""

    def __init__(self, kind: str, arg: Optional[Expr] = None, offset: int = 1,
                 return_type: Optional[T.DataType] = None,
                 # frame: (start, end) in ROWS; None = unbounded; 0 = current
                 frame: Tuple[Optional[int], Optional[int]] = (None, 0)):
        self.kind = kind
        self.arg = arg
        self.offset = offset
        self.frame = frame
        if return_type is not None:
            self.return_type = return_type
        elif kind in ("row_number", "rank", "dense_rank", "count"):
            self.return_type = T.INT64
        elif arg is not None:
            self.return_type = AggCall(kind, arg).return_type if kind in (
                "sum", "avg", "min", "max") else arg.return_type
        else:
            self.return_type = T.INT64


class OverWindowExecutor(UnaryExecutor):
    """Window functions over partitions (`over_window/general.rs`).

    State: all partition rows, ordered by the order key. On each chunk the
    affected partitions are recomputed and output diffs are emitted (U-/U+
    per changed row), which is exactly the observable behavior of the
    reference's incremental range-cache implementation."""

    def __init__(self, input: Executor, partition_by: Sequence[int],
                 order_by: Sequence[Tuple[int, bool]],
                 calls: Sequence[WindowFuncCall],
                 state_table: Optional[StateTable] = None):
        in_schema = input.schema
        fields = list(in_schema.fields) + [
            Field(f"w#{i}", c.return_type) for i, c in enumerate(calls)]
        super().__init__(input, Schema(fields), "OverWindow")
        self.partition_by = list(partition_by)
        self.order_by = list(order_by)
        self.calls = list(calls)
        self.in_dtypes = in_schema.dtypes
        # partition -> list[input row]; recomputed outputs cached for diffing
        self.partitions: Dict[Tuple, List[Tuple]] = {}
        self.prev_out: Dict[Tuple, List[Tuple]] = {}
        self.state_table = state_table
        self._recovered = state_table is None

    def _recover(self) -> None:
        if self._recovered:
            return
        self._recovered = True
        for row in self.state_table.iter_all():
            p = tuple(row[i] for i in self.partition_by)
            self.partitions.setdefault(p, []).append(tuple(row))
        for p, rows in self.partitions.items():
            rows.sort(key=self._order_key)
            self.prev_out[p] = list(zip(rows, self._compute(rows)))

    def _order_key(self, row: Tuple):
        cols = [row[i] for i, _ in self.order_by]
        dts = [self.in_dtypes[i] for i, _ in self.order_by]
        desc = [d for _, d in self.order_by]
        return SortKey(cols, dts, desc).enc + repr(row).encode()

    def _compute(self, rows: List[Tuple]) -> List[Tuple]:
        """Window outputs for an ordered partition."""
        n = len(rows)
        outs: List[List[Any]] = [[] for _ in range(n)]
        order_keys = [tuple(r[i] for i, _ in self.order_by) for r in rows]
        for call in self.calls:
            k = call.kind
            if k == "row_number":
                for i in range(n):
                    outs[i].append(i + 1)
            elif k == "rank":
                rank = 0
                for i in range(n):
                    if i == 0 or order_keys[i] != order_keys[i - 1]:
                        rank = i + 1
                    outs[i].append(rank)
            elif k == "dense_rank":
                rank = 0
                for i in range(n):
                    if i == 0 or order_keys[i] != order_keys[i - 1]:
                        rank += 1
                    outs[i].append(rank)
            elif k in ("lag", "lead"):
                delta = -call.offset if k == "lag" else call.offset
                for i in range(n):
                    j = i + delta
                    outs[i].append(self._eval_one(call.arg, rows[j])
                                   if 0 <= j < n else None)
            elif k in ("sum", "count", "min", "max", "avg",
                       "first_value", "last_value"):
                vals = [self._eval_one(call.arg, r) if call.arg is not None else 1
                        for r in rows]
                lo_off, hi_off = call.frame
                for i in range(n):
                    lo = 0 if lo_off is None else max(0, i + lo_off)
                    hi = n - 1 if hi_off is None else min(n - 1, i + hi_off)
                    st = create_agg_state(AggCall(k, call.arg))
                    for j in range(lo, hi + 1):
                        v = vals[j]
                        if v is not None:
                            st.apply(1, v)
                    outs[i].append(st.output())
            else:
                raise ValueError(f"unknown window function {k}")
        return [tuple(o) for o in outs]

    def _eval_one(self, expr: Expr, row: Tuple) -> Any:
        from ..core.chunk import DataChunk
        ch = DataChunk.from_rows(self.in_dtypes, [row])
        c = expr.eval(ch)
        return c.get(0)

    def on_chunk(self, chunk: StreamChunk) -> Iterator[Message]:
        self._recover()
        touched: Dict[Tuple, None] = {}
        for op, row in chunk.compact().op_rows():
            p = tuple(row[i] for i in self.partition_by)
            rows = self.partitions.setdefault(p, [])
            if op.is_insert:
                rows.append(row)
                if self.state_table is not None:
                    self.state_table.insert(row)
            else:
                try:
                    rows.remove(row)
                except ValueError:
                    pass
                if self.state_table is not None:
                    self.state_table.delete(row)
            touched[p] = None
        out = StreamChunkBuilder(self.schema.dtypes)
        for p in touched:
            rows = self.partitions.get(p, [])
            rows.sort(key=self._order_key)
            new_out = self._compute(rows)
            old_rows_out = self.prev_out.get(p, [])
            new_pairs = list(zip(rows, new_out))
            # diff keyed by input row: changed outputs become update pairs;
            # deletes emit before inserts so pk-conflict handling downstream
            # never sees a transient clobber
            old_by_row: Dict[Tuple, List[Tuple]] = {}
            for (r, o) in old_rows_out:
                old_by_row.setdefault(r, []).append(o)
            deletes: List[Tuple] = []
            updates: List[Tuple[Tuple, Tuple]] = []
            inserts: List[Tuple] = []
            for r, o in new_pairs:
                olds = old_by_row.get(r)
                if olds:
                    old_o = olds.pop(0)
                    if old_o != o:
                        updates.append((r + old_o, r + o))
                else:
                    inserts.append(r + o)
            for r, olds in old_by_row.items():
                for o in olds:
                    deletes.append(r + o)
            for row_out in deletes:
                out.append_row(Op.DELETE, row_out)
            for old_row, new_row in updates:
                out.append_update(old_row, new_row)
            for row_out in inserts:
                out.append_row(Op.INSERT, row_out)
            self.prev_out[p] = new_pairs
            if not rows:
                del self.partitions[p]
                self.prev_out.pop(p, None)
        yield from out.drain()

    def on_barrier(self, barrier: Barrier) -> Iterator[Message]:
        if self.state_table is not None:
            self.state_table.commit(barrier.epoch.curr)
        return iter(())
