"""Window executors: HopWindow (table function) and OverWindow (window
functions).

Reference: `src/stream/src/executor/hop_window.rs` and
`src/stream/src/executor/over_window/general.rs` (+ `over_partition.rs`,
`frame_finder.rs`). HopWindow expands each row into size/hop overlapping
windows — vectorized here with numpy repeat instead of per-row loops.
OverWindow recomputes affected partitions against ordered state and emits
output diffs; correct (if not maximally incremental) for all frame shapes —
the per-partition delta optimization mirrors what `over_partition.rs` caches
and is a later device-path concern.
"""
from __future__ import annotations

from typing import Any, Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from ..core.chunk import Column, Op, StreamChunk, StreamChunkBuilder
from ..core.dtypes import Interval
from ..core.encoding import SortKey
from ..core.schema import Field, Schema
from ..core import dtypes as T
from ..expr.agg import AggCall, create_agg_state
from ..expr.expression import Expr
from ..state.state_table import StateTable
from .executor import Executor, UnaryExecutor
from .message import Barrier, Message, Watermark


class HopWindowExecutor(UnaryExecutor):
    """TUMBLE is the hop==size special case. Appends window_start/window_end
    columns; each input row appears in size/hop output windows."""

    def __init__(self, input: Executor, time_col: int, hop: Interval,
                 size: Interval):
        in_schema = input.schema
        fields = list(in_schema.fields) + [
            Field("window_start", T.TIMESTAMP), Field("window_end", T.TIMESTAMP)]
        super().__init__(input, Schema(fields), "HopWindow")
        self.append_only = input.append_only
        self.time_col = time_col
        self.hop_usecs = hop.total_usecs_approx()
        self.size_usecs = size.total_usecs_approx()
        assert self.hop_usecs > 0 and self.size_usecs % self.hop_usecs == 0, \
            "window size must be a multiple of hop"
        self.n_windows = self.size_usecs // self.hop_usecs

    def on_chunk(self, chunk: StreamChunk) -> Iterator[Message]:
        chunk = chunk.compact()
        n = chunk.capacity
        ts = chunk.columns[self.time_col].values.astype(np.int64)
        # latest hop-aligned start <= ts
        first_start = (ts // self.hop_usecs) * self.hop_usecs
        reps = self.n_windows
        idx = np.repeat(np.arange(n), reps)
        k = np.tile(np.arange(reps, dtype=np.int64), n)
        starts = first_start[idx] - k * self.hop_usecs
        ends = starts + self.size_usecs
        ops = chunk.ops[idx]
        cols = [c.take(idx) for c in chunk.columns]
        cols.append(Column(T.TIMESTAMP, starts))
        cols.append(Column(T.TIMESTAMP, ends))
        valid = chunk.columns[self.time_col].validity[idx]
        yield StreamChunk(ops, cols, valid)

    def on_watermark(self, wm: Watermark) -> Iterator[Message]:
        if wm.col_idx == self.time_col:
            # a closed input timestamp closes windows starting <= wm - (size-hop)
            ws = ((wm.value // self.hop_usecs) * self.hop_usecs
                  - (self.size_usecs - self.hop_usecs))
            yield Watermark(len(self.schema) - 2, T.TIMESTAMP, ws)
        else:
            yield wm


class WindowFuncCall:
    """One OVER() call: kind in {row_number, rank, dense_rank, lag, lead,
    sum, count, min, max, avg, first_value, last_value}.

    frame: (start, end) offsets relative to the current row — None =
    unbounded, 0 = current row. In ROWS mode offsets are positions
    (`frame: (-2, 0)` = 2 PRECEDING..CURRENT ROW); in RANGE mode they are
    ORDER-BY-value deltas (`src/expr/core/src/window_function/` RowsFrame
    / RangeFrame)."""

    def __init__(self, kind: str, arg: Optional[Expr] = None, offset: int = 1,
                 return_type: Optional[T.DataType] = None,
                 frame: Tuple[Optional[int], Optional[int]] = (None, 0),
                 frame_mode: str = "rows"):
        self.kind = kind
        self.arg = arg
        self.offset = offset
        self.frame = frame
        self.frame_mode = frame_mode
        if return_type is not None:
            self.return_type = return_type
        elif kind in ("row_number", "rank", "dense_rank", "count"):
            self.return_type = T.INT64
        elif arg is not None:
            self.return_type = AggCall(kind, arg).return_type if kind in (
                "sum", "avg", "min", "max") else arg.return_type
        else:
            self.return_type = T.INT64


class _Partition:
    """One partition's ordered rows + cached outputs + per-call prefix
    states (the analog of `over_partition.rs`'s range cache)."""
    __slots__ = ("keys", "rows", "outs", "vals", "ovals")

    def __init__(self, n_calls: int):
        self.keys: List[bytes] = []     # sort keys, aligned with rows
        self.rows: List[Tuple] = []
        self.outs: List[Tuple] = []     # cached window outputs per row
        self.vals: List[List[Any]] = [[] for _ in range(n_calls)]
        self.ovals: List[Any] = []      # first ORDER BY column's values

    def nn(self) -> int:
        """Live non-null prefix length of ovals (NULLs sort last)."""
        n = len(self.ovals)
        while n > 0 and self.ovals[n - 1] is None:
            n -= 1
        return n


class OverWindowExecutor(UnaryExecutor):
    """Window functions over partitions (`over_window/general.rs` +
    `over_partition.rs` + `frame_finder.rs`).

    Incremental maintenance: rows live in a per-partition ordered cache;
    a chunk's changes mark the minimum affected sorted position, each
    call widens it by its frame's lookback (`frame_finder.rs` computes
    the same affected ranges), and only [start, n) is recomputed and
    diffed. Appends at the tail of the order — the streaming common case
    — therefore touch O(delta) rows regardless of partition size (the
    `over_window_recomputed_rows` metric asserts this in tests).
    Aggregate frames slide retractable states across the region instead
    of rebuilding per row; RANGE frames use value-space two-pointer
    bounds over the ordered cache."""

    def __init__(self, input: Executor, partition_by: Sequence[int],
                 order_by: Sequence[Tuple[int, bool]],
                 calls: Sequence[WindowFuncCall],
                 state_table: Optional[StateTable] = None):
        in_schema = input.schema
        fields = list(in_schema.fields) + [
            Field(f"w#{i}", c.return_type) for i, c in enumerate(calls)]
        super().__init__(input, Schema(fields), "OverWindow")
        self.partition_by = list(partition_by)
        self.order_by = list(order_by)
        self.calls = list(calls)
        self.in_dtypes = in_schema.dtypes
        self.partitions: Dict[Tuple, _Partition] = {}
        self.state_table = state_table
        self._recovered = state_table is None
        for c in self.calls:
            if c.frame_mode == "range" and (
                    len(self.order_by) != 1 or self.order_by[0][1]):
                raise ValueError("RANGE frames require exactly one "
                                 "ascending ORDER BY column")

    def _recover(self) -> None:
        if self._recovered:
            return
        self._recovered = True
        by_p: Dict[Tuple, List[Tuple]] = {}
        for row in self.state_table.iter_all():
            p = tuple(row[i] for i in self.partition_by)
            by_p.setdefault(p, []).append(tuple(row))
        oc0 = self.order_by[0][0] if self.order_by else None
        for p, rows in by_p.items():
            part = self.partitions.setdefault(p, _Partition(len(self.calls)))
            rows.sort(key=self._order_key)
            part.rows = rows
            part.keys = [self._order_key(r) for r in rows]
            part.vals = self._eval_args(rows)
            if oc0 is not None:
                part.ovals = [r[oc0] for r in rows]
            part.outs = self._compute(part, 0)

    def _order_key(self, row: Tuple):
        cols = [row[i] for i, _ in self.order_by]
        dts = [self.in_dtypes[i] for i, _ in self.order_by]
        desc = [d for _, d in self.order_by]
        return SortKey(cols, dts, desc).enc + repr(row).encode()

    # ---- vectorized argument evaluation -----------------------------------
    def _eval_args(self, rows: List[Tuple]) -> List[List[Any]]:
        """Per-call argument values for `rows`, one DataChunk eval per
        call (not per row)."""
        if not rows:
            return [[] for _ in self.calls]
        from ..core.chunk import DataChunk
        ch = None
        out = []
        for call in self.calls:
            if call.arg is None:
                out.append([1] * len(rows))
                continue
            if ch is None:
                ch = DataChunk.from_rows(self.in_dtypes, rows)
            c = call.arg.eval(ch)
            out.append([c.get(i) for i in range(len(rows))])
        return out

    # ---- affected-range computation (frame_finder.rs analog) --------------
    def _start_of(self, part: _Partition, min_pos: int,
                  min_val: Any, null_change: bool = False) -> int:
        """First sorted position whose output can change, given the
        minimum changed position (positions >= min_pos shifted/changed)
        and the minimum changed ORDER VALUE (for value-space frames —
        a deleted row's value no longer sits at any position)."""
        start = min_pos
        for call in self.calls:
            k = call.kind
            if k in ("row_number", "rank", "dense_rank", "lag"):
                continue                        # look backward only
            if k == "lead":
                start = min(start, max(0, min_pos - call.offset))
                continue
            lo, hi = call.frame
            if hi is None:
                return 0                        # trailing-unbounded frame
            if call.frame_mode == "rows":
                if hi > 0:
                    start = min(start, max(0, min_pos - hi))
            else:                               # range: value-space bound
                if null_change:
                    # a change in the NULL peer group affects every NULL
                    # row's frame — widen to the group start
                    start = min(start, part.nn())
                if min_val is None:
                    continue
                import bisect
                start = min(start, bisect.bisect_left(
                    part.ovals, min_val - hi, 0, part.nn()))
        return max(0, start)

    # ---- region recompute --------------------------------------------------
    def _compute(self, part: _Partition, start: int) -> List[Tuple]:
        """Window outputs for part.rows[start:], using cached outputs
        before `start` to seed prefix-dependent calls."""
        rows, vals_all = part.rows, part.vals
        n = len(rows)
        region = range(start, n)
        outs: List[List[Any]] = [[] for _ in region]
        order_keys = None
        if any(c.kind in ("rank", "dense_rank") for c in self.calls):
            # only the region (plus its predecessor, for the seed compare)
            # is materialized — O(delta), not O(partition)
            order_keys = {i: tuple(rows[i][j] for j, _ in self.order_by)
                          for i in range(max(0, start - 1), n)}
        for ci, call in enumerate(self.calls):
            k = call.kind
            vals = vals_all[ci]
            col = [None] * (n - start)
            if k == "row_number":
                for i in region:
                    col[i - start] = i + 1
            elif k in ("rank", "dense_rank"):
                if start == 0:
                    rank = 0
                else:
                    rank = part.outs[start - 1][ci]
                for i in region:
                    if i == 0:
                        rank = 1
                    elif order_keys[i] != order_keys[i - 1]:
                        rank = i + 1 if k == "rank" else rank + 1
                    col[i - start] = rank
            elif k in ("lag", "lead"):
                delta = -call.offset if k == "lag" else call.offset
                for i in region:
                    j = i + delta
                    col[i - start] = vals[j] if 0 <= j < n else None
            elif k == "last_value" and call.frame == (None, 0) \
                    and call.frame_mode == "rows":
                for i in region:
                    col[i - start] = vals[i]
            elif k == "first_value" and call.frame == (None, 0) \
                    and call.frame_mode == "rows":
                # PG: first_value does NOT skip NULLs — it is the frame's
                # first row's value, NULL included (constant per
                # partition for the default frame)
                fv = part.outs[start - 1][ci] if start > 0 \
                    else (vals[0] if n > 0 else None)
                for i in region:
                    col[i - start] = fv
            elif call.frame == (None, 0) and call.frame_mode == "rows" \
                    and k in ("sum", "count", "min", "max"):
                # prefix state seeded from the cached output at start-1
                # (these outputs ARE their prefix states; extension is
                # insert-only, so no retraction machinery is needed)
                st = create_agg_state(AggCall(k, call.arg))
                if start > 0:
                    seed = part.outs[start - 1][ci]
                    if seed is not None:
                        st.apply(1, seed)
                        if k == "count":
                            st.n = seed
                for i in region:
                    if vals[i] is not None:
                        st.apply(1, vals[i])
                    col[i - start] = st.output()
            elif k in ("sum", "count", "min", "max", "avg",
                       "first_value", "last_value"):
                col = self._sliding_frame(call, vals, part, start, n)
            else:
                raise ValueError(f"unknown window function {k}")
            for i in region:
                outs[i - start].append(col[i - start])
        return [tuple(o) for o in outs]

    def _sliding_frame(self, call: WindowFuncCall, vals: List[Any],
                       part: _Partition, start: int, n: int) -> List[Any]:
        """Aggregate over a moving frame: one retractable state slides
        across the region (O(region + frame) applies, not O(region x
        frame) rebuilds)."""
        lo_off, hi_off = call.frame
        if call.kind in ("first_value", "last_value"):
            return self._edge_value_frame(call, vals, part, start, n)
        st = create_agg_state(AggCall(call.kind, call.arg))
        col = [None] * (n - start)
        if call.frame_mode == "rows":
            cur_lo = 0 if lo_off is None else max(0, start + lo_off)
            cur_hi = cur_lo - 1          # empty window
            for i in range(start, n):
                lo = 0 if lo_off is None else max(0, i + lo_off)
                hi = n - 1 if hi_off is None else min(n - 1, i + hi_off)
                if lo > cur_lo + 64 or lo < cur_lo:   # re-seed on jumps
                    st = create_agg_state(AggCall(call.kind, call.arg))
                    cur_lo, cur_hi = lo, lo - 1
                while cur_hi < hi:
                    cur_hi += 1
                    if vals[cur_hi] is not None:
                        st.apply(1, vals[cur_hi])
                while cur_lo < lo:
                    if vals[cur_lo] is not None:
                        st.apply(-1, vals[cur_lo])
                    cur_lo += 1
                col[i - start] = st.output()
        else:
            # RANGE: frame of row i = rows with order value in
            # [v_i + lo, v_i + hi] (two-pointer over the ascending order).
            # NULL order values sort last and form their own peer group
            # (PG: the frame of a NULL row is the NULL group).
            ovals = part.ovals
            nn = part.nn()
            cur_lo = start
            cur_hi = start - 1
            import bisect
            for i in range(start, n):
                v = ovals[i]
                if v is None:
                    lo, hi = nn, n - 1
                else:
                    lo = 0 if lo_off is None else bisect.bisect_left(
                        ovals, v + lo_off, 0, nn)
                    hi = n - 1 if hi_off is None else bisect.bisect_right(
                        ovals, v + hi_off, 0, nn) - 1
                if lo < cur_lo or lo > cur_hi + 64:
                    st = create_agg_state(AggCall(call.kind, call.arg))
                    cur_lo, cur_hi = lo, lo - 1
                while cur_hi < hi:
                    cur_hi += 1
                    if vals[cur_hi] is not None:
                        st.apply(1, vals[cur_hi])
                while cur_lo < lo:
                    if vals[cur_lo] is not None:
                        st.apply(-1, vals[cur_lo])
                    cur_lo += 1
                col[i - start] = st.output()
        return col

    def _edge_value_frame(self, call: WindowFuncCall, vals: List[Any],
                          part: _Partition, start: int, n: int
                          ) -> List[Any]:
        """first_value / last_value over an explicit frame: PG semantics
        take the frame's edge row's value WITHOUT skipping NULLs (unlike
        aggregates); empty frame -> NULL."""
        import bisect
        lo_off, hi_off = call.frame
        first = call.kind == "first_value"
        col = [None] * (n - start)
        if call.frame_mode == "rows":
            for i in range(start, n):
                lo = 0 if lo_off is None else max(0, i + lo_off)
                hi = n - 1 if hi_off is None else min(n - 1, i + hi_off)
                if lo <= hi:
                    col[i - start] = vals[lo if first else hi]
        else:
            ovals = part.ovals
            nn = part.nn()
            for i in range(start, n):
                v = ovals[i]
                if v is None:
                    lo, hi = nn, n - 1
                else:
                    lo = 0 if lo_off is None else bisect.bisect_left(
                        ovals, v + lo_off, 0, nn)
                    hi = n - 1 if hi_off is None else bisect.bisect_right(
                        ovals, v + hi_off, 0, nn) - 1
                if lo <= hi:
                    col[i - start] = vals[lo if first else hi]
        return col

    def on_chunk(self, chunk: StreamChunk) -> Iterator[Message]:
        import bisect
        from ..utils.metrics import REGISTRY
        self._recover()
        touched: Dict[Tuple, int] = {}       # partition -> min changed pos
        chval: Dict[Tuple, Any] = {}         # partition -> min changed value
        chnull: Dict[Tuple, bool] = {}       # partitions with NULL-order changes
        removed: Dict[Tuple, List[Tuple[Tuple, Tuple]]] = {}
        added: Dict[Tuple, int] = {}
        oc0 = self.order_by[0][0] if self.order_by else None
        for op, row in chunk.compact().op_rows():
            p = tuple(row[i] for i in self.partition_by)
            part = self.partitions.setdefault(p, _Partition(len(self.calls)))
            key = self._order_key(row)
            if oc0 is not None:
                if row[oc0] is None:
                    chnull[p] = True
                else:
                    prev = chval.get(p)
                    chval[p] = row[oc0] if prev is None \
                        else min(prev, row[oc0])
            if op.is_insert:
                pos = bisect.bisect_right(part.keys, key)
                part.keys.insert(pos, key)
                part.rows.insert(pos, row)
                part.outs.insert(pos, None)       # placeholder
                for v in part.vals:
                    v.insert(pos, None)
                if oc0 is not None:
                    part.ovals.insert(pos, row[oc0])
                added[p] = added.get(p, 0) + 1
                if self.state_table is not None:
                    self.state_table.insert(row)
            else:
                pos = bisect.bisect_left(part.keys, key)
                if pos < len(part.keys) and part.keys[pos] == key:
                    removed.setdefault(p, []).append(
                        (row, part.outs[pos]))
                    part.keys.pop(pos)
                    part.rows.pop(pos)
                    part.outs.pop(pos)
                    for v in part.vals:
                        v.pop(pos)
                    if oc0 is not None:
                        part.ovals.pop(pos)
                if self.state_table is not None:
                    self.state_table.delete(row)
            touched[p] = min(touched.get(p, pos), pos)
        out = StreamChunkBuilder(self.schema.dtypes)
        recomputed = 0
        for p, min_pos in touched.items():
            part = self.partitions[p]
            n = len(part.rows)
            start = self._start_of(part, min_pos, chval.get(p),
                                   chnull.get(p, False))
            # refresh cached arg values for the region (inserted rows
            # hold placeholders); one vectorized eval per call
            region_vals = self._eval_args(part.rows[start:])
            for ci in range(len(self.calls)):
                part.vals[ci][start:] = region_vals[ci]
            old_outs = part.outs[start:]
            new_outs = self._compute(part, start)
            recomputed += n - start
            part.outs[start:] = new_outs
            # diff the region; removed rows emit deletes with their
            # cached outputs
            old_by_row: Dict[Tuple, List[Tuple]] = {}
            for r, o in zip(part.rows[start:], old_outs):
                if o is not None:
                    old_by_row.setdefault(r, []).append(o)
            deletes: List[Tuple] = [r + o for r, o in removed.get(p, [])
                                    if o is not None]
            updates: List[Tuple[Tuple, Tuple]] = []
            inserts: List[Tuple] = []
            for r, o in zip(part.rows[start:], new_outs):
                olds = old_by_row.get(r)
                if olds:
                    old_o = olds.pop(0)
                    if old_o != o:
                        updates.append((r + old_o, r + o))
                else:
                    inserts.append(r + o)
            for r, olds in old_by_row.items():
                deletes.extend(r + o for o in olds)
            for row_out in deletes:
                out.append_row(Op.DELETE, row_out)
            for old_row, new_row in updates:
                out.append_update(old_row, new_row)
            for row_out in inserts:
                out.append_row(Op.INSERT, row_out)
            if not part.rows:
                del self.partitions[p]
        REGISTRY.counter(
            "over_window_recomputed_rows",
            "rows recomputed by OverWindow per chunk").inc(recomputed)
        yield from out.drain()

    def on_barrier(self, barrier: Barrier) -> Iterator[Message]:
        if self.state_table is not None:
            self.state_table.commit(barrier.epoch.curr)
        return iter(())
