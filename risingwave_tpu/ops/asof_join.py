"""Streaming ASOF join.

Reference: `src/stream/src/executor/asof_join.rs` (AsOfJoinExecutor):
`a ASOF [LEFT] JOIN b ON a.k = b.k AND a.t <cmp> b.t` — per left row, at
most ONE right row joins: the one with the same key whose inequality
column is *closest* to the left's while satisfying the comparison
(`AsOfInequalityType` + the BTreeMap lower/upper_bound probe,
asof_join.rs:625). Streaming semantics: when a better right row arrives
(or the current match is deleted), the previously emitted pair retracts
and the new best pair emits.

Best-match rule (asof_join.rs:625-645):
    l <  r  -> smallest right > l          l >  r -> largest right < l
    l <= r  -> smallest right >= l         l >= r -> largest right <= l
Ties on the inequality value break deterministically by right pk (the
reference iterates its (ineq, pk)-ordered BTreeMap the same way).

Re-design vs the reference: instead of the cache/degree machinery, each
side keeps key -> {pk: row} plus a per-left-row record of the CURRENTLY
EMITTED output row; a right-side change marks its key dirty and the
executor re-derives best matches for that key's left rows at chunk
granularity, emitting only the diff. Exactness over incrementality — the
per-key scan is the simple host path (the device path batches at barrier
granularity anyway).
"""
from __future__ import annotations

from typing import Any, Dict, Iterator, List, Optional, Sequence, Tuple

from ..core.chunk import Op, StreamChunk, StreamChunkBuilder
from ..core.schema import Schema
from ..state.state_table import StateTable
from .executor import Executor
from .message import Barrier, Message, Watermark

_OPS = ("<", "<=", ">", ">=")


def _null_row(n: int) -> Tuple:
    return tuple(None for _ in range(n))


class _Side:
    """key -> {pk: row}; rows also mirrored to the state table."""

    def __init__(self, key_idx: Sequence[int], pk_idx: Sequence[int],
                 state_table: Optional[StateTable]):
        self.key_idx = list(key_idx)
        self.pk_idx = list(pk_idx)
        self.state_table = state_table
        self.data: Dict[Tuple, Dict[Tuple, Tuple]] = {}

    def key_of(self, row: Tuple) -> Tuple:
        return tuple(row[i] for i in self.key_idx)

    def pk_of(self, row: Tuple) -> Tuple:
        return tuple(row[i] for i in self.pk_idx)

    def insert(self, row: Tuple) -> None:
        self.data.setdefault(self.key_of(row), {})[self.pk_of(row)] = row
        if self.state_table is not None:
            self.state_table.insert(row)

    def delete(self, row: Tuple) -> None:
        key = self.key_of(row)
        group = self.data.get(key)
        if group is not None:
            group.pop(self.pk_of(row), None)
            if not group:
                del self.data[key]
        if self.state_table is not None:
            self.state_table.delete(row)

    def recover(self) -> None:
        if self.state_table is None:
            return
        for row in self.state_table.iter_all():
            row = tuple(row)
            self.data.setdefault(self.key_of(row), {})[self.pk_of(row)] = row


class AsOfJoinExecutor(Executor):
    def __init__(self, left: Executor, right: Executor,
                 left_keys: Sequence[int], right_keys: Sequence[int],
                 left_ineq: int, right_ineq: int, ineq_op: str,
                 left_outer: bool = False,
                 left_pk: Optional[Sequence[int]] = None,
                 right_pk: Optional[Sequence[int]] = None,
                 left_state: Optional[StateTable] = None,
                 right_state: Optional[StateTable] = None):
        assert ineq_op in _OPS, ineq_op
        schema = left.schema.concat(right.schema)
        super().__init__(schema,
                         f"AsOfJoin[{'left' if left_outer else 'inner'}]")
        self.append_only = False          # better matches displace old ones
        self.left_exec, self.right_exec = left, right
        self.ineq_op = ineq_op
        self.left_ineq, self.right_ineq = left_ineq, right_ineq
        self.left_outer = left_outer
        lpk = list(left_pk) if left_pk is not None \
            else list(range(len(left.schema)))
        rpk = list(right_pk) if right_pk is not None \
            else list(range(len(right.schema)))
        self.lside = _Side(left_keys, lpk, left_state)
        self.rside = _Side(right_keys, rpk, right_state)
        self._n_r = len(right.schema)
        # left pk -> currently emitted output row (None = nothing emitted)
        self._emitted: Dict[Tuple, Optional[Tuple]] = {}
        # equi-key watermark alignment, as in hash_join.rs
        self._wm: Dict[str, Dict[int, Any]] = {"l": {}, "r": {}}
        self._emitted_wm: Dict[int, Any] = {}

    # ---- best-match ------------------------------------------------------
    def _best(self, lrow: Tuple) -> Optional[Tuple]:
        v = lrow[self.left_ineq]
        if v is None:
            return None
        key = self.lside.key_of(lrow)
        if any(k is None for k in key):
            return None
        group = self.rside.data.get(key)
        if not group:
            return None
        op = self.ineq_op
        best_item = None
        for pk, row in group.items():
            rv = row[self.right_ineq]
            if rv is None:
                continue
            ok = ((op == "<" and v < rv) or (op == "<=" and v <= rv)
                  or (op == ">" and v > rv) or (op == ">=" and v >= rv))
            if not ok:
                continue
            item = (rv, pk)
            if best_item is None:
                best_item = (item, row)
            elif op in ("<", "<="):          # closest above: smallest
                if item < best_item[0]:
                    best_item = (item, row)
            else:                            # closest below: largest
                if item > best_item[0]:
                    best_item = (item, row)
        return best_item[1] if best_item else None

    def _out_row(self, lrow: Tuple) -> Optional[Tuple]:
        m = self._best(lrow)
        if m is not None:
            return lrow + m
        if self.left_outer:
            return lrow + _null_row(self._n_r)
        return None

    # ---- diff emission ---------------------------------------------------
    def _retarget(self, lpk: Tuple, new_out: Optional[Tuple],
                  out: StreamChunkBuilder) -> None:
        old = self._emitted.get(lpk)
        if old == new_out:
            return
        if old is not None and new_out is not None:
            out.append_row(Op.UPDATE_DELETE, old)
            out.append_row(Op.UPDATE_INSERT, new_out)
        elif old is not None:
            out.append_row(Op.DELETE, old)
        elif new_out is not None:
            out.append_row(Op.INSERT, new_out)
        if new_out is None:
            self._emitted.pop(lpk, None)
        else:
            self._emitted[lpk] = new_out

    def _process_chunk(self, side: str, chunk: StreamChunk
                       ) -> Iterator[Message]:
        out = StreamChunkBuilder(self.schema.dtypes, 1024)
        if side == "l":
            for op, row in chunk.compact().op_rows():
                row = tuple(row)
                lpk = self.lside.pk_of(row)
                if op.is_insert:
                    self.lside.insert(row)
                    self._retarget(lpk, self._out_row(row), out)
                else:
                    self.lside.delete(row)
                    self._retarget(lpk, None, out)
        else:
            dirty: Dict[Tuple, None] = {}
            for op, row in chunk.compact().op_rows():
                row = tuple(row)
                if op.is_insert:
                    self.rside.insert(row)
                else:
                    self.rside.delete(row)
                key = self.rside.key_of(row)
                if not any(k is None for k in key):
                    dirty[key] = None
            for key in dirty:
                for lpk, lrow in self.lside.data.get(key, {}).items():
                    self._retarget(lpk, self._out_row(lrow), out)
        yield from out.drain()

    # ---- watermarks: min-align equi-key columns (hash_join.rs rule) ------
    def _on_watermark(self, side: str, wm: Watermark) -> Iterator[Message]:
        me = self.lside if side == "l" else self.rside
        if wm.col_idx not in me.key_idx:
            return
        kp = me.key_idx.index(wm.col_idx)
        self._wm[side][kp] = wm.value
        ov = self._wm["r" if side == "l" else "l"].get(kp)
        if ov is None:
            return
        low = min(wm.value, ov)
        prev = self._emitted_wm.get(kp)
        if prev is not None and low <= prev:
            return
        self._emitted_wm[kp] = low
        nl = len(self.left_exec.schema)
        yield Watermark(self.lside.key_idx[kp], wm.dtype, low)
        yield Watermark(nl + self.rside.key_idx[kp], wm.dtype, low)

    # ---- barrier-aligned two-input loop ----------------------------------
    def execute(self) -> Iterator[Message]:
        self.lside.recover()
        self.rside.recover()
        # rebuild the emitted map from recovered state (no emission)
        for group in self.lside.data.values():
            for lpk, lrow in group.items():
                o = self._out_row(lrow)
                if o is not None:
                    self._emitted[lpk] = o
        liter = self.left_exec.execute()
        riter = self.right_exec.execute()
        alive = True
        while alive:
            barrier = None
            for side, it in (("l", liter), ("r", riter)):
                while True:
                    try:
                        msg = next(it)
                    except StopIteration:
                        alive = False
                        break
                    if isinstance(msg, Barrier):
                        barrier = msg
                        break
                    if isinstance(msg, StreamChunk):
                        if msg.cardinality:
                            yield from self._process_chunk(side, msg)
                    elif isinstance(msg, Watermark):
                        yield from self._on_watermark(side, msg)
            if barrier is None:
                return
            for s in (self.lside, self.rside):
                if s.state_table is not None:
                    s.state_table.commit(barrier.epoch.curr)
            yield barrier.with_trace(self.name)
            if barrier.is_stop():
                return
