"""DeviceHashJoinExecutor — the SQL-visible TPU join executor.

The dispatch-seam sibling of `ops/device_agg.py` for the reference's
north-star op (`src/stream/src/executor/hash_join.rs:575-686`): an INNER
equi-join whose match-finding runs as one jitted epoch step over sorted
(join_key, row_id) multimaps in HBM (`device/join_step.py`; sharded with a
two-sided all_to_all via `parallel/sharded_join.py`).

Division of labor:
* device — the quadratic part: per-epoch delta reduce, sorted-multimap
  merge, searchsorted probe, static-shape pair expansion. The state holds
  only (jk_hash, row_hash) per row: payload bytes never cross the PCIe/HBM
  boundary on the ingest path.
* host — row materialization: a row_hash -> row dictionary per side (the
  JoinHashMap cache analog) resolves each emitted pk pair to actual rows.
  Row identity is the hash of the WHOLE row, so an upstream update (U-/U+)
  with changed payload never cancels against itself in the delta reduce.
* exactness — emitted pairs are re-checked host-side for actual join-key
  equality (and non-NULL), so a 64-bit jk-hash collision costs a wasted
  candidate, never a wrong row; a row_hash collision is detected and
  raised (same contract as device/key_codec.DictCodec).

Non-inner join types, and conditions that need degree bookkeeping, stay on
the exact host path (`ops/join.py`) — the planner's seam decides.
"""
from __future__ import annotations

from typing import Any, Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from ..core.chunk import Op, StreamChunk, StreamChunkBuilder
from ..core.schema import Schema
from ..core.vnode import hash_columns64
from ..expr.expression import Expr
from ..state.state_table import StateTable
from .executor import Executor
from .message import Barrier, Message, Watermark


class _RowDict:
    """row_hash -> row with collision detection (one per side)."""

    __slots__ = ("rows",)

    def __init__(self):
        self.rows: Dict[int, Tuple] = {}

    def add(self, h: int, row: Tuple) -> None:
        old = self.rows.get(h)
        if old is None:
            self.rows[h] = row
        elif old != row:
            raise RuntimeError(
                f"64-bit row-identity collision: {old!r} vs {row!r}")

    def get(self, h: int) -> Tuple:
        return self.rows[h]

    def remove(self, h: int) -> None:
        self.rows.pop(h, None)


class DeviceHashJoinExecutor(Executor):
    """TPU-resident INNER equi-join behind the executor protocol."""

    def __init__(self, left: Executor, right: Executor,
                 left_keys: Sequence[int], right_keys: Sequence[int],
                 condition: Optional[Expr] = None,
                 left_state: Optional[StateTable] = None,
                 right_state: Optional[StateTable] = None,
                 mesh: Optional[Any] = None,
                 capacity: int = 1024, pair_capacity: int = 4096,
                 max_chunk_size: int = 1024):
        schema = left.schema.concat(right.schema)
        super().__init__(schema, "DeviceHashJoin")
        # INNER join of append-only inputs never retracts
        self.append_only = left.append_only and right.append_only
        self.left_exec, self.right_exec = left, right
        self.key_idx = {"a": list(left_keys), "b": list(right_keys)}
        self.condition = condition
        self.state_tables = {"a": left_state, "b": right_state}
        self._recovered = left_state is None and right_state is None
        self.max_chunk_size = max_chunk_size
        self.mesh = mesh
        self._capacity = capacity
        self._pair_capacity = pair_capacity
        self.engine: Any = self._make_engine(mesh)
        self.dicts = {"a": _RowDict(), "b": _RowDict()}
        # per-epoch net state-row changes: rh -> (net sign, row). Drives
        # both state-table persistence and row-cache eviction — an entry is
        # evicted only when its NET count is negative, so a delete +
        # re-insert of the same row within one epoch (net zero, row stays
        # live in device state) keeps its cache entry.
        self._epoch_net: Dict[str, Dict[int, Tuple[int, Tuple]]] = \
            {"a": {}, "b": {}}
        # watermark min-alignment on equi-key pairs + state cleaning (same
        # contract as the host HashJoinExecutor)
        self._wm: Dict[str, Dict[int, Any]] = {"a": {}, "b": {}}
        self._emitted_wm: Dict[int, Any] = {}
        self._clean_wm: Dict[int, Any] = {}

    def _make_engine(self, mesh: Optional[Any]) -> Any:
        if mesh is not None:
            from ..parallel.sharded_join import ShardedHashJoin
            return ShardedHashJoin([], [], mesh, capacity=self._capacity,
                                   pair_capacity=self._pair_capacity)
        from ..device.join_step import DeviceHashJoin
        return DeviceHashJoin([], [], capacity=self._capacity,
                              pair_capacity=self._pair_capacity)

    def rescale_mesh(self, mesh: Optional[Any]) -> None:
        """Barrier-boundary elastic rescale: rebuild the engine on the new
        mesh and lazily re-load both sides from the committed state tables
        (the recovery path — join state is fully durable per barrier, so
        re-recovery IS the reshard)."""
        buf = getattr(self.engine, "_buf", None)
        assert not buf or not any(buf.values()), \
            "rescale requires a barrier boundary (buffered rows pending)"
        n_new = mesh.devices.size if mesh is not None else 1
        n_old = self.mesh.devices.size if self.mesh is not None else 1
        if n_new == n_old:
            return
        assert all(st is not None for st in self.state_tables.values()), \
            "join rescale requires state tables (re-recovery reshard)"
        self.mesh = mesh
        self.engine = self._make_engine(mesh)
        self.dicts = {"a": _RowDict(), "b": _RowDict()}
        self._epoch_net = {"a": {}, "b": {}}
        # eager: the execute() generator only checks _recovered at stream
        # start, which already ran — reload both sides now (tables are
        # committed; the caller is at a barrier boundary)
        self._recovered = False
        self._recover()

    # ---- recovery -------------------------------------------------------
    def _recover(self) -> None:
        if self._recovered:
            return
        self._recovered = True
        from ..core.chunk import Column
        for side in ("a", "b"):
            st = self.state_tables[side]
            if st is None:
                continue
            schema = (self.left_exec if side == "a"
                      else self.right_exec).schema
            n = len(schema)
            rows = [tuple(r[:n]) for r in st.iter_all()]
            if not rows:
                continue
            cols = [Column.from_list(f.dtype, [r[i] for r in rows])
                    for i, f in enumerate(schema.fields)]
            rh = hash_columns64(cols).view(np.int64)
            jk = hash_columns64([cols[i] for i in self.key_idx[side]]
                                ).view(np.int64)
            # NULL-keyed rows were never stored (inner-join semantics)
            for h, row in zip(rh.tolist(), rows):
                self.dicts[side].add(h, row)
            self.engine.load_side(side, jk, rh)

    # ---- data plane -----------------------------------------------------
    def _process_chunk(self, side: str, chunk: StreamChunk) -> None:
        chunk = chunk.compact()
        key_cols = [chunk.columns[i] for i in self.key_idx[side]]
        jk = hash_columns64(key_cols).view(np.int64)
        rh = hash_columns64(chunk.columns).view(np.int64)
        signs = chunk.signs()
        # inner-join NULL semantics: a NULL key matches nothing — such rows
        # are neither probed nor stored (hash_join.rs null-checks keys)
        valid = np.ones(chunk.capacity, bool)
        for c in key_cols:
            valid &= c.validity
        rows = chunk.rows()
        net = self._epoch_net[side]
        d = self.dicts[side]
        for i, row in enumerate(rows):
            if not valid[i]:
                continue
            h = int(rh[i])
            if signs[i] > 0:
                d.add(h, row)
                net[h] = (net.get(h, (0, row))[0] + 1, row)
            else:
                net[h] = (net.get(h, (0, row))[0] - 1, row)
        if valid.any():
            sel = np.flatnonzero(valid)
            self.engine.push_rows(side, jk[sel], rh[sel], signs[sel], [])

    def _assemble(self, outs, dels: List[Tuple], ins: List[Tuple]) -> None:
        sign = np.asarray(outs["sign"]).reshape(-1)
        a_pk = np.asarray(outs["a_pk"]).reshape(-1)
        b_pk = np.asarray(outs["b_pk"]).reshape(-1)
        mask = np.asarray(outs["mask"]).reshape(-1)
        live = np.flatnonzero(mask & (sign != 0))
        if len(live) == 0:
            return
        lk, rk = self.key_idx["a"], self.key_idx["b"]
        cond_rows: List[Tuple[int, Tuple]] = []
        for i in live.tolist():
            arow = self.dicts["a"].get(int(a_pk[i]))
            brow = self.dicts["b"].get(int(b_pk[i]))
            # exactness re-check: jk-hash collisions surface as candidates
            # with unequal actual keys — drop them (join on hash AND real
            # equality == join on real equality)
            ok = all(arow[x] == brow[y] and arow[x] is not None
                     for x, y in zip(lk, rk))
            if not ok:
                continue
            cond_rows.append((int(sign[i]), arow + brow))
        if self.condition is not None and cond_rows:
            from ..core.chunk import DataChunk
            ch = DataChunk.from_rows(self.schema.dtypes,
                                     [r for _, r in cond_rows])
            c = self.condition.eval(ch)
            cond_rows = [pr for pr, ok, vl in
                         zip(cond_rows, c.values, c.validity)
                         if vl and ok]
        for s, row in cond_rows:
            (ins if s > 0 else dels).append(row)

    def _on_barrier(self, barrier: Barrier) -> Iterator[Message]:
        o1, o2 = self.engine.flush_epoch()
        out = StreamChunkBuilder(self.schema.dtypes, self.max_chunk_size)
        # An upstream U-/U+ keeps its _row_id, so the retract pair and the
        # replacement pair share one downstream stream key — pair order off
        # the device is hash order, so emit ALL deletes before ALL inserts
        # (at barrier granularity that's the only per-key ordering that
        # matters). Identical rows are NETTED across the whole epoch pair
        # set first: dA><B_old can insert the exact pair that A_new><dB
        # deletes (e.g. both join sides changed under a non-equi
        # condition); emitting that net-zero pair as delete-then-insert
        # would resurrect a row the join no longer contains.
        dels: List[Tuple] = []
        ins: List[Tuple] = []
        self._assemble(o1, dels, ins)
        self._assemble(o2, dels, ins)
        from collections import Counter
        net: Counter = Counter(ins)
        net.subtract(dels)
        for row, c in net.items():
            if c < 0:
                for _ in range(-c):
                    out.append_row(Op.DELETE, row)
        for row, c in net.items():
            if c > 0:
                for _ in range(c):
                    out.append_row(Op.INSERT, row)
        yield from out.drain()
        # state persistence: net row inserts/deletes this epoch
        for side in ("a", "b"):
            st = self.state_tables[side]
            net = self._epoch_net[side]
            for h, (s, row) in net.items():
                if st is not None:
                    if s > 0:
                        st.insert(row + (0,))
                    elif s < 0:
                        st.delete(row + (0,))
                if s < 0:
                    self.dicts[side].remove(h)
            if st is not None:
                st.commit(barrier.epoch.curr)
            net.clear()

    def _on_watermark(self, side: str, wm: Watermark) -> Iterator[Message]:
        """Equi-key watermark min-alignment; non-key watermarks don't
        survive a join (old state rows resurface in the output)."""
        keys = self.key_idx[side]
        if wm.col_idx not in keys:
            return
        kp = keys.index(wm.col_idx)
        self._wm[side][kp] = wm.value
        ov = self._wm["b" if side == "a" else "a"].get(kp)
        if ov is None:
            return
        low = min(wm.value, ov)
        prev = self._emitted_wm.get(kp)
        if prev is not None and low <= prev:
            return
        self._emitted_wm[kp] = low
        self._clean_wm[kp] = low
        nl = len(self.left_exec.schema)
        yield Watermark(self.key_idx["a"][kp], wm.dtype, low)
        yield Watermark(nl + self.key_idx["b"][kp], wm.dtype, low)

    def _clean_state(self) -> None:
        """Drop state rows below the aligned key watermark: filter the host
        row caches, re-install the device multimaps via load_side, delete
        the persisted rows."""
        if not self._clean_wm:
            return
        for side in ("a", "b"):
            key_cols = self.key_idx[side]
            d = self.dicts[side]
            dead = []
            for h, row in d.rows.items():
                for kp, wv in self._clean_wm.items():
                    v = row[key_cols[kp]]
                    if v is not None and v < wv:
                        dead.append(h)
                        break
            if not dead:
                continue
            dead_set = set(dead)
            st = self.state_tables[side]
            for h in dead:
                if st is not None:
                    st.delete(d.rows[h] + (0,))
                d.remove(h)
            jk, pk = self.engine.live_side(side)
            keep = ~np.isin(pk, np.fromiter(dead_set, dtype=np.int64))
            self.engine.load_side(side, jk[keep], pk[keep])
        self._clean_wm.clear()

    # ---- barrier-aligned two-input loop (hash_join.rs:575-686) ----------
    def execute(self) -> Iterator[Message]:
        self._recover()
        liter = self.left_exec.execute()
        riter = self.right_exec.execute()
        alive = True
        while alive:
            barrier = None
            for side, it in (("a", liter), ("b", riter)):
                while True:
                    try:
                        msg = next(it)
                    except StopIteration:
                        alive = False
                        break
                    if isinstance(msg, Barrier):
                        barrier = msg
                        break
                    if isinstance(msg, StreamChunk):
                        if msg.cardinality:
                            self._process_chunk(side, msg)
                    elif isinstance(msg, Watermark):
                        yield from self._on_watermark(side, msg)
            if barrier is None:
                return
            yield from self._on_barrier(barrier)
            self._clean_state()
            yield barrier.with_trace(self.name)
            if barrier.is_stop():
                return
