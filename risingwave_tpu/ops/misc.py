"""Misc stream executors: Changelog, Now, DynamicFilter, watermark Sort.

Reference executors (`src/stream/src/executor/{changelog.rs, now.rs,
dynamic_filter.rs, sort.rs}`) that round out the NodeBody inventory:

* `ChangelogExecutor` — turns a retractable change stream into an
  append-only stream with an explicit `op` column (the CDC-export shape;
  uniqueness of output rows comes from the planner-appended stream key,
  the same contract every append-only stream here carries).
* `NowExecutor` — a one-column source that holds the current barrier
  timestamp, emitting an update pair per (checkpoint) barrier; feeds
  temporal filters.
* `DynamicFilterExecutor` — filter whose RHS is a dynamic scalar from a
  second (single-row) stream: rows cross in/out of the output when the
  bound moves (`WHERE v > (SELECT max(x) FROM m)`).
* `SortExecutor` — watermark-driven reorder: buffer until the event-time
  watermark passes, emit in order below it (EOWC building block).
"""
from __future__ import annotations

import operator
from typing import Any, Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from ..core import dtypes as T
from ..core.chunk import Column, Op, StreamChunk, StreamChunkBuilder
from ..core.schema import Field, Schema
from ..state.state_table import StateTable
from .executor import Executor, UnaryExecutor
from .message import Barrier, Message, Watermark


class ChangelogExecutor(UnaryExecutor):
    """Retractable stream -> append-only changelog (`changelog.rs`):
    every input row becomes an INSERT carrying its original op code.

    With `with_row_id`, the schema additionally declares the hidden
    `_changelog_row_id` column the reference exposes; a downstream
    RowIdGenExecutor mints it (chunks leave here without it)."""

    def __init__(self, input: Executor, op_name: str = "op",
                 with_row_id: bool = False):
        fields = list(input.schema.fields) + [Field(op_name, T.INT32)]
        if with_row_id:
            fields.append(Field("_changelog_row_id", T.SERIAL))
        super().__init__(input, Schema(fields), "Changelog")
        self.append_only = True

    # Internal Op order is INSERT=0, DELETE=1, UPDATE_DELETE=2,
    # UPDATE_INSERT=3; the exported CDC contract (`stream_chunk.rs:84`
    # Op::to_i16) is Insert=1, Delete=2, UpdateInsert=3, UpdateDelete=4.
    _OP_EXPORT = np.array([1, 2, 4, 3], dtype=np.int32)

    def on_chunk(self, chunk: StreamChunk) -> Iterator[Message]:
        chunk = chunk.compact()
        cols = list(chunk.columns)
        cols.append(Column(T.INT32, self._OP_EXPORT[chunk.ops]))
        yield StreamChunk(np.zeros(chunk.capacity, dtype=np.int8), cols)


class NowExecutor(Executor):
    """One-row source holding the barrier timestamp (`now.rs`): emits
    INSERT at the first barrier, then U-/U+ pairs as time advances.
    Epochs encode wall-time; the value is the barrier's epoch time."""

    def __init__(self, barrier_source: Executor,
                 state_table: Optional[StateTable] = None):
        super().__init__(Schema.of(("now", T.TIMESTAMP)), "Now")
        self.barrier_source = barrier_source
        self.state_table = state_table
        self._last: Optional[int] = None
        self._recovered = state_table is None

    def _recover(self) -> None:
        if self._recovered:
            return
        self._recovered = True
        for row in self.state_table.iter_all():
            self._last = row[0]

    def execute(self) -> Iterator[Message]:
        from ..core.epoch import physical_time_ms
        for msg in self.barrier_source.execute():
            if isinstance(msg, Barrier):
                self._recover()
                nowv = physical_time_ms(msg.epoch.curr) * 1000
                # Guard the WHOLE update on strict advance: if the barrier
                # timestamp ever regressed, writing state while emitting
                # nothing would make durable state diverge from what
                # downstream saw (silent backwards jump after recovery).
                if self._last is None or nowv > self._last:
                    if self._last is None:
                        yield StreamChunk.from_rows(
                            self.schema.dtypes, [(Op.INSERT, (nowv,))])
                    else:
                        yield StreamChunk.from_rows(
                            self.schema.dtypes,
                            [(Op.UPDATE_DELETE, (self._last,)),
                             (Op.UPDATE_INSERT, (nowv,))])
                    if self.state_table is not None:
                        if self._last is not None:
                            self.state_table.delete((self._last,))
                        self.state_table.insert((nowv,))
                        self.state_table.commit(msg.epoch.curr)
                    self._last = nowv
                yield Watermark(0, T.TIMESTAMP, self._last)
                yield msg.with_trace(self.name)
            elif isinstance(msg, StreamChunk):
                pass                       # barriers only
            else:
                yield msg


_CMP = {">": operator.gt, ">=": operator.ge,
        "<": operator.lt, "<=": operator.le, "=": operator.eq}


class DynamicFilterExecutor(Executor):
    """`left.col <cmp> right_scalar` where the scalar is a 1-row stream
    (`dynamic_filter.rs`): when the bound moves, previously-passing rows
    retract and newly-passing rows emit from the left state."""

    def __init__(self, left: Executor, right: Executor, key_col: int,
                 cmp: str, state_table: Optional[StateTable] = None):
        super().__init__(left.schema, f"DynamicFilter[{cmp}]")
        self.left_exec, self.right_exec = left, right
        self.key_col = key_col
        self.cmp = _CMP[cmp]
        self.state_table = state_table
        self._bound: Optional[Any] = None
        self._rows: Dict[Tuple, int] = {}     # row -> multiplicity
        self._recovered = state_table is None

    def _recover(self) -> None:
        if self._recovered:
            return
        self._recovered = True
        for row in self.state_table.iter_all():
            r, n = tuple(row[:-1]), row[-1]
            self._rows[r] = self._rows.get(r, 0) + n

    def _passes(self, row: Tuple) -> bool:
        v = row[self.key_col]
        return (v is not None and self._bound is not None
                and self.cmp(v, self._bound))

    def execute(self) -> Iterator[Message]:
        liter = self.left_exec.execute()
        riter = self.right_exec.execute()
        out = StreamChunkBuilder(self.schema.dtypes)
        while True:
            new_bound = self._bound
            # drain right to its barrier, applying ops in order: a DELETE
            # with no re-insert means the scalar became NULL (empty
            # subquery) and the comparison passes nothing
            for msg in riter:
                if isinstance(msg, Barrier):
                    break
                if isinstance(msg, StreamChunk):
                    for op, r in msg.compact().op_rows():
                        if op.is_insert:
                            new_bound = r[0]
                        elif r[0] == new_bound:
                            new_bound = None
            got_left_barrier = False
            for msg in liter:
                if isinstance(msg, Barrier):
                    self._recover()
                    # bound move: diff the stored rows' pass sets
                    if new_bound != self._bound:
                        old = self._bound
                        for row, n in self._rows.items():
                            v = row[self.key_col]
                            if v is None or n <= 0:
                                continue
                            was = old is not None and self.cmp(v, old)
                            now = new_bound is not None \
                                and self.cmp(v, new_bound)
                            if was == now:
                                continue
                            for _ in range(n):
                                out.append_row(
                                    Op.INSERT if now else Op.DELETE, row)
                        self._bound = new_bound
                    for chunk in out.drain():
                        yield chunk
                    if self.state_table is not None:
                        self.state_table.commit(msg.epoch.curr)
                    yield msg.with_trace(self.name)
                    got_left_barrier = True
                    break
                if isinstance(msg, StreamChunk):
                    self._recover()
                    for op, row in msg.compact().op_rows():
                        n0 = self._rows.get(row, 0)
                        n1 = n0 + op.sign
                        if n1 <= 0:
                            self._rows.pop(row, None)   # no dead entries
                        else:
                            self._rows[row] = n1
                        if self.state_table is not None:
                            if n1 <= 0:
                                self.state_table.delete(row + (n0,))
                            else:
                                self.state_table.insert(row + (n1,))
                        if self._passes(row):
                            out.append_row(
                                Op.INSERT if op.is_insert else Op.DELETE,
                                row)
                    for chunk in out.drain():
                        yield chunk
                elif isinstance(msg, Watermark):
                    yield msg
            if not got_left_barrier:
                return


class SortExecutor(UnaryExecutor):
    """Event-time reorder (`sort.rs`): buffer append-only rows, release
    them in sort order once the watermark passes their event time."""

    def __init__(self, input: Executor, time_col: int,
                 state_table: Optional[StateTable] = None):
        super().__init__(input, input.schema, "Sort")
        self.append_only = input.append_only
        self.time_col = time_col
        self.state_table = state_table
        self._buf: List[Tuple] = []
        self._wm: Optional[Any] = None
        self._recovered = state_table is None

    def _recover(self) -> None:
        if self._recovered:
            return
        self._recovered = True
        self._buf.extend(self.state_table.iter_all())

    def on_chunk(self, chunk: StreamChunk) -> Iterator[Message]:
        self._recover()
        for op, row in chunk.compact().op_rows():
            assert op.is_insert, "SortExecutor requires append-only input"
            self._buf.append(row)
            if self.state_table is not None:
                self.state_table.insert(row)
        return iter(())

    def on_watermark(self, wm: Watermark) -> Iterator[Message]:
        if wm.col_idx != self.time_col:
            yield wm              # other columns' watermarks pass through
            return
        self._recover()
        self._wm = wm.value
        ready = sorted((r for r in self._buf
                        if r[self.time_col] is not None
                        and r[self.time_col] <= wm.value),
                       key=lambda r: r[self.time_col])
        if ready:
            self._buf = [r for r in self._buf
                         if r[self.time_col] is None
                         or r[self.time_col] > wm.value]
            for r in ready:
                if self.state_table is not None:
                    self.state_table.delete(r)
            yield StreamChunk.from_rows(
                self.schema.dtypes, [(Op.INSERT, r) for r in ready])
        yield wm

    def on_barrier(self, barrier: Barrier) -> Iterator[Message]:
        if self.state_table is not None:
            self.state_table.commit(barrier.epoch.curr)
        return iter(())
