"""DeviceHashAggExecutor — the SQL-visible TPU aggregation executor.

This is the dispatch seam the reference wires in `from_proto/mod.rs:151-197`
(NodeBody::HashAgg -> HashAggExecutor): the planner lowers an eligible
aggregation fragment onto this executor instead of the per-row host
`HashAggExecutor`. Protocol-identical from the outside — consumes
Chunk|Barrier|Watermark, emits barrier-aligned change chunks, commits its
state table — but the group maintenance runs as ONE jitted XLA program per
epoch (`device/agg_step.py`; sharded over a mesh via
`parallel/sharded_agg.py`).

Exactness contract:
* group keys: lossless bit-packing for narrow keys, hash64 + host decode
  dictionary with collision DETECTION otherwise (`device/key_codec.py`);
* outputs are derived host-side from the raw device payload columns, so
  integer sum/avg keep the exact Decimal semantics of the host path
  (`expr/agg.py`); float aggregation order differs (segment-reduce vs
  arrival order) — the same non-associativity the reference accepts across
  parallel actors;
* recovery: payload columns persist per dirty key per barrier into the
  state table (the `minput.rs` partial-state analog, not opaque pickles).
"""
from __future__ import annotations

from decimal import Decimal
from typing import Any, Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from ..core import dtypes as T
from ..core.chunk import Op, StreamChunk, StreamChunkBuilder
from ..core.dtypes import DataType, TypeKind
from ..core.schema import Field, Schema
from ..expr.agg import AggCall
from ..state.state_table import StateTable
from .executor import Executor, UnaryExecutor
from .message import Barrier, Message, Watermark

_SUMMABLE = (TypeKind.INT16, TypeKind.INT32, TypeKind.INT64, TypeKind.SERIAL,
             TypeKind.FLOAT32, TypeKind.FLOAT64)


def _spec_kinds(calls: Sequence[AggCall]) -> List[str]:
    """Host AggCall kinds -> device spec kinds (count(*) has arg None)."""
    return ["count_star" if c.kind == "count" and c.arg is None else c.kind
            for c in calls]


def device_agg_eligible(calls: Sequence[AggCall],
                        include_minmax: bool = True,
                        append_only: bool = False) -> bool:
    """Can this aggregation fragment run on the device path?

    count/sum/avg are exact under retraction; min/max are exact via the
    sorted-multiset side state (`device/minput.py`, the `minput.rs`
    analog) — or, over an append-only input, via a single monotone extreme
    column (the reference's append-only agg specialization,
    `aggregate/agg_impl.rs`), which needs no side state at all.
    DISTINCT/filtered calls and exotic kinds stay on the exact host path.
    """
    for c in calls:
        if c.distinct or c.filter is not None:
            return False
        if c.kind == "count":
            continue                      # needs only the validity mask
        if c.kind in ("sum", "avg"):
            if c.arg is None or c.arg.return_type.kind not in _SUMMABLE:
                return False
        elif c.kind in ("min", "max"):
            if not (include_minmax or append_only) or c.arg is None:
                return False
            rt = c.arg.return_type
            if rt.device_dtype is None or rt.kind == TypeKind.BOOLEAN:
                return False
        else:
            return False
    return True


def _build_sql_spec(calls: Sequence[AggCall], append_only: bool = False):
    """The device spec for these calls. Retractable (SQL default) unless
    the input fragment is append-only; retractable min/max over the same
    input column (InputRef) share one multiset."""
    from ..device.agg_step import DeviceAggSpec
    from ..expr.expression import InputRef
    arg_ids = [("ref", c.arg.index) if isinstance(c.arg, InputRef)
               else ("call", i) for i, c in enumerate(calls)]
    return DeviceAggSpec.build(_spec_kinds(calls),
                               [_arg_np_dtype(c) for c in calls],
                               append_only=append_only, arg_ids=arg_ids)


def device_payload_dtypes(calls: Sequence[AggCall],
                          append_only: bool = False) -> List[DataType]:
    """SQL dtypes of the persisted device payload columns (state-table
    layout; must match DeviceAggSpec.build's column order)."""
    spec = _build_sql_spec(calls, append_only)
    out = []
    for d in spec.dtypes:
        out.append(T.FLOAT64 if np.issubdtype(np.dtype(d), np.floating)
                   else T.INT64)
    return out


def device_minput_count(calls: Sequence[AggCall],
                        append_only: bool = False) -> int:
    """How many minput side tables the executor persists (one per
    retractable min/max call): rows are (group..., encoded value, count)."""
    return len(_build_sql_spec(calls, append_only).minputs)


def _arg_np_dtype(c: AggCall):
    if c.arg is None or c.arg.return_type.device_dtype is None:
        return np.int64
    dt = np.dtype(c.arg.return_type.device_dtype)
    return np.float64 if np.issubdtype(dt, np.floating) else np.int64


class DeviceHashAggExecutor(UnaryExecutor):
    """TPU-resident group-by aggregation behind the executor protocol."""

    def __init__(self, input: Executor, group_key_indices: Sequence[int],
                 calls: Sequence[AggCall],
                 state_table: Optional[StateTable] = None,
                 minput_tables: Sequence[StateTable] = (),
                 mesh: Optional[Any] = None, capacity: int = 1024,
                 append_only: bool = False):
        in_schema = input.schema
        fields = [in_schema.fields[i] for i in group_key_indices]
        fields += [Field(f"agg#{i}", c.return_type)
                   for i, c in enumerate(calls)]
        super().__init__(input, Schema(fields), "DeviceHashAgg")
        self.group_key_indices = list(group_key_indices)
        self.calls = list(calls)
        self.state_table = state_table
        self.minput_tables = list(minput_tables)
        self._recovered = state_table is None
        self._key_dtypes = [in_schema.fields[i].dtype
                            for i in group_key_indices]
        self._clean_wm: Optional[Tuple[int, Any]] = None
        self.input_append_only = append_only

        from ..device.key_codec import make_codec
        self.spec = _build_sql_spec(calls, append_only)
        assert len(self.minput_tables) in (0, len(self.spec.minputs)), \
            "one minput state table per retractable min/max call"
        # call_idx -> is the minput value order-encoded from floats?
        self._minput_float = {
            ci: np.issubdtype(
                np.dtype(calls[ci].arg.return_type.device_dtype),
                np.floating)
            for ci, dc in enumerate(self.spec.calls) if dc.minput is not None}
        self.codec = make_codec(self._key_dtypes)
        # int64 accumulator overflow guard: running bound on the total
        # absolute magnitude ever pushed into integer sum columns. The host
        # path accumulates in unbounded Decimal; the device wraps at 2^63.
        # The bound is conservative (ignores retraction cancellation), so
        # staying under 2^62 PROVES no wrap occurred; crossing it fails
        # loudly instead of silently diverging.
        self._int_sum_bound = 0
        self._int_sum_calls = [i for i, (c, dc) in
                               enumerate(zip(calls, self.spec.calls))
                               if c.kind in ("sum", "avg")
                               and not np.issubdtype(
                                   np.dtype(dc.acc_dtype), np.floating)]
        self.mesh = mesh
        if mesh is not None:
            from ..parallel.sharded_agg import ShardedHashAgg
            self.engine: Any = ShardedHashAgg(self.spec, mesh,
                                              capacity=capacity)
        else:
            from ..device.agg_step import DeviceHashAgg
            self.engine = DeviceHashAgg(self.spec, capacity=capacity)

    # ---- recovery -------------------------------------------------------
    def _recover(self) -> None:
        if self._recovered:
            return
        self._recovered = True
        nk = len(self.group_key_indices)
        rows = list(self.state_table.iter_all())
        if rows:
            key_rows = [r[:nk] for r in rows]
            keys = self.codec.encode_rows(key_rows)
            self.codec.observe_rows(keys, key_rows)
            vals = []
            for j, d in enumerate(self.spec.dtypes):
                npd = (np.float64 if np.issubdtype(np.dtype(d), np.floating)
                       else np.int64)
                vals.append(np.array([r[nk + j] for r in rows], dtype=npd))
            self.engine.load_state(keys, vals)
        for mi, tbl in enumerate(self.minput_tables):
            mrows = list(tbl.iter_all())
            if not mrows:
                continue
            key_rows = [r[:nk] for r in mrows]
            k1 = self.codec.encode_rows(key_rows)
            self.codec.observe_rows(k1, key_rows)
            k2 = np.array([r[nk] for r in mrows], dtype=np.int64)
            cnt = np.array([r[nk + 1] for r in mrows], dtype=np.int64)
            self.engine.load_minput(mi, k1, k2, cnt)

    # ---- data plane -----------------------------------------------------
    def on_chunk(self, chunk: StreamChunk) -> Iterator[Message]:
        self._recover()
        chunk = chunk.compact()
        data = chunk.data_chunk()
        key_cols = [chunk.columns[i] for i in self.group_key_indices]
        keys = self.codec.encode_columns(key_cols)
        self.codec.observe_columns(keys, key_cols)
        inputs = []
        for ci, c in enumerate(self.calls):
            if c.arg is None:
                z = np.zeros(chunk.capacity, np.int64)
                inputs.append((z, np.ones(chunk.capacity, bool)))
                continue
            col = c.arg.eval(data)
            if self.spec.calls[ci].minput is not None:
                # minput value: order-preserving int64 encoding (floats via
                # order_encode). No sentinel remap — multiset padding is
                # discriminated by the GROUP key (k1) alone, so a value
                # equal to int64 max is legitimate and preserved exactly.
                from ..device.minput import order_encode_f64
                if self._minput_float[ci]:
                    enc = order_encode_f64(col.values.astype(np.float64))
                else:
                    enc = col.values.astype(np.int64, copy=False)
                vals = np.where(col.validity, enc, 0)
                inputs.append((vals.astype(np.int64), col.validity))
                continue
            npd = _arg_np_dtype(c)
            vals = col.values.astype(npd, copy=False) \
                if col.dtype.np_dtype != np.dtype(object) \
                else np.zeros(chunk.capacity, npd)
            vals = np.where(col.validity, vals, 0).astype(npd)
            inputs.append((vals, col.validity))
        for ci in self._int_sum_calls:
            v = inputs[ci][0]
            # float64 magnitude estimate with multiplicative slack covers
            # its rounding error; the 2x headroom to 2^63 does the rest
            self._int_sum_bound += int(
                np.abs(v.astype(np.float64)).sum() * 1.000001) + 1
            if self._int_sum_bound >= 1 << 62:
                raise OverflowError(
                    "device integer sum accumulator cannot prove no-wrap "
                    "(total pushed magnitude >= 2^62); run this query with "
                    "device='off' for unbounded Decimal accumulation")
        self.engine.push_rows(keys, chunk.signs(), inputs)
        return iter(())

    # ---- output derivation (exact host semantics from raw payloads) ----
    def _format_row(self, vals: Sequence[np.ndarray], i: int,
                    mm: Optional[Dict[int, np.ndarray]] = None) -> Tuple:
        out: List[Any] = []
        for ci, (call, dc) in enumerate(zip(self.calls, self.spec.calls)):
            rt = call.return_type
            if call.kind == "count":
                out.append(int(vals[dc.cols[0]][i]))
                continue
            if call.kind in ("sum", "avg"):
                acc = vals[dc.cols[0]][i]
                n = int(vals[dc.cols[1]][i])
                if n <= 0:
                    out.append(None)
                elif call.kind == "sum":
                    if rt.kind == TypeKind.DECIMAL:
                        out.append(Decimal(int(acc)))
                    elif rt.kind in (TypeKind.FLOAT32, TypeKind.FLOAT64):
                        out.append(float(acc))
                    else:
                        out.append(int(acc))
                else:  # avg
                    if rt.kind == TypeKind.DECIMAL:
                        out.append(Decimal(int(acc)) / Decimal(n))
                    else:
                        out.append(float(acc) / n)
            elif dc.minput is not None:
                # retractable min/max: extreme from the multiset changes
                n = int(vals[dc.cols[0]][i])
                if n <= 0 or mm is None:
                    out.append(None)
                else:
                    enc = int(mm[ci][i])
                    if self._minput_float[ci]:
                        from ..device.minput import order_decode_f64
                        out.append(float(order_decode_f64(
                            np.array([enc], dtype=np.int64))[0]))
                    else:
                        out.append(enc)
            else:  # min / max, append-only: monotone extreme column
                n = int(vals[dc.cols[1]][i])
                if n <= 0:
                    out.append(None)
                elif np.issubdtype(np.dtype(dc.acc_dtype), np.floating):
                    out.append(float(vals[dc.cols[0]][i]))
                else:
                    out.append(int(vals[dc.cols[0]][i]))
        return tuple(out)

    def on_barrier(self, barrier: Barrier) -> Iterator[Message]:
        self._recover()
        ch = self.engine.flush_epoch()
        if ch is not None:
            yield from self._emit_changes(ch, barrier)
        self._clean_state()
        if self.state_table is not None:
            self.state_table.commit(barrier.epoch.curr)
        for tbl in self.minput_tables:
            tbl.commit(barrier.epoch.curr)

    def _emit_changes(self, ch: Dict[str, Any],
                      barrier: Barrier) -> Iterator[Message]:
        from ..device.sorted_state import EMPTY_KEY
        keys = np.asarray(ch["keys"]).reshape(-1)
        old_found = np.asarray(ch["old_found"]).reshape(-1)
        new_found = np.asarray(ch["new_found"]).reshape(-1)
        old_vals = [np.asarray(v).reshape(-1) for v in ch["old_vals"]]
        new_vals = [np.asarray(v).reshape(-1) for v in ch["new_vals"]]
        live = (keys != EMPTY_KEY) & (old_found | new_found)
        idxs = np.flatnonzero(live)
        if len(idxs) == 0:
            return
        # per-call extreme arrays (encoded) for old/new formatting; min and
        # max calls over one column read opposite ends of a shared multiset
        mm_old: Dict[int, np.ndarray] = {}
        mm_new: Dict[int, np.ndarray] = {}
        for ci, dc in enumerate(self.spec.calls):
            if dc.minput is None:
                continue
            sub = ch[f"minput{dc.minput}"]
            which = ("old_max", "new_max") if self.calls[ci].kind == "max" \
                else ("old_min", "new_min")
            mm_old[ci] = np.asarray(sub[which[0]]).reshape(-1)
            mm_new[ci] = np.asarray(sub[which[1]]).reshape(-1)
        key_tuples = self.codec.decode(keys[idxs])
        out = StreamChunkBuilder(self.schema.dtypes)
        for i, kt in zip(idxs.tolist(), key_tuples):
            of, nf = bool(old_found[i]), bool(new_found[i])
            if nf:
                new_row = kt + self._format_row(new_vals, i, mm_new)
            if of and nf:
                old_row = kt + self._format_row(old_vals, i, mm_old)
                if old_row != new_row:
                    out.append_update(old_row, new_row)
                self._persist(kt, new_vals, i)
            elif nf:
                out.append_row(Op.INSERT, new_row)
                self._persist(kt, new_vals, i)
            else:  # group died this epoch
                out.append_row(Op.DELETE,
                               kt + self._format_row(old_vals, i, mm_old))
                if self.state_table is not None:
                    self.state_table.delete(
                        kt + tuple(self._payload_tuple(old_vals, i)))
        self._persist_minputs(ch)
        dead = idxs[old_found[idxs] & ~new_found[idxs]]
        if len(dead):
            self.codec.forget(keys[dead])
        for chunk in out.drain():
            yield chunk

    def _persist_minputs(self, ch: Dict[str, Any]) -> None:
        """Upsert/delete the touched (group, value, count) multiset pairs
        into the per-minput state tables (decode before dead-key forget)."""
        if not self.minput_tables:
            return
        from ..device.sorted_state import EMPTY_KEY
        for mi in range(len(self.spec.minputs)):
            sub = ch[f"minput{mi}"]
            u1 = np.asarray(sub["u1"]).reshape(-1)
            u2 = np.asarray(sub["u2"]).reshape(-1)
            uc = np.asarray(sub["u_cnt"]).reshape(-1)
            sel = np.flatnonzero(u1 != EMPTY_KEY)
            if len(sel) == 0:
                continue
            gts = self.codec.decode(u1[sel])
            tbl = self.minput_tables[mi]
            for j, gt in zip(sel.tolist(), gts):
                row = gt + (int(u2[j]), int(uc[j]))
                if uc[j] == 0:
                    tbl.delete(row)
                else:
                    tbl.insert(row)

    def _payload_tuple(self, vals: Sequence[np.ndarray], i: int) -> List[Any]:
        out = []
        for d, v in zip(self.spec.dtypes, vals):
            out.append(float(v[i]) if np.issubdtype(np.dtype(d), np.floating)
                       else int(v[i]))
        return out

    def _persist(self, kt: Tuple, vals: Sequence[np.ndarray], i: int) -> None:
        if self.state_table is not None:
            self.state_table.insert(kt + tuple(self._payload_tuple(vals, i)))

    # ---- watermark state cleaning (state_table.rs:1002 analog) ----------
    def _clean_state(self) -> None:
        """Drop groups proven final by a group-key watermark: filter the
        live device rows host-side and re-install via load_state /
        load_minput (no retraction — the MV keeps the rows)."""
        if self._clean_wm is None:
            return
        gi, wv = self._clean_wm
        self._clean_wm = None
        keys, vals = self.engine.live_main()
        if len(keys) == 0:
            return
        tuples = self.codec.decode(keys)
        drop = np.array([t[gi] is not None and t[gi] < wv for t in tuples])
        if not drop.any():
            return
        keep = ~drop
        self.engine.load_state(keys[keep], [v[keep] for v in vals])
        dropped = set(keys[drop].tolist())
        for mi in range(len(self.spec.minputs)):
            k1, k2, cnt = self.engine.live_minput(mi)
            mdrop = np.isin(k1, keys[drop])
            self.engine.load_minput(mi, k1[~mdrop], k2[~mdrop], cnt[~mdrop])
            if mi < len(self.minput_tables):
                tbl = self.minput_tables[mi]
                gts = self.codec.decode(k1[mdrop])
                for gt, v in zip(gts, k2[mdrop].tolist()):
                    tbl.delete(gt + (int(v), 0))
        if self.state_table is not None:
            zeros = tuple(0.0 if np.issubdtype(np.dtype(d), np.floating)
                          else 0 for d in self.spec.dtypes)
            for i in np.flatnonzero(drop).tolist():
                self.state_table.delete(tuples[i] + zeros)
        self.codec.forget(np.fromiter(dropped, dtype=np.int64))

    def on_watermark(self, wm: Watermark) -> Iterator[Message]:
        if wm.col_idx in self.group_key_indices:
            gi = self.group_key_indices.index(wm.col_idx)
            self._clean_wm = (gi, wm.value)
            yield Watermark(gi, wm.dtype, wm.value)
