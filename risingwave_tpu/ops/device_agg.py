"""DeviceHashAggExecutor — the SQL-visible TPU aggregation executor.

This is the dispatch seam the reference wires in `from_proto/mod.rs:151-197`
(NodeBody::HashAgg -> HashAggExecutor): the planner lowers an eligible
aggregation fragment onto this executor instead of the per-row host
`HashAggExecutor`. Protocol-identical from the outside — consumes
Chunk|Barrier|Watermark, emits barrier-aligned change chunks, commits its
state table — but the group maintenance runs as ONE jitted XLA program per
epoch (`device/agg_step.py`; sharded over a mesh via
`parallel/sharded_agg.py`).

Exactness contract:
* group keys: lossless bit-packing for narrow keys, hash64 + host decode
  dictionary with collision DETECTION otherwise (`device/key_codec.py`);
* outputs are derived host-side from the raw device payload columns, so
  integer sum/avg keep the exact Decimal semantics of the host path
  (`expr/agg.py`); float aggregation order differs (segment-reduce vs
  arrival order) — the same non-associativity the reference accepts across
  parallel actors;
* recovery: payload columns persist per dirty key per barrier into the
  state table (the `minput.rs` partial-state analog, not opaque pickles).
"""
from __future__ import annotations

from decimal import Decimal
from typing import Any, Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from ..core import dtypes as T
from ..core.chunk import Op, StreamChunk, StreamChunkBuilder
from ..core.dtypes import DataType, TypeKind
from ..core.schema import Field, Schema
from ..expr.agg import AggCall
from ..state.state_table import StateTable
from .executor import Executor, UnaryExecutor
from .message import Barrier, Message, Watermark

_SUMMABLE = (TypeKind.INT16, TypeKind.INT32, TypeKind.INT64, TypeKind.SERIAL,
             TypeKind.FLOAT32, TypeKind.FLOAT64)


def _spec_kinds(calls: Sequence[AggCall]) -> List[str]:
    """Host AggCall kinds -> device spec kinds (count(*) has arg None)."""
    return ["count_star" if c.kind == "count" and c.arg is None else c.kind
            for c in calls]


def device_agg_eligible(calls: Sequence[AggCall],
                        include_minmax: bool = True,
                        append_only: bool = False) -> bool:
    """Can this aggregation fragment run on the device path?

    count/sum/avg are exact under retraction; min/max are exact via the
    sorted-multiset side state (`device/minput.py`, the `minput.rs`
    analog) — or, over an append-only input, via a single monotone extreme
    column (the reference's append-only agg specialization,
    `aggregate/agg_impl.rs`), which needs no side state at all.
    DISTINCT/filtered calls and exotic kinds stay on the exact host path.
    """
    for c in calls:
        if c.distinct or c.filter is not None:
            return False
        if c.kind == "count":
            continue                      # needs only the validity mask
        if c.kind in ("sum", "avg"):
            if c.arg is None or c.arg.return_type.kind not in _SUMMABLE:
                return False
        elif c.kind in ("min", "max"):
            if not (include_minmax or append_only) or c.arg is None:
                return False
            rt = c.arg.return_type
            if rt.device_dtype is None or rt.kind == TypeKind.BOOLEAN:
                return False
        else:
            return False
    return True


def _build_sql_spec(calls: Sequence[AggCall], append_only: bool = False):
    """The device spec for these calls. Retractable (SQL default) unless
    the input fragment is append-only; retractable min/max over the same
    input column (InputRef) share one multiset."""
    from ..device.agg_step import DeviceAggSpec
    from ..expr.expression import InputRef
    arg_ids = [("ref", c.arg.index) if isinstance(c.arg, InputRef)
               else ("call", i) for i, c in enumerate(calls)]
    return DeviceAggSpec.build(_spec_kinds(calls),
                               [_arg_np_dtype(c) for c in calls],
                               append_only=append_only, arg_ids=arg_ids)


def device_payload_dtypes(calls: Sequence[AggCall],
                          append_only: bool = False) -> List[DataType]:
    """SQL dtypes of the persisted device payload columns (state-table
    layout; must match DeviceAggSpec.build's column order)."""
    spec = _build_sql_spec(calls, append_only)
    out = []
    for d in spec.dtypes:
        out.append(T.FLOAT64 if np.issubdtype(np.dtype(d), np.floating)
                   else T.INT64)
    return out


def device_minput_count(calls: Sequence[AggCall],
                        append_only: bool = False) -> int:
    """How many minput side tables the executor persists (one per
    retractable min/max call): rows are (group..., encoded value, count)."""
    return len(_build_sql_spec(calls, append_only).minputs)


def _arg_np_dtype(c: AggCall):
    if c.arg is None or c.arg.return_type.device_dtype is None:
        return np.int64
    dt = np.dtype(c.arg.return_type.device_dtype)
    return np.float64 if np.issubdtype(dt, np.floating) else np.int64


class DeviceHashAggExecutor(UnaryExecutor):
    """TPU-resident group-by aggregation behind the executor protocol."""

    def __init__(self, input: Executor, group_key_indices: Sequence[int],
                 calls: Sequence[AggCall],
                 state_table: Optional[StateTable] = None,
                 minput_tables: Sequence[StateTable] = (),
                 mesh: Optional[Any] = None, capacity: int = 1024,
                 append_only: bool = False):
        in_schema = input.schema
        fields = [in_schema.fields[i] for i in group_key_indices]
        fields += [Field(f"agg#{i}", c.return_type)
                   for i, c in enumerate(calls)]
        super().__init__(input, Schema(fields), "DeviceHashAgg")
        self.group_key_indices = list(group_key_indices)
        self.calls = list(calls)
        self.state_table = state_table
        self.minput_tables = list(minput_tables)
        self._recovered = state_table is None
        self._key_dtypes = [in_schema.fields[i].dtype
                            for i in group_key_indices]
        self._clean_wm: Optional[Tuple[int, Any]] = None
        self.input_append_only = append_only

        from ..device.key_codec import make_codec
        self.spec = _build_sql_spec(calls, append_only)
        assert len(self.minput_tables) in (0, len(self.spec.minputs)), \
            "one minput state table per retractable min/max call"
        # call_idx -> is the minput value order-encoded from floats?
        self._minput_float = {
            ci: np.issubdtype(
                np.dtype(calls[ci].arg.return_type.device_dtype),
                np.floating)
            for ci, dc in enumerate(self.spec.calls) if dc.minput is not None}
        self.codec = make_codec(self._key_dtypes)
        # int64 accumulator overflow guard: running bound on the total
        # absolute magnitude ever pushed into integer sum columns. The host
        # path accumulates in unbounded Decimal; the device wraps at 2^63.
        # The bound is conservative (ignores retraction cancellation), so
        # staying under 2^62 PROVES no wrap occurred; crossing it fails
        # loudly instead of silently diverging.
        self._int_sum_bound = 0
        self._int_sum_calls = [i for i, (c, dc) in
                               enumerate(zip(calls, self.spec.calls))
                               if c.kind in ("sum", "avg")
                               and not np.issubdtype(
                                   np.dtype(dc.acc_dtype), np.floating)]
        self.mesh = mesh
        self._capacity = capacity
        self.engine: Any = self._make_engine(mesh, capacity)

    def _make_engine(self, mesh: Optional[Any], capacity: int) -> Any:
        if mesh is not None:
            from ..parallel.sharded_agg import ShardedHashAgg
            return ShardedHashAgg(self.spec, mesh, capacity=capacity,
                                  pull_formatted=False)
        from ..device.agg_step import DeviceHashAgg
        return DeviceHashAgg(self.spec, capacity=capacity,
                             pull_formatted=False)

    def rescale_mesh(self, mesh: Optional[Any]) -> None:
        """Barrier-boundary elastic rescale (`scale.rs:2329` analog):
        lift the live device state off the old mesh and re-install it
        vnode-sharded onto the new one (None = single chip). The caller
        (Database._alter_parallelism) guarantees the in-flight barrier
        committed, so the epoch buffers are empty."""
        assert not getattr(self.engine, "_keys", None) \
            and not getattr(self.engine, "_rows", None), \
            "rescale requires a barrier boundary (buffered rows pending)"
        n_new = mesh.devices.size if mesh is not None else 1
        n_old = self.mesh.devices.size if self.mesh is not None else 1
        if n_new == n_old:
            return
        keys, vals = self.engine.live_main()
        minputs = [self.engine.live_minput(mi)
                   for mi in range(len(self.spec.minputs))]
        self.mesh = mesh
        self.engine = self._make_engine(mesh, self._capacity)
        if len(keys):
            self.engine.load_state(keys, vals)
        for mi, (k1, k2, cnt) in enumerate(minputs):
            if len(k1):
                self.engine.load_minput(mi, k1, k2, cnt)

    # ---- recovery -------------------------------------------------------
    def _recover(self) -> None:
        if self._recovered:
            return
        self._recovered = True
        nk = len(self.group_key_indices)
        rows = list(self.state_table.iter_all())
        if rows:
            key_rows = [r[:nk] for r in rows]
            keys = self.codec.encode_rows(key_rows)
            self.codec.observe_rows(keys, key_rows)
            vals = []
            for j, d in enumerate(self.spec.dtypes):
                npd = (np.float64 if np.issubdtype(np.dtype(d), np.floating)
                       else np.int64)
                vals.append(np.array([r[nk + j] for r in rows], dtype=npd))
            self.engine.load_state(keys, vals)
        for mi, tbl in enumerate(self.minput_tables):
            mrows = list(tbl.iter_all())
            if not mrows:
                continue
            key_rows = [r[:nk] for r in mrows]
            k1 = self.codec.encode_rows(key_rows)
            self.codec.observe_rows(k1, key_rows)
            k2 = np.array([r[nk] for r in mrows], dtype=np.int64)
            cnt = np.array([r[nk + 1] for r in mrows], dtype=np.int64)
            self.engine.load_minput(mi, k1, k2, cnt)

    # ---- data plane -----------------------------------------------------
    def on_chunk(self, chunk: StreamChunk) -> Iterator[Message]:
        self._recover()
        chunk = chunk.compact()
        data = chunk.data_chunk()
        key_cols = [chunk.columns[i] for i in self.group_key_indices]
        keys = self.codec.encode_columns(key_cols)
        self.codec.observe_columns(keys, key_cols)
        inputs = []
        for ci, c in enumerate(self.calls):
            if c.arg is None:
                z = np.zeros(chunk.capacity, np.int64)
                inputs.append((z, np.ones(chunk.capacity, bool)))
                continue
            col = c.arg.eval(data)
            if self.spec.calls[ci].minput is not None:
                # minput value: order-preserving int64 encoding (floats via
                # order_encode). No sentinel remap — multiset padding is
                # discriminated by the GROUP key (k1) alone, so a value
                # equal to int64 max is legitimate and preserved exactly.
                from ..device.minput import order_encode_f64
                if self._minput_float[ci]:
                    enc = order_encode_f64(col.values.astype(np.float64))
                else:
                    enc = col.values.astype(np.int64, copy=False)
                vals = np.where(col.validity, enc, 0)
                inputs.append((vals.astype(np.int64), col.validity))
                continue
            npd = _arg_np_dtype(c)
            vals = col.values.astype(npd, copy=False) \
                if col.dtype.np_dtype != np.dtype(object) \
                else np.zeros(chunk.capacity, npd)
            vals = np.where(col.validity, vals, 0).astype(npd)
            inputs.append((vals, col.validity))
        for ci in self._int_sum_calls:
            v = inputs[ci][0]
            # float64 magnitude estimate with multiplicative slack covers
            # its rounding error; the 2x headroom to 2^63 does the rest
            self._int_sum_bound += int(
                np.abs(v.astype(np.float64)).sum() * 1.000001) + 1
            if self._int_sum_bound >= 1 << 62:
                raise OverflowError(
                    "device integer sum accumulator cannot prove no-wrap "
                    "(total pushed magnitude >= 2^62); run this query with "
                    "device='off' for unbounded Decimal accumulation")
        self.engine.push_rows(keys, chunk.signs(), inputs)
        return iter(())

    def on_barrier(self, barrier: Barrier) -> Iterator[Message]:
        self._recover()
        ch = self.engine.flush_epoch()
        if ch is not None:
            yield from self._emit_changes(ch, barrier)
        self._clean_state()
        if self.state_table is not None:
            self.state_table.commit(barrier.epoch.curr)
        for tbl in self.minput_tables:
            tbl.commit(barrier.epoch.curr)

    def _format_columns(self, vals: Sequence[np.ndarray], idxs: np.ndarray,
                        mm: Optional[Dict[int, np.ndarray]]
                        ) -> Tuple[List[np.ndarray], List[np.ndarray]]:
        """Vectorized `_format_row` over the selected state rows: per call,
        (values array in the output column's numpy dtype, validity mask).
        Only DECIMAL outputs pay a per-row conversion (object columns)."""
        outs: List[np.ndarray] = []
        valids: List[np.ndarray] = []
        n = len(idxs)
        for ci, (call, dc) in enumerate(zip(self.calls, self.spec.calls)):
            rt = call.return_type
            if call.kind == "count":
                outs.append(vals[dc.cols[0]][idxs].astype(np.int64))
                valids.append(np.ones(n, dtype=bool))
                continue
            if call.kind in ("sum", "avg"):
                acc = vals[dc.cols[0]][idxs]
                cnt = vals[dc.cols[1]][idxs].astype(np.int64)
                valid = cnt > 0
                if rt.kind == TypeKind.DECIMAL:
                    v = np.empty(n, dtype=object)
                    for j in np.flatnonzero(valid).tolist():
                        d = Decimal(int(acc[j]))
                        v[j] = d if call.kind == "sum" \
                            else d / Decimal(int(cnt[j]))
                elif call.kind == "sum":
                    v = acc.astype(rt.np_dtype)
                else:
                    v = (acc.astype(np.float64)
                         / np.where(valid, cnt, 1)).astype(rt.np_dtype)
                outs.append(v)
                valids.append(valid)
            elif dc.minput is not None:
                # retractable min/max: extreme from the multiset changes
                cnt = vals[dc.cols[0]][idxs].astype(np.int64)
                valid = cnt > 0
                if mm is None:
                    valid = np.zeros(n, dtype=bool)
                    enc = np.zeros(n, dtype=np.int64)
                else:
                    enc = mm[ci][idxs]
                if self._minput_float[ci]:
                    from ..device.minput import order_decode_f64
                    outs.append(order_decode_f64(enc).astype(rt.np_dtype))
                else:
                    outs.append(enc.astype(rt.np_dtype))
                valids.append(valid)
            else:  # min / max, append-only: monotone extreme column
                cnt = vals[dc.cols[1]][idxs].astype(np.int64)
                outs.append(vals[dc.cols[0]][idxs].astype(rt.np_dtype))
                valids.append(cnt > 0)
        return outs, valids

    @staticmethod
    def _interleave(old: np.ndarray, new: np.ndarray) -> np.ndarray:
        out = np.empty(2 * len(old), dtype=old.dtype)
        out[0::2] = old
        out[1::2] = new
        return out

    def _emit_changes(self, ch: Dict[str, Any],
                      barrier: Barrier) -> Iterator[Message]:
        from ..device.sorted_state import EMPTY_KEY
        keys = np.asarray(ch["keys"]).reshape(-1)
        old_found = np.asarray(ch["old_found"]).reshape(-1)
        new_found = np.asarray(ch["new_found"]).reshape(-1)
        old_vals = [np.asarray(v).reshape(-1) for v in ch["old_vals"]]
        new_vals = [np.asarray(v).reshape(-1) for v in ch["new_vals"]]
        live = (keys != EMPTY_KEY) & (old_found | new_found)
        idxs = np.flatnonzero(live)
        if len(idxs) == 0:
            return
        # per-call extreme arrays (encoded) for old/new formatting; min and
        # max calls over one column read opposite ends of a shared multiset
        mm_old: Dict[int, np.ndarray] = {}
        mm_new: Dict[int, np.ndarray] = {}
        for ci, dc in enumerate(self.spec.calls):
            if dc.minput is None:
                continue
            sub = ch[f"minput{dc.minput}"]
            which = ("old_max", "new_max") if self.calls[ci].kind == "max" \
                else ("old_min", "new_min")
            mm_old[ci] = np.asarray(sub[which[0]]).reshape(-1)
            mm_new[ci] = np.asarray(sub[which[1]]).reshape(-1)
        of = old_found[idxs]
        nf = new_found[idxs]
        key_cols = self.codec.decode_columns(keys[idxs])
        new_cols, new_valid = self._format_columns(new_vals, idxs, mm_new)
        old_cols, old_valid = self._format_columns(old_vals, idxs, mm_old)
        upd = of & nf
        ins = nf & ~of
        dead = of & ~nf
        # suppress no-op updates (old row == new row, NaN-strict like the
        # host tuple compare: NaN != NaN keeps the update)
        if upd.any():
            same = upd.copy()
            for ov, ovl, nv, nvl in zip(old_cols, old_valid,
                                        new_cols, new_valid):
                with np.errstate(invalid="ignore"):
                    eq = (ov == nv) & ovl & nvl | (~ovl & ~nvl)
                same &= np.asarray(eq, dtype=bool)
            upd &= ~same
        u_ix = np.flatnonzero(upd)
        i_ix = np.flatnonzero(ins)
        d_ix = np.flatnonzero(dead)
        n_out = 2 * len(u_ix) + len(i_ix) + len(d_ix)
        if n_out:
            ops = np.concatenate([
                np.tile(np.array([Op.UPDATE_DELETE, Op.UPDATE_INSERT],
                                 dtype=np.int8), len(u_ix)),
                np.full(len(i_ix), Op.INSERT, dtype=np.int8),
                np.full(len(d_ix), Op.DELETE, dtype=np.int8)])
            out_cols: List[Any] = []
            from ..core.chunk import Column
            nk = len(self.group_key_indices)
            for c in key_cols:
                vv = np.concatenate([self._interleave(c.values[u_ix],
                                                      c.values[u_ix]),
                                     c.values[i_ix], c.values[d_ix]])
                vl = np.concatenate([self._interleave(c.validity[u_ix],
                                                      c.validity[u_ix]),
                                     c.validity[i_ix], c.validity[d_ix]])
                out_cols.append(Column(self._key_dtypes[len(out_cols)],
                                       vv, vl))
            for j in range(len(self.calls)):
                vv = np.concatenate([self._interleave(old_cols[j][u_ix],
                                                      new_cols[j][u_ix]),
                                     new_cols[j][i_ix], old_cols[j][d_ix]])
                vl = np.concatenate([self._interleave(old_valid[j][u_ix],
                                                      new_valid[j][u_ix]),
                                     new_valid[j][i_ix], old_valid[j][d_ix]])
                out_cols.append(Column(self.schema.fields[nk + j].dtype,
                                       vv, vl))
            yield StreamChunk(ops, out_cols)
        self._persist_batch(key_cols, nf, dead, old_vals, new_vals, idxs)
        self._persist_minputs(ch)
        dead_keys = keys[idxs[dead]]
        if len(dead_keys):
            self.codec.forget(dead_keys)

    def _persist_batch(self, key_cols: Sequence[Any], nf: np.ndarray,
                       dead: np.ndarray, old_vals: Sequence[np.ndarray],
                       new_vals: Sequence[np.ndarray],
                       idxs: np.ndarray) -> None:
        """Bulk-upsert every touched live group's payload (and tombstone
        dead groups) into the state table — the per-barrier recovery write,
        vectorized end-to-end (`StateTable.write_chunk`)."""
        if self.state_table is None:
            return
        from ..core.chunk import Column
        n_ix = np.flatnonzero(nf)
        d_ix = np.flatnonzero(dead)
        if len(n_ix) == 0 and len(d_ix) == 0:
            return
        ops = np.concatenate([np.full(len(n_ix), Op.INSERT, dtype=np.int8),
                              np.full(len(d_ix), Op.DELETE, dtype=np.int8)])
        cols: List[Column] = []
        for c, dt in zip(key_cols, self._key_dtypes):
            cols.append(Column(
                dt, np.concatenate([c.values[n_ix], c.values[d_ix]]),
                np.concatenate([c.validity[n_ix], c.validity[d_ix]])))
        for j, d in enumerate(self.spec.dtypes):
            flt = np.issubdtype(np.dtype(d), np.floating)
            npd = np.float64 if flt else np.int64
            arr = np.concatenate([new_vals[j][idxs][n_ix],
                                  old_vals[j][idxs][d_ix]]).astype(npd)
            cols.append(Column(T.FLOAT64 if flt else T.INT64, arr))
        self.state_table.write_chunk(StreamChunk(ops, cols))

    def _persist_minputs(self, ch: Dict[str, Any]) -> None:
        """Upsert/delete the touched (group, value, count) multiset pairs
        into the per-minput state tables (decode before dead-key forget)."""
        if not self.minput_tables:
            return
        from ..core.chunk import Column
        from ..device.sorted_state import EMPTY_KEY
        for mi in range(len(self.spec.minputs)):
            sub = ch[f"minput{mi}"]
            u1 = np.asarray(sub["u1"]).reshape(-1)
            u2 = np.asarray(sub["u2"]).reshape(-1)
            uc = np.asarray(sub["u_cnt"]).reshape(-1)
            sel = np.flatnonzero(u1 != EMPTY_KEY)
            if len(sel) == 0:
                continue
            gcols = self.codec.decode_columns(u1[sel])
            ops = np.where(uc[sel] == 0, Op.DELETE, Op.INSERT) \
                .astype(np.int8)
            cols = [Column(dt, c.values, c.validity)
                    for c, dt in zip(gcols, self._key_dtypes)]
            cols.append(Column(T.INT64, u2[sel].astype(np.int64)))
            cols.append(Column(T.INT64, uc[sel].astype(np.int64)))
            self.minput_tables[mi].write_chunk(StreamChunk(ops, cols))

    # ---- watermark state cleaning (state_table.rs:1002 analog) ----------
    def _clean_state(self) -> None:
        """Drop groups proven final by a group-key watermark: filter the
        live device rows host-side and re-install via load_state /
        load_minput (no retraction — the MV keeps the rows)."""
        if self._clean_wm is None:
            return
        gi, wv = self._clean_wm
        self._clean_wm = None
        keys, vals = self.engine.live_main()
        if len(keys) == 0:
            return
        tuples = self.codec.decode(keys)
        drop = np.array([t[gi] is not None and t[gi] < wv for t in tuples])
        if not drop.any():
            return
        keep = ~drop
        self.engine.load_state(keys[keep], [v[keep] for v in vals])
        dropped = set(keys[drop].tolist())
        for mi in range(len(self.spec.minputs)):
            k1, k2, cnt = self.engine.live_minput(mi)
            mdrop = np.isin(k1, keys[drop])
            self.engine.load_minput(mi, k1[~mdrop], k2[~mdrop], cnt[~mdrop])
            if mi < len(self.minput_tables):
                tbl = self.minput_tables[mi]
                gts = self.codec.decode(k1[mdrop])
                for gt, v in zip(gts, k2[mdrop].tolist()):
                    tbl.delete(gt + (int(v), 0))
        if self.state_table is not None:
            zeros = tuple(0.0 if np.issubdtype(np.dtype(d), np.floating)
                          else 0 for d in self.spec.dtypes)
            for i in np.flatnonzero(drop).tolist():
                self.state_table.delete(tuples[i] + zeros)
        self.codec.forget(np.fromiter(dropped, dtype=np.int64))

    def on_watermark(self, wm: Watermark) -> Iterator[Message]:
        if wm.col_idx in self.group_key_indices:
            gi = self.group_key_indices.index(wm.col_idx)
            self._clean_wm = (gi, wm.value)
            yield Watermark(gi, wm.dtype, wm.value)
