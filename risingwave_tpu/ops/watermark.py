"""Watermark generation and filtering.

Reference: `src/stream/src/executor/watermark_filter.rs:37` — derives the
watermark `max(event_time) - delay` from the data, emits `Watermark`
messages downstream, filters rows older than the current watermark, and
persists the watermark for recovery. The reference stores one watermark per
vnode; on the TPU runtime a fragment's vnode range lives in one executor, so
a single persisted scalar is the same contract.
"""
from __future__ import annotations

from typing import Any, Iterator, Optional

import numpy as np

from ..core.chunk import StreamChunk
from ..state.state_table import StateTable
from .executor import Executor, UnaryExecutor
from .message import Barrier, Message, Watermark


class WatermarkFilterExecutor(UnaryExecutor):
    def __init__(self, input: Executor, time_col: int, delay: int,
                 state_table: Optional[StateTable] = None):
        super().__init__(input, input.schema, "WatermarkFilter")
        self.append_only = input.append_only
        self.time_col = time_col
        self.delay = delay
        self.watermark: Optional[Any] = None
        self.state_table = state_table
        self._recovered = state_table is None
        self._wm_dirty = False
        self._persisted: Optional[Any] = None

    def _recover(self) -> None:
        if self._recovered:
            return
        self._recovered = True
        for row in self.state_table.iter_all():
            self.watermark = row[1] if self.watermark is None \
                else max(self.watermark, row[1])
        if self.watermark is not None:
            # re-announce the recovered watermark downstream (the reference
            # emits the persisted watermark on startup) so e.g. a recovered
            # EOWC agg can close its pre-crash windows even on a quiet stream
            self._wm_dirty = True
            self._persisted = self.watermark

    def on_chunk(self, chunk: StreamChunk) -> Iterator[Message]:
        self._recover()
        col = chunk.columns[self.time_col]
        vis = chunk.vis_mask() & col.validity
        # filter with the PREVIOUS watermark, then advance — a chunk's own
        # max must not retroactively drop its older sibling rows
        # (watermark_filter.rs evaluates `ts >= watermark` before updating)
        if self.watermark is not None:
            # the reference's filter expression is `ts >= watermark`: late
            # rows AND NULL-ts rows evaluate not-true and are dropped
            # (NULL would otherwise accumulate as never-closing groups in
            # downstream EOWC aggs)
            late = (vis & (col.values < self.watermark)) \
                | (chunk.vis_mask() & ~col.validity)
            if late.any():
                chunk = chunk.with_visibility(chunk.vis_mask() & ~late)
                vis = vis & ~late
        if vis.any():
            cand = col.values[vis].max() - self.delay
            if self.watermark is None or cand > self.watermark:
                self.watermark = cand
                self._wm_dirty = True
        if chunk.cardinality > 0:
            yield chunk

    def on_barrier(self, barrier: Barrier) -> Iterator[Message]:
        self._recover()
        if self.watermark is not None and self._wm_dirty:
            self._wm_dirty = False
            yield Watermark(self.time_col,
                            self.schema.fields[self.time_col].dtype,
                            self.watermark)
        if self.state_table is not None and \
                self.watermark != self._persisted:
            # persist only on change — an idle stream must not produce a
            # spill-run per epoch for an unchanged watermark
            self._persisted = self.watermark
            self.state_table.insert((0, self.watermark))
            self.state_table.commit(barrier.epoch.curr)
