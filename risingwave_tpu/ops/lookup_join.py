"""Lookup (delta) join over shared arrangements.

Reference: `src/stream/src/executor/lookup.rs` + the delta-join plan
(`src/frontend/src/optimizer/plan_node/stream_delta_join.rs`,
`lookup_union.rs`): instead of each join keeping private copies of both
inputs (`hash_join.rs` JoinHashMap), the join reads the inputs' EXISTING
materialized state — their arrangement/state tables — and the maintained
algebra is the delta-join identity

    d(A ⋈ B) = dA ⋈ B_old  ∪  A_new ⋈ dB.

Epoch protocol (the analog of lookup.rs's epoch-pinned arrangement
reads): upstream jobs run to the barrier before this executor, so both
state tables already hold their FULL epoch delta when it runs. Each
epoch, both inputs' deltas are buffered to the barrier; then
  - dA probes  B_old = B_table_now adjusted by removing the buffered dB
    (inserts subtracted, deletes re-added), and
  - dB probes  A_new = A_table_now as-is.
No private join state exists at all: recovery is trivial (the executor
is stateless; upstream tables recover themselves), the reference's
arrangement-sharing win.

INNER join only; requires both sides' join keys to be a prefix of (or
equal to) that side's state-table pk, the same index requirement the
reference's delta-join rule imposes (it builds arrangements/indexes on
the join key). Enabled via SET streaming_enable_delta_join TO true.
"""
from __future__ import annotations

from collections import Counter
from typing import Any, Dict, Iterator, List, Optional, Sequence, Tuple

from ..core.chunk import Op, StreamChunk, StreamChunkBuilder
from ..state.state_table import StateTable
from .executor import Executor
from .message import Barrier, Message, Watermark


class _Arrangement:
    """Probe-side view of an upstream state table."""

    def __init__(self, table: StateTable, key_cols: Sequence[int]):
        self.table = table
        self.key_cols = list(key_cols)       # join key positions in the row
        # join key must cover a pk prefix (in any pair order) for an
        # indexed probe; self.perm reorders probe values into pk order
        pkpre = table.pk_indices[: len(key_cols)]
        if sorted(pkpre) != sorted(key_cols):
            raise ValueError(
                "lookup join requires the join key to cover a pk prefix "
                f"of the arrangement (key {key_cols} vs pk "
                f"{table.pk_indices})")
        self.perm = [self.key_cols.index(c) for c in pkpre]
        # when the probe prefix covers the dist key, the owning vnode is
        # computable from the key — one range read instead of 256
        dist = table.dist_key_indices
        self.dist_in_prefix = ([pkpre.index(c) for c in dist]
                               if set(dist) <= set(pkpre) else None)

    def probe(self, key: Tuple) -> List[Tuple]:
        key = [key[i] for i in self.perm]
        if len(self.key_cols) == len(self.table.pk_indices):
            row = self.table.get_by_pk(key)
            return [tuple(row)] if row is not None else []
        if self.dist_in_prefix is not None:
            from ..core.vnode import vnode_of_row
            vn = vnode_of_row([key[i] for i in self.dist_in_prefix],
                              self.table.vnode_count)
            return [tuple(r)
                    for r in self.table.iter_vnode_prefix(vn, key)]
        out = []
        for vn in range(self.table.vnode_count):
            out.extend(tuple(r)
                       for r in self.table.iter_vnode_prefix(vn, key))
        return out


class LookupJoinExecutor(Executor):
    def __init__(self, left: Executor, right: Executor,
                 left_keys: Sequence[int], right_keys: Sequence[int],
                 left_table: StateTable, right_table: StateTable,
                 condition=None):
        schema = left.schema.concat(right.schema)
        super().__init__(schema, "LookupJoin[inner]")
        self.append_only = left.append_only and right.append_only
        self.left_exec, self.right_exec = left, right
        self.lkeys, self.rkeys = list(left_keys), list(right_keys)
        self.larr = _Arrangement(left_table, left_keys)
        self.rarr = _Arrangement(right_table, right_keys)
        self.condition = condition
        self._n_l = len(left.schema)

    def _key(self, row: Tuple, cols: Sequence[int]) -> Optional[Tuple]:
        k = tuple(row[i] for i in cols)
        return None if any(v is None for v in k) else k

    def _pairs_ok(self, rows: List[Tuple]) -> List[bool]:
        if self.condition is None or not rows:
            return [True] * len(rows)
        from ..core.chunk import DataChunk
        ch = DataChunk.from_rows(
            self.left_exec.schema.dtypes + self.right_exec.schema.dtypes,
            rows)
        c = self.condition.eval(ch)
        return [bool(v) and bool(ok)
                for v, ok in zip(c.values, c.validity)]

    def _emit(self, out: StreamChunkBuilder, sign: int,
              pairs: List[Tuple]) -> None:
        for row, ok in zip(pairs, self._pairs_ok(pairs)):
            if ok:
                out.append_row(Op.INSERT if sign > 0 else Op.DELETE, row)

    def _flush_epoch(self, lbuf: List[Tuple[int, Tuple]],
                     rbuf: List[Tuple[int, Tuple]]
                     ) -> Iterator[StreamChunk]:
        out = StreamChunkBuilder(self.schema.dtypes, 1024)
        # B_old adjustment: net the buffered right delta out of the table
        radj: Dict[Tuple, Counter] = {}
        for sign, row in rbuf:
            k = self._key(row, self.rkeys)
            if k is not None:
                radj.setdefault(k, Counter())[row] += sign
        # dA ⋈ B_old
        for sign, lrow in lbuf:
            k = self._key(lrow, self.lkeys)
            if k is None:
                continue
            matches = Counter(self.rarr.probe(k))
            for row, d in radj.get(k, {}).items():
                matches[row] -= d                 # undo this epoch's dB
            pairs = [lrow + r for r, c in matches.items() if c > 0
                     for _ in range(c)]
            self._emit(out, sign, pairs)
        # A_new ⋈ dB
        for sign, rrow in rbuf:
            k = self._key(rrow, self.rkeys)
            if k is None:
                continue
            lmatches = self.larr.probe(k)   # lkeys[i] pairs with rkeys[i]
            pairs = [lrow + rrow for lrow in lmatches]
            self._emit(out, sign, pairs)
        yield from out.drain()

    def execute(self) -> Iterator[Message]:
        liter = self.left_exec.execute()
        riter = self.right_exec.execute()
        alive = True
        while alive:
            barrier = None
            lbuf: List[Tuple[int, Tuple]] = []
            rbuf: List[Tuple[int, Tuple]] = []
            for buf, it in ((lbuf, liter), (rbuf, riter)):
                while True:
                    try:
                        msg = next(it)
                    except StopIteration:
                        alive = False
                        break
                    if isinstance(msg, Barrier):
                        barrier = msg
                        break
                    if isinstance(msg, StreamChunk):
                        for op, row in msg.compact().op_rows():
                            buf.append((op.sign, tuple(row)))
                    # watermarks: no output watermark (probe rows resurface)
            if barrier is None:
                return
            yield from self._flush_epoch(lbuf, rbuf)
            yield barrier.with_trace(self.name)
            if barrier.is_stop():
                return
