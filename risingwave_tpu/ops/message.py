"""Stream messages: Chunk | Barrier | Watermark.

Mirrors the reference's `Message` enum and `Barrier` struct
(`src/stream/src/executor/mod.rs:1039`, `:324`): barriers carry the epoch
pair, a kind (initial/barrier/checkpoint), and mutations (scale, pause,
config change) that executors apply when the barrier passes through them.
"""
from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Union

from ..core.chunk import StreamChunk
from ..core.dtypes import DataType
from ..core.epoch import EpochPair


class BarrierKind(enum.Enum):
    """`BarrierKind` (`src/meta/src/barrier/command.rs:452`): not every barrier
    is a checkpoint — state flushes to durable storage only on checkpoint
    barriers (every `checkpoint_frequency` ticks)."""
    INITIAL = "initial"
    BARRIER = "barrier"
    CHECKPOINT = "checkpoint"


class MutationKind(enum.Enum):
    """Barrier mutations (`src/stream/src/executor/mod.rs:304`)."""
    STOP = "stop"
    PAUSE = "pause"
    RESUME = "resume"
    ADD = "add"                  # new downstream job attached (backfill start)
    UPDATE = "update"            # scale: dispatcher/vnode bitmap changes
    SOURCE_CHANGE_SPLIT = "source_change_split"
    THROTTLE = "throttle"


@dataclass
class Mutation:
    kind: MutationKind
    # vnode re-assignment for scale: actor/shard id -> vnode bitmap
    vnode_bitmaps: Optional[Dict[int, Any]] = None
    # split assignment changes for sources
    splits: Optional[Dict[str, Any]] = None
    payload: Any = None


@dataclass
class Barrier:
    epoch: EpochPair
    kind: BarrierKind = BarrierKind.CHECKPOINT
    mutation: Optional[Mutation] = None
    # passed_actors-style tracing breadcrumb (which executors saw it)
    trace: List[str] = field(default_factory=list)
    # source->MV freshness stamp: wall time the OLDEST event of the
    # epoch this barrier seals came into existence. Sources fold their
    # first-chunk poll wall in via `note_ingest` (min wins — the
    # injector hands every source the SAME Barrier instance, so the
    # coordinator reads the cluster-wide minimum after the tick);
    # `open_ts` is the injector's conservative fallback (the previous
    # barrier's injection wall — no event of this epoch can predate it).
    ingest_ts: Optional[float] = None
    open_ts: Optional[float] = None

    @property
    def is_checkpoint(self) -> bool:
        return self.kind in (BarrierKind.CHECKPOINT, BarrierKind.INITIAL)

    def is_stop(self) -> bool:
        return self.mutation is not None and self.mutation.kind == MutationKind.STOP

    def note_ingest(self, ts: float) -> None:
        self.ingest_ts = ts if self.ingest_ts is None \
            else min(self.ingest_ts, ts)

    def best_ingest_ts(self) -> Optional[float]:
        """The freshness anchor: a source-stamped first-chunk wall when
        any source stamped one, else the epoch-open fallback."""
        return self.ingest_ts if self.ingest_ts is not None else self.open_ts

    def with_trace(self, name: str) -> "Barrier":
        b = Barrier(self.epoch, self.kind, self.mutation,
                    self.trace + [name])
        b.ingest_ts = self.ingest_ts
        b.open_ts = self.open_ts
        return b


@dataclass
class Watermark:
    """Column watermark (`src/stream/src/executor/mod.rs:964`): all future rows
    have col > value is FALSE; i.e. no row with column value <= `value` - delay
    will arrive. Used for window emission + state cleaning."""
    col_idx: int
    dtype: DataType
    value: Any


Message = Union[StreamChunk, Barrier, Watermark]


def is_chunk(m: Message) -> bool:
    return isinstance(m, StreamChunk)
