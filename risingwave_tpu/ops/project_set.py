"""Set-returning functions: ProjectSet + table-function scan.

Reference: `src/stream/src/executor/project/project_set.rs` (ProjectSet:
each input row expands through a mix of scalar expressions and table
functions, PG-style zipped to the longest function with NULL padding,
plus a `projected_row_id` ordinal that keeps the expanded rows' stream
identity) and `src/expr/core/src/table_function/mod.rs:174` /
`src/expr/impl/src/table_function/generate_series.rs` for the function
semantics (series bounds are INCLUSIVE, zero step is an error).

Supported functions: generate_series over ints and timestamps (+INTERVAL
step), unnest over ARRAY[...] literals of scalar expressions (array-typed
columns are not in the type system yet).
"""
from __future__ import annotations

from typing import Any, Iterator, List, Optional, Sequence, Tuple

from ..core.chunk import Op, StreamChunk, StreamChunkBuilder
from ..core.dtypes import Interval, TypeKind
from ..core.schema import Field, Schema
from ..core import dtypes as T
from ..expr.expression import Expr
from .executor import Executor, UnaryExecutor
from .message import Barrier, Message, Watermark

TABLE_FUNCTIONS = ("generate_series", "unnest")


class BoundTableFunction:
    """One table-function call with bound argument expressions.

    `unnest` carries the ARRAY literal's element expressions directly
    (each evaluated per input row); `generate_series` evaluates
    (start, stop[, step]) per row and yields the inclusive series.
    """

    def __init__(self, name: str, args: Sequence[Expr],
                 return_type: Any):
        self.name = name
        self.args = list(args)
        self.return_type = return_type

    def expand(self, data_chunk) -> List[List[Any]]:
        """Per input row (by position), the list of produced values."""
        cols = [a.eval(data_chunk) for a in self.args]
        n = data_chunk.capacity
        vals = [[c.get(i) for c in cols] for i in range(n)]
        if self.name == "unnest":
            return vals                       # element exprs ARE the rows
        out: List[List[Any]] = []
        for row in vals:
            out.append(_series(row, self.return_type))
        return out


def _series(args: List[Any], rt) -> List[Any]:
    if any(a is None for a in args):
        return []                             # PG: NULL bound -> no rows
    start, stop = args[0], args[1]
    step = args[2] if len(args) > 2 else 1
    if isinstance(step, Interval):
        if step.months:
            raise ValueError("generate_series month-interval steps are "
                             "not supported")
        step = step.days * 86_400_000_000 + step.usecs
    if step == 0:
        raise ValueError("step size cannot equal zero")
    out = []
    v = start
    if step > 0:
        while v <= stop:
            out.append(v)
            v += step
    else:
        while v >= stop:
            out.append(v)
            v += step
    return out


def series_return_type(arg_types: Sequence[Any]):
    """Result element type of generate_series, PG-style."""
    if arg_types[0].kind in (TypeKind.TIMESTAMP, TypeKind.DATE):
        return T.TIMESTAMP
    return T.INT64


class TableFunctionScanExecutor(Executor):
    """FROM-clause table function over constant arguments: emits the whole
    row set once (like Values), then passes barriers. The hidden trailing
    `_row_id` ordinal is the stream key (expansions may repeat values)."""

    def __init__(self, tf: BoundTableFunction, name: str,
                 barrier_source: Executor):
        schema = Schema([Field(name, tf.return_type),
                         Field("_row_id", T.INT64)])
        super().__init__(schema, f"TableFunctionScan[{tf.name}]")
        self.append_only = True
        self.tf = tf
        self.barrier_source = barrier_source

    def execute(self) -> Iterator[Message]:
        from ..core.chunk import DataChunk
        emitted = False
        for msg in self.barrier_source.execute():
            if not emitted and isinstance(msg, Barrier):
                yield msg
                one = DataChunk.from_rows([T.INT64], [(0,)])  # 1-row driver
                (vals,) = self.tf.expand(one)
                if vals:
                    yield StreamChunk.from_rows(
                        self.schema.dtypes,
                        [(Op.INSERT, (v, i)) for i, v in enumerate(vals)])
                emitted = True
            else:
                yield msg


class ProjectSetExecutor(UnaryExecutor):
    """SELECT-list expansion (`project_set.rs`): items are ('s', expr) or
    ('tf', BoundTableFunction). Output = item columns + carried hidden
    columns (`carry`, input column indices — the upstream stream key) +
    `projected_row_id`.

    PG zip semantics: per input row, every table function runs; the row
    expands to max(lengths) output rows; shorter functions NULL-pad;
    scalars repeat. A row whose functions all return empty produces
    nothing. Updates decay to DELETE+INSERT (expansion lengths may
    differ across the pair)."""

    def __init__(self, input: Executor,
                 items: Sequence[Tuple[str, Any]],
                 names: Sequence[str],
                 carry: Sequence[int] = ()):
        fields = []
        for (kind, item), nm in zip(items, names):
            rt = item.return_type
            fields.append(Field(nm, rt))
        for ci in carry:
            fields.append(Field(f"_carry{ci}", input.schema.fields[ci].dtype))
        fields.append(Field("_projected_row_id", T.INT64))
        super().__init__(input, Schema(fields))
        self.append_only = input.append_only
        self.items = list(items)
        self.carry = list(carry)

    def on_chunk(self, chunk: StreamChunk) -> Iterator[Message]:
        chunk = chunk.compact()
        data = chunk.data_chunk()
        n = chunk.capacity
        per_item: List[Any] = []
        for kind, item in self.items:
            if kind == "s":
                col = item.eval(data)
                per_item.append([col.get(i) for i in range(n)])
            else:
                per_item.append(item.expand(data))
        carried = [[data.columns[ci].get(i) for ci in self.carry]
                   for i in range(n)]
        out = StreamChunkBuilder(self.schema.dtypes, 1024)
        for i in range(n):
            op = Op(int(chunk.ops[i]))
            if op == Op.UPDATE_DELETE:
                op = Op.DELETE
            elif op == Op.UPDATE_INSERT:
                op = Op.INSERT
            lens = [len(v[i]) for (k, _), v in zip(self.items, per_item)
                    if k == "tf"]
            m = max(lens) if lens else 1
            for j in range(m):
                row = []
                for (kind, _), vals in zip(self.items, per_item):
                    if kind == "s":
                        row.append(vals[i])
                    else:
                        row.append(vals[i][j] if j < len(vals[i]) else None)
                row.extend(carried[i])
                row.append(j)
                out.append_row(op, tuple(row))
        yield from out.drain()

    def on_watermark(self, wm: Watermark) -> Iterator[Message]:
        from ..expr.expression import InputRef
        for out_idx, (kind, item) in enumerate(self.items):
            if kind == "s" and isinstance(item, InputRef) \
                    and item.index == wm.col_idx:
                yield Watermark(out_idx, wm.dtype, wm.value)
                return
        # not selected — the watermark column may still ride a hidden
        # carry column (the planner points downstream at that index)
        if wm.col_idx in self.carry:
            out_idx = len(self.items) + self.carry.index(wm.col_idx)
            yield Watermark(out_idx, wm.dtype, wm.value)
