"""Temporal join: stream JOIN table FOR SYSTEM_TIME AS OF PROCTIME().

Reference: `src/stream/src/executor/temporal_join.rs:44`. The left side is
an (append-only) stream; the right side is a *version table* — its change
stream maintains an index, but versions are looked up, never joined
symmetrically: a left row matches the right side's CURRENT rows at
processing time, the output is append-only, and later right-side changes
never retract rows already emitted (the defining difference from a regular
streaming join, which would).

Barrier protocol: two-input alignment like HashJoin, with the right
(version) side drained first inside each epoch so lookups see the freshest
committed version — proc-time semantics make any intra-epoch interleaving
legal; this one is deterministic for tests.
"""
from __future__ import annotations

from typing import Any, Dict, Iterator, List, Optional, Sequence, Tuple

from ..core.chunk import Op, StreamChunk, StreamChunkBuilder
from ..core.schema import Schema
from ..expr.expression import Expr
from ..state.state_table import StateTable
from .executor import Executor
from .message import Barrier, Message, Watermark


class TemporalJoinExecutor(Executor):
    def __init__(self, left: Executor, right: Executor,
                 left_keys: Sequence[int], right_keys: Sequence[int],
                 outer: bool = False,
                 condition: Optional[Expr] = None,
                 right_pk: Optional[Sequence[int]] = None,
                 right_state: Optional[StateTable] = None,
                 max_chunk_size: int = 1024):
        schema = left.schema.concat(right.schema)
        super().__init__(schema,
                         f"TemporalJoin[{'left' if outer else 'inner'}]")
        # output rows are never retracted, whatever the right side does
        self.append_only = left.append_only
        self.left_exec, self.right_exec = left, right
        self.left_keys = list(left_keys)
        self.right_keys = list(right_keys)
        self.outer = outer
        self.condition = condition
        self.right_pk = list(right_pk) if right_pk is not None \
            else list(range(len(right.schema)))
        self.right_state = right_state
        self._recovered = right_state is None
        # version index: join key -> {pk: row}
        self.index: Dict[Tuple, Dict[Tuple, Tuple]] = {}
        self.max_chunk_size = max_chunk_size

    # ---- version side ----------------------------------------------------
    def _recover(self) -> None:
        if self._recovered:
            return
        self._recovered = True
        for row in self.right_state.iter_all():
            row = tuple(row)
            key = tuple(row[i] for i in self.right_keys)
            pk = tuple(row[i] for i in self.right_pk)
            self.index.setdefault(key, {})[pk] = row

    def _apply_version(self, chunk: StreamChunk) -> None:
        for op, row in chunk.compact().op_rows():
            key = tuple(row[i] for i in self.right_keys)
            pk = tuple(row[i] for i in self.right_pk)
            if op.is_insert:
                self.index.setdefault(key, {})[pk] = row
                if self.right_state is not None:
                    self.right_state.insert(row)
            else:
                d = self.index.get(key)
                if d is not None:
                    d.pop(pk, None)
                    if not d:
                        del self.index[key]
                if self.right_state is not None:
                    self.right_state.delete(row)

    # ---- stream side -----------------------------------------------------
    def _lookup(self, row: Tuple) -> List[Tuple]:
        key = tuple(row[i] for i in self.left_keys)
        if any(v is None for v in key):
            return []
        cands = list(self.index.get(key, {}).values())
        if self.condition is None or not cands:
            return cands
        from ..core.chunk import DataChunk
        rows = [row + c for c in cands]
        ch = DataChunk.from_rows(self.schema.dtypes, rows)
        col = self.condition.eval(ch)
        return [c for c, ok, valid in zip(cands, col.values, col.validity)
                if valid and ok]

    def _process_left(self, chunk: StreamChunk) -> Iterator[StreamChunk]:
        out = StreamChunkBuilder(self.schema.dtypes, self.max_chunk_size)
        nulls = tuple([None] * len(self.right_exec.schema))
        for op, row in chunk.compact().op_rows():
            if not op.is_insert:
                raise ValueError(
                    "temporal join requires an append-only left input "
                    "(temporal_join.rs append-only precondition)")
            matches = self._lookup(row)
            if matches:
                for m in matches:
                    out.append_row(Op.INSERT, row + m)
            elif self.outer:
                out.append_row(Op.INSERT, row + nulls)
        yield from out.drain()

    # ---- the aligned loop ------------------------------------------------
    def execute(self) -> Iterator[Message]:
        self._recover()
        liter = self.left_exec.execute()
        riter = self.right_exec.execute()
        alive = True
        while alive:
            barrier = None
            # version side first: lookups inside this epoch see its writes
            for side, it in (("r", riter), ("l", liter)):
                while True:
                    try:
                        msg = next(it)
                    except StopIteration:
                        alive = False
                        break
                    if isinstance(msg, Barrier):
                        barrier = msg
                        break
                    if isinstance(msg, StreamChunk):
                        if not msg.cardinality:
                            continue
                        if side == "r":
                            self._apply_version(msg)
                        else:
                            yield from self._process_left(msg)
                    elif isinstance(msg, Watermark) and side == "l":
                        yield msg        # left watermark cols keep indices
            if barrier is None:
                return
            if self.right_state is not None:
                self.right_state.commit(barrier.epoch.curr)
            yield barrier.with_trace(self.name)
            if barrier.is_stop():
                return
