"""Read-serving tier: host-side epoch-versioned MV snapshot caches.

The write path (fused epoch programs over the device mesh) publishes
state once per checkpoint; this package makes the READ path scale
independently of it — see `read_cache.MVReadCache`.
"""
from .read_cache import MVReadCache

__all__ = ["MVReadCache"]
