"""Epoch-versioned MV read cache: the serving tier's host-side half.

Every SELECT against a fused MV ultimately costs one `device_get` (the
in-program gather of `shard_exec.merge_keyed_pull`). Between two
checkpoint commits that pull returns the SAME rows — the MV only
changes at barrier commits — so the coordinator caches one
`(epoch, rows)` snapshot per MV and serves every reader in that commit
window from host memory:

* **Versioning** — a snapshot is stamped with the committed epoch the
  pull reflected (`FusedJob.mv_rows_versioned`, which retries a pull
  torn by a racing commit). A commit does not eagerly refill anything;
  it merely advances `committed_epoch`, which makes stale snapshots
  unservable. The FIRST read after a commit repopulates — so a restart
  or in-place recovery simply starts cold and heals on first contact.

* **Staleness bound** — a snapshot serves iff
  `cache_epoch >= committed_epoch - staleness` (the
  `rw_serving_staleness_epochs` knob; 0 = always-fresh, the cache still
  coalesces all readers within one commit window).

* **Request coalescing** — concurrent cache-miss readers of one MV
  block on a per-MV condition while a single filler runs the device
  pull; they wake into a cache hit. One device pull per (MV, epoch)
  regardless of reader count — the acceptance invariant, asserted by
  tests against `shard_exec.PULL_STATS`.
"""
from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple


class _Entry:
    __slots__ = ("epoch", "rows", "filling", "hits", "misses",
                 "coalesced", "fills", "fill_ts")

    def __init__(self) -> None:
        self.epoch = -1
        self.rows: Optional[List[Tuple]] = None
        self.filling = False
        self.hits = 0
        self.misses = 0
        self.coalesced = 0
        self.fills = 0
        # wall clock of the last fill: a snapshot reflects commits up
        # to this moment — the served-staleness anchor rw_mv_freshness
        # reports for cache-lagged reads
        self.fill_ts: Optional[float] = None


class MVReadCache:
    """Per-MV `(epoch, rows)` snapshots with single-flight fills."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._entries: Dict[str, _Entry] = {}
        self._conds: Dict[str, threading.Condition] = {}

    def _slot(self, name: str) -> Tuple[_Entry, threading.Condition]:
        with self._lock:
            ent = self._entries.get(name)
            if ent is None:
                ent = self._entries[name] = _Entry()
                self._conds[name] = threading.Condition()
            return ent, self._conds[name]

    def peek(self, name: str, committed_epoch: int,
             staleness: int = 0) -> Optional[List[Tuple]]:
        """Servable snapshot or None — no fill, no blocking, no stats."""
        with self._lock:
            ent = self._entries.get(name)
        if ent is None or ent.rows is None:
            return None
        return ent.rows if ent.epoch >= committed_epoch - staleness \
            else None

    def get(self, name: str, committed_epoch: int, staleness: int,
            fill: Callable[[], Tuple[int, List[Tuple]]]
            ) -> Tuple[int, List[Tuple]]:
        """Serve `name` as of (at least) `committed_epoch - staleness`,
        filling through `fill` (-> (epoch, rows), e.g. a bound
        `FusedJob.mv_rows_versioned`) on miss. Concurrent missers
        coalesce onto one fill."""
        ent, cond = self._slot(name)
        waited = False
        with cond:
            while True:
                if ent.rows is not None \
                        and ent.epoch >= committed_epoch - staleness:
                    ent.hits += 1
                    if waited:
                        ent.coalesced += 1
                    return ent.epoch, ent.rows
                if ent.filling:
                    waited = True
                    cond.wait()
                    continue
                ent.filling = True
                ent.misses += 1
                break
        try:
            epoch, rows = fill()
            with cond:
                if epoch >= ent.epoch:
                    ent.epoch, ent.rows = int(epoch), rows
                    ent.fill_ts = time.time()
                ent.fills += 1
            return int(epoch), rows
        finally:
            with cond:
                ent.filling = False
                cond.notify_all()

    def invalidate(self, name: Optional[str] = None) -> None:
        """Forget one MV's snapshot (DROP) or everything (recovery /
        rebalance: the cache rebuilds cold, first read repopulates).
        Never called on commit — staleness does that job lazily."""
        with self._lock:
            if name is None:
                self._entries.clear()
                self._conds.clear()
            else:
                self._entries.pop(name, None)
                self._conds.pop(name, None)

    def fill_time(self, name: str) -> Optional[float]:
        """Wall clock of `name`'s last snapshot fill (None when cold)."""
        with self._lock:
            ent = self._entries.get(name)
        return ent.fill_ts if ent is not None else None

    def stats(self) -> Dict[str, int]:
        with self._lock:
            ents = list(self._entries.values())
        return {"hits": sum(e.hits for e in ents),
                "misses": sum(e.misses for e in ents),
                "coalesced": sum(e.coalesced for e in ents),
                "fills": sum(e.fills for e in ents)}

    def report(self) -> List[Tuple[str, int, int, int, int, int, int]]:
        """Per-MV rows for the `rw_serving_cache` system table /
        `risectl serving`: (mv, cache_epoch, cached_rows, hits, misses,
        coalesced, fills)."""
        with self._lock:
            items = sorted(self._entries.items())
        return [(name, e.epoch, len(e.rows) if e.rows is not None else 0,
                 e.hits, e.misses, e.coalesced, e.fills)
                for name, e in items]
