"""Runtime configuration — the reference's 3-tier config system.

* `NodeConfig` — per-process startup config, TOML-loadable
  (`src/common/src/config.rs:137`; `risingwave.toml`). Immutable for the
  process lifetime.
* `SystemParams` — cluster-wide parameters alterable at runtime via
  `ALTER SYSTEM SET` (`src/common/src/system_param/mod.rs:97`): mutations
  are DDL-logged so a restarted process replays them.
* session variables — per-connection `SET`/`SHOW`
  (`src/common/src/session_config/`), held on the Database session.

The device tier (`DeviceConfig`) governs the SQL->device dispatch seam:
whether eligible plan fragments lower onto the TPU executors and over
which mesh.
"""
from __future__ import annotations

from dataclasses import dataclass, field, fields
from typing import Any, Dict, Optional


@dataclass
class DeviceConfig:
    """Device-path lowering config (the `from_proto` dispatch policy).

    mesh      — jax.sharding.Mesh to shard operator state over; None = one
                chip (still jitted epoch steps, no collectives).
    capacity  — initial per-operator state slots (grows by pow2 on demand).
    minmax    — lower min/max aggregates onto the retractable sorted-
                multiset state (device/minput.py).
    """
    mesh: Optional[Any] = None
    capacity: int = 1024
    minmax: bool = True
    # mesh-sharded FUSED programs (device/shard_exec.py): eligible fused
    # MV fragments execute as ONE shard_map'd epoch program over an
    # n-device 1-D mesh — node state carries a leading shard axis with a
    # vnode-keyed PartitionSpec, the cross-vnode shuffle for joins/aggs
    # runs as an in-program all_to_all over ICI, and global stats reduce
    # via psum/pmax. 1 = today's single-chip fused path, byte-for-byte
    # unchanged. Distinct from `mesh`, which shards the PER-OPERATOR
    # host executors (parallel/sharded_*) and disables fusion.
    mesh_shards: int = 1
    # serving replicas (parallel/mesh.REPLICA_AXIS): the fused mesh
    # becomes (mesh_shards, replicas) with state sharded over the data
    # axis and MIRRORED over the replica axis — the same fused program,
    # byte-for-byte, with every MV arrangement readable from any replica
    # column (SELECT pulls round-robin over replicas). Needs
    # mesh_shards * replicas devices; 1 = today's 1-D mesh, unchanged.
    # RW_MESH_REPLICAS overrides.
    replicas: int = 1
    # whole-fragment fusion (device/fuse_planner.py): eligible MV plans
    # become one jitted epoch program. Off forces the per-operator path.
    fuse: bool = True
    # host-ingest feed for fused sources (device/ingest.py): every
    # source of a fused job becomes an IngestNode whose per-epoch input
    # is a pre-staged device buffer — host connectors poll into reused
    # staging buffers, a staging thread double-buffers the H2D transfer
    # under the previous epoch's dispatch, and per-shard blocks land
    # directly on their chips under mesh_shards > 1. Off (default) keeps
    # deterministic sources regenerating on device (fastest for
    # synthetic benchmarks; host ingest is the production source path).
    # RW_HOST_INGEST overrides; a single source opts in via
    # WITH (nexmark.ingest='host').
    host_ingest: bool = False
    # fused jobs mirror their MV into the host state table for non-device
    # readers every N checkpoints (plus at drain/recovery). 1 = every
    # checkpoint (reference-strict); higher trades mirror freshness for
    # throughput — queries always serve live device state regardless.
    mv_persist_every: int = 8
    # capacity lifecycle (device/capacity.py): a growth replay sizes EVERY
    # node from its observed entries-per-event rate extrapolated over
    # max_events (cascade-free, ~1 replay per job) instead of doubling
    # only the overflowed state. Off restores blind pow2 doubling.
    predictive_growth: bool = True
    # HBM budget the predictor's projections are scaled down to (never
    # below the observed need — the budget trims headroom, not
    # correctness).
    hbm_budget_mb: int = 4096
    # persistent XLA compilation cache directory: per-bucket re-traces hit
    # disk across processes and runs. None = the platform-gated default
    # (device/__init__.py); RW_COMPILE_CACHE_DIR overrides either ("" in
    # the env disables). No-op on jax builds without the cache config.
    compile_cache_dir: Optional[str] = None
    # epoch-timeline profiler (utils/profile.py): per-epoch phase-split
    # spans (host-pack / dispatch / device-sync / commit), compile-event
    # timing, and the rw_epoch_profile / rw_fused_node_stats surfaces.
    # Costs a few perf_counter reads per epoch; off removes even that.
    profile: bool = True
    # AOT compile service (device/compile_service.py): jit compiles of
    # fused epoch programs move off the barrier hot loop onto a
    # background worker pool — at CREATE time the plan's shapes (and,
    # once rates are observed, its predicted growth buckets) compile
    # ahead while the interpreted path serves the first epochs, and the
    # compiled executable swaps in at the next barrier. Off restores
    # inline compiles on first dispatch (the pre-ISSUE-6 behavior).
    aot_compile: bool = True
    # max background pre-warm rounds per job for predicted growth-bucket
    # shapes (the capacity ladder ahead of observed need). 0 disables
    # bucket pre-warm while keeping CREATE-time AOT.
    compile_buckets: int = 4
    # key-skew telemetry (device/skew_stats.py): keyed fused nodes
    # (Agg/Join) compute a vnode-occupancy histogram over their live key
    # tables and per-epoch top-K heavy-hitter counters inside the traced
    # epoch step, riding the stats vector (psum/pmax across mesh shards
    # like every other stat) — the rw_key_skew evidence surface the
    # adaptive-partitioning work needs. Costs one O(capacity) bucket
    # pass + one O(epoch) sort per keyed node per epoch; off removes the
    # stats from the trace entirely (and changes the plan-shape hash —
    # the traced programs genuinely differ). RW_SKEW_STATS=0/1 in the
    # environment overrides this without code changes.
    skew_stats: bool = True
    # --- skew defenses (act on the rw_key_skew evidence) ----------------
    # local pre-combine (device/agg_step.py `PrecombineNode`): duplicate-
    # key rows of an agg's epoch input combine to one partial-aggregate
    # row per key BEFORE the state merge — and, under mesh sharding,
    # BEFORE the ICI exchange, so a hot key ships one combined row per
    # (shard, epoch) instead of every raw row ("Global Hash Tables
    # Strike Back!": per-partition pre-aggregation + global merge).
    # Exact: applies only to integer-reduction aggs (no retractable
    # min/max multisets, no float sums — their reductions are not
    # order-independent bit-for-bit). RW_AGG_PRECOMBINE=0/1 overrides.
    agg_precombine: bool = True
    # hot-key replication (device/shard_exec.py): join keys flagged by
    # the in-program heavy-hitter counters get one side's rows
    # replicated to every shard while the other side's rows salt
    # round-robin by row identity — the PanJoin/JSPIM split-hot-keys
    # move. Policy changes adopt at a checkpoint barrier via the
    # rebuild-replay maneuver (bit-identical). RW_HOT_KEY_REP=0/1.
    hot_key_rep: bool = True
    # a key is "hot" when its per-epoch row count reaches this fraction
    # of the epoch cadence (evidence: the skh* heavy-hitter slots).
    hot_key_frac: float = 0.125
    # barrier-time vnode rebalancing (device/shard_exec.py routing +
    # FusedJob._maybe_retune): when the per-shard load implied by the
    # vnode-occupancy histogram exceeds rebalance_threshold (max/mean),
    # the job recomputes the vnode-block bounds at a checkpoint, pre-
    # warms the re-routed exchange executables in the background, and
    # switches via the rebuild-replay maneuver — zero fresh compiles,
    # bit-identical. RW_VNODE_REBALANCE=0/1 overrides.
    vnode_rebalance: bool = True
    rebalance_threshold: float = 2.0
    # tiered state beyond HBM (device/tiering.py): keyed fused state
    # (agg groups, join rows, the terminal MV's rows) demotes its
    # coldest keys to per-shard host stores when occupancy crosses a
    # high-water fraction of capacity, and promotes them back — probed
    # through an Xor8 negative cache — the moment a window touches them
    # again, so results stay bit-identical to the untiered run. Arms a
    # last-touched-epoch column in the traced step (part of the plan-
    # shape hash, like skew_stats). RW_STATE_TIERING=0/1 overrides;
    # RW_TIER_HIGH_WATER / RW_TIER_LOW_WATER tune the marks.
    state_tiering: bool = True
    # flow telemetry (device/skew_stats.py): keyed fused nodes count
    # this epoch's ROUTED rows per vnode bucket inside the traced step —
    # the traffic histogram occupancy-driven rebalancing is blind to
    # (hot flow over cold state). Slots ride the stat_sums split (sum
    # across epochs, psum across shards — exact totals). Arming extends
    # the traced step, so it is part of the plan-shape hash exactly
    # like skew_stats; RW_FLOW_STATS=0/1 overrides without code changes.
    flow_stats: bool = True


@dataclass
class StreamingConfig:
    """[streaming] section (`StreamingConfig`, config.rs)."""
    chunk_size: int = 1024             # max rows per stream chunk
    barrier_interval_ms: int = 1000    # timed-runtime barrier cadence
    checkpoint_frequency: int = 1      # checkpoints per N barriers


@dataclass
class StorageConfig:
    """[storage] section (`StorageConfig`, config.rs)."""
    data_dir: Optional[str] = None     # None = in-memory state store
    block_cache_blocks: int = 4096     # hummock LRU capacity
    compact_threshold: int = 8         # runs per table before compaction


@dataclass
class RobustnessConfig:
    """Retry / timeout / supervision knobs for the multi-process runtime
    (the reference's `[meta] max_heartbeat_interval_secs` +
    `[streaming] actor retry` family, collapsed to what this runtime
    needs). Read once per process from `RW_<FIELD>` environment
    variables, so worker OS processes spawned by the coordinator inherit
    the operator's settings without a config file of their own; tests
    mutate the module-global `ROBUSTNESS` instance directly."""
    # RemoteInput -> coordinator exchange connect: bounded exponential
    # backoff (base doubles per attempt, capped at 1s per sleep)
    connect_attempts: int = 5
    connect_backoff_s: float = 0.05
    connect_timeout_s: float = 10.0
    # worker process spawn: ADDR-handshake deadline + retries
    spawn_attempts: int = 3
    spawn_timeout_s: float = 30.0
    spawn_backoff_s: float = 0.05
    # ExchangeServer.wait_drained default deadline (worker shutdown)
    drain_deadline_s: float = 120.0
    # FragmentSupervisor: in-place respawns per worker slot before
    # escalating to RemoteWorkerDied (full job recovery)
    respawn_attempts: int = 3
    respawn_backoff_s: float = 0.05
    # poison-pill quarantine: consecutive respawns of ONE slot that die
    # on the SAME retained input window (fingerprinted) before the
    # supervisor sidelines the window's data chunks into the durable
    # rw_dead_letter table and resumes past them — bounded data loss
    # with an audit trail instead of a wedged-forever fragment. Must be
    # <= respawn_attempts or the attempt bound escalates first; <= 0
    # disables quarantine (the pre-v3 respawn-until-escalate behavior).
    poison_threshold: int = 2
    # fused device jobs: in-place recoveries per job from a device-path
    # failure (dispatch/sync/replay/commit exception or an armed
    # fused.* failpoint) before the error propagates to the classic
    # DDL-replay restart. Recovery rebuilds program state from the last
    # checkpoint and re-dispatches the retained crash-window epochs —
    # all on AOT-cached executables, so it is zero-compile.
    fused_recovery_attempts: int = 3
    # metrics plane: a worker whose last heartbeat frame (piggybacked on
    # its result stream) is older than this is flagged WEDGED in
    # rw_worker_liveness / worker_liveness — alive-but-stuck detection
    # ahead of the spawn/drain deadlines (detection is passive for
    # unsupervised sets; supervised sets ACT on it, see wedge_kill_factor)
    heartbeat_timeout_s: float = 60.0
    # wedge reaper (supervised sets only): a worker whose heartbeat age
    # exceeds heartbeat_timeout_s * wedge_kill_factor while its process
    # is still alive is SIGKILLed and routed through the same in-place
    # respawn path as a dead worker (bounded attempts, then escalation).
    # <= 0 disables reaping (observe-only, the pre-supervision-v2
    # behavior).
    wedge_kill_factor: float = 3.0
    # ---- overload control plane (credit flow + degradation ladder) ----
    # initial credit (in chunks) a receiver grants each exchange stream,
    # and the unit the producer-side queue bound derives from (queue
    # capacity = 4x credits). Lower = tighter memory bound + earlier
    # backpressure; higher = more in-flight pipelining.
    exchange_credits: int = 256
    # master gate for the graceful-degradation ladder
    # (normal -> throttled -> degraded -> shedding). Off: the ladder
    # observes (pressure gauge, rw_overload stays 'normal') but never
    # throttles, stretches, or sheds.
    overload_ladder: bool = True
    # sliding window the credit-stall fraction is computed over
    overload_window_s: float = 5.0
    # pressure thresholds with a dead band between them (hysteresis):
    # >= high sustained for hold_s escalates one rung; <= low sustained
    # for hold_s recovers one rung; in between nothing moves.
    overload_high: float = 0.5
    overload_low: float = 0.1
    overload_hold_s: float = 2.0
    # epoch-cadence stretch factor on the degraded/shedding rungs: fused
    # jobs dispatch this many epochs per barrier (same AOT executables —
    # zero fresh compiles), host sources allow this many times the
    # per-epoch chunk bound — bigger batches, fewer barrier overheads,
    # freshness p99 traded against eps (rw_mv_freshness measures it).
    overload_stretch: int = 4
    # the ladder's top rung: shed oldest unadmitted source windows into
    # the durable audited rw_shed_log table. DEFAULT OFF — with shedding
    # off the ladder caps at 'degraded' and results stay bit-identical
    # (throttling and stretch only re-time work, never change it).
    load_shed: bool = False
    # front-door SELECT admission: pgwire statements past this many
    # in-flight SELECTs get a clean SQLSTATE 53000 rejection instead of
    # queueing unboundedly on the coordinator lock. <= 0 disables the
    # gate (the repo's knob-off convention).
    select_concurrency: int = 64
    # per-session slice of the SELECT admission budget: one pgwire
    # session may hold at most this many in-flight SELECTs, so a chatty
    # session exhausts its own slice (53000) long before it can starve
    # the global budget for everyone else. <= 0 disables the per-session
    # cap (the knob-off convention); the global bound still applies.
    select_per_session: int = 8
    # serving-tier read cache (serving/read_cache.py): pgwire SELECTs
    # over fused MVs serve from host-side epoch-versioned snapshots —
    # one device pull per (MV, epoch) regardless of reader count, with
    # concurrent cache-miss readers coalesced onto a single pull.
    serving_cache: bool = True
    # staleness bound, in committed epochs: a cached snapshot serves iff
    # cache_epoch >= committed_epoch - serving_staleness_epochs. 0 =
    # always-fresh (the cache still coalesces readers within an epoch);
    # higher trades bounded staleness for zero pulls across commits.
    serving_staleness_epochs: int = 0
    # sink spool bound (rows buffered in one checkpoint window) past
    # which the sink reports pressure to the ladder; a stalled external
    # sink parks its backlog in the DURABLE sink log (disk), never RSS.
    sink_spool_rows: int = 65536
    # coordinator-side fused epoch event log byte cap: entries past it
    # spill beside epoch_profile.jsonl and reload transparently on
    # in-place recovery — a degraded-mode (stretched-cadence) job must
    # not trade queue growth for event-log growth.
    fused_epoch_log_bytes: int = 1 << 20
    # supervised stateful respawn refresh mode: True (default) seeds the
    # respawned worker with state as of its last DELIVERED epoch
    # (un-applying the retained crash-window input), replays the window,
    # and emits a per-epoch NET DIFF vs the seed snapshot — exact, no
    # duplicate rows downstream. False restores the v1 full owned-group
    # refresh (live-shadow seed + re-INSERT of every owned group), which
    # relies on materialize-by-pk / sink dedupe to reconcile.
    incremental_refresh: bool = True

    @classmethod
    def from_env(cls) -> "RobustnessConfig":
        import os
        cfg = cls()
        for f in fields(cls):
            var = "RW_" + f.name.upper()
            raw = os.environ.get(var)
            if raw is not None:
                kind = type(getattr(cfg, f.name))
                try:
                    if kind is bool:
                        low = raw.strip().lower()
                        if low in ("t", "true", "1", "on", "yes"):
                            setattr(cfg, f.name, True)
                        elif low in ("f", "false", "0", "off", "no"):
                            setattr(cfg, f.name, False)
                        else:
                            raise ValueError
                    else:
                        setattr(cfg, f.name, kind(raw))
                except ValueError:
                    raise ValueError(
                        f"bad {var}={raw!r}: expected {kind.__name__}"
                    ) from None
        return cfg


# process-global instance (env-seeded once; workers re-derive from the
# env they inherit at spawn)
ROBUSTNESS = RobustnessConfig.from_env()


@dataclass
class NodeConfig:
    """Per-process startup configuration (the `risingwave.toml` analog).

    Load with `NodeConfig.from_toml(path)`; unknown keys are rejected so
    typos fail at startup, like the reference's serde deny_unknown_fields.
    """
    streaming: StreamingConfig = field(default_factory=StreamingConfig)
    storage: StorageConfig = field(default_factory=StorageConfig)
    device: Optional[DeviceConfig] = None

    @classmethod
    def from_toml(cls, path: str) -> "NodeConfig":
        try:
            import tomllib             # stdlib since 3.11
        except ModuleNotFoundError:
            import tomli as tomllib    # same API on 3.10
        with open(path, "rb") as f:
            raw = tomllib.load(f)
        cfg = cls()
        for section, target in (("streaming", cfg.streaming),
                                ("storage", cfg.storage)):
            known = {f.name for f in fields(target)}
            for k, v in raw.pop(section, {}).items():
                if k not in known:
                    raise ValueError(
                        f"unknown config key [{section}] {k!r}")
                setattr(target, k, v)
        dev = raw.pop("device", None)
        if dev is not None:
            mode = dev.pop("mode", "off")
            for k in dev:
                if k not in ("capacity", "minmax", "fuse", "mesh_shards",
                             "replicas",
                             "mv_persist_every", "predictive_growth",
                             "hbm_budget_mb", "compile_cache_dir",
                             "profile", "aot_compile", "compile_buckets"):
                    raise ValueError(f"unknown config key [device] {k!r}")
            base = resolve_device(
                int(mode) if isinstance(mode, str) and mode.isdigit()
                else mode)
            if base is not None:
                for k, v in dev.items():
                    setattr(base, k, v)
            cfg.device = base
        if raw:
            raise ValueError(f"unknown config sections {sorted(raw)!r}")
        return cfg


class SystemParams:
    """Cluster parameters alterable via ALTER SYSTEM SET
    (`system_param/mod.rs:97`). Each entry: default + coercion; mutation
    goes through `set` so the runtime can react (e.g. checkpoint
    frequency applies to the running barrier injector)."""

    DEFAULTS: Dict[str, Any] = {
        "checkpoint_frequency": 1,
        "barrier_interval_ms": 1000,
        "pause_on_next_bootstrap": False,
    }

    def __init__(self) -> None:
        self.values: Dict[str, Any] = dict(self.DEFAULTS)

    def get(self, name: str) -> Any:
        if name not in self.values:
            raise ValueError(f"unknown system parameter {name!r}")
        return self.values[name]

    # per-parameter validation: stored and effective values must agree
    _MIN = {"checkpoint_frequency": 1, "barrier_interval_ms": 1}

    def set(self, name: str, value: Any) -> Any:
        if name not in self.DEFAULTS:
            raise ValueError(f"unknown system parameter {name!r}")
        want = type(self.DEFAULTS[name])
        if want is bool and isinstance(value, str):
            value = value.strip().lower() in ("t", "true", "1", "on")
        else:
            value = want(value)
        lo = self._MIN.get(name)
        if lo is not None and value < lo:
            raise ValueError(f"system parameter {name} must be >= {lo}")
        self.values[name] = value
        return value


# session variables: name -> default. The subset the runtime honors;
# unknown SET names are rejected like PG's "unrecognized configuration
# parameter". Values coerce to the default's type on SET.
SESSION_VAR_DEFAULTS: Dict[str, Any] = {
    "timezone": "UTC",
    "query_mode": "auto",
    "streaming_parallelism": 0,        # 0 = use the device config default
    # 'local' = parallel fragments as in-process generators (topology
    # only); 'process' = worker OS processes over the credit-flow exchange
    # (real CPU parallelism — the compute-node placement analog)
    "streaming_placement": "local",
    # true + process placement: a FragmentSupervisor respawns a single
    # dead worker in place (shadow re-seed / epoch replay) instead of
    # tearing the whole job down; bounded attempts, then the classic
    # RemoteWorkerDied full-recovery path (graceful degradation)
    "streaming_supervision": False,
    # true: plan eligible inner joins as arrangement-sharing lookup/delta
    # joins (ops/lookup_join.py) instead of private-state hash joins —
    # the reference's streaming_enable_delta_join session variable
    "streaming_enable_delta_join": False,
    "application_name": "",
    "extra_float_digits": 1,
}


def default_session_vars() -> Dict[str, Any]:
    return dict(SESSION_VAR_DEFAULTS)


def resolve_device(device) -> Optional[DeviceConfig]:
    """Normalize the Database(device=...) argument.

    None | "off"      -> host-only execution
    "on" | "single"   -> device path on one chip
    int n             -> device path sharded over an n-device mesh
    DeviceConfig      -> as given
    """
    if device is None or device == "off":
        return None
    if isinstance(device, DeviceConfig):
        cfg = device
    elif device in ("on", "single"):
        cfg = DeviceConfig()
    elif isinstance(device, int):
        from .parallel import make_mesh
        cfg = DeviceConfig(mesh=make_mesh(device))
    else:
        raise ValueError(f"bad device config {device!r}")
    if cfg.compile_cache_dir is not None:
        from .device import configure_compile_cache
        configure_compile_cache(cfg.compile_cache_dir)
    return cfg
