"""Runtime configuration.

The start of the reference's 3-tier config system (`src/common/src/
config.rs:137` node config, `system_param/mod.rs:97` cluster params,
`session_config/` session vars). The device tier here governs the
SQL->device dispatch seam: whether eligible plan fragments lower onto the
TPU executors and over which mesh.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional


@dataclass
class DeviceConfig:
    """Device-path lowering config (the `from_proto` dispatch policy).

    mesh      — jax.sharding.Mesh to shard operator state over; None = one
                chip (still jitted epoch steps, no collectives).
    capacity  — initial per-operator state slots (grows by pow2 on demand).
    minmax    — lower min/max aggregates onto the retractable sorted-
                multiset state (device/minput.py).
    """
    mesh: Optional[Any] = None
    capacity: int = 1024
    minmax: bool = True


def resolve_device(device) -> Optional[DeviceConfig]:
    """Normalize the Database(device=...) argument.

    None | "off"      -> host-only execution
    "on" | "single"   -> device path on one chip
    int n             -> device path sharded over an n-device mesh
    DeviceConfig      -> as given
    """
    if device is None or device == "off":
        return None
    if isinstance(device, DeviceConfig):
        return device
    if device in ("on", "single"):
        return DeviceConfig()
    if isinstance(device, int):
        from .parallel import make_mesh
        return DeviceConfig(mesh=make_mesh(device))
    raise ValueError(f"bad device config {device!r}")
