"""Sharded hash-join: two-sided exchange + per-shard join as ONE jitted step.

Device analog of the reference's north-star two-sided join path
(`dispatch.rs:843` hash dispatch on both inputs -> `merge.rs:235` alignment
-> `hash_join.rs:575-686` eq-join): inside a `shard_map` over the mesh each
shard

  1. CRC32-hashes BOTH sides' local rows by join key -> destination shards,
  2. buckets each side into a [n_shards, B] send buffer,
  3. two `lax.all_to_all`s swap the buckets over ICI,
  4. runs the sorted-multimap join epoch (`device/join_step.join_core`) on
     its own state shards.

Both sides route by the same key hash, so every (jk-equal) pair meets on
exactly one shard and the pair change set is exchange-free afterwards.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..core.vnode import VNODE_COUNT, compute_vnodes_jnp
from ..device.agg_step import _acc_cast, _bucket
from ..device.join_step import (JoinSide, grow_side, join_core, make_side,
                                sanitize_keys)
from ..device.sorted_state import EMPTY_KEY
from .mesh import (SHARD_AXIS, shard_map as _shard_map,
                   shard_of_vnode)
from .sharded_agg import _bucketize



def make_sharded_join_step(n_a_vals: int, n_b_vals: int, mesh: Mesh,
                           m: int, vnode_count: int = VNODE_COUNT):
    """Jitted distributed join epoch step. All arrays sharded on axis 0:
    sides' states are JoinSides of [n_shards, C] arrays; each input side is
    ([n_shards, B] jk/pk/sign/mask, tuple of [n_shards, B] vals)."""
    n = mesh.devices.size

    def exchange(jk, pk, signs, mask, vals):
        vn = compute_vnodes_jnp(jk, vnode_count)
        dest = shard_of_vnode(vn, n, vnode_count).astype(jnp.int32)
        flat = [jk, pk, signs.astype(jnp.int32)]
        fills: List[Any] = [EMPTY_KEY, EMPTY_KEY, 0]
        for v in vals:
            flat.append(v)
            fills.append(0)
        bufs = _bucketize(dest, mask, n, flat, fills)
        recv = [jax.lax.all_to_all(x, SHARD_AXIS, split_axis=0,
                                   concat_axis=0, tiled=False) for x in bufs]
        rb = n * jk.shape[0]
        rjk = recv[0].reshape(rb)
        rpk = recv[1].reshape(rb)
        rsign = recv[2].reshape(rb)
        rmask = rjk != EMPTY_KEY
        rvals = tuple(r.reshape(rb) for r in recv[3:])
        return rjk, rpk, rsign, rmask, rvals

    def local_step(a, b, a_in, b_in):
        drop = lambda s: JoinSide(s.jk[0], s.pk[0], s.count[0],
                                  tuple(v[0] for v in s.vals))
        sa, sb = drop(a), drop(b)
        # unpack [1, ...] shard slices
        a_jk, a_pk, a_sg, a_mask = (a_in[0][0], a_in[1][0], a_in[2][0],
                                    a_in[3][0])
        a_vals = tuple(v[0] for v in a_in[4])
        b_jk, b_pk, b_sg, b_mask = (b_in[0][0], b_in[1][0], b_in[2][0],
                                    b_in[3][0])
        b_vals = tuple(v[0] for v in b_in[4])

        ra = exchange(a_jk, a_pk, a_sg, a_mask, a_vals)
        rb = exchange(b_jk, b_pk, b_sg, b_mask, b_vals)

        new_a, new_b, o1, o2, needed = join_core(
            sa, sb, ra[0], ra[1], ra[2], ra[3], ra[4],
            rb[0], rb[1], rb[2], rb[3], rb[4], m)

        ex = lambda x: x[None]
        lift = lambda s: JoinSide(ex(s.jk), ex(s.pk), ex(s.count),
                                  tuple(ex(v) for v in s.vals))
        o1 = jax.tree_util.tree_map(ex, o1)
        o2 = jax.tree_util.tree_map(ex, o2)
        needed = jax.tree_util.tree_map(lambda x: ex(x[None]), needed)
        return lift(new_a), lift(new_b), o1, o2, needed

    sharded = P(SHARD_AXIS)

    def step(a, b, a_in, b_in):
        side_spec = lambda s: JoinSide(sharded, sharded, sharded,
                                       tuple(sharded for _ in s.vals))
        in_spec = lambda nv: (sharded, sharded, sharded, sharded,
                              tuple(sharded for _ in range(nv)))
        out_pairs = lambda nv_a, nv_b: {
            "sign": sharded, "jk": sharded, "a_pk": sharded, "b_pk": sharded,
            "a_vals": tuple(sharded for _ in range(nv_a)),
            "b_vals": tuple(sharded for _ in range(nv_b)),
            "mask": sharded}
        in_specs = (side_spec(a), side_spec(b),
                    in_spec(n_a_vals), in_spec(n_b_vals))
        out_specs = (side_spec(a), side_spec(b),
                     out_pairs(n_a_vals, n_b_vals),
                     out_pairs(n_a_vals, n_b_vals),
                     {"a": sharded, "b": sharded, "pairs": sharded})
        fn = _shard_map(local_step, mesh=mesh,
                           in_specs=in_specs, out_specs=out_specs)
        return fn(a, b, a_in, b_in)

    return jax.jit(step)


class ShardedHashJoin:
    """Host wrapper: sharded two-sided state + epoch buffering + growth.
    API-compatible with device/join_step.DeviceHashJoin."""

    def __init__(self, a_dtypes: Sequence, b_dtypes: Sequence, mesh: Mesh,
                 capacity: int = 1024, pair_capacity: int = 4096,
                 vnode_count: int = VNODE_COUNT):
        self.mesh = mesh
        self.n = mesh.devices.size
        self.vnode_count = vnode_count
        self.m = pair_capacity
        self._sharding = NamedSharding(mesh, P(SHARD_AXIS))
        self.a = self._make_side(capacity, a_dtypes)
        self.b = self._make_side(capacity, b_dtypes)
        self._steps: Dict[int, Any] = {}
        self._buf: Dict[str, List] = {"a": [], "b": []}

    def _make_side(self, capacity: int, dtypes: Sequence) -> JoinSide:
        s = make_side(capacity, dtypes)
        tile = lambda x: jax.device_put(
            np.broadcast_to(np.asarray(x)[None],
                            (self.n,) + x.shape).copy(), self._sharding)
        cnt = jax.device_put(np.zeros(self.n, np.int32), self._sharding)
        return JoinSide(tile(s.jk), tile(s.pk), cnt,
                        tuple(tile(v) for v in s.vals))

    def _grow_side(self, which: str, capacity: int) -> None:
        s = getattr(self, which)
        pad = capacity - s.jk.shape[1]
        padk = np.full((self.n, pad), EMPTY_KEY, dtype=np.int64)
        put = lambda arr, p: jax.device_put(
            np.concatenate([np.asarray(arr), p], 1), self._sharding)
        vals = tuple(put(v, np.zeros((self.n, pad), np.asarray(v).dtype))
                     for v in s.vals)
        setattr(self, which, JoinSide(put(s.jk, padk), put(s.pk, padk),
                                      s.count, vals))

    def live_side(self, side: str) -> Tuple[np.ndarray, np.ndarray]:
        s = getattr(self, "a" if side == "a" else "b")
        counts = np.asarray(s.count)
        jks = [np.asarray(s.jk)[i, : int(counts[i])] for i in range(self.n)]
        pks = [np.asarray(s.pk)[i, : int(counts[i])] for i in range(self.n)]
        return np.concatenate(jks), np.concatenate(pks)

    def load_side(self, side: str, jk, pk, vals=()) -> None:
        """Recovery: place rows on the shard owning their join key's vnode."""
        from ..core.vnode import crc32_bytes_matrix, _int_key_bytes
        which = "a" if side == "a" else "b"
        cur = getattr(self, which)
        jk = sanitize_keys(np.asarray(jk, np.int64))
        pk = sanitize_keys(np.asarray(pk, np.int64))
        vn = crc32_bytes_matrix(_int_key_bytes(jk)) % np.uint32(
            self.vnode_count)
        dest = shard_of_vnode(vn.astype(np.int64), self.n, self.vnode_count)
        per = [np.flatnonzero(dest == s) for s in range(self.n)]
        cap = _bucket(max([len(i) for i in per] + [cur.jk.shape[1]]))
        gjk = np.full((self.n, cap), EMPTY_KEY, np.int64)
        gpk = np.full((self.n, cap), EMPTY_KEY, np.int64)
        gvals = [np.zeros((self.n, cap), np.asarray(v).dtype)
                 for v in cur.vals]
        counts = np.zeros(self.n, np.int32)
        for s, idx in enumerate(per):
            order = idx[np.lexsort((pk[idx], jk[idx]))]
            counts[s] = len(order)
            gjk[s, : len(order)] = jk[order]
            gpk[s, : len(order)] = pk[order]
            for gv, v in zip(gvals, vals):
                gv[s, : len(order)] = np.asarray(v)[order]
        put = lambda a: jax.device_put(a, self._sharding)
        setattr(self, which, JoinSide(put(gjk), put(gpk), put(counts),
                                      tuple(put(v) for v in gvals)))

    def push_rows(self, side: str, jk, pk, signs, vals) -> None:
        self._buf[side].append((sanitize_keys(np.asarray(jk, np.int64)),
                                sanitize_keys(np.asarray(pk, np.int64)),
                                np.asarray(signs, np.int32),
                                [np.asarray(v) for v in vals]))

    def _shard2d(self, arr: np.ndarray, per: int, fill) -> jax.Array:
        out = np.full((self.n, per), fill, dtype=arr.dtype)
        for s in range(self.n):
            piece = arr[s::self.n]
            out[s, : len(piece)] = piece
        return jax.device_put(out, self._sharding)

    def _pack_side(self, buf, nvals, per):
        if buf:
            jk = np.concatenate([x[0] for x in buf])
            pk = np.concatenate([x[1] for x in buf])
            sg = np.concatenate([x[2] for x in buf])
            vals = [np.concatenate([x[3][i] for x in buf])
                    for i in range(nvals)]
        else:
            jk = pk = np.zeros(0, np.int64)
            sg = np.zeros(0, np.int32)
            vals = [np.zeros(0, np.int64)] * nvals
        mask = np.ones(len(jk), bool)
        return (self._shard2d(jk, per, EMPTY_KEY),
                self._shard2d(pk, per, EMPTY_KEY),
                self._shard2d(sg, per, 0),
                self._shard2d(mask, per, False),
                tuple(self._shard2d(_acc_cast(v), per, 0) for v in vals))

    def flush_epoch(self):
        na, nb = len(self.a.vals), len(self.b.vals)
        bufs = self._buf
        self._buf = {"a": [], "b": []}
        total = max([sum(len(x[0]) for x in bufs[s]) for s in ("a", "b")]
                    + [1])
        per = _bucket(-(-total // self.n), lo=64)
        A = self._pack_side(bufs["a"], na, per)
        B = self._pack_side(bufs["b"], nb, per)
        while True:
            step = self._steps.get(self.m)
            if step is None:
                step = self._steps[self.m] = make_sharded_join_step(
                    na, nb, self.mesh, self.m, self.vnode_count)
            new_a, new_b, o1, o2, needed = step(self.a, self.b, A, B)
            np_ = int(np.max(np.asarray(needed["pairs"])))
            if np_ > self.m:
                self.m = _bucket(np_, lo=self.m * 2)
                continue
            grown = False
            na_ = int(np.max(np.asarray(needed["a"])))
            nb_ = int(np.max(np.asarray(needed["b"])))
            if na_ > self.a.jk.shape[1]:
                self._grow_side("a", _bucket(na_, lo=self.a.jk.shape[1] * 2))
                grown = True
            if nb_ > self.b.jk.shape[1]:
                self._grow_side("b", _bucket(nb_, lo=self.b.jk.shape[1] * 2))
                grown = True
            if grown:
                continue
            self.a, self.b = new_a, new_b
            return (jax.tree_util.tree_map(np.asarray, o1),
                    jax.tree_util.tree_map(np.asarray, o2))
