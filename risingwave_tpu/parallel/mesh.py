"""Device mesh + vnode -> shard mapping.

Analog of the reference's WorkerSlotMapping / vnode mapping
(`src/common/src/hash/consistent_hash/vnode_mapping/`, `hash/
table_distribution.rs`): vnodes are assigned to parallel units in contiguous
blocks. Contiguous blocks (not round-robin) keep a shard's key-range compact,
which is what the sorted-run state wants, and make rescale a block-boundary
move (`scale.rs:2329` analog) rather than a full reshuffle.
"""
from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..core.vnode import VNODE_COUNT

SHARD_AXIS = "shard"

# jax moved shard_map out of experimental at 0.5; support both
try:
    shard_map = jax.shard_map
except AttributeError:                     # jax < 0.5
    from jax.experimental.shard_map import shard_map


def make_mesh(n_devices: Optional[int] = None,
              devices: Optional[Sequence] = None) -> Mesh:
    """1-D mesh over the shard axis. Multi-host meshes come from passing the
    global device list; the shape is (n,) either way — streaming dataflow
    parallelism is one-dimensional (vnodes), unlike ML TP x DP grids.

    When the default platform has fewer devices than requested (one real TPU
    chip but an 8-shard dry run), fall back to the CPU backend, which serves
    virtual devices under --xla_force_host_platform_device_count."""
    if devices is None:
        devices = jax.devices()
        if n_devices is not None:
            if len(devices) < n_devices:
                try:
                    cpu = jax.devices("cpu")
                except RuntimeError:
                    cpu = []
                if len(cpu) >= n_devices:
                    devices = cpu
            if len(devices) < n_devices:
                raise ValueError(
                    f"need {n_devices} devices but only {len(devices)} exist "
                    "(set XLA_FLAGS=--xla_force_host_platform_device_count=N "
                    "before jax initializes to get virtual CPU devices)")
            devices = devices[:n_devices]
    return Mesh(np.asarray(devices), (SHARD_AXIS,))


def vnode_block_bounds(n_shards: int, vnode_count: int = VNODE_COUNT
                       ) -> np.ndarray:
    """start vnode of each shard's contiguous block, plus end sentinel."""
    return (np.arange(n_shards + 1) * vnode_count) // n_shards


def shard_of_vnode(vnodes, n_shards: int, vnode_count: int = VNODE_COUNT):
    """Owning shard of each vnode — the exact inverse of
    `vnode_block_bounds`: shard k owns [bounds[k], bounds[k+1]), i.e.
    the largest k with (k*vnode_count)//n_shards <= v. The naive
    `(v*n)//vnode_count` disagrees at block boundaries whenever n_shards
    does not divide vnode_count (vnode 85 of 256 under 3 shards sits in
    block 1 but floor(85*3/256)=0), silently splitting a block across
    two shards. Works on numpy or jnp arrays (pure int arithmetic,
    jit-safe)."""
    return ((vnodes + 1) * n_shards - 1) // vnode_count


def state_sharding(mesh: Mesh) -> NamedSharding:
    """State arrays are [n_shards, ...] sharded on the leading axis."""
    return NamedSharding(mesh, P(SHARD_AXIS))
