"""Device mesh + vnode -> shard mapping.

Analog of the reference's WorkerSlotMapping / vnode mapping
(`src/common/src/hash/consistent_hash/vnode_mapping/`, `hash/
table_distribution.rs`): vnodes are assigned to parallel units in contiguous
blocks. Contiguous blocks (not round-robin) keep a shard's key-range compact,
which is what the sorted-run state wants, and make rescale a block-boundary
move (`scale.rs:2329` analog) rather than a full reshuffle.
"""
from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..core.vnode import VNODE_COUNT

SHARD_AXIS = "shard"
# Serving replicas: a second, named mesh axis. State PartitionSpecs only
# ever name SHARD_AXIS, and jax replicates over any mesh axis a spec
# does not mention — so the same P("shard") specs shard vnode blocks
# over the data axis and mirror them across replicas with zero operator
# changes. Collectives (all_to_all/psum/pmax) also name only SHARD_AXIS,
# which scopes them to the per-replica data group.
REPLICA_AXIS = "replica"

# jax moved shard_map out of experimental at 0.5; support both
try:
    shard_map = jax.shard_map
except AttributeError:                     # jax < 0.5
    from jax.experimental.shard_map import shard_map


def make_mesh(n_devices: Optional[int] = None,
              devices: Optional[Sequence] = None,
              replicas: int = 1) -> Mesh:
    """Mesh over the shard axis, optionally times a replica axis.

    `replicas=1` builds the exact 1-D `(n,)` mesh the engine has always
    used — same devices, same axis tuple — so every existing program
    lowers byte-for-byte identically. `replicas=r > 1` asks for
    `n_devices * r` devices and shapes them `(n_devices, r)` with axes
    `(shard, replica)`: device [d, k] holds data-shard d of replica k.

    When the default platform has fewer devices than requested (one real TPU
    chip but an 8-shard dry run), fall back to the CPU backend, which serves
    virtual devices under --xla_force_host_platform_device_count."""
    replicas = max(1, int(replicas))
    want = None if n_devices is None else int(n_devices) * replicas
    if devices is None:
        devices = jax.devices()
        if want is not None:
            if len(devices) < want:
                try:
                    cpu = jax.devices("cpu")
                except RuntimeError:
                    cpu = []
                if len(cpu) >= want:
                    devices = cpu
            if len(devices) < want:
                raise ValueError(
                    f"need {want} devices but only {len(devices)} exist "
                    "(set XLA_FLAGS=--xla_force_host_platform_device_count=N "
                    "before jax initializes to get virtual CPU devices)")
            devices = devices[:want]
    devices = np.asarray(devices)
    if replicas == 1:
        return Mesh(devices, (SHARD_AXIS,))
    if devices.size % replicas:
        raise ValueError(
            f"{devices.size} devices do not divide into {replicas} replicas")
    return Mesh(devices.reshape(devices.size // replicas, replicas),
                (SHARD_AXIS, REPLICA_AXIS))


def data_shards(mesh: Mesh) -> int:
    """Size of the vnode-partition (data) axis. Equals `devices.size` on
    the classic 1-D mesh; on a replicated 2-D mesh it is the per-replica
    shard count — the number every capacity/exchange/stat shape keys on."""
    return int(mesh.shape[SHARD_AXIS])


def mesh_replicas(mesh: Mesh) -> int:
    """Replica-axis size (1 on the classic 1-D mesh)."""
    return int(mesh.shape.get(REPLICA_AXIS, 1))


def vnode_block_bounds(n_shards: int, vnode_count: int = VNODE_COUNT
                       ) -> np.ndarray:
    """start vnode of each shard's contiguous block, plus end sentinel."""
    return (np.arange(n_shards + 1) * vnode_count) // n_shards


def shard_of_vnode(vnodes, n_shards: int, vnode_count: int = VNODE_COUNT):
    """Owning shard of each vnode — the exact inverse of
    `vnode_block_bounds`: shard k owns [bounds[k], bounds[k+1]), i.e.
    the largest k with (k*vnode_count)//n_shards <= v. The naive
    `(v*n)//vnode_count` disagrees at block boundaries whenever n_shards
    does not divide vnode_count (vnode 85 of 256 under 3 shards sits in
    block 1 but floor(85*3/256)=0), silently splitting a block across
    two shards. Works on numpy or jnp arrays (pure int arithmetic,
    jit-safe)."""
    return ((vnodes + 1) * n_shards - 1) // vnode_count


def state_sharding(mesh: Mesh) -> NamedSharding:
    """State arrays are [n_shards, ...] sharded on the leading axis."""
    return NamedSharding(mesh, P(SHARD_AXIS))
