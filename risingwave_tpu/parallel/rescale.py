"""Elastic rescale: move vnode-sharded device state between mesh sizes.

Analog of `ScaleController::reschedule_actors` + the vnode-bitmap updates
stateful executors apply at barriers (`src/meta/src/stream/scale.rs:2329`,
`state_table.rs:694-790`): state rows move to the shard that owns their
vnode under the new mapping. Runs at a barrier boundary (no in-flight
epoch), host-driven — rescale is rare and control-plane-paced, so the
gather/scatter through host memory is the simple correct choice; the
steady-state path never pays for it.
"""
from __future__ import annotations

from typing import List, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..core.vnode import VNODE_COUNT
from ..device.sorted_state import EMPTY_KEY, SortedState, _neutral
from .mesh import SHARD_AXIS, shard_of_vnode


def _vnode_of_keys(keys: np.ndarray, vnode_count: int) -> np.ndarray:
    """vnode per key — must match the device exchange's CRC32 routing."""
    from ..native import vnodes_i64
    vn = vnodes_i64(keys, vnode_count)
    if vn is not None:
        return vn
    from ..core.vnode import crc32_bytes_matrix, _int_key_bytes
    crc = crc32_bytes_matrix(_int_key_bytes(keys))
    return (crc % np.uint32(vnode_count)).astype(np.int32)


def reshard_state(state: SortedState, kinds, new_mesh: Mesh,
                  vnode_count: int = VNODE_COUNT,
                  min_capacity: int = 64) -> SortedState:
    """Redistribute a [n_old, C] sharded SortedState onto `new_mesh`.

    Per-shard sorted order is preserved (keys were globally hashed, so a
    shard's rows stay sorted after filtering), capacity grows to the
    largest new shard (pow2)."""
    n_new = new_mesh.devices.size
    keys = np.asarray(state.keys).reshape(-1)          # [n_old * C]
    vals = [np.asarray(v).reshape(-1) for v in state.vals]
    live = keys != EMPTY_KEY
    lkeys = keys[live]
    lvals = [v[live] for v in vals]
    dest = shard_of_vnode(_vnode_of_keys(lkeys, vnode_count), n_new,
                          vnode_count)
    counts = np.bincount(dest, minlength=n_new)
    cap = max(min_capacity, 1 << int(max(1, counts.max()) - 1).bit_length())
    new_keys = np.full((n_new, cap), EMPTY_KEY, dtype=np.int64)
    new_vals = [np.full((n_new, cap), np.asarray(_neutral(k, v.dtype)),
                        dtype=v.dtype) for v, k in zip(lvals, kinds)]
    for s in range(n_new):
        sel = dest == s
        ks = lkeys[sel]
        order = np.argsort(ks, kind="stable")
        n = len(ks)
        new_keys[s, :n] = ks[order]
        for dst, src in zip(new_vals, lvals):
            dst[s, :n] = src[sel][order]
    sharding = NamedSharding(new_mesh, P(SHARD_AXIS))
    return SortedState(
        jax.device_put(new_keys, sharding),
        jax.device_put(counts.astype(np.int32), sharding),
        tuple(jax.device_put(v, sharding) for v in new_vals))


def reshard_multiset(ms, new_mesh: Mesh, vnode_count: int = VNODE_COUNT,
                     min_capacity: int = 64):
    """Redistribute a [n_old, C] sharded SortedMultiset (retractable
    min/max side state) onto `new_mesh` — pairs follow their GROUP key's
    vnode, the same routing as the main state's rows."""
    from ..device.minput import SortedMultiset
    n_new = new_mesh.devices.size
    k1 = np.asarray(ms.k1).reshape(-1)
    k2 = np.asarray(ms.k2).reshape(-1)
    cnt = np.asarray(ms.cnt).reshape(-1)
    live = k1 != EMPTY_KEY
    k1, k2, cnt = k1[live], k2[live], cnt[live]
    dest = shard_of_vnode(_vnode_of_keys(k1, vnode_count), n_new, vnode_count)
    counts = np.bincount(dest, minlength=n_new)
    cap = max(min_capacity, 1 << int(max(1, counts.max()) - 1).bit_length())
    nk1 = np.full((n_new, cap), EMPTY_KEY, dtype=np.int64)
    nk2 = np.full((n_new, cap), EMPTY_KEY, dtype=np.int64)
    ncnt = np.zeros((n_new, cap), dtype=np.int64)
    for s in range(n_new):
        sel = dest == s
        order = np.lexsort((k2[sel], k1[sel]))
        n = int(sel.sum())
        nk1[s, :n] = k1[sel][order]
        nk2[s, :n] = k2[sel][order]
        ncnt[s, :n] = cnt[sel][order]
    sharding = NamedSharding(new_mesh, P(SHARD_AXIS))
    return SortedMultiset(
        jax.device_put(nk1, sharding), jax.device_put(nk2, sharding),
        jax.device_put(counts.astype(np.int32), sharding),
        jax.device_put(ncnt, sharding))
