"""Sharded hash-agg: exchange + per-shard epoch apply as ONE jitted step.

This is the device analog of the reference's hot path #2 + #3
(`dispatch.rs:843` vnode hash dispatch -> `merge.rs:235` alignment ->
`hash_agg.rs:331` apply): inside a `shard_map` over the mesh each shard

  1. CRC32-hashes its local rows to vnodes -> destination shards,
  2. buckets rows into a [n_shards, B] send buffer,
  3. `lax.all_to_all` swaps buckets over ICI,
  4. runs the sorted-run agg epoch step on its own state shard.

The change set comes back sharded; the host assembles the barrier change
chunk. One XLA program per epoch = no data-dependent launches, and the
all-to-all is the only cross-device traffic.
"""
from __future__ import annotations

from functools import partial
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..core.vnode import VNODE_COUNT, compute_vnodes_jnp
from ..device.agg_step import (DeviceAggSpec, DeviceAggState, _acc_cast,
                               _bucket, epoch_core_full)
from ..device.minput import SortedMultiset, ms_make
from ..device.sorted_state import EMPTY_KEY, SortedState, sanitize_keys
from .mesh import (SHARD_AXIS, shard_map as _shard_map,
                   shard_of_vnode)



def _bucketize(dest: jax.Array, mask: jax.Array, n_shards: int,
               arrays: Sequence[jax.Array], fills: Sequence[Any]
               ) -> List[jax.Array]:
    """Scatter local rows [B] into per-destination buffers [n_shards, B].

    Position within a destination bucket = running count of earlier rows with
    the same destination (a per-destination cumsum — the vectorized form of
    the reference's per-output StreamChunkBuilder in `dispatch.rs:843-930`).
    """
    b = dest.shape[0]
    onehot = (dest[None, :] == jnp.arange(n_shards)[:, None]) & mask[None, :]
    pos = jnp.cumsum(onehot, axis=1) - 1          # [n_shards, B]
    pos_of_row = jnp.take_along_axis(pos, dest[None, :], axis=0)[0]
    row_dest = jnp.where(mask, dest, n_shards)    # OOB drop for padding
    out = []
    for arr, fill in zip(arrays, fills):
        buf = jnp.full((n_shards, b), fill, dtype=arr.dtype)
        out.append(buf.at[row_dest, pos_of_row].set(arr, mode="drop"))
    return out


def make_sharded_agg_step(spec: DeviceAggSpec, mesh: Mesh,
                          vnode_count: int = VNODE_COUNT):
    """Build the jitted distributed epoch step.

    Signature of the returned fn (all global arrays, sharded on axis 0):
        state:  SortedState of [n_shards, C] arrays
        keys:   [n_shards, B] int64   (rows resident on each source shard)
        signs:  [n_shards, B] int32
        mask:   [n_shards, B] bool
        inputs: tuple of ([n_shards, B] values, [n_shards, B] valid) per call
    Returns (new_state, needed[n_shards], changes dict of [n_shards, R*]).
    """
    n = mesh.devices.size
    ncalls = len(spec.calls)
    npay = len(spec.kinds)
    nms = len(spec.minputs)

    def local_step(state, minputs, keys, signs, mask, inputs):
        # shard_map gives [1, ...] slices; drop the leading mesh axis
        st = SortedState(state.keys[0], state.count[0],
                         tuple(v[0] for v in state.vals))
        mss = tuple(SortedMultiset(m.k1[0], m.k2[0], m.count[0], m.cnt[0])
                    for m in minputs)
        keys, signs, mask = keys[0], signs[0], mask[0]
        inputs = tuple((v[0], m[0]) for v, m in inputs)
        b = keys.shape[0]

        # ---- exchange: vnode hash -> all_to_all --------------------------
        vn = compute_vnodes_jnp(keys, vnode_count)
        dest = shard_of_vnode(vn, n, vnode_count).astype(jnp.int32)
        flat: List[jax.Array] = [keys, signs.astype(jnp.int32)]
        fills: List[Any] = [EMPTY_KEY, 0]
        for v, m in inputs:
            flat += [v, m]
            fills += [0, False]
        bufs = _bucketize(dest, mask, n, flat, fills)
        recv = [jax.lax.all_to_all(x, SHARD_AXIS, split_axis=0, concat_axis=0,
                                   tiled=False) for x in bufs]
        rb = n * b
        rkeys = recv[0].reshape(rb)
        rsigns = recv[1].reshape(rb)
        rmask = rkeys != EMPTY_KEY
        rinputs = tuple((recv[2 + 2 * i].reshape(rb),
                         recv[3 + 2 * i].reshape(rb))
                        for i in range(ncalls))

        # ---- per-shard agg epoch apply (shared core with agg_step) ----
        full = DeviceAggState(st, mss)
        new_full, (needed, ms_needed), ch = epoch_core_full(
            spec, full, rkeys, rsigns, rmask, rinputs)

        ex = lambda x: x[None]    # re-add the mesh axis for out_specs
        changes = jax.tree_util.tree_map(
            ex, {**ch, "count": ch["count"][None]})
        new_st = new_full.main
        out_state = SortedState(ex(new_st.keys), ex(new_st.count),
                                tuple(ex(v) for v in new_st.vals))
        out_ms = tuple(SortedMultiset(ex(m.k1), ex(m.k2), ex(m.count),
                                      ex(m.cnt)) for m in new_full.minputs)
        return (out_state, out_ms, ex(needed[None]),
                tuple(ex(nd[None]) for nd in ms_needed), changes)

    sharded = P(SHARD_AXIS)

    def step(state, minputs, keys, signs, mask, inputs):
        main_spec = SortedState(sharded, sharded,
                                tuple(sharded for _ in state.vals))
        ms_spec = tuple(SortedMultiset(sharded, sharded, sharded, sharded)
                        for _ in range(nms))
        in_specs = (main_spec, ms_spec, sharded, sharded, sharded,
                    tuple((sharded, sharded) for _ in inputs))
        ch_spec = {"keys": sharded, "count": sharded,
                   "old_found": sharded, "new_found": sharded,
                   "old_out": tuple(sharded for _ in range(ncalls)),
                   "old_null": tuple(sharded for _ in range(ncalls)),
                   "new_out": tuple(sharded for _ in range(ncalls)),
                   "new_null": tuple(sharded for _ in range(ncalls)),
                   "old_vals": tuple(sharded for _ in range(npay)),
                   "new_vals": tuple(sharded for _ in range(npay))}
        for mi in range(nms):
            ch_spec[f"minput{mi}"] = {
                k: sharded for k in ("old_found", "old_min", "old_max",
                                     "new_found", "new_min", "new_max",
                                     "u1", "u2", "u_cnt")}
        out_specs = (main_spec, ms_spec, sharded,
                     tuple(sharded for _ in range(nms)), ch_spec)
        fn = _shard_map(local_step, mesh=mesh,
                           in_specs=in_specs, out_specs=out_specs)
        return fn(state, minputs, keys, signs, mask, inputs)

    return jax.jit(step)


class ShardedHashAgg:
    """Host wrapper: global sharded state + epoch buffering + growth."""

    def __init__(self, spec: DeviceAggSpec, mesh: Mesh, capacity: int = 1024,
                 vnode_count: int = VNODE_COUNT,
                 pull_formatted: bool = True):
        self.spec = spec
        self.pull_formatted = pull_formatted
        self.mesh = mesh
        self.n = mesh.devices.size
        self.vnode_count = vnode_count
        self._step = make_sharded_agg_step(spec, mesh, vnode_count)
        self._sharding = NamedSharding(mesh, P(SHARD_AXIS))
        self.state = self._make_state(capacity)
        self.minputs: Tuple[SortedMultiset, ...] = tuple(
            self._make_minput(capacity) for _ in spec.minputs)
        self._rows: List[Tuple[np.ndarray, ...]] = []

    def _make_state(self, capacity: int) -> SortedState:
        from ..device.sorted_state import make_state
        st = make_state(capacity, self.spec.dtypes, self.spec.kinds)
        tile = lambda x: jax.device_put(
            np.broadcast_to(np.asarray(x)[None], (self.n,) + x.shape).copy(),
            self._sharding)
        cnt = jax.device_put(np.zeros(self.n, np.int32), self._sharding)
        return SortedState(tile(st.keys), cnt,
                           tuple(tile(v) for v in st.vals))

    def _make_minput(self, capacity: int) -> SortedMultiset:
        ms = ms_make(capacity)
        tile = lambda x: jax.device_put(
            np.broadcast_to(np.asarray(x)[None],
                            (self.n,) + x.shape).copy(), self._sharding)
        cnt = jax.device_put(np.zeros(self.n, np.int32), self._sharding)
        return SortedMultiset(tile(ms.k1), tile(ms.k2), cnt, tile(ms.cnt))

    def _grow_minput(self, mi: int, capacity: int) -> None:
        ms = self.minputs[mi]
        pad = capacity - ms.k1.shape[1]
        padk = np.full((self.n, pad), EMPTY_KEY, dtype=np.int64)
        padc = np.zeros((self.n, pad), dtype=np.int64)
        put = lambda a, p: jax.device_put(
            np.concatenate([np.asarray(a), p], 1), self._sharding)
        new = SortedMultiset(put(ms.k1, padk), put(ms.k2, padk),
                             ms.count, put(ms.cnt, padc))
        self.minputs = self.minputs[:mi] + (new,) + self.minputs[mi + 1:]

    @staticmethod
    def _flatten_sharded(counts: np.ndarray, arrs: Sequence[np.ndarray]
                         ) -> List[np.ndarray]:
        """[n, C] arrays + per-shard live counts -> concatenated live rows."""
        pieces = [[a[s, : int(counts[s])] for s in range(len(counts))]
                  for a in arrs]
        return [np.concatenate(p) if p else np.zeros(0) for p in pieces]

    def live_main(self) -> Tuple[np.ndarray, List[np.ndarray]]:
        counts = np.asarray(self.state.count)
        arrs = [np.asarray(self.state.keys)] + \
            [np.asarray(v) for v in self.state.vals]
        flat = self._flatten_sharded(counts, arrs)
        return flat[0], flat[1:]

    def live_minput(self, mi: int) -> Tuple[np.ndarray, np.ndarray,
                                            np.ndarray]:
        ms = self.minputs[mi]
        counts = np.asarray(ms.count)
        flat = self._flatten_sharded(counts, [np.asarray(ms.k1),
                                              np.asarray(ms.k2),
                                              np.asarray(ms.cnt)])
        return flat[0], flat[1], flat[2]

    def load_minput(self, mi: int, k1: np.ndarray, k2: np.ndarray,
                    cnt: np.ndarray) -> None:
        """Recovery: place (group, value, count) pairs on the shard owning
        the GROUP key's vnode (same routing as the main state)."""
        from ..core.vnode import crc32_bytes_matrix, _int_key_bytes
        k1 = sanitize_keys(np.asarray(k1, np.int64))
        k2 = np.asarray(k2, np.int64)   # values are k1-discriminated
        cnt = np.asarray(cnt, np.int64)
        vn = crc32_bytes_matrix(_int_key_bytes(k1)) % np.uint32(
            self.vnode_count)
        dest = shard_of_vnode(vn.astype(np.int64), self.n, self.vnode_count)
        per = [np.flatnonzero(dest == s) for s in range(self.n)]
        cap = _bucket(max([len(i) for i in per]
                          + [self.minputs[mi].k1.shape[1]]))
        gk1 = np.full((self.n, cap), EMPTY_KEY, np.int64)
        gk2 = np.full((self.n, cap), EMPTY_KEY, np.int64)
        gc = np.zeros((self.n, cap), np.int64)
        counts = np.zeros(self.n, np.int32)
        for s, idx in enumerate(per):
            order = idx[np.lexsort((k2[idx], k1[idx]))]
            counts[s] = len(order)
            gk1[s, : len(order)] = k1[order]
            gk2[s, : len(order)] = k2[order]
            gc[s, : len(order)] = cnt[order]
        put = lambda a: jax.device_put(a, self._sharding)
        new = SortedMultiset(put(gk1), put(gk2), put(counts), put(gc))
        self.minputs = self.minputs[:mi] + (new,) + self.minputs[mi + 1:]

    @property
    def capacity(self) -> int:
        return self.state.keys.shape[1]

    def push_rows(self, keys: np.ndarray, signs: np.ndarray,
                  inputs: Sequence[Tuple[np.ndarray, np.ndarray]]) -> None:
        if self.spec.append_only and (np.asarray(signs) < 0).any():
            raise ValueError(
                "retraction through an append-only (min/max) device agg — "
                "use the exact host path (aggregate/minput.rs analog)")
        self._rows.append((sanitize_keys(keys), signs.astype(np.int32),
                           [(np.asarray(v), np.asarray(m)) for v, m in inputs]))

    def _grow(self, capacity: int) -> None:
        st = self.state
        pad = capacity - self.capacity
        padk = np.full((self.n, pad), EMPTY_KEY, dtype=np.int64)
        keys = jax.device_put(np.concatenate([np.asarray(st.keys), padk], 1),
                              self._sharding)
        vals = []
        from ..device.sorted_state import _neutral
        for v, k in zip(st.vals, self.spec.kinds):
            nv = np.asarray(_neutral(k, v.dtype))
            padv = np.full((self.n, pad), nv, dtype=np.asarray(v).dtype)
            vals.append(jax.device_put(
                np.concatenate([np.asarray(v), padv], 1), self._sharding))
        self.state = SortedState(keys, st.count, tuple(vals))

    def load_state(self, keys: np.ndarray,
                   vals: Sequence[np.ndarray]) -> None:
        """Recovery: place (key, payload...) rows on their owning shards
        (vnode of the device key — must agree with the jitted exchange's
        crc32_u64_jnp routing) and install as the sharded state."""
        from ..core.vnode import crc32_bytes_matrix, _int_key_bytes
        from .mesh import shard_of_vnode as _sov
        keys = sanitize_keys(np.asarray(keys, np.int64))
        vn = crc32_bytes_matrix(_int_key_bytes(keys)) % np.uint32(
            self.vnode_count)
        dest = _sov(vn.astype(np.int64), self.n, self.vnode_count)
        per_shard = [np.flatnonzero(dest == s) for s in range(self.n)]
        cap = _bucket(max([len(i) for i in per_shard] + [self.capacity]))
        proto = self.spec.make_state(cap)
        gkeys = np.broadcast_to(np.asarray(proto.keys)[None],
                                (self.n, cap)).copy()
        gvals = [np.broadcast_to(np.asarray(v)[None], (self.n, cap)).copy()
                 for v in proto.vals]
        counts = np.zeros(self.n, np.int32)
        for s, idx in enumerate(per_shard):
            order = idx[np.argsort(keys[idx], kind="stable")]
            counts[s] = len(order)
            gkeys[s, : len(order)] = keys[order]
            for gv, v in zip(gvals, vals):
                gv[s, : len(order)] = np.asarray(v)[order]
        put = lambda a: jax.device_put(a, self._sharding)
        self.state = SortedState(put(gkeys), put(counts),
                                 tuple(put(v) for v in gvals))

    def rescale(self, new_mesh: Mesh) -> None:
        """Barrier-synchronized elastic re-shard onto a different mesh
        (`scale.rs:2329` analog). Epoch buffers must be flushed first."""
        assert not self._rows, "rescale must happen at a barrier boundary"
        from .rescale import reshard_multiset, reshard_state
        self.state = reshard_state(self.state, self.spec.kinds, new_mesh,
                                   self.vnode_count)
        self.minputs = tuple(reshard_multiset(m, new_mesh, self.vnode_count)
                             for m in self.minputs)
        self.mesh = new_mesh
        self.n = new_mesh.devices.size
        self._step = make_sharded_agg_step(self.spec, new_mesh,
                                           self.vnode_count)
        self._sharding = NamedSharding(new_mesh, P(SHARD_AXIS))

    def flush_epoch(self) -> Optional[Dict[str, Any]]:
        if not self._rows:
            return None
        keys = np.concatenate([r[0] for r in self._rows])
        signs = np.concatenate([r[1] for r in self._rows])
        ins = [(np.concatenate([r[2][i][0] for r in self._rows]),
                np.concatenate([r[2][i][1] for r in self._rows]))
               for i in range(len(self.spec.calls))]
        self._rows = []
        # partition rows round-robin across source shards, pad to [n, B]
        total = len(keys)
        per = _bucket(-(-total // self.n), lo=64)
        def shard2d(a, fill):
            out = np.full((self.n, per), fill, dtype=a.dtype)
            for s in range(self.n):
                piece = a[s::self.n]
                out[s, : len(piece)] = piece
            return jax.device_put(out, self._sharding)
        gkeys = shard2d(keys, EMPTY_KEY)
        gsigns = shard2d(signs, 0)
        mask = shard2d(np.ones(total, bool), False)
        gins = tuple((shard2d(_acc_cast(v), 0),
                      shard2d(m.astype(bool), False)) for v, m in ins)
        while True:
            new_state, new_ms, needed, ms_needed, changes = self._step(
                self.state, self.minputs, gkeys, gsigns, mask, gins)
            grown = False
            nmax = int(np.max(np.asarray(needed)))
            if nmax > self.capacity:
                self._grow(_bucket(nmax, lo=self.capacity * 2))
                grown = True
            for mi, nd in enumerate(ms_needed):
                m = int(np.max(np.asarray(nd)))
                cap = self.minputs[mi].k1.shape[1]
                if m > cap:
                    self._grow_minput(mi, _bucket(m, lo=cap * 2))
                    grown = True
            if grown:
                continue
            self.state, self.minputs = new_state, new_ms
            # one batched transfer; pipeline-only formatted outputs skip
            # the pull when the consumer formats from raw payloads
            from ..device.agg_step import _PULL_DROP
            return jax.device_get(
                {k: v for k, v in changes.items()
                 if self.pull_formatted or k not in _PULL_DROP})
