"""Parallel execution: vnode-sharded dataflow over a jax device mesh.

The reference's only compute parallelism is streaming data parallelism:
rows hash to one of VNODE_COUNT virtual nodes (CRC32, `consistent_hash/
vnode.rs:30`), vnodes map to parallel actors, and a HashDataDispatcher +
MergeExecutor pair moves rows between them over gRPC with credit-based
backpressure (`dispatch.rs:777`, `merge.rs:235`, `exchange/permit.rs:35`).

TPU-native re-design: the parallel units are mesh shards. vnode -> shard is a
static contiguous-block map, the hash exchange is a single
`lax.all_to_all` over ICI inside a `shard_map`'d epoch step, and barrier
alignment is implicit — the all-to-all IS the barrier-granular exchange, so
no per-channel alignment machinery is needed. Backpressure degenerates to the
host feeding epochs one at a time.
"""
from .mesh import make_mesh, shard_of_vnode, vnode_block_bounds  # noqa: F401
from .sharded_agg import ShardedHashAgg, make_sharded_agg_step  # noqa: F401
