"""Epochs — the global logical clock advanced by barriers.

Mirrors `src/common/src/util/epoch.rs:31-127`: an epoch is a 64-bit value,
`physical_time_ms << 16`, with the low 16 bits as a sequence number so multiple
barriers can share one millisecond. `EpochPair{curr, prev}` travels in every
barrier; state commits are tagged with `curr`.
"""
from __future__ import annotations

import time
from dataclasses import dataclass

EPOCH_PHYSICAL_SHIFT = 16
INVALID_EPOCH = 0


def epoch_from_physical(ms: int, seq: int = 0) -> int:
    return (ms << EPOCH_PHYSICAL_SHIFT) | (seq & 0xFFFF)


def physical_time_ms(epoch: int) -> int:
    return epoch >> EPOCH_PHYSICAL_SHIFT


def now_epoch(prev: int = 0) -> int:
    """A fresh epoch strictly greater than prev."""
    e = epoch_from_physical(int(time.time() * 1000))
    return e if e > prev else prev + 1


@dataclass(frozen=True)
class EpochPair:
    """`EpochPair` (`epoch.rs`): curr = the epoch being opened by this barrier,
    prev = the epoch being sealed."""
    curr: int
    prev: int

    @classmethod
    def new_initial(cls, curr: int) -> "EpochPair":
        return cls(curr=curr, prev=INVALID_EPOCH)

    def next(self, curr: int) -> "EpochPair":
        assert curr > self.curr
        return EpochPair(curr=curr, prev=self.curr)

    def next_seq(self) -> "EpochPair":
        return EpochPair(curr=self.curr + 1, prev=self.curr)
