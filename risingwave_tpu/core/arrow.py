"""Arrow interop: DataChunk/StreamChunk <-> pyarrow RecordBatch, and the
zero-copy host->device seam.

Reference: `src/common/src/array/arrow/arrow_impl.rs:64` (ToArrow) and
`:472` (FromArrow) — the reference's external columnar boundary (UDFs,
Iceberg, connectors) is Arrow; this module is the same seam. Fixed-width
columns cross WITHOUT copying values (`pa.Array.from_buffers` over the
numpy buffer; only the validity bitmap is packed), and `to_jax` moves a
column into a device buffer with no intermediate host copy
(`jnp.asarray` rides dlpack on CPU and the direct transfer path on TPU).

BASELINE.json names this ingestion seam explicitly: StreamChunk batches
zero-copy into jax.Array via Arrow.
"""
from __future__ import annotations

from decimal import Decimal
from typing import Any, List, Optional, Tuple

import numpy as np

from . import dtypes as T
from .chunk import Column, DataChunk, Op, StreamChunk
from .dtypes import DataType, TypeKind
from .schema import Schema


def _pa():
    import pyarrow
    return pyarrow


# fixed-width kinds that cross zero-copy (value buffer shared)
_FIXED = {
    TypeKind.INT16: "int16", TypeKind.INT32: "int32",
    TypeKind.INT64: "int64", TypeKind.SERIAL: "int64",
    TypeKind.FLOAT32: "float32", TypeKind.FLOAT64: "float64",
}


def _arrow_type(dtype: DataType):
    pa = _pa()
    k = dtype.kind
    if k in _FIXED:
        return getattr(pa, _FIXED[k])()
    if k == TypeKind.BOOLEAN:
        return pa.bool_()
    if k == TypeKind.VARCHAR:
        return pa.string()
    if k == TypeKind.BYTEA:
        return pa.binary()
    if k == TypeKind.TIMESTAMP:
        return pa.timestamp("us")
    if k == TypeKind.TIMESTAMPTZ:
        return pa.timestamp("us", tz="UTC")
    if k == TypeKind.DATE:
        return pa.date32()
    if k == TypeKind.TIME:
        return pa.time64("us")
    if k == TypeKind.DECIMAL:
        # rw_int256-free subset: 38 digits, dynamic scale handled at
        # conversion (arrow_impl.rs maps Decimal -> Decimal128 likewise)
        return pa.decimal128(38, 9)
    if k == TypeKind.INTERVAL:
        return pa.month_day_nano_interval()
    raise ValueError(f"no arrow mapping for {dtype}")


def _validity_buffer(validity: np.ndarray):
    pa = _pa()
    if validity.all():
        return None
    return pa.py_buffer(np.packbits(validity, bitorder="little").tobytes())


def column_to_arrow(col: Column):
    """Column -> pa.Array; fixed-width value buffers are SHARED."""
    pa = _pa()
    k = col.dtype.kind
    if k in _FIXED:
        vals = np.ascontiguousarray(col.values)
        typ = _arrow_type(col.dtype)
        return pa.Array.from_buffers(
            typ, len(vals),
            [_validity_buffer(col.validity), pa.py_buffer(vals)],
            null_count=int((~col.validity).sum()))
    if k in (TypeKind.TIMESTAMP, TypeKind.TIMESTAMPTZ, TypeKind.TIME):
        vals = np.ascontiguousarray(col.values.astype(np.int64))
        return pa.Array.from_buffers(
            _arrow_type(col.dtype), len(vals),
            [_validity_buffer(col.validity), pa.py_buffer(vals)],
            null_count=int((~col.validity).sum()))
    if k == TypeKind.DATE:
        vals = np.ascontiguousarray(col.values.astype(np.int32))
        return pa.Array.from_buffers(
            _arrow_type(col.dtype), len(vals),
            [_validity_buffer(col.validity), pa.py_buffer(vals)],
            null_count=int((~col.validity).sum()))
    # variable width / object columns: element-wise conversion
    items = [col.get(i) for i in range(len(col))]
    if k == TypeKind.INTERVAL:
        pa_ = _pa()
        items = [None if v is None else
                 pa_.MonthDayNano([v.months, v.days, v.usecs * 1000])
                 for v in items]
        return pa_.array(items, type=_arrow_type(col.dtype))
    if k == TypeKind.DECIMAL:
        items = [None if v is None else Decimal(v) for v in items]
    return _pa().array(items, type=_arrow_type(col.dtype))


def column_from_arrow(arr, dtype: DataType) -> Column:
    """pa.Array -> Column; fixed-width value buffers are SHARED."""
    arr = arr.combine_chunks() if hasattr(arr, "combine_chunks") else arr
    k = dtype.kind
    n = len(arr)
    if k in _FIXED or k in (TypeKind.TIMESTAMP, TypeKind.TIMESTAMPTZ,
                            TypeKind.TIME, TypeKind.DATE):
        np_dt = {TypeKind.TIMESTAMP: np.int64, TypeKind.TIMESTAMPTZ: np.int64,
                 TypeKind.TIME: np.int64, TypeKind.DATE: np.int32}.get(
                     k, np.dtype(_FIXED.get(k, "int64")))
        buffers = arr.buffers()
        off = arr.offset
        vals = np.frombuffer(buffers[1], dtype=np_dt,
                             count=n + off)[off:]
        if buffers[0] is None:
            validity = np.ones(n, dtype=bool)
        else:
            bits = np.frombuffer(buffers[0], dtype=np.uint8)
            validity = np.unpackbits(bits, bitorder="little",
                                     count=n + off)[off:].astype(bool)
        return Column(dtype, vals, validity)
    items = arr.to_pylist()
    if k == TypeKind.INTERVAL:
        from .dtypes import Interval
        items = [None if v is None else
                 Interval(v.months, v.days, v.nanoseconds // 1000)
                 for v in items]
    return Column.from_list(dtype, items)


def datachunk_to_arrow(chunk: DataChunk, names: Optional[List[str]] = None):
    pa = _pa()
    names = names or [f"c{i}" for i in range(len(chunk.columns))]
    return pa.RecordBatch.from_arrays(
        [column_to_arrow(c) for c in chunk.columns], names=names)


def datachunk_from_arrow(batch, dtypes: List[DataType]) -> DataChunk:
    cols = [column_from_arrow(batch.column(i), dt)
            for i, dt in enumerate(dtypes)]
    return DataChunk(cols)


def streamchunk_to_arrow(chunk: StreamChunk,
                         names: Optional[List[str]] = None):
    """StreamChunk -> RecordBatch with a leading `__op__` int8 column
    (I/U-/U+/D), visibility compacted away first."""
    pa = _pa()
    chunk = chunk.compact()
    names = names or [f"c{i}" for i in range(len(chunk.columns))]
    arrays = [pa.array(chunk.ops, type=pa.int8())] \
        + [column_to_arrow(c) for c in chunk.columns]
    return pa.RecordBatch.from_arrays(arrays, names=["__op__"] + names)


def streamchunk_from_arrow(batch, dtypes: List[DataType]) -> StreamChunk:
    ops = np.asarray(batch.column(0)).astype(np.int8)
    cols = [column_from_arrow(batch.column(i + 1), dt)
            for i, dt in enumerate(dtypes)]
    return StreamChunk(ops, cols)


def _device_representable(dtype: DataType) -> bool:
    return dtype.kind in _FIXED or dtype.kind in (
        TypeKind.TIMESTAMP, TypeKind.DATE, TypeKind.BOOLEAN)


def to_jax(col: Column):
    """Device transfer with no intermediate host copy: numpy -> jax.Array
    (dlpack on CPU; the direct H2D path on an accelerator). Only
    fixed-width, non-null columns cross — the device path's contract."""
    import jax.numpy as jnp
    if not col.validity.all():
        raise ValueError(
            "NULLs do not cross the device seam (mask first) — use "
            "to_jax_masked() to carry a validity bitmap alongside "
            "sentinel-filled values, or filter the NULL rows host-side "
            "before the transfer")
    if not _device_representable(col.dtype):
        raise ValueError(f"{col.dtype} has no device representation")
    return jnp.asarray(col.values)


def to_jax_masked(col: Column, sentinel=0):
    """Nullable fixed-width column -> (values jax.Array, valid jax.Array
    bool mask): NULL slots are filled with `sentinel` (any in-range
    value — downstream device code must gate on the mask, never on the
    fill) and the validity bitmap rides along as a device bool vector.
    The valid-path fast case stays zero-copy (`jnp.asarray` over the
    shared numpy buffer); only a column that actually holds NULLs pays
    one host-side `np.where` to materialize the sentinel fill."""
    import jax.numpy as jnp
    if not _device_representable(col.dtype):
        raise ValueError(f"{col.dtype} has no device representation")
    valid = np.ascontiguousarray(col.validity)
    if valid.all():
        return jnp.asarray(col.values), jnp.asarray(valid)
    vals = np.where(valid, col.values,
                    np.asarray(sentinel, dtype=np.asarray(col.values).dtype))
    return jnp.asarray(vals), jnp.asarray(valid)
