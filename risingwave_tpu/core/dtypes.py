"""SQL data type system.

TPU-native re-design of the reference's type layer
(`src/common/src/types/mod.rs:120` — `DataType`). Instead of one Rust enum with
per-type array impls, types here carry (a) a numpy dtype for the exact host
path, (b) a JAX dtype for the device path, and (c) SQL semantics metadata
(nullability is carried per-column via validity bitmaps, not in the type).

Fixed-width types live on device; VARCHAR/DECIMAL keep exact host
representations and enter the device as 64-bit hashes / scaled ints when used
as keys (see `risingwave_tpu/core/chunk.py`).
"""
from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Any, Optional, Tuple

import numpy as np


class TypeKind(enum.Enum):
    BOOLEAN = "boolean"
    INT16 = "smallint"
    INT32 = "int"
    INT64 = "bigint"
    FLOAT32 = "real"
    FLOAT64 = "double precision"
    DECIMAL = "numeric"
    DATE = "date"
    TIME = "time"
    TIMESTAMP = "timestamp"      # microseconds since epoch, no tz
    TIMESTAMPTZ = "timestamptz"  # microseconds since epoch, UTC
    INTERVAL = "interval"        # months:i32, days:i32, usecs:i64 packed
    VARCHAR = "varchar"
    BYTEA = "bytea"
    STRUCT = "struct"
    LIST = "list"
    MAP = "map"
    JSONB = "jsonb"
    SERIAL = "serial"
    INT256 = "rw_int256"


# numpy dtype for the exact host-side column representation.
_NP_DTYPES = {
    TypeKind.BOOLEAN: np.dtype(np.bool_),
    TypeKind.INT16: np.dtype(np.int16),
    TypeKind.INT32: np.dtype(np.int32),
    TypeKind.INT64: np.dtype(np.int64),
    TypeKind.FLOAT32: np.dtype(np.float32),
    TypeKind.FLOAT64: np.dtype(np.float64),
    TypeKind.DECIMAL: np.dtype(object),      # decimal.Decimal scalars
    TypeKind.DATE: np.dtype(np.int32),       # days since 1970-01-01
    TypeKind.TIME: np.dtype(np.int64),       # usecs since midnight
    TypeKind.TIMESTAMP: np.dtype(np.int64),  # usecs since epoch
    TypeKind.TIMESTAMPTZ: np.dtype(np.int64),
    TypeKind.INTERVAL: np.dtype(object),     # Interval scalars
    TypeKind.VARCHAR: np.dtype(object),      # python str
    TypeKind.BYTEA: np.dtype(object),        # python bytes
    TypeKind.STRUCT: np.dtype(object),
    TypeKind.LIST: np.dtype(object),
    TypeKind.MAP: np.dtype(object),
    TypeKind.JSONB: np.dtype(object),
    TypeKind.SERIAL: np.dtype(np.int64),
    TypeKind.INT256: np.dtype(object),
}

# JAX/device dtype; None => host-only type (enters device as hash64/scaled repr).
_DEVICE_DTYPES = {
    TypeKind.BOOLEAN: np.dtype(np.bool_),
    TypeKind.INT16: np.dtype(np.int16),
    TypeKind.INT32: np.dtype(np.int32),
    TypeKind.INT64: np.dtype(np.int64),
    TypeKind.FLOAT32: np.dtype(np.float32),
    TypeKind.FLOAT64: np.dtype(np.float64),
    TypeKind.DATE: np.dtype(np.int32),
    TypeKind.TIME: np.dtype(np.int64),
    TypeKind.TIMESTAMP: np.dtype(np.int64),
    TypeKind.TIMESTAMPTZ: np.dtype(np.int64),
    TypeKind.SERIAL: np.dtype(np.int64),
}


@dataclass(frozen=True)
class DataType:
    """A SQL data type. Compare with `DataType` in the reference
    (`src/common/src/types/mod.rs:120`)."""

    kind: TypeKind
    # DECIMAL precision/scale (None = unconstrained, Postgres-style).
    precision: Optional[int] = None
    scale: Optional[int] = None
    # STRUCT fields / LIST element / MAP key+value.
    fields: Tuple[Tuple[str, "DataType"], ...] = field(default_factory=tuple)
    elem: Optional["DataType"] = None

    # ---- classification ----
    @property
    def np_dtype(self) -> np.dtype:
        return _NP_DTYPES[self.kind]

    @property
    def device_dtype(self) -> Optional[np.dtype]:
        return _DEVICE_DTYPES.get(self.kind)

    @property
    def is_numeric(self) -> bool:
        return self.kind in (
            TypeKind.INT16, TypeKind.INT32, TypeKind.INT64,
            TypeKind.FLOAT32, TypeKind.FLOAT64, TypeKind.DECIMAL,
            TypeKind.SERIAL, TypeKind.INT256,
        )

    @property
    def is_integral(self) -> bool:
        return self.kind in (TypeKind.INT16, TypeKind.INT32, TypeKind.INT64,
                             TypeKind.SERIAL)

    @property
    def is_fixed_width(self) -> bool:
        return self.kind in _DEVICE_DTYPES

    def sql_name(self) -> str:
        return self.kind.value

    def __str__(self) -> str:  # pragma: no cover - debug aid
        return self.sql_name()


# Singleton-ish constructors for the common types.
BOOLEAN = DataType(TypeKind.BOOLEAN)
INT16 = DataType(TypeKind.INT16)
INT32 = DataType(TypeKind.INT32)
INT64 = DataType(TypeKind.INT64)
FLOAT32 = DataType(TypeKind.FLOAT32)
FLOAT64 = DataType(TypeKind.FLOAT64)
DECIMAL = DataType(TypeKind.DECIMAL)
DATE = DataType(TypeKind.DATE)
TIME = DataType(TypeKind.TIME)
TIMESTAMP = DataType(TypeKind.TIMESTAMP)
TIMESTAMPTZ = DataType(TypeKind.TIMESTAMPTZ)
INTERVAL = DataType(TypeKind.INTERVAL)
VARCHAR = DataType(TypeKind.VARCHAR)
BYTEA = DataType(TypeKind.BYTEA)
JSONB = DataType(TypeKind.JSONB)
SERIAL = DataType(TypeKind.SERIAL)


def struct_of(*fields: Tuple[str, DataType]) -> DataType:
    return DataType(TypeKind.STRUCT, fields=tuple(fields))


def list_of(elem: DataType) -> DataType:
    return DataType(TypeKind.LIST, elem=elem)


_SQL_NAME_TO_TYPE = {
    "boolean": BOOLEAN, "bool": BOOLEAN,
    "smallint": INT16, "int2": INT16,
    "int": INT32, "integer": INT32, "int4": INT32,
    "bigint": INT64, "int8": INT64,
    "real": FLOAT32, "float4": FLOAT32,
    "double precision": FLOAT64, "double": FLOAT64, "float8": FLOAT64,
    "float": FLOAT64,
    "numeric": DECIMAL, "decimal": DECIMAL,
    "date": DATE,
    "time": TIME, "time without time zone": TIME,
    "timestamp": TIMESTAMP, "timestamp without time zone": TIMESTAMP,
    "timestamptz": TIMESTAMPTZ, "timestamp with time zone": TIMESTAMPTZ,
    "interval": INTERVAL,
    "varchar": VARCHAR, "text": VARCHAR, "string": VARCHAR,
    "character varying": VARCHAR,
    "bytea": BYTEA,
    "jsonb": JSONB,
    "serial": SERIAL,
}


def type_from_sql_name(name: str) -> DataType:
    key = " ".join(name.strip().lower().split())
    # strip parenthesized precision e.g. varchar(30), numeric(10,2)
    if "(" in key:
        base, rest = key.split("(", 1)
        base = base.strip()
        if base in ("numeric", "decimal"):
            args = rest.rstrip(")").split(",")
            prec = int(args[0])
            scale = int(args[1]) if len(args) > 1 else 0
            return DataType(TypeKind.DECIMAL, precision=prec, scale=scale)
        key = base
    t = _SQL_NAME_TO_TYPE.get(key)
    if t is None:
        raise ValueError(f"unknown SQL type: {name!r}")
    return t


@dataclass(frozen=True)
class Interval:
    """Postgres interval: months, days, microseconds — mirrors the reference's
    `Interval` (`src/common/src/types/interval.rs`)."""
    months: int = 0
    days: int = 0
    usecs: int = 0

    def total_usecs_approx(self) -> int:
        """Exact only when months == 0; used for window arithmetic where the
        reference also requires day/usec intervals."""
        return ((self.months * 30 + self.days) * 86_400_000_000) + self.usecs

    def __add__(self, other: "Interval") -> "Interval":
        return Interval(self.months + other.months, self.days + other.days,
                        self.usecs + other.usecs)

    def __str__(self) -> str:
        parts = []
        if self.months:
            parts.append(f"{self.months} mons")
        if self.days:
            parts.append(f"{self.days} days")
        if self.usecs or not parts:
            secs = self.usecs / 1_000_000
            parts.append(f"{secs:g} secs")
        return " ".join(parts)


def parse_interval(text: str) -> Interval:
    """Parse a small useful subset of Postgres interval syntax:
    '2 seconds', '10 minutes', '1 hour', '1 day', '3 months', '00:00:10'."""
    s = text.strip().lower()
    if ":" in s and not any(c.isalpha() for c in s):
        hh, mm, *rest = s.split(":")
        ss = float(rest[0]) if rest else 0.0
        usecs = int((int(hh) * 3600 + int(mm) * 60) * 1_000_000 + ss * 1_000_000)
        return Interval(usecs=usecs)
    tokens = s.split()
    if len(tokens) % 2 != 0:
        raise ValueError(f"cannot parse interval: {text!r}")
    months = days = usecs = 0
    unit_usecs = {
        "microsecond": 1, "microseconds": 1,
        "millisecond": 1_000, "milliseconds": 1_000,
        "second": 1_000_000, "seconds": 1_000_000, "sec": 1_000_000, "secs": 1_000_000,
        "minute": 60_000_000, "minutes": 60_000_000, "min": 60_000_000, "mins": 60_000_000,
        "hour": 3_600_000_000, "hours": 3_600_000_000,
    }
    for qty, unit in zip(tokens[::2], tokens[1::2]):
        n = float(qty)
        if unit in unit_usecs:
            usecs += int(n * unit_usecs[unit])
        elif unit in ("day", "days"):
            days += int(n)
        elif unit in ("week", "weeks"):
            days += int(n) * 7
        elif unit in ("month", "months", "mon", "mons"):
            months += int(n)
        elif unit in ("year", "years"):
            months += int(n) * 12
        else:
            raise ValueError(f"unknown interval unit {unit!r} in {text!r}")
    return Interval(months=months, days=days, usecs=usecs)
