"""Virtual-node consistent hashing.

Re-design of `src/common/src/hash/consistent_hash/vnode.rs:30-151`: rows are
partitioned by CRC32(distribution key) % vnode_count; vnodes map onto
parallel units. Here the parallel units are TPU mesh shards
(`risingwave_tpu/parallel/`), and the per-chunk vnode computation
(`VirtualNode::compute_chunk`, vnode.rs:151) is vectorized two ways:

* numpy table-driven CRC32 on host (bit-identical to zlib/crc32fast IEEE), and
* a jnp variant usable inside jitted dispatch steps (table lookups on device).
"""
from __future__ import annotations

import zlib
from typing import List, Optional, Sequence

import numpy as np

from .chunk import Column, DataChunk
from .dtypes import TypeKind

# Default vnode count (reference: 256 for backwards compat, max 2^15).
VNODE_COUNT = 256
MAX_VNODE_COUNT = 1 << 15

# ---------------------------------------------------------------------------
# CRC32 (IEEE, reflected — matches zlib.crc32 / Rust crc32fast)
# ---------------------------------------------------------------------------

def _make_crc32_table() -> np.ndarray:
    poly = np.uint32(0xEDB88320)
    table = np.zeros(256, dtype=np.uint32)
    for i in range(256):
        c = np.uint32(i)
        for _ in range(8):
            c = (c >> np.uint32(1)) ^ (poly if (c & np.uint32(1)) else np.uint32(0))
        table[i] = c
    return table


CRC32_TABLE = _make_crc32_table()


def crc32_bytes_matrix(data: np.ndarray,
                       init: Optional[np.ndarray] = None) -> np.ndarray:
    """CRC32 of each row of a (n, k) uint8 matrix, vectorized across n.
    Matches zlib.crc32(row_bytes) bit-for-bit."""
    assert data.dtype == np.uint8 and data.ndim == 2
    n, k = data.shape
    crc = (np.full(n, 0xFFFFFFFF, dtype=np.uint32) if init is None
           else (init ^ np.uint32(0xFFFFFFFF)))
    for j in range(k):
        idx = (crc ^ data[:, j]) & np.uint32(0xFF)
        crc = (crc >> np.uint32(8)) ^ CRC32_TABLE[idx]
    return crc ^ np.uint32(0xFFFFFFFF)


def _int_key_bytes(values: np.ndarray) -> np.ndarray:
    """Serialize integral key values to (n, 8) big-endian bytes — the key
    serialization contract for hashing (value-encoding analog of the
    reference's HashKey, `src/common/src/hash/key_v2.rs:221`)."""
    v = values.astype(np.int64, copy=False).astype(np.uint64)
    out = np.empty((len(v), 8), dtype=np.uint8)
    for b in range(8):
        out[:, b] = ((v >> np.uint64(8 * (7 - b))) & np.uint64(0xFF)).astype(np.uint8)
    return out


_NULL_SENTINEL_BYTES = b"\x00null\x00"
FNV_OFFSET = np.uint64(0xCBF29CE484222325)
FNV_PRIME = np.uint64(0x100000001B3)


def _fnv1a64_bytes_matrix(data: np.ndarray, lengths: Optional[np.ndarray] = None,
                          init: Optional[np.ndarray] = None) -> np.ndarray:
    """FNV-1a 64 over each row of an (n, k) uint8 matrix."""
    n, k = data.shape
    h = np.full(n, FNV_OFFSET, dtype=np.uint64) if init is None else init.copy()
    with np.errstate(over="ignore"):
        for j in range(k):
            if lengths is not None:
                active = j < lengths
                h = np.where(active, (h ^ data[:, j].astype(np.uint64)) * FNV_PRIME, h)
            else:
                h = (h ^ data[:, j].astype(np.uint64)) * FNV_PRIME
    return h


def column_hash64(col: Column) -> np.ndarray:
    """Stable null-aware 64-bit hash per row (FNV-1a over the serialized
    value). For host-only dtypes this is the device-side key projection."""
    n = len(col)
    kind = col.dtype.kind
    if col.dtype.is_fixed_width:
        if kind == TypeKind.BOOLEAN:
            data = col.values.astype(np.uint8).reshape(n, 1)
        elif kind == TypeKind.FLOAT32 or kind == TypeKind.FLOAT64:
            # normalize -0.0 to 0.0 so equal SQL values hash equal
            v = col.values.astype(np.float64, copy=True)
            v[v == 0.0] = 0.0
            data = v.view(np.uint64).reshape(n, 1)
            data = _int_key_bytes(data.view(np.int64).ravel())
        else:
            data = _int_key_bytes(col.values)
        h = _fnv1a64_bytes_matrix(data)
    else:
        h = np.empty(n, dtype=np.uint64)
        for i in range(n):
            v = col.values[i]
            if v is None:
                h[i] = 0
                continue
            if isinstance(v, str):
                b = v.encode("utf-8")
            elif isinstance(v, bytes):
                b = v
            else:
                b = repr(v).encode("utf-8")
            acc = FNV_OFFSET
            with np.errstate(over="ignore"):
                for byte in b:
                    acc = (acc ^ np.uint64(byte)) * FNV_PRIME
            h[i] = acc
    # null → fixed sentinel hash
    null_h = np.uint64(0x9E3779B97F4A7C15)
    return _avoid_device_sentinel(np.where(col.validity, h, null_h))


# int64 max is the device state's EMPTY_KEY padding sentinel
# (device/sorted_state.py): a hash landing there would be silently treated
# as padding (masked from reduce, dropped by merge, filtered from the
# all-to-all receive mask). Every host->device key projection remaps it.
_DEVICE_EMPTY = np.uint64(0x7FFFFFFFFFFFFFFF)


def _avoid_device_sentinel(h: np.ndarray) -> np.ndarray:
    return np.where(h == _DEVICE_EMPTY, _DEVICE_EMPTY - np.uint64(1), h)


def hash_columns64(cols: Sequence[Column]) -> np.ndarray:
    """Combine per-column hash64s into one 64-bit key hash (boost-style mix)."""
    assert cols
    h = column_hash64(cols[0])
    with np.errstate(over="ignore"):
        for c in cols[1:]:
            h2 = column_hash64(c)
            h = h ^ (h2 + np.uint64(0x9E3779B97F4A7C15)
                     + (h << np.uint64(6)) + (h >> np.uint64(2)))
    return _avoid_device_sentinel(h)


def compute_vnodes(key_cols: Sequence[Column], n: Optional[int] = None,
                   vnode_count: int = VNODE_COUNT) -> np.ndarray:
    """Per-row vnode for a chunk's distribution-key columns
    (`VirtualNode::compute_chunk`, vnode.rs:151).

    Contract: CRC32 over the concatenated big-endian key serialization
    (nulls contribute a sentinel), mod vnode_count. All shards/processes must
    agree on this function — it defines the state layout.
    """
    if not key_cols:
        # Singleton distribution: everything on vnode 0.
        assert n is not None
        return np.zeros(n, dtype=np.int32)
    n = len(key_cols[0])
    # fast path: single non-null integral key -> fused C++ kernel
    if len(key_cols) == 1:
        col = key_cols[0]
        if (col.dtype.is_fixed_width and col.validity.all()
                and col.dtype.kind not in (TypeKind.BOOLEAN, TypeKind.FLOAT32,
                                           TypeKind.FLOAT64)):
            from ..native import vnodes_i64
            vn = vnodes_i64(col.values.astype(np.int64, copy=False),
                            vnode_count)
            if vn is not None:
                return vn
    crc = None
    for col in key_cols:
        if col.dtype.is_fixed_width:
            kind = col.dtype.kind
            if kind == TypeKind.BOOLEAN:
                data = col.values.astype(np.uint8).reshape(n, 1)
            elif kind in (TypeKind.FLOAT32, TypeKind.FLOAT64):
                v = col.values.astype(np.float64, copy=True)
                v[v == 0.0] = 0.0
                data = _int_key_bytes(v.view(np.int64))
            else:
                data = _int_key_bytes(col.values)
            # null handling: splice in sentinel bytes per-row where invalid
            if not col.validity.all():
                crc_part_valid = crc32_bytes_matrix(data, init=crc)
                sent = np.frombuffer(_NULL_SENTINEL_BYTES, dtype=np.uint8)
                sent_mat = np.broadcast_to(sent, (n, len(sent))).copy()
                crc_part_null = crc32_bytes_matrix(sent_mat, init=crc)
                crc = np.where(col.validity, crc_part_valid, crc_part_null)
            else:
                crc = crc32_bytes_matrix(data, init=crc)
        else:
            out = np.empty(n, dtype=np.uint32)
            for i in range(n):
                v = col.values[i]
                if not col.validity[i]:
                    b = _NULL_SENTINEL_BYTES
                elif isinstance(v, str):
                    b = v.encode("utf-8")
                elif isinstance(v, bytes):
                    b = v
                else:
                    b = repr(v).encode("utf-8")
                # zlib.crc32(data, prev) chains CRCs exactly like our
                # table-driven matrix version with init=prev.
                out[i] = zlib.crc32(b, int(crc[i])) if crc is not None else zlib.crc32(b)
            crc = out.astype(np.uint32)
    return (crc % np.uint32(vnode_count)).astype(np.int32)


def vnode_of_row(key: Sequence, vnode_count: int = VNODE_COUNT) -> int:
    """Single-row vnode (must agree with compute_vnodes)."""
    crc = 0
    started = False
    for v in key:
        if v is None:
            b = _NULL_SENTINEL_BYTES
        elif isinstance(v, bool):
            b = bytes([int(v)])
        elif isinstance(v, (int, np.integer)):
            b = int(v).to_bytes(8, "big", signed=True)
        elif isinstance(v, (float, np.floating)):
            fv = 0.0 if v == 0.0 else float(v)
            b = np.array([fv]).view(np.int64)[0].item().to_bytes(8, "big", signed=True)
        elif isinstance(v, str):
            b = v.encode("utf-8")
        elif isinstance(v, bytes):
            b = v
        else:
            b = repr(v).encode("utf-8")
        crc = zlib.crc32(b, crc) if started else zlib.crc32(b)
        started = True
    return crc % vnode_count


# ---------------------------------------------------------------------------
# Device-side (jnp) vnode computation for jitted dispatch
# ---------------------------------------------------------------------------

def crc32_u64_jnp(values):
    """CRC32 of big-endian 8-byte serialization of int64 values, on device.
    Used inside jitted exchange/dispatch steps; agrees with compute_vnodes for
    single-int64 keys."""
    import jax.numpy as jnp
    table = jnp.asarray(CRC32_TABLE.astype(np.int64))
    v = values.astype(jnp.uint64)
    crc = jnp.full(values.shape, 0xFFFFFFFF, dtype=jnp.uint32)
    for b in range(8):
        byte = ((v >> np.uint64(8 * (7 - b))) & np.uint64(0xFF)).astype(jnp.uint32)
        idx = ((crc ^ byte) & np.uint32(0xFF)).astype(jnp.int32)
        crc = (crc >> np.uint32(8)) ^ jnp.take(table, idx).astype(jnp.uint32)
    return crc ^ np.uint32(0xFFFFFFFF)


def compute_vnodes_jnp(values, vnode_count: int = VNODE_COUNT):
    return (crc32_u64_jnp(values) % np.uint32(vnode_count)).astype("int32")
