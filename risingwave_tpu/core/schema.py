"""Schema: named, typed columns — the reference's `Schema`/`Field`
(`src/common/src/catalog/schema.rs`)."""
from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

from .dtypes import DataType


@dataclass(frozen=True)
class Field:
    name: str
    dtype: DataType


class Schema:
    def __init__(self, fields: Sequence[Field]):
        self.fields: List[Field] = list(fields)

    @classmethod
    def of(cls, *pairs: Tuple[str, DataType]) -> "Schema":
        return cls([Field(n, t) for n, t in pairs])

    @property
    def names(self) -> List[str]:
        return [f.name for f in self.fields]

    @property
    def dtypes(self) -> List[DataType]:
        return [f.dtype for f in self.fields]

    def index_of(self, name: str) -> int:
        for i, f in enumerate(self.fields):
            if f.name == name:
                return i
        raise KeyError(name)

    def maybe_index_of(self, name: str) -> Optional[int]:
        for i, f in enumerate(self.fields):
            if f.name == name:
                return i
        return None

    def __len__(self) -> int:
        return len(self.fields)

    def __iter__(self):
        return iter(self.fields)

    def concat(self, other: "Schema") -> "Schema":
        return Schema(self.fields + other.fields)

    def project(self, indices: Sequence[int]) -> "Schema":
        return Schema([self.fields[i] for i in indices])

    def __repr__(self) -> str:  # pragma: no cover
        inner = ", ".join(f"{f.name} {f.dtype}" for f in self.fields)
        return f"Schema({inner})"
