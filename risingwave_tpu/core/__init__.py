"""Core kernel: types, columnar chunks, vnode hashing, epochs, encodings."""
from . import dtypes
from .chunk import Column, DataChunk, DeviceChunk, Op, StreamChunk, StreamChunkBuilder, to_device_chunk
from .dtypes import DataType, Interval, TypeKind, parse_interval, type_from_sql_name
from .epoch import EpochPair, INVALID_EPOCH, now_epoch
from .schema import Field, Schema
from .vnode import VNODE_COUNT, compute_vnodes, hash_columns64, vnode_of_row

__all__ = [
    "dtypes", "Column", "DataChunk", "DeviceChunk", "Op", "StreamChunk",
    "StreamChunkBuilder", "to_device_chunk", "DataType", "Interval", "TypeKind",
    "parse_interval", "type_from_sql_name", "EpochPair", "INVALID_EPOCH",
    "now_epoch", "Field", "Schema", "VNODE_COUNT", "compute_vnodes",
    "hash_columns64", "vnode_of_row",
]
