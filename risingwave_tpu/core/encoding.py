"""Key / value encodings for state and checkpoints.

Re-design of the reference's two encodings:

* memcomparable key encoding (`src/common/src/util/memcmp_encoding.rs:38`):
  byte strings whose lexicographic order equals the row order — used for state
  table primary keys and range scans, including DESC columns and null
  ordering.
* value encoding (`src/common/src/util/value_encoding/mod.rs:57`): compact
  non-ordered serialization for row payloads in checkpoints.

Host-side only (checkpoint/restore and ordered iteration are host concerns);
the device path never sees encoded bytes.
"""
from __future__ import annotations

import struct
from decimal import Decimal
from typing import Any, List, Optional, Sequence, Tuple

from .dtypes import DataType, Interval, TypeKind

# ---------------------------------------------------------------------------
# Memcomparable encoding
# ---------------------------------------------------------------------------
# Format per datum: 1 tag byte (null ordering) + payload.
#   ASC:  null tag 0x00 (nulls first... reference uses NULLS LAST default for
#         ASC in storage: tag 0x01 for non-null, 0x02 for null) — we follow
#         "non-null < null" = NULLS LAST for ASC, matching RW's default
#         `OrderType::ascending()` (nulls last).
# DESC is handled by bit-flipping the whole datum encoding.

_NONNULL_TAG = b"\x01"
_NULL_TAG = b"\x02"  # sorts after non-null => NULLS LAST under ASC


def _enc_uint_like(v: int, width: int) -> bytes:
    return v.to_bytes(width, "big", signed=False)


def _flip_sign_int(v: int, width: int) -> bytes:
    # two's complement with sign bit flipped orders correctly unsigned
    u = (v + (1 << (8 * width))) % (1 << (8 * width))
    u ^= 1 << (8 * width - 1)
    return _enc_uint_like(u, width)


def _enc_float(v: float) -> bytes:
    bits = struct.unpack(">Q", struct.pack(">d", float(v)))[0]
    if bits & (1 << 63):
        bits = ~bits & ((1 << 64) - 1)   # negative: flip all
    else:
        bits |= 1 << 63                   # positive: flip sign
    return _enc_uint_like(bits, 8)


def _enc_bytes_escaped(b: bytes) -> bytes:
    # escape 0x00 so shorter prefixes sort first and terminator is unambiguous
    return b.replace(b"\x00", b"\x00\xff") + b"\x00\x00"


def encode_datum_memcomparable(v: Any, dtype: DataType, desc: bool = False,
                               nulls_first: Optional[bool] = None) -> bytes:
    """Encode one datum; lexicographic byte order == SQL ORDER BY order.
    Default null ordering follows RW: ASC => nulls last, DESC => nulls first.
    """
    if nulls_first is None:
        nulls_first = desc
    if v is None:
        out = (b"\x00" if nulls_first else _NULL_TAG)
        payload = out
    else:
        kind = dtype.kind
        if kind == TypeKind.BOOLEAN:
            body = b"\x01" if v else b"\x00"
        elif kind in (TypeKind.INT16,):
            body = _flip_sign_int(int(v), 2)
        elif kind in (TypeKind.INT32, TypeKind.DATE):
            body = _flip_sign_int(int(v), 4)
        elif kind in (TypeKind.INT64, TypeKind.TIME, TypeKind.TIMESTAMP,
                      TypeKind.TIMESTAMPTZ, TypeKind.SERIAL):
            body = _flip_sign_int(int(v), 8)
        elif kind in (TypeKind.FLOAT32, TypeKind.FLOAT64):
            body = _enc_float(float(v))
        elif kind == TypeKind.DECIMAL:
            # order-preserving: encode as (sign-adjusted) scaled float prefix +
            # exact text for tiebreak. Sufficient for ordering Nexmark-scale
            # decimals; TODO exact decimal memcomparable like memcmp_encoding.rs
            d = Decimal(v)
            body = _enc_float(float(d)) + _enc_bytes_escaped(str(d.normalize()).encode())
        elif kind == TypeKind.VARCHAR:
            body = _enc_bytes_escaped(str(v).encode("utf-8"))
        elif kind == TypeKind.BYTEA:
            body = _enc_bytes_escaped(bytes(v))
        elif kind == TypeKind.INTERVAL:
            iv: Interval = v
            body = _flip_sign_int(iv.total_usecs_approx(), 16)
        else:
            raise NotImplementedError(f"memcomparable for {dtype}")
        payload = _NONNULL_TAG + body
    if desc:
        payload = bytes(0xFF - b for b in payload)
    return payload


def encode_key(row: Sequence[Any], dtypes: Sequence[DataType],
               order: Optional[Sequence[bool]] = None) -> bytes:
    """Encode a pk row; order[i]=True means DESC for column i."""
    out = bytearray()
    for i, (v, dt) in enumerate(zip(row, dtypes)):
        desc = bool(order[i]) if order is not None else False
        out += encode_datum_memcomparable(v, dt, desc=desc)
    return bytes(out)


# fixed-width memcomparable kinds: payload bytes per non-null datum
_FIXED_KEY_WIDTH = {
    TypeKind.BOOLEAN: 1,
    TypeKind.INT16: 2,
    TypeKind.INT32: 4, TypeKind.DATE: 4,
    TypeKind.INT64: 8, TypeKind.TIME: 8, TypeKind.TIMESTAMP: 8,
    TypeKind.TIMESTAMPTZ: 8, TypeKind.SERIAL: 8,
    TypeKind.FLOAT32: 8, TypeKind.FLOAT64: 8,   # both encode as f64 bits
}


def encode_key_matrix(cols: Sequence, dtypes: Sequence[DataType],
                      order: Optional[Sequence[bool]] = None):
    """Vectorized `encode_key` over whole columns.

    Returns an (n, W) uint8 matrix whose rows are byte-for-byte identical
    to `encode_key` of the corresponding row — or None when a column kind
    is not fixed-width or any datum is NULL (those batches take the exact
    per-row path). The bulk write path (`StateTable.write_chunk`) depends
    on the byte-for-byte contract: point lookups re-encode per-row.
    """
    import numpy as np
    if not cols:
        return None
    n = len(cols[0])
    widths = []
    for c, dt in zip(cols, dtypes):
        w = _FIXED_KEY_WIDTH.get(dt.kind)
        if w is None or not c.validity.all():
            return None
        widths.append(w)
    total = sum(w + 1 for w in widths)
    mat = np.empty((n, total), dtype=np.uint8)
    off = 0
    for i, (c, dt, w) in enumerate(zip(cols, dtypes, widths)):
        mat[:, off] = _NONNULL_TAG[0]
        kind = dt.kind
        if kind == TypeKind.BOOLEAN:
            body = c.values.astype(np.uint8).reshape(n, 1)
        elif kind in (TypeKind.FLOAT32, TypeKind.FLOAT64):
            bits = np.ascontiguousarray(
                c.values.astype(np.float64)).view(np.uint64)
            neg = (bits >> np.uint64(63)).astype(bool)
            bits = np.where(neg, ~bits, bits | np.uint64(1 << 63))
            body = bits.astype(">u8").view(np.uint8).reshape(n, 8)
        else:
            v = c.values.astype(np.int64, copy=False)
            if w == 8:
                u = (v ^ np.int64(-2**63)).view(np.uint64)
                body = u.astype(">u8").view(np.uint8).reshape(n, 8)
            else:
                mask_w = np.int64((1 << (8 * w)) - 1)
                u = (v & mask_w) ^ np.int64(1 << (8 * w - 1))
                body = u.astype(f">u{w}").view(np.uint8).reshape(n, w)
        mat[:, off + 1: off + 1 + w] = body
        if order is not None and order[i]:
            mat[:, off: off + 1 + w] = \
                np.uint8(0xFF) - mat[:, off: off + 1 + w]
        off += 1 + w
    return mat


# ---------------------------------------------------------------------------
# Value encoding (compact, non-ordered) — checkpoint row payloads
# ---------------------------------------------------------------------------

def encode_value_datum(v: Any, dtype: DataType) -> bytes:
    if v is None:
        return b"\x00"
    kind = dtype.kind
    if kind == TypeKind.BOOLEAN:
        body = b"\x01" if v else b"\x00"
    elif kind in (TypeKind.INT16,):
        body = struct.pack("<h", int(v))
    elif kind in (TypeKind.INT32, TypeKind.DATE):
        body = struct.pack("<i", int(v))
    elif kind in (TypeKind.INT64, TypeKind.TIME, TypeKind.TIMESTAMP,
                  TypeKind.TIMESTAMPTZ, TypeKind.SERIAL):
        body = struct.pack("<q", int(v))
    elif kind == TypeKind.FLOAT32:
        body = struct.pack("<f", float(v))
    elif kind == TypeKind.FLOAT64:
        body = struct.pack("<d", float(v))
    elif kind == TypeKind.DECIMAL:
        s = str(v)
        body = struct.pack("<I", len(s)) + s.encode()
    elif kind == TypeKind.VARCHAR:
        b = str(v).encode("utf-8")
        body = struct.pack("<I", len(b)) + b
    elif kind in (TypeKind.BYTEA, TypeKind.JSONB):
        b = bytes(v) if kind == TypeKind.BYTEA else str(v).encode()
        body = struct.pack("<I", len(b)) + b
    elif kind == TypeKind.INTERVAL:
        iv: Interval = v
        body = struct.pack("<iiq", iv.months, iv.days, iv.usecs)
    else:
        raise NotImplementedError(f"value encoding for {dtype}")
    return b"\x01" + body


def decode_value_datum(buf: bytes, pos: int, dtype: DataType) -> Tuple[Any, int]:
    tag = buf[pos]
    pos += 1
    if tag == 0:
        return None, pos
    kind = dtype.kind
    if kind == TypeKind.BOOLEAN:
        return buf[pos] == 1, pos + 1
    if kind == TypeKind.INT16:
        return struct.unpack_from("<h", buf, pos)[0], pos + 2
    if kind in (TypeKind.INT32, TypeKind.DATE):
        return struct.unpack_from("<i", buf, pos)[0], pos + 4
    if kind in (TypeKind.INT64, TypeKind.TIME, TypeKind.TIMESTAMP,
                TypeKind.TIMESTAMPTZ, TypeKind.SERIAL):
        return struct.unpack_from("<q", buf, pos)[0], pos + 8
    if kind == TypeKind.FLOAT32:
        return struct.unpack_from("<f", buf, pos)[0], pos + 4
    if kind == TypeKind.FLOAT64:
        return struct.unpack_from("<d", buf, pos)[0], pos + 8
    if kind == TypeKind.DECIMAL:
        ln = struct.unpack_from("<I", buf, pos)[0]
        s = buf[pos + 4: pos + 4 + ln].decode()
        return Decimal(s), pos + 4 + ln
    if kind == TypeKind.VARCHAR:
        ln = struct.unpack_from("<I", buf, pos)[0]
        return buf[pos + 4: pos + 4 + ln].decode("utf-8"), pos + 4 + ln
    if kind in (TypeKind.BYTEA, TypeKind.JSONB):
        ln = struct.unpack_from("<I", buf, pos)[0]
        raw = buf[pos + 4: pos + 4 + ln]
        return (bytes(raw) if kind == TypeKind.BYTEA else raw.decode()), pos + 4 + ln
    if kind == TypeKind.INTERVAL:
        months, days, usecs = struct.unpack_from("<iiq", buf, pos)
        return Interval(months, days, usecs), pos + 16
    raise NotImplementedError(f"value decoding for {dtype}")


def encode_row(row: Sequence[Any], dtypes: Sequence[DataType]) -> bytes:
    out = bytearray()
    for v, dt in zip(row, dtypes):
        out += encode_value_datum(v, dt)
    return bytes(out)


def decode_row(buf: bytes, dtypes: Sequence[DataType]) -> Tuple[Any, ...]:
    pos = 0
    out: List[Any] = []
    for dt in dtypes:
        v, pos = decode_value_datum(buf, pos, dt)
        out.append(v)
    return tuple(out)


class SortKey:
    """Python-comparable wrapper for ordered in-memory state iteration —
    delegates to the memcomparable encoding so in-memory order and on-disk
    order always agree."""

    __slots__ = ("enc",)

    def __init__(self, row: Sequence[Any], dtypes: Sequence[DataType],
                 order: Optional[Sequence[bool]] = None):
        self.enc = encode_key(row, dtypes, order)

    def __lt__(self, other: "SortKey") -> bool:
        return self.enc < other.enc

    def __eq__(self, other: object) -> bool:
        return isinstance(other, SortKey) and self.enc == other.enc

    def __hash__(self) -> int:
        return hash(self.enc)
