"""Columnar chunks — the unit of dataflow.

Re-design of the reference's array/chunk layer
(`src/common/src/array/data_chunk.rs:66` `DataChunk`,
`src/common/src/array/stream_chunk.rs:106` `StreamChunk`, `:45` `Op`).

Differences from the reference, driven by the TPU target:

* One generic `Column` (numpy values + numpy validity) instead of 20 typed
  array impls — numpy already gives us vectorized kernels on host, and the
  device path only needs the fixed-width subset.
* `DeviceChunk` is the `jax.Array` projection of a chunk: fixed-width columns
  padded to a static capacity (XLA wants static shapes), with a row-mask in
  place of the visibility bitmap. String/decimal columns enter the device as
  stable 64-bit hashes (sufficient for group keys / join keys; exact values
  round-trip on host).
"""
from __future__ import annotations

import enum
from dataclasses import dataclass
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

from .dtypes import DataType, TypeKind, VARCHAR


class Op(enum.IntEnum):
    """Row operation tag (`src/common/src/array/stream_chunk.rs:45`)."""
    INSERT = 0
    DELETE = 1
    UPDATE_DELETE = 2
    UPDATE_INSERT = 3

    @property
    def is_insert(self) -> bool:
        return self in (Op.INSERT, Op.UPDATE_INSERT)

    @property
    def is_delete(self) -> bool:
        return self in (Op.DELETE, Op.UPDATE_DELETE)

    @property
    def sign(self) -> int:
        """+1 for inserts, -1 for deletes — the retraction algebra."""
        return 1 if self.is_insert else -1


def _sign_of_ops(ops: np.ndarray) -> np.ndarray:
    """Vectorized Op.sign: +1 insert-like, -1 delete-like."""
    return np.where((ops == Op.INSERT) | (ops == Op.UPDATE_INSERT), 1, -1).astype(np.int32)


class Column:
    """A column: values array + validity mask (True = non-null).

    Object-dtype columns (varchar/decimal/...) store Python scalars; nulls are
    None in `values` AND False in `validity` (both maintained to keep host
    kernels simple).
    """

    __slots__ = ("dtype", "values", "validity")

    def __init__(self, dtype: DataType, values: np.ndarray,
                 validity: Optional[np.ndarray] = None):
        values = np.asarray(values, dtype=dtype.np_dtype)
        if validity is None:
            if dtype.np_dtype == np.dtype(object):
                validity = np.array([v is not None for v in values], dtype=np.bool_)
            else:
                validity = np.ones(len(values), dtype=np.bool_)
        self.dtype = dtype
        self.values = values
        self.validity = np.asarray(validity, dtype=np.bool_)
        assert len(self.values) == len(self.validity)

    # ---- constructors ----
    @classmethod
    def from_list(cls, dtype: DataType, items: Sequence[Any]) -> "Column":
        validity = np.array([x is not None for x in items], dtype=np.bool_)
        if dtype.np_dtype == np.dtype(object):
            values = np.empty(len(items), dtype=object)
            for i, x in enumerate(items):
                values[i] = x
        else:
            # fill nulls with 0 to keep fixed-width arrays dense
            fill = False if dtype.kind == TypeKind.BOOLEAN else 0
            values = np.array([fill if x is None else x for x in items],
                              dtype=dtype.np_dtype)
        return cls(dtype, values, validity)

    # ---- basics ----
    def __len__(self) -> int:
        return len(self.values)

    def get(self, i: int) -> Any:
        if not self.validity[i]:
            return None
        v = self.values[i]
        if self.dtype.np_dtype == np.dtype(object):
            return v
        return v.item() if isinstance(v, np.generic) else v

    def to_list(self) -> List[Any]:
        if self.dtype.np_dtype == np.dtype(object):
            out = list(self.values)
        else:
            out = self.values.tolist()   # C-speed scalar conversion
        if not self.validity.all():
            for i in np.flatnonzero(~self.validity).tolist():
                out[i] = None
        return out

    def take(self, indices: np.ndarray) -> "Column":
        return Column(self.dtype, self.values[indices], self.validity[indices])

    def filter(self, mask: np.ndarray) -> "Column":
        return Column(self.dtype, self.values[mask], self.validity[mask])

    def concat(self, other: "Column") -> "Column":
        return Column(self.dtype,
                      np.concatenate([self.values, other.values]),
                      np.concatenate([self.validity, other.validity]))

    def hash64(self) -> np.ndarray:
        """Stable per-row 64-bit hash, null-aware. Used for device-side keys of
        host-only types and for multi-column key compression."""
        from . import vnode as _vnode
        return _vnode.column_hash64(self)

    def __repr__(self) -> str:  # pragma: no cover
        return f"Column({self.dtype}, n={len(self)})"


class DataChunk:
    """A batch of columns + optional row visibility
    (`src/common/src/array/data_chunk.rs:66`)."""

    __slots__ = ("columns", "visibility")

    def __init__(self, columns: Sequence[Column],
                 visibility: Optional[np.ndarray] = None):
        self.columns: List[Column] = list(columns)
        n = len(self.columns[0]) if self.columns else 0
        for c in self.columns:
            assert len(c) == n, "ragged chunk"
        self.visibility = (np.asarray(visibility, dtype=np.bool_)
                           if visibility is not None else None)
        if self.visibility is not None:
            assert len(self.visibility) == n

    # ---- constructors ----
    @classmethod
    def from_rows(cls, dtypes: Sequence[DataType],
                  rows: Iterable[Sequence[Any]]) -> "DataChunk":
        rows = list(rows)
        cols = []
        for j, dt in enumerate(dtypes):
            cols.append(Column.from_list(dt, [r[j] for r in rows]))
        return cls(cols)

    # ---- basics ----
    @property
    def capacity(self) -> int:
        return len(self.columns[0]) if self.columns else 0

    def __len__(self) -> int:
        return self.capacity

    @property
    def cardinality(self) -> int:
        """Number of visible rows."""
        if self.visibility is None:
            return self.capacity
        return int(self.visibility.sum())

    def vis_mask(self) -> np.ndarray:
        if self.visibility is None:
            return np.ones(self.capacity, dtype=np.bool_)
        return self.visibility

    def row_at(self, i: int) -> Tuple[Any, ...]:
        return tuple(c.get(i) for c in self.columns)

    def rows(self) -> List[Tuple[Any, ...]]:
        """Visible rows as tuples (columns convert in bulk, then one zip)."""
        out = list(zip(*(c.to_list() for c in self.columns))) \
            if self.columns else []
        mask = self.vis_mask()
        if not mask.all():
            out = [r for r, ok in zip(out, mask.tolist()) if ok]
        return out

    def compact(self) -> "DataChunk":
        """Drop invisible rows (`DataChunk::compact` in the reference)."""
        if self.visibility is None:
            return self
        mask = self.visibility
        return DataChunk([c.filter(mask) for c in self.columns])

    def project(self, indices: Sequence[int]) -> "DataChunk":
        return DataChunk([self.columns[i] for i in indices], self.visibility)

    def with_visibility(self, mask: np.ndarray) -> "DataChunk":
        base = self.vis_mask() & np.asarray(mask, dtype=np.bool_)
        return DataChunk(self.columns, base)

    @property
    def dtypes(self) -> List[DataType]:
        return [c.dtype for c in self.columns]

    def __repr__(self) -> str:  # pragma: no cover
        return f"DataChunk(cols={len(self.columns)}, rows={self.cardinality}/{self.capacity})"


class StreamChunk(DataChunk):
    """DataChunk + per-row Op tags (`src/common/src/array/stream_chunk.rs:106`)."""

    __slots__ = ("ops",)

    def __init__(self, ops: np.ndarray, columns: Sequence[Column],
                 visibility: Optional[np.ndarray] = None):
        super().__init__(columns, visibility)
        self.ops = np.asarray(ops, dtype=np.int8)
        assert len(self.ops) == self.capacity

    # ---- constructors ----
    @classmethod
    def from_rows(cls, dtypes: Sequence[DataType],
                  op_rows: Iterable[Tuple[Op, Sequence[Any]]]) -> "StreamChunk":
        op_rows = list(op_rows)
        ops = np.array([int(op) for op, _ in op_rows], dtype=np.int8)
        cols = [Column.from_list(dt, [r[j] for _, r in op_rows])
                for j, dt in enumerate(dtypes)]
        return cls(ops, cols)

    @classmethod
    def all_inserts(cls, chunk: DataChunk) -> "StreamChunk":
        ops = np.full(chunk.capacity, int(Op.INSERT), dtype=np.int8)
        return cls(ops, chunk.columns, chunk.visibility)

    # ---- basics ----
    def data_chunk(self) -> DataChunk:
        return DataChunk(self.columns, self.visibility)

    def signs(self) -> np.ndarray:
        """Vectorized retraction signs (+1/-1) for visible-row math."""
        return _sign_of_ops(self.ops)

    def compact(self) -> "StreamChunk":
        if self.visibility is None:
            return self
        mask = self.visibility
        return StreamChunk(self.ops[mask], [c.filter(mask) for c in self.columns])

    def project(self, indices: Sequence[int]) -> "StreamChunk":
        return StreamChunk(self.ops, [self.columns[i] for i in indices],
                           self.visibility)

    def with_visibility(self, mask: np.ndarray) -> "StreamChunk":
        base = self.vis_mask() & np.asarray(mask, dtype=np.bool_)
        return StreamChunk(self.ops, self.columns, base)

    def op_rows(self) -> List[Tuple[Op, Tuple[Any, ...]]]:
        mask = self.vis_mask()
        return [(Op(int(self.ops[i])), self.row_at(i))
                for i in range(self.capacity) if mask[i]]

    def concat(self, other: "StreamChunk") -> "StreamChunk":
        a, b = self.compact(), other.compact()
        return StreamChunk(
            np.concatenate([a.ops, b.ops]),
            [ca.concat(cb) for ca, cb in zip(a.columns, b.columns)])

    def __repr__(self) -> str:  # pragma: no cover
        return f"StreamChunk(cols={len(self.columns)}, rows={self.cardinality}/{self.capacity})"


class StreamChunkBuilder:
    """Row-appending builder with max chunk size
    (`src/common/src/array/stream_chunk_builder.rs`)."""

    def __init__(self, dtypes: Sequence[DataType], max_chunk_size: int = 1024):
        self.dtypes = list(dtypes)
        self.max_chunk_size = max_chunk_size
        self._ops: List[int] = []
        self._rows: List[Sequence[Any]] = []
        self._pending: List[StreamChunk] = []

    def append_row(self, op: Op, row: Sequence[Any]) -> None:
        self._ops.append(int(op))
        self._rows.append(row)
        # Keep U-/U+ pairs in one chunk: never split right after UPDATE_DELETE.
        if (len(self._rows) >= self.max_chunk_size
                and op != Op.UPDATE_DELETE):
            self._flush()

    def append_update(self, old_row: Sequence[Any],
                      new_row: Sequence[Any]) -> None:
        self.append_row(Op.UPDATE_DELETE, old_row)
        self.append_row(Op.UPDATE_INSERT, new_row)

    def __len__(self) -> int:
        return len(self._rows)

    def _flush(self) -> None:
        if not self._rows:
            return
        ops = np.array(self._ops, dtype=np.int8)
        cols = [Column.from_list(dt, [r[j] for r in self._rows])
                for j, dt in enumerate(self.dtypes)]
        self._ops, self._rows = [], []
        self._pending.append(StreamChunk(ops, cols))

    def drain(self) -> List[StreamChunk]:
        """All completed chunks + the current buffer; resets the builder."""
        self._flush()
        out, self._pending = self._pending, []
        return out

    def take(self) -> Optional[StreamChunk]:
        """Single-chunk convenience: concatenation of everything appended.
        Use `drain()` on paths that may exceed max_chunk_size."""
        chunks = self.drain()
        if not chunks:
            return None
        out = chunks[0]
        for c in chunks[1:]:
            out = out.concat(c)
        return out


# ---------------------------------------------------------------------------
# Device projection
# ---------------------------------------------------------------------------

@dataclass
class DeviceChunk:
    """The `jax.Array` projection of a StreamChunk: static-capacity padded
    columns + row mask + retraction signs. This replaces the reference's
    Arrow interop seam (`src/common/src/array/arrow/arrow_impl.rs:64`) — there
    is no Arrow hop; numpy buffers are device_put directly.

    `cols[i]` is the device array for column i if it is fixed-width, else the
    64-bit hash projection. Shapes are `(capacity,)` with `mask` False past
    `n_rows` (and for invisible rows).
    """
    cols: List[Any]          # jax arrays
    mask: Any                # bool (capacity,)
    signs: Any               # int32 (capacity,) +1/-1
    capacity: int
    n_rows: int


def _pad_to(arr: np.ndarray, capacity: int, fill=0) -> np.ndarray:
    if len(arr) == capacity:
        return arr
    out = np.full(capacity, fill, dtype=arr.dtype)
    out[: len(arr)] = arr
    return out


def to_device_chunk(chunk: StreamChunk, capacity: Optional[int] = None,
                    columns: Optional[Sequence[int]] = None) -> DeviceChunk:
    """Project a StreamChunk onto the device with a static capacity.

    capacity defaults to the next power of two ≥ len(chunk) (bucketing keeps
    the number of distinct XLA program shapes small, so recompiles are rare).
    """
    import jax.numpy as jnp

    n = chunk.capacity
    if capacity is None:
        capacity = max(16, 1 << (n - 1).bit_length()) if n else 16
    assert capacity >= n
    idxs = range(len(chunk.columns)) if columns is None else columns
    cols = []
    for i in idxs:
        c = chunk.columns[i]
        if c.dtype.is_fixed_width:
            vals = c.values.astype(c.dtype.device_dtype, copy=False)
        else:
            vals = c.hash64()
        cols.append(jnp.asarray(_pad_to(vals, capacity)))
    mask = _pad_to(chunk.vis_mask(), capacity, fill=False)
    signs = _pad_to(chunk.signs(), capacity, fill=0)
    return DeviceChunk(cols=cols, mask=jnp.asarray(mask),
                       signs=jnp.asarray(signs), capacity=capacity, n_rows=n)
