"""Filesystem source connector: a directory of newline-delimited files.

Reference shape: `src/connector/src/source/filesystem/` (posix_fs / s3 /
opendal sources list files as splits and tail them by byte offset). Here
the "object store" is a local directory; every file matching the pattern
is one split, the offset is a byte position, and new files appearing
between polls become new splits (late split discovery, the
`SplitEnumerator` re-list contract)."""
from __future__ import annotations

import fnmatch
import os
from typing import Any, List, Optional, Tuple

from .base import SourceSplit, SplitEnumerator, SplitReader


class DirEnumerator(SplitEnumerator):
    """One split per file under `path` matching `pattern` (sorted name
    order, so split ids are stable across restarts)."""

    def __init__(self, path: str, pattern: str = "*"):
        self.path = path
        self.pattern = pattern

    def list_splits(self) -> List[SourceSplit]:
        try:
            names = sorted(os.listdir(self.path))
        except FileNotFoundError:
            return []
        return [SourceSplit(n, os.path.join(self.path, n))
                for n in names
                if fnmatch.fnmatch(n, self.pattern)
                and os.path.isfile(os.path.join(self.path, n))]


class LineFileReader(SplitReader):
    """Reads complete newline-terminated records from a byte offset.

    A trailing partial line (a writer mid-append) is NOT consumed — the
    offset stays at the last complete record, so a crash/retry never
    splits a record (at-least-once becomes exactly-once through the
    offset-in-state protocol)."""

    def read(self, split: SourceSplit, offset: Any, max_records: int
             ) -> Tuple[List[bytes], Any]:
        pos = int(offset or 0)
        try:
            f = open(split.meta, "rb")
        except FileNotFoundError:
            return [], pos
        with f:
            size = os.fstat(f.fileno()).st_size
            if size < pos:
                # rotated/truncated shorter than the committed offset:
                # silently re-reading would duplicate, skipping would lose
                # data — fail loudly (reference treats file shrink the
                # same way: splits are append-only by contract)
                raise IOError(
                    f"source file {split.meta!r} shrank below the "
                    f"committed offset ({size} < {pos}); file splits "
                    "must be append-only")
            f.seek(pos)
            out: List[bytes] = []
            while len(out) < max_records:
                line = f.readline()
                if not line or not line.endswith(b"\n"):
                    break               # EOF or partial trailing record
                pos += len(line)
                s = line.rstrip(b"\r\n")   # only the framing, not content
                if s:
                    out.append(s)
        return out, pos
