"""Datagen source: deterministic generated rows at a configurable rate.

Reference: the `datagen` connector (`src/connector/src/source/datagen/`) —
per-column sequence or random generators, split-parallel, seed-stable.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from ..core.chunk import Column, Op, StreamChunk
from ..core.dtypes import DataType
from ..core.schema import Schema
from ..ops.source import SourceReader


def splitmix64(x: np.ndarray) -> np.ndarray:
    """Deterministic stateless PRNG (public splitmix64 constants)."""
    x = (x + np.uint64(0x9E3779B97F4A7C15))
    with np.errstate(over="ignore"):
        z = x
        z = (z ^ (z >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
        z = (z ^ (z >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
        z = z ^ (z >> np.uint64(31))
    return z


class FieldGen:
    """Per-column generator. kind = 'sequence' | 'random' | 'zipf'
    (power-law over [start, end), pmf ~ rank^-s with `s` > 1; rank 1 =
    `start`, the stationary hot key — reproducible skewed workloads)."""

    def __init__(self, kind: str = "sequence", start: int = 0, end: int = 2**31,
                 seed: int = 0, length: int = 10,
                 values: Optional[List[Any]] = None, s: float = 1.5):
        self.kind = kind
        self.start = start
        self.end = end
        self.seed = seed
        self.length = length
        self.values = values
        self.s = max(float(s), 1.0 + 1e-6)

    def generate(self, dtype: DataType, offsets: np.ndarray) -> Column:
        n = len(offsets)
        if self.kind == "sequence":
            vals = (self.start + offsets).astype(np.int64)
            if dtype.np_dtype == np.dtype(object):
                return Column.from_list(dtype, [str(v) for v in vals])
            return Column(dtype, vals.astype(dtype.np_dtype))
        r = splitmix64(offsets.astype(np.uint64) + np.uint64(self.seed << 32))
        if self.kind == "zipf":
            span = np.int64(max(1, self.end - self.start))
            u = (r >> np.uint64(11)).astype(np.float64) * (2.0 ** -53)
            rank = np.floor(np.power(1.0 - u, -1.0 / (self.s - 1.0)))
            rank = np.clip(rank, 1.0, float(span)).astype(np.int64)
            vals = self.start + rank - 1
            if dtype.np_dtype == np.dtype(object):
                return Column.from_list(dtype, [str(v) for v in vals])
            return Column(dtype, vals.astype(dtype.np_dtype))
        if self.values is not None:
            idx = (r % np.uint64(len(self.values))).astype(np.int64)
            return Column.from_list(dtype, [self.values[i] for i in idx])
        if dtype.np_dtype == np.dtype(object):
            return Column.from_list(
                dtype, ["s" + format(int(v) & ((1 << (4 * self.length)) - 1),
                                     f"0{self.length}x") for v in r])
        span = max(1, self.end - self.start)
        vals = self.start + (r % np.uint64(span)).astype(np.int64)
        return Column(dtype, vals.astype(dtype.np_dtype))


class DatagenReader(SourceReader):
    def __init__(self, schema: Schema, fields: Optional[Dict[str, FieldGen]] = None,
                 rows_per_chunk: int = 1024, max_rows: Optional[int] = None,
                 split_id: str = "0"):
        self.schema = schema
        self.fields = fields or {}
        self.rows_per_chunk = rows_per_chunk
        self.max_rows = max_rows
        self.offset = 0
        self.split_id = split_id

    def poll(self) -> Optional[StreamChunk]:
        if self.max_rows is not None and self.offset >= self.max_rows:
            return None
        import time
        n = self.rows_per_chunk
        if self.max_rows is not None:
            n = min(n, self.max_rows - self.offset)
        offs = np.arange(self.offset, self.offset + n, dtype=np.int64)
        cols = []
        for f in self.schema.fields:
            gen = self.fields.get(f.name, FieldGen("sequence"))
            cols.append(gen.generate(f.dtype, offs))
        self.offset += n
        ops = np.zeros(n, dtype=np.int8)  # all inserts
        # generated data "arrives" the moment it is minted — the stamp
        # the freshness ground-truth tests anchor against
        self.last_ingest_ts = time.time()
        return StreamChunk(ops, cols)

    def split_states(self) -> Dict[str, Any]:
        return {self.split_id: self.offset}

    def seek(self, states: Dict[str, Any]) -> None:
        if self.split_id in states:
            self.offset = int(states[self.split_id])


class ListReader(SourceReader):
    """Feed a fixed list of chunks — the `MockSource` analog for tests
    (`src/stream/src/executor/test_utils/`)."""

    def __init__(self, chunks: Sequence[StreamChunk], split_id: str = "0"):
        self.chunks = list(chunks)
        self.pos = 0
        self.split_id = split_id

    def push(self, chunk: StreamChunk) -> None:
        self.chunks.append(chunk)

    def poll(self) -> Optional[StreamChunk]:
        if self.pos >= len(self.chunks):
            return None
        c = self.chunks[self.pos]
        self.pos += 1
        return c

    def split_states(self) -> Dict[str, Any]:
        return {self.split_id: self.pos}

    def seek(self, states: Dict[str, Any]) -> None:
        if self.split_id in states:
            self.pos = int(states[self.split_id])
