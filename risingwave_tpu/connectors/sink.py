"""Sinks: deliver a change stream to an external system, exactly once.

Reference: `src/connector/src/sink/mod.rs:602` (`Sink` trait) + the
log-store decoupling and the two-phase "write epoch, then commit" the
coordinated sinks follow. The TPU runtime's analog keeps the same epoch
discipline without the log store (the in-process stream IS the log):

* rows buffer per epoch;
* at a CHECKPOINT barrier the epoch's rows append to the data file,
  fsync, then a manifest (epoch -> byte length) renames into place —
  the atomic commit point;
* on restart the sink truncates the data file to the manifested length
  and ignores epochs <= the committed epoch during replay, so a crash
  between append and manifest (or a replayed epoch after recovery) never
  duplicates or loses rows — exactly-once delivery.

Formats: `jsonl` (append-only streams emit the bare row object;
retractable streams wrap it as {"op": "+"/"-", "row": {...}} — the
debezium-ish changelog shape) and `csv`.
"""
from __future__ import annotations

import json
import os
from typing import Any, Iterator, List, Optional, Tuple

from ..core.chunk import StreamChunk
from ..core.schema import Schema
from ..ops.executor import Executor
from ..ops.message import Barrier, Message, Watermark


def _json_default(v):
    return str(v)


class FileSink:
    """Append-only local-file sink with epoch-manifest exactly-once."""

    def __init__(self, path: str, schema: Schema, fmt: str = "jsonl",
                 append_only: bool = False):
        self.path = path
        self.schema = schema
        self.fmt = fmt
        self.append_only = append_only
        self._pending: List[Tuple[int, Any]] = []   # (sign, row)
        self.committed_epoch = 0
        self._committed_bytes = 0
        self._recover()

    # ---- recovery -------------------------------------------------------
    @property
    def _manifest_path(self) -> str:
        return self.path + ".manifest"

    def _recover(self) -> None:
        if os.path.exists(self._manifest_path):
            with open(self._manifest_path) as f:
                m = json.load(f)
            self.committed_epoch = m["epoch"]
            self._committed_bytes = m["bytes"]
        if os.path.exists(self.path):
            size = os.path.getsize(self.path)
            if size > self._committed_bytes:
                # drop any append that never reached its manifest commit
                with open(self.path, "r+b") as f:
                    f.truncate(self._committed_bytes)
            elif size < self._committed_bytes:
                # externally truncated: continuing would overstate
                # _committed_bytes and silently break the torn-tail guard
                raise IOError(
                    f"sink data file {self.path!r} is {size} bytes but "
                    f"manifest committed {self._committed_bytes}: external "
                    "truncation/corruption")
        elif self._committed_bytes:
            raise FileNotFoundError(
                f"sink data file {self.path!r} missing but manifest "
                f"claims {self._committed_bytes} bytes")

    # ---- write path -----------------------------------------------------
    def write_chunk(self, chunk: StreamChunk) -> None:
        for op, row in chunk.op_rows():
            self._pending.append((op.sign, row))

    def _format_row(self, sign: int, row: Tuple) -> str:
        names = [f.name for f in self.schema.fields]
        if self.fmt == "csv":
            import csv
            import io
            buf = io.StringIO()
            w = csv.writer(buf, lineterminator="")
            vals = ["" if v is None else str(v) for v in row]
            w.writerow(vals if self.append_only
                       else ["+" if sign > 0 else "-"] + vals)
            return buf.getvalue()
        obj = dict(zip(names, row))
        if self.append_only:
            return json.dumps(obj, default=_json_default)
        return json.dumps({"op": "+" if sign > 0 else "-", "row": obj},
                          default=_json_default)

    def commit(self, epoch: int) -> None:
        """Checkpoint-barrier commit: append + fsync + manifest rename.
        Empty epochs advance committed_epoch in memory only — a replayed
        empty epoch has nothing to duplicate, so idle ticks cost no IO."""
        if epoch <= self.committed_epoch:
            self._pending.clear()     # replayed epoch: already delivered
            return
        self.committed_epoch = epoch
        if not self._pending:
            return
        data = "".join(self._format_row(s, r) + "\n"
                       for s, r in self._pending)
        enc = data.encode("utf-8")
        with open(self.path, "ab") as f:
            f.write(enc)
            f.flush()
            os.fsync(f.fileno())
        self._committed_bytes += len(enc)
        self._pending.clear()
        tmp = self._manifest_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"epoch": epoch, "bytes": self._committed_bytes}, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._manifest_path)


class SinkExecutor(Executor):
    """Executor shim: pipes the upstream change stream into a sink object,
    committing at checkpoint barriers (`SinkExecutor`, `src/stream/src/
    executor/sink.rs` analog)."""

    def __init__(self, input: Executor, sink: FileSink, name: str = "Sink"):
        super().__init__(input.schema, name)
        self.input = input
        self.sink = sink

    def execute(self) -> Iterator[Message]:
        for msg in self.input.execute():
            if isinstance(msg, StreamChunk):
                if msg.cardinality:
                    self.sink.write_chunk(msg.compact())
            elif isinstance(msg, Barrier):
                if msg.is_checkpoint:
                    self.sink.commit(msg.epoch.curr)
            yield msg
