"""Sinks: deliver a change stream to an external system, exactly once.

Reference: `src/connector/src/sink/mod.rs:602` (`Sink` trait) + the
log-store decoupling (`src/stream/src/common/log_store_impl/`) and the
"write epoch, then commit" discipline of the coordinated sinks. The TPU
runtime's analog keeps the full two-store protocol:

* rows buffer per epoch in memory;
* at a CHECKPOINT barrier the epoch's rows are written to a durable LOG
  state table (the log-store analog) — that write becomes durable in the
  SAME store commit as the source offsets and every operator's state, so
  the log and the rest of the checkpoint agree by construction;
* delivery to the external file happens one checkpoint later, once the
  log entries are known durable: append + fsync, then a manifest
  (epoch -> byte length) renames into place — the external commit point —
  and the delivered log entries are deleted;
* on restart the sink truncates the data file to the manifested length,
  re-delivers any durable log epochs past the manifest, and drops log
  epochs at or below it. Every crash window re-delivers exactly the rows
  whose delivery is not manifested and whose ingestion is checkpointed —
  exactly-once end to end.

Formats: `jsonl` (append-only streams emit the bare row object;
retractable streams wrap it as {"op": "+"/"-", "row": {...}} — the
debezium-ish changelog shape) and `csv` (RFC-4180 quoting).
"""
from __future__ import annotations

import json
import os
import time
from typing import Any, Iterator, List, Optional, Tuple

from ..core.chunk import StreamChunk
from ..core.encoding import decode_row, encode_row
from ..core.schema import Schema
from ..ops.executor import Executor
from ..ops.message import Barrier, Message
from ..state.state_table import StateTable
from ..utils.failpoint import declare, failpoint

_FORMATS = ("jsonl", "json", "ndjson", "csv")

declare("overload.slow_sink",
        "stalled-external-sink chaos: while armed, sink delivery is "
        "deferred (the external system is 'unavailable') — the backlog "
        "parks in the DURABLE sink log, the sink reports `stalled` in "
        "liveness, and the overload ladder sees full sink pressure; "
        "disarming delivers the backlog at the next checkpoint")


def _json_default(v):
    return str(v)


class FileSink:
    """Append-only local-file sink; the manifest rename is the external
    commit point."""

    def __init__(self, path: str, schema: Schema, fmt: str = "jsonl",
                 append_only: bool = False):
        if fmt not in _FORMATS:
            raise ValueError(
                f"unknown sink format {fmt!r} (expected one of {_FORMATS})")
        self.path = path
        self.schema = schema
        self.fmt = fmt
        self.append_only = append_only
        self._names = [f.name for f in schema.fields]
        self.committed_epoch = 0
        self._committed_bytes = 0
        self._recover()

    # ---- recovery -------------------------------------------------------
    @property
    def _manifest_path(self) -> str:
        return self.path + ".manifest"

    def _recover(self) -> None:
        if os.path.exists(self._manifest_path):
            with open(self._manifest_path) as f:
                m = json.load(f)
            self.committed_epoch = m["epoch"]
            self._committed_bytes = m["bytes"]
        if os.path.exists(self.path):
            size = os.path.getsize(self.path)
            if size > self._committed_bytes:
                if self.committed_epoch == 0 and self._committed_bytes == 0:
                    # no manifest: this file was NOT written by this sink —
                    # truncating would destroy someone else's data
                    raise FileExistsError(
                        f"sink path {self.path!r} already exists with "
                        "content but no sink manifest; refusing to "
                        "overwrite")
                # drop any append that never reached its manifest commit
                with open(self.path, "r+b") as f:
                    f.truncate(self._committed_bytes)
            elif size < self._committed_bytes:
                # externally truncated: continuing would overstate
                # _committed_bytes and silently break the torn-tail guard
                raise IOError(
                    f"sink data file {self.path!r} is {size} bytes but "
                    f"manifest committed {self._committed_bytes}: external "
                    "truncation/corruption")
        elif self._committed_bytes:
            raise FileNotFoundError(
                f"sink data file {self.path!r} missing but manifest "
                f"claims {self._committed_bytes} bytes")

    # ---- delivery -------------------------------------------------------
    def _format_rows(self, pairs: List[Tuple[int, Tuple]]) -> str:
        if self.fmt == "csv":
            import csv
            import io
            buf = io.StringIO()
            w = csv.writer(buf)
            for sign, row in pairs:
                vals = ["" if v is None else str(v) for v in row]
                w.writerow(vals if self.append_only
                           else ["+" if sign > 0 else "-"] + vals)
            return buf.getvalue()
        out = []
        for sign, row in pairs:
            obj = dict(zip(self._names, row))
            if self.append_only:
                out.append(json.dumps(obj, default=_json_default))
            else:
                out.append(json.dumps(
                    {"op": "+" if sign > 0 else "-", "row": obj},
                    default=_json_default))
        return "".join(s + "\n" for s in out)

    def deliver(self, epoch: int, pairs: List[Tuple[int, Tuple]]) -> None:
        """Append `pairs` (already durable in the log) and move the
        manifest to `epoch`: append + fsync + atomic rename."""
        if epoch <= self.committed_epoch:
            return
        if pairs:
            enc = self._format_rows(pairs).encode("utf-8")
            with open(self.path, "ab") as f:
                f.write(enc)
                f.flush()
                os.fsync(f.fileno())
            self._committed_bytes += len(enc)
        self.committed_epoch = epoch
        if not pairs:
            return                       # idle epochs cost no IO
        tmp = self._manifest_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"epoch": epoch, "bytes": self._committed_bytes}, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._manifest_path)


class SinkExecutor(Executor):
    """Executor shim: change stream -> durable log -> external delivery
    (`src/stream/src/executor/sink.rs` + log-store analog).

    The log state table rows are (epoch, seq) -> (sign, value-encoded
    row). Current-epoch rows are logged at their checkpoint barrier (they
    become durable in the same store commit as everything else); log
    epochs already durable — at or below the store's committed epoch —
    deliver to the file at the NEXT checkpoint and are then deleted.

    Refresh dedupe (`pk_indices`): a supervised worker respawn's v1
    full refresh re-INSERTs every owned group — the MV reconciles by pk,
    but the change stream carries duplicate `+` records straight into
    the sink. With a pk, the sink keeps a per-pk mirror of what it has
    delivered and reconciles at its boundary: a `+` identical to the
    mirrored row is dropped (the duplicate), a `+` for a pk holding a
    DIFFERENT row becomes a `-old`/`+new` repair pair, and a `-` for a
    pk the mirror holds retracts the mirrored row (robust to refresh
    artifacts). Rows for unseen pks always pass — unseen means the
    mirror genuinely never delivered them.

    Durable mirror journal (`mirror_table`, fault-tolerance v3): the
    per-pk mirror is no longer a coordinator-process-lifetime structure.
    Its deltas journal through a durable state table with EPOCH-FENCED
    commits — the same store commit as the sink log and every operator's
    state — and a restarted coordinator REBUILDS the mirror from the
    journal before the first post-restart change arrives. A refresh
    racing a coordinator crash therefore cannot duplicate into the
    external file: the re-stated rows meet a mirror that remembers them.
    Append-only streams and pk-less shapes skip the mirror entirely."""

    def __init__(self, input: Executor, sink: FileSink,
                 log_table: Optional[StateTable] = None,
                 pk_indices: Optional[List[int]] = None,
                 mirror_table: Optional[StateTable] = None,
                 name: str = "Sink"):
        super().__init__(input.schema, name)
        self.input = input
        self.sink = sink
        self.log_table = log_table
        self._pending: List[Tuple[int, Tuple]] = []
        # slow-sink isolation (overload control plane): `stalled` flips
        # while external delivery is deferred (overload.slow_sink chaos,
        # or a real delivery failure) — surfaced in rw_worker_liveness
        # and read by the overload manager as full sink pressure. The
        # backlog parks in the DURABLE sink log (disk), never RSS; the
        # in-memory window spool is bounded by `pending_rows()` feeding
        # the ladder, which throttles the sources upstream.
        self.stalled = False
        self.last_delivery_ts = time.time()
        self._dtypes = [f.dtype for f in input.schema.fields]
        self.pk_indices = list(pk_indices) if pk_indices else None
        self._mirror: dict = {}
        self.dedupe = bool(self.pk_indices) and not input.append_only
        self.mirror_table = mirror_table if self.dedupe else None
        self._pk_dtypes = [self._dtypes[i] for i in self.pk_indices] \
            if self.pk_indices else []
        # pk -> the exact journal row last written (delete-then-insert
        # upserts need the old row back)
        self._journaled: dict = {}
        # pks whose mirror entry changed since the last checkpoint —
        # the journal writes deltas, not full snapshots
        self._mirror_dirty: set = set()
        if self.mirror_table is not None:
            # coordinator restart: rebuild the delivered mirror from the
            # journal — only COMMITTED entries survive in the store, so
            # the rebuild is epoch-fenced by construction
            for jrow in self.mirror_table.iter_all():
                jrow = tuple(jrow)
                row = decode_row(jrow[2], self._dtypes)
                self._mirror[tuple(row[i] for i in self.pk_indices)] = row
                self._journaled[jrow[0]] = jrow

    def _reconcile(self, sign: int, row: Tuple) -> List[Tuple[int, Tuple]]:
        """Map one change through the delivered-row mirror; returns the
        (sign, row) pairs that actually go to the log/file."""
        pk = tuple(row[i] for i in self.pk_indices)
        held = self._mirror.get(pk)
        if sign > 0:
            if held == row:
                from ..utils.metrics import REGISTRY
                REGISTRY.counter(
                    "sink_dedupe_dropped_total",
                    "duplicate refresh records dropped at the sink "
                    "boundary").inc()
                return []
            self._mirror[pk] = row
            self._mirror_dirty.add(pk)
            if held is not None:        # refresh with a changed value
                return [(-1, held), (1, row)]
            return [(1, row)]
        if held is not None:
            del self._mirror[pk]
            self._mirror_dirty.add(pk)
            return [(-1, held)]
        return [(-1, row)]              # unseen pk: trust upstream

    def _journal_mirror(self, epoch: int) -> None:
        """Write the checkpoint window's mirror deltas to the journal
        table and commit them fenced at `epoch` — the same store commit
        that makes the sink log and the operators' state durable, so the
        mirror can never run ahead of (or behind) the data it fences."""
        if self.mirror_table is None or not self._mirror_dirty:
            self._mirror_dirty.clear()
            return
        for pk in self._mirror_dirty:
            key = encode_row(pk, self._pk_dtypes)
            old = self._journaled.pop(key, None)
            if old is not None:
                self.mirror_table.delete(old)
            row = self._mirror.get(pk)
            if row is not None:
                new = (key, epoch, encode_row(row, self._dtypes))
                self.mirror_table.insert(new)
                self._journaled[key] = new
        self._mirror_dirty.clear()
        self.mirror_table.commit(epoch)

    def pending_rows(self) -> int:
        """Rows spooled in the current checkpoint window (the in-memory
        spool the overload manager bounds against RW_SINK_SPOOL_ROWS)."""
        return len(self._pending)

    def _mark_stalled(self) -> None:
        if not self.stalled:
            from ..utils.metrics import REGISTRY
            REGISTRY.counter(
                "sink_stalls_total",
                "times a sink's external delivery stalled",
                labels=("sink",)).labels(self.name).inc()
        self.stalled = True

    def _stall(self) -> bool:
        """True while the external system is 'unavailable' (armed
        overload.slow_sink). Delivery defers — the durable log keeps the
        backlog on disk — and the stalled flag feeds liveness plus the
        overload ladder. A REAL delivery failure (OSError out of the
        external append/rename) takes the same path via the callers'
        except clauses."""
        if failpoint("overload.slow_sink"):
            self._mark_stalled()
            return True
        return False

    def deliver_durable(self) -> None:
        """Ship every log epoch that the store has made durable. Called by
        the barrier loop right after `store.commit_epoch` (the
        post-checkpoint sink-committer step), and again defensively at the
        next checkpoint barrier (covers recovery)."""
        if self.log_table is None:
            return
        if self._stall():
            return                       # backlog stays in the durable log
        durable = getattr(self.log_table.store, "committed_epoch", 0)
        by_epoch: dict = {}
        for row in list(self.log_table.iter_all()):
            epoch, seq, sign, payload = row
            if epoch > durable:
                continue
            by_epoch.setdefault(epoch, []).append((seq, sign, payload, row))
        for epoch in sorted(by_epoch):
            # explicit (epoch, seq) replay order — table iteration is
            # vnode-prefixed, which would interleave rows
            entries = sorted(by_epoch[epoch])
            if epoch > self.sink.committed_epoch:
                pairs = [(sign, decode_row(payload, self._dtypes))
                         for _, sign, payload, _ in entries]
                try:
                    self.sink.deliver(epoch, pairs)
                except OSError:
                    # real external failure (disk full, unmounted path):
                    # isolate like the chaos stall — backlog stays in
                    # the durable log, retried next checkpoint — instead
                    # of crashing the coordinator tick
                    self._mark_stalled()
                    return
            for _, _, _, row in entries:   # delivered or already manifested
                self.log_table.delete(row)
        self.stalled = False
        self.last_delivery_ts = time.time()

    def execute(self) -> Iterator[Message]:
        for msg in self.input.execute():
            if isinstance(msg, StreamChunk):
                if msg.cardinality:
                    for op, row in msg.compact().op_rows():
                        if self.dedupe:
                            self._pending.extend(
                                self._reconcile(op.sign, row))
                        else:
                            self._pending.append((op.sign, row))
            elif isinstance(msg, Barrier) and msg.is_checkpoint:
                epoch = msg.epoch.curr
                if self.log_table is None:
                    # non-durable runtime: deliver directly (tests/
                    # ephemeral); a stalled external defers delivery and
                    # the window accumulates in the bounded spool (the
                    # ladder throttles the sources against it)
                    if not self._stall():
                        try:
                            self.sink.deliver(epoch, self._pending)
                        except OSError:
                            self._mark_stalled()
                        else:
                            self._pending.clear()
                            self.stalled = False
                            self.last_delivery_ts = time.time()
                else:
                    self.deliver_durable()
                    if epoch > self.sink.committed_epoch:
                        for i, (sign, row) in enumerate(self._pending):
                            self.log_table.insert(
                                (epoch, i, sign,
                                 encode_row(row, self._dtypes)))
                    self._pending.clear()
                    self.log_table.commit(epoch)
                    # mirror deltas journal in the SAME epoch fence as
                    # the log entries they deduplicated against
                    self._journal_mirror(epoch)
            yield msg
