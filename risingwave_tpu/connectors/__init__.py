"""Connectors: sources & sinks (reference: `src/connector/`)."""
from .base import (CsvParser, JsonParser, Parser, SourceSplit,
                   SplitEnumerator, SplitReader, SplitSourceReader,
                   make_parser)
from .datagen import DatagenReader, FieldGen, ListReader
from .filesystem import DirEnumerator, LineFileReader
from .nexmark import (AUCTION_SCHEMA, BID_SCHEMA, PERSON_SCHEMA, NexmarkConfig,
                      NexmarkGenerator, NexmarkReader)
from .sink import FileSink, SinkExecutor

__all__ = [
    "CsvParser", "JsonParser", "Parser", "SourceSplit", "SplitEnumerator",
    "SplitReader", "SplitSourceReader", "make_parser",
    "DatagenReader", "FieldGen", "ListReader",
    "DirEnumerator", "LineFileReader", "FileSink", "SinkExecutor",
    "AUCTION_SCHEMA", "BID_SCHEMA", "PERSON_SCHEMA", "NexmarkConfig",
    "NexmarkGenerator", "NexmarkReader",
]
