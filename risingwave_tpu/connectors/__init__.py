"""Connectors: sources & sinks (reference: `src/connector/`)."""
from .datagen import DatagenReader, FieldGen, ListReader
from .nexmark import (AUCTION_SCHEMA, BID_SCHEMA, PERSON_SCHEMA, NexmarkConfig,
                      NexmarkGenerator, NexmarkReader)

__all__ = [
    "DatagenReader", "FieldGen", "ListReader", "AUCTION_SCHEMA", "BID_SCHEMA",
    "PERSON_SCHEMA", "NexmarkConfig", "NexmarkGenerator", "NexmarkReader",
]
