"""Connector framework: splits, enumerators, readers, parsers.

Re-design of the reference's source stack (`src/connector/src/source/
base.rs:77` `SplitEnumerator`, `:474` `SplitReader`, parser at
`src/connector/src/parser/mod.rs`) collapsed to the pieces the
single-process TPU runtime needs:

* `SourceSplit` — one unit of parallel ingestion (a file, a partition, a
  generator shard) with a resumable offset.
* `SplitEnumerator` — discovers the current split set (re-run per poll so
  late-arriving splits, e.g. new files, are picked up).
* `SplitReader` — reads raw records from one split starting at an offset.
* `Parser` — raw records -> columnar StreamChunk for a schema, with PG-ish
  type coercion. Parsing is host-side and batched: records come in lists
  and columns are built once per batch, not per field.
* `SplitSourceReader` — composes the three behind the runtime's
  `SourceReader` protocol (`ops/source.py`): round-robins live splits,
  tracks per-split offsets, and persists/restores them through the split
  state table (offset-in-state recovery, `source_executor.rs:53`).
"""
from __future__ import annotations

import json
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..core.chunk import Column, Op, StreamChunk
from ..core.dtypes import DataType, TypeKind
from ..core.schema import Schema
from ..ops.source import SourceReader


@dataclass(frozen=True)
class SourceSplit:
    """One resumable unit of ingestion (`SplitImpl` analog)."""
    split_id: str
    meta: Any = None


class SplitEnumerator:
    """Discovers the live split set (`SplitEnumerator::list_splits`)."""

    def list_splits(self) -> List[SourceSplit]:
        raise NotImplementedError


class SplitReader:
    """Reads raw records from one split (`SplitReader::into_stream`)."""

    def read(self, split: SourceSplit, offset: Any, max_records: int
             ) -> Tuple[List[bytes], Any]:
        """Up to max_records raw records from `offset`; returns
        (records, next_offset). Empty list = nothing available now."""
        raise NotImplementedError


# ---------------------------------------------------------------------------
# Parsers
# ---------------------------------------------------------------------------

def _coerce(v: Any, dtype: DataType) -> Any:
    """JSON value -> host representation for `dtype` (PG-ish casts)."""
    if v is None:
        return None
    kind = dtype.kind
    if kind in (TypeKind.INT16, TypeKind.INT32, TypeKind.INT64,
                TypeKind.SERIAL):
        return int(v)
    if kind in (TypeKind.FLOAT32, TypeKind.FLOAT64):
        return float(v)
    if kind == TypeKind.BOOLEAN:
        if isinstance(v, str):
            return v.strip().lower() in ("t", "true", "1", "yes", "on")
        return bool(v)
    if kind == TypeKind.VARCHAR:
        return v if isinstance(v, str) else json.dumps(v)
    if kind in (TypeKind.TIMESTAMP, TypeKind.TIMESTAMPTZ):
        if isinstance(v, (int, float)):
            return int(v)                      # already epoch usecs
        from datetime import datetime, timezone
        dt = datetime.fromisoformat(str(v))
        if dt.tzinfo is None:
            dt = dt.replace(tzinfo=timezone.utc)
        return int(dt.timestamp() * 1_000_000)
    if kind == TypeKind.DATE:
        from datetime import date
        return (date.fromisoformat(v) - date(1970, 1, 1)).days \
            if isinstance(v, str) else int(v)
    if kind == TypeKind.DECIMAL:
        from decimal import Decimal
        return Decimal(str(v))
    raise NotImplementedError(f"json coercion for {dtype}")


class Parser:
    """Raw record batch -> StreamChunk (`ByteStreamSourceParser` analog)."""

    def __init__(self, schema: Schema):
        self.schema = schema

    def parse(self, records: Sequence[bytes]) -> StreamChunk:
        raise NotImplementedError

    def _chunk_from_rows(self, rows: List[List[Any]]) -> StreamChunk:
        cols = [Column.from_list(f.dtype, [r[i] for r in rows])
                for i, f in enumerate(self.schema.fields)]
        return StreamChunk(np.zeros(len(rows), dtype=np.int8), cols)


class JsonParser(Parser):
    """One JSON object per record, fields matched by column name
    (`parser/json_parser.rs` analog): missing fields are NULL, unknown
    fields are ignored, malformed records are skipped with a count."""

    def __init__(self, schema: Schema):
        super().__init__(schema)
        self.errors = 0

    def parse(self, records: Sequence[bytes]) -> StreamChunk:
        names = [f.name for f in self.schema.fields]
        dtypes = [f.dtype for f in self.schema.fields]
        rows: List[List[Any]] = []
        for rec in records:
            try:
                obj = json.loads(rec)
                if not isinstance(obj, dict):   # e.g. bare array/number
                    self.errors += 1
                    continue
                rows.append([_coerce(obj.get(n), d)
                             for n, d in zip(names, dtypes)])
            except (ValueError, TypeError, KeyError, ArithmeticError):
                self.errors += 1    # ArithmeticError covers bad DECIMALs
        return self._chunk_from_rows(rows)


class CsvParser(Parser):
    """Delimiter-separated records, positional columns, RFC-4180 quoting
    (`parser/csv_parser.rs` analog). Empty unquoted field = NULL.
    Values with embedded newlines need a record-aware reader upstream —
    the newline-framed `LineFileReader` hands over one line per record."""

    def __init__(self, schema: Schema, delimiter: str = ","):
        super().__init__(schema)
        self.delimiter = delimiter
        self.errors = 0

    def parse(self, records: Sequence[bytes]) -> StreamChunk:
        import csv
        dtypes = [f.dtype for f in self.schema.fields]
        rows: List[List[Any]] = []
        for rec in records:
            try:
                parts = next(csv.reader([rec.decode("utf-8")],
                                        delimiter=self.delimiter))
                rows.append([
                    _coerce(p if p != "" else None, d)
                    for p, d in zip(parts + [None] * len(dtypes), dtypes)])
            except (ValueError, TypeError, StopIteration, ArithmeticError):
                self.errors += 1
        return self._chunk_from_rows(rows)


def make_parser(fmt: str, schema: Schema, options: Dict[str, str]) -> Parser:
    fmt = fmt.lower()
    if fmt in ("json", "jsonl", "ndjson"):
        return JsonParser(schema)
    if fmt == "csv":
        return CsvParser(schema, options.get("csv.delimiter", ","))
    raise ValueError(f"unknown source format {fmt!r}")


# ---------------------------------------------------------------------------
# Generic reader
# ---------------------------------------------------------------------------

class SplitSourceReader(SourceReader):
    """Enumerator + reader + parser behind the runtime SourceReader
    protocol. Per-split offsets are the recovery state: they persist into
    the split state table at every checkpoint and `seek` restores them."""

    def __init__(self, enumerator: SplitEnumerator, reader: SplitReader,
                 parser: Parser, records_per_poll: int = 4096):
        self.enumerator = enumerator
        self.reader = reader
        self.parser = parser
        self.records_per_poll = records_per_poll
        # admission batch throttle in (0, 1]: the overload control plane
        # (ops/source._poll_gated) shrinks the per-poll batch together
        # with the poll cadence when downstream credit starves — the
        # unread records stay in the split at their offset, which IS the
        # backpressure reaching the connector
        self.throttle = 1.0
        self.offsets: Dict[str, Any] = {}
        self._rr: int = 0   # round-robin cursor over the live split list
        # wall of the last successful poll — the source->MV freshness
        # measure anchors here (data "exists" the moment it is read off
        # the split, BEFORE parsing: parse cost is inside the measure)
        self.last_ingest_ts: Optional[float] = None

    def poll(self) -> Optional[StreamChunk]:
        import time
        splits = self.enumerator.list_splits()
        if not splits:
            return None
        # round-robin: give every split a chance before returning None
        budget = max(1, int(self.records_per_poll
                            * min(1.0, max(0.0, self.throttle))))
        for probe in range(len(splits)):
            s = splits[(self._rr + probe) % len(splits)]
            records, nxt = self.reader.read(
                s, self.offsets.get(s.split_id), budget)
            if records:
                read_ts = time.time()
                self._rr = (self._rr + probe + 1) % len(splits)
                self.offsets[s.split_id] = nxt
                chunk = self.parser.parse(records)
                if chunk.cardinality > 0:
                    self.last_ingest_ts = read_ts
                    return chunk
        self._rr = (self._rr + 1) % max(1, len(splits))
        return None

    def split_states(self) -> Dict[str, Any]:
        return dict(self.offsets)

    def seek(self, states: Dict[str, Any]) -> None:
        self.offsets.update(states)
