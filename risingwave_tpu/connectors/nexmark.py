"""Nexmark event generator — the benchmark workload source.

Reference: `src/connector/src/source/nexmark/` (which wraps the external
`nexmark` crate) and the e2e source definitions at
`e2e_test/nexmark/create_sources.slt.part`. This is an independent,
vectorized re-implementation of the standard Nexmark generator semantics:

* one global event sequence; event n is a Person if n % 50 == 0, an Auction
  if n % 50 in 1..=3, else a Bid (1:3:46 proportions);
* ids are dense per entity type with the standard offsets;
* bids reference recent "hot" auctions/people with the standard 90% skew;
* event timestamps advance at a configurable inter-event gap.

Fully deterministic given a seed; all columns generated with numpy
(vectorized splitmix64) so the generator itself never bottlenecks the
benchmark.

Schemas (matching the reference's CREATE SOURCE):
  person(id, name, email_address, credit_card, city, state, date_time, extra)
  auction(id, item_name, description, initial_bid, reserve, date_time,
          expires, seller, category, extra)
  bid(auction, bidder, price, channel, url, date_time, extra)
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..core.chunk import Column, StreamChunk
from ..core.schema import Field, Schema
from ..core import dtypes as T
from ..ops.source import SourceReader
from .datagen import splitmix64

PERSON_PROPORTION = 1
AUCTION_PROPORTION = 3
TOTAL_PROPORTION = 50  # 46 bids per 50 events

FIRST_PERSON_ID = 1000
FIRST_AUCTION_ID = 1000
FIRST_CATEGORY_ID = 10

HOT_AUCTION_RATIO = 100
HOT_BIDDER_RATIO = 100
HOT_SELLER_RATIO = 100

US_STATES = ["az", "ca", "id", "or", "wa", "wy"]
US_CITIES = ["phoenix", "los angeles", "san francisco", "boise", "portland",
             "bend", "redmond", "seattle", "kent", "cheyenne"]
FIRST_NAMES = ["peter", "paul", "luke", "john", "saul", "vicky", "kate",
               "julie", "sarah", "deiter", "walter"]
LAST_NAMES = ["shultz", "abrams", "spencer", "white", "bartels", "walton",
              "smith", "jones", "noris"]
CHANNELS = ["apple", "google", "facebook", "baidu"]

# Precomputed object-dtype pools: string columns are produced by fancy
# indexing (C speed), never per-row Python. NAME/EMAIL pools are the
# first x last cross product, indexed fi * len(LAST_NAMES) + li.
_CH_POOL = np.array(CHANNELS, dtype=object)
_URL_POOL = np.array([f"https://www.nexmark.com/{c}/item.htm?query=1"
                      for c in CHANNELS], dtype=object)
_CITY_POOL = np.array(US_CITIES, dtype=object)
_STATE_POOL = np.array(US_STATES, dtype=object)
_NAME_POOL = np.array([f"{a} {b}" for a in FIRST_NAMES for b in LAST_NAMES],
                      dtype=object)
_EMAIL_POOL = np.array([f"{a}@{b}.com" for a in FIRST_NAMES
                        for b in LAST_NAMES], dtype=object)


def _obj_col(values: np.ndarray) -> Column:
    """VARCHAR column from an all-valid object array (skips the per-row
    null scan Column would otherwise do)."""
    return Column(T.VARCHAR, values, np.ones(len(values), dtype=np.bool_))


def _empty_strings(n: int) -> Column:
    return _obj_col(np.full(n, "", dtype=object))

PERSON_SCHEMA = Schema.of(
    ("id", T.INT64), ("name", T.VARCHAR), ("email_address", T.VARCHAR),
    ("credit_card", T.VARCHAR), ("city", T.VARCHAR), ("state", T.VARCHAR),
    ("date_time", T.TIMESTAMP), ("extra", T.VARCHAR))

AUCTION_SCHEMA = Schema.of(
    ("id", T.INT64), ("item_name", T.VARCHAR), ("description", T.VARCHAR),
    ("initial_bid", T.INT64), ("reserve", T.INT64), ("date_time", T.TIMESTAMP),
    ("expires", T.TIMESTAMP), ("seller", T.INT64), ("category", T.INT64),
    ("extra", T.VARCHAR))

BID_SCHEMA = Schema.of(
    ("auction", T.INT64), ("bidder", T.INT64), ("price", T.INT64),
    ("channel", T.VARCHAR), ("url", T.VARCHAR), ("date_time", T.TIMESTAMP),
    ("extra", T.VARCHAR))


@dataclass
class NexmarkConfig:
    seed: int = 42
    base_time_usecs: int = 1_500_000_000_000_000  # 2017-07-14-ish, like nexmark
    inter_event_gap_usecs: int = 100  # matches min.event.gap.in.ns=100 in e2e
    # auctions stay open for this many events' worth of time
    auction_duration_events: int = 200
    strings_on: bool = True  # generating varchar columns costs host time
    # "" = nexmark's hot/cold picks; "zipf:<s>" (s > 1, e.g. "zipf:1.5",
    # SQL: WITH (nexmark.key.dist='zipf:1.5')) reshapes the bid
    # auction/bidder picks into a power law — reproducible Zipfian
    # workloads for skew tests/bench. Device twin: device/nexmark_gen.py
    # (bit-identical streams).
    key_dist: str = ""


def _event_kinds(event_ids: np.ndarray) -> np.ndarray:
    """0=person, 1=auction, 2=bid."""
    m = event_ids % TOTAL_PROPORTION
    return np.where(m == 0, 0, np.where(m <= AUCTION_PROPORTION, 1, 2))


def _person_count_before(event_ids: np.ndarray) -> np.ndarray:
    """Number of person events among events [0, n)."""
    full, rem = np.divmod(event_ids, TOTAL_PROPORTION)
    return full * PERSON_PROPORTION + (rem > 0)


def _mulhi_bound(r: np.ndarray, m: np.ndarray) -> np.ndarray:
    """Uniform u64 `r` -> [0, m): high 64 bits of r*m (Lemire reduce).
    Mirrors `device/nexmark_gen.py::_mulhi_bound` EXACTLY — the device
    generator avoids 64-bit vector division (XLA-compile-pathological),
    and host/device surrogate streams must stay bit-identical."""
    mask = np.uint64(0xFFFFFFFF)
    r = r.astype(np.uint64)
    m = m.astype(np.uint64)
    a0, a1 = r & mask, r >> np.uint64(32)
    b0, b1 = m & mask, m >> np.uint64(32)
    m00 = a0 * b0
    m01 = a0 * b1
    m10 = a1 * b0
    m11 = a1 * b1
    sh = np.uint64(32)
    carry = (m00 >> sh) + (m01 & mask) + (m10 & mask)
    return (m11 + (m01 >> sh) + (m10 >> sh)
            + (carry >> sh)).astype(np.int64)


def _zipf_ordinal(rand_pick: np.ndarray, n_entities: np.ndarray,
                  s: float) -> np.ndarray:
    """Power-law entity ordinal (pmf ~ rank^-s): bounded-Pareto inverse
    CDF, rank = floor((1-u)^(-1/(s-1))) clipped to [1, n]; ordinal 0 =
    the hottest entity, stationary as n grows. Mirrors
    `device/nexmark_gen.py::_zipf_ordinal` EXACTLY (same f64 expression
    over the same rand draws) — host/device streams stay bit-identical."""
    u = (rand_pick.astype(np.uint64) >> np.uint64(11)
         ).astype(np.float64) * (2.0 ** -53)
    rank = np.floor(np.power(1.0 - u, -1.0 / (s - 1.0)))
    rank = np.minimum(rank, n_entities.astype(np.float64))
    return np.maximum(rank, 1.0).astype(np.int64) - 1


def _auction_count_before(event_ids: np.ndarray) -> np.ndarray:
    full, rem = np.divmod(event_ids, TOTAL_PROPORTION)
    return full * AUCTION_PROPORTION + np.clip(rem - PERSON_PROPORTION, 0,
                                               AUCTION_PROPORTION)


class NexmarkGenerator:
    """Vectorized generator over a contiguous range of event ids."""

    def __init__(self, config: Optional[NexmarkConfig] = None):
        self.cfg = config or NexmarkConfig()

    def _rand(self, ids: np.ndarray, salt: int) -> np.ndarray:
        return splitmix64(ids.astype(np.uint64)
                          + np.uint64((self.cfg.seed << 20) + salt))

    def _timestamps(self, event_ids: np.ndarray) -> np.ndarray:
        return (self.cfg.base_time_usecs
                + event_ids * self.cfg.inter_event_gap_usecs).astype(np.int64)

    def _strings(self, r: np.ndarray, pool: np.ndarray) -> np.ndarray:
        """Pool lookup via fancy indexing -> object array (no Python loop)."""
        idx = (r % np.uint64(len(pool))).astype(np.int64)
        return pool[idx]

    def gen_persons(self, event_ids: np.ndarray) -> StreamChunk:
        n = len(event_ids)
        person_idx = _person_count_before(event_ids)  # dense person ordinal
        ids = (FIRST_PERSON_ID + person_idx).astype(np.int64)
        ts = self._timestamps(event_ids)
        cols = [Column(T.INT64, ids)]
        if self.cfg.strings_on:
            fi = (self._rand(ids, 1) % np.uint64(len(FIRST_NAMES)))
            li = (self._rand(ids, 2) % np.uint64(len(LAST_NAMES)))
            combo = (fi * np.uint64(len(LAST_NAMES)) + li).astype(np.int64)
            cc = np.char.zfill(
                (self._rand(ids, 3) % np.uint64(10**16)).astype("U16"), 16)
            cols += [_obj_col(_NAME_POOL[combo]),
                     _obj_col(_EMAIL_POOL[combo]),
                     _obj_col(cc.astype(object)),
                     _obj_col(self._strings(self._rand(ids, 4), _CITY_POOL)),
                     _obj_col(self._strings(self._rand(ids, 5), _STATE_POOL))]
        else:
            cols += [_empty_strings(n)] * 5
        cols.append(Column(T.TIMESTAMP, ts))
        cols.append(_empty_strings(n))
        return StreamChunk(np.zeros(n, dtype=np.int8), cols)

    def gen_auctions(self, event_ids: np.ndarray) -> StreamChunk:
        n = len(event_ids)
        auction_idx = _auction_count_before(event_ids)
        ids = (FIRST_AUCTION_ID + auction_idx).astype(np.int64)
        ts = self._timestamps(event_ids)
        n_person = np.maximum(_person_count_before(event_ids), 1)
        r_seller = self._rand(ids, 10)
        # hot sellers: 90% pick from the most recent 1/HOT_SELLER_RATIO people
        hot = (r_seller % np.uint64(10)) != 0
        hot_span = np.maximum(n_person // HOT_SELLER_RATIO, 1)
        r2 = self._rand(ids, 11)
        seller_ord = np.where(
            hot,
            n_person - 1 - _mulhi_bound(r2, hot_span),
            _mulhi_bound(r2, n_person))
        seller = (FIRST_PERSON_ID + seller_ord).astype(np.int64)
        category = (FIRST_CATEGORY_ID
                    + (self._rand(ids, 12) % np.uint64(5)).astype(np.int64))
        initial_bid = 100 + (self._rand(ids, 13) % np.uint64(1000)).astype(np.int64)
        reserve = initial_bid + (self._rand(ids, 14) % np.uint64(1000)).astype(np.int64)
        expires = ts + (self.cfg.auction_duration_events
                        * self.cfg.inter_event_gap_usecs)
        cols = [Column(T.INT64, ids)]
        if self.cfg.strings_on:
            item = np.char.add("item-", ids.astype("U20"))
            desc = np.char.add(
                "desc-", (self._rand(ids, 15) % np.uint64(1000)).astype("U4"))
            cols += [_obj_col(item.astype(object)),
                     _obj_col(desc.astype(object))]
        else:
            empty = _empty_strings(n)
            cols += [empty, empty]
        cols += [Column(T.INT64, initial_bid), Column(T.INT64, reserve),
                 Column(T.TIMESTAMP, ts), Column(T.TIMESTAMP, expires),
                 Column(T.INT64, seller), Column(T.INT64, category),
                 _empty_strings(n)]
        return StreamChunk(np.zeros(n, dtype=np.int8), cols)

    def gen_bids(self, event_ids: np.ndarray) -> StreamChunk:
        n = len(event_ids)
        ts = self._timestamps(event_ids)
        n_auction = np.maximum(_auction_count_before(event_ids), 1)
        n_person = np.maximum(_person_count_before(event_ids), 1)
        if self.cfg.key_dist:
            # power-law picks (device twin: nexmark_gen._zipf_ordinal,
            # identical f64 expression over the same rand draws — the
            # streams stay bit-identical across host/device paths)
            from ..device.nexmark_gen import key_dist_s
            s = key_dist_s(self.cfg.key_dist)
            auction_ord = _zipf_ordinal(self._rand(event_ids, 21),
                                        n_auction, s)
            bidder_ord = _zipf_ordinal(self._rand(event_ids, 23),
                                       n_person, s)
        else:
            r = self._rand(event_ids, 20)
            hot_a = (r % np.uint64(100)) < np.uint64(90)
            r2 = self._rand(event_ids, 21)
            hot_span = np.maximum(n_auction // HOT_AUCTION_RATIO, 1)
            auction_ord = np.where(
                hot_a,
                n_auction - 1 - _mulhi_bound(r2, hot_span),
                _mulhi_bound(r2, n_auction))
            r3 = self._rand(event_ids, 22)
            hot_b = (r3 % np.uint64(100)) < np.uint64(90)
            r4 = self._rand(event_ids, 23)
            bspan = np.maximum(n_person // HOT_BIDDER_RATIO, 1)
            bidder_ord = np.where(
                hot_b,
                n_person - 1 - _mulhi_bound(r4, bspan),
                _mulhi_bound(r4, n_person))
        auction = (FIRST_AUCTION_ID + auction_ord).astype(np.int64)
        bidder = (FIRST_PERSON_ID + bidder_ord).astype(np.int64)
        price = 100 + (self._rand(event_ids, 24) % np.uint64(10_000)).astype(np.int64)
        cols = [Column(T.INT64, auction), Column(T.INT64, bidder),
                Column(T.INT64, price)]
        if self.cfg.strings_on:
            ci = (self._rand(event_ids, 25)
                  % np.uint64(len(_CH_POOL))).astype(np.int64)
            cols += [_obj_col(_CH_POOL[ci]), _obj_col(_URL_POOL[ci])]
        else:
            empty = _empty_strings(n)
            cols += [empty, empty]
        cols += [Column(T.TIMESTAMP, ts), _empty_strings(n)]
        return StreamChunk(np.zeros(n, dtype=np.int8), cols)

    def gen_range(self, start_event: int, end_event: int
                  ) -> Dict[str, StreamChunk]:
        """All events in [start, end), split per entity stream."""
        ids = np.arange(start_event, end_event, dtype=np.int64)
        kinds = _event_kinds(ids)
        out = {}
        p = ids[kinds == 0]
        a = ids[kinds == 1]
        b = ids[kinds == 2]
        if len(p):
            out["person"] = self.gen_persons(p)
        if len(a):
            out["auction"] = self.gen_auctions(a)
        if len(b):
            out["bid"] = self.gen_bids(b)
        return out


class NexmarkReader(SourceReader):
    """Reader for one entity stream; all three readers share one event clock
    (same event-id sequence) so cross-stream joins line up like the reference's
    single nexmark datagen."""

    def __init__(self, table: str, generator: NexmarkGenerator,
                 events_per_poll: int = 8192, max_events: Optional[int] = None,
                 columns: Optional[Sequence[str]] = None):
        assert table in ("person", "auction", "bid")
        self.table = table
        self.gen = generator
        self.events_per_poll = events_per_poll
        self.max_events = max_events
        self.next_event = 0
        self.schema = {"person": PERSON_SCHEMA, "auction": AUCTION_SCHEMA,
                       "bid": BID_SCHEMA}[table]
        # CREATE SOURCE may declare a column subset/reorder: project the
        # generated chunks onto the declared names
        self._proj: Optional[List[int]] = None
        if columns is not None:
            names = [f.name for f in self.schema.fields]
            missing = [c for c in columns if c not in names]
            if missing:
                raise ValueError(
                    f"nexmark table {table!r} has no columns {missing}; "
                    f"available: {names}")
            idx = [names.index(c) for c in columns]
            if idx != list(range(len(names))):
                self._proj = idx

    def poll(self) -> Optional[StreamChunk]:
        if self.max_events is not None and self.next_event >= self.max_events:
            return None
        end = self.next_event + self.events_per_poll
        if self.max_events is not None:
            end = min(end, self.max_events)
        chunks = self.gen.gen_range(self.next_event, end)
        self.next_event = end
        ch = chunks.get(self.table)
        if ch is not None and self._proj is not None:
            ch = ch.project(self._proj)
        return ch

    def split_states(self) -> Dict[str, Any]:
        return {f"nexmark-{self.table}": self.next_event}

    def seek(self, states: Dict[str, Any]) -> None:
        k = f"nexmark-{self.table}"
        if k in states:
            self.next_event = int(states[k])


# ---------------------------------------------------------------------------
# host-side SURROGATE generation (the fused host-ingest feed)
# ---------------------------------------------------------------------------


def _hot_pick_np(rand_hot: np.ndarray, rand_pick: np.ndarray,
                 n_entities: np.ndarray, hot_ratio: int,
                 hot_mod: int) -> np.ndarray:
    """numpy twin of `device/nexmark_gen._hot_pick` (same draws, same
    Lemire reduce) — shared by the surrogate generator below."""
    if hot_mod == 10:
        hot = (rand_hot % np.uint64(10)) != 0
    else:
        hot = (rand_hot % np.uint64(100)) < np.uint64(90)
    span = np.maximum(n_entities // hot_ratio, 1)
    ord_hot = n_entities - 1 - _mulhi_bound(rand_pick, span)
    ord_cold = _mulhi_bound(rand_pick, n_entities)
    return np.where(hot, ord_hot, ord_cold)


def gen_surrogates(cfg: NexmarkConfig, table: str,
                   event_ids: np.ndarray,
                   cols: Optional[Sequence[str]] = None
                   ) -> Dict[str, np.ndarray]:
    """Columns of `table` for these event ids as int64 SURROGATE
    arrays — the numpy twin of `device/nexmark_gen.gen_table`, value-
    identical by construction (same splitmix64 draws, same Lemire/zipf
    reduces, same pool-index encoding). This is what the host-ingest
    staging path (`device/ingest.py`) ships over the Arrow seam: the
    fused program consumes surrogate int64 columns either way, so a
    host-fed job is bit-identical to a device-datagen one, and string
    materialization cost never enters the ingest hot path (pull-time
    `decode_column` reconstructs the exact strings, as it always has).

    `cols` restricts generation to the named columns (feed-column
    pruning: the staging pipeline only pays for columns the fused
    program actually reads — the host-side twin of the XLA dead-code
    elimination the device generator gets for free). Per-column salts
    make every column's draws independent, so a pruned generation is
    value-identical to the corresponding slice of a full one."""
    seed = np.uint64((cfg.seed << 20))
    rand = lambda ids, salt: splitmix64(
        ids.astype(np.uint64) + (seed + np.uint64(salt)))
    mod = lambda r, k: (r % np.uint64(k)).astype(np.int64)
    _memo: Dict[str, Any] = {}

    def once(key, fn):
        # shared intermediates (ts, entity ordinals, initial_bid, pool
        # combos) compute at most once per call even when several
        # requested columns read them
        if key not in _memo:
            _memo[key] = fn()
        return _memo[key]

    ts = lambda: once("ts", lambda: (
        cfg.base_time_usecs + event_ids * cfg.inter_event_gap_usecs
    ).astype(np.int64))
    if table == "person":
        ids = (FIRST_PERSON_ID
               + _person_count_before(event_ids)).astype(np.int64)
        combo = lambda: once("combo", lambda: mod(
            rand(ids, 1), len(_NAME_POOL) // 9) * 9 + mod(rand(ids, 2),
                                                          9))
        thunks = {
            "id": lambda: ids,
            "name": combo, "email_address": combo,
            "credit_card": lambda: mod(rand(ids, 3), 10**16),
            "city": lambda: mod(rand(ids, 4), len(_CITY_POOL)),
            "state": lambda: mod(rand(ids, 5), len(_STATE_POOL)),
            "date_time": ts,
            "extra": lambda: np.zeros_like(ids),
        }
    elif table == "auction":
        ids = (FIRST_AUCTION_ID
               + _auction_count_before(event_ids)).astype(np.int64)

        def seller():
            n_person = np.maximum(_person_count_before(event_ids), 1)
            return (FIRST_PERSON_ID + _hot_pick_np(
                rand(ids, 10), rand(ids, 11), n_person,
                HOT_SELLER_RATIO, hot_mod=10)).astype(np.int64)

        initial_bid = lambda: once(
            "ib", lambda: 100 + mod(rand(ids, 13), 1000))
        thunks = {
            "id": lambda: ids, "item_name": lambda: ids,
            "description": lambda: mod(rand(ids, 15), 1000),
            "initial_bid": initial_bid,
            "reserve": lambda: initial_bid() + mod(rand(ids, 14), 1000),
            "date_time": ts,
            "expires": lambda: ts() + (cfg.auction_duration_events
                                       * cfg.inter_event_gap_usecs),
            "seller": seller,
            "category": lambda: FIRST_CATEGORY_ID + mod(rand(ids, 12), 5),
            "extra": lambda: np.zeros_like(ids),
        }
    elif table == "bid":
        def _ords():
            n_auction = np.maximum(_auction_count_before(event_ids), 1)
            n_person = np.maximum(_person_count_before(event_ids), 1)
            if cfg.key_dist:
                from ..device.nexmark_gen import key_dist_s
                s = key_dist_s(cfg.key_dist)
                return (_zipf_ordinal(rand(event_ids, 21), n_auction, s),
                        _zipf_ordinal(rand(event_ids, 23), n_person, s))
            return (_hot_pick_np(rand(event_ids, 20), rand(event_ids, 21),
                                 n_auction, HOT_AUCTION_RATIO,
                                 hot_mod=100),
                    _hot_pick_np(rand(event_ids, 22), rand(event_ids, 23),
                                 n_person, HOT_BIDDER_RATIO, hot_mod=100))

        ords = lambda: once("ords", _ords)
        ch = lambda: once("ch", lambda: mod(rand(event_ids, 25),
                                            len(_CH_POOL)))
        thunks = {
            "auction": lambda: (FIRST_AUCTION_ID
                                + ords()[0]).astype(np.int64),
            "bidder": lambda: (FIRST_PERSON_ID
                               + ords()[1]).astype(np.int64),
            "price": lambda: 100 + mod(rand(event_ids, 24), 10_000),
            "channel": ch, "url": ch, "date_time": ts,
            "extra": lambda: np.zeros_like(event_ids),
        }
    else:
        raise ValueError(f"unknown nexmark table {table!r}")
    want = list(thunks) if cols is None else list(cols)
    return {c: thunks[c]() for c in want}
