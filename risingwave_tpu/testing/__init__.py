"""Test harnesses: sqllogictest runner, deterministic sim helpers."""
from .slt import SltError, run_slt_file, run_slt_text
