"""sqllogictest runner.

The reference's e2e tier runs `.slt` files against a live cluster
(`docs/dev/src/tests/intro.md:43-75`, `e2e_test/`); this runner executes the
same format against an in-process `Database`. Supported directives:

    statement ok          statement error [substring]
    query <types> [rowsort]   ...SQL...   ----   expected rows
    include <path>        halt          sleep (ignored)

Values compare as canonical strings (ints un-decorated, floats rounded to
3 decimals like sqllogictest, NULL spelled NULL).
"""
from __future__ import annotations

import os
from decimal import Decimal
from typing import List, Optional

from ..sql import Database


class SltError(AssertionError):
    pass


def _canon(v) -> str:
    if v is None:
        return "NULL"
    if isinstance(v, bool):
        return "t" if v else "f"
    if isinstance(v, float):
        return f"{v:.3f}".rstrip("0").rstrip(".") if v % 1 else str(int(v))
    if isinstance(v, Decimal):
        return _canon(float(v)) if v % 1 else str(int(v))
    return str(v)


def _rows_to_lines(rows: List[tuple]) -> List[str]:
    return ["\t".join(_canon(v) for v in r) for r in rows]


def run_slt_text(text: str, db: Optional[Database] = None,
                 path: str = "<string>") -> Database:
    db = db or Database()
    lines = text.splitlines()
    i = 0

    def take_sql() -> str:
        nonlocal i
        sql_lines = []
        while i < len(lines) and lines[i].strip() not in ("", "----"):
            sql_lines.append(lines[i])
            i += 1
        return "\n".join(sql_lines)

    while i < len(lines):
        line = lines[i].strip()
        if not line or line.startswith("#"):
            i += 1
            continue
        parts = line.split()
        directive = parts[0]
        if directive == "halt":
            break
        if directive == "sleep":
            i += 1
            continue
        if directive == "include":
            base = os.path.dirname(path)
            with open(os.path.join(base, parts[1])) as f:
                run_slt_text(f.read(), db, parts[1])
            i += 1
            continue
        if directive == "statement":
            expect_err = parts[1] == "error"
            err_sub = " ".join(parts[2:]) if len(parts) > 2 else None
            i += 1
            sql = take_sql()
            try:
                db.run(sql)
                if expect_err:
                    raise SltError(f"{path}: expected error for: {sql}")
            except SltError:
                raise
            except Exception as e:
                if not expect_err:
                    raise SltError(f"{path}: statement failed: {sql}\n{e}") \
                        from e
                if err_sub and err_sub.lower() not in str(e).lower():
                    raise SltError(
                        f"{path}: error {e!r} missing {err_sub!r}") from e
            continue
        if directive == "query":
            sort_mode = parts[2] if len(parts) > 2 else "nosort"
            i += 1
            sql = take_sql()
            if i < len(lines) and lines[i].strip() == "----":
                i += 1
            expected = []
            while i < len(lines) and lines[i].strip() != "":
                expected.append(lines[i].rstrip("\n"))
                i += 1
            rows = db.query(sql)
            got = _rows_to_lines(rows)
            exp = [e.replace("    ", "\t") for e in expected]
            if sort_mode == "rowsort":
                got, exp = sorted(got), sorted(exp)
            if got != exp:
                raise SltError(
                    f"{path}: query mismatch for: {sql}\n"
                    f"expected:\n  " + "\n  ".join(exp) +
                    "\ngot:\n  " + "\n  ".join(got))
            continue
        raise SltError(f"{path}: unknown directive {directive!r}")
    return db


def run_slt_file(path: str, db: Optional[Database] = None) -> Database:
    with open(path) as f:
        return run_slt_text(f.read(), db, path)
