"""ctypes loader for the C++ host kernels (native/rw_native.cpp).

The library is built on first import (g++ is in the image; result cached
next to the sources). Every entry point has a numpy fallback so the
framework still runs where no toolchain exists — but the native path is the
default for host hot loops (vnode hashing for dispatch, key encoding),
mirroring the reference's native `src/common/src/hash/` kernels.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
_SRC = os.path.join(_REPO, "native", "rw_native.cpp")
_SO = os.path.join(_REPO, "native", "librw_native.so")

_lib: Optional[ctypes.CDLL] = None
_tried = False
_lock = threading.Lock()


def _build() -> bool:
    try:
        subprocess.run(
            ["g++", "-O3", "-shared", "-fPIC", "-o", _SO, _SRC],
            check=True, capture_output=True, timeout=120)
        return True
    except Exception:
        return False


def get_lib() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if not os.path.exists(_SO) or \
                os.path.getmtime(_SO) < os.path.getmtime(_SRC):
            if not os.path.exists(_SRC) or not _build():
                return None
        try:
            lib = ctypes.CDLL(_SO)
        except OSError:
            return None
        u8p = np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS")
        i64p = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
        u64p = np.ctypeslib.ndpointer(np.uint64, flags="C_CONTIGUOUS")
        u32p = np.ctypeslib.ndpointer(np.uint32, flags="C_CONTIGUOUS")
        i32p = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
        lib.rw_crc32_rows.argtypes = [u8p, ctypes.c_int64, ctypes.c_int64,
                                      u32p]
        lib.rw_crc32_i64_be.argtypes = [i64p, ctypes.c_int64, u32p]
        lib.rw_vnodes_i64.argtypes = [i64p, ctypes.c_int64, ctypes.c_int32,
                                      i32p]
        lib.rw_fnv1a64_rows.argtypes = [u8p, i64p, ctypes.c_int64,
                                        ctypes.c_int64, u64p]
        lib.rw_memcmp_i64.argtypes = [i64p, ctypes.c_int64, u8p]
        _lib = lib
        return _lib


def available() -> bool:
    return get_lib() is not None


def crc32_rows(data: np.ndarray) -> Optional[np.ndarray]:
    lib = get_lib()
    if lib is None:
        return None
    data = np.ascontiguousarray(data, dtype=np.uint8)
    n, k = data.shape
    out = np.empty(n, dtype=np.uint32)
    lib.rw_crc32_rows(data, n, k, out)
    return out


def vnodes_i64(vals: np.ndarray, vnode_count: int) -> Optional[np.ndarray]:
    lib = get_lib()
    if lib is None:
        return None
    vals = np.ascontiguousarray(vals, dtype=np.int64)
    out = np.empty(len(vals), dtype=np.int32)
    lib.rw_vnodes_i64(vals, len(vals), vnode_count, out)
    return out


def memcmp_i64(vals: np.ndarray) -> Optional[np.ndarray]:
    lib = get_lib()
    if lib is None:
        return None
    vals = np.ascontiguousarray(vals, dtype=np.int64)
    out = np.empty(len(vals) * 8, dtype=np.uint8)
    lib.rw_memcmp_i64(vals, len(vals), out)
    return out.reshape(len(vals), 8)
