"""Benchmark: Nexmark-q4-style streaming group-by aggregation throughput.

Workload: bid events (auction id zipf-ish, price), GROUP BY auction ->
count(*) / sum(price) / max(price), applied epoch-by-epoch with change-chunk
emission — the reference's `hash_agg.rs` hot path. Baseline = the exact host
(numpy/dict) path of this framework on the same rows, i.e. the "single-node
CPU" of BASELINE.json; value = device-path events/sec on the available chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""
import json
import time

import numpy as np


EPOCHS = 20
ROWS = 200_000          # events per epoch
KEYSPACE = 10_000       # live auctions


def gen_epochs(seed=42):
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(EPOCHS):
        # skewed auction popularity (zipf tail clipped into keyspace)
        keys = (rng.zipf(1.3, size=ROWS) % KEYSPACE).astype(np.int64)
        prices = rng.integers(1, 10_000, size=ROWS).astype(np.int64)
        out.append((keys, prices))
    return out


def run_device(epochs):
    from risingwave_tpu.device.agg_step import DeviceAggSpec, DeviceHashAgg

    spec = DeviceAggSpec.build(["count_star", "sum", "max"],
                               [np.int64, np.int64, np.int64])
    agg = DeviceHashAgg(spec, capacity=1 << 14)
    valid = np.ones(ROWS, dtype=bool)
    ones = np.ones(ROWS, dtype=np.int32)
    # warmup epoch (compile) on epoch-shaped data, fresh state afterwards
    k, p = epochs[0]
    agg.push_rows(k, ones, [(p, valid)] * 3)
    agg.flush_epoch()
    agg = DeviceHashAgg(spec, capacity=agg.state.capacity)
    t0 = time.perf_counter()
    for k, p in epochs:
        agg.push_rows(k, ones, [(p, valid)] * 3)
        agg.flush_epoch()
    dt = time.perf_counter() - t0
    return EPOCHS * ROWS / dt, agg


def run_host(epochs, limit_epochs=4):
    """Exact host path: AggGroup dict loop (HashAggExecutor's hot loop)."""
    from risingwave_tpu.expr.agg import AggCall, create_agg_state
    from risingwave_tpu.expr.expression import InputRef
    from risingwave_tpu.core import dtypes as T

    price = InputRef(1, T.INT64)
    calls = [AggCall("count"), AggCall("sum", price), AggCall("max", price)]
    groups = {}
    t0 = time.perf_counter()
    for k, p in epochs[:limit_epochs]:
        for i in range(len(k)):
            g = groups.get(k[i])
            if g is None:
                g = groups[k[i]] = [create_agg_state(c) for c in calls]
            g[0].apply(1, 1)
            g[1].apply(1, p[i])
            g[2].apply(1, p[i])
    dt = time.perf_counter() - t0
    return limit_epochs * ROWS / dt


def main():
    epochs = gen_epochs()
    device_eps, agg = run_device(epochs)
    host_eps = run_host(epochs)
    import jax
    result = {
        "metric": "nexmark_q4_agg_throughput",
        "value": round(device_eps),
        "unit": "events/s",
        "vs_baseline": round(device_eps / host_eps, 3),
        "detail": {
            "host_baseline_eps": round(host_eps),
            "epochs": EPOCHS, "rows_per_epoch": ROWS,
            "groups": int(np.asarray(agg.state.count)),
            "platform": jax.devices()[0].platform,
        },
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
