"""Benchmark: Nexmark-q4-style streaming group-by aggregation throughput.

Workload: bid events (hot-auction power-law, uniform prices), GROUP BY
auction -> count(*) / sum(price) / max(price), materialized into an
MV — the reference's `hash_agg.rs` + `materialize.rs` hot path, with the
datagen source on-device (the reference also benches against an in-process
datagen connector; see device/datagen.py).

The device path is the fused epoch program (device/pipeline.py): source,
exchange-free single-chip agg, and MV upsert all in HBM; the host touches
the device once per epoch to enqueue the step. Correctness: the final MV is
pulled and checked bit-for-bit against the exact host path on the same
event stream before the score is reported.

Baseline = the exact host (numpy/dict) path of this framework, i.e. the
"single-node CPU" reference of BASELINE.json.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""
import json
import time

import numpy as np

EPOCHS = 50
ROWS = 262_144          # events per epoch (pow2 keeps one compiled shape)
N_AUCTIONS = 10_000     # live auctions
HOST_EPOCHS = 4         # host baseline is timed on a subset (it's slow)


def build():
    from risingwave_tpu.device.agg_step import DeviceAggSpec
    from risingwave_tpu.device.pipeline import make_bid_pipeline

    spec = DeviceAggSpec.build(["count_star", "sum", "max"],
                               [np.int64, np.int64, np.int64])
    agg, mv = make_bid_pipeline(spec, 1 << 14)
    return spec, agg, mv


def run_device():
    import jax
    import jax.numpy as jnp
    from risingwave_tpu.device.pipeline import bid_agg_epoch

    spec, agg, mv = build()
    rng = jax.random.PRNGKey(42)
    zero = jnp.zeros((), jnp.int32)
    # warmup/compile
    a, m, r, mn = bid_agg_epoch(spec, ROWS, N_AUCTIONS, agg, mv, rng, zero)
    jax.block_until_ready(mn)
    # timed run from fresh state
    rng = jax.random.PRNGKey(42)
    mn = zero
    t0 = time.perf_counter()
    for _ in range(EPOCHS):
        agg, mv, rng, mn = bid_agg_epoch(spec, ROWS, N_AUCTIONS, agg, mv,
                                         rng, mn)
    jax.block_until_ready(mn)
    dt = time.perf_counter() - t0
    assert int(mn) <= agg.keys.shape[0], "state overflow: results invalid"
    return EPOCHS * ROWS / dt, (spec, agg, mv)


def host_events():
    """Replay the device generator's event stream on host (same seed)."""
    import jax
    from risingwave_tpu.device.datagen import gen_bids

    rng = jax.random.PRNGKey(42)
    out = []
    for _ in range(EPOCHS):
        auction, price, rng = gen_bids(rng, ROWS, N_AUCTIONS)
        out.append((np.asarray(auction), np.asarray(price)))
    return out


def run_host(epochs):
    """Exact host path: AggGroup dict loop (HashAggExecutor's hot loop).
    Throughput is timed over the first HOST_EPOCHS; the full replay then
    continues so the end state doubles as the parity oracle."""
    from risingwave_tpu.expr.agg import AggCall, create_agg_state
    from risingwave_tpu.expr.expression import InputRef
    from risingwave_tpu.core import dtypes as T

    price_ref = InputRef(1, T.INT64)
    calls = [AggCall("count"), AggCall("sum", price_ref),
             AggCall("max", price_ref)]
    groups = {}
    eps = None
    t0 = time.perf_counter()
    for n_done, (k, p) in enumerate(epochs):
        if n_done == HOST_EPOCHS:
            eps = HOST_EPOCHS * ROWS / (time.perf_counter() - t0)
        for i in range(len(k)):
            g = groups.get(k[i])
            if g is None:
                g = groups[k[i]] = [create_agg_state(c) for c in calls]
            g[0].apply(1, 1)
            g[1].apply(1, int(p[i]))
            g[2].apply(1, int(p[i]))
    if eps is None:
        eps = len(epochs) * ROWS / (time.perf_counter() - t0)
    return eps, groups


def verify(spec, mv, host_groups):
    """Final MV must equal the exact host path's outputs
    (barrier-boundary parity, the reference's core oracle)."""
    from risingwave_tpu.device.materialize import mv_rows

    keys, cols, nulls = mv_rows(mv, [c.acc_dtype for c in spec.calls])
    assert len(keys) == len(host_groups), (len(keys), len(host_groups))
    for i, key in enumerate(keys.tolist()):
        expect = [st.output() for st in host_groups[key]]
        got = (int(cols[0][i]), int(cols[1][i]), int(cols[2][i]))
        assert got == tuple(int(e) for e in expect), (key, got, expect)


def main():
    import jax

    device_eps, (spec, agg, mv) = run_device()
    events = host_events()
    host_eps, host_groups = run_host(events)
    verify(spec, mv, host_groups)
    result = {
        "metric": "nexmark_q4_agg_throughput",
        "value": round(device_eps),
        "unit": "events/s",
        "vs_baseline": round(device_eps / host_eps, 3),
        "detail": {
            "host_baseline_eps": round(host_eps),
            "epochs": EPOCHS, "rows_per_epoch": ROWS,
            "groups": int(np.asarray(agg.count)),
            "mv_verified": True,
            "platform": jax.devices()[0].platform,
        },
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
