"""Nexmark benchmarks: device (TPU) vs honest CPU baselines — timeout-proof.

Workloads (BASELINE.json targets; reference SQL from
`/root/reference/src/tests/simulation/src/nexmark/q{5,7,8}.sql`):

1. **q4 fused ceiling** — bid datagen + group-by agg + MV upsert as one
   jitted program per epoch, everything resident in HBM
   (`device/pipeline.py`). This is the architecture's headline number.
2. **q4 through SQL** — `CREATE SOURCE ... nexmark` + `CREATE MATERIALIZED
   VIEW` with the device dispatch seam on: host datagen, chunks through the
   executor stack, epochs on the TPU, recovery persistence on. Ingest-
   inclusive (host->device transfer is in the measured path).
3. **q5 / q7 / q8 through SQL** — the full reference queries (hop/tumble
   windows, self-joins) on the device path.

Baselines, stated per workload:
- `numpy_batch_eps`: a vectorized single-node CPU implementation of the
  same query (sort/reduceat groupby — the strongest simple CPU baseline;
  batch one-shot, no incremental maintenance, no durability).
- `host_sql_eps`: this framework's exact host executor path (device off),
  measured at a smaller scale (it is per-row Python).

Correctness: every SQL workload's final MV is compared against an
independently computed numpy oracle over the SAME event stream (bit-exact
multiset equality). The fused ceiling is verified against the numpy
groupby of its on-device-generated stream.

**Un-killable by construction** (BENCH_r03 was rc=124 with zero output —
never again): every stage runs in its own subprocess under a wall-clock
budget; a stage that overruns is SIGKILLed and retried at a smaller scale;
results accumulate in `bench_progress.json` after every stage; the final
aggregate prints even on SIGTERM/SIGINT. A transient device-tunnel stall
can cost one stage, not the whole run.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", "detail"}.
Flags: --smoke (tiny scales, <2 min); env RW_BENCH_BUDGET=secs total.
"""
import json
import multiprocessing as mp
import os
import signal
import sys
import time

import numpy as np

# q4 fused-ceiling scale
EPOCHS = 50
ROWS = 262_144
N_AUCTIONS = 10_000
# SQL-path scales (events are 1:3:46 person:auction:bid out of 50).
# Every retry stays at the SAME scale: a killed attempt's finished
# compiles persist in the cache, so same-scale retries converge, while a
# different scale would re-trace (the programs embed the event bound).
Q4_SQL_EVENTS = (8_388_608,)
# qx runs at the scale/capacity pairing that is measured to complete on
# the tunnel: larger capacities make each epoch's sorts so heavy that a
# single pass outruns any stage budget, and larger scales grow capacity
# mid-run (each growth replays every epoch since the last checkpoint).
# The honest note: qx device throughput is growth-replay-bound at this
# configuration; q4 is the device path's headline.
QX_SQL_EVENTS = (1_048_576,)
QX_CAPACITY = 1 << 16
HOST_SQL_EVENTS = 131_072                # host path is per-row Python
HOST_QX_EVENTS = 16_384                  # hop expansion is 5x rows on host
Q4_CHUNK = 16384                         # 1M-row fused epochs
CKPT_EVERY = 8                           # checkpoint every 8 barriers
# Fused jobs mirror their MV into the host state table every N checkpoints
# (readers are served from live device state either way; recovery needs
# only the committed event counter, which commits at every checkpoint).
# 64 keeps the Python-side mirror out of the steady-state loop.
MV_PERSIST_EVERY = 64

USEC = 1_000_000
PROGRESS_PATH = os.environ.get("RW_BENCH_PROGRESS", "bench_progress.json")

BID_SRC = ("CREATE SOURCE bid (auction BIGINT, bidder BIGINT, price BIGINT,"
           " channel VARCHAR, url VARCHAR, date_time TIMESTAMP,"
           " extra VARCHAR) WITH (connector='nexmark',"
           " nexmark.table='bid', nexmark.max.events='{n}',"
           " nexmark.chunk.size='{c}')")
AUCTION_SRC = ("CREATE SOURCE auction (id BIGINT, item_name VARCHAR,"
               " description VARCHAR, initial_bid BIGINT, reserve BIGINT,"
               " date_time TIMESTAMP, expires TIMESTAMP, seller BIGINT,"
               " category BIGINT, extra VARCHAR) WITH (connector='nexmark',"
               " nexmark.table='auction', nexmark.max.events='{n}',"
               " nexmark.chunk.size='{c}')")
PERSON_SRC = ("CREATE SOURCE person (id BIGINT, name VARCHAR,"
              " email_address VARCHAR, credit_card VARCHAR, city VARCHAR,"
              " state VARCHAR, date_time TIMESTAMP, extra VARCHAR)"
              " WITH (connector='nexmark', nexmark.table='person',"
              " nexmark.max.events='{n}', nexmark.chunk.size='{c}')")

Q4_MV = ("CREATE MATERIALIZED VIEW q4 AS SELECT auction, count(*) AS c,"
         " sum(price) AS s, max(price) AS m FROM bid GROUP BY auction")

Q5_MV = """CREATE MATERIALIZED VIEW nexmark_q5 AS
SELECT AuctionBids.auction, AuctionBids.num FROM (
    SELECT bid.auction, count(*) AS num, window_start AS starttime
    FROM HOP(bid, date_time, INTERVAL '2' SECOND, INTERVAL '10' SECOND)
    GROUP BY window_start, bid.auction
) AS AuctionBids
JOIN (
    SELECT max(CountBids.num) AS maxn, CountBids.starttime_c
    FROM (
        SELECT count(*) AS num, window_start AS starttime_c
        FROM HOP(bid, date_time, INTERVAL '2' SECOND, INTERVAL '10' SECOND)
        GROUP BY bid.auction, window_start
    ) AS CountBids
    GROUP BY CountBids.starttime_c
) AS MaxBids
ON AuctionBids.starttime = MaxBids.starttime_c
   AND AuctionBids.num >= MaxBids.maxn"""

Q7_MV = """CREATE MATERIALIZED VIEW nexmark_q7 AS
SELECT B.auction, B.price, B.bidder, B.date_time
FROM bid B
JOIN (
    SELECT MAX(price) AS maxprice, window_end as date_time
    FROM TUMBLE(bid, date_time, INTERVAL '10' SECOND)
    GROUP BY window_end
) B1 ON B.price = B1.maxprice
WHERE B.date_time BETWEEN B1.date_time - INTERVAL '10' SECOND
      AND B1.date_time"""

Q8_MV = """CREATE MATERIALIZED VIEW nexmark_q8 AS
SELECT P.id, P.name, P.starttime
FROM (
    SELECT id, name, window_start AS starttime, window_end AS endtime
    FROM TUMBLE(person, date_time, INTERVAL '10' SECOND)
    GROUP BY id, name, window_start, window_end
) P
JOIN (
    SELECT seller, window_start AS starttime, window_end AS endtime
    FROM TUMBLE(auction, date_time, INTERVAL '10' SECOND)
    GROUP BY seller, window_start, window_end
) A ON P.id = A.seller AND P.starttime = A.starttime
   AND P.endtime = A.endtime"""


# ---------------------------------------------------------------------------
# numpy batch baselines / oracles (vectorized single-node CPU)
# ---------------------------------------------------------------------------

def groupby_reduce(keys: np.ndarray, cols):
    """Sort-reduceat groupby: [(reduce, col), ...] -> (ukeys, results)."""
    order = np.argsort(keys, kind="stable")
    k = keys[order]
    bounds = np.flatnonzero(np.r_[True, k[1:] != k[:-1]])
    out = []
    for how, c in cols:
        if how == "count":
            out.append(np.diff(np.r_[bounds, len(k)]))
            continue
        c = c[order]
        if how == "sum":
            out.append(np.add.reduceat(c, bounds))
        elif how == "max":
            out.append(np.maximum.reduceat(c, bounds))
    return k[bounds], out


def numpy_q4(auction, price):
    keys, (c, s, m) = groupby_reduce(
        auction, [("count", None), ("sum", price), ("max", price)])
    return {int(k): (int(cc), int(ss), int(mm))
            for k, cc, ss, mm in zip(keys, c, s, m)}


def _hop_expand(ts, hop, size):
    """Per-row window_starts for HOP (latest aligned start <= ts, n back)."""
    n = size // hop
    first = (ts // hop) * hop
    offs = (np.arange(n) * hop)[None, :]
    return (first[:, None] - offs).reshape(-1)   # row-major: row i repeats n


def numpy_q5(auction, ts):
    hop, size = 2 * USEC, 10 * USEC
    n = size // hop
    ws = _hop_expand(ts, hop, size)
    au = np.repeat(auction, n)
    # normalize window starts to small hop ordinals so the composite
    # (window, auction) key fits in int64
    wn = (ws - ws.min()) // hop
    composite = wn * np.int64(1 << 32) + au      # auction ids << 2^32
    keys, (num,) = groupby_reduce(composite, [("count", None)])
    kws, kau = keys >> 32, keys & ((1 << 32) - 1)
    out = {}
    for w in np.unique(kws):
        sel = kws == w
        mx = num[sel].max()
        for a, c in zip(kau[sel][num[sel] >= mx], num[sel][num[sel] >= mx]):
            out[(int(w), int(a))] = int(c)
    # multiset of output rows (auction, num)
    rows = sorted((a, c) for (_w, a), c in out.items())
    return rows


def numpy_q7(auction, bidder, price, ts):
    size = 10 * USEC
    wend = (ts // size) * size + size
    keys, (mp_,) = groupby_reduce(wend, [("max", price)])
    rows = []
    for e, m in zip(keys, mp_):
        sel = (price == m) & (ts >= e - size) & (ts <= e)
        for i in np.flatnonzero(sel):
            rows.append((int(auction[i]), int(price[i]), int(bidder[i]),
                         int(ts[i])))
    return sorted(rows)


def numpy_q8(p_id, p_name, p_ts, a_seller, a_ts):
    size = 10 * USEC
    pw = (p_ts // size) * size
    aw = (a_ts // size) * size
    persons = {(int(i), str(nm), int(w)) for i, nm, w in zip(p_id, p_name, pw)}
    sellers = {(int(s), int(w)) for s, w in zip(a_seller, aw)}
    rows = [(i, nm, w) for (i, nm, w) in persons if (i, w) in sellers]
    return sorted(rows)


# ---------------------------------------------------------------------------
# stage bodies (each runs in a fresh subprocess under a wall budget)
# ---------------------------------------------------------------------------

def stage_fused(epochs, rows):
    """Workload 1: fused device ceiling + oracle verify + CPU baselines."""
    import jax
    import jax.numpy as jnp
    from risingwave_tpu.device.agg_step import DeviceAggSpec
    from risingwave_tpu.device.datagen import gen_bids
    from risingwave_tpu.device.materialize import mv_rows
    from risingwave_tpu.device.pipeline import bid_agg_epoch, make_bid_pipeline

    spec = DeviceAggSpec.build(["count_star", "sum", "max"],
                               [np.int64, np.int64, np.int64])
    agg, mv = make_bid_pipeline(spec, 1 << 14)
    rng = jax.random.PRNGKey(42)
    zero = jnp.zeros((), jnp.int32)
    t_c = time.perf_counter()
    a, m, r, mn = bid_agg_epoch(spec, rows, N_AUCTIONS, agg, mv, rng, zero)
    jax.block_until_ready(mn)      # compile
    compile_s = time.perf_counter() - t_c
    rng = jax.random.PRNGKey(42)
    mn = zero
    t0 = time.perf_counter()
    for _ in range(epochs):
        agg, mv, rng, mn = bid_agg_epoch(spec, rows, N_AUCTIONS, agg, mv,
                                         rng, mn)
    jax.block_until_ready(mn)
    dt = time.perf_counter() - t0
    assert int(mn) <= agg.keys.shape[0], "state overflow: results invalid"
    fused_eps = epochs * rows / dt

    # replay the on-device generator (device arrays accumulate, ONE
    # batched pull — remote links pay per transfer)
    rng = jax.random.PRNGKey(42)
    auctions, prices = [], []
    for _ in range(epochs):
        auction, price, rng = gen_bids(rng, rows, N_AUCTIONS)
        auctions.append(auction)
        prices.append(price)
    auctions, prices = jax.device_get((auctions, prices))
    auction = np.concatenate(auctions)
    price = np.concatenate(prices)

    t0 = time.perf_counter()
    oracle = numpy_q4(auction, price)
    numpy_q4_eps = len(auction) / (time.perf_counter() - t0)

    keys, cols, nulls = mv_rows(mv, [c.acc_dtype for c in spec.calls])
    assert len(keys) == len(oracle), (len(keys), len(oracle))
    for i, key in enumerate(keys.tolist()):
        got = (int(cols[0][i]), int(cols[1][i]), int(cols[2][i]))
        assert got == oracle[key], (key, got, oracle[key])

    dict_eps = host_dict_eps(auction, price)
    return {
        "platform": jax.devices()[0].platform,
        "q4_fused": {
            "device_eps": round(fused_eps),
            "compile_s": round(compile_s, 1),
            "numpy_batch_eps": round(numpy_q4_eps),
            "python_dict_eps": round(dict_eps),
            "events": epochs * rows, "groups": len(oracle),
            "mv_verified": True,
            "note": "datagen on device; numpy baseline is compute-only "
                    "sort-reduce over the identical replayed stream",
        },
    }


def host_dict_eps(auction, price, n=2 * ROWS):
    """The per-row Python loop (this framework's exact host agg hot loop) —
    kept for continuity with BENCH_r01; NOT the honest CPU baseline."""
    from risingwave_tpu.expr.agg import AggCall, create_agg_state
    from risingwave_tpu.expr.expression import InputRef
    from risingwave_tpu.core import dtypes as T
    n = min(n, len(auction))
    price_ref = InputRef(1, T.INT64)
    calls = [AggCall("count"), AggCall("sum", price_ref),
             AggCall("max", price_ref)]
    groups = {}
    t0 = time.perf_counter()
    for i in range(n):
        g = groups.get(auction[i])
        if g is None:
            g = groups[auction[i]] = [create_agg_state(c) for c in calls]
        g[0].apply(1, 1)
        g[1].apply(1, int(price[i]))
        g[2].apply(1, int(price[i]))
    return n / (time.perf_counter() - t0)


def nexmark_host_columns(n_events):
    """Replay the SQL connector's generator host-side (same seed/config)."""
    from risingwave_tpu.connectors.nexmark import NexmarkGenerator
    chunks = NexmarkGenerator().gen_range(0, n_events)
    out = {}
    for name, ch in chunks.items():
        if ch is not None:
            out[name] = [c.values for c in ch.columns]
    return out


def drive(db, n_events, chunk=8192):
    """Tick until the bounded sources drain; return wall seconds.
    Fused jobs dispatch asynchronously, so the clock stops only after
    their device work is DONE (sync), not merely enqueued."""
    ticks = n_events // (64 * chunk) + 3
    t0 = time.perf_counter()
    for _ in range(ticks):
        db.tick()
    for job in db._fused.values():
        job.sync()
    return time.perf_counter() - t0


def _device_cfg(on, capacity):
    if not on:
        return "off"
    from risingwave_tpu.config import DeviceConfig
    return DeviceConfig(capacity=capacity,
                        mv_persist_every=MV_PERSIST_EVERY)


def _cap_stats(db):
    """Per-fused-job capacity lifecycle: whether a (future) regression is
    capacity-churn or compute lives in these counters."""
    return {name: job.cap_report() for name, job in db._fused.items()}


def _profile_stats(db):
    """Per-fused-job epoch-timeline summary (utils/profile.py): phase
    totals + compile events + slowest epochs, so eps regressions are
    attributable to a PHASE (compile vs dispatch vs device vs commit)
    instead of a single end-to-end number."""
    return {name: job.profiler.summary() for name, job in db._fused.items()}


def _warmup_stats(db, warmup_s):
    """Warmup decomposition (ISSUE 6): how much of the wall was compile,
    how many compiles/retraces/growth-replays happened, and what the AOT
    service did (background compiles, cache hits, interpreted-bridge
    epochs) — the numbers that prove (or disprove) the warmup wall is
    gone, recorded into the BENCH json."""
    events = [e for job in db._fused.values()
              for e in job.profiler.summary()["compile_events"]]
    out = {
        "warmup_s": round(warmup_s, 1),
        "compile_s": round(sum(e.get("s") or 0 for e in events), 1),
        "compiles": sum(1 for e in events if e.get("kind") == "compile"),
        "retraces": sum(1 for e in events if e.get("kind") == "retrace"),
        "growth_replays": sum(j.growth_replays for j in db._fused.values()),
        "plan_hashes": {n: j.plan_hash for n, j in db._fused.items()},
    }
    try:
        from risingwave_tpu.device.compile_service import get_service
        out["aot"] = get_service().summary()
    except ImportError:
        pass
    return out


def _freshness_stats(db):
    """Per-MV source->commit freshness quantiles (utils/freshness.py):
    p50/p99/last over the run's commits — eps without freshness is half
    the perf story (a fast-but-stale engine fails the paper's
    serve-production-traffic bar), so the trajectory records both."""
    return db._freshness.summary()


def _q4_db(on, n_events, chunk=None):
    from risingwave_tpu.sql import Database
    chunk = chunk or (Q4_CHUNK if on else 8192)
    db = Database(device=_device_cfg(on, 1 << 20),
                  checkpoint_frequency=CKPT_EVERY if on else 1)
    db.run(BID_SRC.format(n=n_events, c=chunk))
    db.run(Q4_MV)
    dt = drive(db, n_events, chunk=chunk)
    rows = db.query("SELECT * FROM q4")
    return (n_events / dt, rows, _cap_stats(db), _profile_stats(db),
            _warmup_stats(db, dt), _freshness_stats(db))


def stage_q4_device(n_events):
    """Workload 2: q4 through SQL on the device path + oracle verify.

    Runs TWICE in-process: the first (warmup) pass compiles every epoch
    program — node steps hash structurally, so the second Database reuses
    the in-process jit cache and the measured pass is pure execution, the
    steady state a long-running stream job lives in. Compile cost is
    reported separately (`warmup_s`); cache entries also persist to disk
    (.jax_cache) so later processes skip the compile entirely."""
    t0 = time.perf_counter()
    _, _, _, _, warm, _ = _q4_db(True, n_events)
    warmup_s = time.perf_counter() - t0
    warm["warmup_s"] = round(warmup_s, 1)
    eps, rows, caps, prof, _, fresh = _q4_db(True, n_events)
    cols = nexmark_host_columns(n_events)["bid"]
    oracle = numpy_q4(cols[0].astype(np.int64), cols[2].astype(np.int64))
    assert len(rows) == len(oracle)
    for a, c, s, m in rows:
        assert oracle[int(a)] == (int(c), int(s), int(m)), a
    return {"q4_sql": {
        "device_eps": round(eps), "events": n_events, "groups": len(rows),
        "warmup_s": round(warmup_s, 1),
        "warmup": warm,
        "capacity": caps,
        "profile": prof,
        "freshness": fresh,
        "mv_verified": True,
        "note": "full SQL stack on device (fused epoch programs, "
                "checkpoint every 8 barriers); warmup_s = first full "
                "pass incl. compile/cache-load, device_eps = steady "
                "state (second pass, jit-cached); profile block = "
                "measured-pass epoch timeline (phase_s splits the wall "
                "into host-pack/dispatch/device-sync/commit; "
                "compile_events decompose any residual warmup); "
                "freshness block = per-MV source->commit p50/p99 "
                "seconds (rw_mv_freshness over the measured pass)",
    }}


def stage_q4_host(n_events):
    out = _q4_db(False, n_events)
    return {"q4_sql_host": {"host_sql_eps": round(out[0]),
                            "events": n_events,
                            "freshness": out[5]}}


QX_CHUNK = 2048   # smaller fused epochs: q5's hop(5x)+agg cascade compiles
                  # ~25x smaller programs than at 8192 (remote-compile RAM
                  # killed the big ones), and growth replays stay short


def _qx_db(on, n_events, capacity):
    """q5+q7+q8 in one database (sources shared, compile cache shared)."""
    from risingwave_tpu.sql import Database
    db = Database(device=_device_cfg(on, capacity),
                  checkpoint_frequency=CKPT_EVERY if on else 1)
    db.run(BID_SRC.format(n=n_events, c=QX_CHUNK))
    db.run(AUCTION_SRC.format(n=n_events, c=QX_CHUNK))
    db.run(PERSON_SRC.format(n=n_events, c=QX_CHUNK))
    db.run(Q5_MV)
    db.run(Q7_MV)
    db.run(Q8_MV)
    dt = drive(db, n_events, chunk=QX_CHUNK)
    out = {
        "q5": db.query("SELECT * FROM nexmark_q5"),
        "q7": db.query("SELECT * FROM nexmark_q7"),
        "q8": db.query("SELECT * FROM nexmark_q8"),
    }
    return (n_events / dt, out, _cap_stats(db), _profile_stats(db),
            _warmup_stats(db, dt), _freshness_stats(db))


def stage_qx_device(n_events):
    """Workload 3: q5/q7/q8 through SQL on the device path + oracles.
    SINGLE pass (unlike q4): qx throughput is growth-replay-bound, so a
    separate warmup pass would double a stage that already brushes its
    budget without changing the steady-state story; compiled programs
    persist in the cache across attempts either way."""
    t0 = time.perf_counter()
    eps, qx, caps, prof, warm, fresh = _qx_db(True, n_events, QX_CAPACITY)
    warmup_s = round(time.perf_counter() - t0, 1)
    warm["warmup_s"] = warmup_s
    c = nexmark_host_columns(n_events)
    bid, auc, per = c["bid"], c["auction"], c["person"]
    t0 = time.perf_counter()
    q5_oracle = numpy_q5(bid[0].astype(np.int64), bid[5].astype(np.int64))
    q5_np_eps = len(bid[0]) / (time.perf_counter() - t0)
    t0 = time.perf_counter()
    q7_oracle = numpy_q7(bid[0].astype(np.int64), bid[1].astype(np.int64),
                         bid[2].astype(np.int64), bid[5].astype(np.int64))
    q7_np_eps = len(bid[0]) / (time.perf_counter() - t0)
    t0 = time.perf_counter()
    q8_oracle = numpy_q8(per[0].astype(np.int64), per[1],
                         per[6].astype(np.int64),
                         auc[7].astype(np.int64), auc[5].astype(np.int64))
    q8_np_eps = (len(per[0]) + len(auc[0])) / (time.perf_counter() - t0)
    assert sorted((int(a), int(n)) for a, n in qx["q5"]) == q5_oracle
    assert sorted((int(a), int(p), int(b), int(t))
                  for a, p, b, t in qx["q7"]) == q7_oracle
    assert sorted((int(i), str(nm), int(w))
                  for i, nm, w in qx["q8"]) == q8_oracle
    return {"q5_q7_q8_sql": {
        "device_eps": round(eps), "events": n_events,
        "warmup_s": round(warmup_s, 1),
        "warmup": warm,
        "capacity": caps,
        "profile": prof,
        "freshness": fresh,
        "numpy_batch_eps": {"q5": round(q5_np_eps), "q7": round(q7_np_eps),
                            "q8": round(q8_np_eps)},
        "rows": {k: len(v) for k, v in qx.items()},
        "mv_verified": True,
        "note": "three reference-SQL MVs concurrently over shared "
                "sources; device_eps counts each source event once; "
                "single pass (warmup_s = its wall incl. cache loads); "
                "capacity block = predictive-growth lifecycle counters "
                "(replays should be <=2/job; more means the predictor "
                "regressed); profile block attributes the wall to "
                "compile vs dispatch vs device-sync vs commit per job; "
                "oracles computed independently in numpy",
    }}


def stage_qx_host(n_events):
    out = _qx_db(False, n_events, QX_CAPACITY)
    return {"q5_q7_q8_sql_host": {"host_sql_eps": round(out[0]),
                                  "events": n_events,
                                  "freshness": out[5]}}


# ---------------------------------------------------------------------------
# mesh-shard sweep (ISSUE 7): the same fused SQL on 1 vs 8 chips
# ---------------------------------------------------------------------------

SHARDS_SWEEP = (1, 8)
SHARDS_Q4_EVENTS = 2_097_152      # a quarter of the headline scale: the
                                  # sweep runs FOUR q4 passes (warm +
                                  # measured per shard count)


def _shards_pass(shards, mv_sqls, mv_names, srcs, n_events, chunk,
                 capacity):
    """One sweep pass at the given mesh_shards: eps, exchange-stage wall,
    the shard count the planner actually achieved (falls back to 1 when
    the platform lacks devices), and sorted MV rows for cross-verify."""
    from risingwave_tpu.config import DeviceConfig
    from risingwave_tpu.sql import Database
    db = Database(device=DeviceConfig(capacity=capacity,
                                      mesh_shards=shards,
                                      mv_persist_every=MV_PERSIST_EVERY),
                  checkpoint_frequency=CKPT_EVERY)
    for s in srcs:
        db.run(s.format(n=n_events, c=chunk))
    for mv in mv_sqls:
        db.run(mv)
    dt = drive(db, n_events, chunk=chunk)
    jobs = db._fused
    eff = max([j.mesh_shards for j in jobs.values()] or [1])
    exch = sum(j.profiler.totals.get("exchange", 0.0)
               for j in jobs.values())
    rows = {m: sorted(db.query(f"SELECT * FROM {m}")) for m in mv_names}
    return n_events / dt, exch, eff, rows, _cap_stats(db)


def _shards_sweep(key, mv_sqls, mv_names, srcs, n_events, chunk, capacity,
                  warm_pass):
    out = {"events": n_events, "note":
           "same fused SQL, DeviceConfig.mesh_shards swept; device_eps = "
           "steady state" + (" (second pass, jit-cached)" if warm_pass
                             else " (single pass incl. warmup)") +
           "; exchange_s = wall of the in-program all_to_all dispatch "
           "stage; MV rows cross-verified bit-identical between shard "
           "counts"}
    rows_ref = None
    for shards in SHARDS_SWEEP:
        if warm_pass:
            _shards_pass(shards, mv_sqls, mv_names, srcs, n_events, chunk,
                         capacity)
        eps, exch, eff, rows, caps = _shards_pass(
            shards, mv_sqls, mv_names, srcs, n_events, chunk, capacity)
        if rows_ref is None:
            rows_ref = rows
        else:
            assert rows == rows_ref, "sharded MV diverged from 1-shard"
        out[str(shards)] = {"device_eps": round(eps),
                            "exchange_s": round(exch, 2),
                            "effective_shards": eff,
                            "capacity": caps}
        out["mv_verified"] = rows_ref is not None
    lo, hi = str(SHARDS_SWEEP[0]), str(SHARDS_SWEEP[-1])
    if out.get(lo, {}).get("device_eps"):
        out[f"speedup_{hi}v{lo}"] = round(
            out[hi]["device_eps"] / out[lo]["device_eps"], 3)
    return {key: out}


def stage_shards_q4(n_events):
    return _shards_sweep("shards_sweep_q4", [Q4_MV], ["q4"], [BID_SRC],
                        n_events, Q4_CHUNK, 1 << 19, warm_pass=True)


def stage_shards_qx(n_events):
    return _shards_sweep(
        "shards_sweep_q5_q7_q8", [Q5_MV, Q7_MV, Q8_MV],
        ["nexmark_q5", "nexmark_q7", "nexmark_q8"],
        [BID_SRC, AUCTION_SRC, PERSON_SRC],
        n_events, QX_CHUNK, QX_CAPACITY, warm_pass=False)


# ---------------------------------------------------------------------------
# Zipfian skew sweep (ISSUE 13): power-law keys, defenses off vs on
# ---------------------------------------------------------------------------


def _skew_src(src_sql, s):
    return src_sql.replace("connector='nexmark'",
                           f"connector='nexmark', "
                           f"nexmark.key.dist='zipf:{s}'")


def _skew_pass(shards, defenses, mv_sqls, mv_names, srcs, n_events, chunk,
               capacity, s, threshold):
    """One Zipfian pass: eps, achieved shards, per-job skew report
    (raw key skew_ratio, per-shard load ratio under the current routing
    bounds, adopted policy counters), sorted MV rows for cross-verify."""
    import time as _t
    os.environ["RW_SKEW_STATS"] = "1"   # the defenses need the evidence
    from risingwave_tpu.config import DeviceConfig
    from risingwave_tpu.sql import Database
    db = Database(device=DeviceConfig(capacity=capacity,
                                      mesh_shards=shards,
                                      mv_persist_every=MV_PERSIST_EVERY,
                                      agg_precombine=defenses,
                                      hot_key_rep=defenses,
                                      vnode_rebalance=defenses,
                                      rebalance_threshold=threshold),
                  checkpoint_frequency=CKPT_EVERY)
    for src in srcs:
        db.run(_skew_src(src.format(n=n_events, c=chunk), s))
    for mv in mv_sqls:
        db.run(mv)
    dt = drive(db, n_events, chunk=chunk)
    jobs = db._fused
    # let a staged routing policy (background pre-warm) adopt
    for j in jobs.values():
        for _ in range(100):
            if j._pending_policy is None:
                break
            _t.sleep(0.1)
            db.tick()
    db.tick()
    eff = max([j.mesh_shards for j in jobs.values()] or [1])
    skew = {}
    for name, j in jobs.items():
        rep = j.skew_report()
        ratios = [r[6] for r in rep if r[2] == "skew_ratio"]
        shard_r = [r[6] for r in rep if r[2] == "shard_skew"]
        # max per-epoch ICI send-bucket fill: pre-combine's wire win —
        # one combined row per key per (shard, epoch) instead of every
        # raw row — shows up directly here
        exch_hw = max([r[5] for r in j.node_report() if r[2] == "exch"]
                      or [0])
        skew[name] = {
            "skew_ratio": round(max(ratios or [0.0]), 3),
            "shard_skew_ratio": round(max(shard_r or [0.0]), 3),
            "rebalances": j.rebalances,
            "hot_keys": sum(len(nd.hot_keys)
                            for nd in j.program.nodes),
            "exch_rows_high_water": int(exch_hw),
        }
    rows = {m: sorted(db.query(f"SELECT * FROM {m}")) for m in mv_names}
    return n_events / dt, eff, skew, rows


def _skew_sweep(key, mv_sqls, mv_names, srcs, n_events, chunk, capacity,
                s=1.5, threshold=1.5):
    """The same Zipfian SQL at 1 vs 8 shards, skew defenses off vs on:
    the number that matters is speedup_8v1 per arm — a power-law key
    distribution collapses it toward 1x without the defenses; the
    defenses (pre-combine, hot-key replication, vnode rebalancing) are
    what keep '8 chips' meaning '8x'. MVs are cross-verified
    bit-identical across every arm (the defenses are pure routing)."""
    out = {"events": n_events, "zipf_s": s,
           "note": "nexmark.key.dist=zipf:%s; defenses_off/on x 1/8 "
                   "shards; skew_ratio = raw key skew (max/mean vnode "
                   "bucket, bounds-independent), shard_skew_ratio = "
                   "per-shard load under the CURRENT routing bounds "
                   "(what rebalancing reduces); MV rows cross-verified "
                   "bit-identical across all four arms" % s}
    rows_ref = None
    for defenses in (False, True):
        sub = {}
        for shards in SHARDS_SWEEP:
            eps, eff, skew, rows = _skew_pass(
                shards, defenses, mv_sqls, mv_names, srcs, n_events,
                chunk, capacity, s, threshold)
            if rows_ref is None:
                rows_ref = rows
            else:
                assert rows == rows_ref, "skew-defense MV diverged"
            sub[str(shards)] = {"device_eps": round(eps),
                                "effective_shards": eff,
                                "skew": skew}
        lo, hi = str(SHARDS_SWEEP[0]), str(SHARDS_SWEEP[-1])
        if sub.get(lo, {}).get("device_eps"):
            sub["speedup_8v1"] = round(
                sub[hi]["device_eps"] / sub[lo]["device_eps"], 3)
        out["defenses_on" if defenses else "defenses_off"] = sub
    out["mv_verified"] = rows_ref is not None
    return {key: out}


def stage_skew_q4(n_events):
    return _skew_sweep("skew_q4", [Q4_MV], ["q4"], [BID_SRC], n_events,
                       Q4_CHUNK, 1 << 19)


def stage_skew_qx(n_events):
    # q5: the join-bearing reference query — exercises hot-key
    # replication and the pre-combined hop+agg chain together
    return _skew_sweep("skew_qx", [Q5_MV], ["nexmark_q5"], [BID_SRC],
                       n_events, QX_CHUNK, QX_CAPACITY)


def stage_chaos_mttr(n_events):
    """Workload: recovery MTTR under chaos (fault-tolerance v3).

    Two halves, both deterministic:
    * kill a SUPERVISED worker mid-run (SIGKILL) — time until the
      FragmentSupervisor's in-place respawn converges, then measure the
      post-recovery throughput of fresh traffic;
    * fire a fused device-path failpoint (`fused.dispatch`) mid-run —
      time the in-place fused recovery (state rebuild + crash-window
      re-dispatch on AOT-cached executables), then the post-recovery
      steady-state eps."""
    import time as _t
    from risingwave_tpu.config import ROBUSTNESS
    from risingwave_tpu.sql import Database
    from risingwave_tpu.sql.database import _walk_executors
    from risingwave_tpu.utils import failpoint as fp
    ROBUSTNESS.respawn_backoff_s = 0.001
    out = {}
    # ---- half 1: supervised worker kill -> in-place respawn ----------
    db = Database()
    db.run("CREATE TABLE t (k BIGINT, v BIGINT)")
    db.run("SET streaming_parallelism = 2")
    db.run("SET streaming_placement = 'process'")
    db.run("SET streaming_supervision TO true")
    db.run("CREATE MATERIALIZED VIEW ra AS SELECT k, count(*) AS c,"
           " sum(v) AS s FROM t GROUP BY k")
    n_seed = 2000
    vals = ", ".join(f"({k % 97}, {k})" for k in range(n_seed))
    db.run(f"INSERT INTO t VALUES {vals}")
    for _ in range(4):
        db.tick()
    rset = None
    for e in _walk_executors(db.catalog.get("ra").runtime["shared"]
                             .upstream):
        rset = getattr(e, "_remote", None) or rset
    t0 = _t.perf_counter()
    rset.workers[0].proc.kill()
    while rset.supervisor.respawns == 0:
        db.tick()
    respawn_s = _t.perf_counter() - t0
    # post-recovery eps over fresh traffic
    vals = ", ".join(f"({k % 97}, {k})" for k in range(n_seed))
    t0 = _t.perf_counter()
    db.run(f"INSERT INTO t VALUES {vals}")
    post_dt = _t.perf_counter() - t0
    assert len(db.query("SELECT * FROM ra")) == 97
    rset.shutdown()
    out["worker_kill"] = {
        "respawn_mttr_s": round(respawn_s, 3),
        "post_recovery_eps": round(n_seed / post_dt),
        "respawns": rset.supervisor.respawns,
        "escalated": rset.supervisor._escalated is not None,
    }
    # ---- half 2: fused device-path fault -> in-place recovery --------
    # chunk sized for ~8 epochs: the fault must land MID-RUN, with real
    # committed history to rebuild and a real crash window to re-dispatch
    chunk = max(64, n_events // (64 * 8))
    db2 = Database(device=_device_cfg(True, 1 << 18))
    db2.run(BID_SRC.format(n=n_events, c=chunk))
    db2.run(Q4_MV)
    job = db2.catalog.get("q4").runtime["fused_job"]
    epochs = max(1, n_events // job.program.epoch_events)
    warm = max(1, epochs // 4)
    for _ in range(warm):
        db2.tick()
    fp.arm("fused.dispatch", 1.0, 0, 1)
    t0 = _t.perf_counter()
    db2.tick()                     # fires + recovers inside this barrier
    job.sync()
    mttr = _t.perf_counter() - t0
    fp.reset()
    assert job.recoveries == 1
    t0 = _t.perf_counter()
    for _ in range(epochs - warm + 2):
        db2.tick()
    job.sync()
    post_dt = max(1e-9, _t.perf_counter() - t0)
    post_events = job.counter - (warm + 1) * job.program.epoch_events
    out["fused_fault"] = {
        "recovery_mttr_s": round(mttr, 3),
        "recoveries": job.recoveries,
        "post_recovery_eps": round(max(0, post_events) / post_dt),
        "events": n_events,
        "zero_ddl_replay": True,
    }
    out["note"] = ("worker_kill: SIGKILL a supervised stateful-agg "
                   "worker, MTTR = kill->in-place respawn converged; "
                   "fused_fault: fused.dispatch failpoint fires once "
                   "mid-run, MTTR = barrier wall incl. state rebuild + "
                   "crash-window re-dispatch (AOT-cached, zero compiles)")
    return {"chaos_mttr": out}


INGEST_CHUNK = 4096    # epoch = 262144 events: the staged pipeline needs
                       # MANY windows per run for the double buffer to
                       # have anything to hide (one giant window = one
                       # synchronous stage, no overlap to measure)


def _ingest_arm(n_events, shards, warm_pass):
    """One host-ingest q4 arm: eps + freshness + the pack/h2d/dispatch
    split that proves (or disproves) the double-buffer overlap."""
    from risingwave_tpu.config import DeviceConfig
    from risingwave_tpu.sql import Database

    def one_pass():
        db = Database(device=DeviceConfig(capacity=1 << 18,
                                          host_ingest=True,
                                          mesh_shards=shards,
                                          mv_persist_every=MV_PERSIST_EVERY),
                      checkpoint_frequency=CKPT_EVERY)
        db.run(BID_SRC.format(n=n_events, c=INGEST_CHUNK))
        db.run(Q4_MV)
        dt = drive(db, n_events, chunk=INGEST_CHUNK)
        return db, dt

    if warm_pass:
        one_pass()
    db, dt = one_pass()
    job = db._fused["q4"]
    rows = db.query("SELECT * FROM q4")
    st = job.ingest.stats()
    ph = job.profiler.totals
    disp = ph.get("dispatch", 0.0)
    return {
        "device_eps": round(n_events / dt),
        "events": n_events,
        "effective_shards": job.mesh_shards,
        "groups": len(rows),
        "ingest": {k: (round(v, 4) if isinstance(v, float) else v)
                   for k, v in st.items()},
        # the overlap evidence: total H2D wall over total dispatch wall
        # (< 1.0 means the transfer hid under dispatch), plus the
        # dispatch-thread residual phases (pack/h2d ~ 0 when the double
        # buffer is warm)
        "h2d_over_dispatch": round(st["h2d_s"] / disp, 4) if disp else None,
        "prefetched_frac": round(
            st["prefetched"] / max(1, st["windows"]), 3),
        "phase_s": {k: round(v, 4) for k, v in ph.items()},
        "freshness": _freshness_stats(db),
    }, rows


def _copy_firehose(n_rows, producers):
    """COPY FROM STDIN firehose: `producers` concurrent pgwire
    connections stream text COPY batches into one table with a counting
    MV while the coordinator ticks — rows/s through the admission gate,
    with rw_mv_freshness as the SLO check."""
    import socket
    import struct
    import threading
    import time as _t
    from risingwave_tpu.pgwire import PgServer
    from risingwave_tpu.sql import Database
    db = Database()
    db.run("CREATE TABLE fh (v BIGINT)")
    db.run("CREATE MATERIALIZED VIEW fh_mv AS SELECT count(*) AS n,"
           " sum(v) AS sv FROM fh")
    srv = PgServer(db).start()
    per = n_rows // producers
    batch = 4096

    def produce(k):
        s = socket.create_connection((srv.host, srv.port), timeout=30)
        buf = b""

        def recv(n):
            nonlocal buf
            while len(buf) < n:
                got = s.recv(65536)
                if not got:
                    raise ConnectionError
                buf += got
            out, buf2 = buf[:n], buf[n:]
            buf = buf2
            return out

        def until(stop):
            while True:
                t = recv(1)
                (ln,) = struct.unpack(">I", recv(4))
                recv(ln - 4)
                if t == stop:
                    return

        body = struct.pack(">I", 196608) + b"user\0bench\0\0"
        s.sendall(struct.pack(">I", len(body) + 4) + body)
        until(b"Z")

        def send(tag, p=b""):
            s.sendall(tag + struct.pack(">I", len(p) + 4) + p)

        send(b"Q", b"COPY fh FROM STDIN\0")
        t = recv(1)
        (ln,) = struct.unpack(">I", recv(4))
        recv(ln - 4)
        assert t == b"G", t
        lo = k * per
        for off in range(0, per, batch):
            n = min(batch, per - off)
            data = b"".join(b"%d\n" % (lo + off + i) for i in range(n))
            send(b"d", data)
        send(b"c")
        until(b"Z")
        s.close()

    threads = [threading.Thread(target=produce, args=(k,), daemon=True)
               for k in range(producers)]
    t0 = _t.perf_counter()
    for t in threads:
        t.start()
    alive = True
    while alive:
        # the handler threads serialize on the server's session lock —
        # the tick loop must too (Database has no internal lock; an
        # unlocked tick would interleave barrier processing with
        # copy_rows' bucket read-modify-write)
        with srv.lock:
            db.tick()
        alive = any(t.is_alive() for t in threads)
    for t in threads:
        t.join()
    # drain: everything pushed must reach the MV
    for _ in range(200):
        with srv.lock:
            db.tick()
            got = db.query("SELECT n FROM fh_mv")
        if got and int(got[0][0] or 0) >= producers * per:
            break
    dt = max(1e-9, _t.perf_counter() - t0)
    srv.stop()
    total = producers * per
    n_mv, sv = db.query("SELECT n, sv FROM fh_mv")[0]
    bucket = db._overload.bucket("fh")
    assert int(n_mv) == total, (n_mv, total)
    assert int(sv) == total * (total - 1) // 2, "firehose sum mismatch"
    return {
        "producers": producers,
        "rows": total,
        "copy_eps": round(total / dt),
        "admitted_rows": bucket.admitted_rows,
        "lag_batches": bucket.lag,
        "mv_verified": True,
        "freshness": db._freshness.summary(),
    }


def stage_ingest(n_events, firehose_rows=200_000, producers=8):
    """Workload: line-rate host ingest (ISSUE 15) — q4 with HOST ingest
    in the measured path, before (host executor DAG, the BENCH_r05
    671k-eps architecture) vs after (zero-copy staged feed into the
    fused program), at 1 and 8 shards, plus the COPY firehose arm.
    Freshness p50/p99 rides every arm: ingest rate is only real if
    freshness holds under it."""
    out = {}
    # before: the old measured path — host chunks through the executor
    # stack (per-row Python; measured at its own smaller scale)
    before_n = min(n_events, HOST_SQL_EVENTS)
    eps_before, _rows, _c, _p, _w, fresh = _q4_db(False, before_n)
    out["before_host_executor"] = {
        "host_sql_eps": round(eps_before), "events": before_n,
        "freshness": fresh,
    }
    arm1, rows1 = _ingest_arm(n_events, 1, warm_pass=True)
    out["host_ingest_1shard"] = arm1
    # oracle verify (the host feed must change nothing)
    cols = nexmark_host_columns(n_events)["bid"]
    oracle = numpy_q4(cols[0].astype(np.int64), cols[2].astype(np.int64))
    assert len(rows1) == len(oracle)
    for a, c, s, m in rows1:
        assert oracle[int(a)] == (int(c), int(s), int(m)), a
    arm1["mv_verified"] = True
    # 8-shard arm at a quarter scale: on a CPU-only host the "8 chips"
    # are virtual devices over one CPU, so this arm proves per-shard
    # placement + bit-identity, not speedup (the 1-vs-8 speedup story
    # lives in shards_q4 on real chips)
    arm8, rows8 = _ingest_arm(max(64 * INGEST_CHUNK, n_events // 4), 8,
                              warm_pass=False)
    arm1q, rows1q = _ingest_arm(max(64 * INGEST_CHUNK, n_events // 4), 1,
                                warm_pass=False)
    assert rows8 == rows1q, "8-shard host-ingest MV diverged"
    arm8["mv_verified"] = True
    out["host_ingest_8shard"] = arm8
    out["ingest_speedup_vs_host_executor"] = round(
        arm1["device_eps"] / max(1, eps_before), 2)
    out["firehose_copy"] = _copy_firehose(firehose_rows, producers)
    out["note"] = (
        "before = host executor DAG with ingest in the measured path "
        "(the BENCH_r05 671k-eps q4_sql architecture, at its own "
        "scale); after = zero-copy staged host feed into the fused "
        "program (device/ingest.py), same host. h2d_over_dispatch < 1 "
        "= the double-buffered transfer hid under dispatch; "
        "prefetched_frac = windows staged off the dispatch thread. "
        "firehose_copy = concurrent pgwire COPY producers through the "
        "admission gate, MV count+sum verified exactly. On a CPU-only "
        "host the 'device' compute shares the same CPU as the ingest "
        "pipeline, so the before/after ratio understates what an "
        "accelerator sees (there, staging+H2D hide under real device "
        "dispatch and the executor-DAG baseline gains nothing).")
    return {"ingest": out}


def stage_overload(n_rows):
    """Workload: overload survival (ISSUE 14) — the same bounded datagen
    MV + file sink at 1x/2x/10x offered load (rows per poll scaled).
    Records freshness p50/p99 + eps + admission lag + shed counts per
    arm. The 10x arm additionally stalls the sink for a deterministic
    window (`overload.slow_sink`, RW_LOAD_SHED on) so the record shows
    the full ladder: escalation transitions, audited sheds, and the
    recovery back to `normal` once the stall clears."""
    from risingwave_tpu.config import ROBUSTNESS
    from risingwave_tpu.utils import failpoint as fp
    from risingwave_tpu.utils.overload import PRESSURE
    saved = {k: getattr(ROBUSTNESS, k)
             for k in ("overload_hold_s", "overload_window_s",
                       "load_shed")}
    ROBUSTNESS.overload_hold_s = 0.05
    ROBUSTNESS.overload_window_s = 2.0
    out = {}
    try:
        _overload_arms(n_rows, out)
    finally:
        fp.reset()
        PRESSURE.reset()
        for k, v in saved.items():
            setattr(ROBUSTNESS, k, v)
    out["note"] = ("offered load scaled by rows.per.poll; 10x arm runs "
                   "with RW_LOAD_SHED=true + a deterministic "
                   "overload.slow_sink stall window — shed_rows are "
                   "audited in rw_shed_log (accounted = MV rows + shed "
                   "rows cover every offered row); freshness blocks = "
                   "rw_mv_freshness p50/p99 per arm (the eps-vs-"
                   "freshness trade the cadence stretch makes)")
    return {"overload": out}


def _overload_arms(n_rows, out):
    import tempfile
    import time as _t
    from risingwave_tpu.config import ROBUSTNESS
    from risingwave_tpu.sql import Database
    from risingwave_tpu.utils import failpoint as fp
    from risingwave_tpu.utils.overload import PRESSURE
    for mult in (1, 2, 10):
        stress = mult == 10
        ROBUSTNESS.load_shed = stress
        PRESSURE.reset()
        fp.reset()
        db = Database()
        db.run("CREATE SOURCE s (v BIGINT) WITH (connector='datagen',"
               f" rows.per.poll='{64 * mult}',"
               f" datagen.max.rows='{n_rows}')")
        db.run("CREATE MATERIALIZED VIEW mo AS SELECT count(*) AS n,"
               " sum(v) AS sv FROM s")
        sink_path = os.path.join(tempfile.mkdtemp(prefix="rw_ovl_"),
                                 "out.jsonl")
        db.run(f"CREATE SINK so FROM mo WITH (connector='fs',"
               f" fs.path='{sink_path}', format='jsonl')")
        if stress:
            # stall the first ~30 delivery attempts: the ladder must
            # escalate under the stall and recover after it clears
            fp.arm("overload.slow_sink", 1.0, 0, 30)
        worst = 0
        t0 = _t.perf_counter()
        done = 0
        for tick in range(4000):
            db.tick()
            for c in db._overload.controllers.values():
                worst = max(worst, c.rung)
            if tick % 16 == 15:
                rows = db.query("SELECT n FROM mo")
                done = int(rows[0][0] or 0) if rows else 0
                bucket = db._overload.buckets["s"]
                if done + bucket.shed_rows >= n_rows and all(
                        c.rung == 0
                        for c in db._overload.controllers.values()):
                    break
        dt = max(1e-9, _t.perf_counter() - t0)
        bucket = db._overload.buckets["s"]
        shed_entries = db._shed_log.entries()
        transitions = sum(len(c.transitions)
                          for c in db._overload.controllers.values())
        fp.reset()
        out[f"x{mult}"] = {
            "offered_rows": n_rows,
            "rows_per_poll": 64 * mult,
            "admitted_rows": bucket.admitted_rows,
            "deferred_polls": bucket.deferred,
            "lag_polls": bucket.lag,
            "shed_rows": bucket.shed_rows,
            "shed_windows": len(shed_entries),
            "eps": round(done / dt),
            "wall_s": round(dt, 2),
            "ladder_transitions": transitions,
            "worst_state": ["normal", "throttled", "degraded",
                            "shedding"][worst],
            "recovered_to_normal": all(
                c.rung == 0 for c in db._overload.controllers.values()),
            "freshness": db._freshness.summary(),
            "accounted": done + bucket.shed_rows == n_rows,
        }


def stage_tiering(n_events):
    """Workload: tiered state beyond HBM (ISSUE 16) — a q8-style
    unbounded-key GROUP BY (nexmark auction ids keep arriving for the
    life of the stream) run at a device capacity clamped BELOW the
    final distinct-key count, tiering off vs on at the SAME clamp.

    The untiered arm has to grow (capacity-doubling replays); the
    tiered arm demotes cold groups to host memory off the commit phase
    and touch-promotes them back when their keys reappear (Xor8
    negative caches keep absent-key windows off the promotion path).
    Records eps for both arms, the demotion/promotion counters, the
    negative-cache hit rate, the HBM budget-utilization high-water and
    freshness p50/p99 — and asserts the MVs bit-identical."""
    import time as _t
    from risingwave_tpu.config import DeviceConfig
    from risingwave_tpu.sql import Database
    from risingwave_tpu.utils.metrics import REGISTRY
    # clamp ~half the run's distinct auctions (974 per 16384 bids)
    cap = 1 << max(10, int(0.03 * n_events).bit_length() - 1)
    chunk = max(512, n_events // (64 * 24))
    os.environ.setdefault("RW_TIER_HIGH_WATER", "0.35")
    os.environ.setdefault("RW_TIER_LOW_WATER", "0.15")
    # both demotion-inert-by-design shapes must stay out of this stage
    # (documented residuals): min/max fold through a minput multiset,
    # and a pre-combined agg's input lineage is the combiner, not an
    # ingest source — so q4 minus max(price), pre-combine off BOTH arms
    os.environ["RW_AGG_PRECOMBINE"] = "0"
    mv = ("CREATE MATERIALIZED VIEW qt AS SELECT auction,"
          " count(*) AS c, sum(price) AS s FROM bid GROUP BY auction")
    out = {"events": n_events, "capacity": cap}
    rows_by_arm = {}
    for arm, tier in (("untiered", "0"), ("tiered", "1")):
        os.environ["RW_STATE_TIERING"] = tier
        os.environ["RW_HOST_INGEST"] = tier
        db = Database(device=DeviceConfig(capacity=cap,
                                          hbm_budget_mb=256,
                                          mv_persist_every=
                                          MV_PERSIST_EVERY))
        db.run(BID_SRC.format(n=n_events, c=chunk))
        db.run(mv)
        dt = drive(db, n_events, chunk=chunk)
        db.tick()                       # harvest the last demote pull
        job = db._fused["qt"]
        rows_by_arm[arm] = db.query("SELECT * FROM qt")
        rec = {
            "eps": round(n_events / dt),
            "groups": len(rows_by_arm[arm]),
            "growth_replays": job.growth_replays,
            "capacity_final": job.cap_report(),
            "freshness": _freshness_stats(db),
        }
        if tier == "1":
            tm = job.tiering
            probes = tm.counters["filter_probes"]
            rec["tier"] = {
                "demotions": tm.counters["demotions"],
                "promotions": tm.counters["promotions"],
                "demote_events": tm.counters["demote_events"],
                "cold_rows": sum(len(s) for s in tm.stores.values()),
                "filter_probes": probes,
                "filter_hit_rate": round(
                    tm.counters["filter_hits"] / probes, 4)
                if probes else None,
                "filter_fallbacks": tm.counters["filter_fallbacks"],
            }
            util = [float(line.rsplit(" ", 1)[1])
                    for line in REGISTRY.expose().splitlines()
                    if line.startswith("rw_hbm_budget_utilization")]
            rec["hbm_budget_utilization_high_water"] = (
                round(max(util), 6) if util else None)
            rec["profile_tier_phase_s"] = {
                "demote_d2h": round(
                    job.profiler.totals.get("demote_d2h", 0.0), 3),
                "promote_h2d": round(
                    job.profiler.totals.get("promote_h2d", 0.0), 3),
            }
        out[arm] = rec
    assert rows_by_arm["tiered"] == rows_by_arm["untiered"], \
        "tiered MV must be bit-identical to untiered"
    out["mv_bit_identical"] = True
    out["note"] = ("same capacity clamp both arms; the untiered arm "
                   "pays growth replays, the tiered arm demotes cold "
                   "groups to host ColdStores (commit-phase async D2H) "
                   "and touch-promotes on reappearance — Xor8 negative "
                   "caches filter promotion probes; MVs asserted "
                   "bit-identical incl. row order")
    return {"tiering": out}


def stage_serving(n_events, window_s=1.0):
    """Workload: the read path at scale (ISSUE 19) — a fused q4 MV
    served to 1/8/64 concurrent readers, read cache off vs on, staleness
    bound 0 vs 2 epochs. Records read QPS, read p50/p99, device pulls
    per 1k SELECTs, and write-eps interference (ingest driven alone vs
    under a 64-reader cached storm). Asserts the acceptance invariants:
    a 64-reader cached storm between two checkpoints costs <= 1 device
    pull, and cached read QPS >= 5x uncached."""
    import threading as _th
    import time as _t
    from risingwave_tpu.config import DeviceConfig, ROBUSTNESS
    from risingwave_tpu.device import shard_exec
    from risingwave_tpu.sql import Database

    chunk = max(2048, n_events // (64 * 8))
    db = Database(device=DeviceConfig(capacity=1 << 16,
                                      mv_persist_every=MV_PERSIST_EVERY))
    db.run(BID_SRC.format(n=n_events, c=chunk))
    db.run(Q4_MV)
    job = db._fused["q4"]
    total_ticks = n_events // (64 * chunk) + 3
    quarter = max(1, total_ticks // 4)

    def ticks_eps(k):
        c0 = job.counter
        t0 = _t.perf_counter()
        for _ in range(k):
            db.tick()
        job.sync()
        dt = _t.perf_counter() - t0
        return round((job.counter - c0) / dt) if dt > 0 else None

    # write path alone: one warm quarter (absorbs the compiles), one
    # measured quarter
    ticks_eps(quarter)
    write_eps_alone = ticks_eps(quarter)

    # write path under a continuous 64-reader cached storm
    saved = (ROBUSTNESS.serving_cache, ROBUSTNESS.serving_staleness_epochs)
    ROBUSTNESS.serving_cache = True
    ROBUSTNESS.serving_staleness_epochs = 0
    stop_ev = _th.Event()

    def bg_reader():
        while not stop_ev.is_set():
            db._serve_mv_rows("q4", job)

    bg = [_th.Thread(target=bg_reader, daemon=True) for _ in range(64)]
    for t in bg:
        t.start()
    try:
        write_eps_storm = ticks_eps(total_ticks - 2 * quarter)
    finally:
        stop_ev.set()
        for t in bg:
            t.join(30.0)

    # read arms over the drained (stable) MV: readers x cache x staleness
    def read_storm(readers, seconds):
        lats = []
        lock = _th.Lock()
        deadline = _t.perf_counter() + seconds

        def worker():
            my = []
            while _t.perf_counter() < deadline:
                r0 = _t.perf_counter()
                db._serve_mv_rows("q4", job)
                my.append(_t.perf_counter() - r0)
            with lock:
                lats.extend(my)

        ts = [_th.Thread(target=worker) for _ in range(readers)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(seconds + 60.0)
        lats.sort()
        n = len(lats)
        return {"n_selects": n,
                "read_qps": round(n / seconds),
                "read_p50_ms": round(lats[n // 2] * 1e3, 3) if n else None,
                "read_p99_ms": round(lats[min(n - 1, int(n * 0.99))] * 1e3,
                                     3) if n else None}

    arms = {}
    try:
        for cache, stale in (("off", 0), ("on", 0), ("on", 2)):
            ROBUSTNESS.serving_cache = cache == "on"
            ROBUSTNESS.serving_staleness_epochs = stale
            for readers in (1, 8, 64):
                db.read_cache.invalidate()
                shard_exec.reset_pull_stats()
                rec = read_storm(readers, window_s)
                pulls = shard_exec.PULL_STATS["device_pulls"]
                rec["device_pulls"] = pulls
                rec["pulls_per_1k_selects"] = (
                    round(1e3 * pulls / rec["n_selects"], 3)
                    if rec["n_selects"] else None)
                arms[f"cache_{cache}_stale{stale}_r{readers}"] = rec
    finally:
        ROBUSTNESS.serving_cache, ROBUSTNESS.serving_staleness_epochs = saved

    # acceptance: one pull per (MV, epoch) under the cached 64-reader
    # storm (the stream is drained — exactly one commit window), and
    # cached QPS >= 5x uncached at the same reader count
    hot = arms["cache_on_stale0_r64"]
    cold = arms["cache_off_stale0_r64"]
    assert hot["device_pulls"] <= 1, \
        f"cached 64-reader storm pulled {hot['device_pulls']}x"
    assert hot["read_qps"] >= 5 * cold["read_qps"], \
        f"cached QPS {hot['read_qps']} < 5x uncached {cold['read_qps']}"
    out = {
        "events": n_events,
        "window_s": window_s,
        "write_eps_alone": write_eps_alone,
        "write_eps_under_64_reader_storm": write_eps_storm,
        "cache": db.read_cache.stats(),
        "speedup_cached_vs_uncached_64r":
            round(hot["read_qps"] / max(1, cold["read_qps"]), 1),
        "arms": arms,
        "note": ("read QPS over the drained fused q4 MV; cached arms "
                 "serve (epoch, rows) snapshots from host memory with "
                 "single-flight fills — pulls_per_1k_selects is the "
                 "device-pull amortization; interference compares ingest "
                 "eps alone vs under a continuous 64-reader cached "
                 "storm"),
    }
    return {"serving": out}


# ---------------------------------------------------------------------------
# the un-killable harness
# ---------------------------------------------------------------------------

_STAGES = {
    "fused": stage_fused,
    "q4_device": stage_q4_device,
    "q4_host": stage_q4_host,
    "qx_device": stage_qx_device,
    "qx_host": stage_qx_host,
    "shards_q4": stage_shards_q4,
    "shards_qx": stage_shards_qx,
    "skew_q4": stage_skew_q4,
    "skew_qx": stage_skew_qx,
    "chaos_mttr": stage_chaos_mttr,
    "overload": stage_overload,
    "ingest": stage_ingest,
    "tiering": stage_tiering,
    "serving": stage_serving,
}


def _stage_child(name, args, out_path):
    """Subprocess entry: run one stage, dump its dict to out_path.
    Write-then-rename so the parent can never read a half-written file."""
    try:
        # Kernel policy per workload (device/sorted_state.cheap_compile):
        # the fused ceiling and the join-dense q5/q7/q8 programs measure
        # FASTER with the compile-cheap kernel forms on the tunnel
        # (fused: 1.64B vs 984M ev/s, compile 30s vs 229s); q4's
        # 1M-capacity agg measures faster with the variadic-sort forms
        # (1.17M vs 350k ev/s warm). Must be set before jax imports.
        if name in ("fused", "qx_device", "shards_qx", "skew_qx"):
            os.environ["RW_TPU_CHEAP_COMPILE"] = "1"
        if name.startswith("shards") or name.startswith("skew") \
                or name == "ingest":
            # mesh fallback for CPU-only hosts: 8 virtual devices (the
            # flag is inert when the default platform has real chips);
            # must land before jax initializes in this child
            flags = os.environ.get("XLA_FLAGS", "")
            if "xla_force_host_platform_device_count" not in flags:
                os.environ["XLA_FLAGS"] = (
                    flags + " --xla_force_host_platform_device_count=8"
                ).strip()
        result = _STAGES[name](*args)
        payload = {"ok": True, "result": result}
    except BaseException as e:  # report, don't propagate — parent decides
        payload = {"ok": False, "error": f"{type(e).__name__}: {e}"}
    with open(out_path + ".part", "w") as f:
        json.dump(payload, f)
    os.replace(out_path + ".part", out_path)


class Harness:
    def __init__(self, total_budget, record=True):
        self.deadline = time.monotonic() + total_budget
        self.detail = {}
        self.log = []
        self._printed = False
        self._proc = None               # live stage subprocess, if any
        # write the round's BENCH record file only for full, uninterrupted
        # runs — a smoke run or a ctrl-C'd partial must never clobber the
        # canonical BENCH_rNN.json next to the committed history
        self.record = record
        signal.signal(signal.SIGTERM, self._on_term)
        signal.signal(signal.SIGINT, self._on_term)

    def _on_term(self, signum, frame):
        self.record = False
        self.log.append(f"signal {signum} — emitting partial results")
        if self._proc is not None and self._proc.is_alive():
            self._proc.kill()          # os._exit skips mp atexit cleanup
        self.emit()
        os._exit(1)

    def remaining(self):
        return self.deadline - time.monotonic()

    def run_stage(self, name, args, budget, note=""):
        """Run one stage subprocess under a wall budget; merge its result."""
        budget = min(budget, max(5.0, self.remaining() - 10.0))
        if budget <= 5.0:
            self.log.append(f"{name}{args}: skipped (total budget exhausted)")
            self._progress()
            return False
        out_path = f"{PROGRESS_PATH}.{name}.tmp"
        if os.path.exists(out_path):
            os.unlink(out_path)
        ctx = mp.get_context("spawn")
        t0 = time.monotonic()
        proc = ctx.Process(target=_stage_child, args=(name, args, out_path),
                           daemon=True)
        self._proc = proc
        proc.start()
        proc.join(budget)
        wall = time.monotonic() - t0
        if proc.is_alive():
            proc.kill()
            proc.join(10)
            self._proc = None
            self.log.append(f"{name}{args}: KILLED after {wall:.0f}s "
                            f"(budget {budget:.0f}s){note}")
            self._progress()
            return False
        self._proc = None
        ok = False
        payload = None
        if os.path.exists(out_path):
            try:
                with open(out_path) as f:
                    payload = json.load(f)
            except (OSError, ValueError) as e:   # truncated/unreadable
                payload = {"ok": False, "error": f"result unreadable: {e}"}
            os.unlink(out_path)
        if payload is not None:
            if payload.get("ok"):
                self.detail.update(payload["result"])
                self.log.append(f"{name}{args}: ok in {wall:.0f}s")
                ok = True
            else:
                self.log.append(f"{name}{args}: {payload['error']}")
        else:
            self.log.append(f"{name}{args}: died (rc={proc.exitcode}) "
                            f"after {wall:.0f}s")
        self._progress()
        return ok

    def _progress(self):
        tmp = PROGRESS_PATH + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"detail": self.detail, "log": self.log}, f, indent=1)
        os.replace(tmp, PROGRESS_PATH)

    def emit(self):
        if self._printed:
            return
        self._printed = True
        d = self.detail
        # fold the separately-staged host baselines into their workloads
        # (host runs at its own, smaller scale — keep that visible)
        if "q4_sql" in d and "q4_sql_host" in d:
            h = d.pop("q4_sql_host")
            d["q4_sql"]["host_sql_eps"] = h["host_sql_eps"]
            d["q4_sql"]["host_sql_events"] = h["events"]
        if "q5_q7_q8_sql" in d and "q5_q7_q8_sql_host" in d:
            h = d.pop("q5_q7_q8_sql_host")
            d["q5_q7_q8_sql"]["host_sql_eps"] = h["host_sql_eps"]
            d["q5_q7_q8_sql"]["host_sql_events"] = h["events"]
        d["stage_log"] = self.log
        fused = d.get("q4_fused", {})
        value = fused.get("device_eps", 0)
        base = fused.get("numpy_batch_eps")
        if not value:  # fused stage lost — fall back to the SQL headline
            value = d.get("q4_sql", {}).get("device_eps", 0)
            base = d.get("q4_sql", {}).get("host_sql_eps")
        result = {
            "metric": "nexmark_q4_agg_throughput",
            "value": value,
            "unit": "events/s",
            # honest denominator: the vectorized numpy batch baseline, not
            # the per-row Python loop BENCH_r01 used
            "vs_baseline": round(value / base, 3) if base else None,
            "detail": d,
        }
        # record the round's numbers (warmup_s + compile/retrace counts in
        # the per-stage `warmup` blocks) so regressions diff as files
        out_path = os.environ.get("RW_BENCH_OUT", "BENCH_r19.json")
        if out_path and self.record:
            try:
                with open(out_path + ".tmp", "w") as f:
                    json.dump(result, f, indent=1)
                os.replace(out_path + ".tmp", out_path)
            except OSError:
                pass
        print(json.dumps(result), flush=True)


def main():
    smoke = "--smoke" in sys.argv
    total = float(os.environ.get("RW_BENCH_BUDGET", "100" if smoke
                                 else "3400"))
    h = Harness(total, record=not smoke)
    if smoke:
        h.run_stage("fused", (10, 65_536), 60)
        h.run_stage("q4_device", (524_288,), 60)
        h.run_stage("q4_host", (32_768,), 30)
        h.run_stage("qx_device", (262_144,), 60)
        h.run_stage("qx_host", (8_192,), 30)
        h.run_stage("shards_q4", (262_144,), 90)
        h.run_stage("shards_qx", (65_536,), 90)
        h.run_stage("skew_q4", (131_072,), 120)
        h.run_stage("chaos_mttr", (262_144,), 90)
        h.run_stage("overload", (50_000,), 60)
        # >= 4 staged windows at INGEST_CHUNK so the double buffer has
        # something to overlap even at smoke scale
        h.run_stage("ingest", (1_048_576, 20_000, 4), 180)
        h.run_stage("tiering", (262_144,), 150)
        h.run_stage("serving", (131_072, 0.5), 120)
    else:
        # Budgets assume a possibly-cold persistent compile cache: one cold
        # compile of a fused epoch program set is ~200-400s on the remote-
        # compile tunnel. A killed attempt still wrote cache entries for
        # every program that finished, so the SAME-scale retry resumes from
        # there; only after two full-scale attempts do we shrink. Warm runs
        # finish each stage in well under 120s.
        if not h.run_stage("fused", (EPOCHS, ROWS), 300):
            h.run_stage("fused", (EPOCHS, ROWS), 150, " — retry (warmer)")
        # retries stay at the SAME scale: the traced programs embed the
        # event bound (SourceNode max_events / pack-plan ranges), so a
        # smaller fallback scale would start cold while same-scale
        # attempts resume from every cache entry the killed attempt wrote
        if not h.run_stage("q4_device", (Q4_SQL_EVENTS[0],), 600):
            if not h.run_stage("q4_device", (Q4_SQL_EVENTS[0],), 400,
                               " — retry (warmer)"):
                h.run_stage("q4_device", (Q4_SQL_EVENTS[0],), 300,
                            " — retry (warmer still)")
        h.run_stage("q4_host", (HOST_SQL_EVENTS,), 60)
        # mesh-shard sweep (ISSUE 7): the SAME fused q4 SQL at 1 vs 8
        # chips — warm + measured pass per shard count at a quarter of
        # the headline scale, MVs cross-verified bit-identical
        if not h.run_stage("shards_q4", (SHARDS_Q4_EVENTS,), 700):
            h.run_stage("shards_q4", (SHARDS_Q4_EVENTS,), 500,
                        " — retry (warmer)")
        # warmup + measured pass + three numpy oracles ≈ 650-850s warm
        if not h.run_stage("qx_device", (QX_SQL_EVENTS[0],), 1200):
            if not h.run_stage("qx_device", (QX_SQL_EVENTS[0],), 900,
                               " — retry (warmer)"):
                h.run_stage("qx_device", (QX_SQL_EVENTS[0],), 700,
                            " — retry (warmer still)")
        h.run_stage("qx_host", (HOST_QX_EVENTS,), 60)
        # q5/q7/q8 shard sweep: single pass per shard count (the qx
        # programs are compile-heavy; the cache from qx_device warms 1-
        # shard, the 8-shard pass pays its own compiles once)
        h.run_stage("shards_qx", (QX_SQL_EVENTS[0],), 900)
        # Zipfian skew sweep (ISSUE 13): the same fused SQL under a
        # power-law key distribution, defenses off vs on at 1 vs 8
        # shards — speedup_8v1 per arm is the straggler-proofing number
        if not h.run_stage("skew_q4", (SHARDS_Q4_EVENTS // 2,), 800):
            h.run_stage("skew_q4", (SHARDS_Q4_EVENTS // 2,), 500,
                        " — retry (warmer)")
        h.run_stage("skew_qx", (QX_SQL_EVENTS[0] // 4,), 700)
        # recovery MTTR under chaos (fault-tolerance v3): worker SIGKILL
        # respawn + fused device-fault in-place recovery, both timed
        h.run_stage("chaos_mttr", (Q4_SQL_EVENTS[0] // 4,), 300)
        # overload survival sweep (ISSUE 14): freshness p50/p99 + eps +
        # shed counts at 1x/2x/10x offered load, ladder + audit asserted
        h.run_stage("overload", (500_000,), 240)
        # line-rate host ingest (ISSUE 15): q4 with host ingest in the
        # measured path — before (executor DAG) vs after (staged feed)
        # at 1/8 shards + the concurrent-producer COPY firehose
        if not h.run_stage("ingest", (Q4_SQL_EVENTS[0] // 2,
                                      500_000, 16), 900):
            h.run_stage("ingest", (Q4_SQL_EVENTS[0] // 2,
                                   500_000, 16), 600, " — retry (warmer)")
        # tiered state beyond HBM (ISSUE 16): unbounded-key agg at a
        # clamped capacity, untiered (growth replays) vs tiered
        # (demote/promote), MVs asserted bit-identical
        if not h.run_stage("tiering", (Q4_SQL_EVENTS[0] // 4,), 600):
            h.run_stage("tiering", (Q4_SQL_EVENTS[0] // 4,), 400,
                        " — retry (warmer)")
        # serving read path (ISSUE 19): epoch-versioned MV read cache
        # off/on x staleness 0/2 x 1/8/64 readers — read QPS + p50/p99,
        # device pulls per 1k SELECTs, write-eps interference under a
        # 64-reader storm; coalescing + >=5x QPS asserted in-stage
        if not h.run_stage("serving", (Q4_SQL_EVENTS[0] // 4,), 400):
            h.run_stage("serving", (Q4_SQL_EVENTS[0] // 4,), 300,
                        " — retry (warmer)")
    h.emit()


if __name__ == "__main__":
    main()
