"""Device sorted-run state + agg epoch step, vs a dict-based oracle."""
import numpy as np
import pytest

import jax.numpy as jnp

from risingwave_tpu.device import (EMPTY_KEY, ReduceKind, batch_reduce,
                                   lookup, make_state, merge)
from risingwave_tpu.device.agg_step import DeviceAggSpec, DeviceHashAgg


def np_state(state):
    n = int(state.count)
    return {int(k): tuple(float(v[i]) for v in state.vals)
            for i, k in enumerate(np.asarray(state.keys)[:n])}


def test_batch_reduce_unique_sums():
    keys = jnp.asarray([5, 3, 5, 3, 5, 9], dtype=jnp.int64)
    mask = jnp.asarray([1, 1, 1, 1, 1, 0], dtype=bool)
    vals = [jnp.asarray([1, 10, 2, 20, 3, 99], dtype=jnp.int64)]
    uk, uv, uc = batch_reduce(keys, mask, vals, [ReduceKind.SUM])
    assert int(uc) == 2
    got = {int(k): int(v) for k, v in zip(np.asarray(uk), np.asarray(uv[0]))
           if k != EMPTY_KEY}
    assert got == {3: 30, 5: 6}


def test_merge_insert_update_delete():
    st = make_state(8, [jnp.int64, jnp.int64], [ReduceKind.SUM, ReduceKind.SUM])
    # insert keys 1,2 with row_count 2,1
    dk = jnp.asarray([1, 2] + [int(EMPTY_KEY)] * 2, dtype=jnp.int64)
    dv = [jnp.asarray([2, 1, 0, 0], dtype=jnp.int64),
          jnp.asarray([20, 10, 0, 0], dtype=jnp.int64)]
    st, needed = merge(st, dk, dv, [ReduceKind.SUM, ReduceKind.SUM])
    assert int(needed) == 2 and np_state(st) == {1: (2, 20), 2: (1, 10)}
    # retract key 2 fully, update key 1, insert 7 — delta deliberately
    # UNSORTED: merge's variadic sort handles any delta order
    dk = jnp.asarray([2, 1, 7, int(EMPTY_KEY)], dtype=jnp.int64)
    dv = [jnp.asarray([-1, 1, 3, 0], dtype=jnp.int64),
          jnp.asarray([-10, 5, 7, 0], dtype=jnp.int64)]
    st, needed = merge(st, dk, dv, [ReduceKind.SUM, ReduceKind.SUM])
    assert np_state(st) == {1: (3, 25), 7: (3, 7)}
    found, vals = lookup(st, jnp.asarray([1, 2, 7], dtype=jnp.int64))
    assert list(np.asarray(found)) == [True, False, True]
    assert int(vals[1][0]) == 25 and int(vals[1][2]) == 7


def test_merge_overflow_reports_needed():
    st = make_state(4, [jnp.int64], [ReduceKind.SUM])
    dk = jnp.asarray([1, 2, 3, 4, 5, 6], dtype=jnp.int64)
    dv = [jnp.ones(6, dtype=jnp.int64)]
    st, needed = merge(st, dk, dv, [ReduceKind.SUM])
    assert int(needed) == 6  # > capacity: caller must grow and retry


def random_oracle_run(seed, kinds, n_epochs=6, rows=200, keyspace=17):
    rng = np.random.default_rng(seed)
    spec = DeviceAggSpec.build(kinds, [np.int64] * len(kinds))
    agg = DeviceHashAgg(spec, capacity=8)  # force growth
    oracle = {}  # key -> list of multisets? maintain sums/counts
    out_oracle = {}
    for _ in range(n_epochs):
        keys = rng.integers(0, keyspace, size=rows).astype(np.int64)
        vals = rng.integers(-50, 50, size=rows).astype(np.int64)
        valid = rng.random(rows) > 0.1
        if any(k in ("min", "max") for k in kinds):
            signs = np.ones(rows, dtype=np.int32)
        else:
            signs = np.where(rng.random(rows) > 0.3, 1, -1).astype(np.int32)
            # keep oracle row counts non-negative: flip deletes of absent keys
            cnt = dict.fromkeys(range(keyspace), 0)
            for i in range(rows):
                k = int(keys[i])
                c = cnt.get(k, 0) + oracle.get(k, {"rc": 0})["rc"]
                if signs[i] < 0 and c <= 0:
                    signs[i] = 1
                cnt[k] = cnt.get(k, 0) + int(signs[i])
        agg.push_rows(keys, signs,
                      [(vals, valid) for _ in kinds])
        # oracle update
        for i in range(rows):
            k = int(keys[i]); s = int(signs[i])
            e = oracle.setdefault(k, {"rc": 0, "sum": 0, "cnt": 0,
                                      "min": None, "max": None})
            e["rc"] += s
            if valid[i]:
                e["sum"] += s * int(vals[i]); e["cnt"] += s
                v = int(vals[i])
                e["min"] = v if e["min"] is None else min(e["min"], v)
                e["max"] = v if e["max"] is None else max(e["max"], v)
        # group death is a barrier-time event (hash_agg.rs flush_data), not a
        # mid-epoch one: additive state survives transient row_count == 0
        for k in [k for k, e in oracle.items() if e["rc"] == 0]:
            del oracle[k]
        changes = agg.flush_epoch()
        assert changes is not None
        # apply change set to materialized output oracle
        n = int(changes["count"])
        for i in range(n):
            k = int(changes["keys"][i])
            if bool(changes["new_found"][i]):
                row = []
                for c, kind in enumerate(kinds):
                    if bool(changes["new_null"][c][i]):
                        row.append(None)
                    else:
                        row.append(changes["new_out"][c][i])
                out_oracle[k] = row
            elif bool(changes["old_found"][i]):
                out_oracle.pop(k, None)
    # final: materialized outputs must match oracle
    assert set(out_oracle) == set(oracle)
    for k, row in out_oracle.items():
        e = oracle[k]
        for kind, got in zip(kinds, row):
            if kind == "count_star":
                assert int(got) == e["rc"], (k, kind)
            elif kind == "count":
                assert int(got) == e["cnt"], (k, kind)
            elif kind == "sum":
                exp = e["sum"] if e["cnt"] != 0 else None
                assert (got is None) == (exp is None)
                if exp is not None:
                    assert int(got) == exp, (k, kind)
            elif kind == "avg":
                if e["cnt"]:
                    assert abs(float(got) - e["sum"] / e["cnt"]) < 1e-9
            elif kind == "min":
                assert (got is None and e["min"] is None) or int(got) == e["min"]
            elif kind == "max":
                assert (got is None and e["max"] is None) or int(got) == e["max"]


def test_agg_retractable_vs_oracle():
    random_oracle_run(1, ["count_star", "sum", "count", "avg"])


def test_agg_append_only_minmax_vs_oracle():
    random_oracle_run(2, ["min", "max", "sum"])


def test_capacity_growth():
    spec = DeviceAggSpec.build(["sum"], [np.int64])
    agg = DeviceHashAgg(spec, capacity=8)
    keys = np.arange(1000, dtype=np.int64)
    agg.push_rows(keys, np.ones(1000, dtype=np.int32),
                  [(keys * 2, np.ones(1000, dtype=bool))])
    ch = agg.flush_epoch()
    assert int(ch["count"]) == 1000
    assert agg.state.capacity >= 1000 and int(agg.state.count) == 1000


def test_sort_cols_stable_and_compact_rows():
    """Variadic-sort building blocks: stable multi-key sort + stable
    front-compaction with fills (the merge kernels' primitives)."""
    from risingwave_tpu.device.sorted_state import compact_rows, sort_cols
    rng = np.random.default_rng(7)
    for _ in range(10):
        n = int(rng.integers(1, 60))
        k1 = rng.integers(0, 6, size=n).astype(np.int64)
        k2 = rng.integers(0, 6, size=n).astype(np.int64)
        v = np.arange(n, dtype=np.int64)
        (s1, s2), (sv,) = sort_cols([jnp.asarray(k1), jnp.asarray(k2)],
                                    [jnp.asarray(v)])
        order = np.lexsort((v, k2, k1))   # stable: position breaks ties
        assert list(np.asarray(s1)) == list(k1[order])
        assert list(np.asarray(s2)) == list(k2[order])
        assert list(np.asarray(sv)) == list(v[order])
        # compact: keep even-valued rows, truncate to n, fill with -1
        alive = (sv % 2) == 0
        out = compact_rows(alive, [s1], [sv], n, [-1, -1])
        want = [int(x) for x, a in zip(np.asarray(sv), np.asarray(alive))
                if a]
        got = list(np.asarray(out[1]))
        assert got[:len(want)] == want
        assert all(x == -1 for x in got[len(want):])
