"""Union watermark propagation (min across inputs — `union.rs`
BufferedWatermarks) + ProjectSet watermark-through-carry + typed literals
and DATE-bound generate_series (round-5 ADVICE fixes)."""
from typing import Iterator, List

from risingwave_tpu.core import Op, Schema, StreamChunk, dtypes as T
from risingwave_tpu.ops import Barrier, BarrierKind, Message, Watermark
from risingwave_tpu.ops.executor import Executor
from risingwave_tpu.ops.message import EpochPair
from risingwave_tpu.ops.simple import UnionExecutor

SCHEMA = Schema.of(("w", T.INT64), ("v", T.INT64))


class MessageList(Executor):
    def __init__(self, schema: Schema, msgs: List[Message]):
        super().__init__(schema, "MessageList")
        self.msgs = msgs

    def execute(self) -> Iterator[Message]:
        yield from self.msgs


def barrier(e: int) -> Barrier:
    return Barrier(EpochPair(e, e - 1), kind=BarrierKind.CHECKPOINT)


def wm(v: int) -> Watermark:
    return Watermark(0, T.INT64, v)


class TestUnionWatermark:
    def test_min_across_inputs(self):
        a = MessageList(SCHEMA, [wm(10), barrier(1), wm(30), barrier(2)])
        b = MessageList(SCHEMA, [wm(20), barrier(1), wm(25), barrier(2)])
        out = list(UnionExecutor([a, b]).execute())
        wms = [m.value for m in out if isinstance(m, Watermark)]
        # eager min-tracking: 10 when both reported; a's 30 raises the min
        # to b's standing 20; b's 25 raises it again
        assert wms == [10, 20, 25]

    def test_no_emission_until_all_inputs_report(self):
        a = MessageList(SCHEMA, [wm(10), barrier(1), barrier(2)])
        b = MessageList(SCHEMA, [barrier(1), wm(5), barrier(2)])
        out = list(UnionExecutor([a, b]).execute())
        wms = [m.value for m in out if isinstance(m, Watermark)]
        assert wms == [5]

    def test_release_on_input_death(self):
        """A watermark held for a silent input is released when that input
        terminates (it no longer constrains the min)."""
        a = MessageList(SCHEMA, [barrier(1)])                 # dies early
        b = MessageList(SCHEMA, [wm(10), barrier(1), wm(20), barrier(2)])
        out = list(UnionExecutor([a, b]).execute())
        wms = [m.value for m in out if isinstance(m, Watermark)]
        assert wms == [10, 20]

    def test_non_decreasing_output(self):
        # input b regresses its own already-counted min contribution: the
        # union must never re-emit a lower watermark
        a = MessageList(SCHEMA, [wm(10), barrier(1), wm(11), barrier(2)])
        b = MessageList(SCHEMA, [wm(40), barrier(1), barrier(2)])
        out = list(UnionExecutor([a, b]).execute())
        wms = [m.value for m in out if isinstance(m, Watermark)]
        # 40 is released once input a terminates and stops constraining
        assert wms == [10, 11, 40]


class TestProjectSetWatermarkCarry:
    def test_watermark_rides_carry_column(self):
        """A watermark column not in the SELECT list survives through the
        ProjectSet's hidden carry columns (planner maps the index)."""
        from risingwave_tpu.ops.project_set import ProjectSetExecutor, \
            BoundTableFunction
        from risingwave_tpu.expr.expression import InputRef, Literal
        tf = BoundTableFunction(
            "generate_series",
            [Literal(1, T.INT64), InputRef(1, T.INT64)], T.INT64)
        src = MessageList(SCHEMA, [wm(42), barrier(1)])
        ps = ProjectSetExecutor(src, [("tf", tf)], ["g"], carry=[0, 1])
        out = list(ps.execute())
        wms = [m for m in out if isinstance(m, Watermark)]
        # carried col 0 sits at output index n_items + carry.index(0) = 1
        assert len(wms) == 1 and wms[0].col_idx == 1 and wms[0].value == 42


class TestTypedLiterals:
    def test_date_literal_and_series(self):
        from risingwave_tpu.sql import Database
        db = Database()
        assert db.query("SELECT DATE '2024-01-01'") == [(19723,)]
        rows = db.query("SELECT * FROM generate_series(DATE '2024-01-01',"
                        " DATE '2024-01-04', interval '1 day')")
        day = 86_400_000_000
        assert [r[0] for r in rows] == [1704067200000000 + i * day
                                        for i in range(4)]

    def test_timestamp_literal(self):
        from risingwave_tpu.sql import Database
        db = Database()
        rows = db.query("SELECT TIMESTAMP '2024-01-01 00:00:01'")
        assert rows == [(1704067201000000,)]

    def test_date_series_requires_step(self):
        """2-arg DATE form would iterate per MICROSECOND after the cast —
        PG requires the interval step; so do we."""
        import pytest
        from risingwave_tpu.sql import Database
        db = Database()
        with pytest.raises(ValueError, match="interval step"):
            db.query("SELECT * FROM generate_series(DATE '2024-01-01',"
                     " DATE '2024-01-04')")
