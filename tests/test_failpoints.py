"""Failpoint fault injection + self-healing worker supervision.

Reference analogs: the `fail::fail_point!` hooks the recovery tests arm
(`src/meta/src/hummock/manager/commit_epoch.rs` commit-epoch failpoints)
and the madsim deterministic node-kill tier
(`src/tests/simulation/tests/integration_tests/recovery/`). Here the
seeded registry (`utils/failpoint.py`) is exercised through every layer
it hooks — exchange sockets, worker spawn/crash, spill/manifest
commit — plus the FragmentSupervisor's in-place respawn paths.
"""
import os
import time

import numpy as np
import pytest

from risingwave_tpu.sql import Database
from risingwave_tpu.utils import failpoint as fp


@pytest.fixture(autouse=True)
def _clean_failpoints():
    fp.reset()
    yield
    fp.reset()


# ---------------------------------------------------------------------------
# global ordinal ledger: record, dump, replay
# ---------------------------------------------------------------------------


def test_ledger_records_every_fire_in_global_order():
    fp.clear_ledger()
    fp.arm("a", prob=1.0, seed=0, max_fires=2)
    fp.arm("b", prob=1.0, seed=0, max_fires=1)
    fp.failpoint("a")
    fp.failpoint("b")
    fp.failpoint("a")
    fp.failpoint("a")                  # capped: no fire, no entry
    led = fp.ledger()
    assert [(o, p, h) for o, p, _t, h in led] == \
        [(0, "a", 1), (1, "b", 1), (2, "a", 2)]
    fp.clear_ledger()


def test_ledger_dump_load_roundtrip(tmp_path):
    fp.clear_ledger()
    fp.arm("x", prob=0.5, seed=3)
    for _ in range(40):
        fp.failpoint("x")
    path = str(tmp_path / "l.jsonl")
    n = fp.dump_ledger(path)
    assert n == len(fp.ledger()) > 0
    assert fp.load_ledger(path) == fp.ledger()
    fp.clear_ledger()


def test_ledger_env_replay_arms_recorded_points(tmp_path, monkeypatch):
    """RW_FAILPOINT_LEDGER pointed at an EXISTING recording re-arms the
    recorded points in replay mode at load_env time — the process-tree
    arming path (workers inherit the env)."""
    import os
    fp.clear_ledger()
    fp.arm("x", prob=0.3, seed=11)
    fired1 = [fp.failpoint("x") for _ in range(60)]
    path = str(tmp_path / "l.jsonl")
    fp.dump_ledger(path)
    fp.reset()
    fp.clear_ledger()
    monkeypatch.setenv(fp.LEDGER_ENV, path)
    monkeypatch.delenv(fp.ENV_VAR, raising=False)
    monkeypatch.delenv(fp.MODE_ENV, raising=False)
    fp.load_env()
    armed = {p.name: p for p in fp.armed()}
    assert armed["x"].replay_hits is not None
    # the root pins its decision into the env for descendants
    assert os.environ[fp.MODE_ENV] == "replay"
    fired2 = [fp.failpoint("x") for _ in range(60)]
    assert fired1 == fired2
    fp.clear_ledger()


def test_ledger_mode_pin_survives_file_appearing(tmp_path, monkeypatch):
    """A process that inherited mode=record must KEEP recording even
    though the ledger file now exists (a sibling recorder exited first):
    without the pin, every worker spawned after the first clean sibling
    exit would silently flip to replaying a partial ledger mid-run."""
    path = str(tmp_path / "l.jsonl")
    fp.arm("x", prob=1.0, seed=0, max_fires=1)
    fp.failpoint("x")
    fp.dump_ledger(path)               # the file now exists...
    fp.reset()
    monkeypatch.setenv(fp.LEDGER_ENV, path)
    monkeypatch.setenv(fp.MODE_ENV, "record")   # ...but mode was pinned
    monkeypatch.delenv(fp.ENV_VAR, raising=False)
    fp.load_env()
    assert not fp.armed(), \
        "pinned record mode must not arm replay points from the file"
    fp.clear_ledger()


# ---------------------------------------------------------------------------
# registry semantics
# ---------------------------------------------------------------------------


def test_disarmed_failpoint_is_noop():
    assert fp.failpoint("no.such.point") is False
    assert fp.failpoint("exchange.connect") is False


def test_seeded_firing_is_deterministic():
    fp.arm("x", prob=0.3, seed=7)
    seq1 = [fp.failpoint("x") for _ in range(200)]
    fp.reset()
    fp.arm("x", prob=0.3, seed=7)
    seq2 = [fp.failpoint("x") for _ in range(200)]
    assert seq1 == seq2
    assert any(seq1) and not all(seq1)
    fp.reset()
    fp.arm("x", prob=0.3, seed=8)
    assert [fp.failpoint("x") for _ in range(200)] != seq1, \
        "a different seed must fire a different hit sequence"


def test_max_fires_caps_total():
    fp.arm("x", prob=1.0, seed=0, max_fires=3)
    assert [fp.failpoint("x") for _ in range(10)].count(True) == 3


def test_env_spec_parsing():
    pts = fp.parse_spec("a, b:0.5 , c:0.25:9:2")
    assert [(p.name, p.prob, p.seed, p.max_fires) for p in pts] == \
        [("a", 1.0, 0, None), ("b", 0.5, 0, None), ("c", 0.25, 9, 2)]
    with pytest.raises(ValueError):
        fp.parse_spec("bad:prob")
    with pytest.raises(ValueError):
        fp.parse_spec("x:2.0")          # prob out of range
    # round trip through the canonical spec string
    assert fp.parse_spec(pts[2].spec())[0].max_fires == 2


def test_env_load(monkeypatch):
    monkeypatch.setenv(fp.ENV_VAR, "worker.crash:0.5:11")
    fp.load_env()
    armed = {p.name: p for p in fp.armed()}
    assert armed["worker.crash"].seed == 11


# ---------------------------------------------------------------------------
# exchange layer: connect retry/backoff, frame faults
# ---------------------------------------------------------------------------


def _roundtrip_one_chunk():
    """One chunk coordinator->consumer over a real socket exchange."""
    from risingwave_tpu.core import Op, Schema, StreamChunk, dtypes as T
    from risingwave_tpu.core.schema import Field
    from risingwave_tpu.runtime.exchange_net import (ExchangeServer,
                                                     RemoteInput)
    schema = Schema([Field("v", T.INT64)])
    server = ExchangeServer()
    ch = server.register(0, schema.dtypes)
    ch.send(StreamChunk.from_rows([T.INT64], [(Op.INSERT, (7,))]))
    ch.close()
    inp = RemoteInput(server.addr, 0, schema)
    rows = [r for msg in inp.execute() for _, r in msg.op_rows()]
    server.close()
    return rows


def test_connect_retry_absorbs_transient_refusals():
    from risingwave_tpu.config import ROBUSTNESS
    fp.arm("exchange.connect", prob=1.0, seed=0, max_fires=2)
    old = ROBUSTNESS.connect_backoff_s
    ROBUSTNESS.connect_backoff_s = 0.001
    try:
        assert _roundtrip_one_chunk() == [(7,)]
    finally:
        ROBUSTNESS.connect_backoff_s = old
    assert fp._ARMED["exchange.connect"].fires == 2


def test_connect_gives_up_after_bounded_attempts():
    from risingwave_tpu.config import ROBUSTNESS
    fp.arm("exchange.connect", prob=1.0, seed=0)   # every attempt refused
    old = ROBUSTNESS.connect_backoff_s
    ROBUSTNESS.connect_backoff_s = 0.001
    try:
        with pytest.raises(ConnectionError, match="attempts"):
            _roundtrip_one_chunk()
    finally:
        ROBUSTNESS.connect_backoff_s = old


def test_recv_frame_fault_surfaces_as_connection_error():
    fp.arm("exchange.recv_frame", prob=1.0, seed=0, max_fires=1)
    with pytest.raises(ConnectionError):
        _roundtrip_one_chunk()


# ---------------------------------------------------------------------------
# worker spawn: startup retry + escalation
# ---------------------------------------------------------------------------


def test_spawn_retry_absorbs_transient_failures():
    from risingwave_tpu.config import ROBUSTNESS
    from risingwave_tpu.runtime import remote_fragments as rf
    fp.arm("fragment.spawn", prob=1.0, seed=0,
           max_fires=ROBUSTNESS.spawn_attempts - 1)
    old = ROBUSTNESS.spawn_backoff_s
    ROBUSTNESS.spawn_backoff_s = 0.001
    try:
        from risingwave_tpu.runtime.exchange_net import ExchangeServer
        server = ExchangeServer()
        plan = {"coord": [server.addr[0], server.addr[1]], "in_channel": 0,
                "in_schema": [["k", "bigint"]], "append_only": True,
                "fragment": {"kind": "partial_hash_agg",
                             "group_indices": [0],
                             "calls": [["count", None]]}}
        w = rf._spawn_worker(plan)     # succeeds on the last attempt
        assert w.proc.poll() is None
        w.proc.kill()
        server.close()
    finally:
        ROBUSTNESS.spawn_backoff_s = old


def test_spawn_escalates_past_bounded_attempts():
    from risingwave_tpu.config import ROBUSTNESS
    from risingwave_tpu.runtime import remote_fragments as rf
    fp.arm("fragment.spawn", prob=1.0, seed=0)
    old = ROBUSTNESS.spawn_backoff_s
    ROBUSTNESS.spawn_backoff_s = 0.001
    try:
        with pytest.raises(rf.RemoteWorkerDied, match="spawn failed"):
            rf._spawn_worker({"in_channel": 0})
    finally:
        ROBUSTNESS.spawn_backoff_s = old


# ---------------------------------------------------------------------------
# state layer: crash-consistency torture
# ---------------------------------------------------------------------------


def _ingest(store, table_id, epoch, rows):
    store.ingest_batch(table_id,
                       [(k.encode(), (v,)) for k, v in rows], epoch)


def test_manifest_commit_crash_preserves_previous_version(tmp_path):
    from risingwave_tpu.state import SpillStateStore
    d = str(tmp_path / "data")
    store = SpillStateStore(d)
    _ingest(store, 1, 10, [("a", 1), ("b", 2)])
    store.commit_epoch(10)
    # crash between the tmp manifest write and the atomic rename
    fp.arm("state.manifest_commit", prob=1.0, seed=0, max_fires=1)
    _ingest(store, 1, 20, [("c", 3)])
    with pytest.raises(fp.FailpointError):
        store.commit_epoch(20)
    fp.reset()
    store.close()
    # recovery: the previous version must be fully readable, the
    # uncommitted epoch gone ('uncommitted epochs vanish')
    store2 = SpillStateStore(d)
    assert store2.committed_epoch == 10
    assert store2.get(1, b"a") == (1,)
    assert store2.get(1, b"b") == (2,)
    assert store2.get(1, b"c") is None
    store2.close()


def test_spill_write_crash_preserves_previous_version(tmp_path):
    from risingwave_tpu.state import SpillStateStore
    d = str(tmp_path / "data")
    store = SpillStateStore(d)
    _ingest(store, 1, 10, [("a", 1)])
    store.commit_epoch(10)
    fp.arm("state.spill_write", prob=1.0, seed=0, max_fires=1)
    _ingest(store, 1, 20, [("b", 2)])
    with pytest.raises(fp.FailpointError):
        store.commit_epoch(20)
    fp.reset()
    store.close()
    store2 = SpillStateStore(d)
    assert store2.committed_epoch == 10
    assert store2.get(1, b"a") == (1,)
    assert store2.get(1, b"b") is None
    # no torn .tmp run files survive recovery
    leftovers = [f for f in os.listdir(os.path.join(d, "runs"))
                 if f.endswith(".tmp")]
    assert leftovers == []
    store2.close()


def test_repeated_runs_fire_identically_through_the_state_layer(tmp_path):
    """Acceptance: same RW_FAILPOINTS seed => identical firing. Drive the
    same commit sequence twice under a probabilistic point and compare
    which commits crashed."""
    from risingwave_tpu.state import SpillStateStore

    def run(sub):
        fp.reset()
        fp.arm("state.manifest_commit", prob=0.4, seed=123)
        store = SpillStateStore(str(tmp_path / sub))
        crashed = []
        for i, epoch in enumerate(range(10, 100, 10)):
            _ingest(store, 1, epoch, [(f"k{i}", i)])
            try:
                store.commit_epoch(epoch)
                crashed.append(False)
            except fp.FailpointError:
                crashed.append(True)
        store.close()
        fp.reset()
        return crashed

    a, b = run("a"), run("b")
    assert a == b and any(a) and not all(a)


# ---------------------------------------------------------------------------
# risectl surface
# ---------------------------------------------------------------------------


def test_risectl_failpoints_lists_and_arms(capsys):
    from risingwave_tpu.ctl import main
    assert main(["failpoints"]) == 0
    out = capsys.readouterr().out
    for name in ("exchange.connect", "worker.crash", "state.spill_write",
                 "state.manifest_commit", "fragment.spawn"):
        assert name in out
    assert main(["failpoints", "--arm", "worker.crash:0.1:42:3"]) == 0
    out = capsys.readouterr().out
    assert "export RW_FAILPOINTS='worker.crash:0.1:42:3'" in out
    with pytest.raises(SystemExit):
        main(["failpoints", "--arm", "nope.never"])
    with pytest.raises(SystemExit):
        main(["failpoints", "--arm", "worker.crash:banana"])


def test_risectl_failpoints_ledger(tmp_path, capsys):
    from risingwave_tpu.ctl import main
    fp.clear_ledger()
    assert main(["failpoints", "--ledger"]) == 0   # live, nothing fired
    assert "ledger is empty" in capsys.readouterr().out
    fp.arm("a", prob=1.0, seed=0, max_fires=2)
    fp.arm("b", prob=1.0, seed=0, max_fires=1)
    fp.failpoint("a"), fp.failpoint("b"), fp.failpoint("a")
    assert main(["failpoints", "--ledger"]) == 0   # live in-process ledger
    out = capsys.readouterr().out
    assert "3 fires" in out and fp.LEDGER_ENV in out
    assert out.index(" a ") < out.index(" b ")     # global ordinal order
    path = str(tmp_path / "l.jsonl")
    fp.dump_ledger(path)
    fp.clear_ledger()
    assert main(["failpoints", "--ledger", path]) == 0   # recorded file
    out = capsys.readouterr().out
    assert "3 fires" in out and "a" in out and "b" in out
    with pytest.raises(SystemExit):
        main(["failpoints", "--ledger", str(tmp_path / "nope.jsonl")])
    bad = tmp_path / "bad.jsonl"
    bad.write_text("not json\n")
    with pytest.raises(SystemExit):
        main(["failpoints", "--ledger", str(bad)])
