"""SQL -> device dispatch seam: the same statements must produce identical
results with the TPU path on, off, and sharded over an 8-device mesh
(VERDICT #2: `CREATE MATERIALIZED VIEW` actually runs on the device)."""
import numpy as np
import pytest

from risingwave_tpu.sql import Database


def _mk(device):
    return Database(device=device)


def _mirror(db_pairs, sql):
    for db in db_pairs:
        db.run(sql)


DEVICES = ["off", "on", 8]


@pytest.mark.parametrize("device", DEVICES[1:])
def test_device_agg_matches_host_random_workload(device):
    """Random inserts/deletes/updates through SQL; MV parity device vs host."""
    rng = np.random.default_rng(7)
    host, dev = _mk("off"), _mk(device)
    both = (host, dev)
    _mirror(both, "CREATE TABLE t (k INT, cat VARCHAR, v BIGINT, f DOUBLE)")
    _mirror(both, "CREATE MATERIALIZED VIEW mv AS SELECT k, count(*) AS c, "
            "count(v) AS cv, sum(v) AS s, avg(v) AS a "
            "FROM t GROUP BY k")
    _mirror(both, "CREATE MATERIALIZED VIEW mv2 AS SELECT cat, sum(f) AS sf "
            "FROM t GROUP BY cat")
    for _ in range(4):
        rows = []
        for _ in range(40):
            k = int(rng.integers(0, 6))
            cat = f"c{int(rng.integers(0, 4))}"
            v = "NULL" if rng.random() < 0.15 else int(rng.integers(0, 100))
            f = round(float(rng.random()), 3)
            rows.append(f"({k}, '{cat}', {v}, {f})")
        _mirror(both, f"INSERT INTO t VALUES {', '.join(rows)}")
        kd = int(rng.integers(0, 6))
        _mirror(both, f"DELETE FROM t WHERE k = {kd} AND v < 30")
        _mirror(both, f"UPDATE t SET v = v + 1 WHERE k = {kd}")
    a = sorted(host.query("SELECT * FROM mv"))
    b = sorted(dev.query("SELECT * FROM mv"))
    assert a == b and len(a) > 0
    a2 = dict(host.query("SELECT * FROM mv2"))
    b2 = dict(dev.query("SELECT * FROM mv2"))
    assert set(a2) == set(b2)
    for kk in a2:   # float sums: reduce-order differs; tolerance compare
        assert abs(a2[kk] - b2[kk]) < 1e-9


@pytest.mark.parametrize("device", DEVICES[1:])
def test_device_agg_null_group_and_distinct(device):
    host, dev = _mk("off"), _mk(device)
    both = (host, dev)
    _mirror(both, "CREATE TABLE t (k INT, v BIGINT)")
    _mirror(both, "CREATE MATERIALIZED VIEW mv AS "
            "SELECT k, count(*) AS c FROM t GROUP BY k")
    _mirror(both, "CREATE MATERIALIZED VIEW dmv AS SELECT DISTINCT k FROM t")
    _mirror(both, "INSERT INTO t VALUES (NULL, 1), (NULL, 2), (3, 3), (3, 4)")
    assert sorted(host.query("SELECT * FROM mv"), key=repr) == \
        sorted(dev.query("SELECT * FROM mv"), key=repr)
    assert sorted(host.query("SELECT * FROM dmv"), key=repr) == \
        sorted(dev.query("SELECT * FROM dmv"), key=repr)
    _mirror(both, "DELETE FROM t WHERE v <= 2")
    assert sorted(host.query("SELECT * FROM mv"), key=repr) == \
        sorted(dev.query("SELECT * FROM mv"), key=repr)
    assert sorted(dev.query("SELECT * FROM dmv"), key=repr) == [(3,)]


@pytest.mark.parametrize("device", ["on", 8])
def test_device_agg_recovery(tmp_path, device):
    """Kill/restart: device agg state reloads from the state table at the
    committed epoch and the stream continues exactly."""
    d = str(tmp_path)
    db = Database(data_dir=d, device=device)
    db.run("CREATE TABLE t (k INT, v BIGINT)")
    db.run("CREATE MATERIALIZED VIEW mv AS SELECT k, count(*) AS c, "
           "sum(v) AS s FROM t GROUP BY k")
    db.run("INSERT INTO t VALUES (1, 10), (2, 20), (1, 5)")
    before = sorted(db.query("SELECT * FROM mv"))

    db2 = Database(data_dir=d, device=device)   # simulated restart
    assert sorted(db2.query("SELECT * FROM mv")) == before
    db2.run("INSERT INTO t VALUES (1, 100)")
    db2.run("DELETE FROM t WHERE k = 2")
    after = sorted(db2.query("SELECT * FROM mv"))
    oracle = sorted(db2.query("SELECT k, count(*), sum(v) FROM t GROUP BY k"))
    assert after == oracle
    assert after == [(1, 3, 115)]


def test_device_agg_nexmark_parity_sharded():
    """Nexmark generated data, q4-core style agg, mesh-sharded device path
    vs host path — the VERDICT done-criterion."""
    host, dev = _mk("off"), _mk(8)
    src = ("CREATE SOURCE nbid (auction BIGINT, bidder BIGINT, price BIGINT,"
           " channel VARCHAR, url VARCHAR, date_time TIMESTAMP, "
           "extra VARCHAR) WITH (connector='nexmark', nexmark.table='bid', "
           "nexmark.max.events='3000')")
    mv = ("CREATE MATERIALIZED VIEW agg AS SELECT auction, count(*) AS c, "
          "sum(price) AS s, avg(price) AS a FROM nbid GROUP BY auction")
    for db in (host, dev):
        db.run(src)
        db.run(mv)
        db.run("FLUSH")
        db.run("FLUSH")
    a = sorted(host.query("SELECT * FROM agg"))
    b = sorted(dev.query("SELECT * FROM agg"))
    assert a == b and len(a) > 10


@pytest.mark.parametrize("device", DEVICES[1:])
def test_device_minmax_retractable(device):
    """min/max with deletes/updates: the sorted-multiset (minput.rs analog)
    recovers the next extreme exactly — no host fallback."""
    rng = np.random.default_rng(11)
    host, dev = _mk("off"), _mk(device)
    both = (host, dev)
    _mirror(both, "CREATE TABLE t (k INT, v BIGINT, f DOUBLE)")
    _mirror(both, "CREATE MATERIALIZED VIEW mv AS SELECT k, min(v) AS mn, "
            "max(v) AS mx, min(f) AS fmn, max(f) AS fmx, count(*) AS c "
            "FROM t GROUP BY k")
    for _ in range(4):
        rows = []
        for _ in range(30):
            k = int(rng.integers(0, 5))
            v = "NULL" if rng.random() < 0.1 else int(rng.integers(-50, 50))
            f = round(float(rng.standard_normal()), 3)
            rows.append(f"({k}, {v}, {f})")
        _mirror(both, f"INSERT INTO t VALUES {', '.join(rows)}")
        _mirror(both, f"DELETE FROM t WHERE v > {int(rng.integers(0, 40))} "
                f"AND k = {int(rng.integers(0, 5))}")
        _mirror(both, f"UPDATE t SET v = v - 7 WHERE k = "
                f"{int(rng.integers(0, 5))}")
    a = sorted(host.query("SELECT * FROM mv"), key=repr)
    b = sorted(dev.query("SELECT * FROM mv"), key=repr)
    assert a == b and len(a) > 0


def test_device_minmax_extreme_values_exact():
    """int64 max/min as aggregate VALUES must round-trip exactly (values are
    k1-discriminated in the multiset, never sentinel-remapped)."""
    host, dev = _mk("off"), _mk("on")
    both = (host, dev)
    _mirror(both, "CREATE TABLE t (k INT, v BIGINT)")
    _mirror(both, "CREATE MATERIALIZED VIEW mv AS SELECT k, min(v) AS mn, "
            "max(v) AS mx FROM t GROUP BY k")
    big, small = 2**63 - 1, -(2**63) + 1
    _mirror(both, f"INSERT INTO t VALUES (1, {big}), (1, {small}), (1, 0)")
    assert sorted(dev.query("SELECT * FROM mv")) == \
        sorted(host.query("SELECT * FROM mv")) == [(1, small, big)]
    _mirror(both, f"DELETE FROM t WHERE v = {big}")
    assert sorted(dev.query("SELECT * FROM mv")) == [(1, small, 0)]


def test_minmax_same_column_share_one_multiset():
    from risingwave_tpu.expr import AggCall, InputRef
    from risingwave_tpu.core import dtypes as T
    from risingwave_tpu.ops.device_agg import _build_sql_spec
    calls = [AggCall("min", InputRef(1, T.INT64)),
             AggCall("max", InputRef(1, T.INT64)),
             AggCall("max", InputRef(2, T.INT64))]
    spec = _build_sql_spec(calls)
    assert len(spec.minputs) == 2   # v-column shared, second column separate


@pytest.mark.parametrize("device", ["on", 8])
def test_device_minmax_recovery(tmp_path, device):
    d = str(tmp_path)
    db = Database(data_dir=d, device=device)
    db.run("CREATE TABLE t (k INT, v BIGINT)")
    db.run("CREATE MATERIALIZED VIEW mv AS SELECT k, max(v) AS m "
           "FROM t GROUP BY k")
    db.run("INSERT INTO t VALUES (1, 10), (1, 20), (2, 7)")
    db2 = Database(data_dir=d, device=device)
    db2.run("DELETE FROM t WHERE v = 20")   # retract the recovered max
    assert sorted(db2.query("SELECT * FROM mv")) == [(1, 10), (2, 7)]


@pytest.mark.parametrize("device", DEVICES[1:])
def test_device_join_matches_host_random_workload(device):
    """INNER equi-join under random inserts/deletes/updates: device
    (sorted-multimap probe, sharded two-sided all_to_all) vs host oracle."""
    rng = np.random.default_rng(23)
    host, dev = _mk("off"), _mk(device)
    both = (host, dev)
    _mirror(both, "CREATE TABLE a (k INT, s VARCHAR, x BIGINT)")
    _mirror(both, "CREATE TABLE b (k INT, y BIGINT)")
    _mirror(both, "CREATE MATERIALIZED VIEW j AS SELECT a.k, a.s, a.x, b.y "
            "FROM a JOIN b ON a.k = b.k")
    _mirror(both, "CREATE MATERIALIZED VIEW jc AS SELECT a.k, b.y "
            "FROM a JOIN b ON a.k = b.k AND a.x < b.y")
    for _ in range(3):
        arows, brows = [], []
        for _ in range(25):
            k = "NULL" if rng.random() < 0.1 else int(rng.integers(0, 8))
            arows.append(f"({k}, 's{int(rng.integers(0, 3))}', "
                         f"{int(rng.integers(0, 50))})")
            k2 = "NULL" if rng.random() < 0.1 else int(rng.integers(0, 8))
            brows.append(f"({k2}, {int(rng.integers(0, 50))})")
        _mirror(both, f"INSERT INTO a VALUES {', '.join(arows)}")
        _mirror(both, f"INSERT INTO b VALUES {', '.join(brows)}")
        _mirror(both, f"DELETE FROM a WHERE x > {int(rng.integers(25, 45))}")
        _mirror(both, f"UPDATE b SET y = y + 3 WHERE k = "
                f"{int(rng.integers(0, 8))}")
    for mv in ("j", "jc"):
        a = sorted(host.query(f"SELECT * FROM {mv}"), key=repr)
        b = sorted(dev.query(f"SELECT * FROM {mv}"), key=repr)
        assert a == b, mv
    assert len(host.query("SELECT * FROM j")) > 0


@pytest.mark.parametrize("device", ["on", 8])
def test_device_join_recovery(tmp_path, device):
    d = str(tmp_path)
    db = Database(data_dir=d, device=device)
    db.run("CREATE TABLE a (k INT, x BIGINT)")
    db.run("CREATE TABLE b (k INT, y BIGINT)")
    db.run("CREATE MATERIALIZED VIEW j AS SELECT a.k, a.x, b.y "
           "FROM a JOIN b ON a.k = b.k")
    db.run("INSERT INTO a VALUES (1, 10), (2, 20)")
    db.run("INSERT INTO b VALUES (1, 100), (2, 200), (1, 101)")
    before = sorted(db.query("SELECT * FROM j"))
    db2 = Database(data_dir=d, device=device)
    assert sorted(db2.query("SELECT * FROM j")) == before
    db2.run("DELETE FROM b WHERE y = 100")   # retract against recovered state
    db2.run("INSERT INTO a VALUES (2, 21)")
    out = sorted(db2.query("SELECT * FROM j"))
    oracle = sorted(db2.query(
        "SELECT a.k, a.x, b.y FROM a JOIN b ON a.k = b.k"))
    assert out == oracle == [(1, 10, 101), (2, 20, 200), (2, 21, 200)]


def test_device_join_net_zero_reinsert_keeps_row_cache():
    """delete + identical re-insert in one epoch nets to zero on device;
    the host row cache must NOT evict (the row is still live in state)."""
    from risingwave_tpu.core import Op, Schema, StreamChunk, dtypes as T
    from risingwave_tpu.core.epoch import EpochPair
    from risingwave_tpu.ops.device_join import DeviceHashJoinExecutor
    from risingwave_tpu.ops.executor import Executor
    from risingwave_tpu.ops.message import Barrier

    class Stub(Executor):
        pass

    S = Schema.of(("k", T.INT64), ("v", T.INT64))
    j = DeviceHashJoinExecutor(Stub(S), Stub(S), [0], [0])
    bar = lambda e: Barrier(EpochPair(e, e - 1))
    j._process_chunk("a", StreamChunk.from_rows(
        S.dtypes, [(Op.INSERT, (1, 10))]))
    j._process_chunk("b", StreamChunk.from_rows(
        S.dtypes, [(Op.INSERT, (1, 100))]))
    list(j._on_barrier(bar(1)))
    j._process_chunk("a", StreamChunk.from_rows(
        S.dtypes, [(Op.DELETE, (1, 10)), (Op.INSERT, (1, 10))]))
    list(j._on_barrier(bar(2)))
    j._process_chunk("b", StreamChunk.from_rows(
        S.dtypes, [(Op.INSERT, (1, 101))]))
    out = list(j._on_barrier(bar(3)))
    rows = [r for ch in out for _, r in ch.op_rows()]
    assert rows == [(1, 10, 1, 101)], rows


def test_planner_lowers_eligible_fragment_to_device():
    """The dispatch seam actually engages: the MV's executor tree contains a
    DeviceHashAggExecutor when the device path is on (grep-proof for
    VERDICT missing-item #1)."""
    from risingwave_tpu.ops import DeviceHashAggExecutor, HashAggExecutor
    db = _mk("on")
    db.run("CREATE TABLE t (k INT, v BIGINT, s VARCHAR)")
    db.run("CREATE MATERIALIZED VIEW mv AS SELECT k, sum(v) FROM t GROUP BY k")
    # min/max is gated off until retractable device min/max lands
    db.run("CREATE MATERIALIZED VIEW mv2 AS SELECT k, string_agg(s) "
           "FROM t GROUP BY k")

    def find(ex, cls):
        seen = []
        stack = [ex]
        while stack:
            e = stack.pop()
            if isinstance(e, cls):
                seen.append(e)
            for attr in ("input", "port", "left", "right"):
                child = getattr(e, attr, None)
                if child is not None:
                    stack.append(child)
        return seen

    mat1 = db.catalog.get("mv").runtime["shared"].upstream
    mat2 = db.catalog.get("mv2").runtime["shared"].upstream
    assert find(mat1, DeviceHashAggExecutor), "eligible agg not lowered"
    assert not find(mat1, HashAggExecutor)
    assert find(mat2, HashAggExecutor), "ineligible agg must stay on host"


def test_key_codecs():
    from risingwave_tpu.core import dtypes as T
    from risingwave_tpu.core.chunk import Column
    from risingwave_tpu.device.key_codec import (DictCodec, PackCodec,
                                                 make_codec)
    # narrow tuple -> PackCodec, lossless roundtrip incl. NULLs + negatives
    c = make_codec([T.INT32, T.BOOLEAN, T.INT16])
    assert isinstance(c, PackCodec)
    rows = [(5, True, -3), (-2**31, False, 32767), (None, None, 0),
            (2**31 - 1, True, -32768)]
    keys = c.encode_rows(rows)
    assert len(set(keys.tolist())) == len(rows)
    assert c.decode(keys) == rows
    # wide tuple -> DictCodec with decode dictionary
    c2 = make_codec([T.INT64, T.VARCHAR])
    assert isinstance(c2, DictCodec)
    rows2 = [(1, "a"), (2, None), (None, "x"), (2**63 - 1, "edge")]
    cols = [Column.from_list(T.INT64, [r[0] for r in rows2]),
            Column.from_list(T.VARCHAR, [r[1] for r in rows2])]
    k2 = c2.encode_columns(cols)
    c2.observe_columns(k2, cols)
    assert c2.decode(k2) == rows2
