"""Stateful executor tests: HashAgg, HashJoin, TopN, OverWindow, HopWindow,
Dedup — including the MV-result oracle: streaming result == batch recompute
over the same input (the reference's core correctness oracle, SURVEY.md §4)."""
from decimal import Decimal

import numpy as np
import pytest

from risingwave_tpu.core import Column, Op, Schema, StreamChunk, dtypes as T
from risingwave_tpu.core.dtypes import parse_interval
from risingwave_tpu.connectors import ListReader
from risingwave_tpu.expr import AggCall, InputRef, Literal, build_func
from risingwave_tpu.ops import (
    AppendOnlyDedupExecutor, BarrierInjector, BatchScan, ConflictBehavior,
    HashAggExecutor, HashJoinExecutor, HopWindowExecutor, JoinType,
    MaterializeExecutor, OverWindowExecutor, SimpleAggExecutor, SourceExecutor,
    TopNExecutor, WindowFuncCall,
)
from risingwave_tpu.runtime import StreamJob
from risingwave_tpu.state import MemoryStateStore, StateTable


def run_pipeline(chunks, schema, build, pk, conflict=ConflictBehavior.OVERWRITE):
    """source(chunks) -> build(source) -> materialize; returns MV rows."""
    store = MemoryStateStore()
    injector = BarrierInjector()
    src = SourceExecutor(schema, ListReader(chunks), injector)
    node = build(src, store)
    table = StateTable(store, 1, node.schema.dtypes, list(pk))
    mat = MaterializeExecutor(node, table, conflict)
    job = StreamJob(mat, injector, store)
    job.run_until_idle()
    return sorted(BatchScan(table, None).rows()), job


SCHEMA_KV = Schema.of(("k", T.INT64), ("v", T.INT64))


def chunks_kv(*op_rows_lists):
    return [StreamChunk.from_rows(SCHEMA_KV.dtypes, list(orl))
            for orl in op_rows_lists]


class TestHashAgg:
    def test_count_sum_grouped(self):
        chunks = chunks_kv(
            [(Op.INSERT, (1, 10)), (Op.INSERT, (1, 20)), (Op.INSERT, (2, 5))],
            [(Op.INSERT, (2, 7)), (Op.DELETE, (1, 10))],
        )
        def build(src, store):
            return HashAggExecutor(
                src, [0],
                [AggCall("count"), AggCall("sum", InputRef(1, T.INT64)),
                 AggCall("min", InputRef(1, T.INT64))],
                state_table=StateTable(store, 10, [T.INT64, T.BYTEA], [0]))
        rows, _ = run_pipeline(chunks, SCHEMA_KV, build, pk=(0,))
        assert rows == [(1, 1, Decimal(20), 20), (2, 2, Decimal(12), 5)]

    def test_group_deletion_emits_delete(self):
        chunks = chunks_kv(
            [(Op.INSERT, (1, 10))],
            [(Op.DELETE, (1, 10))],
        )
        def build(src, store):
            return HashAggExecutor(src, [0], [AggCall("count")])
        rows, _ = run_pipeline(chunks, SCHEMA_KV, build, pk=(0,))
        assert rows == []

    def test_updates_collapse(self):
        chunks = chunks_kv(
            [(Op.INSERT, (1, 10))],
            [(Op.UPDATE_DELETE, (1, 10)), (Op.UPDATE_INSERT, (1, 99))],
        )
        def build(src, store):
            return HashAggExecutor(src, [0], [AggCall("sum", InputRef(1, T.INT64)),
                                              AggCall("max", InputRef(1, T.INT64))])
        rows, _ = run_pipeline(chunks, SCHEMA_KV, build, pk=(0,))
        assert rows == [(1, Decimal(99), 99)]

    def test_distinct_agg(self):
        chunks = chunks_kv(
            [(Op.INSERT, (1, 7)), (Op.INSERT, (1, 7)), (Op.INSERT, (1, 9))])
        def build(src, store):
            return HashAggExecutor(
                src, [0], [AggCall("count", InputRef(1, T.INT64), distinct=True),
                           AggCall("count")])
        rows, _ = run_pipeline(chunks, SCHEMA_KV, build, pk=(0,))
        assert rows == [(1, 2, 3)]

    def test_agg_state_recovery(self):
        """Kill-and-restart: rebuild groups from the state table."""
        store = MemoryStateStore()
        injector = BarrierInjector()
        src = SourceExecutor(SCHEMA_KV, ListReader(chunks_kv(
            [(Op.INSERT, (1, 10)), (Op.INSERT, (1, 5))])), injector)
        st = StateTable(store, 10, [T.INT64, T.BYTEA], [0])
        agg = HashAggExecutor(src, [0], [AggCall("sum", InputRef(1, T.INT64))],
                              state_table=st)
        table = StateTable(store, 1, agg.schema.dtypes, [0])
        job = StreamJob(MaterializeExecutor(agg, table,
                                            ConflictBehavior.OVERWRITE),
                        injector, store)
        job.run_until_idle()
        # "restart": new executor over the same store + more data
        injector2 = BarrierInjector()
        src2 = SourceExecutor(SCHEMA_KV, ListReader(chunks_kv(
            [(Op.INSERT, (1, 1))])), injector2)
        st2 = StateTable(store, 10, [T.INT64, T.BYTEA], [0])
        agg2 = HashAggExecutor(src2, [0], [AggCall("sum", InputRef(1, T.INT64))],
                               state_table=st2)
        table2 = StateTable(store, 1, agg2.schema.dtypes, [0])
        job2 = StreamJob(MaterializeExecutor(agg2, table2,
                                             ConflictBehavior.OVERWRITE),
                         injector2, store)
        job2.run_until_idle()
        assert sorted(BatchScan(table2, None).rows()) == [(1, Decimal(16))]


class TestSimpleAgg:
    def test_global_count_empty_and_updates(self):
        chunks = chunks_kv([(Op.INSERT, (1, 10)), (Op.INSERT, (2, 20))])
        def build(src, store):
            return SimpleAggExecutor(src, [AggCall("count"),
                                           AggCall("sum", InputRef(1, T.INT64))])
        rows, _ = run_pipeline(chunks, SCHEMA_KV, build, pk=())
        assert rows == [(2, Decimal(30))]

    def test_zero_rows_emits_initial(self):
        def build(src, store):
            return SimpleAggExecutor(src, [AggCall("count")])
        rows, _ = run_pipeline([], SCHEMA_KV, build, pk=())
        assert rows == [(0,)]


AB_SCHEMA = Schema.of(("a_k", T.INT64), ("a_v", T.VARCHAR))
CD_SCHEMA = Schema.of(("b_k", T.INT64), ("b_v", T.VARCHAR))


def run_join(l_chunks, r_chunks, jt, pk, l_keys=(0,), r_keys=(0,), cond=None):
    store = MemoryStateStore()
    injector = BarrierInjector()
    lsrc = SourceExecutor(AB_SCHEMA, ListReader(l_chunks), injector)
    rsrc = SourceExecutor(CD_SCHEMA, ListReader(r_chunks), injector)
    join = HashJoinExecutor(lsrc, rsrc, list(l_keys), list(r_keys), jt,
                            condition=cond)
    table = StateTable(store, 1, join.schema.dtypes, list(pk))
    mat = MaterializeExecutor(join, table, ConflictBehavior.OVERWRITE)
    job = StreamJob(mat, injector, store)
    job.run_until_idle()
    return sorted(BatchScan(table, None).rows(),
                  key=lambda r: tuple((x is None, x) for x in r))


def ab(*rows):
    return [StreamChunk.from_rows(AB_SCHEMA.dtypes, [(op, r) for op, r in rows])]


class TestHashJoin:
    def test_inner_join(self):
        out = run_join(ab((Op.INSERT, (1, "l1")), (Op.INSERT, (2, "l2"))),
                       ab((Op.INSERT, (1, "r1")), (Op.INSERT, (3, "r3"))),
                       JoinType.INNER, pk=(0, 1, 2, 3))
        assert out == [(1, "l1", 1, "r1")]

    def test_inner_join_retraction(self):
        l = [StreamChunk.from_rows(AB_SCHEMA.dtypes,
                                   [(Op.INSERT, (1, "l1"))]),
             StreamChunk.from_rows(AB_SCHEMA.dtypes,
                                   [(Op.DELETE, (1, "l1"))])]
        out = run_join(l, ab((Op.INSERT, (1, "r1"))), JoinType.INNER,
                       pk=(0, 1, 2, 3))
        assert out == []

    def test_left_outer_null_padding_and_retract(self):
        # left arrives first -> null-padded row; right match retracts it
        l = ab((Op.INSERT, (1, "l1")), (Op.INSERT, (2, "l2")))
        r = ab((Op.INSERT, (1, "r1")))
        out = run_join(l, r, JoinType.LEFT_OUTER, pk=(0, 1, 2, 3))
        assert out == [(1, "l1", 1, "r1"), (2, "l2", None, None)]

    def test_full_outer(self):
        out = run_join(ab((Op.INSERT, (1, "l1"))),
                       ab((Op.INSERT, (2, "r2"))),
                       JoinType.FULL_OUTER, pk=(0, 1, 2, 3))
        assert out == [(1, "l1", None, None), (None, None, 2, "r2")]

    def test_left_semi(self):
        out = run_join(ab((Op.INSERT, (1, "l1")), (Op.INSERT, (2, "l2"))),
                       ab((Op.INSERT, (1, "r1")), (Op.INSERT, (1, "r1b"))),
                       JoinType.LEFT_SEMI, pk=(0, 1))
        assert out == [(1, "l1")]

    def test_left_anti_with_flip(self):
        l = ab((Op.INSERT, (1, "l1")), (Op.INSERT, (2, "l2")))
        r = [StreamChunk.from_rows(CD_SCHEMA.dtypes, [(Op.INSERT, (1, "r1"))]),
             StreamChunk.from_rows(CD_SCHEMA.dtypes, [(Op.DELETE, (1, "r1"))])]
        out = run_join(l, r, JoinType.LEFT_ANTI, pk=(0, 1))
        # r1 deleted again -> both left rows unmatched
        assert out == [(1, "l1"), (2, "l2")]

    def test_join_condition(self):
        cond = build_func("not_equal", [InputRef(1, T.VARCHAR),
                                        InputRef(3, T.VARCHAR)])
        out = run_join(ab((Op.INSERT, (1, "x")), (Op.INSERT, (1, "y"))),
                       ab((Op.INSERT, (1, "x"))),
                       JoinType.INNER, pk=(0, 1, 2, 3), cond=cond)
        assert out == [(1, "y", 1, "x")]


class TestTopN:
    def test_top2(self):
        chunks = chunks_kv(
            [(Op.INSERT, (1, 50)), (Op.INSERT, (2, 30)), (Op.INSERT, (3, 70))],
            [(Op.INSERT, (4, 90))],
            [(Op.DELETE, (3, 70))],
        )
        def build(src, store):
            return TopNExecutor(src, order_by=[(1, True)], limit=2)
        rows, _ = run_pipeline(chunks, SCHEMA_KV, build, pk=(0,))
        # final data: 50,30,90 -> top2 by v desc: 90, 50
        assert sorted(rows) == [(1, 50), (4, 90)]

    def test_topn_offset(self):
        chunks = chunks_kv([(Op.INSERT, (i, i * 10)) for i in range(1, 6)])
        def build(src, store):
            return TopNExecutor(src, order_by=[(1, True)], limit=2, offset=1)
        rows, _ = run_pipeline(chunks, SCHEMA_KV, build, pk=(0,))
        # sorted desc: 50,40,30,20,10 -> skip 1, take 2 -> 40,30
        assert sorted(rows) == [(3, 30), (4, 40)]

    def test_group_topn(self):
        schema = Schema.of(("g", T.INT64), ("k", T.INT64), ("v", T.INT64))
        chunks = [StreamChunk.from_rows(schema.dtypes, [
            (Op.INSERT, (1, 1, 10)), (Op.INSERT, (1, 2, 30)),
            (Op.INSERT, (1, 3, 20)), (Op.INSERT, (2, 4, 5))])]
        store = MemoryStateStore()
        injector = BarrierInjector()
        src = SourceExecutor(schema, ListReader(chunks), injector)
        topn = TopNExecutor(src, order_by=[(2, True)], limit=1, group_key=[0])
        table = StateTable(store, 1, schema.dtypes, [0, 1])
        job = StreamJob(MaterializeExecutor(topn, table,
                                            ConflictBehavior.OVERWRITE),
                        injector, store)
        job.run_until_idle()
        assert sorted(BatchScan(table, None).rows()) == [(1, 2, 30), (2, 4, 5)]


class TestDedup:
    def test_append_only_dedup(self):
        chunks = chunks_kv(
            [(Op.INSERT, (1, 10)), (Op.INSERT, (1, 99)), (Op.INSERT, (2, 20))])
        def build(src, store):
            return AppendOnlyDedupExecutor(src, [0])
        rows, _ = run_pipeline(chunks, SCHEMA_KV, build, pk=(0,))
        assert rows == [(1, 10), (2, 20)]


class TestHopWindow:
    def test_expansion(self):
        schema = Schema.of(("id", T.INT64), ("ts", T.TIMESTAMP))
        # 10s window, 5s hop -> each row in 2 windows
        chunks = [StreamChunk.from_rows(schema.dtypes,
                                        [(Op.INSERT, (1, 7_000_000))])]
        store = MemoryStateStore()
        injector = BarrierInjector()
        src = SourceExecutor(schema, ListReader(chunks), injector)
        hop = HopWindowExecutor(src, 1, parse_interval("5 seconds"),
                                parse_interval("10 seconds"))
        table = StateTable(store, 1, hop.schema.dtypes, [0, 2])
        job = StreamJob(MaterializeExecutor(hop, table,
                                            ConflictBehavior.OVERWRITE),
                        injector, store)
        job.run_until_idle()
        rows = sorted(BatchScan(table, None).rows())
        assert rows == [
            (1, 7_000_000, 0, 10_000_000),
            (1, 7_000_000, 5_000_000, 15_000_000),
        ]


class TestOverWindow:
    def test_row_number_rank(self):
        schema = Schema.of(("g", T.INT64), ("v", T.INT64))
        chunks = [StreamChunk.from_rows(schema.dtypes, [
            (Op.INSERT, (1, 30)), (Op.INSERT, (1, 10)), (Op.INSERT, (1, 30)),
            (Op.INSERT, (2, 5))])]
        store = MemoryStateStore()
        injector = BarrierInjector()
        src = SourceExecutor(schema, ListReader(chunks), injector)
        ow = OverWindowExecutor(
            src, partition_by=[0], order_by=[(1, False)],
            calls=[WindowFuncCall("row_number"), WindowFuncCall("rank"),
                   WindowFuncCall("sum", InputRef(1, T.INT64))])
        table = StateTable(store, 1, ow.schema.dtypes, [0, 1, 2])
        job = StreamJob(MaterializeExecutor(ow, table,
                                            ConflictBehavior.OVERWRITE),
                        injector, store)
        job.run_until_idle()
        rows = sorted(BatchScan(table, None).rows())
        # g=1 ordered: 10,30,30 -> rn 1,2,3; rank 1,2,2; running sums 10,40,70
        assert rows == [
            (1, 10, 1, 1, Decimal(10)),
            (1, 30, 2, 2, Decimal(40)),
            (1, 30, 3, 2, Decimal(70)),
            (2, 5, 1, 1, Decimal(5)),
        ]

    def test_lag_and_retraction(self):
        schema = Schema.of(("g", T.INT64), ("v", T.INT64))
        chunks = [
            StreamChunk.from_rows(schema.dtypes, [
                (Op.INSERT, (1, 10)), (Op.INSERT, (1, 20)), (Op.INSERT, (1, 30))]),
            StreamChunk.from_rows(schema.dtypes, [(Op.DELETE, (1, 20))]),
        ]
        store = MemoryStateStore()
        injector = BarrierInjector()
        src = SourceExecutor(schema, ListReader(chunks), injector)
        ow = OverWindowExecutor(src, [0], [(1, False)],
                                [WindowFuncCall("lag", InputRef(1, T.INT64))])
        table = StateTable(store, 1, ow.schema.dtypes, [0, 1])
        job = StreamJob(MaterializeExecutor(ow, table,
                                            ConflictBehavior.OVERWRITE),
                        injector, store)
        job.run_until_idle()
        rows = sorted(BatchScan(table, None).rows())
        assert rows == [(1, 10, None), (1, 30, 10)]


class TestMvParityOracle:
    """Streaming MV == batch recompute — the core oracle (SURVEY.md §4)."""

    def test_agg_parity_random_stream(self):
        rng = np.random.default_rng(0)
        rows = []
        live = []
        op_rows = []
        for _ in range(500):
            if live and rng.random() < 0.3:
                i = rng.integers(0, len(live))
                op_rows.append((Op.DELETE, live.pop(i)))
            else:
                r = (int(rng.integers(0, 10)), int(rng.integers(0, 100)))
                live.append(r)
                op_rows.append((Op.INSERT, r))
        chunks = [StreamChunk.from_rows(SCHEMA_KV.dtypes, op_rows[i:i + 97])
                  for i in range(0, len(op_rows), 97)]
        def build(src, store):
            return HashAggExecutor(
                src, [0], [AggCall("count"), AggCall("sum", InputRef(1, T.INT64)),
                           AggCall("min", InputRef(1, T.INT64)),
                           AggCall("max", InputRef(1, T.INT64))])
        rows_out, _ = run_pipeline(chunks, SCHEMA_KV, build, pk=(0,))
        # batch recompute from surviving rows
        expect = {}
        for k, v in live:
            c, s, mn, mx = expect.get(k, (0, Decimal(0), None, None))
            expect[k] = (c + 1, s + v,
                         v if mn is None else min(mn, v),
                         v if mx is None else max(mx, v))
        assert rows_out == sorted((k,) + t for k, t in expect.items())

    def test_join_parity_random_stream(self):
        rng = np.random.default_rng(1)
        l_live, r_live = [], []
        l_ops, r_ops = [], []
        seen = set()  # the stream-key contract: rows are pk-unique per side
        for _ in range(300):
            side = rng.random() < 0.5
            live, ops = (l_live, l_ops) if side else (r_live, r_ops)
            if live and rng.random() < 0.25:
                i = rng.integers(0, len(live))
                row = live.pop(i)
                seen.discard((side, row))
                ops.append((Op.DELETE, row))
            else:
                r = (int(rng.integers(0, 8)), f"s{int(rng.integers(0, 1000))}")
                if (side, r) in seen:
                    continue
                seen.add((side, r))
                live.append(r)
                ops.append((Op.INSERT, r))
        lc = [StreamChunk.from_rows(AB_SCHEMA.dtypes, l_ops[i:i + 53])
              for i in range(0, len(l_ops), 53)]
        rc = [StreamChunk.from_rows(CD_SCHEMA.dtypes, r_ops[i:i + 53])
              for i in range(0, len(r_ops), 53)]
        out = run_join(lc, rc, JoinType.INNER, pk=(0, 1, 2, 3))
        expect = sorted((lk, lv, rk, rv) for lk, lv in l_live
                        for rk, rv in r_live if lk == rk)
        # dedup: identical rows collapse in the MV (pk covers all cols)
        assert out == sorted(set(expect))


class TestNullJoinKeys:
    """SQL NULL semantics: NULL join keys match nothing (not even NULL)."""

    def test_inner_null_keys_never_match(self):
        out = run_join(ab((Op.INSERT, (None, "l1")), (Op.INSERT, (1, "l2"))),
                       ab((Op.INSERT, (None, "r1")), (Op.INSERT, (1, "r2"))),
                       JoinType.INNER, pk=(0, 1, 2, 3))
        assert out == [(1, "l2", 1, "r2")]

    def test_left_outer_null_key_is_unmatched(self):
        out = run_join(ab((Op.INSERT, (None, "l1"))),
                       ab((Op.INSERT, (None, "r1"))),
                       JoinType.LEFT_OUTER, pk=(0, 1, 2, 3))
        assert out == [(None, "l1", None, None)]

    def test_anti_null_key_emits(self):
        out = run_join(ab((Op.INSERT, (None, "l1"))),
                       ab((Op.INSERT, (None, "r1"))),
                       JoinType.LEFT_ANTI, pk=(0, 1))
        assert out == [(None, "l1")]

    def test_null_key_delete_roundtrip(self):
        l = [StreamChunk.from_rows(AB_SCHEMA.dtypes,
                                   [(Op.INSERT, (None, "l1"))]),
             StreamChunk.from_rows(AB_SCHEMA.dtypes,
                                   [(Op.DELETE, (None, "l1"))])]
        out = run_join(l, ab((Op.INSERT, (1, "r1"))), JoinType.LEFT_OUTER,
                       pk=(0, 1, 2, 3))
        assert out == []


class TestChunkOverflow:
    """Emission larger than max_chunk_size must not drop rows."""

    def test_join_fanout_exceeds_chunk_size(self):
        n = 40
        l = [StreamChunk.from_rows(AB_SCHEMA.dtypes,
                                   [(Op.INSERT, (1, f"l{i}")) for i in range(n)])]
        r = [StreamChunk.from_rows(CD_SCHEMA.dtypes,
                                   [(Op.INSERT, (1, f"r{i}")) for i in range(n)])]
        store = MemoryStateStore()
        injector = BarrierInjector()
        lsrc = SourceExecutor(AB_SCHEMA, ListReader(l), injector)
        rsrc = SourceExecutor(CD_SCHEMA, ListReader(r), injector)
        join = HashJoinExecutor(lsrc, rsrc, [0], [0], JoinType.INNER,
                                max_chunk_size=16)  # 40*40 = 1600 outputs
        table = StateTable(store, 1, join.schema.dtypes, [0, 1, 2, 3])
        mat = MaterializeExecutor(join, table, ConflictBehavior.OVERWRITE)
        job = StreamJob(mat, injector, store)
        job.run_until_idle()
        assert len(BatchScan(table, None).rows()) == n * n
