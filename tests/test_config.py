"""3-tier config system (SURVEY A6): TOML node config, ALTER SYSTEM
parameters, SET/SHOW session variables. Reference:
src/common/src/config.rs:137, system_param/mod.rs:97, session_config/."""
import pytest

from risingwave_tpu.config import NodeConfig, SystemParams
from risingwave_tpu.sql import Database


def test_node_config_from_toml(tmp_path):
    p = tmp_path / "rw.toml"
    p.write_text("""
[streaming]
chunk_size = 512
checkpoint_frequency = 3

[storage]
block_cache_blocks = 128
""")
    cfg = NodeConfig.from_toml(str(p))
    assert cfg.streaming.chunk_size == 512
    assert cfg.streaming.checkpoint_frequency == 3
    assert cfg.storage.block_cache_blocks == 128
    assert cfg.streaming.barrier_interval_ms == 1000   # default kept


def test_node_config_rejects_unknown_keys(tmp_path):
    p = tmp_path / "rw.toml"
    p.write_text("[streaming]\nchunk_sz = 1\n")
    with pytest.raises(ValueError, match="unknown config key"):
        NodeConfig.from_toml(str(p))
    p.write_text("[nonsense]\nx = 1\n")
    with pytest.raises(ValueError, match="unknown config sections"):
        NodeConfig.from_toml(str(p))


def test_database_accepts_config_file(tmp_path):
    p = tmp_path / "rw.toml"
    p.write_text("[streaming]\ncheckpoint_frequency = 4\n")
    db = Database(config=str(p))
    assert db.injector.checkpoint_frequency == 4
    assert db.system_params.get("checkpoint_frequency") == 4


def test_session_vars_set_show():
    db = Database()
    assert db.run("SHOW timezone") == ["UTC"]
    db.run("SET timezone TO 'America/New_York'")
    assert db.run("SHOW timezone") == ["America/New_York"]
    db.run("SET extra_float_digits = 3")
    assert db.run("SHOW extra_float_digits") == [3]
    allv = db.run("SHOW ALL")[0]
    assert ("timezone", "America/New_York") in allv
    with pytest.raises(ValueError, match="unrecognized"):
        db.run("SET no_such_var = 1")


def test_alter_system_applies_and_persists(tmp_path):
    d = str(tmp_path)
    db = Database(data_dir=d)
    db.run("ALTER SYSTEM SET checkpoint_frequency = 5")
    assert db.injector.checkpoint_frequency == 5
    assert db.run("SHOW checkpoint_frequency") == [5]
    params = dict(db.run("SHOW PARAMETERS")[0])
    assert params["checkpoint_frequency"] == 5

    db2 = Database(data_dir=d)              # replayed from the DDL log
    assert db2.injector.checkpoint_frequency == 5
    with pytest.raises(ValueError, match="unknown system parameter"):
        db2.run("ALTER SYSTEM SET no_such = 1")


def test_system_params_coercion():
    sp = SystemParams()
    assert sp.set("pause_on_next_bootstrap", "true") is True
    assert sp.set("checkpoint_frequency", "7") == 7
    with pytest.raises(ValueError):
        sp.get("bogus")
    with pytest.raises(ValueError, match=">= 1"):
        sp.set("checkpoint_frequency", 0)


def test_set_accepts_exponent_literal():
    db = Database()
    db.run("SET extra_float_digits = 1e1")
    assert db.run("SHOW extra_float_digits") == [10]


def test_ctor_overrides_config_file(tmp_path):
    p = tmp_path / "rw.toml"
    p.write_text("[streaming]\ncheckpoint_frequency = 4\n")
    db = Database(config=str(p), checkpoint_frequency=1)
    assert db.injector.checkpoint_frequency == 1


def test_device_section_typo_fails_even_when_off(tmp_path):
    p = tmp_path / "rw.toml"
    p.write_text("[device]\nmode = 'off'\ncapcity = 9\n")
    from risingwave_tpu.config import NodeConfig
    with pytest.raises(ValueError, match="unknown config key"):
        NodeConfig.from_toml(str(p))
