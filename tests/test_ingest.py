"""Host-ingest staging for fused jobs (device/ingest.py, ISSUE 15).

The contract under test: a fused MV whose sources are HOST-FED through
the staging pipeline (poll -> pack into reused buffers -> double-buffered
H2D -> IngestNode feed) is BIT-IDENTICAL — including row order — to the
same MV on the device-datagen fused path, at 1 and 8 shards, with
admission control and the fault-tolerance machinery engaged. (The host
EXECUTOR path is compared order-insensitively, as every fused-vs-host
test in this repo always has: the host MV's iteration order was never
part of the engine's bit-identity contract — the fused family's row
order is.)
"""
import os

import numpy as np
import pytest

from risingwave_tpu.config import DeviceConfig
from risingwave_tpu.sql import Database

N = 4096
CHUNK = 32          # fused epoch = 64 * CHUNK = 2048 events

BID_SRC = ("CREATE SOURCE bid (auction BIGINT, bidder BIGINT, price BIGINT,"
           " channel VARCHAR, url VARCHAR, date_time TIMESTAMP,"
           " extra VARCHAR) WITH (connector='nexmark',"
           " nexmark.table='bid', nexmark.max.events='{n}',"
           " nexmark.chunk.size='{c}'{x})")
AUCTION_SRC = ("CREATE SOURCE auction (id BIGINT, item_name VARCHAR,"
               " description VARCHAR, initial_bid BIGINT, reserve BIGINT,"
               " date_time TIMESTAMP, expires TIMESTAMP, seller BIGINT,"
               " category BIGINT, extra VARCHAR) WITH (connector='nexmark',"
               " nexmark.table='auction', nexmark.max.events='{n}',"
               " nexmark.chunk.size='{c}'{x})")
Q1_MV = ("CREATE MATERIALIZED VIEW q1a AS SELECT bidder,"
         " count(*) AS n, sum(price) AS dol, max(price) AS top"
         " FROM bid GROUP BY bidder")
Q3_MV = ("CREATE MATERIALIZED VIEW q3a AS SELECT b.auction, b.price,"
         " a.seller, a.category FROM bid b JOIN auction a"
         " ON b.auction = a.id WHERE b.price > 500")
Q5_MV = """CREATE MATERIALIZED VIEW q5 AS
SELECT AuctionBids.auction, AuctionBids.num FROM (
    SELECT bid.auction, count(*) AS num, window_start AS starttime
    FROM HOP(bid, date_time, INTERVAL '2' SECOND, INTERVAL '10' SECOND)
    GROUP BY window_start, bid.auction
) AS AuctionBids
JOIN (
    SELECT max(CountBids.num) AS maxn, CountBids.starttime_c
    FROM (
        SELECT count(*) AS num, window_start AS starttime_c
        FROM HOP(bid, date_time, INTERVAL '2' SECOND, INTERVAL '10' SECOND)
        GROUP BY bid.auction, window_start
    ) AS CountBids
    GROUP BY CountBids.starttime_c
) AS MaxBids
ON AuctionBids.starttime = MaxBids.starttime_c
   AND AuctionBids.num >= MaxBids.maxn"""


def _drive(db, n=N):
    for _ in range(n // (64 * CHUNK) + 3):
        db.tick()


def _run(mv_sql, name, srcs, *, ingest, shards=1, capacity=512, n=N,
         data_dir=None, aot=False, keep=False, src_opt=""):
    db = Database(device=DeviceConfig(capacity=capacity,
                                      host_ingest=ingest,
                                      mesh_shards=shards,
                                      aot_compile=aot),
                  data_dir=data_dir)
    for s in srcs:
        db.run(s.format(n=n, c=CHUNK, x=src_opt))
    db.run(mv_sql)
    job = db._fused[name]
    assert (job.ingest is not None) == (ingest or bool(src_opt))
    _drive(db, n)
    rows = db.query(f"SELECT * FROM {name}")
    return (rows, job, db) if keep else (rows, job, None)


@pytest.fixture(scope="module")
def q1_ref():
    """Device-datagen fused q1 — the established bit-identical family's
    reference rows (host-executor parity of this path is covered by
    test_fused_sql/test_mesh_fused)."""
    rows, _, _ = _run(Q1_MV, "q1a", [BID_SRC], ingest=False)
    return rows


# ---------------------------------------------------------------------------
# the surrogate feed itself
# ---------------------------------------------------------------------------


def test_surrogate_twin_bit_identical():
    """connectors/nexmark.gen_surrogates must equal the device generator
    value-for-value — the property the whole host-feed bit-identity
    stands on."""
    import jax.numpy as jnp
    from risingwave_tpu.connectors.nexmark import (NexmarkConfig,
                                                   gen_surrogates)
    from risingwave_tpu.device.nexmark_gen import GenCfg, gen_table
    ids = np.arange(0, 3000, dtype=np.int64)
    for kd in ("", "zipf:1.5"):
        cfg = NexmarkConfig(key_dist=kd)
        g = GenCfg.from_config(cfg)
        for table in ("person", "auction", "bid"):
            host = gen_surrogates(cfg, table, ids)
            dev = gen_table(g, table, jnp.asarray(ids))
            for col, h in host.items():
                assert h.dtype == np.int64
                assert np.array_equal(h, np.asarray(dev[col])), \
                    (kd, table, col)


def test_to_jax_masked_nullable_columns():
    """Arrow-seam satellite: nullable fixed-width columns cross with a
    validity mask + sentinel fill; the bare path's error names the
    remediation."""
    from risingwave_tpu.core import dtypes as T
    from risingwave_tpu.core.arrow import to_jax, to_jax_masked
    from risingwave_tpu.core.chunk import Column
    col = Column.from_list(T.INT64, [1, None, 3])
    with pytest.raises(ValueError, match="to_jax_masked"):
        to_jax(col)
    vals, valid = to_jax_masked(col, sentinel=-1)
    assert np.asarray(vals).tolist() == [1, -1, 3]
    assert np.asarray(valid).tolist() == [True, False, True]
    # all-valid fast path stays exact (and keeps the value buffer)
    full = Column.from_list(T.INT64, [7, 8])
    v2, m2 = to_jax_masked(full)
    assert np.asarray(v2).tolist() == [7, 8] and np.asarray(m2).all()
    with pytest.raises(ValueError, match="no device representation"):
        to_jax_masked(Column.from_list(T.VARCHAR, ["x"]))


# ---------------------------------------------------------------------------
# bit-identity: host-fed vs device-datagen (and vs host executor)
# ---------------------------------------------------------------------------


def test_q1_host_fed_bit_identity(q1_ref):
    got, job, db = _run(Q1_MV, "q1a", [BID_SRC], ingest=True, keep=True)
    assert got == q1_ref, "host-fed q1 diverged from device datagen " \
        "(bit-identity incl. row order)"
    st = job.ingest.stats()
    assert st["events"] == N and st["deferred"] == 0
    # host-executor parity (order-insensitive, the repo-wide contract)
    dbh = Database(device="off")
    dbh.run(BID_SRC.format(n=N, c=CHUNK, x=""))
    dbh.run(Q1_MV)
    _drive(dbh)
    assert sorted(got) == sorted(dbh.query("SELECT * FROM q1a"))
    # the observability surfaces know the new node and the new phases
    ea = db.run("EXPLAIN ANALYZE q1a")[0]
    assert "IngestNode" in str(ea)
    adm = db.query("SELECT * FROM rw_source_admission")
    assert any(r[0] == "bid" for r in adm)
    prof_rows = db.query("SELECT * FROM rw_epoch_profile")
    assert prof_rows
    for (_j, _s, _e, _sh, pack, h2d, pro, disp, exch, sync, dem,
         commit, wall) in prof_rows:
        assert pack + h2d + pro + disp + exch + sync + dem + commit \
            <= wall * 1.001 + 0.05


def test_q1_per_source_opt_in(q1_ref):
    """WITH (nexmark.ingest='host') arms host feed for one source
    without the global DeviceConfig knob."""
    got, job, _ = _run(Q1_MV, "q1a", [BID_SRC], ingest=False,
                       src_opt=", nexmark.ingest='host'")
    assert job.ingest is not None
    assert got == q1_ref


def test_q3_multi_source_multiplex(q1_ref):
    """Two independent sources concatenate into one fused dispatch per
    epoch; per-source provenance balances exactly."""
    ref, _, _ = _run(Q3_MV, "q3a", [BID_SRC, AUCTION_SRC], ingest=False)
    got, job, _ = _run(Q3_MV, "q3a", [BID_SRC, AUCTION_SRC], ingest=True)
    assert got == ref
    st = job.ingest.stats()
    assert set(st["sources"]) == {"bid", "auction"}
    assert all(v > 0 for v in st["sources"].values())
    # rows in == rows dispatched: per-source offered rows equal the
    # ingest nodes' dispatched rows_out, summed over the run
    dispatched = 0
    for i, node in enumerate(job.program.nodes):
        if getattr(node, "takes_feed", False):
            dispatched += job.program.node_stats(
                i, job._stat_totals).get("rows_out", 0)
    assert dispatched == sum(st["sources"].values())


def test_q5_host_fed_bit_identity():
    ref, _, _ = _run(Q5_MV, "q5", [BID_SRC], ingest=False)
    got, _, _ = _run(Q5_MV, "q5", [BID_SRC], ingest=True)
    assert got == ref


@pytest.mark.mesh
def test_mesh_host_fed_bit_identity(q1_ref):
    """8-shard host-fed == 1-shard device-datagen, incl. row order —
    per-shard H2D placement composing with the in-program exchange."""
    got, job, _ = _run(Q1_MV, "q1a", [BID_SRC], ingest=True, shards=8)
    assert job.mesh_shards == 8
    assert got == q1_ref
    ref3, _, _ = _run(Q3_MV, "q3a", [BID_SRC, AUCTION_SRC], ingest=False)
    got3, _, _ = _run(Q3_MV, "q3a", [BID_SRC, AUCTION_SRC], ingest=True,
                      shards=8)
    assert got3 == ref3


@pytest.mark.mesh
def test_per_shard_feed_placement(mesh8):
    """The staged device buffers are [n_shards, cap] arrays carrying the
    SAME vnode-block NamedSharding as every state array, each shard
    holding its contiguous event block — ingest lands directly on its
    chip, no post-transfer scatter."""
    from risingwave_tpu.device.ingest import feed_capacity
    from risingwave_tpu.parallel.mesh import state_sharding
    db = Database(device=DeviceConfig(capacity=512, host_ingest=True,
                                      mesh_shards=8))
    db.run(BID_SRC.format(n=N, c=CHUNK, x=""))
    db.run(Q1_MV)
    job = db._fused["q1a"]
    w, _p, _h = job.ingest.take(0)
    ee = job.program.epoch_events
    cap = feed_capacity(ee, 8)
    sh = state_sharding(job.program.mesh)
    (idx, src), = job.ingest.sources
    cnt, pk = w.feeds[idx][0], w.feeds[idx][1]
    assert pk.shape == (8, cap)
    for leaf in w.feeds[idx]:
        assert leaf.sharding == sh
    counts = np.asarray(cnt)
    ids, _cols = src.rows_for(0, ee)
    for s in range(8):
        block = ids[(ids >= s * cap) & (ids < (s + 1) * cap)]
        assert counts[s] == len(block)
        # the shard's addressable data IS its event block (one device)
        shard = next(x for x in pk.addressable_shards
                     if x.index[0] == slice(s, s + 1, None)
                     or x.index[0] == s)
        local = np.asarray(shard.data).reshape(-1)[:counts[s]]
        assert np.array_equal(local, block)
    # the manually taken window replays idempotently: the job's own
    # dispatch re-serves it from retention, results unharmed
    _drive(db)
    assert len(db.query("SELECT * FROM q1a")) > 0


# ---------------------------------------------------------------------------
# double buffering / profiler evidence
# ---------------------------------------------------------------------------


def test_double_buffer_overlap_and_phases():
    """With the staging thread warm, H2D hides under dispatch: the
    stager's total transfer wall stays below the job's dispatch wall,
    and most windows were prefetched off the dispatch thread. A
    stretched cadence (several epochs per barrier) gives the prefetcher
    a dense take sequence to overlap against."""
    db = Database(device=DeviceConfig(capacity=512, host_ingest=True))
    db.run(BID_SRC.format(n=4 * N, c=CHUNK, x=""))
    db.run(Q1_MV)
    job = db._fused["q1a"]
    job.cadence_stretch = 4
    _drive(db, 4 * N)
    st = job.ingest.stats()
    assert st["prefetched"] > 0, "the staging thread never got ahead"
    disp = job.profiler.totals.get("dispatch", 0.0)
    assert st["h2d_s"] < disp, (st, job.profiler.totals)
    # phases stayed disjoint + within wall (pack/h2d included)
    for r in job.profiler.rows():
        pack, h2d, pro, dispatch, exch, sync, dem, commit, wall = r[4:]
        assert pack + h2d + pro + dispatch + exch + sync + dem \
            + commit <= wall * 1.001 + 0.05


# ---------------------------------------------------------------------------
# admission: throttle / defer exactness
# ---------------------------------------------------------------------------


def test_admission_throttle_defer_exact(q1_ref):
    """Throttled (smaller windows) and deferred (zero-token) epochs
    re-time ingestion without changing the answer; a 10x-offered burst
    phase (stretch tokens) drains exactly once admission recovers."""
    from risingwave_tpu.utils.overload import AdmissionBucket
    db = Database(device=DeviceConfig(capacity=512, host_ingest=True))
    db.run(BID_SRC.format(n=N, c=CHUNK, x=""))
    db.run(Q1_MV)
    job = db._fused["q1a"]
    # detached bucket: the overload manager must not re-rate it back
    bucket = AdmissionBucket("bid")
    job.ingest.buckets["bid"] = bucket
    # phase 1: throttled to quarter windows
    bucket.factor = 0.25
    for _ in range(3):
        db.tick()
    assert any(ev < job.program.epoch_events
               for _, ev in job.ingest.recent_windows)
    # phase 2: starved — counter must not move
    class Starved(AdmissionBucket):
        def epoch_refill(self, mult=1):
            self.tokens = 0
    job.ingest.buckets["bid"] = Starved("bid")
    # drain the window the warm pipeline already admitted, then freeze
    db.tick()
    c0 = job.counter
    db.tick()
    assert job.counter == c0
    assert job.ingest.stats()["deferred"] >= 1
    # phase 3: burst recovery — 10x the per-barrier budget until drained
    bucket.factor = 1.0
    job.ingest.buckets["bid"] = bucket
    job.cadence_stretch = 10
    _drive(db)
    job.cadence_stretch = 1
    _drive(db)
    assert db.query("SELECT * FROM q1a") == q1_ref
    assert bucket.lag >= 0


def test_zero_fresh_compiles_across_batch_sizes():
    """Varying admitted window sizes (throttle sweep) all hit the ONE
    pre-lowered aval signature: compile-service counters stay flat."""
    from risingwave_tpu.device.compile_service import get_service
    from risingwave_tpu.utils.overload import AdmissionBucket
    db = Database(device=DeviceConfig(capacity=1 << 14, host_ingest=True,
                                      aot_compile=True))
    db.run(BID_SRC.format(n=8 * N, c=CHUNK, x=""))
    db.run(Q1_MV)
    job = db._fused["q1a"]
    svc = get_service()
    for _ in range(3):
        db.tick()
    assert svc.wait_idle(180)
    before = svc.summary()["compiles"]
    bucket = AdmissionBucket("bid")
    job.ingest.buckets["bid"] = bucket
    for f in (0.5, 0.25, 0.8, 1.0):
        bucket.factor = f
        db.tick()
        db.tick()
    job.sync()
    assert svc.wait_idle(60)
    assert len({ev for _, ev in job.ingest.recent_windows}) >= 3, \
        "throttle sweep failed to vary the admitted window size"
    assert svc.summary()["compiles"] == before, \
        "a varying poll batch size must never trigger a fresh compile"


# ---------------------------------------------------------------------------
# fault tolerance: staged-window replay
# ---------------------------------------------------------------------------


def test_staged_window_inplace_recovery(q1_ref):
    """A device fault mid-window heals in place: the crash window's
    staged-but-uncommitted epochs replay from the epoch event log via
    the stager's retained host arrays — bit-identical MV."""
    from risingwave_tpu.utils import failpoint as fp
    fp.arm("fused.dispatch", prob=1.0, seed=11, max_fires=2)
    try:
        got, job, _ = _run(Q1_MV, "q1a", [BID_SRC], ingest=True)
    finally:
        fp.reset()
    assert job.recoveries >= 1
    assert got == q1_ref


def test_growth_replay_from_retained_windows(q1_ref):
    """Capacity overflow replays the checkpoint window through the
    stager's retained feeds — same rows, same boundaries, same MV."""
    got, job, _ = _run(Q1_MV, "q1a", [BID_SRC], ingest=True, capacity=64)
    assert job.growth_replays >= 1
    assert got == q1_ref


def test_restart_recovery(tmp_path, q1_ref):
    d = str(tmp_path / "data")
    _, job, db = _run(Q1_MV, "q1a", [BID_SRC], ingest=True, keep=True,
                      data_dir=d)
    committed = job.committed
    assert committed >= N
    del db, job
    db2 = Database(data_dir=d,
                   device=DeviceConfig(capacity=512, host_ingest=True))
    job2 = db2._fused["q1a"]
    assert job2.committed == committed
    assert db2.query("SELECT * FROM q1a") == q1_ref


def test_mixed_opt_in_promotes_whole_job(q1_ref):
    """One host-opted source promotes the job's other sources to ingest
    too (shared event clock: a mixed job would double-ingest datagen
    rows the moment admission shrank a staged window) — and a throttled
    run of the promoted job stays exact."""
    from risingwave_tpu.utils.overload import AdmissionBucket
    db = Database(device=DeviceConfig(capacity=512))
    db.run(BID_SRC.format(n=N, c=CHUNK, x=", nexmark.ingest='host'"))
    db.run(AUCTION_SRC.format(n=N, c=CHUNK, x=""))   # no opt-in
    db.run(Q3_MV)
    job = db._fused["q3a"]
    assert job.ingest is not None
    from risingwave_tpu.device.fused import IngestNode, SourceNode
    flat = [n for nd in job.program.nodes
            for n in (getattr(nd, "chain", None) or [nd])]
    assert not any(isinstance(n, SourceNode) for n in flat)
    assert sum(isinstance(n, IngestNode) for n in flat) == 2
    # throttle mid-run: windows shrink, both sources stay in lockstep
    b = AdmissionBucket("bid")
    b.factor = 0.5
    job.ingest.buckets["bid"] = b
    _drive(db)
    ref, _, _ = _run(Q3_MV, "q3a", [BID_SRC, AUCTION_SRC], ingest=False)
    assert db.query("SELECT * FROM q3a") == ref
