"""WatermarkFilter + emit-on-window-close HashAgg behavior."""
from typing import Iterator, List

import pytest

from risingwave_tpu.core import Op, Schema, StreamChunk, dtypes as T
from risingwave_tpu.expr import AggCall
from risingwave_tpu.ops import (Barrier, BarrierKind, HashAggExecutor,
                                Message, Watermark, WatermarkFilterExecutor)
from risingwave_tpu.ops.executor import Executor
from risingwave_tpu.ops.message import EpochPair
from risingwave_tpu.state import MemoryStateStore, StateTable

SCHEMA = Schema.of(("w", T.INT64), ("v", T.INT64))


class MessageList(Executor):
    """Yields a scripted message sequence (chunks / watermarks / barriers)."""

    def __init__(self, schema: Schema, msgs: List[Message]):
        super().__init__(schema, "MessageList")
        self.msgs = msgs

    def execute(self) -> Iterator[Message]:
        yield from self.msgs


def barrier(e: int, checkpoint: bool = True) -> Barrier:
    return Barrier(EpochPair(e, e - 1),
                   kind=BarrierKind.CHECKPOINT if checkpoint
                   else BarrierKind.BARRIER)


def chunk(*rows):
    return StreamChunk.from_rows(SCHEMA.dtypes,
                                 [(Op.INSERT, r) for r in rows])


def run(execu) -> List[Message]:
    return list(execu.execute())


def eowc_agg(src, store=None):
    st = None
    if store is not None:
        agg_dtypes = [T.INT64, T.BYTEA]
        st = StateTable(store, 7, agg_dtypes, [0])
    return HashAggExecutor(src, [0], [AggCall("count")], state_table=st,
                           emit_on_window_close=True, window_col_in_group=0), st


class TestWatermarkFilter:
    def test_derives_and_emits_watermark_at_barrier(self):
        src = MessageList(SCHEMA, [chunk((10, 1), (20, 2)), barrier(1)])
        wf = WatermarkFilterExecutor(src, time_col=0, delay=5)
        msgs = run(wf)
        wms = [m for m in msgs if isinstance(m, Watermark)]
        assert len(wms) == 1 and wms[0].value == 15 and wms[0].col_idx == 0

    def test_filters_late_rows(self):
        src = MessageList(SCHEMA, [chunk((100, 1)), barrier(1),
                                   chunk((10, 2), (99, 3), (200, 4)),
                                   barrier(2)])
        wf = WatermarkFilterExecutor(src, time_col=0, delay=0)
        msgs = run(wf)
        rows = [r for m in msgs if isinstance(m, StreamChunk)
                for _, r in m.compact().op_rows()]
        # wm after epoch1 = 100; rows 10 and 99 are late and dropped
        assert (10, 2) not in rows and (99, 3) not in rows
        assert (100, 1) in rows and (200, 4) in rows

    def test_own_chunk_max_does_not_filter_siblings(self):
        """The watermark derived from a chunk must not retroactively drop
        older rows of the same chunk (filter first, then advance)."""
        src = MessageList(SCHEMA, [chunk((1, 1), (1, 2), (100, 3)),
                                   barrier(1)])
        wf = WatermarkFilterExecutor(src, time_col=0, delay=0)
        msgs = run(wf)
        rows = [r for m in msgs if isinstance(m, StreamChunk)
                for _, r in m.compact().op_rows()]
        assert len(rows) == 3

    def test_watermark_recovery(self):
        store = MemoryStateStore()
        st = StateTable(store, 9, [T.INT64, T.INT64], [0])
        src = MessageList(SCHEMA, [chunk((50, 1)), barrier(1)])
        wf = WatermarkFilterExecutor(src, 0, 0, state_table=st)
        run(wf)
        st2 = StateTable(store, 9, [T.INT64, T.INT64], [0])
        src2 = MessageList(SCHEMA, [chunk((10, 9)), barrier(2)])
        wf2 = WatermarkFilterExecutor(src2, 0, 0, state_table=st2)
        msgs = run(wf2)
        rows = [r for m in msgs if isinstance(m, StreamChunk)
                for _, r in m.compact().op_rows()]
        assert rows == []  # 10 < recovered watermark 50 -> filtered


class TestEowcHashAgg:
    def test_rows_before_watermark(self):
        """Windows close only when the watermark passes; emission precedes
        the (buffered) watermark release."""
        src = MessageList(SCHEMA, [
            chunk((1, 1), (1, 2), (2, 3)), barrier(1),
            Watermark(0, T.INT64, 2), barrier(2),
        ])
        agg, _ = eowc_agg(src)
        msgs = run(agg)
        # barrier1: nothing closed, no watermark yet
        b1 = msgs.index(next(m for m in msgs if isinstance(m, Barrier)))
        assert not any(isinstance(m, (StreamChunk, Watermark))
                       for m in msgs[:b1])
        # after barrier2: window 1 INSERT (count=2), then watermark, no w=2
        tail = msgs[b1 + 1:]
        chunks = [m for m in tail if isinstance(m, StreamChunk)]
        wms = [m for m in tail if isinstance(m, Watermark)]
        assert len(chunks) == 1
        assert chunks[0].compact().op_rows() == [(Op.INSERT, (1, 2))]
        assert len(wms) == 1 and wms[0].value == 2
        assert tail.index(chunks[0]) < tail.index(wms[0])

    def test_late_rows_dropped_after_close(self):
        src = MessageList(SCHEMA, [
            chunk((1, 1)), Watermark(0, T.INT64, 5), barrier(1),
            chunk((1, 99)), barrier(2),   # late row for closed window 1
        ])
        agg, _ = eowc_agg(src)
        msgs = run(agg)
        chunks = [m for m in msgs if isinstance(m, StreamChunk)]
        assert len(chunks) == 1  # the late row produced no second INSERT
        assert chunks[0].compact().op_rows() == [(Op.INSERT, (1, 1))]

    def test_open_windows_survive_recovery(self):
        store = MemoryStateStore()
        src = MessageList(SCHEMA, [chunk((8, 1), (8, 2)), barrier(1)])
        agg, _ = eowc_agg(src, store)
        run(agg)
        # restart: same state table id; watermark now closes window 8
        src2 = MessageList(SCHEMA, [Watermark(0, T.INT64, 9), barrier(2)])
        agg2, _ = eowc_agg(src2, store)
        msgs = run(agg2)
        chunks = [m for m in msgs if isinstance(m, StreamChunk)]
        assert len(chunks) == 1
        assert chunks[0].compact().op_rows() == [(Op.INSERT, (8, 2))]

    def test_eowc_requires_window_col(self):
        src = MessageList(SCHEMA, [])
        with pytest.raises(AssertionError):
            HashAggExecutor(src, [0], [AggCall("count")],
                            emit_on_window_close=True)
