"""Sharded (8-virtual-device) hash agg == single-device hash agg == oracle."""
import numpy as np
import pytest

import jax

from risingwave_tpu.device.agg_step import DeviceAggSpec, DeviceHashAgg
from risingwave_tpu.parallel import ShardedHashAgg, make_mesh


def collect_outputs(changes_list, ncalls):
    """Fold change sets into the materialized output table."""
    out = {}
    for ch in changes_list:
        keys = ch["keys"].reshape(-1)
        of = ch["old_found"].reshape(-1)
        nf = ch["new_found"].reshape(-1)
        nout = [c.reshape(-1) for c in ch["new_out"]]
        nnull = [c.reshape(-1) for c in ch["new_null"]]
        for i in range(len(keys)):
            k = int(keys[i])
            if k == np.iinfo(np.int64).max:
                continue
            if bool(nf[i]):
                out[k] = tuple(None if bool(nnull[c][i]) else nout[c][i]
                               for c in range(ncalls))
            elif bool(of[i]):
                out.pop(k, None)
    return out


def test_sharded_matches_single_device():
    mesh = make_mesh()
    assert mesh.devices.size == 8
    kinds = ["count_star", "sum", "max"]
    spec = DeviceAggSpec.build(kinds, [np.int64] * 3)
    single = DeviceHashAgg(spec, capacity=16)
    sharded = ShardedHashAgg(spec, mesh, capacity=16)

    rng = np.random.default_rng(7)
    single_changes, sharded_changes = [], []
    for _ in range(4):
        n = 500
        keys = rng.integers(0, 40, size=n).astype(np.int64)
        vals = rng.integers(-100, 100, size=n).astype(np.int64)
        valid = rng.random(n) > 0.05
        signs = np.ones(n, dtype=np.int32)  # max => append-only
        ins = [(vals, valid)] * 3
        single.push_rows(keys, signs, ins)
        sharded.push_rows(keys, signs, ins)
        single_changes.append(single.flush_epoch())
        sharded_changes.append(sharded.flush_epoch())

    a = collect_outputs(single_changes, 3)
    b = collect_outputs(sharded_changes, 3)
    assert set(a) == set(b) and len(a) > 0
    for k in a:
        assert tuple(map(lambda x: None if x is None else int(x), a[k])) == \
               tuple(map(lambda x: None if x is None else int(x), b[k])), k


def test_sharded_growth_and_key_placement():
    mesh = make_mesh()
    spec = DeviceAggSpec.build(["sum"], [np.int64])
    agg = ShardedHashAgg(spec, mesh, capacity=8)
    n = 4000
    keys = np.arange(n, dtype=np.int64)
    agg.push_rows(keys, np.ones(n, np.int32),
                  [(keys, np.ones(n, bool))])
    ch = agg.flush_epoch()
    out = collect_outputs([ch], 1)
    assert len(out) == n
    assert all(int(out[k][0]) == k for k in (0, 1, 1999, 3999))
    # every shard should own a nontrivial slice (CRC32 balance)
    counts = np.asarray(agg.state.count).reshape(-1)
    assert counts.sum() == n and (counts > n / 32).all()


def test_rescale_preserves_results():
    """Scale 2 -> 4 -> 3 shards mid-stream; outputs match an unrescaled run
    (ALTER PARALLELISM analog: vnode re-shard at barrier boundaries)."""
    devs = jax.devices()
    spec = DeviceAggSpec.build(["count_star", "sum"], [np.int64] * 2)
    fixed = ShardedHashAgg(spec, make_mesh(2), capacity=16)
    elastic = ShardedHashAgg(spec, make_mesh(2), capacity=16)
    rng = np.random.default_rng(11)
    fixed_ch, elastic_ch = [], []
    for step, n_shards in enumerate([2, 4, 4, 3, 3]):
        if n_shards != elastic.n:
            elastic.rescale(make_mesh(n_shards))
        n = 300
        keys = rng.integers(0, 50, size=n).astype(np.int64)
        vals = rng.integers(-20, 20, size=n).astype(np.int64)
        ins = [(vals, np.ones(n, bool))] * 2
        for agg, acc in ((fixed, fixed_ch), (elastic, elastic_ch)):
            agg.push_rows(keys, np.ones(n, np.int32), ins)
            acc.append(agg.flush_epoch())
    a = collect_outputs(fixed_ch, 2)
    b = collect_outputs(elastic_ch, 2)
    assert len(a) > 0 and set(a) == set(b)
    for k in a:
        assert tuple(map(int, a[k])) == tuple(map(int, b[k])), k
    counts = np.asarray(elastic.state.count).reshape(-1)
    assert len(counts) == 3 and counts.sum() == len(a)
