"""Expression layer tests: host/device parity, SQL semantics."""
from decimal import Decimal

import numpy as np
import pytest

from risingwave_tpu.core import Column, DataChunk, dtypes as T
from risingwave_tpu.expr import (
    AggCall, Case, DistinctDedup, InputRef, Literal, build_func, cast,
    create_agg_state,
)


def chunk_i64(*cols):
    return DataChunk([Column.from_list(T.INT64, list(c)) for c in cols])


class TestScalar:
    def test_add_ints(self):
        e = build_func("add", [InputRef(0, T.INT64), InputRef(1, T.INT64)])
        out = e.eval(chunk_i64([1, 2, None], [10, 20, 30]))
        assert out.to_list() == [11, 22, None]

    def test_int_division_truncates_toward_zero(self):
        e = build_func("divide", [InputRef(0, T.INT64), InputRef(1, T.INT64)])
        out = e.eval(chunk_i64([7, -7, 7, -7], [2, 2, -2, -2]))
        assert out.to_list() == [3, -3, -3, 3]

    def test_division_by_zero_yields_null(self):
        e = build_func("divide", [InputRef(0, T.INT64), InputRef(1, T.INT64)])
        out = e.eval(chunk_i64([1], [0]))
        assert out.to_list() == [None]

    def test_modulus_sign(self):
        e = build_func("modulus", [InputRef(0, T.INT64), InputRef(1, T.INT64)])
        out = e.eval(chunk_i64([7, -7, 7, -7], [3, 3, -3, -3]))
        assert out.to_list() == [1, -1, 1, -1]  # PG: sign of dividend

    def test_decimal_multiply_exact(self):
        e = build_func("multiply", [InputRef(0, T.INT64), Literal(Decimal("0.908"), T.DECIMAL)])
        out = e.eval(chunk_i64([100, 25]))
        assert out.to_list() == [Decimal("90.800"), Decimal("22.700")]

    def test_mixed_promotion(self):
        e = build_func("add", [InputRef(0, T.INT32), InputRef(1, T.FLOAT64)])
        ch = DataChunk([Column.from_list(T.INT32, [1]), Column.from_list(T.FLOAT64, [0.5])])
        assert e.return_type.kind == T.TypeKind.FLOAT64
        assert e.eval(ch).to_list() == [1.5]

    def test_comparison_strings(self):
        e = build_func("less_than", [InputRef(0, T.VARCHAR), InputRef(1, T.VARCHAR)])
        ch = DataChunk([Column.from_list(T.VARCHAR, ["a", "c", None]),
                        Column.from_list(T.VARCHAR, ["b", "b", "x"])])
        assert e.eval(ch).to_list() == [True, False, None]

    def test_three_valued_logic(self):
        a = InputRef(0, T.BOOLEAN)
        b = InputRef(1, T.BOOLEAN)
        ch = DataChunk([Column.from_list(T.BOOLEAN, [True, False, None, None]),
                        Column.from_list(T.BOOLEAN, [None, None, None, True])])
        and_out = build_func("and", [a, b]).eval(ch)
        assert and_out.to_list() == [None, False, None, None]
        or_out = build_func("or", [a, b]).eval(ch)
        assert or_out.to_list() == [True, None, None, True]  # TRUE OR NULL = TRUE

    def test_case(self):
        cond = build_func("greater_than", [InputRef(0, T.INT64), Literal(0, T.INT64)])
        e = Case([(cond, Literal("pos", T.VARCHAR))], Literal("neg", T.VARCHAR), T.VARCHAR)
        out = e.eval(chunk_i64([5, -5, 0]))
        assert out.to_list() == ["pos", "neg", "neg"]

    def test_cast_str_int(self):
        e = cast(InputRef(0, T.VARCHAR), T.INT64)
        ch = DataChunk([Column.from_list(T.VARCHAR, ["42", " 7 ", "bad"])])
        assert e.eval(ch).to_list() == [42, 7, None]

    def test_cast_timestamp_str(self):
        e = cast(InputRef(0, T.VARCHAR), T.TIMESTAMP)
        ch = DataChunk([Column.from_list(T.VARCHAR, ["2024-01-01 00:00:01"])])
        (v,) = e.eval(ch).to_list()
        assert v == 1704067201000000

    def test_like(self):
        e = build_func("like", [InputRef(0, T.VARCHAR), Literal("%rule%", T.VARCHAR)])
        ch = DataChunk([Column.from_list(T.VARCHAR, ["hard rules", "soft", None])])
        assert e.eval(ch).to_list() == [True, False, None]

    def test_substr_split_part(self):
        e = build_func("split_part", [InputRef(0, T.VARCHAR),
                                      Literal(",", T.VARCHAR), Literal(2, T.INT32)])
        ch = DataChunk([Column.from_list(T.VARCHAR, ["a,b,c"])])
        assert e.eval(ch).to_list() == ["b"]

    def test_extract_date_trunc(self):
        ts = 1704067201000000  # 2024-01-01 00:00:01
        e = build_func("extract", [Literal("year", T.VARCHAR), InputRef(0, T.TIMESTAMP)])
        ch = DataChunk([Column.from_list(T.TIMESTAMP, [ts])])
        assert e.eval(ch).to_list() == [Decimal(2024)]
        e2 = build_func("date_trunc", [Literal("day", T.VARCHAR), InputRef(0, T.TIMESTAMP)])
        assert e2.eval(ch).to_list() == [1704067200000000]

    def test_ts_plus_interval(self):
        from risingwave_tpu.core import parse_interval
        e = build_func("add", [InputRef(0, T.TIMESTAMP),
                               Literal(parse_interval("10 seconds"), T.INTERVAL)])
        ch = DataChunk([Column.from_list(T.TIMESTAMP, [1000000])])
        assert e.eval(ch).to_list() == [11000000]


class TestDeviceParity:
    def _both(self, e, ch):
        import jax.numpy as jnp
        host = e.eval(ch)
        cols = [jnp.asarray(c.values) for c in ch.columns]
        dv, dok = e.eval_device(cols)
        return host, np.asarray(dv), np.asarray(dok)

    def test_arith_parity(self):
        e = build_func("multiply", [
            build_func("add", [InputRef(0, T.INT64), Literal(5, T.INT64)]),
            InputRef(1, T.INT64)])
        assert e.supports_device()
        ch = chunk_i64([1, 2, 3], [4, 5, 6])
        host, dv, dok = self._both(e, ch)
        assert host.to_list() == list(dv)

    def test_division_null_parity(self):
        e = build_func("divide", [InputRef(0, T.INT64), InputRef(1, T.INT64)])
        ch = chunk_i64([10, 6], [0, 2])
        host, dv, dok = self._both(e, ch)
        assert list(dok) == [False, True]
        assert host.to_list() == [None, 3]

    def test_cmp_and_case_parity(self):
        cond = build_func("greater_than_or_equal",
                          [InputRef(0, T.INT64), Literal(2, T.INT64)])
        e = Case([(cond, InputRef(1, T.INT64))], Literal(0, T.INT64), T.INT64)
        assert e.supports_device()
        ch = chunk_i64([1, 2, 3], [10, 20, 30])
        host, dv, _ = self._both(e, ch)
        assert host.to_list() == list(dv)

    def test_float_parity(self):
        e = build_func("multiply", [InputRef(0, T.FLOAT64), Literal(0.908, T.FLOAT64)])
        ch = DataChunk([Column.from_list(T.FLOAT64, [1.0, 2.5])])
        host, dv, _ = self._both(e, ch)
        np.testing.assert_allclose(host.values, dv)


class TestAgg:
    def _run(self, call, pairs):
        st = create_agg_state(call)
        for sign, v in pairs:
            st.apply(sign, v)
        return st.output()

    def test_count_retract(self):
        c = AggCall("count")
        assert self._run(c, [(1, 1), (1, 1), (-1, 1)]) == 1

    def test_sum_bigint_is_decimal(self):
        c = AggCall("sum", InputRef(0, T.INT64))
        assert c.return_type.kind == T.TypeKind.DECIMAL
        assert self._run(c, [(1, 5), (1, 7), (-1, 2)]) == Decimal(10)

    def test_sum_empty_is_null(self):
        c = AggCall("sum", InputRef(0, T.INT32))
        assert self._run(c, [(1, 5), (-1, 5)]) is None

    def test_min_retract_recovers_next(self):
        c = AggCall("min", InputRef(0, T.INT64))
        assert self._run(c, [(1, 5), (1, 3), (1, 7), (-1, 3)]) == 5

    def test_avg(self):
        c = AggCall("avg", InputRef(0, T.INT64))
        assert self._run(c, [(1, 4), (1, 8)]) == Decimal(6)

    def test_first_last_value(self):
        c = AggCall("last_value", InputRef(0, T.INT64))
        assert self._run(c, [(1, 1), (1, 2), (1, 3)]) == 3

    def test_distinct_dedup(self):
        d = DistinctDedup()
        assert d.apply(1, "x") == 1
        assert d.apply(1, "x") == 0
        assert d.apply(-1, "x") == 0
        assert d.apply(-1, "x") == -1
