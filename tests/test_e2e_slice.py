"""Minimum end-to-end slice (SURVEY.md §7 step 2): source → project/filter →
materialize, driven by the barrier loop; MV read back at committed epochs.
This is the Nexmark q1/q2-shaped pipeline."""
from decimal import Decimal

import numpy as np
import pytest

from risingwave_tpu.core import Column, Op, Schema, StreamChunk, dtypes as T
from risingwave_tpu.connectors import (BID_SCHEMA, ListReader, NexmarkConfig,
                                       NexmarkGenerator, NexmarkReader)
from risingwave_tpu.expr import InputRef, Literal, build_func
from risingwave_tpu.ops import (BarrierInjector, BatchScan, ConflictBehavior,
                                FilterExecutor, MaterializeExecutor,
                                ProjectExecutor, SourceExecutor)
from risingwave_tpu.runtime import StreamJob
from risingwave_tpu.state import MemoryStateStore, StateTable


def make_job(reader, schema, exprs=None, predicate=None, pk=(0,),
             conflict=ConflictBehavior.NO_CHECK, checkpoint_frequency=1):
    store = MemoryStateStore()
    injector = BarrierInjector(checkpoint_frequency=checkpoint_frequency)
    src = SourceExecutor(schema, reader, injector,
                         split_state_table=StateTable(store, 900, [T.VARCHAR, T.VARCHAR], [0]))
    node = src
    if predicate is not None:
        node = FilterExecutor(node, predicate)
    if exprs is not None:
        node = ProjectExecutor(node, exprs)
    table = StateTable(store, 1, node.schema.dtypes, list(pk))
    mat = MaterializeExecutor(node, table, conflict)
    job = StreamJob(mat, injector, store)
    return job, table, mat


class TestE2ESlice:
    def test_nexmark_q1_currency_conversion(self):
        """q1: SELECT auction, bidder, 0.908 * price, date_time FROM bid."""
        gen = NexmarkGenerator(NexmarkConfig(seed=7))
        reader = NexmarkReader("bid", gen, events_per_poll=500, max_events=2000)
        exprs = [InputRef(0, T.INT64), InputRef(1, T.INT64),
                 build_func("multiply", [Literal(Decimal("0.908"), T.DECIMAL),
                                         InputRef(2, T.INT64)]),
                 InputRef(5, T.TIMESTAMP)]
        # keyless MV → uses (auction,bidder,dt) composite for test pk
        job, table, _ = make_job(reader, BID_SCHEMA, exprs=exprs, pk=(0, 1, 3))
        job.run_until_idle()
        rows = BatchScan(table, None).rows()
        assert len(rows) > 1500  # 46/50 of 2000 events, minus pk collisions
        # exact decimal arithmetic
        for r in rows[:50]:
            assert (r[2] % Decimal("0.004")) == 0  # 0.908 * int has 3 decimals

    def test_filter_and_project(self):
        """q2-shaped: SELECT auction, price FROM bid WHERE auction % 123 = 0."""
        gen = NexmarkGenerator(NexmarkConfig(seed=3))
        reader = NexmarkReader("bid", gen, events_per_poll=1000, max_events=5000)
        pred = build_func("equal", [
            build_func("modulus", [InputRef(0, T.INT64), Literal(123, T.INT64)]),
            Literal(0, T.INT64)])
        exprs = [InputRef(0, T.INT64), InputRef(2, T.INT64),
                 InputRef(5, T.TIMESTAMP)]
        job, table, _ = make_job(reader, BID_SCHEMA, exprs=exprs,
                                 predicate=pred, pk=(0, 1, 2))
        job.run_until_idle()
        for r in BatchScan(table, None).rows():
            assert r[0] % 123 == 0

    def test_update_pairs_through_filter(self):
        """U-/U+ degradation when predicate flips (filter.rs semantics)."""
        schema = Schema.of(("k", T.INT64), ("v", T.INT64))
        chunks = [
            StreamChunk.from_rows(schema.dtypes, [
                (Op.INSERT, (1, 10)), (Op.INSERT, (2, 100))]),
            StreamChunk.from_rows(schema.dtypes, [
                (Op.UPDATE_DELETE, (1, 10)), (Op.UPDATE_INSERT, (1, 200)),   # false->true? 10<50 pass, 200>=50 fail
                (Op.UPDATE_DELETE, (2, 100)), (Op.UPDATE_INSERT, (2, 30))]),
        ]
        pred = build_func("less_than", [InputRef(1, T.INT64), Literal(50, T.INT64)])
        job, table, _ = make_job(ListReader(chunks), schema, predicate=pred, pk=(0,),
                                 conflict=ConflictBehavior.OVERWRITE)
        job.run_until_idle()
        rows = sorted(BatchScan(table, None).rows())
        # k=1: insert passed (10), update to 200 fails pred -> DELETE. gone.
        # k=2: insert 100 filtered; update to 30 passes -> INSERT. present.
        assert rows == [(2, 30)]

    def test_materialize_overwrite_conflict(self):
        schema = Schema.of(("k", T.INT64), ("v", T.VARCHAR))
        chunks = [StreamChunk.from_rows(schema.dtypes, [
            (Op.INSERT, (1, "a")), (Op.INSERT, (1, "b")), (Op.INSERT, (2, "c"))])]
        job, table, _ = make_job(ListReader(chunks), schema, pk=(0,),
                                 conflict=ConflictBehavior.OVERWRITE)
        job.run_until_idle()
        assert sorted(BatchScan(table, None).rows()) == [(1, "b"), (2, "c")]

    def test_deletes_and_updates_materialize(self):
        schema = Schema.of(("k", T.INT64), ("v", T.INT64))
        chunks = [
            StreamChunk.from_rows(schema.dtypes, [(Op.INSERT, (i, i * 10)) for i in range(5)]),
            StreamChunk.from_rows(schema.dtypes, [
                (Op.DELETE, (2, 20)),
                (Op.UPDATE_DELETE, (3, 30)), (Op.UPDATE_INSERT, (3, 99))]),
        ]
        job, table, _ = make_job(ListReader(chunks), schema, pk=(0,))
        job.run_until_idle()
        rows = sorted(BatchScan(table, None).rows())
        assert rows == [(0, 0), (1, 10), (3, 99), (4, 40)]

    def test_barrier_epochs_commit(self):
        schema = Schema.of(("k", T.INT64),)
        reader = ListReader([StreamChunk.from_rows(schema.dtypes, [(Op.INSERT, (1,))])])
        job, table, _ = make_job(reader, schema, pk=(0,))
        b1 = job.run_until_barrier()
        assert b1 is not None and job.barriers_seen == 1
        job.flush()
        assert job.committed_epoch > 0
        assert job.store.committed_epoch == job.committed_epoch

    def test_checkpoint_frequency_noncheckpoint_barriers(self):
        schema = Schema.of(("k", T.INT64),)
        reader = ListReader([])
        job, table, _ = make_job(reader, schema, pk=(0,), checkpoint_frequency=3)
        kinds = []
        for _ in range(7):
            b = job.run_until_barrier()
            kinds.append(b.kind.value)
        # initial, then barrier/barrier/checkpoint cycles
        assert kinds[0] == "initial"
        assert kinds[1:4].count("checkpoint") == 1

    def test_source_split_recovery(self):
        """Split offsets persist at barriers; a new reader seeks to them."""
        gen = NexmarkGenerator()
        store = MemoryStateStore()
        injector = BarrierInjector()
        split_table = StateTable(store, 900, [T.VARCHAR, T.VARCHAR], [0])
        reader = NexmarkReader("bid", gen, events_per_poll=100, max_events=300)
        src = SourceExecutor(BID_SCHEMA, reader, injector, split_table)
        table = StateTable(store, 1, BID_SCHEMA.dtypes, [0, 1, 5])
        mat = MaterializeExecutor(src, table)
        job = StreamJob(mat, injector, store)
        job.run_until_idle()
        assert reader.next_event == 300
        # "restart": fresh reader recovers offset from the split table
        reader2 = NexmarkReader("bid", gen, events_per_poll=100)
        injector2 = BarrierInjector()
        src2 = SourceExecutor(BID_SCHEMA, reader2, injector2, split_table)
        it = src2.execute()
        injector2.inject()
        next(it)  # initial barrier triggers recovery
        assert reader2.next_event == 300


class TestNexmarkGen:
    def test_deterministic(self):
        g1 = NexmarkGenerator(NexmarkConfig(seed=5))
        g2 = NexmarkGenerator(NexmarkConfig(seed=5))
        c1 = g1.gen_range(0, 1000)
        c2 = g2.gen_range(0, 1000)
        for k in c1:
            assert c1[k].rows() == c2[k].rows()

    def test_proportions(self):
        g = NexmarkGenerator()
        out = g.gen_range(0, 5000)
        assert out["person"].capacity == 100
        assert out["auction"].capacity == 300
        assert out["bid"].capacity == 4600

    def test_referential_plausibility(self):
        g = NexmarkGenerator()
        out = g.gen_range(0, 5000)
        auction_ids = set(out["auction"].columns[0].values.tolist())
        bid_auctions = out["bid"].columns[0].values
        # bids reference auctions that exist (ids are dense from 1000)
        assert bid_auctions.min() >= 1000
        assert bid_auctions.max() <= max(auction_ids)

    def test_timestamps_monotone_per_stream(self):
        g = NexmarkGenerator()
        out = g.gen_range(0, 2000)
        for k in out:
            ts = out[k].columns[{"person": 6, "auction": 5, "bid": 5}[k]].values
            assert (np.diff(ts) >= 0).all()
