"""Metrics kernel + runtime wiring."""
from risingwave_tpu.utils.metrics import MetricsRegistry


def test_counter_gauge_histogram_exposition():
    reg = MetricsRegistry()
    c = reg.counter("rows_total", "rows", labels=("executor",))
    c.labels("HashAgg").inc(5)
    c.labels("Filter").inc()
    g = reg.gauge("mem_bytes", "memory")
    g.set(1024)
    h = reg.histogram("lat", "latency", buckets=(0.1, 1.0))
    h.observe(0.05)
    h.observe(0.5)
    h.observe(5.0)
    text = reg.expose()
    assert 'rows_total{executor="HashAgg"} 5' in text
    assert 'rows_total{executor="Filter"} 1' in text
    assert "mem_bytes 1024" in text
    assert 'lat_bucket{le="0.1"} 1' in text
    assert 'lat_bucket{le="1"} 2' in text
    assert 'lat_bucket{le="+Inf"} 3' in text
    assert "lat_count 3" in text
    assert h.labels().quantile(0.5) == 1.0


def test_registry_dedup():
    reg = MetricsRegistry()
    a = reg.counter("x", "one")
    b = reg.counter("x", "two")
    assert a is b


def test_database_emits_metrics():
    from risingwave_tpu.sql import Database
    db = Database()
    db.run("CREATE TABLE t (k BIGINT)")
    db.run("INSERT INTO t VALUES (1)")
    text = db.metrics()
    assert "barrier_count" in text and "committed_epoch" in text
    assert "barrier_latency_seconds_count" in text


def test_barrier_trace_breadcrumbs():
    """Barriers accumulate the executor path they traversed
    (TracingContext-in-barrier analog)."""
    from risingwave_tpu.core import Op, Schema, StreamChunk, dtypes as T
    from risingwave_tpu.connectors import ListReader
    from risingwave_tpu.expr import AggCall
    from risingwave_tpu.ops import (BarrierInjector, HashAggExecutor,
                                    SourceExecutor)
    from risingwave_tpu.ops.message import Barrier
    S = Schema.of(("k", T.INT64))
    inj = BarrierInjector()
    src = SourceExecutor(S, ListReader([]), inj)
    agg = HashAggExecutor(src, [0], [AggCall("count")])
    it = agg.execute()
    inj.inject()
    inj.inject_stop()
    barriers = [m for m in it if isinstance(m, Barrier)]
    assert barriers and "HashAgg" in barriers[0].trace
