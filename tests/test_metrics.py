"""Metrics kernel + runtime wiring."""
import re

from risingwave_tpu.utils.metrics import MetricsRegistry, lint_registry


# ---------------------------------------------------------------------------
# exposition parser (round-trip testing): understands HELP/TYPE lines,
# escaped label values and histogram series
# ---------------------------------------------------------------------------

_SAMPLE_RE = re.compile(r'^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{(.*)\})? (\S+)$')
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def _unesc(v: str) -> str:
    out, i = [], 0
    while i < len(v):
        if v[i] == "\\" and i + 1 < len(v):
            out.append({"n": "\n", '"': '"', "\\": "\\"}[v[i + 1]])
            i += 2
        else:
            out.append(v[i])
            i += 1
    return "".join(out)


def parse_exposition(text: str):
    """{(name, frozenset(labels.items())): float} + {name: type}."""
    samples, types = {}, {}
    for line in text.splitlines():
        if not line or line.startswith("# HELP"):
            continue
        if line.startswith("# TYPE"):
            _, _, name, t = line.split(None, 3)
            types[name] = t
            continue
        m = _SAMPLE_RE.match(line)
        assert m, f"unparseable exposition line: {line!r}"
        labels = {k: _unesc(v) for k, v in _LABEL_RE.findall(m.group(3) or "")}
        samples[(m.group(1), frozenset(labels.items()))] = float(m.group(4))
    return samples, types


def test_counter_gauge_histogram_exposition():
    reg = MetricsRegistry()
    c = reg.counter("rows_total", "rows", labels=("executor",))
    c.labels("HashAgg").inc(5)
    c.labels("Filter").inc()
    g = reg.gauge("mem_bytes", "memory")
    g.set(1024)
    h = reg.histogram("lat", "latency", buckets=(0.1, 1.0))
    h.observe(0.05)
    h.observe(0.5)
    h.observe(5.0)
    text = reg.expose()
    assert 'rows_total{executor="HashAgg"} 5' in text
    assert 'rows_total{executor="Filter"} 1' in text
    assert "mem_bytes 1024" in text
    assert 'lat_bucket{le="0.1"} 1' in text
    assert 'lat_bucket{le="1"} 2' in text
    assert 'lat_bucket{le="+Inf"} 3' in text
    assert "lat_count 3" in text
    assert h.labels().quantile(0.5) == 1.0


def test_registry_dedup():
    reg = MetricsRegistry()
    a = reg.counter("x", "one")
    b = reg.counter("x", "two")
    assert a is b


def test_database_emits_metrics():
    from risingwave_tpu.sql import Database
    db = Database()
    db.run("CREATE TABLE t (k BIGINT)")
    db.run("INSERT INTO t VALUES (1)")
    text = db.metrics()
    assert "barrier_count" in text and "committed_epoch" in text
    assert "barrier_latency_seconds_count" in text


def test_barrier_trace_breadcrumbs():
    """Barriers accumulate the executor path they traversed
    (TracingContext-in-barrier analog)."""
    from risingwave_tpu.core import Op, Schema, StreamChunk, dtypes as T
    from risingwave_tpu.connectors import ListReader
    from risingwave_tpu.expr import AggCall
    from risingwave_tpu.ops import (BarrierInjector, HashAggExecutor,
                                    SourceExecutor)
    from risingwave_tpu.ops.message import Barrier
    S = Schema.of(("k", T.INT64))
    inj = BarrierInjector()
    src = SourceExecutor(S, ListReader([]), inj)
    agg = HashAggExecutor(src, [0], [AggCall("count")])
    it = agg.execute()
    inj.inject()
    inj.inject_stop()
    barriers = [m for m in it if isinstance(m, Barrier)]
    assert barriers and "HashAgg" in barriers[0].trace


def test_label_value_escaping_round_trip():
    """Quotes, backslashes and newlines in label VALUES must survive the
    exposition format (the pre-PR5 _fmt_labels emitted broken text)."""
    reg = MetricsRegistry()
    c = reg.counter("q_total", "queries", labels=("sql",))
    nasty = 'SELECT "a\\b"\nFROM t'
    c.labels(nasty).inc(3)
    text = reg.expose()
    # the raw text contains no literal newline inside a sample line
    assert all(l.count('"') % 2 == 0 for l in text.splitlines() if l)
    samples, _ = parse_exposition(text)
    assert samples[("q_total", frozenset({("sql", nasty)}.union(set())))] \
        == 3.0


def test_exposition_full_round_trip_and_bucket_monotonicity():
    reg = MetricsRegistry()
    reg.counter("a_total", "a", labels=("k",)).labels("x").inc(2)
    reg.gauge("g", "g").set(-1.5)
    h = reg.histogram("lat_s", "lat", labels=("op",), buckets=(0.1, 1, 5))
    for v in (0.05, 0.5, 0.5, 3, 30):
        h.labels("scan").observe(v)
    samples, types = parse_exposition(reg.expose())
    assert types == {"a_total": "counter", "g": "gauge",
                     "lat_s": "histogram"}
    assert samples[("a_total", frozenset({("k", "x")}))] == 2.0
    assert samples[("g", frozenset())] == -1.5
    base = {("op", "scan")}
    buckets = [samples[("lat_s_bucket",
                        frozenset(base | {("le", le)}))]
               for le in ("0.1", "1", "5", "+Inf")]
    assert buckets == sorted(buckets), "bucket counts must be cumulative"
    assert buckets[-1] == samples[("lat_s_count", frozenset(base))] == 5.0
    assert samples[("lat_s_sum", frozenset(base))] == 34.05


def test_child_mutation_thread_safety():
    """Exchange drains + supervisor + barrier loop increment concurrently;
    += on a float is not atomic without the mutation lock."""
    import threading
    reg = MetricsRegistry()
    c = reg.counter("n_total", "n").labels()
    g = reg.gauge("gv", "g").labels()
    h = reg.histogram("hd", "h", buckets=(1.0,)).labels()

    def work():
        for _ in range(10_000):
            c.inc()
            g.inc(2)
            h.observe(0.5)

    ts = [threading.Thread(target=work) for _ in range(8)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert c.value == 80_000
    assert g.value == 160_000
    assert h.total == 80_000 and h.counts[0] == 80_000


def test_dump_delta_and_merge_remote():
    """The cluster plane: worker-side registry deltas replace (never add)
    on the coordinator, under an extra worker label."""
    worker = MetricsRegistry()
    worker.counter("worker_epochs_total", "e", labels=("fragment",)) \
        .labels("partial_hash_agg").inc(4)
    worker.histogram("w_lat", "l", buckets=(1.0,)).observe(0.5)
    delta, state = worker.dump_delta({})
    assert "worker_epochs_total" in delta and "w_lat" in delta
    # nothing changed -> empty delta (the piggyback frame stays small)
    delta2, state2 = worker.dump_delta(state)
    assert delta2 == {}
    coord = MetricsRegistry()
    coord.counter("barrier_count", "b").inc()
    coord.merge_remote(delta, worker="partial0/123")
    coord.merge_remote(delta, worker="partial0/123")   # idempotent
    samples, _ = parse_exposition(coord.expose())
    assert samples[("worker_epochs_total",
                    frozenset({("fragment", "partial_hash_agg"),
                               ("worker", "partial0/123")}))] == 4.0
    assert samples[("w_lat_count",
                    frozenset({("worker", "partial0/123")}))] == 1.0
    # local families still expose
    assert samples[("barrier_count", frozenset())] == 1.0


def test_lint_registry():
    reg = MetricsRegistry()
    reg.counter("ok_total", "fine", labels=("a",))
    assert lint_registry(reg) == []
    reg.counter("bad-name", "dash is invalid")
    reg.gauge("bad_label", "x", labels=("0digit",))
    # same name, conflicting label sets (second registration is silently
    # deduped at runtime — the lint must still flag it)
    reg.counter("ok_total", "fine", labels=("a", "b"))
    problems = lint_registry(reg)
    assert any("bad-name" in p for p in problems)
    assert any("0digit" in p for p in problems)
    assert any("ok_total" in p and "conflicting" in p for p in problems)
