"""Multi-role chaos: kill a WORKER OS PROCESS mid-stream while a file
sink is attached downstream; recovery must converge with exactly-once
external delivery.

Reference: `src/tests/simulation/tests/integration_tests/recovery/`
(node-kill recovery suites) + the sink log-store exactly-once contract
(`src/stream/src/common/log_store_impl/kv_log_store/mod.rs`).
"""
import json

import numpy as np
import pytest

from risingwave_tpu.sql import Database

SRC = ("CREATE SOURCE bid (auction BIGINT, bidder BIGINT, price BIGINT,"
       " channel VARCHAR, url VARCHAR, date_time TIMESTAMP, extra VARCHAR)"
       " WITH (connector='nexmark', nexmark.table='bid',"
       " nexmark.max.events='{n}', nexmark.chunk.size='{c}')")
MV = ("CREATE MATERIALIZED VIEW q4 AS SELECT auction, count(*) AS c,"
      " sum(price) AS s FROM bid GROUP BY auction")


def find_remote(db, name):
    obj = db.catalog.get(name)
    stack = [obj.runtime["shared"].upstream]
    while stack:
        e = stack.pop()
        r = getattr(e, "_remote", None)
        if r is not None:
            return r
        for attr in ("input", "left_exec", "right_exec"):
            c = getattr(e, attr, None)
            if c is not None:
                stack.append(c)
    raise AssertionError("no RemoteFragmentSet in the plan")


def oracle(n, chunk):
    db = Database()
    db.run(SRC.format(n=n, c=chunk))
    db.run(MV)
    for _ in range(n // (64 * chunk) + 4):
        db.tick()
    return sorted(db.query("SELECT * FROM q4"))


def replay_changelog(path):
    """Apply the sink's +/- changelog; returns the net row multiset."""
    state = {}
    for ln in open(path):
        rec = json.loads(ln)
        row = tuple(rec["row"][k] for k in sorted(rec["row"]))
        state[row] = state.get(row, 0) + (1 if rec["op"] == "+" else -1)
        if state[row] == 0:
            del state[row]
    out = []
    for row, cnt in state.items():
        assert cnt > 0, f"negative multiplicity for {row}"
        out.extend([row] * cnt)
    return sorted(out)


@pytest.mark.parametrize("seed", [11, 23])
def test_worker_kill_midstream_exactly_once_sink(tmp_path, seed):
    from risingwave_tpu.runtime.remote_fragments import RemoteWorkerDied
    n, chunk = 30_000, 256
    rng = np.random.default_rng(seed)
    d = str(tmp_path / "data")
    out = tmp_path / "out.jsonl"
    db = Database(data_dir=d)
    db.run(SRC.format(n=n, c=chunk))
    db.run("SET streaming_parallelism = 2")
    db.run("SET streaming_placement = 'process'")
    db.run(MV)
    db.run(f"CREATE SINK snk FROM q4 WITH (connector='fs',"
           f" fs.path='{out}')")
    kill_at = int(rng.integers(2, 6))
    total_ticks = n // (64 * chunk) + 4
    for i in range(kill_at):
        db.tick()
    # kill one worker MID-EPOCH (after dispatch, before collection)
    rfs = find_remote(db, "q4")
    rfs.workers[int(rng.integers(0, 2))].proc.kill()
    with pytest.raises(RemoteWorkerDied):
        for _ in range(total_ticks):
            db.tick()
    rfs.shutdown()
    del db
    # recovery: fresh coordinator + fresh workers, replayed DDL, source
    # rewind to the committed offset
    db2 = Database(data_dir=d)
    for _ in range(total_ticks + 2):
        db2.tick()
    want = oracle(n, chunk)
    assert sorted(db2.query("SELECT * FROM q4")) == want
    # exactly-once external delivery: the changelog's net result is the
    # oracle MV — nothing lost in the crash window, nothing re-delivered
    got = replay_changelog(out)
    # normalize types: JSON renders the Decimal sum as a string
    want_rows = sorted(tuple(str(v) for v in r) for r in want)
    got = sorted(tuple(str(v) for v in r) for r in got)
    assert got == want_rows, (len(got), len(want_rows))
    rfs2 = find_remote(db2, "q4")
    rfs2.shutdown()


# ---------------------------------------------------------------------------
# FragmentSupervisor: in-place self-healing (SET streaming_supervision)
# ---------------------------------------------------------------------------


class TestSupervisedRecovery:
    """`SET streaming_supervision TO true`: one dead worker respawns in
    place — same job objects, no DDL replay — instead of tearing the
    whole job down (the reference survives node kills inside
    `GlobalBarrierWorker::recovery`; this is the per-fragment analog)."""

    def _fast_backoff(self):
        from risingwave_tpu.config import ROBUSTNESS
        ROBUSTNESS.respawn_backoff_s = 0.001
        ROBUSTNESS.spawn_backoff_s = 0.001

    @pytest.mark.parametrize("victim", [0, 1])
    def test_stateless_worker_killed_midstream_respawns_in_place(
            self, victim):
        """Kill one stateless partial-agg worker MID-EPOCH (between the
        37th dispatched chunk and its barrier — deterministic, no timer
        races): the supervisor replays the retained input epoch(s) into
        a fresh worker — exactly-once (worker output is epoch-atomic),
        so the final MV equals the oracle with no job restart."""
        from risingwave_tpu.core.chunk import StreamChunk
        self._fast_backoff()
        n, chunk = 40_000, 64
        db = Database()
        db.run(SRC.format(n=n, c=chunk))
        db.run("SET streaming_parallelism = 2")
        db.run("SET streaming_placement = 'process'")
        db.run("SET streaming_supervision TO true")
        db.run(MV)
        rfs = find_remote(db, "q4")
        old_pid = rfs.workers[victim].proc.pid
        # hook the victim's input channel: hard-kill it right after its
        # 37th data chunk — guaranteed mid-stream AND mid-epoch (epochs
        # carry up to 64 source chunks), dispatch still in flight
        vin = rfs.in_channels[0][victim]
        orig_send, seen = vin.send, [0]

        def send_and_kill(msg):
            orig_send(msg)
            if isinstance(msg, StreamChunk):
                seen[0] += 1
                if seen[0] == 37:
                    rfs.workers[victim].proc.kill()
                    rfs.workers[victim].proc.wait()
        vin.send = send_and_kill
        for _ in range(n // (64 * chunk) + 4):
            db.tick()                  # must NOT raise RemoteWorkerDied
        assert find_remote(db, "q4") is rfs, \
            "job objects must survive (in-place recovery, no DDL replay)"
        assert rfs.supervisor.respawns == 1
        assert rfs.workers[victim].proc.pid != old_pid
        assert sorted(db.query("SELECT * FROM q4")) == oracle(n, chunk)
        rfs.shutdown()

    def test_stateful_agg_worker_killed_respawns_with_shadow_reseed(self):
        """Kill exactly one stateful-agg worker after it holds state: the
        supervisor re-seeds the respawn from the coordinator shadow and
        the post-respawn refresh reconciles the MV; retractions against
        the reseeded state stay exact."""
        self._fast_backoff()
        db = Database()
        db.run("CREATE TABLE t (k BIGINT, v BIGINT)")
        db.run("SET streaming_parallelism = 2")
        db.run("SET streaming_placement = 'process'")
        db.run("SET streaming_supervision TO true")
        db.run("CREATE MATERIALIZED VIEW ra AS SELECT k, count(*) AS c,"
               " min(v) AS lo, max(v) AS hi FROM t GROUP BY k")
        rfs = find_remote(db, "ra")
        assert rfs.kind == "stateful"
        db.run("INSERT INTO t VALUES (1, 10), (1, 5), (2, 7), (3, 30)")
        for _ in range(4):
            db.tick()
        assert sorted(db.query("SELECT * FROM ra")) == \
            [(1, 2, 5, 10), (2, 1, 7, 7), (3, 1, 30, 30)]
        victim = 0
        old_pid = rfs.workers[victim].proc.pid
        rfs.workers[victim].proc.kill()
        for _ in range(4):
            db.tick()                  # supervisor respawns, no teardown
        assert find_remote(db, "ra") is rfs
        assert rfs.supervisor.respawns == 1
        assert rfs.workers[victim].proc.pid != old_pid
        # refresh must have reconciled every owned group exactly
        assert sorted(db.query("SELECT * FROM ra")) == \
            [(1, 2, 5, 10), (2, 1, 7, 7), (3, 1, 30, 30)]
        # retraction against RESEEDED worker state: min(5) must retract
        db.run("DELETE FROM t WHERE v = 5")
        for _ in range(4):
            db.tick()
        assert sorted(db.query("SELECT * FROM ra")) == \
            [(1, 1, 10, 10), (2, 1, 7, 7), (3, 1, 30, 30)]
        # and fresh inserts keep aggregating on the respawned worker
        db.run("INSERT INTO t VALUES (1, 2), (2, 9)")
        for _ in range(4):
            db.tick()
        assert sorted(db.query("SELECT * FROM ra")) == \
            [(1, 2, 2, 10), (2, 2, 7, 9), (3, 1, 30, 30)]
        rfs.shutdown()

    def test_drain_flap_failpoint_triggers_one_respawn(self):
        """A seeded `fragment.drain` failpoint aborts exactly one result
        drain (connection flap, worker still alive): the supervisor
        treats it as a worker failure, respawns, and the job converges
        — repeatable because max_fires bounds the chaos."""
        from risingwave_tpu.utils import failpoint as fp
        self._fast_backoff()
        n, chunk = 20_000, 256
        fp.arm("fragment.drain", prob=1.0, seed=0, max_fires=1)
        try:
            db = Database()
            db.run(SRC.format(n=n, c=chunk))
            db.run("SET streaming_parallelism = 2")
            db.run("SET streaming_placement = 'process'")
            db.run("SET streaming_supervision TO true")
            db.run(MV)
            rfs = find_remote(db, "q4")
            for _ in range(n // (64 * chunk) + 4):
                db.tick()
            assert rfs.supervisor.respawns == 1
            assert sorted(db.query("SELECT * FROM q4")) == oracle(n, chunk)
            rfs.shutdown()
        finally:
            fp.reset()

    def test_crash_looping_worker_escalates_to_full_recovery(
            self, monkeypatch, tmp_path):
        """RW_FAILPOINTS=worker.crash:1:0:1 makes EVERY worker process
        (respawns included — they inherit the env) die on its first
        message: bounded respawn attempts must exhaust and escalate to
        the classic RemoteWorkerDied full-recovery path, never hang."""
        from risingwave_tpu.runtime.remote_fragments import RemoteWorkerDied
        self._fast_backoff()
        monkeypatch.setenv("RW_FAILPOINTS", "worker.crash:1:0:1")
        d = str(tmp_path / "data")
        db = Database(data_dir=d)
        db.run("CREATE TABLE t (k BIGINT, v BIGINT)")
        db.run("SET streaming_parallelism = 2")
        db.run("SET streaming_placement = 'process'")
        db.run("SET streaming_supervision TO true")
        db.run("CREATE MATERIALIZED VIEW ra AS SELECT k, count(*) AS c"
               " FROM t GROUP BY k")
        rfs = find_remote(db, "ra")
        with pytest.raises(RemoteWorkerDied, match="escalating"):
            # the INSERT's flush already ticks the dataflow, so the
            # chaos can escalate inside it or in the explicit ticks
            db.run("INSERT INTO t VALUES (1, 10), (2, 20)")
            for _ in range(30):
                db.tick()
        assert rfs.supervisor.respawns >= 1, \
            "escalation must come AFTER in-place attempts were tried"
        rfs.shutdown()
        del db
        # chaos off: full recovery (DDL replay) converges. The crash may
        # have landed before or after the INSERT's checkpoint, so compare
        # the MV against the recovered base table, not a pinned row set.
        monkeypatch.delenv("RW_FAILPOINTS")
        db2 = Database(data_dir=d)
        for _ in range(4):
            db2.tick()
        db2.run("INSERT INTO t VALUES (1, 11)")
        for _ in range(4):
            db2.tick()
        want = sorted(db2.query("SELECT k, count(*) FROM t GROUP BY k"))
        got = sorted(db2.query("SELECT * FROM ra"))
        assert got == want and any(k == 1 for k, _ in got), (got, want)
        find_remote(db2, "ra").shutdown()

    @pytest.mark.chaos
    def test_join_fragment_death_respawns_in_place(self):
        """Supervision v2: a dead two-input join worker respawns IN
        PLACE — re-seeded from both-side shadows rolled back to its last
        delivered epoch, window replayed on both dispatchers — instead
        of escalating to RemoteWorkerDied. Retractions and fresh inserts
        against the respawned worker stay exact."""
        self._fast_backoff()
        db = Database()
        db.run("CREATE TABLE a (k BIGINT, v BIGINT)")
        db.run("CREATE TABLE b (k BIGINT, w BIGINT)")
        db.run("SET streaming_parallelism = 2")
        db.run("SET streaming_placement = 'process'")
        db.run("SET streaming_supervision TO true")
        db.run("CREATE MATERIALIZED VIEW rj AS SELECT a.v, b.w"
               " FROM a JOIN b ON a.k = b.k")
        db.run("INSERT INTO a VALUES (1, 10), (2, 20), (3, 30)")
        db.run("INSERT INTO b VALUES (1, 100), (2, 200)")
        for _ in range(4):
            db.tick()
        assert sorted(db.query("SELECT * FROM rj")) == \
            [(10, 100), (20, 200)]
        rfs = find_remote(db, "rj")
        assert rfs.kind == "join"
        victim = 0
        old_pid = rfs.workers[victim].proc.pid
        rfs.workers[victim].proc.kill()
        for _ in range(4):
            db.tick()                  # supervisor respawns, no teardown
        assert find_remote(db, "rj") is rfs
        assert rfs.supervisor.respawns == 1
        assert rfs.workers[victim].proc.pid != old_pid
        assert sorted(db.query("SELECT * FROM rj")) == \
            [(10, 100), (20, 200)]
        # retraction against RESEEDED both-side state must match exactly
        db.run("DELETE FROM b WHERE k = 1")
        db.run("INSERT INTO b VALUES (3, 300)")
        for _ in range(4):
            db.tick()
        assert sorted(db.query("SELECT * FROM rj")) == \
            [(20, 200), (30, 300)]
        rfs.shutdown()
