"""Multi-role chaos: kill a WORKER OS PROCESS mid-stream while a file
sink is attached downstream; recovery must converge with exactly-once
external delivery.

Reference: `src/tests/simulation/tests/integration_tests/recovery/`
(node-kill recovery suites) + the sink log-store exactly-once contract
(`src/stream/src/common/log_store_impl/kv_log_store/mod.rs`).
"""
import json

import numpy as np
import pytest

from risingwave_tpu.sql import Database

SRC = ("CREATE SOURCE bid (auction BIGINT, bidder BIGINT, price BIGINT,"
       " channel VARCHAR, url VARCHAR, date_time TIMESTAMP, extra VARCHAR)"
       " WITH (connector='nexmark', nexmark.table='bid',"
       " nexmark.max.events='{n}', nexmark.chunk.size='{c}')")
MV = ("CREATE MATERIALIZED VIEW q4 AS SELECT auction, count(*) AS c,"
      " sum(price) AS s FROM bid GROUP BY auction")


def find_remote(db, name):
    obj = db.catalog.get(name)
    stack = [obj.runtime["shared"].upstream]
    while stack:
        e = stack.pop()
        r = getattr(e, "_remote", None)
        if r is not None:
            return r
        for attr in ("input", "left_exec", "right_exec"):
            c = getattr(e, attr, None)
            if c is not None:
                stack.append(c)
    raise AssertionError("no RemoteFragmentSet in the plan")


def oracle(n, chunk):
    db = Database()
    db.run(SRC.format(n=n, c=chunk))
    db.run(MV)
    for _ in range(n // (64 * chunk) + 4):
        db.tick()
    return sorted(db.query("SELECT * FROM q4"))


def replay_changelog(path):
    """Apply the sink's +/- changelog; returns the net row multiset."""
    state = {}
    for ln in open(path):
        rec = json.loads(ln)
        row = tuple(rec["row"][k] for k in sorted(rec["row"]))
        state[row] = state.get(row, 0) + (1 if rec["op"] == "+" else -1)
        if state[row] == 0:
            del state[row]
    out = []
    for row, cnt in state.items():
        assert cnt > 0, f"negative multiplicity for {row}"
        out.extend([row] * cnt)
    return sorted(out)


@pytest.mark.parametrize("seed", [11, 23])
def test_worker_kill_midstream_exactly_once_sink(tmp_path, seed):
    from risingwave_tpu.runtime.remote_fragments import RemoteWorkerDied
    n, chunk = 30_000, 256
    rng = np.random.default_rng(seed)
    d = str(tmp_path / "data")
    out = tmp_path / "out.jsonl"
    db = Database(data_dir=d)
    db.run(SRC.format(n=n, c=chunk))
    db.run("SET streaming_parallelism = 2")
    db.run("SET streaming_placement = 'process'")
    db.run(MV)
    db.run(f"CREATE SINK snk FROM q4 WITH (connector='fs',"
           f" fs.path='{out}')")
    kill_at = int(rng.integers(2, 6))
    total_ticks = n // (64 * chunk) + 4
    for i in range(kill_at):
        db.tick()
    # kill one worker MID-EPOCH (after dispatch, before collection)
    rfs = find_remote(db, "q4")
    rfs.workers[int(rng.integers(0, 2))].proc.kill()
    with pytest.raises(RemoteWorkerDied):
        for _ in range(total_ticks):
            db.tick()
    rfs.shutdown()
    del db
    # recovery: fresh coordinator + fresh workers, replayed DDL, source
    # rewind to the committed offset
    db2 = Database(data_dir=d)
    for _ in range(total_ticks + 2):
        db2.tick()
    want = oracle(n, chunk)
    assert sorted(db2.query("SELECT * FROM q4")) == want
    # exactly-once external delivery: the changelog's net result is the
    # oracle MV — nothing lost in the crash window, nothing re-delivered
    got = replay_changelog(out)
    # normalize types: JSON renders the Decimal sum as a string
    want_rows = sorted(tuple(str(v) for v in r) for r in want)
    got = sorted(tuple(str(v) for v in r) for r in got)
    assert got == want_rows, (len(got), len(want_rows))
    rfs2 = find_remote(db2, "q4")
    rfs2.shutdown()
