"""Mesh-sharded fused epoch programs (device/shard_exec.py).

The contract under test: `DeviceConfig.mesh_shards=8` executes a fused
MV as ONE shard_map'd program over the 8-device mesh (vnode-block state
partitioning, in-program all_to_all exchange, psum/pmax stats) and is a
pure execution detail — results are BIT-IDENTICAL to the single-chip
path, including row order, on q1/q3/q5-shaped Nexmark plans. The
conftest forces 8 virtual CPU devices so all of this runs in tier-1.
"""
import os

import numpy as np
import pytest

from risingwave_tpu.config import DeviceConfig
from risingwave_tpu.core.vnode import VNODE_COUNT
from risingwave_tpu.parallel.mesh import shard_of_vnode, vnode_block_bounds
from risingwave_tpu.sql import Database

N = 4096
CHUNK = 32          # fused epoch = 64 * CHUNK = 2048 events

BID_SRC = ("CREATE SOURCE bid (auction BIGINT, bidder BIGINT, price BIGINT,"
           " channel VARCHAR, url VARCHAR, date_time TIMESTAMP,"
           " extra VARCHAR) WITH (connector='nexmark',"
           " nexmark.table='bid', nexmark.max.events='{n}',"
           " nexmark.chunk.size='{c}')")
AUCTION_SRC = ("CREATE SOURCE auction (id BIGINT, item_name VARCHAR,"
               " description VARCHAR, initial_bid BIGINT, reserve BIGINT,"
               " date_time TIMESTAMP, expires TIMESTAMP, seller BIGINT,"
               " category BIGINT, extra VARCHAR) WITH (connector='nexmark',"
               " nexmark.table='auction', nexmark.max.events='{n}',"
               " nexmark.chunk.size='{c}')")

# q1-shaped: stateless projection arithmetic folded into a grouped agg
# (a bare stateless MV stays on host by design — no pair identity)
Q1_MV = ("CREATE MATERIALIZED VIEW q1a AS SELECT bidder,"
         " count(*) AS n, sum(price) AS dol, max(price) AS top"
         " FROM bid GROUP BY bidder")
# q3-shaped: filtered equi-join with pair-identity MV
Q3_MV = ("CREATE MATERIALIZED VIEW q3a AS SELECT b.auction, b.price,"
         " a.seller, a.category FROM bid b JOIN auction a"
         " ON b.auction = a.id WHERE b.price > 500")
# q5 (reference SQL): hop windows, two agg chains, non-equi join
Q5_MV = """CREATE MATERIALIZED VIEW q5 AS
SELECT AuctionBids.auction, AuctionBids.num FROM (
    SELECT bid.auction, count(*) AS num, window_start AS starttime
    FROM HOP(bid, date_time, INTERVAL '2' SECOND, INTERVAL '10' SECOND)
    GROUP BY window_start, bid.auction
) AS AuctionBids
JOIN (
    SELECT max(CountBids.num) AS maxn, CountBids.starttime_c
    FROM (
        SELECT count(*) AS num, window_start AS starttime_c
        FROM HOP(bid, date_time, INTERVAL '2' SECOND, INTERVAL '10' SECOND)
        GROUP BY bid.auction, window_start
    ) AS CountBids
    GROUP BY CountBids.starttime_c
) AS MaxBids
ON AuctionBids.starttime = MaxBids.starttime_c
   AND AuctionBids.num >= MaxBids.maxn"""


def _run(mv_sql, name, shards, srcs=(BID_SRC,), n=N, capacity=512,
         aot=False, data_dir=None, keep=False):
    db = Database(device=DeviceConfig(capacity=capacity,
                                      mesh_shards=shards,
                                      aot_compile=aot),
                  data_dir=data_dir)
    for s in srcs:
        db.run(s.format(n=n, c=CHUNK))
    db.run(mv_sql)
    job = db.catalog.get(name).runtime["fused_job"]
    assert job is not None, f"{name} must fuse"
    if shards > 1:
        assert job.program.mesh is not None \
            and job.program.mesh.devices.size == shards
    else:
        assert job.program.mesh is None
    for _ in range(n // (64 * CHUNK) + 3):
        db.tick()
    job.sync()
    rows = db.query(f"SELECT * FROM {name}")
    return (rows, job, db) if keep else (rows, job, None)


# ---------------------------------------------------------------------------
# vnode -> shard mapping edges
# ---------------------------------------------------------------------------


def test_vnode_block_bounds_edges():
    """Contiguous blocks must cover every vnode exactly once for ANY
    shard count — including ones that do not divide VNODE_COUNT — with
    block sizes differing by at most one (balanced)."""
    for n in (1, 2, 3, 5, 7, 8, 100, VNODE_COUNT):
        b = vnode_block_bounds(n)
        assert b[0] == 0 and b[-1] == VNODE_COUNT
        sizes = np.diff(b)
        assert (sizes >= 0).all() and sizes.sum() == VNODE_COUNT
        assert sizes.max() - sizes.min() <= 1
        # shard_of_vnode must agree with the block bounds exactly
        vn = np.arange(VNODE_COUNT)
        s = shard_of_vnode(vn, n)
        for k in range(n):
            blk = vn[(vn >= b[k]) & (vn < b[k + 1])]
            assert (s[blk] == k).all()
        assert s.min() == 0 and s.max() == n - 1 if n <= VNODE_COUNT else True


def test_vnode_one_shard_degenerate():
    assert (shard_of_vnode(np.arange(VNODE_COUNT), 1) == 0).all()
    assert list(vnode_block_bounds(1)) == [0, VNODE_COUNT]


def test_vnode_rescale_block_boundaries():
    """Doubling the shard count is a block-boundary SPLIT: every old
    boundary survives (bounds(n) is a subset of bounds(2n)), so rescale
    moves contiguous sub-blocks instead of reshuffling keys."""
    for n in (1, 2, 4, 8, 16):
        coarse = set(vnode_block_bounds(n).tolist())
        fine = set(vnode_block_bounds(2 * n).tolist())
        assert coarse <= fine


# ---------------------------------------------------------------------------
# sharded-vs-single bit-identity (q1/q3/q5-shaped fused plans)
# ---------------------------------------------------------------------------


@pytest.mark.mesh
def test_q1_agg_bit_identity():
    r1, j1, _ = _run(Q1_MV, "q1a", 1)
    r8, j8, _ = _run(Q1_MV, "q1a", 8)
    assert r1 == r8                     # bit-identical, ORDER included
    assert j8.plan_hash != j1.plan_hash  # per-shard state never collides


@pytest.mark.mesh
def test_q3_join_bit_identity():
    r1, _, _ = _run(Q3_MV, "q3a", 1, srcs=(BID_SRC, AUCTION_SRC))
    r8, j8, _ = _run(Q3_MV, "q3a", 8, srcs=(BID_SRC, AUCTION_SRC))
    assert len(r1) > 0
    assert r1 == r8
    # the join's two inputs were exchange-routed in-program
    from risingwave_tpu.device.fused import JoinNode
    joins = [n for n in j8.program.nodes if isinstance(n, JoinNode)]
    assert joins and all(n.exch is not None for n in joins)


@pytest.mark.mesh
def test_non_dividing_cadence_pads_and_engages_8_shards(monkeypatch):
    """ROADMAP mesh residual closed: an epoch cadence that does not
    divide the shard count used to degrade SILENTLY to one chip. Now
    each shard's event block is ceil-div sized and the tail block pads
    (over-generated ids mask out inside the traced step) — all 8 shards
    engage at cadence 2015 (2015 % 8 == 7) and the MV stays
    bit-identical to the single-chip run."""
    from risingwave_tpu.device import fuse_planner
    monkeypatch.setattr(fuse_planner, "EPOCH_POLLS", 65)
    n, chunk = 4096, 31            # cadence = 65 * 31 = 2015

    def run(shards):
        db = Database(device=DeviceConfig(capacity=512,
                                          mesh_shards=shards))
        db.run(BID_SRC.format(n=n, c=chunk))
        db.run(Q1_MV)
        job = db.catalog.get("q1a").runtime["fused_job"]
        assert job is not None and job.program.epoch_events == 2015
        for _ in range(n // 2015 + 4):
            db.tick()
        job.sync()
        return db.query("SELECT * FROM q1a"), job

    r8, j8 = run(8)
    assert j8.program.mesh is not None \
        and j8.program.mesh.devices.size == 8, \
        "non-dividing cadence must still engage the full mesh"
    r1, j1 = run(1)
    assert j1.program.mesh is None
    assert len(r1) > 0 and r8 == r1
    # the flow stats are exact too: the padded tail's masked events are
    # recounted out of rows_out before the psum
    src = 0
    assert j8.program.node_stats(src, j8._stat_totals).get("rows_out") \
        == j1.program.node_stats(src, j1._stat_totals).get("rows_out")


@pytest.mark.mesh
def test_q5_hop_agg_join_bit_identity():
    r1, _, _ = _run(Q5_MV, "q5", 1, n=2048)
    r8, j8, _ = _run(Q5_MV, "q5", 8, n=2048)
    assert len(r1) > 0
    assert r1 == r8


# ---------------------------------------------------------------------------
# exchange capacity lifecycle
# ---------------------------------------------------------------------------


@pytest.mark.mesh
def test_exchange_overflow_grows_and_replays(monkeypatch):
    """A send bucket too small for the epoch's skew must overflow the
    `exch` stat, grow through the NORMAL replay path, and still produce
    the single-chip answer — correctness never depends on the initial
    exchange sizing."""
    from risingwave_tpu.device import capacity as cap_mod
    monkeypatch.setattr(cap_mod, "exchange_cap",
                        lambda epoch_events, n_shards, lo=4: 4)
    r8, j8, _ = _run(Q1_MV, "q1a", 8)
    r1, _, _ = _run(Q1_MV, "q1a", 1)
    assert r8 == r1
    assert j8.growth_replays >= 1
    grown = [n.exch for n in j8.program.nodes if n.exch is not None]
    assert grown and all(e > 4 for e in grown)


@pytest.mark.mesh
def test_sharded_capacity_growth_replay():
    """Tiny main capacity on the sharded path: per-shard overflow is
    pmax-reported, the growth replay runs through the shard axis, and
    the answer still matches the single chip."""
    r8, j8, _ = _run(Q1_MV, "q1a", 8, capacity=4)
    r1, _, _ = _run(Q1_MV, "q1a", 1)
    assert r8 == r1
    assert len(r1) > 8 * 4              # per-shard groups really overflow
    assert j8.growth_replays >= 1


# ---------------------------------------------------------------------------
# observability: shards dimension + exchange phase
# ---------------------------------------------------------------------------


@pytest.mark.mesh
def test_profiler_shards_and_exchange_phase():
    _, job, db = _run(Q1_MV, "q1a", 8, keep=True)
    assert job.profiler.shards == 8
    assert job.profiler.totals.get("exchange", 0.0) > 0.0
    rows = db.query("SELECT * FROM rw_epoch_profile")
    assert rows
    dispatched = 0
    for j, seq, events, shards, hp, h2d, pro, disp, exch, sync, dem, \
            commit, wall in rows:
        assert shards == 8
        phases = hp + h2d + pro + disp + exch + sync + dem + commit
        # the exchange split must stay disjoint from dispatch: phase
        # sums within 10% of wall (epsilon for sub-ms timer noise)
        assert phases <= wall * 1.001 + 0.05
        if wall > 1.0:
            assert phases >= wall * 0.9
        if events and exch > 0.0:
            dispatched += 1
    assert dispatched, "dispatched epochs must time the exchange stage"
    from risingwave_tpu.utils.metrics import REGISTRY
    text = REGISTRY.expose()
    assert 'rw_hbm_bytes{job="q1a"' in text and 'shards="8"' in text


# ---------------------------------------------------------------------------
# durability: device marker + recovery + offline compile-status
# ---------------------------------------------------------------------------


@pytest.mark.mesh
def test_mesh_marker_and_recovery(tmp_path):
    d = str(tmp_path / "data")
    r8, job, db = _run(Q1_MV, "q1a", 8, data_dir=d, keep=True)
    committed = job.committed
    assert committed >= N
    del db
    # same shard count: recovery replays device-side and presizes
    db2 = Database(data_dir=d, device=DeviceConfig(capacity=512,
                                                   mesh_shards=8))
    j2 = db2._fused["q1a"]
    assert j2.committed == committed
    assert db2.query("SELECT * FROM q1a") == r8
    del db2
    # different shard count: state layouts differ per shard — fail fast
    with pytest.raises(ValueError, match="device="):
        Database(data_dir=d, device=DeviceConfig(capacity=512))


@pytest.mark.mesh
@pytest.mark.aot
def test_offline_compile_status_dead_dir(tmp_path, capsys, monkeypatch):
    """`risectl compile-status --offline` must answer from a dead data
    dir via the compile_manifest.json mirror — no Database, no rebuild,
    no recompiles (the PR 6 residual)."""
    monkeypatch.delenv("RW_COMPILE_CACHE_DIR", raising=False)
    d = str(tmp_path / "data")
    _, job, db = _run(Q1_MV, "q1a", 8, aot=True, data_dir=d, keep=True)
    plan_hash = job.plan_hash
    from risingwave_tpu.device.compile_service import get_service
    assert get_service().wait_idle(60.0)
    del db
    assert os.path.exists(os.path.join(d, "compile_manifest.json"))
    from risingwave_tpu import ctl
    rc = ctl.main(["compile-status", "--data-dir", d, "--offline"])
    out = capsys.readouterr().out
    assert rc == 0
    assert plan_hash in out             # the plan shape is on record
    assert '"shards": 8' in out         # sharded executables are labeled
